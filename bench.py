"""Benchmark harness: GPT causal-LM pretraining throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
- metric: GPT-125M tokens/sec/chip (fwd+bwd+update; bf16 activations via
  amp O1, flash-attention Pallas kernel, S=2048 — the BASELINE.json config
  #4 single-chip slice).
- vs_baseline: achieved MFU / 0.45 (the north-star ≥45% MFU target;
  BASELINE.md records no reference numbers in-tree, so the target ratio is
  the comparison axis).

Timing methodology (IMPORTANT, round-4 fix): on the tunneled TPU platform
``block_until_ready`` returns at dispatch, not completion — a host readback
(``float(loss)``) is the only true synchronization.  The timed region ends
with that readback; steps chain donated state so device execution
serializes.  The r03 number (53.7k tok/s) predates this fix.

Extra diagnostics go to stderr so stdout stays one parseable line:
- flash-vs-XLA attention check,
- an honest GPT-1.3B slice measurement: time L=2 and L=6 layer slices of
  the 1.3B config (remat + bf16), difference out the per-layer cost, and
  report the composed full-24-layer estimate labelled as an estimate.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


# bf16 peak matmul TFLOPs per chip by TPU generation (public specs);
# CPU fallback uses a nominal figure so the script still runs in dev envs.
_PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}


def _peak_flops_per_sec() -> float:
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for gen, tf in _PEAK_TFLOPS.items():
        if gen in kind:
            return tf * 1e12
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if gen in _PEAK_TFLOPS:
        return _PEAK_TFLOPS[gen] * 1e12
    return _PEAK_TFLOPS["v5e"] * 1e12


def _param_count(params) -> int:
    return sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(params))


def _flops_per_token(n_params: int, cfg, S: int) -> float:
    # 6N for fwd+bwd matmuls + causal attention term 12*L*h*S per token
    return 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * S // 2


def _build(cfg, B, S, lr=1e-4, opt_factory=None):
    """(jitted step, params, opt_state, ids, labels, key) for one config."""
    import paddle_tpu as pt
    from paddle_tpu import amp as amp_mod
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.models import GPTForCausalLM

    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    params = model.state_dict()
    if opt_factory is None:
        opt = pt.optimizer.AdamW(learning_rate=lr, weight_decay=0.01)
    else:
        opt = opt_factory(lr)
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def train_step(params, opt_state, input_ids, labels, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                with amp_mod.auto_cast(level="O1", dtype="bfloat16"):
                    loss, _ = model.apply(p, input_ids, labels=labels)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_state = opt.apply_gradients(grads, params, opt_state)
        return loss, new_params, new_state

    jitted = jax.jit(train_step, donate_argnums=(0, 1))
    return jitted, model, params, opt_state, ids, labels


def _timed_steps(jitted, params, opt_state, ids, labels, steps, warmup):
    """Seconds per step with host-readback synchronization."""
    key = jax.random.key(0)
    t0 = time.perf_counter()
    for i in range(warmup):
        loss, params, opt_state = jitted(params, opt_state, ids, labels,
                                         jax.random.fold_in(key, i))
    _ = float(loss)                       # true sync (see module docstring)
    warm_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(steps):
        loss, params, opt_state = jitted(params, opt_state, ids, labels,
                                         jax.random.fold_in(key, warmup + i))
    final_loss = float(loss)              # sync INSIDE the timed region
    dt = (time.perf_counter() - t0) / steps
    return dt, final_loss, warm_t


def _bench_config(cfg, B, S, steps, warmup, tag):
    jitted, model, params, opt_state, ids, labels = _build(cfg, B, S)
    n_params = _param_count(params)
    dt, loss, warm_t = _timed_steps(jitted, params, opt_state, ids, labels,
                                    steps, warmup)
    tok_s = B * S / dt
    mfu = tok_s * _flops_per_token(n_params, cfg, S) / _peak_flops_per_sec()
    print(f"[{tag}] params={n_params / 1e6:.1f}M B={B} S={S} "
          f"compile+warmup={warm_t:.1f}s step={dt * 1e3:.1f}ms "
          f"tok/s={tok_s:.0f} mfu={mfu:.3f} loss={loss:.3f}",
          file=sys.stderr, flush=True)
    return tok_s, mfu


def _bench_1p3b_slice(S=2048, B=4):
    """Honest 1.3B methodology: full 1.3B + fp32 Adam does not fit one v5e
    chip, so measure 2- and 6-layer slices (remat on), difference out the
    per-layer cost, and compose an ESTIMATE for the 24-layer model."""
    from paddle_tpu.models import gpt_1p3b
    times = {}
    for L in (2, 6):
        cfg = gpt_1p3b(num_layers=L, hidden_dropout=0.0,
                       attention_dropout=0.0, use_recompute=True,
                       use_pallas_attention=True, dtype="bfloat16")
        jitted, model, params, opt_state, ids, labels = _build(cfg, B, S)
        dt, loss, _ = _timed_steps(jitted, params, opt_state, ids, labels,
                                   steps=5, warmup=2)
        times[L] = dt
        print(f"[1.3b-slice L={L}] step={dt * 1e3:.1f}ms loss={loss:.3f}",
              file=sys.stderr, flush=True)
    per_layer = (times[6] - times[2]) / 4
    est = times[2] + 22 * per_layer
    tok_s = B * S / est
    # full-model params for the MFU estimate
    from paddle_tpu.models import GPTForCausalLM
    cfg24 = gpt_1p3b()
    n24 = (cfg24.vocab_size * cfg24.hidden_size
           + cfg24.max_position_embeddings * cfg24.hidden_size
           + cfg24.num_layers * 12 * cfg24.hidden_size ** 2)
    mfu = tok_s * _flops_per_token(n24, cfg24, S) / _peak_flops_per_sec()
    print(f"[1.3b-estimate] per_layer={per_layer * 1e3:.1f}ms "
          f"est_step={est * 1e3:.0f}ms est_tok/s={tok_s:.0f} "
          f"est_mfu={mfu:.3f} (ESTIMATE composed from measured slices)",
          file=sys.stderr, flush=True)


def _bench_1p3b_fullstep(S=2048, B=4):
    """MEASURED full 24-layer GPT-1.3B step on one chip (VERDICT r4
    weak #8): real hidden/layer/head dims AND the real 50304 vocab —
    feasible on a single 16GB chip because the fused linear CE
    (ops/fused.py) never materializes [B, S, V] logits; the optimizer is
    SGD so fp32 params+grads fit HBM (bf16 activations + remat).  Falls
    back to the historical reduced-vocab 8k variant if HBM is exceeded.
    MFU is computed against the measured variant's own FLOPs — a measured
    number, not an estimate.  Measured r5 on v5e: B=4 → MFU 0.489."""
    import paddle_tpu as pt
    from paddle_tpu.models import gpt_1p3b
    for vocab, tag in ((50304, "full-vocab"), (8192, "reduced-vocab 8k")):
        cfg = gpt_1p3b(vocab_size=vocab, hidden_dropout=0.0,
                       attention_dropout=0.0, use_recompute=True,
                       use_pallas_attention=True, dtype="bfloat16")
        try:
            jitted, model, params, opt_state, ids, labels = _build(
                cfg, B, S, opt_factory=lambda lr: pt.optimizer.SGD(
                    learning_rate=lr))
            n_params = _param_count(params)
            dt, loss, warm_t = _timed_steps(jitted, params, opt_state, ids,
                                            labels, steps=5, warmup=2)
        except Exception as e:
            print(f"[1.3b-fullstep {tag}] failed ({repr(e)[:120]}); "
                  f"trying smaller", file=sys.stderr, flush=True)
            # drop the failed attempt's device buffers (fp32 full-vocab
            # params + executable) before building the fallback, or the
            # fallback OOMs on the leftovers
            try:
                del jitted, model, params, opt_state, ids, labels
            except NameError:
                pass            # _build itself failed: nothing bound
            import gc
            gc.collect()
            continue
        tok_s = B * S / dt
        mfu = (tok_s * _flops_per_token(n_params, cfg, S)
               / _peak_flops_per_sec())
        print(f"[1.3b-fullstep-measured] params={n_params / 1e6:.0f}M "
              f"({tag}, SGD) B={B} S={S} step={dt * 1e3:.0f}ms "
              f"tok/s={tok_s:.0f} mfu={mfu:.3f} loss={loss:.3f}",
              file=sys.stderr, flush=True)
        return {"tok_s": tok_s, "mfu": mfu, "step_ms": dt * 1e3,
                "params_m": n_params / 1e6, "vocab": vocab}
    return None


def _bench_flash_ab(B=8, S=2048, steps=8, warmup=3):
    """Recorded flash-vs-XLA attention A/B on the same 125M config
    (VERDICT r4 #1): both paths timed identically; artifact written to
    benchmarks/flash_ab.json."""
    from paddle_tpu.models import gpt_125m
    rows = {}
    for tag, pallas in (("flash", True), ("xla", False)):
        cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                       attention_dropout=0.0, use_pallas_attention=pallas,
                       max_position_embeddings=S)
        jitted, model, params, opt_state, ids, labels = _build(cfg, B, S)
        dt, loss, _ = _timed_steps(jitted, params, opt_state, ids, labels,
                                   steps, warmup)
        rows[tag] = {"step_ms": dt * 1e3, "tok_s": B * S / dt}
        print(f"[flash-ab {tag}] step={dt * 1e3:.1f}ms "
              f"tok/s={B * S / dt:.0f}", file=sys.stderr, flush=True)
    rows["speedup_flash_over_xla"] = (rows["xla"]["step_ms"]
                                      / rows["flash"]["step_ms"])
    _write_artifact("flash_ab.json", rows)
    return rows


def _sweep_block_sizes(bh=96, S=2048, d=64):
    """Block-size sweep for the flash kernel (the artifact behind the
    block-size claim in ops/flash_attention.py::_block_sizes — measured
    512/512 = 1.6x over 128/128 on v5e): time fwd+bwd attention alone per
    (block_q, block_k); writes benchmarks/flash_block_sweep.json."""
    import importlib
    # NB: ``paddle_tpu.ops`` re-exports the ``flash_attention`` *function*,
    # shadowing the submodule attribute — ``import ... as`` would bind the
    # function, so resolve the module explicitly.
    fa_mod = importlib.import_module("paddle_tpu.ops.flash_attention")
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
    results = {}
    orig = fa_mod._block_sizes
    try:
        for b in (128, 256, 512):
            fa_mod._block_sizes = lambda sq, sk, _b=b: (_b, _b)

            def loss(q_, k_, v_):
                return jnp.sum(fa_mod.flash_attention(
                    q_, k_, v_, causal=True).astype(jnp.float32) ** 2)

            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            out = g(q, k, v)          # compile
            _ = float(out[0][0, 0, 0, 0])
            t0 = time.perf_counter()
            for _i in range(5):
                out = g(q, k, v)
            _ = float(out[0][0, 0, 0, 0])
            dt = (time.perf_counter() - t0) / 5
            results[f"{b}/{b}"] = {"fwd_bwd_ms": dt * 1e3}
            print(f"[block-sweep {b}/{b}] fwd+bwd={dt * 1e3:.1f}ms",
                  file=sys.stderr, flush=True)
    finally:
        fa_mod._block_sizes = orig
    _write_artifact("flash_block_sweep.json", results)
    return results


def _write_artifact(name: str, payload) -> None:
    import pathlib
    d = pathlib.Path(__file__).parent / "benchmarks"
    d.mkdir(exist_ok=True)
    payload = dict(payload)
    payload["_meta"] = {
        "device": str(jax.devices()[0]),
        "recorded_unix": time.time(),
    }
    (d / name).write_text(json.dumps(payload, indent=2))
    print(f"[artifact] wrote benchmarks/{name}", file=sys.stderr,
          flush=True)


def _tpu_reachable(timeout_s: int = 420) -> bool:
    """Probe device init in a subprocess: a dead TPU tunnel makes
    jax.devices() hang indefinitely, which must not take the bench (and
    the driver's BENCH json) down with it."""
    import subprocess
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and "tpu" in out.stdout


def main():
    if os.environ.get("BENCH_CPU", "0") == "1":  # local smoke, no TPU probe
        from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
        force_virtual_cpu_mesh(1)
    elif not _tpu_reachable():
        print("[tpu unreachable after probe timeout — falling back to the "
              "CPU smoke so the bench still reports]", file=sys.stderr,
              flush=True)
        from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
        force_virtual_cpu_mesh(1)
    on_tpu = jax.devices()[0].platform != "cpu"
    from paddle_tpu.models import gpt_125m, gpt_tiny

    if on_tpu:
        try:
            cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                           attention_dropout=0.0, use_pallas_attention=True,
                           max_position_embeddings=2048)
            tok_s, mfu = _bench_config(cfg, B=8, S=2048, steps=10, warmup=3,
                                       tag="gpt-125m-flash")
        except Exception as e:
            # the headline number must survive a kernel regression: fall
            # back to the XLA attention path and say so
            print(f"[flash path failed: {e!r}] falling back to XLA "
                  f"attention", file=sys.stderr)
            cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                           attention_dropout=0.0,
                           use_pallas_attention=False,
                           max_position_embeddings=2048)
            tok_s, mfu = _bench_config(cfg, B=8, S=2048, steps=10,
                                       warmup=3, tag="gpt-125m-xla")
        # diagnostics must not kill the headline number.
        # BENCH_SKIP_SLICE keeps its historical meaning (skip ALL stderr
        # diagnostics); BENCH_SKIP_DIAGNOSTICS is an explicit alias.
        skip_diag = (os.environ.get("BENCH_SKIP_DIAGNOSTICS", "0") == "1"
                     or os.environ.get("BENCH_SKIP_SLICE", "0") == "1")
        if not skip_diag:
            try:
                _bench_flash_ab()
            except Exception as e:
                print(f"[flash-ab] failed: {e!r}", file=sys.stderr)
            try:
                _sweep_block_sizes()
            except Exception as e:
                print(f"[block-sweep] failed: {e!r}", file=sys.stderr)
            try:
                _bench_1p3b_fullstep()
            except Exception as e:
                print(f"[1.3b-fullstep] failed: {e!r}", file=sys.stderr)
        if not skip_diag:
            try:
                _bench_1p3b_slice()
            except Exception as e:
                print(f"[1.3b-slice] failed: {e!r}", file=sys.stderr)
    else:  # dev smoke path
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        tok_s, mfu = _bench_config(cfg, B=2, S=128, steps=3, warmup=1,
                                   tag="smoke")

    print(json.dumps({
        "metric": "gpt_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
