"""Benchmark harness: GPT causal-LM pretraining throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
- metric: GPT-125M tokens/sec/chip (fwd+bwd+update; bf16 activations via
  amp O1, flash-attention Pallas kernel, S=2048 — the BASELINE.json config
  #4 single-chip slice).
- vs_baseline: achieved MFU / 0.45 (the north-star ≥45% MFU target;
  BASELINE.md records no reference numbers in-tree, so the target ratio is
  the comparison axis).

Timing methodology (IMPORTANT, round-4 fix): on the tunneled TPU platform
``block_until_ready`` returns at dispatch, not completion — a host readback
(``float(loss)``) is the only true synchronization.  The timed region ends
with that readback; steps chain donated state so device execution
serializes.  The r03 number (53.7k tok/s) predates this fix.

Extra diagnostics go to stderr so stdout stays one parseable line:
- flash-vs-XLA attention check,
- an honest GPT-1.3B slice measurement: time L=2 and L=6 layer slices of
  the 1.3B config (remat + bf16), difference out the per-layer cost, and
  report the composed full-24-layer estimate labelled as an estimate.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


# bf16 peak matmul TFLOPs per chip by TPU generation (public specs);
# CPU fallback uses a nominal figure so the script still runs in dev envs.
_PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}


def _peak_flops_per_sec() -> float:
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for gen, tf in _PEAK_TFLOPS.items():
        if gen in kind:
            return tf * 1e12
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if gen in _PEAK_TFLOPS:
        return _PEAK_TFLOPS[gen] * 1e12
    return _PEAK_TFLOPS["v5e"] * 1e12


def _param_count(params) -> int:
    return sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(params))


def _flops_per_token(n_params: int, cfg, S: int) -> float:
    # 6N for fwd+bwd matmuls + causal attention term 12*L*h*S per token
    return 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * S // 2


def _build(cfg, B, S, lr=1e-4):
    """(jitted step, params, opt_state, ids, labels, key) for one config."""
    import paddle_tpu as pt
    from paddle_tpu import amp as amp_mod
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.models import GPTForCausalLM

    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    params = model.state_dict()
    opt = pt.optimizer.AdamW(learning_rate=lr, weight_decay=0.01)
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def train_step(params, opt_state, input_ids, labels, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                with amp_mod.auto_cast(level="O1", dtype="bfloat16"):
                    loss, _ = model.apply(p, input_ids, labels=labels)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_state = opt.apply_gradients(grads, params, opt_state)
        return loss, new_params, new_state

    jitted = jax.jit(train_step, donate_argnums=(0, 1))
    return jitted, model, params, opt_state, ids, labels


def _timed_steps(jitted, params, opt_state, ids, labels, steps, warmup):
    """Seconds per step with host-readback synchronization."""
    key = jax.random.key(0)
    t0 = time.perf_counter()
    for i in range(warmup):
        loss, params, opt_state = jitted(params, opt_state, ids, labels,
                                         jax.random.fold_in(key, i))
    _ = float(loss)                       # true sync (see module docstring)
    warm_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(steps):
        loss, params, opt_state = jitted(params, opt_state, ids, labels,
                                         jax.random.fold_in(key, warmup + i))
    final_loss = float(loss)              # sync INSIDE the timed region
    dt = (time.perf_counter() - t0) / steps
    return dt, final_loss, warm_t


def _bench_config(cfg, B, S, steps, warmup, tag):
    jitted, model, params, opt_state, ids, labels = _build(cfg, B, S)
    n_params = _param_count(params)
    dt, loss, warm_t = _timed_steps(jitted, params, opt_state, ids, labels,
                                    steps, warmup)
    tok_s = B * S / dt
    mfu = tok_s * _flops_per_token(n_params, cfg, S) / _peak_flops_per_sec()
    print(f"[{tag}] params={n_params / 1e6:.1f}M B={B} S={S} "
          f"compile+warmup={warm_t:.1f}s step={dt * 1e3:.1f}ms "
          f"tok/s={tok_s:.0f} mfu={mfu:.3f} loss={loss:.3f}",
          file=sys.stderr, flush=True)
    return tok_s, mfu


def _bench_1p3b_slice(S=2048, B=4):
    """Honest 1.3B methodology: full 1.3B + fp32 Adam does not fit one v5e
    chip, so measure 2- and 6-layer slices (remat on), difference out the
    per-layer cost, and compose an ESTIMATE for the 24-layer model."""
    from paddle_tpu.models import gpt_1p3b
    times = {}
    for L in (2, 6):
        cfg = gpt_1p3b(num_layers=L, hidden_dropout=0.0,
                       attention_dropout=0.0, use_recompute=True,
                       use_pallas_attention=True, dtype="bfloat16")
        jitted, model, params, opt_state, ids, labels = _build(cfg, B, S)
        dt, loss, _ = _timed_steps(jitted, params, opt_state, ids, labels,
                                   steps=5, warmup=2)
        times[L] = dt
        print(f"[1.3b-slice L={L}] step={dt * 1e3:.1f}ms loss={loss:.3f}",
              file=sys.stderr, flush=True)
    per_layer = (times[6] - times[2]) / 4
    est = times[2] + 22 * per_layer
    tok_s = B * S / est
    # full-model params for the MFU estimate
    from paddle_tpu.models import GPTForCausalLM
    cfg24 = gpt_1p3b()
    n24 = (cfg24.vocab_size * cfg24.hidden_size
           + cfg24.max_position_embeddings * cfg24.hidden_size
           + cfg24.num_layers * 12 * cfg24.hidden_size ** 2)
    mfu = tok_s * _flops_per_token(n24, cfg24, S) / _peak_flops_per_sec()
    print(f"[1.3b-estimate] per_layer={per_layer * 1e3:.1f}ms "
          f"est_step={est * 1e3:.0f}ms est_tok/s={tok_s:.0f} "
          f"est_mfu={mfu:.3f} (ESTIMATE composed from measured slices)",
          file=sys.stderr, flush=True)


def _tpu_reachable(timeout_s: int = 420) -> bool:
    """Probe device init in a subprocess: a dead TPU tunnel makes
    jax.devices() hang indefinitely, which must not take the bench (and
    the driver's BENCH json) down with it."""
    import subprocess
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and "tpu" in out.stdout


def main():
    if os.environ.get("BENCH_CPU", "0") == "1":  # local smoke, no TPU probe
        from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
        force_virtual_cpu_mesh(1)
    elif not _tpu_reachable():
        print("[tpu unreachable after probe timeout — falling back to the "
              "CPU smoke so the bench still reports]", file=sys.stderr,
              flush=True)
        from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
        force_virtual_cpu_mesh(1)
    on_tpu = jax.devices()[0].platform != "cpu"
    from paddle_tpu.models import gpt_125m, gpt_tiny

    if on_tpu:
        try:
            cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                           attention_dropout=0.0, use_pallas_attention=True,
                           max_position_embeddings=2048)
            tok_s, mfu = _bench_config(cfg, B=8, S=2048, steps=10, warmup=3,
                                       tag="gpt-125m-flash")
        except Exception as e:
            # the headline number must survive a kernel regression: fall
            # back to the XLA attention path and say so
            print(f"[flash path failed: {e!r}] falling back to XLA "
                  f"attention", file=sys.stderr)
            cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                           attention_dropout=0.0,
                           use_pallas_attention=False,
                           max_position_embeddings=2048)
            tok_s, mfu = _bench_config(cfg, B=8, S=2048, steps=10,
                                       warmup=3, tag="gpt-125m-xla")
        if os.environ.get("BENCH_SKIP_SLICE", "0") != "1":
            try:
                _bench_1p3b_slice()
            except Exception as e:  # diagnostics must not kill the headline
                print(f"[1.3b-slice] failed: {e!r}", file=sys.stderr)
    else:  # dev smoke path
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        tok_s, mfu = _bench_config(cfg, B=2, S=128, steps=3, warmup=1,
                                   tag="smoke")

    print(json.dumps({
        "metric": "gpt_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
