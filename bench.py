"""Benchmark harness: GPT causal-LM pretraining throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
- metric: GPT-125M tokens/sec/chip (fwd+bwd+update; bf16 activations via
  amp O1, flash-attention Pallas kernel, S=2048 — the BASELINE.json config
  #4 single-chip slice).
- vs_baseline: achieved MFU / 0.45 (the north-star ≥45% MFU target;
  BASELINE.md records no reference numbers in-tree, so the target ratio is
  the comparison axis).

Timing methodology (IMPORTANT, round-4 fix): on the tunneled TPU platform
``block_until_ready`` returns at dispatch, not completion — a host readback
(``float(loss)``) is the only true synchronization.  The timed region ends
with that readback; steps chain donated state so device execution
serializes.  The r03 number (53.7k tok/s) predates this fix.

Extra diagnostics go to stderr so stdout stays one parseable line:
- flash-vs-XLA attention check,
- an honest GPT-1.3B slice measurement: time L=2 and L=6 layer slices of
  the 1.3B config (remat + bf16), difference out the per-layer cost, and
  report the composed full-24-layer estimate labelled as an estimate.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


# The peak-TFLOPs table and MFU math live in paddle_tpu.observability.mfu
# (ISSUE 3) — one definition shared by this one-shot harness and the live
# per-step MFU in hapi.Model.fit.  Imported lazily: bench must configure
# the (virtual) mesh in main() before paddle_tpu touches a backend.


def _peak_flops_per_sec() -> float:
    from paddle_tpu.observability.mfu import peak_flops_per_sec
    return peak_flops_per_sec()


def _param_count(params) -> int:
    from paddle_tpu.observability.mfu import param_count
    return param_count(params)


def _flops_per_token(n_params: int, cfg, S: int) -> float:
    # 6N for fwd+bwd matmuls + causal attention term 12*L*h*S per token
    from paddle_tpu.observability.mfu import flops_per_token
    return flops_per_token(n_params, num_layers=cfg.num_layers,
                           hidden_size=cfg.hidden_size, seq_len=S,
                           causal=True)


def _emit_diag(kind: str, **fields) -> None:
    """Mirror a stderr diagnostic as a structured telemetry record: with
    a metrics sink attached (``PTPU_METRICS_DIR``, or any sink on the
    global registry) every bench diagnostic also lands on the JSONL
    timeline as ``bench.<kind>``; with none attached this is a no-op —
    stdout stays one parseable JSON line either way."""
    from paddle_tpu.observability import get_registry
    get_registry().emit("bench." + kind, **fields)


def _build(cfg, B, S, lr=1e-4, opt_factory=None):
    """(jitted step, params, opt_state, ids, labels, key) for one config."""
    import paddle_tpu as pt
    from paddle_tpu import amp as amp_mod
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.models import GPTForCausalLM

    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    params = model.state_dict()
    if opt_factory is None:
        opt = pt.optimizer.AdamW(learning_rate=lr, weight_decay=0.01)
    else:
        opt = opt_factory(lr)
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def train_step(params, opt_state, input_ids, labels, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                with amp_mod.auto_cast(level="O1", dtype="bfloat16"):
                    loss, _ = model.apply(p, input_ids, labels=labels)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_state = opt.apply_gradients(grads, params, opt_state)
        return loss, new_params, new_state

    from paddle_tpu.observability.compilation import track_jit
    jitted = track_jit(jax.jit(train_step, donate_argnums=(0, 1)),
                       name="bench.gpt_step",
                       arg_names=("params", "opt_state", "inputs",
                                  "labels", "key"))
    return jitted, model, params, opt_state, ids, labels


def _timed_steps(jitted, params, opt_state, ids, labels, steps, warmup):
    """Seconds per step with host-readback synchronization."""
    key = jax.random.key(0)
    t0 = time.perf_counter()
    for i in range(warmup):
        loss, params, opt_state = jitted(params, opt_state, ids, labels,
                                         jax.random.fold_in(key, i))
    _ = float(loss)                       # true sync (see module docstring)
    warm_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(steps):
        loss, params, opt_state = jitted(params, opt_state, ids, labels,
                                         jax.random.fold_in(key, warmup + i))
    final_loss = float(loss)              # sync INSIDE the timed region
    dt = (time.perf_counter() - t0) / steps
    return dt, final_loss, warm_t


def _bench_config(cfg, B, S, steps, warmup, tag):
    jitted, model, params, opt_state, ids, labels = _build(cfg, B, S)
    n_params = _param_count(params)
    dt, loss, warm_t = _timed_steps(jitted, params, opt_state, ids, labels,
                                    steps, warmup)
    tok_s = B * S / dt
    mfu = tok_s * _flops_per_token(n_params, cfg, S) / _peak_flops_per_sec()
    print(f"[{tag}] params={n_params / 1e6:.1f}M B={B} S={S} "
          f"compile+warmup={warm_t:.1f}s step={dt * 1e3:.1f}ms "
          f"tok/s={tok_s:.0f} mfu={mfu:.3f} loss={loss:.3f}",
          file=sys.stderr, flush=True)
    _emit_diag("config", tag=tag, params_m=n_params / 1e6, batch=B,
               seqlen=S, warmup_s=warm_t, step_ms=dt * 1e3, tok_s=tok_s,
               mfu=mfu, loss=loss)
    return tok_s, mfu


def _bench_slice_estimate(cfg_factory, slice_layers, B, S=2048, tag="slice",
                          opt_factory=None, artifact=None):
    """Honest slice-differencing methodology shared by the 1.3B and 6.7B
    estimates: models whose full depth (or full optimizer state) cannot fit
    one chip are measured as two layer-count slices; the per-layer cost is
    differenced out and composed into a full-depth ESTIMATE, always
    labelled as such.  ``slice_layers`` is the (small, large) pair; the
    full depth comes from ``cfg_factory()``'s default num_layers."""
    import gc
    lo, hi = slice_layers
    times = {}
    for L in (lo, hi):
        cfg = cfg_factory(num_layers=L, hidden_dropout=0.0,
                          attention_dropout=0.0, use_recompute=True,
                          use_pallas_attention=True, dtype="bfloat16")
        jitted, model, params, opt_state, ids, labels = _build(
            cfg, B, S, opt_factory=opt_factory)
        dt, loss, _ = _timed_steps(jitted, params, opt_state, ids, labels,
                                   steps=5, warmup=2)
        times[L] = dt
        print(f"[{tag} L={L}] step={dt * 1e3:.1f}ms loss={loss:.3f}",
              file=sys.stderr, flush=True)
        _emit_diag("slice", tag=tag, num_layers=L, step_ms=dt * 1e3,
                   loss=loss)
        # drop this slice's device buffers before building the next/bigger
        # one — leftovers OOM the large slice on a 16GB chip
        del jitted, model, params, opt_state, ids, labels
        gc.collect()
    per_layer = (times[hi] - times[lo]) / (hi - lo)
    cfg_full = cfg_factory()
    est = times[lo] + (cfg_full.num_layers - lo) * per_layer
    tok_s = B * S / est
    n_full = (cfg_full.vocab_size * cfg_full.hidden_size
              + cfg_full.max_position_embeddings * cfg_full.hidden_size
              + cfg_full.num_layers * 12 * cfg_full.hidden_size ** 2)
    mfu = tok_s * _flops_per_token(n_full, cfg_full, S) / _peak_flops_per_sec()
    print(f"[{tag}-estimate] per_layer={per_layer * 1e3:.1f}ms "
          f"est_step={est * 1e3:.0f}ms est_tok/s={tok_s:.0f} "
          f"est_mfu={mfu:.3f} (ESTIMATE composed from measured slices)",
          file=sys.stderr, flush=True)
    _emit_diag("slice_estimate", tag=tag, per_layer_ms=per_layer * 1e3,
               est_step_ms=est * 1e3, est_tok_s=tok_s, est_mfu=mfu,
               estimate=True)
    if artifact is not None:
        _write_artifact(artifact, {
            "slice_step_ms": {str(k): v * 1e3 for k, v in times.items()},
            "per_layer_ms": per_layer * 1e3, "est_step_ms": est * 1e3,
            "est_tok_per_sec": tok_s, "est_mfu": mfu,
            "note": "estimate composed from measured layer slices; the "
                    "full model does not fit a single 16GB chip"})
    return tok_s, mfu


def _bench_1p3b_slice(S=2048, B=4):
    """1.3B + fp32 Adam does not fit one chip: 2-/6-layer slice estimate
    (the measured full step with SGD lives in _bench_1p3b_fullstep)."""
    from paddle_tpu.models import gpt_1p3b
    _bench_slice_estimate(gpt_1p3b, (2, 6), B=B, S=S, tag="1.3b-slice")


def _bench_1p3b_fullstep(S=2048, B=4):
    """MEASURED full 24-layer GPT-1.3B step on one chip (VERDICT r4
    weak #8): real hidden/layer/head dims AND the real 50304 vocab —
    feasible on a single 16GB chip because the fused linear CE
    (ops/fused.py) never materializes [B, S, V] logits; the optimizer is
    SGD so fp32 params+grads fit HBM (bf16 activations + remat).  Falls
    back to the historical reduced-vocab 8k variant if HBM is exceeded.
    MFU is computed against the measured variant's own FLOPs — a measured
    number, not an estimate.  Measured r5 on v5e: B=4 → MFU 0.489."""
    import paddle_tpu as pt
    from paddle_tpu.models import gpt_1p3b
    for vocab, tag in ((50304, "full-vocab"), (8192, "reduced-vocab 8k")):
        cfg = gpt_1p3b(vocab_size=vocab, hidden_dropout=0.0,
                       attention_dropout=0.0, use_recompute=True,
                       use_pallas_attention=True, dtype="bfloat16")
        try:
            jitted, model, params, opt_state, ids, labels = _build(
                cfg, B, S, opt_factory=lambda lr: pt.optimizer.SGD(
                    learning_rate=lr))
            n_params = _param_count(params)
            dt, loss, warm_t = _timed_steps(jitted, params, opt_state, ids,
                                            labels, steps=5, warmup=2)
        except Exception as e:
            print(f"[1.3b-fullstep {tag}] failed ({repr(e)[:120]}); "
                  f"trying smaller", file=sys.stderr, flush=True)
            # drop the failed attempt's device buffers (fp32 full-vocab
            # params + executable) before building the fallback, or the
            # fallback OOMs on the leftovers
            try:
                del jitted, model, params, opt_state, ids, labels
            except NameError:
                pass            # _build itself failed: nothing bound
            import gc
            gc.collect()
            continue
        tok_s = B * S / dt
        mfu = (tok_s * _flops_per_token(n_params, cfg, S)
               / _peak_flops_per_sec())
        print(f"[1.3b-fullstep-measured] params={n_params / 1e6:.0f}M "
              f"({tag}, SGD) B={B} S={S} step={dt * 1e3:.0f}ms "
              f"tok/s={tok_s:.0f} mfu={mfu:.3f} loss={loss:.3f}",
              file=sys.stderr, flush=True)
        _emit_diag("fullstep_1p3b", tag=tag, params_m=n_params / 1e6,
                   batch=B, seqlen=S, step_ms=dt * 1e3, tok_s=tok_s,
                   mfu=mfu, loss=loss)
        return {"tok_s": tok_s, "mfu": mfu, "step_ms": dt * 1e3,
                "params_m": n_params / 1e6, "vocab": vocab}
    return None


def _bench_flash_ab(B=8, S=2048, steps=8, warmup=3):
    """Recorded flash-vs-XLA attention A/B on the same 125M config
    (VERDICT r4 #1): both paths timed identically; artifact written to
    benchmarks/flash_ab.json."""
    from paddle_tpu.models import gpt_125m
    rows = {}
    for tag, pallas in (("flash", True), ("xla", False)):
        cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                       attention_dropout=0.0, use_pallas_attention=pallas,
                       max_position_embeddings=S)
        jitted, model, params, opt_state, ids, labels = _build(cfg, B, S)
        dt, loss, _ = _timed_steps(jitted, params, opt_state, ids, labels,
                                   steps, warmup)
        rows[tag] = {"step_ms": dt * 1e3, "tok_s": B * S / dt}
        print(f"[flash-ab {tag}] step={dt * 1e3:.1f}ms "
              f"tok/s={B * S / dt:.0f}", file=sys.stderr, flush=True)
    rows["speedup_flash_over_xla"] = (rows["xla"]["step_ms"]
                                      / rows["flash"]["step_ms"])
    _emit_diag("flash_ab", flash_step_ms=rows["flash"]["step_ms"],
               xla_step_ms=rows["xla"]["step_ms"],
               speedup=rows["speedup_flash_over_xla"])
    _write_artifact("flash_ab.json", rows)
    return rows


def _xla_memory(jitted, *args):
    """Compiled-program memory analysis (temp/argument/output bytes) for a
    (possibly track_jit-wrapped) jitted step — the platform-independent
    peak-HBM proxy behind the fused-op memory claims.  None when the
    backend doesn't expose it."""
    try:
        fn = getattr(jitted, "__wrapped_fn__", jitted)
        mem = fn.lower(*args).compile().memory_analysis()
        return {"temp_bytes": int(mem.temp_size_in_bytes),
                "argument_bytes": int(mem.argument_size_in_bytes),
                "output_bytes": int(mem.output_size_in_bytes)}
    except Exception as e:
        print(f"[xla-memory] unavailable: {repr(e)[:80]}", file=sys.stderr,
              flush=True)
        return None


def _ab_train_legs(legs, B, S, steps, warmup, build=None):
    """Shared A/B harness (ISSUE 7): time each (tag, cfg) leg identically
    via _build/_timed_steps, with a compile-tracker reset around each leg
    so the artifact records the compile contract (exactly one compile per
    step shape, zero retraces/storms) alongside the step time.

    ``build`` (ISSUE 8): per-leg builder with _build's return contract
    ``(jitted, model, params, opt_state, ids, labels)`` — the dp-comm A/B
    passes one that closes over a leg's gradient-sync mode; the default
    is the single-chip GPT step builder."""
    from paddle_tpu.observability.compilation import get_tracker, \
        reset_tracker
    import gc
    build = build or _build
    rows = {}
    for tag, cfg in legs:
        reset_tracker()
        jitted, model, params, opt_state, ids, labels = build(cfg, B, S)
        mem = _xla_memory(jitted, params, opt_state, ids, labels,
                          jax.random.key(0))
        dt, loss, _ = _timed_steps(jitted, params, opt_state, ids, labels,
                                   steps, warmup)
        stats = get_tracker().stats("bench.gpt_step")
        rows[tag] = {"step_ms": dt * 1e3, "tok_s": B * S / dt,
                     "loss": loss, "memory": mem,
                     "compiles": stats["traces"],
                     "retraces": stats["retraces"],
                     "storms": stats["storms"]}
        print(f"[{tag}] step={dt * 1e3:.1f}ms tok/s={B * S / dt:.0f} "
              f"compiles={stats['traces']} retraces={stats['retraces']} "
              f"temp={mem['temp_bytes'] / 1e6:.1f}MB" if mem else
              f"[{tag}] step={dt * 1e3:.1f}ms tok/s={B * S / dt:.0f} "
              f"compiles={stats['traces']} retraces={stats['retraces']}",
              file=sys.stderr, flush=True)
        del jitted, model, params, opt_state, ids, labels
        gc.collect()
    reset_tracker()
    return rows


def _bench_fused_block_ab(B=8, S=2048, steps=8, warmup=3, cfg_factory=None,
                          dropout=0.1, artifact=True):
    """Fused-block vs unfused A/B on the same config (ISSUE 7 acceptance):
    GPTConfig.use_fused_block routes the whole block through
    ops/fused_block.py; both paths timed identically on the realistic
    training config (dropout on — the fused path's counter-hash dropout
    replaces three threefry mask draws per layer).  Artifact:
    benchmarks/fused_block_ab.json, including the compile contract (one
    compile per shape, zero retraces/storms) for the fused leg."""
    if cfg_factory is None:
        from paddle_tpu.models import gpt_125m
        cfg_factory = lambda **kw: gpt_125m(  # noqa: E731
            dtype="bfloat16", use_pallas_attention=True,
            max_position_embeddings=S, **kw)
    legs = [(tag, cfg_factory(hidden_dropout=dropout,
                              attention_dropout=dropout,
                              use_fused_block=fused))
            for tag, fused in (("fused_block", True), ("unfused", False))]
    rows = _ab_train_legs(legs, B, S, steps, warmup)
    rows["speedup_fused_over_unfused"] = (rows["unfused"]["step_ms"]
                                          / rows["fused_block"]["step_ms"])
    _emit_diag("fused_block_ab",
               fused_step_ms=rows["fused_block"]["step_ms"],
               unfused_step_ms=rows["unfused"]["step_ms"],
               speedup=rows["speedup_fused_over_unfused"],
               fused_retraces=rows["fused_block"]["retraces"])
    if artifact:
        _write_artifact("fused_block_ab.json", rows)
    return rows


def _bench_fused_ce_ab(B=8, S=2048, steps=8, warmup=3, cfg_factory=None,
                       artifact=True, op_memory=True):
    """Fused vs unfused LM-loss A/B (ISSUE 7 satellite): the
    linear_softmax_cross_entropy memory claim in ops/fused.py's module
    note, backed by a checked-in artifact — step time plus the compiled
    program's temp-allocation bytes (the [B, S, V] logits the fused path
    never materializes).  Artifact: benchmarks/fused_ce_ab.json."""
    if cfg_factory is None:
        from paddle_tpu.models import gpt_125m
        cfg_factory = lambda **kw: gpt_125m(  # noqa: E731
            dtype="bfloat16", use_pallas_attention=True,
            hidden_dropout=0.0, attention_dropout=0.0,
            max_position_embeddings=S, **kw)
    legs = [(tag, cfg_factory(fused_lm_loss=fused))
            for tag, fused in (("fused_ce", True), ("unfused", False))]
    rows = _ab_train_legs(legs, B, S, steps, warmup)
    if op_memory:
        rows["op_level"] = _fused_ce_op_memory()
    rows["speedup_fused_over_unfused"] = (rows["unfused"]["step_ms"]
                                          / rows["fused_ce"]["step_ms"])
    if (rows["fused_ce"]["memory"] and rows["unfused"]["memory"]):
        rows["temp_bytes_saved"] = (
            rows["unfused"]["memory"]["temp_bytes"]
            - rows["fused_ce"]["memory"]["temp_bytes"])
    _emit_diag("fused_ce_ab",
               fused_step_ms=rows["fused_ce"]["step_ms"],
               unfused_step_ms=rows["unfused"]["step_ms"],
               temp_saved=rows.get("temp_bytes_saved"))
    if artifact:
        _write_artifact("fused_ce_ab.json", rows)
    return rows


def _build_comm_leg(leg, B, S, lr=1e-3):
    """_build-contract builder for one dp-comm leg (ISSUE 8): the whole
    device set becomes a dp mesh and the leg decides how gradients move —

    - ``fp32``:    exact all-reduce gradient sync, replicated Adam;
    - ``int8_ef``: blockwise-int8 two-phase sync with error feedback
                   (the residual rides the opt_state bundle, stacked
                   along dp so each rank keeps its own);
    - ``zero1``:   ShardedOptimizer — reduce-scatter grads, 1/dp-shard
                   Adam update, all-gather params.

    ``leg`` is ``{"mode": ..., "cfg": GPTConfig}``; returns _build's
    ``(jitted, model, params, opt_state, ids, labels)`` so the shared
    _ab_train_legs harness times every leg identically."""
    import paddle_tpu as pt
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.distributed import comm as comm_mod
    from paddle_tpu.distributed.comm import CommConfig
    from paddle_tpu.observability.compilation import track_jit
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mode, cfg = leg["mode"], leg["cfg"]
    n = jax.device_count()
    assert B % n == 0, f"batch {B} not divisible by dp={n}"
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    params = model.state_dict()
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def local_grads(p, ids, labels, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                loss, _ = model.apply(p, ids, labels=labels)
            return loss
        return jax.value_and_grad(loss_fn)(p)

    data_spec = P("dp", None)
    if mode == "zero1":
        opt = comm_mod.ShardedOptimizer(pt.optimizer.Adam(learning_rate=lr),
                                        axis="dp", num_shards=n)
        state_specs = opt.state_sharding_specs()

        def step(p, state, ids, labels, key):
            loss, grads = local_grads(p, ids, labels, key)
            new_p, new_state = opt.apply_gradients(grads, p, state)
            return lax.pmean(loss, "dp"), new_p, new_state

        smapped = shard_map(step, mesh=mesh,
                            in_specs=(P(), state_specs, data_spec,
                                      data_spec, P()),
                            out_specs=(P(), P(), state_specs),
                            check_rep=False)
        opt_state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                                      out_specs=state_specs,
                                      check_rep=False))(params)
    else:
        ccfg = (CommConfig(dtype="int8", error_feedback=True)
                if mode == "int8_ef" else CommConfig())
        opt = pt.optimizer.Adam(learning_rate=lr)
        bundle = {"opt": opt.init(params)}
        bundle_specs = {"opt": jax.tree_util.tree_map(lambda _: P(),
                                                      bundle["opt"])}
        if ccfg.error_feedback:
            # per-rank residuals: global leaves are the n per-rank
            # param-shaped residuals concatenated along dim 0
            bundle["resid"] = jax.tree_util.tree_map(
                lambda p: jnp.zeros((n * p.shape[0],) + tuple(p.shape[1:]),
                                    jnp.float32), params)
            bundle_specs["resid"] = comm_mod.stacked_specs(params)

        def step(p, bundle, ids, labels, key):
            loss, grads = local_grads(p, ids, labels, key)
            synced, resid = comm_mod.sync_gradients(
                grads, config=ccfg, group="dp",
                residual=bundle.get("resid"), op="avg")
            new_p, new_os = opt.apply_gradients(synced, p, bundle["opt"])
            out = {"opt": new_os}
            if resid is not None:
                out["resid"] = resid
            return lax.pmean(loss, "dp"), new_p, out

        smapped = shard_map(step, mesh=mesh,
                            in_specs=(P(), bundle_specs, data_spec,
                                      data_spec, P()),
                            out_specs=(P(), P(), bundle_specs),
                            check_rep=False)
        opt_state = bundle
    jitted = track_jit(jax.jit(smapped, donate_argnums=(0, 1)),
                       name="bench.gpt_step",
                       arg_names=("params", "opt_state", "inputs",
                                  "labels", "key"))
    return jitted, model, params, opt_state, ids, labels


def _opt_state_bytes_per_replica(opt_state, mode, n) -> int:
    """Optimizer-state footprint one replica actually holds — the
    ZeRO-1 claim in numbers (flat master + slots are 1/n per replica)."""
    total = sum(leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree_util.tree_leaves(opt_state)
                if hasattr(leaf, "size"))
    return total // n if mode == "zero1" else total


def _bench_comm_ab(B=8, S=2048, steps=8, warmup=3, cfg_factory=None,
                   artifact=True):
    """dp-comm A/B (ISSUE 8): fp32 all-reduce vs int8+error-feedback vs
    ZeRO-1 on the same model/data/step-count over a dp mesh spanning all
    local devices.  One row per leg via the shared _ab_train_legs
    harness: step time, final loss, the compile contract, bytes-on-wire
    per device-step from the comm package's trace-time accounting
    (``comm.bytes`` = what the exact schedule would ship,
    ``comm.compressed_bytes`` = what this leg ships), and the per-replica
    optimizer-state footprint.  Artifact: benchmarks/comm_ab.json."""
    from paddle_tpu.observability import get_registry
    n = jax.device_count()
    if n < 2:
        print("[comm-ab] skipped: needs >=2 devices for a dp axis "
              f"(have {n})", file=sys.stderr, flush=True)
        return None
    B = -(-B // n) * n          # global batch divisible by dp
    if cfg_factory is None:
        from paddle_tpu.models import gpt_125m
        cfg_factory = lambda **kw: gpt_125m(  # noqa: E731
            hidden_dropout=0.0, attention_dropout=0.0,
            max_position_embeddings=S, **kw)
    cfg = cfg_factory()
    reg = get_registry()
    rows = {}
    for mode in ("fp32", "int8_ef", "zero1"):
        raw0 = reg.counter("comm.bytes").value
        wire0 = reg.counter("comm.compressed_bytes").value
        leg_rows = _ab_train_legs([(mode, {"mode": mode, "cfg": cfg})],
                                  B, S, steps, warmup,
                                  build=_build_comm_leg)
        row = leg_rows[mode]
        # trace-time accounting: one compile per leg (asserted by the
        # compile contract) => the delta IS the per-device-step bill
        raw = reg.counter("comm.bytes").value - raw0
        wire = reg.counter("comm.compressed_bytes").value - wire0
        row["bytes_on_wire"] = int(wire)
        row["bytes_exact_equiv"] = int(raw)
        row["compress_ratio"] = (raw / wire) if wire else None
        row["opt_state_bytes_per_replica"] = None
        rows[mode] = row
        print(f"[comm-ab {mode}] wire={wire / 1e6:.2f}MB/step "
              f"(exact-equiv {raw / 1e6:.2f}MB, "
              f"ratio {row['compress_ratio']:.2f}x)",
              file=sys.stderr, flush=True)
    # per-replica optimizer-state footprint (rebuild cheaply: state
    # shapes only depend on the param tree)
    for mode in ("fp32", "zero1"):
        _, _, _, opt_state, _, _ = _build_comm_leg(
            {"mode": mode, "cfg": cfg}, B, S)
        rows[mode]["opt_state_bytes_per_replica"] = \
            _opt_state_bytes_per_replica(opt_state, mode, n)
    rows["int8_ef"]["opt_state_bytes_per_replica"] = \
        rows["fp32"]["opt_state_bytes_per_replica"]
    rows["dp_degree"] = n
    rows["int8_vs_fp32_loss_rel"] = (
        abs(rows["int8_ef"]["loss"] - rows["fp32"]["loss"])
        / max(1e-9, abs(rows["fp32"]["loss"])))
    rows["zero1_vs_fp32_loss_rel"] = (
        abs(rows["zero1"]["loss"] - rows["fp32"]["loss"])
        / max(1e-9, abs(rows["fp32"]["loss"])))
    _emit_diag("comm_ab", dp=n,
               fp32_step_ms=rows["fp32"]["step_ms"],
               int8_step_ms=rows["int8_ef"]["step_ms"],
               zero1_step_ms=rows["zero1"]["step_ms"],
               int8_compress_ratio=rows["int8_ef"]["compress_ratio"],
               int8_loss_rel=rows["int8_vs_fp32_loss_rel"],
               zero1_loss_rel=rows["zero1_vs_fp32_loss_rel"])
    if artifact:
        _write_artifact("comm_ab.json", rows)
    return rows


# smoke-model shapes for the fused A/Bs (shared by main()'s CPU branch and
# the ci.sh kernels-tier smoke so both measure the same thing): big enough
# that the deltas clear timer noise on a dev box, small enough for CI
def _smoke_block_cfg(**kw):
    from paddle_tpu.models import gpt_tiny
    return gpt_tiny(hidden_size=256, num_heads=8, num_layers=4,
                    max_position_embeddings=256, **kw)


def _smoke_ce_cfg(**kw):
    from paddle_tpu.models import gpt_tiny
    return gpt_tiny(vocab_size=8192, max_position_embeddings=256,
                    hidden_dropout=0.0, attention_dropout=0.0, **kw)


_SMOKE_FUSED_BLOCK_AB = dict(B=4, S=256, steps=6, warmup=2,
                             cfg_factory=_smoke_block_cfg)
_SMOKE_FUSED_CE_AB = dict(B=4, S=256, steps=6, warmup=2,
                          cfg_factory=_smoke_ce_cfg)


def _smoke_comm_cfg(**kw):
    from paddle_tpu.models import gpt_tiny
    return gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0,
                    max_position_embeddings=128, **kw)


# 30 steps is the ISSUE 8 acceptance length: enough for the int8+EF leg's
# loss trajectory to visibly track (or visibly diverge from) fp32
_SMOKE_COMM_AB = dict(B=8, S=128, steps=30, warmup=2,
                      cfg_factory=_smoke_comm_cfg)


_SMOKE_INTEGRITY_AB = dict(B=4, S=256, steps=6, warmup=2,
                           cfg_factory=_smoke_block_cfg)


def _bench_integrity_overhead(B=4, S=256, steps=6, warmup=2,
                              cfg_factory=None, interval=None,
                              artifact=True):
    """Integrity-guard overhead A/B (ISSUE 11 acceptance): the per-check
    cost of the tree fingerprint (jitted digest + board publish +
    compare), amortized over the default ``PTPU_INTEGRITY_EVERY``
    interval, against the same smoke step the fused-block A/B times.
    The digest runs OUTSIDE the jitted train step (``note_step_ok``), so
    the honest measure is per-check wall time over ``interval *
    step_time``, not a fused-leg timing diff.  Artifact:
    benchmarks/integrity_overhead.json."""
    from paddle_tpu.distributed.fingerprint import TreeFingerprint
    from paddle_tpu.supervisor.integrity import IntegrityGuard, \
        default_interval
    import tempfile

    cfg_factory = cfg_factory or _smoke_block_cfg
    interval = default_interval() if interval is None else int(interval)
    rows = _ab_train_legs([("base", cfg_factory())], B, S, steps, warmup)
    _jitted, _model, params, opt_state, _ids, _labels = _build(
        cfg_factory(), B, S)
    state = {"params": dict(params), "opt": opt_state}
    fp = TreeFingerprint()
    fp.digest(state).tree                     # compile, out of the timing
    reps = max(3, steps)
    t0 = time.perf_counter()
    for _ in range(reps):
        fpr = fp.digest(state)
        _ = fpr.tree                          # the one scalar readback
    digest_ms = (time.perf_counter() - t0) / reps * 1e3
    with tempfile.TemporaryDirectory() as run_dir:
        guard = IntegrityGuard(run_dir, every=interval, expected=1)
        t0 = time.perf_counter()
        for i in range(reps):
            guard.publish((i + 1) * interval, fpr)
            guard.compare((i + 1) * interval)
        board_ms = (time.perf_counter() - t0) / reps * 1e3
    check_ms = digest_ms + board_ms
    overhead = check_ms / (interval * rows["base"]["step_ms"])
    rows["integrity"] = {"digest_ms": digest_ms, "board_ms": board_ms,
                         "check_ms": check_ms, "interval": interval,
                         "overhead_frac": overhead}
    print(f"[integrity-overhead] digest={digest_ms:.2f}ms "
          f"board={board_ms:.2f}ms step={rows['base']['step_ms']:.1f}ms "
          f"every={interval} → {overhead:.3%} of step time",
          file=sys.stderr, flush=True)
    _emit_diag("integrity_overhead", digest_ms=digest_ms,
               board_ms=board_ms, interval=interval,
               step_ms=rows["base"]["step_ms"], overhead_frac=overhead)
    if artifact:
        _write_artifact("integrity_overhead.json", rows)
    return rows


def _fused_ce_op_memory(B=2, S=512, H=256, V=50304, chunk=128):
    """Op-level rendering of the fused-CE memory claim: loss+grad of
    linear_softmax_cross_entropy at a chunk < S (the scan engages) vs the
    materialized-logits composition, compared by compiled temp bytes.
    The model-level smoke legs can degenerate to one chunk == the whole
    sequence, which hides exactly the [B, S, V] temps this op exists to
    avoid — this measurement pins them."""
    from paddle_tpu.ops.fused import linear_softmax_cross_entropy
    from paddle_tpu.distributed.mp_ops import parallel_cross_entropy
    rng = np.random.RandomState(0)
    hidden = jnp.asarray(rng.randn(B, S, H) * 0.3, jnp.float32)
    table = jnp.asarray(rng.randn(V, H) * 0.3, jnp.float32)
    labels = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)

    def fused(h, t):
        return linear_softmax_cross_entropy(h, t, labels, seq_chunk=chunk)

    def unfused(h, t):
        logits = jnp.einsum("bsh,vh->bsv", h, t).astype(jnp.float32)
        return parallel_cross_entropy(logits, labels, reduction="mean")

    out = {"batch": B, "seqlen": S, "hidden": H, "vocab": V,
           "seq_chunk": chunk}
    for tag, fn in (("fused", fused), ("unfused", unfused)):
        g = jax.jit(jax.grad(fn, argnums=(0, 1)))
        out[tag] = _xla_memory(g, hidden, table)
    if out["fused"] and out["unfused"]:
        out["temp_bytes_saved"] = (out["unfused"]["temp_bytes"]
                                   - out["fused"]["temp_bytes"])
    return out


def _bench_6p7b_slice(S=2048, B=1):
    """GPT-6.7B half of BASELINE row #4 (single-chip evidence): the full
    32-layer h=4096 model cannot fit one 16GB chip even with SGD (params
    alone are 27GB fp32), so compose the 2-/4-layer slice estimate (remat,
    SGD, fused CE, real 50304 vocab) via _bench_slice_estimate."""
    import paddle_tpu as pt
    from paddle_tpu.models import gpt_6p7b
    _bench_slice_estimate(
        gpt_6p7b, (2, 4), B=B, S=S, tag="6.7b-slice",
        opt_factory=lambda lr: pt.optimizer.SGD(learning_rate=lr),
        artifact="gpt6p7b_slice.json")


def _bench_resnet50(B=128, hw=224, steps=10, warmup=3, depth=50):
    """BASELINE.md row #2: ResNet-50 ImageNet-config train step (synthetic
    224x224 batch, Momentum+weight-decay, bf16 amp O1).  Reports img/s/chip
    and an MFU against the well-known 4.09 GFLOPs/img forward cost (x3 for
    fwd+bwd).  Artifact: benchmarks/resnet50.json.  The smaller
    ``depth``/``hw`` knobs exist only for the CPU smoke test
    (tests/test_bench_smoke.py), which gets no MFU and no artifact."""
    import paddle_tpu as pt
    from paddle_tpu import amp as amp_mod
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.vision.models import resnet18, resnet50
    import paddle_tpu.nn.functional as F

    pt.seed(0)
    model = resnet50() if depth == 50 else resnet18()
    model.train()
    trainable = model.trainable_variables()
    rest = {k: v for k, v in model.state_dict().items() if k not in trainable}
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                weight_decay=1e-4)
    opt_state = opt.init(trainable)
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.randn(B, 3, hw, hw) * 0.5, jnp.float32)
    labels = jnp.asarray(rng.randint(0, 1000, (B,)), jnp.int32)

    def train_step(params, opt_state, x, y, key):
        def loss_fn(tp):
            with fw_random.key_scope(key):
                with amp_mod.auto_cast(level="O1", dtype="bfloat16"):
                    logits, newv = model.apply({**rest, **tp}, x,
                                               mutable=True)
            loss = F.cross_entropy(logits.astype(jnp.float32), y)
            return loss, newv
        (loss, _newv), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        new_params, new_state = opt.apply_gradients(grads, params, opt_state)
        return loss, new_params, new_state

    from paddle_tpu.observability.compilation import track_jit
    jitted = track_jit(jax.jit(train_step, donate_argnums=(0, 1)),
                       name="bench.resnet_step",
                       arg_names=("params", "opt_state", "inputs",
                                  "labels", "key"))
    dt, loss, warm_t = _timed_steps(jitted, trainable, opt_state, imgs,
                                    labels, steps=steps, warmup=warmup)
    img_s = B / dt
    real_config = depth == 50 and hw == 224
    print(f"[resnet{depth}] B={B} hw={hw} compile+warmup={warm_t:.1f}s "
          f"step={dt * 1e3:.1f}ms img/s={img_s:.0f} loss={loss:.3f}",
          file=sys.stderr, flush=True)
    if real_config:
        # 4.089 GFLOPs is specifically ResNet-50 fwd at 224x224; the MFU
        # and the recorded artifact only make sense on that config
        mfu = img_s * 3 * 4.089e9 / _peak_flops_per_sec()
        print(f"[resnet50] mfu={mfu:.3f}", file=sys.stderr, flush=True)
        _emit_diag("resnet50", batch=B, step_ms=dt * 1e3, img_s=img_s,
                   mfu=mfu)
        _write_artifact("resnet50.json", {
            "batch": B, "step_ms": dt * 1e3, "img_per_sec": img_s,
            "mfu": mfu})
    return img_s


def _bench_bert_base(B=16, S=512, steps=10, warmup=3, cfg_factory=None):
    """BASELINE.md row #3, measured on the real BERT-base model (not the
    GPT proxy): MLM+NSP pretraining step, 15% masking, AdamW, bf16 amp O1,
    flash (non-causal) attention path.  Artifact: benchmarks/bert_base.json."""
    import paddle_tpu as pt
    from paddle_tpu import amp as amp_mod
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.models.bert import bert_base, BertForPretraining

    factory = cfg_factory or bert_base
    cfg = factory(dtype="bfloat16", hidden_dropout=0.0,
                  attention_dropout=0.0,
                  use_pallas_attention=cfg_factory is None)
    pt.seed(0)
    model = BertForPretraining(cfg)
    model.train()
    params = model.state_dict()
    opt = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01)
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    mask = rng.rand(B, S) < 0.15
    mlm = np.where(mask, rng.randint(0, cfg.vocab_size, (B, S)), -100)
    mlm = jnp.asarray(mlm, jnp.int32)
    nsp = jnp.asarray(rng.randint(0, 2, (B,)), jnp.int32)

    def train_step(params, opt_state, ids, mlm, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                with amp_mod.auto_cast(level="O1", dtype="bfloat16"):
                    loss, _ = model.apply(p, ids, mlm_labels=mlm,
                                          nsp_labels=nsp)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_state = opt.apply_gradients(grads, params, opt_state)
        return loss, new_params, new_state

    from paddle_tpu.observability.compilation import track_jit
    jitted = track_jit(jax.jit(train_step, donate_argnums=(0, 1)),
                       name="bench.bert_step",
                       arg_names=("params", "opt_state", "inputs",
                                  "labels", "key"))
    dt, loss, warm_t = _timed_steps(jitted, params, opt_state, ids, mlm,
                                    steps=steps, warmup=warmup)
    seq_s = B / dt
    n_params = _param_count(params)
    # 6N per token + bidirectional attention 12*L*h*S (no causal halving)
    from paddle_tpu.observability.mfu import flops_per_token
    flops_tok = flops_per_token(n_params, num_layers=cfg.num_layers,
                                hidden_size=cfg.hidden_size, seq_len=S,
                                causal=False)
    mfu = seq_s * S * flops_tok / _peak_flops_per_sec()
    tag = "bert-base" if cfg_factory is None else "bert-smoke"
    print(f"[{tag}] params={n_params / 1e6:.1f}M B={B} S={S} "
          f"compile+warmup={warm_t:.1f}s step={dt * 1e3:.1f}ms "
          f"seq/s={seq_s:.0f} mfu={mfu:.3f} loss={loss:.3f}",
          file=sys.stderr, flush=True)
    _emit_diag("bert", tag=tag, params_m=n_params / 1e6, batch=B,
               seqlen=S, step_ms=dt * 1e3, seq_s=seq_s, mfu=mfu,
               loss=loss)
    if cfg_factory is None:      # only record the real bert-base config
        _write_artifact("bert_base.json", {
            "batch": B, "seqlen": S, "step_ms": dt * 1e3,
            "seq_per_sec": seq_s, "mfu": mfu})
    return seq_s


def _sweep_seqlen_ab(bh=24, d=64, seqlens=(2048, 4096, 8192), steps=5,
                     artifact=True):
    """Attention-only flash-vs-XLA A/B across sequence lengths (fwd+bwd,
    causal, bf16).  The fused path's advantage is O(S^2) memory traffic
    avoided, so it grows with S; artifact benchmarks/flash_seqlen_ab.json
    is the evidence behind the per-shape path policy.  ``seqlens``/
    ``steps``/``artifact`` exist for the CPU smoke test, which records
    nothing."""
    from paddle_tpu.ops.flash_attention import flash_attention

    def xla_attn(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * (d ** -0.5)
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    results = {}
    for S in seqlens:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
        k = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
        v = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
        row = {}
        for tag, fn in (("flash", lambda q_, k_, v_: flash_attention(
                            q_, k_, v_, causal=True)),
                        ("xla", xla_attn)):
            def loss(q_, k_, v_, _fn=fn):
                return jnp.sum(_fn(q_, k_, v_).astype(jnp.float32) ** 2)
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            try:
                out = g(q, k, v)
                _ = float(out[0][0, 0, 0, 0])
                t0 = time.perf_counter()
                for _i in range(steps):
                    out = g(q, k, v)
                _ = float(out[0][0, 0, 0, 0])
                row[tag] = (time.perf_counter() - t0) / steps * 1e3
            except Exception as e:          # XLA path may OOM at long S
                row[tag] = None
                print(f"[seqlen-ab S={S} {tag}] failed: {repr(e)[:100]}",
                      file=sys.stderr, flush=True)
        if row.get("flash") and row.get("xla"):
            row["speedup_flash_over_xla"] = row["xla"] / row["flash"]
        results[str(S)] = row
        print(f"[seqlen-ab S={S}] flash={row.get('flash')}ms "
              f"xla={row.get('xla')}ms", file=sys.stderr, flush=True)
        _emit_diag("seqlen_ab", seqlen=S, flash_ms=row.get("flash"),
                   xla_ms=row.get("xla"),
                   speedup=row.get("speedup_flash_over_xla"))
    if artifact:
        _write_artifact("flash_seqlen_ab.json", results)
    return results


def _sweep_block_sizes(bh=96, S=2048, d=64):
    """Block-size sweep for the flash kernel (the artifact behind the
    block-size claim in ops/flash_attention.py::_block_sizes — measured
    512/512 = 1.6x over 128/128 on v5e): time fwd+bwd attention alone per
    (block_q, block_k); writes benchmarks/flash_block_sweep.json."""
    import importlib
    # NB: ``paddle_tpu.ops`` re-exports the ``flash_attention`` *function*,
    # shadowing the submodule attribute — ``import ... as`` would bind the
    # function, so resolve the module explicitly.
    fa_mod = importlib.import_module("paddle_tpu.ops.flash_attention")
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, bh, S, d) * 0.3, jnp.bfloat16)
    results = {}
    orig = fa_mod._block_sizes
    try:
        for b in (128, 256, 512, 1024):
            fa_mod._block_sizes = lambda sq, sk, _b=b: (_b, _b)

            def loss(q_, k_, v_):
                return jnp.sum(fa_mod.flash_attention(
                    q_, k_, v_, causal=True).astype(jnp.float32) ** 2)

            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            out = g(q, k, v)          # compile
            _ = float(out[0][0, 0, 0, 0])
            # best-of-3: single-shot timings on the tunneled chip are
            # noisy enough to invert the block ranking (seen in r05)
            dt = 1e9
            for _r in range(3):
                t0 = time.perf_counter()
                for _i in range(5):
                    out = g(q, k, v)
                _ = float(out[0][0, 0, 0, 0])
                dt = min(dt, (time.perf_counter() - t0) / 5)
            results[f"{b}/{b}"] = {"fwd_bwd_ms": dt * 1e3}
            print(f"[block-sweep {b}/{b}] fwd+bwd={dt * 1e3:.1f}ms",
                  file=sys.stderr, flush=True)
            _emit_diag("block_sweep", block=b, fwd_bwd_ms=dt * 1e3)
    finally:
        fa_mod._block_sizes = orig
    _write_artifact("flash_block_sweep.json", results)
    return results


def _write_artifact(name: str, payload) -> None:
    """Record a benchmark artifact with device provenance.  A CPU run
    NEVER overwrites an existing artifact recorded on accelerator hardware
    — dev-box invocations of the bench helpers must not replace committed
    hardware evidence with plausible-looking CPU timings."""
    import pathlib
    d = pathlib.Path(__file__).parent / "benchmarks"
    d.mkdir(exist_ok=True)
    path = d / name
    if (jax.devices()[0].platform == "cpu"
            and os.environ.get("BENCH_ALLOW_CPU_ARTIFACTS", "0") != "1"):
        print(f"[artifact] SKIPPED benchmarks/{name}: CPU runs record no "
              f"evidence (set BENCH_ALLOW_CPU_ARTIFACTS=1 to override)",
              file=sys.stderr, flush=True)
        return
    payload = dict(payload)
    payload["_meta"] = {
        "device": str(jax.devices()[0]),
        "recorded_unix": time.time(),
    }
    path.write_text(json.dumps(payload, indent=2))
    print(f"[artifact] wrote benchmarks/{name}", file=sys.stderr,
          flush=True)


def _tpu_reachable(timeout_s: int = 420) -> bool:
    """Back-compat alias: the probe lives in ``paddle_tpu.bench.harness``
    now (the matrix runner needs it too)."""
    from paddle_tpu.bench.harness import tpu_reachable
    return tpu_reachable(timeout_s)


def main():
    # why the run ended up on the device it did — stamped on the emitted
    # row so a CPU-fallback number can never be mistaken for a TPU one
    # (ISSUE 13: structured provenance, not a stderr note)
    fallback_reason = None
    if os.environ.get("BENCH_CPU", "0") == "1":  # local smoke, no TPU probe
        from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
        # BENCH_CPU_DEVICES>1 fakes a dp mesh so the comm A/B has an axis
        # to span (the ci.sh comm smoke runs with 8)
        force_virtual_cpu_mesh(int(os.environ.get("BENCH_CPU_DEVICES", "1")))
    elif not _tpu_reachable():
        print("[tpu unreachable after probe timeout — falling back to the "
              "CPU smoke so the bench still reports]", file=sys.stderr,
              flush=True)
        fallback_reason = "tpu_unreachable"
        from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
        force_virtual_cpu_mesh(1)
    on_tpu = jax.devices()[0].platform != "cpu"
    from paddle_tpu.models import gpt_125m, gpt_tiny

    if on_tpu:
        try:
            cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                           attention_dropout=0.0, use_pallas_attention=True,
                           max_position_embeddings=2048)
            tok_s, mfu = _bench_config(cfg, B=8, S=2048, steps=10, warmup=3,
                                       tag="gpt-125m-flash")
        except Exception as e:
            # the headline number must survive a kernel regression: fall
            # back to the XLA attention path and say so
            print(f"[flash path failed: {e!r}] falling back to XLA "
                  f"attention", file=sys.stderr)
            cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                           attention_dropout=0.0,
                           use_pallas_attention=False,
                           max_position_embeddings=2048)
            tok_s, mfu = _bench_config(cfg, B=8, S=2048, steps=10,
                                       warmup=3, tag="gpt-125m-xla")
        # diagnostics must not kill the headline number.
        # BENCH_SKIP_SLICE keeps its historical meaning (skip ALL stderr
        # diagnostics); BENCH_SKIP_DIAGNOSTICS is an explicit alias.
        skip_diag = (os.environ.get("BENCH_SKIP_DIAGNOSTICS", "0") == "1"
                     or os.environ.get("BENCH_SKIP_SLICE", "0") == "1")
        if not skip_diag:
            try:
                _bench_flash_ab()
            except Exception as e:
                print(f"[flash-ab] failed: {e!r}", file=sys.stderr)
            try:
                _bench_fused_block_ab()
            except Exception as e:
                print(f"[fused-block-ab] failed: {e!r}", file=sys.stderr)
            try:
                _bench_fused_ce_ab()
            except Exception as e:
                print(f"[fused-ce-ab] failed: {e!r}", file=sys.stderr)
            try:
                # dp-comm A/B (ISSUE 8): needs >=2 local devices for a dp
                # axis; single-chip runs print the skip note and move on
                _bench_comm_ab()
            except Exception as e:
                print(f"[comm-ab] failed: {e!r}", file=sys.stderr)
            try:
                _sweep_block_sizes()
            except Exception as e:
                print(f"[block-sweep] failed: {e!r}", file=sys.stderr)
            try:
                _bench_1p3b_fullstep()
            except Exception as e:
                print(f"[1.3b-fullstep] failed: {e!r}", file=sys.stderr)
            try:
                _sweep_seqlen_ab()
            except Exception as e:
                print(f"[seqlen-ab] failed: {e!r}", file=sys.stderr)
            try:
                _bench_resnet50()
            except Exception as e:
                print(f"[resnet50] failed: {e!r}", file=sys.stderr)
            try:
                _bench_bert_base()
            except Exception as e:
                print(f"[bert-base] failed: {e!r}", file=sys.stderr)
            try:
                _bench_6p7b_slice()
            except Exception as e:
                print(f"[6.7b-slice] failed: {e!r}", file=sys.stderr)
            try:
                _bench_1p3b_slice()
            except Exception as e:
                print(f"[1.3b-slice] failed: {e!r}", file=sys.stderr)
    else:  # dev smoke path
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        tok_s, mfu = _bench_config(cfg, B=2, S=128, steps=3, warmup=1,
                                   tag="smoke")
        skip_diag = (os.environ.get("BENCH_SKIP_DIAGNOSTICS", "0") == "1"
                     or os.environ.get("BENCH_SKIP_SLICE", "0") == "1")
        if not skip_diag:
            # smoke-model renderings of the fused A/Bs (the TPU branch runs
            # the 125M configs); the CPU platform gate in _write_artifact
            # governs whether evidence is recorded
            try:
                _bench_fused_block_ab(**_SMOKE_FUSED_BLOCK_AB)
            except Exception as e:
                print(f"[fused-block-ab] failed: {e!r}", file=sys.stderr)
            try:
                _bench_fused_ce_ab(**_SMOKE_FUSED_CE_AB)
            except Exception as e:
                print(f"[fused-ce-ab] failed: {e!r}", file=sys.stderr)
            try:
                _bench_comm_ab(**_SMOKE_COMM_AB)
            except Exception as e:
                print(f"[comm-ab] failed: {e!r}", file=sys.stderr)

    _emit_diag("headline", metric="gpt_tokens_per_sec_per_chip",
               tok_s=tok_s, mfu=mfu, vs_target=mfu / 0.45,
               device_kind=str(jax.devices()[0].device_kind),
               fallback_reason=fallback_reason)
    from paddle_tpu.observability import get_registry
    get_registry().flush()
    print(json.dumps({
        "metric": "gpt_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "device_kind": str(jax.devices()[0].device_kind),
        "fallback_reason": fallback_reason,
    }))


if __name__ == "__main__":
    main()
