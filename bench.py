"""Benchmark harness: GPT causal-LM pretraining throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
- metric: GPT tokens/sec/chip (fwd+bwd+update, bf16 activations, fp32 master
  weights — the BASELINE.json config #4 single-chip slice).
- vs_baseline: achieved MFU / 0.45 (the north-star ≥45% MFU target;
  BASELINE.md records no reference numbers in-tree, so the target ratio is
  the comparison axis).

Extra diagnostics go to stderr so stdout stays one parseable line.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


# bf16 peak matmul TFLOPs per chip by TPU generation (public specs);
# CPU fallback uses a nominal figure so the script still runs in dev envs.
_PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}


def _peak_flops_per_sec() -> float:
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for gen, tf in _PEAK_TFLOPS.items():
        if gen in kind:
            return tf * 1e12
    import os
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if gen in _PEAK_TFLOPS:
        return _PEAK_TFLOPS[gen] * 1e12
    return _PEAK_TFLOPS["v5e"] * 1e12


def _param_count(params) -> int:
    return sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(params))


def main():
    on_tpu = jax.devices()[0].platform != "cpu"
    import paddle_tpu as pt
    from paddle_tpu.framework import random as fw_random
    from paddle_tpu.models import GPTForCausalLM, gpt_125m, gpt_tiny

    if on_tpu:
        cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                       attention_dropout=0.0)
        B, S, steps, warmup = 8, 1024, 10, 3
    else:  # dev smoke path
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        B, S, steps, warmup = 2, 128, 3, 1

    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.train()
    params = model.state_dict()
    n_params = _param_count(params)

    opt = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01)
    opt_state = opt.init(params)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def train_step(params, opt_state, input_ids, labels, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                loss, _ = model.apply(p, input_ids, labels=labels)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_state = opt.apply_gradients(grads, params, opt_state)
        return loss, new_params, new_state

    jitted = jax.jit(train_step, donate_argnums=(0, 1))
    key = jax.random.key(0)

    t0 = time.perf_counter()
    for i in range(warmup):
        loss, params, opt_state = jitted(params, opt_state, ids, labels,
                                         jax.random.fold_in(key, i))
    loss.block_until_ready()
    print(f"compile+warmup {time.perf_counter()-t0:.1f}s loss={float(loss):.3f}",
          file=sys.stderr)

    t0 = time.perf_counter()
    for i in range(steps):
        loss, params, opt_state = jitted(params, opt_state, ids, labels,
                                         jax.random.fold_in(key, warmup + i))
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    tokens_per_sec = B * S * steps / dt
    # 6ND for fwd+bwd matmul FLOPs + attention term 12*L*h*S^2... use the
    # standard 6*N approximation plus attention: 6*N + 12*L*H*S per token
    attn_flops_per_tok = 12 * cfg.num_layers * cfg.hidden_size * S
    flops_per_tok = 6 * n_params + attn_flops_per_tok
    mfu = tokens_per_sec * flops_per_tok / _peak_flops_per_sec()

    print(f"params={n_params/1e6:.1f}M step={dt/steps*1e3:.1f}ms "
          f"tok/s={tokens_per_sec:.0f} mfu={mfu:.3f} loss={float(loss):.3f}",
          file=sys.stderr)
    print(json.dumps({
        "metric": "gpt_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
