"""Image classification with the hapi high-level API.

A small convnet on (synthetic) MNIST through the full reference recipe:
transforms → DataLoader → Model.prepare/fit/evaluate with an LR schedule.
(Swap in paddle_tpu.vision.models.mobilenet_v3_small + spatial
augmentation for a real corpus — the synthetic stand-in's signal is
pixel-aligned, so the example keeps the pipeline minimal and fast.)  Run:

    JAX_PLATFORMS=cpu python examples/image_classification.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.hapi import Model
from paddle_tpu.io import DataLoader
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.datasets import MNIST


class SmallNet(nn.Layer):
    """LeNet-ish head kept tiny so the example runs fast on CPU."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 8, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2),
            nn.Conv2D(8, 16, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2))
        self.head = nn.Sequential(nn.Flatten(),
                                  nn.Linear(16 * 7 * 7, num_classes))

    def forward(self, x):
        return self.head(self.features(x))


def main():
    np.random.seed(0)
    pt.seed(0)
    # NOTE: the synthetic MNIST stand-in carries a pixel-aligned signal,
    # so spatial augmentation (RandomCrop etc.) would wash it out — with
    # the real corpus you'd add it back
    plain = T.Compose([T.ToTensor(), T.Normalize([0.5], [0.5])])
    train = MNIST(mode="train", transform=plain, synthetic_size=2048)
    test = MNIST(mode="test", transform=plain, synthetic_size=512)

    model = Model(SmallNet())
    # T_max is in SCHEDULER STEPS; fit's LRScheduler callback steps
    # per BATCH (reference default by_step=True): 16 batches/epoch
    sched = pt.optimizer.lr.CosineAnnealingDecay(3e-3, T_max=160)
    model.prepare(pt.optimizer.Adam(learning_rate=sched),
                  nn.CrossEntropyLoss(), Accuracy())
    model.fit(DataLoader(train, batch_size=128, shuffle=True),
              epochs=10, verbose=1)
    metrics = model.evaluate(DataLoader(test, batch_size=256), verbose=0)
    print("eval:", metrics)
    assert metrics["acc"] > 0.9, "the synthetic-MNIST convnet must learn"


if __name__ == "__main__":
    main()
