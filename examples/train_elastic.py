"""Elastic-fleet training worker (ISSUE 9) — the script the launcher's
reconciliation loop drives:

    python -m paddle_tpu.distributed.launch --nnodes 2 --elastic 1:2 \
        --run_dir runs/elastic examples/train_elastic.py -- --steps 40

Each worker:

- joins the world published in ``<run_dir>/world.json`` and fences its
  checkpoint commits against the live generation
  (``ElasticTrainState.bind_world``);
- trains a tiny deterministic full-batch model — every member computes
  the identical update for a given global step, so the loss trajectory
  is width-independent by construction (the zero-communication rendering
  of replicated data parallelism: this container's CPU backend cannot
  run cross-process collectives, and the drill's parity claim must not
  depend on them);
- beats its heartbeat (generation-stamped) every step;
- the chief (lowest member id) commits a checkpoint every
  ``--save-interval`` steps and finalizes at the end;
- on a generation bump it either exits (retired from the world) or
  **rewinds to last_good_step()** and continues at the new width — one
  checkpoint interval lost, recorded as an ``elastic.rewind`` event;
- a respawned worker restores from the chief's committed chain, which is
  the cross-process state handoff the drill asserts.

Fault hook: ``PTPU_TEST_SIGKILL_STEP`` / ``PTPU_TEST_SIGKILL_RANK``
SIGKILL the matching rank at that step in generation 0 (see
``testing.faults.sigkill_at``) — the mid-run preemption the SIGKILL
drill injects.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.distributed import elastic as el
from paddle_tpu.supervisor.heartbeat import HeartbeatWriter
from paddle_tpu.supervisor.report import SupervisorReport
from paddle_tpu.testing import faults
from paddle_tpu.utils import fsio

DIM = 8


def make_batch(step: int):
    """Deterministic full-batch data for a global step — identical on
    every member, so the update (and therefore the loss trajectory) is
    independent of the world width."""
    rng = np.random.RandomState(10_000 + step)
    x = rng.randn(16, DIM).astype(np.float32)
    w_true = np.linspace(-1.0, 1.0, DIM).astype(np.float32)
    y = x @ w_true + 0.01 * rng.randn(16).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


@jax.jit
def train_step(w, x, y, lr):
    def loss_fn(w):
        err = x @ w - y
        return jnp.mean(err * err)
    loss, grad = jax.value_and_grad(loss_fn)(w)
    return w - lr * grad, loss


def wait_for_membership(run_dir: str, worker: int, timeout: float = 30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        world = el.read_world(run_dir)
        if world and worker in world["members"]:
            return world
        time.sleep(0.05)
    raise SystemExit(f"worker {worker}: never became a member of "
                     f"{el.world_path(run_dir)}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--save-interval", type=int, default=8)
    p.add_argument("--step-time", type=float, default=0.05,
                   help="simulated per-step wall time (keeps the run "
                        "alive long enough to lose a worker mid-run)")
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args(argv)

    worker = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    run_dir = os.environ.get("PTPU_RUN_DIR")
    if not run_dir:
        raise SystemExit("train_elastic.py needs PTPU_RUN_DIR (run it "
                         "under `launch --elastic --run_dir ...`)")

    os.makedirs(os.path.join(run_dir, "reports"), exist_ok=True)
    report = SupervisorReport(
        os.path.join(run_dir, "reports", f"worker-{worker}.json"))
    world = wait_for_membership(run_dir, worker)
    generation = int(world["generation"])

    mgr = el.ElasticTrainState(
        os.path.join(run_dir, "checkpoints"),
        save_interval_steps=args.save_interval,
        install_sigterm_handler=False, event_sink=report.record)
    mgr.bind_world(run_dir, generation=generation, worker_id=worker)

    heartbeat = HeartbeatWriter(run_dir, worker_id=worker)
    heartbeat.generation = generation
    heartbeat.start()
    kill_fault = faults.sigkill_at.from_env(worker)

    state, start = mgr.restore_or(
        lambda: {"w": jnp.zeros((DIM,), jnp.float32)},
        lambda: {"w": jnp.zeros((DIM,), jnp.float32)})
    report.record("worker_start", worker=worker, generation=generation,
                  start_step=start, members=world["members"])

    losses = {}
    rewinds = 0
    generations_seen = [generation]
    step = start
    while step < args.steps:
        world = el.read_world(run_dir) or world
        if int(world["generation"]) > generation:
            generation = int(world["generation"])
            generations_seen.append(generation)
            heartbeat.generation = generation
            mgr.set_generation(generation)
            if worker not in world["members"]:
                report.record("worker_retired", worker=worker,
                              generation=generation, step=step)
                heartbeat.stop()
                return 0
            # membership changed: the run re-forms from the last
            # committed step — one checkpoint interval lost, not the job
            try:
                mgr.wait()
            except (el.StaleGeneration, OSError) as e:
                report.record("pending_save_dropped", error=str(e))
            state, new_start = mgr.restore_or(
                lambda: {"w": jnp.zeros((DIM,), jnp.float32)},
                lambda: {"w": jnp.zeros((DIM,), jnp.float32)})
            report.record("elastic.rewind", worker=worker,
                          generation=generation, from_step=step,
                          to_step=new_start,
                          world_size=world["world_size"])
            rewinds += 1
            step = new_start
            continue

        kill_fault(step, generation)
        x, y = make_batch(step)
        new_w, loss = train_step(state["w"], x, y, args.lr)
        state = {"w": new_w}
        losses[str(step)] = float(loss)
        heartbeat.maybe_beat(step)
        chief = min(world["members"])
        if worker == chief:
            try:
                mgr.maybe_save(step, state)
            except el.StaleGeneration:
                continue  # the poll at loop top will pick up the world
        if args.step_time:
            time.sleep(args.step_time)
        step += 1

    chief = min((el.read_world(run_dir) or world)["members"])
    if worker == chief:
        try:
            mgr.finalize(args.steps, state)
        except el.StaleGeneration:
            pass
    heartbeat.beat(step)
    heartbeat.stop()
    result = {"worker": worker, "final_step": step,
              "final_loss": losses.get(str(args.steps - 1)),
              "rewinds": rewinds, "generations": generations_seen,
              "losses": losses}
    fsio.atomic_write_bytes(
        os.path.join(run_dir, f"result-worker-{worker}.json"),
        json.dumps(result, indent=1).encode("utf-8"))
    report.record("worker_done", **{k: v for k, v in result.items()
                                    if k != "losses"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
