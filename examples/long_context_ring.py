"""Long-context training with ring attention (context parallelism).

The sequence is sharded over the ``sp`` mesh axis and NO device ever
holds the full sequence: KV chunks rotate around the ring via ppermute
while each device accumulates its queries' online-softmax state
(distributed/sequence_parallel.py) — the TPU-native form of the
reference's long-sequence ambitions, and the capability BASELINE
configs lean on for S >> chip HBM.  Run:

    python examples/long_context_ring.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh

force_virtual_cpu_mesh(8)

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.distributed import fleet  # noqa: E402
from paddle_tpu.framework import random as fw_random  # noqa: E402
from paddle_tpu.models import GPTForCausalLM, gpt_tiny  # noqa: E402


def main():
    # dp=2 × sp=4: batch over dp, SEQUENCE over sp
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sp_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)

    S = 512          # 4x one device's worth of context
    pt.seed(0)
    cfg = gpt_tiny(max_position_embeddings=S, context_parallel=True,
                   hidden_dropout=0.0, attention_dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.train()
    params = fleet.distributed_model(model).state_dict()
    opt = pt.optimizer.AdamW(learning_rate=3e-3)
    state = opt.init(params)

    rng = np.random.RandomState(0)
    # a learnable long-range task: the sequence repeats with period S//2,
    # so predicting token t needs token t - S//2 — far beyond any single
    # device's sequence shard
    half = rng.randint(0, cfg.vocab_size, (2, S // 2))
    ids = jnp.asarray(np.concatenate([half, half], axis=1), jnp.int32)

    @jax.jit
    def step(params, state, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                loss, _ = model.apply(p, ids, labels=ids)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        p2, s2 = opt.apply_gradients(grads, params, state)
        return loss, p2, s2

    key = jax.random.key(0)
    first = None
    for i in range(30):
        loss, params, state = step(params, state,
                                   jax.random.fold_in(key, i))
        if first is None:
            first = float(loss)
        if i % 5 == 0:
            print(f"step {i:3d}  loss {float(loss):.4f}")
    print(f"ring-attention training: {first:.4f} -> {float(loss):.4f} "
          f"over S={S} split across sp=4 devices")
    assert float(loss) < first


if __name__ == "__main__":
    main()
