"""Serving-fleet drills (ISSUE 16 + 17): a replica fleet behind the
router, killed, upgraded, crashed and autoscaled under load, with
token-exactness proved against an uninterrupted single-engine
reference.

    python examples/serve_fleet.py --sigkill_drill
        spawn 2 engine workers, push 6 concurrent streams, SIGKILL one
        replica after streams have accepted tokens, and assert: every
        client completes, every completion is token-identical to a
        single uninterrupted engine, `fleet.failovers` >= 1, and the
        surviving replica's KV allocator leak report is clean.

    python examples/serve_fleet.py --rolling_upgrade
        same fleet + load, then drain each replica in turn while the
        router migrates its spilled streams and the manager respawns
        it — zero dropped or truncated streams, and /statusz's fleet
        census shows every replica healthy again at the end.

    python examples/serve_fleet.py --router_crash_drill
        ISSUE 17 crash-safety acceptance: a child process runs a
        journaling router over 6 ragged streams, the parent SIGKILLs
        the *router* mid-stream (the workers survive as orphans), and
        a fresh ``Router(recover=run_dir)`` built from the journal
        directory alone must finish every stream token-identical to
        the reference — with zero replica restarts and no live
        journal files left behind.

    python examples/serve_fleet.py --autoscale_drill
        ISSUE 17 autoscaler acceptance, on fake time: a queue burst
        must scale the fleet up, continued burn at the ceiling must
        record ``blocked_at_max``, and a fully idle window must drain
        + retire back down — every transition a ``fleet.autoscale``
        record, and the burst's streams still token-exact.

    python examples/serve_fleet.py --trace_drill
        ISSUE 18 request-tracing acceptance: 8 ragged streams through
        a journaled 2-replica fleet, replica 0 SIGKILLed mid-stream.
        The assembler must produce exactly ONE waterfall per request
        (the victims stitched across both replicas), coverage >= 95%
        with zero orphan spans, and the tail-latency doctor must name
        failover recompute as the dominant p99 component.

All drills print one JSON line of evidence and exit nonzero on any
violated invariant, so ci.sh can run them as smokes.
"""
import argparse
import json
import os
import re
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.inference.fleet import (FleetAutoscaler, HttpReplica,
                                        LocalReplicaManager, ReplicaManager,
                                        Router, ServingSLO)
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability.monitor import StatusServer
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.testing import faults

SPEC = {"seed": 7,
        "config": {"vocab_size": 32, "hidden_size": 32, "num_layers": 2,
                   "num_heads": 2, "ffn_hidden_size": 64,
                   "max_position_embeddings": 64, "hidden_dropout": 0.0,
                   "attention_dropout": 0.0},
        "engine": {"max_seqs": 4}}
PROMPTS = [[1, 2, 3 + i] for i in range(6)]


def reference_outputs(max_new):
    """What an uninterrupted single engine produces for PROMPTS."""
    pt.seed(SPEC["seed"])
    model = GPTForCausalLM(GPTConfig(**SPEC["config"]))
    model.eval()
    ref = ServingEngine(model, max_seqs=4, registry=MetricsRegistry())
    return ref.generate(PROMPTS, max_new_tokens=max_new)


def start_fleet(run_dir, journal=False):
    reg = MetricsRegistry()
    mgr = ReplicaManager(SPEC, replicas=2, registry=reg, run_dir=run_dir)
    mgr.start()
    router = Router(mgr.replicas, manager=mgr, registry=reg,
                    run_dir=run_dir if journal else None)
    return reg, mgr, router


def sigkill_drill(run_dir):
    max_new = 40
    reg, mgr, router = start_fleet(run_dir)
    try:
        rids = [router.submit(p, max_new_tokens=max_new)
                for p in PROMPTS]
        kill = faults.kill_replica(
            mgr, index=0,
            when=lambda: any(
                len(j.tokens) >= 2 for j in router.journals.values()
                if j.replica_id == 0 and not j.finished))
        deadline = time.monotonic() + 120
        while not kill.fired and time.monotonic() < deadline:
            router.pump()
            kill.maybe()
            time.sleep(0.01)
        assert kill.fired == 1, "kill predicate never held"
        assert mgr.poll_states()[0] == "dead"
        outs = [router.collect(r, timeout=120) for r in rids]
        ref = reference_outputs(max_new)
        exact = sum(o["tokens"] == ref[i] for i, o in enumerate(outs))
        assert exact == len(PROMPTS), \
            f"only {exact}/{len(PROMPTS)} streams token-exact"
        assert router.failovers >= 1, "no failover observed"
        survivor = router.replicas[1].serving_stats()
        assert survivor["kv_blocks"]["leaked"] == 0, survivor
        page = StatusServer(registry=reg, router=router).statusz()
        assert page["fleet"]["states"].get("dead") == 1
        print(json.dumps({
            "drill": "sigkill", "streams": len(PROMPTS),
            "token_exact": exact, "failovers": router.failovers,
            "survivor_leaked_blocks":
                survivor["kv_blocks"]["leaked"]}))
    finally:
        mgr.stop()


def rolling_upgrade(run_dir):
    max_new = 48
    reg, mgr, router = start_fleet(run_dir)
    try:
        rids = [router.submit(p, max_new_tokens=max_new)
                for p in PROMPTS]
        router.pump()
        migrated = router.rolling_upgrade(timeout_per_replica=0.05)
        states = mgr.poll_states()
        assert all(s == "healthy" for s in states.values()), states
        outs = [router.collect(r, timeout=120) for r in rids]
        dropped = sum(len(o["tokens"]) != max_new for o in outs)
        assert dropped == 0, f"{dropped} truncated streams"
        ref = reference_outputs(max_new)
        exact = sum(o["tokens"] == ref[i] for i, o in enumerate(outs))
        assert exact == len(PROMPTS), \
            f"only {exact}/{len(PROMPTS)} streams token-exact"
        page = StatusServer(registry=reg, router=router).statusz()
        assert page["fleet"]["states"].get("healthy") == 2
        assert page["fleet"]["restarts"] == 2
        print(json.dumps({
            "drill": "rolling_upgrade", "streams": len(PROMPTS),
            "dropped": dropped, "token_exact": exact,
            "migrated": migrated, "restarts": mgr.restarts}))
    finally:
        mgr.stop()


_READY_FILE = "crash_child_ready.json"
_RAGGED_MAX_NEW = [40 + 4 * i for i in range(len(PROMPTS))]


def _crash_child(run_dir):
    """The victim: a journaling router that admits 6 ragged streams,
    pumps until every journal holds accepted tokens, then parks and
    waits for the parent's SIGKILL.  No cleanup — that is the point."""
    reg, mgr, router = start_fleet(run_dir, journal=True)
    rids = [router.submit(p, max_new_tokens=_RAGGED_MAX_NEW[i])
            for i, p in enumerate(PROMPTS)]
    deadline = time.monotonic() + 120
    while (any(len(j.tokens) < 2 for j in router.journals.values())
           and time.monotonic() < deadline):
        router.pump()
        time.sleep(0.01)
    assert all(len(j.tokens) >= 2 for j in router.journals.values()), \
        "streams never accepted tokens"
    ready = {"streams": [{"request_id": r, "max_new": _RAGGED_MAX_NEW[i]}
                         for i, r in enumerate(rids)],
             "workers": [{"replica": i, "port": rep.port,
                          "pid": rep.process.pid}
                         for i, rep in enumerate(mgr.replicas)]}
    path = os.path.join(run_dir, _READY_FILE)
    with open(path + ".tmp", "w") as f:
        json.dump(ready, f)
    os.replace(path + ".tmp", path)     # atomic: parent sees all or nothing
    while True:                          # hold streams mid-flight
        time.sleep(1)


def _reap_workers(workers):
    """Shut down the orphaned worker processes the drill left behind."""
    for w in workers:
        HttpReplica(w["replica"], w["port"]).stop()
    deadline = time.monotonic() + 15
    for w in workers:
        while time.monotonic() < deadline:
            try:
                os.kill(w["pid"], 0)
            except ProcessLookupError:
                break
            time.sleep(0.05)
        else:
            try:
                os.kill(w["pid"], signal.SIGKILL)
            except ProcessLookupError:
                pass


def router_crash_drill(run_dir):
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--_crash_child", run_dir],
        stdout=subprocess.DEVNULL)
    ready_path = os.path.join(run_dir, _READY_FILE)
    info = None
    try:
        deadline = time.monotonic() + 300
        while not os.path.exists(ready_path):
            assert child.poll() is None, \
                f"router child died before ready (rc={child.returncode})"
            assert time.monotonic() < deadline, "router child never ready"
            time.sleep(0.02)
        with open(ready_path) as f:
            info = json.load(f)
        # SIGKILL the router — no atexit, no drain, no journal flush
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=10)
        for w in info["workers"]:        # workers must have survived
            os.kill(w["pid"], 0)
        reg = MetricsRegistry()
        replicas = [HttpReplica(w["replica"], w["port"])
                    for w in info["workers"]]
        router = Router(replicas, registry=reg, recover=run_dir)
        rec = dict(router.recovered)
        assert rec["streams"] == len(info["streams"]), rec
        assert rec["reattached"] + rec["redispatched"] >= 1, rec
        outs = [router.collect(s["request_id"], timeout=120)
                for s in info["streams"]]
        ref = reference_outputs(max(_RAGGED_MAX_NEW))
        exact = sum(o["tokens"] == ref[i][: s["max_new"]]
                    for i, (s, o) in enumerate(zip(info["streams"], outs)))
        assert exact == len(PROMPTS), \
            f"only {exact}/{len(PROMPTS)} recovered streams token-exact"
        leaked = 0
        for w, replica in zip(info["workers"], replicas):
            os.kill(w["pid"], 0)         # original pid: never restarted
            leaked += replica.serving_stats()["kv_blocks"]["leaked"]
        assert leaked == 0, f"{leaked} KV blocks leaked across the crash"
        assert router.store.live_count() == 0, \
            "live journal files left after every stream finished"
        print(json.dumps({
            "drill": "router_crash", "streams": len(PROMPTS),
            "token_exact": exact, "recovered": rec,
            "worker_restarts": 0, "leaked_blocks": leaked,
            "journal_live": router.store.live_count(),
            "journal_drops": dict(router.store.drops)}))
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=10)
        if info is not None:
            _reap_workers(info["workers"])


def autoscale_drill(run_dir):
    max_new = 8
    reg = MetricsRegistry()
    records = []

    class _Capture:
        def write(self, r):
            records.append(r)

        def flush(self):
            pass

        def close(self):
            pass

    reg.add_sink(_Capture())

    def factory(i):
        pt.seed(SPEC["seed"])
        model = GPTForCausalLM(GPTConfig(**SPEC["config"]))
        model.eval()
        return ServingEngine(model, max_seqs=4, registry=reg)

    clk = {"t": 0.0}
    mgr = LocalReplicaManager(factory, replicas=1, registry=reg)
    router = Router(mgr.replicas, manager=mgr, registry=reg)
    scaler = FleetAutoscaler(
        mgr, router=router, slo=ServingSLO(queue_depth=2.0),
        min_replicas=1, max_replicas=2, window_secs=10.0,
        cooldown_secs=5.0, registry=reg, clock=lambda: clk["t"])

    def tick_until(action, limit=60):
        for _ in range(limit):
            clk["t"] += 1.0
            if scaler.step() == action:
                return
        raise AssertionError(f"autoscaler never chose {action!r}: "
                             f"{scaler.stats()}")

    # burst: 6 streams against 1 replica — queue SLO burns -> scale up
    rids = [router.submit(p, max_new_tokens=max_new) for p in PROMPTS]
    tick_until("up")
    assert len(scaler.active_ids()) == 2, mgr.poll_states()
    # still burning at the ceiling -> the page-worthy record, not a spawn
    tick_until("blocked_at_max")
    assert len(scaler.active_ids()) == 2, mgr.poll_states()
    # drain the burst; a fully idle window -> drain + retire back down
    router.run(timeout=120)
    tick_until("down")
    states = mgr.poll_states()
    assert sum(1 for s in states.values() if s == "retired") == 1, states
    assert len(scaler.active_ids()) == 1, states
    outs = [router.collect(r, timeout=10) for r in rids]
    ref = reference_outputs(max_new)
    exact = sum(o["tokens"] == ref[i] for i, o in enumerate(outs))
    assert exact == len(PROMPTS), \
        f"only {exact}/{len(PROMPTS)} streams token-exact across scaling"
    scale_records = [r for r in records if r["kind"] == "fleet.autoscale"]
    actions = [r["action"] for r in scale_records]
    for want in ("up", "blocked_at_max", "down"):
        assert want in actions, f"no fleet.autoscale {want!r}: {actions}"
    for r in scale_records:              # the timeline schema operators page on
        for field in ("action", "replicas", "target", "burn", "idle",
                      "why", "slo"):
            assert field in r, (field, r)
    print(json.dumps({
        "drill": "autoscale", "streams": len(PROMPTS),
        "token_exact": exact, "actions": actions,
        "active_end": len(scaler.active_ids()),
        "scaler": scaler.stats()["actions"]}))


_TRACE_PROMPTS = [[1, 2, 3 + i % 6, 4 + i % 3] for i in range(8)]
_TRACE_MAX_NEW = [24 + 2 * i for i in range(8)]     # ragged 24..38


def trace_drill(run_dir):
    """ISSUE 18 acceptance: per-request waterfalls survive a replica
    SIGKILL.  Every victim stream's trace must stitch across BOTH
    replicas, every request must assemble into exactly one trace with
    coverage >= 95% and zero orphan spans, and both the attribution
    helper and the doctor must name failover recompute as what the
    p99 tail pays extra for (migrants requeue + re-prefill behind the
    survivor's residents)."""
    from paddle_tpu.observability import doctor, requesttrace
    from paddle_tpu.observability.aggregate import read_worker_stream
    from paddle_tpu.observability.sinks import MetricsWriter, metrics_dir

    mdir = metrics_dir(run_dir)
    reg = MetricsRegistry()
    # router spans go to worker-0; each engine worker writes its own
    # stream (worker-i+1) via PTPU_METRICS_DIR, flushed per record so
    # the SIGKILL victim's spans survive
    writer = reg.add_sink(MetricsWriter(mdir, worker_id=0,
                                        flush_every=1))
    mgr = ReplicaManager(SPEC, replicas=2, registry=reg,
                         run_dir=run_dir,
                         env={"PTPU_METRICS_DIR": mdir})
    mgr.start()
    router = Router(mgr.replicas, manager=mgr, registry=reg,
                    run_dir=run_dir)       # journaled: WAL cross-check
    rids = []
    try:
        # warm EVERY replica directly (least-loaded dispatch can pile
        # all warmup onto one replica, leaving the other to compile
        # mid-drill and serialize the whole fleet behind its worker
        # lock): the len-4 prefill bucket + the padded decode batch.
        # ``"trace_id": None`` is an explicit not-traced decision, so
        # warmup streams never enter the assembly
        for i, rep in enumerate(mgr.replicas):
            warm = [f"warm-{i}-{w}" for w in range(4)]
            for rid in warm:
                rep.submit({"request_id": rid, "prompt": [1, 2, 3, 4],
                            "output": [], "max_new_tokens": 4,
                            "eos_token_id": None, "preemptions": 0,
                            "trace_id": None})
            for rid in warm:
                deadline = time.monotonic() + 120
                while not rep.poll(rid, start=0)["finished"]:
                    assert time.monotonic() < deadline, \
                        f"warmup stream {rid} never finished"
                    time.sleep(0.01)
        rids = [router.submit(p, max_new_tokens=_TRACE_MAX_NEW[i])
                for i, p in enumerate(_TRACE_PROMPTS)]
        kill = faults.kill_replica(
            mgr, index=0,
            when=lambda: any(
                len(j.tokens) >= 2 for j in router.journals.values()
                if j.replica_id == 0 and not j.finished))
        deadline = time.monotonic() + 120
        while not kill.fired and time.monotonic() < deadline:
            router.pump()
            kill.maybe()
            time.sleep(0.01)
        assert kill.fired == 1, "kill predicate never held"
        outs = [router.collect(r, timeout=120) for r in rids]
        truncated = sum(len(o["tokens"]) != _TRACE_MAX_NEW[i]
                        for i, o in enumerate(outs))
        assert truncated == 0, f"{truncated} truncated streams"
        assert router.failovers >= 1, "no failover observed"
    finally:
        mgr.stop()
    reg.remove_sink(writer)                # flush + close worker-0

    result = requesttrace.assemble_run(run_dir)
    traces = result["traces"]
    assert len(traces) == len(rids), \
        f"{len(traces)} traces for {len(rids)} requests"
    assert {t["request_id"] for t in traces} == set(rids), \
        "assembled request ids do not match the submitted set"
    assert result["complete"] == len(rids), result
    assert not result["orphan_spans"], result["orphan_spans"]
    stitched = [t for t in traces
                if {"replica-0", "replica-1"} <= set(t["procs"])]
    assert stitched, "no trace stitched across both replicas"
    min_cov = min(t["coverage"] for t in traces)
    assert min_cov >= 0.95, \
        f"trace coverage floor {min_cov:.1%} < 95%"
    attrib = requesttrace.tail_latency_attribution(traces)
    assert attrib is not None and \
        attrib["dominant"] == "failover_recompute", attrib

    workers = {}
    for name in sorted(os.listdir(mdir)):
        m = re.match(r"^worker-(\d+)\.jsonl$", name)
        if m:
            workers[int(m.group(1))] = read_worker_stream(
                os.path.join(mdir, name))
    findings = doctor.check_tail_latency(workers)
    assert findings, "doctor produced no tail_latency verdict"
    assert findings[0]["data"]["dominant"] == "failover_recompute", \
        findings[0]
    print(json.dumps({
        "drill": "trace", "streams": len(rids),
        "traces": len(traces), "complete": result["complete"],
        "stitched_across_replicas": len(stitched),
        "coverage_min": round(min_cov, 4),
        "orphan_spans": len(result["orphan_spans"]),
        "wal_matched": result["wal_matched"],
        "tail_dominant": attrib["dominant"],
        "tail_p99_ms": attrib["p99_ms"],
        "tail_median_ms": attrib["median_ms"],
        "doctor_severity": findings[0]["severity"]}))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sigkill_drill", action="store_true")
    ap.add_argument("--rolling_upgrade", action="store_true")
    ap.add_argument("--router_crash_drill", action="store_true")
    ap.add_argument("--autoscale_drill", action="store_true")
    ap.add_argument("--trace_drill", action="store_true")
    ap.add_argument("--_crash_child", metavar="RUN_DIR", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args._crash_child:
        _crash_child(args._crash_child)
        return
    import tempfile
    with tempfile.TemporaryDirectory() as run_dir:
        if args.sigkill_drill:
            sigkill_drill(run_dir)
        elif args.rolling_upgrade:
            rolling_upgrade(run_dir)
        elif args.router_crash_drill:
            router_crash_drill(run_dir)
        elif args.autoscale_drill:
            autoscale_drill(run_dir)
        elif args.trace_drill:
            trace_drill(run_dir)
        else:
            ap.error("pick --sigkill_drill, --rolling_upgrade, "
                     "--router_crash_drill, --autoscale_drill or "
                     "--trace_drill")


if __name__ == "__main__":
    main()
