"""Serving-fleet drills (ISSUE 16 + 17): a replica fleet behind the
router, killed, upgraded, crashed and autoscaled under load, with
token-exactness proved against an uninterrupted single-engine
reference.

    python examples/serve_fleet.py --sigkill_drill
        spawn 2 engine workers, push 6 concurrent streams, SIGKILL one
        replica after streams have accepted tokens, and assert: every
        client completes, every completion is token-identical to a
        single uninterrupted engine, `fleet.failovers` >= 1, and the
        surviving replica's KV allocator leak report is clean.

    python examples/serve_fleet.py --rolling_upgrade
        same fleet + load, then drain each replica in turn while the
        router migrates its spilled streams and the manager respawns
        it — zero dropped or truncated streams, and /statusz's fleet
        census shows every replica healthy again at the end.

    python examples/serve_fleet.py --router_crash_drill
        ISSUE 17 crash-safety acceptance: a child process runs a
        journaling router over 6 ragged streams, the parent SIGKILLs
        the *router* mid-stream (the workers survive as orphans), and
        a fresh ``Router(recover=run_dir)`` built from the journal
        directory alone must finish every stream token-identical to
        the reference — with zero replica restarts and no live
        journal files left behind.

    python examples/serve_fleet.py --autoscale_drill
        ISSUE 17 autoscaler acceptance, on fake time: a queue burst
        must scale the fleet up, continued burn at the ceiling must
        record ``blocked_at_max``, and a fully idle window must drain
        + retire back down — every transition a ``fleet.autoscale``
        record, and the burst's streams still token-exact.

All drills print one JSON line of evidence and exit nonzero on any
violated invariant, so ci.sh can run them as smokes.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.inference.fleet import (FleetAutoscaler, HttpReplica,
                                        LocalReplicaManager, ReplicaManager,
                                        Router, ServingSLO)
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability.monitor import StatusServer
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.testing import faults

SPEC = {"seed": 7,
        "config": {"vocab_size": 32, "hidden_size": 32, "num_layers": 2,
                   "num_heads": 2, "ffn_hidden_size": 64,
                   "max_position_embeddings": 64, "hidden_dropout": 0.0,
                   "attention_dropout": 0.0},
        "engine": {"max_seqs": 4}}
PROMPTS = [[1, 2, 3 + i] for i in range(6)]


def reference_outputs(max_new):
    """What an uninterrupted single engine produces for PROMPTS."""
    pt.seed(SPEC["seed"])
    model = GPTForCausalLM(GPTConfig(**SPEC["config"]))
    model.eval()
    ref = ServingEngine(model, max_seqs=4, registry=MetricsRegistry())
    return ref.generate(PROMPTS, max_new_tokens=max_new)


def start_fleet(run_dir, journal=False):
    reg = MetricsRegistry()
    mgr = ReplicaManager(SPEC, replicas=2, registry=reg, run_dir=run_dir)
    mgr.start()
    router = Router(mgr.replicas, manager=mgr, registry=reg,
                    run_dir=run_dir if journal else None)
    return reg, mgr, router


def sigkill_drill(run_dir):
    max_new = 40
    reg, mgr, router = start_fleet(run_dir)
    try:
        rids = [router.submit(p, max_new_tokens=max_new)
                for p in PROMPTS]
        kill = faults.kill_replica(
            mgr, index=0,
            when=lambda: any(
                len(j.tokens) >= 2 for j in router.journals.values()
                if j.replica_id == 0 and not j.finished))
        deadline = time.monotonic() + 120
        while not kill.fired and time.monotonic() < deadline:
            router.pump()
            kill.maybe()
            time.sleep(0.01)
        assert kill.fired == 1, "kill predicate never held"
        assert mgr.poll_states()[0] == "dead"
        outs = [router.collect(r, timeout=120) for r in rids]
        ref = reference_outputs(max_new)
        exact = sum(o["tokens"] == ref[i] for i, o in enumerate(outs))
        assert exact == len(PROMPTS), \
            f"only {exact}/{len(PROMPTS)} streams token-exact"
        assert router.failovers >= 1, "no failover observed"
        survivor = router.replicas[1].serving_stats()
        assert survivor["kv_blocks"]["leaked"] == 0, survivor
        page = StatusServer(registry=reg, router=router).statusz()
        assert page["fleet"]["states"].get("dead") == 1
        print(json.dumps({
            "drill": "sigkill", "streams": len(PROMPTS),
            "token_exact": exact, "failovers": router.failovers,
            "survivor_leaked_blocks":
                survivor["kv_blocks"]["leaked"]}))
    finally:
        mgr.stop()


def rolling_upgrade(run_dir):
    max_new = 48
    reg, mgr, router = start_fleet(run_dir)
    try:
        rids = [router.submit(p, max_new_tokens=max_new)
                for p in PROMPTS]
        router.pump()
        migrated = router.rolling_upgrade(timeout_per_replica=0.05)
        states = mgr.poll_states()
        assert all(s == "healthy" for s in states.values()), states
        outs = [router.collect(r, timeout=120) for r in rids]
        dropped = sum(len(o["tokens"]) != max_new for o in outs)
        assert dropped == 0, f"{dropped} truncated streams"
        ref = reference_outputs(max_new)
        exact = sum(o["tokens"] == ref[i] for i, o in enumerate(outs))
        assert exact == len(PROMPTS), \
            f"only {exact}/{len(PROMPTS)} streams token-exact"
        page = StatusServer(registry=reg, router=router).statusz()
        assert page["fleet"]["states"].get("healthy") == 2
        assert page["fleet"]["restarts"] == 2
        print(json.dumps({
            "drill": "rolling_upgrade", "streams": len(PROMPTS),
            "dropped": dropped, "token_exact": exact,
            "migrated": migrated, "restarts": mgr.restarts}))
    finally:
        mgr.stop()


_READY_FILE = "crash_child_ready.json"
_RAGGED_MAX_NEW = [40 + 4 * i for i in range(len(PROMPTS))]


def _crash_child(run_dir):
    """The victim: a journaling router that admits 6 ragged streams,
    pumps until every journal holds accepted tokens, then parks and
    waits for the parent's SIGKILL.  No cleanup — that is the point."""
    reg, mgr, router = start_fleet(run_dir, journal=True)
    rids = [router.submit(p, max_new_tokens=_RAGGED_MAX_NEW[i])
            for i, p in enumerate(PROMPTS)]
    deadline = time.monotonic() + 120
    while (any(len(j.tokens) < 2 for j in router.journals.values())
           and time.monotonic() < deadline):
        router.pump()
        time.sleep(0.01)
    assert all(len(j.tokens) >= 2 for j in router.journals.values()), \
        "streams never accepted tokens"
    ready = {"streams": [{"request_id": r, "max_new": _RAGGED_MAX_NEW[i]}
                         for i, r in enumerate(rids)],
             "workers": [{"replica": i, "port": rep.port,
                          "pid": rep.process.pid}
                         for i, rep in enumerate(mgr.replicas)]}
    path = os.path.join(run_dir, _READY_FILE)
    with open(path + ".tmp", "w") as f:
        json.dump(ready, f)
    os.replace(path + ".tmp", path)     # atomic: parent sees all or nothing
    while True:                          # hold streams mid-flight
        time.sleep(1)


def _reap_workers(workers):
    """Shut down the orphaned worker processes the drill left behind."""
    for w in workers:
        HttpReplica(w["replica"], w["port"]).stop()
    deadline = time.monotonic() + 15
    for w in workers:
        while time.monotonic() < deadline:
            try:
                os.kill(w["pid"], 0)
            except ProcessLookupError:
                break
            time.sleep(0.05)
        else:
            try:
                os.kill(w["pid"], signal.SIGKILL)
            except ProcessLookupError:
                pass


def router_crash_drill(run_dir):
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--_crash_child", run_dir],
        stdout=subprocess.DEVNULL)
    ready_path = os.path.join(run_dir, _READY_FILE)
    info = None
    try:
        deadline = time.monotonic() + 300
        while not os.path.exists(ready_path):
            assert child.poll() is None, \
                f"router child died before ready (rc={child.returncode})"
            assert time.monotonic() < deadline, "router child never ready"
            time.sleep(0.02)
        with open(ready_path) as f:
            info = json.load(f)
        # SIGKILL the router — no atexit, no drain, no journal flush
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=10)
        for w in info["workers"]:        # workers must have survived
            os.kill(w["pid"], 0)
        reg = MetricsRegistry()
        replicas = [HttpReplica(w["replica"], w["port"])
                    for w in info["workers"]]
        router = Router(replicas, registry=reg, recover=run_dir)
        rec = dict(router.recovered)
        assert rec["streams"] == len(info["streams"]), rec
        assert rec["reattached"] + rec["redispatched"] >= 1, rec
        outs = [router.collect(s["request_id"], timeout=120)
                for s in info["streams"]]
        ref = reference_outputs(max(_RAGGED_MAX_NEW))
        exact = sum(o["tokens"] == ref[i][: s["max_new"]]
                    for i, (s, o) in enumerate(zip(info["streams"], outs)))
        assert exact == len(PROMPTS), \
            f"only {exact}/{len(PROMPTS)} recovered streams token-exact"
        leaked = 0
        for w, replica in zip(info["workers"], replicas):
            os.kill(w["pid"], 0)         # original pid: never restarted
            leaked += replica.serving_stats()["kv_blocks"]["leaked"]
        assert leaked == 0, f"{leaked} KV blocks leaked across the crash"
        assert router.store.live_count() == 0, \
            "live journal files left after every stream finished"
        print(json.dumps({
            "drill": "router_crash", "streams": len(PROMPTS),
            "token_exact": exact, "recovered": rec,
            "worker_restarts": 0, "leaked_blocks": leaked,
            "journal_live": router.store.live_count(),
            "journal_drops": dict(router.store.drops)}))
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=10)
        if info is not None:
            _reap_workers(info["workers"])


def autoscale_drill(run_dir):
    max_new = 8
    reg = MetricsRegistry()
    records = []

    class _Capture:
        def write(self, r):
            records.append(r)

        def flush(self):
            pass

        def close(self):
            pass

    reg.add_sink(_Capture())

    def factory(i):
        pt.seed(SPEC["seed"])
        model = GPTForCausalLM(GPTConfig(**SPEC["config"]))
        model.eval()
        return ServingEngine(model, max_seqs=4, registry=reg)

    clk = {"t": 0.0}
    mgr = LocalReplicaManager(factory, replicas=1, registry=reg)
    router = Router(mgr.replicas, manager=mgr, registry=reg)
    scaler = FleetAutoscaler(
        mgr, router=router, slo=ServingSLO(queue_depth=2.0),
        min_replicas=1, max_replicas=2, window_secs=10.0,
        cooldown_secs=5.0, registry=reg, clock=lambda: clk["t"])

    def tick_until(action, limit=60):
        for _ in range(limit):
            clk["t"] += 1.0
            if scaler.step() == action:
                return
        raise AssertionError(f"autoscaler never chose {action!r}: "
                             f"{scaler.stats()}")

    # burst: 6 streams against 1 replica — queue SLO burns -> scale up
    rids = [router.submit(p, max_new_tokens=max_new) for p in PROMPTS]
    tick_until("up")
    assert len(scaler.active_ids()) == 2, mgr.poll_states()
    # still burning at the ceiling -> the page-worthy record, not a spawn
    tick_until("blocked_at_max")
    assert len(scaler.active_ids()) == 2, mgr.poll_states()
    # drain the burst; a fully idle window -> drain + retire back down
    router.run(timeout=120)
    tick_until("down")
    states = mgr.poll_states()
    assert sum(1 for s in states.values() if s == "retired") == 1, states
    assert len(scaler.active_ids()) == 1, states
    outs = [router.collect(r, timeout=10) for r in rids]
    ref = reference_outputs(max_new)
    exact = sum(o["tokens"] == ref[i] for i, o in enumerate(outs))
    assert exact == len(PROMPTS), \
        f"only {exact}/{len(PROMPTS)} streams token-exact across scaling"
    scale_records = [r for r in records if r["kind"] == "fleet.autoscale"]
    actions = [r["action"] for r in scale_records]
    for want in ("up", "blocked_at_max", "down"):
        assert want in actions, f"no fleet.autoscale {want!r}: {actions}"
    for r in scale_records:              # the timeline schema operators page on
        for field in ("action", "replicas", "target", "burn", "idle",
                      "why", "slo"):
            assert field in r, (field, r)
    print(json.dumps({
        "drill": "autoscale", "streams": len(PROMPTS),
        "token_exact": exact, "actions": actions,
        "active_end": len(scaler.active_ids()),
        "scaler": scaler.stats()["actions"]}))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sigkill_drill", action="store_true")
    ap.add_argument("--rolling_upgrade", action="store_true")
    ap.add_argument("--router_crash_drill", action="store_true")
    ap.add_argument("--autoscale_drill", action="store_true")
    ap.add_argument("--_crash_child", metavar="RUN_DIR", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args._crash_child:
        _crash_child(args._crash_child)
        return
    import tempfile
    with tempfile.TemporaryDirectory() as run_dir:
        if args.sigkill_drill:
            sigkill_drill(run_dir)
        elif args.rolling_upgrade:
            rolling_upgrade(run_dir)
        elif args.router_crash_drill:
            router_crash_drill(run_dir)
        elif args.autoscale_drill:
            autoscale_drill(run_dir)
        else:
            ap.error("pick --sigkill_drill, --rolling_upgrade, "
                     "--router_crash_drill or --autoscale_drill")


if __name__ == "__main__":
    main()
