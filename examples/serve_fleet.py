"""Serving-fleet drills (ISSUE 16): a 2-replica fleet behind the
router, killed and upgraded under load, with token-exactness proved
against an uninterrupted single-engine reference.

    python examples/serve_fleet.py --sigkill_drill
        spawn 2 engine workers, push 6 concurrent streams, SIGKILL one
        replica after streams have accepted tokens, and assert: every
        client completes, every completion is token-identical to a
        single uninterrupted engine, `fleet.failovers` >= 1, and the
        surviving replica's KV allocator leak report is clean.

    python examples/serve_fleet.py --rolling_upgrade
        same fleet + load, then drain each replica in turn while the
        router migrates its spilled streams and the manager respawns
        it — zero dropped or truncated streams, and /statusz's fleet
        census shows every replica healthy again at the end.

Both drills print one JSON line of evidence and exit nonzero on any
violated invariant, so ci.sh can run them as smokes.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.inference.fleet import ReplicaManager, Router
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability.monitor import StatusServer
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.testing import faults

SPEC = {"seed": 7,
        "config": {"vocab_size": 32, "hidden_size": 32, "num_layers": 2,
                   "num_heads": 2, "ffn_hidden_size": 64,
                   "max_position_embeddings": 64, "hidden_dropout": 0.0,
                   "attention_dropout": 0.0},
        "engine": {"max_seqs": 4}}
PROMPTS = [[1, 2, 3 + i] for i in range(6)]


def reference_outputs(max_new):
    """What an uninterrupted single engine produces for PROMPTS."""
    pt.seed(SPEC["seed"])
    model = GPTForCausalLM(GPTConfig(**SPEC["config"]))
    model.eval()
    ref = ServingEngine(model, max_seqs=4, registry=MetricsRegistry())
    return ref.generate(PROMPTS, max_new_tokens=max_new)


def start_fleet(run_dir):
    reg = MetricsRegistry()
    mgr = ReplicaManager(SPEC, replicas=2, registry=reg, run_dir=run_dir)
    mgr.start()
    return reg, mgr, Router(mgr.replicas, manager=mgr, registry=reg)


def sigkill_drill(run_dir):
    max_new = 40
    reg, mgr, router = start_fleet(run_dir)
    try:
        rids = [router.submit(p, max_new_tokens=max_new)
                for p in PROMPTS]
        kill = faults.kill_replica(
            mgr, index=0,
            when=lambda: any(
                len(j.tokens) >= 2 for j in router.journals.values()
                if j.replica_id == 0 and not j.finished))
        deadline = time.monotonic() + 120
        while not kill.fired and time.monotonic() < deadline:
            router.pump()
            kill.maybe()
            time.sleep(0.01)
        assert kill.fired == 1, "kill predicate never held"
        assert mgr.poll_states()[0] == "dead"
        outs = [router.collect(r, timeout=120) for r in rids]
        ref = reference_outputs(max_new)
        exact = sum(o["tokens"] == ref[i] for i, o in enumerate(outs))
        assert exact == len(PROMPTS), \
            f"only {exact}/{len(PROMPTS)} streams token-exact"
        assert router.failovers >= 1, "no failover observed"
        survivor = router.replicas[1].serving_stats()
        assert survivor["kv_blocks"]["leaked"] == 0, survivor
        page = StatusServer(registry=reg, router=router).statusz()
        assert page["fleet"]["states"].get("dead") == 1
        print(json.dumps({
            "drill": "sigkill", "streams": len(PROMPTS),
            "token_exact": exact, "failovers": router.failovers,
            "survivor_leaked_blocks":
                survivor["kv_blocks"]["leaked"]}))
    finally:
        mgr.stop()


def rolling_upgrade(run_dir):
    max_new = 48
    reg, mgr, router = start_fleet(run_dir)
    try:
        rids = [router.submit(p, max_new_tokens=max_new)
                for p in PROMPTS]
        router.pump()
        migrated = router.rolling_upgrade(timeout_per_replica=0.05)
        states = mgr.poll_states()
        assert all(s == "healthy" for s in states.values()), states
        outs = [router.collect(r, timeout=120) for r in rids]
        dropped = sum(len(o["tokens"]) != max_new for o in outs)
        assert dropped == 0, f"{dropped} truncated streams"
        ref = reference_outputs(max_new)
        exact = sum(o["tokens"] == ref[i] for i, o in enumerate(outs))
        assert exact == len(PROMPTS), \
            f"only {exact}/{len(PROMPTS)} streams token-exact"
        page = StatusServer(registry=reg, router=router).statusz()
        assert page["fleet"]["states"].get("healthy") == 2
        assert page["fleet"]["restarts"] == 2
        print(json.dumps({
            "drill": "rolling_upgrade", "streams": len(PROMPTS),
            "dropped": dropped, "token_exact": exact,
            "migrated": migrated, "restarts": mgr.restarts}))
    finally:
        mgr.stop()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sigkill_drill", action="store_true")
    ap.add_argument("--rolling_upgrade", action="store_true")
    args = ap.parse_args()
    import tempfile
    with tempfile.TemporaryDirectory() as run_dir:
        if args.sigkill_drill:
            sigkill_drill(run_dir)
        elif args.rolling_upgrade:
            rolling_upgrade(run_dir)
        else:
            ap.error("pick --sigkill_drill or --rolling_upgrade")


if __name__ == "__main__":
    main()
