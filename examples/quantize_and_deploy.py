"""Train → PTQ int8 → export → serve: the deployment path end-to-end.

1. train a small fp32 classifier;
2. post-training-quantize with a calibration set (running-max observers,
   model stays in eval);
3. convert to Int8Linear (int8 MXU matmuls);
4. export the fp32 model with jit.save (StableHLO) and reload via the
   inference predictor facade.

Run:  JAX_PLATFORMS=cpu python examples/quantize_and_deploy.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu import quantization as Q
from paddle_tpu.jit import InputSpec, save
from paddle_tpu.inference import Config, create_predictor


def main():
    pt.seed(0)
    rng = np.random.RandomState(0)
    # learnable toy task
    x_all = jnp.asarray(rng.randn(512, 16), jnp.float32)
    w_true = jnp.asarray(rng.randn(16, 4), jnp.float32)
    y_all = jnp.argmax(x_all @ w_true, axis=1).astype(jnp.int32)

    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    params = model.state_dict()
    opt = pt.optimizer.Adam(learning_rate=5e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s, x, y):
        def lf(q):
            return nn.functional.cross_entropy(model.apply(q, x), y)
        loss, g = jax.value_and_grad(lf)(p)
        return (loss, *opt.apply_gradients(g, p, s))

    for epoch in range(30):
        loss, params, state = step(params, state, x_all, y_all)
    model.load_dict(params)
    model.eval()
    fp32_acc = float(jnp.mean(
        jnp.argmax(model(x_all), 1).astype(jnp.int32) == y_all))
    print(f"fp32 accuracy: {fp32_acc:.3f} (loss {float(loss):.4f})")

    # --- PTQ: calibrate + convert to int8 -------------------------------
    ptq = Q.PostTrainingQuantization()
    ptq.quantize(model, [x_all[i * 64:(i + 1) * 64] for i in range(4)])
    ptq.convert(model)
    model.eval()
    int8_acc = float(jnp.mean(
        jnp.argmax(model(x_all), 1).astype(jnp.int32) == y_all))
    n_int8 = sum(isinstance(l, Q.Int8Linear) for l in model.sublayers())
    print(f"int8 accuracy: {int8_acc:.3f} ({n_int8} Int8Linear layers)")

    # --- export + serve -------------------------------------------------
    fresh = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    fresh.load_dict(params)
    fresh.eval()
    path = os.path.join(tempfile.mkdtemp(), "clf")
    save(fresh, path, [InputSpec([None, 16], "float32")])
    predictor = create_predictor(Config(path))
    in_handle = predictor.get_input_handle(predictor.get_input_names()[0])
    in_handle.copy_from_cpu(np.asarray(x_all[:8]))
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
    served_pred = np.argmax(out, 1)
    direct_pred = np.argmax(np.asarray(fresh(x_all[:8])), 1)
    assert (served_pred == direct_pred).all()
    print("serving artifact matches direct inference — deploy path ok")


if __name__ == "__main__":
    main()
