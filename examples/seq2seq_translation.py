"""Seq2seq machine translation: WMT14 data -> nn.Transformer training ->
BeamSearchDecoder inference (the reference's transformer tutorial flow,
python/paddle/text/datasets/wmt14.py + nn/layer/transformer.py +
nn/decode.py, rebuilt on the TPU-native stack).

The synthetic WMT14 corpus maps source tokens through a fixed permutation
(a toy "translation"), so the model can and must drive loss toward zero;
beam search must then reproduce held-out translations exactly.

Run: python examples/seq2seq_translation.py  (CPU or TPU; ~1 min on CPU)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.io import DataLoader
from paddle_tpu.text import WMT14

V = 64            # dict size (3 specials + 61 content tokens)
L = 18            # fixed padded length (synthetic max is 16+2)
D = 64

pt.seed(0)


def collate(batch):
    """Pad to fixed length; teacher-forcing pairs (src, tgt_in, tgt_next)."""
    src = np.full((len(batch), L), 2, np.int64)        # <unk> as pad
    tin = np.full((len(batch), L), 1, np.int64)        # </e> pads
    tnx = np.full((len(batch), L), -100, np.int64)     # ignore pads
    for i, (s, t, tn) in enumerate(batch):
        src[i, : len(s)] = s[:L]
        tin[i, : len(t)] = t[:L]
        tnx[i, : len(tn)] = tn[:L]
    return jnp.asarray(src), jnp.asarray(tin), jnp.asarray(tnx)


class TranslationModel(nn.Layer):
    def __init__(self):
        super().__init__()
        self.src_emb = nn.Embedding(V, D)
        self.tgt_emb = nn.Embedding(V, D)
        self.pos = nn.Embedding(L, D)
        self.core = nn.Transformer(d_model=D, nhead=4,
                                   num_encoder_layers=2,
                                   num_decoder_layers=2,
                                   dim_feedforward=128, dropout=0.0)
        self.head = nn.Linear(D, V)

    def _embed(self, emb, ids):
        p = jnp.arange(ids.shape[1])
        return emb(ids) + self.pos(p)[None]

    def forward(self, src, tgt_in):
        tgt_mask = nn.Transformer.generate_square_subsequent_mask(
            tgt_in.shape[1])
        out = self.core(self._embed(self.src_emb, src),
                        self._embed(self.tgt_emb, tgt_in),
                        tgt_mask=tgt_mask)
        return self.head(out)


def main():
    train = WMT14(mode="train", dict_size=V, synthetic_size=2048)
    gen = WMT14(mode="gen", dict_size=V, synthetic_size=8)
    loader = DataLoader(train, batch_size=64, shuffle=True,
                        collate_fn=collate, drop_last=True)

    model = TranslationModel()
    model.train()
    params = model.trainable_variables()
    opt = pt.optimizer.AdamW(learning_rate=3e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s, src, tin, tnx):
        def loss_fn(p_):
            logits = model.apply(p_, src, tin)
            mask = tnx >= 0
            safe = jnp.where(mask, tnx, 0)
            ll = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(ll, safe[..., None], -1)[..., 0]
            return (nll * mask).sum() / mask.sum()

        l, g = jax.value_and_grad(loss_fn)(p)
        new_p, new_s = opt.apply_gradients(g, p, s)
        return l, new_p, new_s

    first = last = None
    for epoch in range(8):
        for src, tin, tnx in loader:
            loss, params, state = step(params, state, src, tin, tnx)
            first = first if first is not None else float(loss)
            last = float(loss)
        print(f"epoch {epoch}: loss {last:.4f}")
    assert last < 0.05 < first, (first, last)

    # ---- inference: beam search over the trained decoder ---------------
    model.eval()

    def make_cell(p):
        """Cell contract: (tokens (B*,), state) -> (logits, state); the
        state carries the growing decoded prefix (re-encode per step —
        fine at toy scale; the kv-cache path lives in models/gpt.py)."""

        def cell(tok, st):
            prefix = st["prefix"]                     # (B*, t)
            prefix = jnp.concatenate([prefix, tok[:, None]], axis=1)
            logits = model.apply(p, st["src"], prefix)
            return logits[:, -1], {"src": st["src"], "prefix": prefix}

        return cell

    correct = 0
    for i in range(len(gen)):
        s, t, tn = gen[i]
        src = jnp.asarray(np.pad(s, (0, L - len(s)),
                                 constant_values=2))[None]
        beam = 3
        dec = nn.BeamSearchDecoder(
            make_cell(params), start_token=0, end_token=1,
            beam_size=beam)
        # initialize replicates state to batch*beam rows; the prefix
        # starts EMPTY (the decoder feeds the start token as the first
        # cell input)
        seqs, lp = nn.dynamic_decode(
            dec, inits={"src": src,
                        "prefix": jnp.zeros((1, 0), jnp.int32)},
            max_step_num=len(s) + 2)
        best = np.asarray(seqs)[0, 0]
        want = np.asarray(tn)        # ends with </e>=1
        got = best[: len(want)]
        ok = np.array_equal(got, want)
        correct += ok
        if i < 3:
            print(f"  src={s.tolist()}\n  ref={want.tolist()}"
                  f"\n  hyp={got.tolist()}  {'OK' if ok else 'MISS'}")
    print(f"beam-search exact-match: {correct}/{len(gen)}")
    assert correct == len(gen), "trained translator must decode exactly"
    print("seq2seq example OK")


if __name__ == "__main__":
    main()
