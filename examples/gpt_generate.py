"""Train-then-serve: a tiny GPT learns a formal language, then a paged-KV
**serving engine** decodes it back — N concurrent ragged streams through
one continuous-batching loop (ISSUE 6; the reference's CacheKV decode,
fused_attention_op.cc:235, now lands in shared KV blocks addressed by
per-sequence block tables).

The language: sequences  BOS a^n b^n EOS  (n in 1..6).  A correct model
must COUNT — after the a-run it has to emit exactly as many b's — so
greedy generation proves real sequence modeling, not bigram statistics.
All six prompts (ragged lengths 3..13) are submitted to the engine AT
ONCE and decode as one interleaved batch.

Run: python examples/gpt_generate.py              (~1 min on CPU)
     python examples/gpt_generate.py --bench_serve
        skip training; push 8 concurrent synthetic streams through the
        engine and print one JSON row (tokens/s, TTFT/TPOT p50/p99,
        serve-mode MFU via the shared observability/mfu definitions).
     python examples/gpt_generate.py --chaos_serve
        the ISSUE 15 resilience drill: poison one of 8 concurrent
        ragged streams mid-batch and prove the engine quarantines
        exactly that request (durable record), every peer's output is
        token-identical to the un-faulted run, and the KV allocator
        returns to baseline; then drain under load, spill, and resume
        the spill on a fresh engine to completion.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM

BOS, A, B, EOS, PAD = 0, 1, 2, 3, 4
L = 16


def make_corpus(n_samples: int, rng):
    seqs = np.full((n_samples, L), PAD, np.int32)
    for i in range(n_samples):
        n = rng.randint(1, 7)
        s = [BOS] + [A] * n + [B] * n + [EOS]
        seqs[i, : len(s)] = s
    return seqs


def _tiny_config(**kw):
    return GPTConfig(vocab_size=8, hidden_size=64, num_layers=2,
                     num_heads=4, ffn_hidden_size=128,
                     max_position_embeddings=L, hidden_dropout=0.0,
                     attention_dropout=0.0, **kw)


def train(model):
    params = model.trainable_variables()
    opt = pt.optimizer.AdamW(learning_rate=3e-3)
    state = opt.init(params)
    rng = np.random.RandomState(0)
    data = jnp.asarray(make_corpus(256, rng))

    @jax.jit
    def step(p, s, batch):
        def loss_fn(p_):
            # labels == inputs; the model applies the causal shift and
            # ignores PAD via ignore_index
            masked = jnp.where(batch == PAD, -100, batch)
            loss, _ = model.apply(p_, batch, labels=masked)
            return loss

        l, g = jax.value_and_grad(loss_fn)(p)
        new_p, new_s = opt.apply_gradients(g, p, s)
        return l, new_p, new_s

    first = last = None
    for _i in range(300):
        l, params, state = step(params, state, data)
        first = first if first is not None else float(l)
        last = float(l)
    print(f"a^n b^n LM loss: {first:.3f} -> {last:.4f}")
    # the language has IRREDUCIBLE entropy (n is unpredictable: every
    # a→{a,b} branch carries information), so loss cannot approach 0;
    # the deterministic part — counting out the b-run — is what the
    # serve check below pins exactly
    assert last < first * 0.3, (first, last)
    return params


def serve_counting_check(model):
    """All six ragged prompts decode CONCURRENTLY through the engine —
    the paged-KV analog of the old one-at-a-time generate() loop."""
    engine = ServingEngine(model, max_seqs=8, kv_block_size=4)
    rids = {}
    for n in range(1, 7):
        prompt = [BOS] + [A] * n + [B]
        rids[n] = engine.submit(prompt, max_new_tokens=L - len(prompt),
                                eos_token_id=EOS)
    engine.run(max_steps=500)
    correct = 0
    for n in range(1, 7):
        got_all = engine.collect(rids[n])["tokens"]
        want = [B] * (n - 1) + [EOS]
        got = got_all[: len(want)]
        ok = got == want
        correct += ok
        print(f"  n={n}: continue a^{n} b -> {got} "
              f"{'OK' if ok else f'(want {want})'}")
    print(f"counting accuracy: {correct}/6 "
          f"(served in {engine.steps} engine steps)")
    assert correct >= 5, "the LM must have learned to count"
    # the continuous-batching contract: one compilation per step-shape
    # bucket, no retrace storms (PR 4 tracker)
    from paddle_tpu.observability.compilation import get_tracker
    tr = get_tracker()
    for fn in tr.functions():
        if fn.startswith("serve"):
            st = tr.stats(fn)
            assert st["retraces"] == 0 and st["storms"] == 0, (fn, st)


def bench_serve(n_streams: int = 8, max_new_tokens: int = 10):
    """Synthetic-traffic benchmark: one JSON row through the shared
    observability/mfu.py definitions (serve-mode = fwd-only FLOPs)."""
    from paddle_tpu.observability.mfu import (flops_per_token, mfu,
                                              param_count)
    from paddle_tpu.observability.registry import MetricsRegistry

    cfg = _tiny_config()
    model = GPTForCausalLM(cfg)
    model.eval()
    reg = MetricsRegistry()
    engine = ServingEngine(model, max_seqs=n_streams, kv_block_size=4,
                           registry=reg)
    rng = np.random.RandomState(7)
    # ragged prompt lengths 3..6, so prompt + max_new fits the model's
    # 16 positions
    prompts = [[BOS] + rng.randint(1, 4, rng.randint(2, 6)).tolist()
               for _ in range(n_streams)]
    # warm the compile caches outside the timed window (bench measures
    # serving, not XLA), then point the engine at a fresh registry so
    # the percentiles below cover only the timed traffic
    engine.generate([p[:3] for p in prompts[:2]], max_new_tokens=2)
    reg = MetricsRegistry()
    engine._registry = reg
    t0 = time.perf_counter()
    rids = [engine.submit(p, max_new_tokens=max_new_tokens)
            for p in prompts]
    steps = engine.run(max_steps=2000)
    elapsed = time.perf_counter() - t0
    results = [engine.collect(r) for r in rids]
    generated = sum(len(r["tokens"]) for r in results)
    tokens_per_sec = generated / elapsed
    snap = reg.snapshot()

    def pct(name, p):
        m = snap.get(name)
        return None if not m else m.get(p)

    n_params = param_count(model.trainable_variables())
    flops_tok = flops_per_token(
        n_params, num_layers=cfg.num_layers, hidden_size=cfg.hidden_size,
        seq_len=cfg.max_position_embeddings, fwd_only=True)
    row = {
        "bench": "serve",
        "device": jax.devices()[0].device_kind,
        "n_streams": n_streams,
        "generated_tokens": generated,
        "engine_steps": steps,
        "elapsed_s": round(elapsed, 4),
        "tokens_per_sec": round(tokens_per_sec, 2),
        "ttft_ms_p50": pct("serve.ttft_ms", "p50"),
        "ttft_ms_p99": pct("serve.ttft_ms", "p99"),
        "tpot_ms_p50": pct("serve.tpot_ms", "p50"),
        "tpot_ms_p99": pct("serve.tpot_ms", "p99"),
        "kv_block_size": engine.cache.block_size,
        "preemptions": engine.sched.preemptions,
        "mfu": mfu(tokens_per_sec, flops_tok),
    }
    print(json.dumps(row))
    assert all(r["finish_reason"] is not None for r in results), results
    assert generated >= n_streams, generated
    return row


def chaos_serve(n_streams: int = 8, max_new_tokens: int = 8):
    """The serving-resilience drill (ISSUE 15), two acts:

    1. **Quarantine**: run ``n_streams`` ragged streams clean, then the
       same traffic with ``faults.poison_request`` on stream 3 — the
       engine must evict exactly that stream (``reason="poisoned"``,
       durable record under run_dir), every other stream token-exact vs
       the clean run, allocator occupancy back to baseline.
    2. **Drain/resume**: under fresh load, ``drain(timeout=)`` finishes
       the running set, spills the rest, and a brand-new engine
       ``resume()``s the spill to completion.
    """
    import tempfile

    from paddle_tpu.observability.registry import MetricsRegistry
    from paddle_tpu.testing import faults

    cfg = _tiny_config()
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(7)
    prompts = [[BOS] + rng.randint(1, 4, rng.randint(2, 6)).tolist()
               for _ in range(n_streams)]

    def run_traffic(step_fault=None, run_dir=None):
        eng = ServingEngine(model, max_seqs=n_streams, kv_block_size=4,
                            registry=MetricsRegistry(), run_dir=run_dir,
                            step_fault=step_fault)
        baseline = eng.cache.allocator.num_used
        rids = [eng.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        eng.run(max_steps=2000)
        outs = {i: eng.collect(r) for i, r in enumerate(rids)}
        return eng, outs, baseline

    # act 1: clean reference, then the poisoned replay
    _eng, clean, _ = run_traffic()
    with tempfile.TemporaryDirectory() as run_dir:
        injector = faults.poison_request(3, mode="raise")
        eng, poisoned, baseline = run_traffic(step_fault=injector,
                                              run_dir=run_dir)
        assert poisoned[3]["finish_reason"] == "poisoned", poisoned[3]
        assert list(eng.quarantined) == [eng._submit_order[3]]
        qdir = os.path.join(run_dir, "serve", "replica-0", "quarantine")
        assert len(os.listdir(qdir)) == 1, os.listdir(qdir)
        exact = sum(poisoned[i]["tokens"] == clean[i]["tokens"]
                    for i in range(n_streams) if i != 3)
        assert exact == n_streams - 1, \
            f"only {exact}/{n_streams - 1} peers token-exact"
        assert eng.cache.allocator.num_used == baseline, \
            eng.cache.leak_report()
        print(f"chaos_serve: poisoned stream quarantined ({injector.fired}"
              f" injections), {exact}/{n_streams - 1} peers token-exact, "
              f"allocator back to baseline")

    # act 2: drain under load, resume the spill on a fresh engine
    with tempfile.TemporaryDirectory() as run_dir:
        eng = ServingEngine(model, max_seqs=2, kv_block_size=4,
                            registry=MetricsRegistry(), run_dir=run_dir)
        rids = [eng.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        for _ in range(3):
            eng.step()            # start some work, leave the rest queued
        report = eng.drain(timeout=30.0)
        assert eng.state == "stopped"
        assert not report["timed_out"], report
        done = sum(1 for r in rids if eng.sched.finished[r].finish_reason
                   in ("eos", "max_new_tokens"))
        assert done + report["spilled"] == n_streams, (done, report)
        fresh = ServingEngine(model, max_seqs=2, kv_block_size=4,
                              registry=MetricsRegistry())
        if report["spilled"]:
            resumed = fresh.resume(report["spill_path"])
            fresh.run(max_steps=2000)
            for r in resumed:
                out = fresh.collect(r)
                assert out["finish_reason"] in ("eos", "max_new_tokens")
        print(f"chaos_serve: drain finished {report['finished']}, "
              f"spilled {report['spilled']}, resumed to completion")
    print("chaos_serve OK")


def main():
    pt.seed(11)
    if "--bench_serve" in sys.argv:
        bench_serve()
        return
    if "--chaos_serve" in sys.argv:
        chaos_serve()
        return
    model = GPTForCausalLM(_tiny_config())
    params = train(model)
    model.set_state_dict({**model.state_dict(), **params})
    model.eval()
    serve_counting_check(model)
    print("gpt_generate example OK")


if __name__ == "__main__":
    main()
