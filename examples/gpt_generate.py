"""Train-then-generate: a tiny GPT learns a formal language, then decodes
it back with the kv-cache generate() path (the reference's CacheKV decode,
fused_attention_op.cc:235, here one jitted step with preallocated caches —
and the flash decode kernel when running on the TPU).

The language: sequences  BOS a^n b^n EOS  (n in 1..6).  A correct model
must COUNT — after the a-run it has to emit exactly as many b's — so
greedy generation proves real sequence modeling, not bigram statistics.

Run: python examples/gpt_generate.py    (~1 min on CPU)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models import GPTConfig, GPTForCausalLM

BOS, A, B, EOS, PAD = 0, 1, 2, 3, 4
L = 16


def make_corpus(n_samples: int, rng):
    seqs = np.full((n_samples, L), PAD, np.int32)
    for i in range(n_samples):
        n = rng.randint(1, 7)
        s = [BOS] + [A] * n + [B] * n + [EOS]
        seqs[i, : len(s)] = s
    return seqs


def main():
    pt.seed(11)
    cfg = GPTConfig(vocab_size=8, hidden_size=64, num_layers=2,
                    num_heads=4, ffn_hidden_size=128,
                    max_position_embeddings=L, hidden_dropout=0.0,
                    attention_dropout=0.0)
    model = GPTForCausalLM(cfg)
    params = model.trainable_variables()
    opt = pt.optimizer.AdamW(learning_rate=3e-3)
    state = opt.init(params)
    rng = np.random.RandomState(0)
    data = jnp.asarray(make_corpus(256, rng))

    @jax.jit
    def step(p, s, batch):
        def loss_fn(p_):
            # labels == inputs; the model applies the causal shift and
            # ignores PAD via ignore_index
            masked = jnp.where(batch == PAD, -100, batch)
            loss, _ = model.apply(p_, batch, labels=masked)
            return loss

        l, g = jax.value_and_grad(loss_fn)(p)
        new_p, new_s = opt.apply_gradients(g, p, s)
        return l, new_p, new_s

    first = last = None
    for i in range(300):
        l, params, state = step(params, state, data)
        first = first if first is not None else float(l)
        last = float(l)
    print(f"a^n b^n LM loss: {first:.3f} -> {last:.4f}")
    # the language has IRREDUCIBLE entropy (n is unpredictable: every
    # a→{a,b} branch carries information), so loss cannot approach 0;
    # the deterministic part — counting out the b-run — is what the
    # decode check below pins exactly
    assert last < first * 0.3, (first, last)

    # ---- kv-cache greedy decode: the model must COUNT ------------------
    model.set_state_dict({**model.state_dict(), **params})
    model.eval()
    correct = 0
    for n in range(1, 7):
        prompt = jnp.asarray([[BOS] + [A] * n + [B]], jnp.int32)
        out = model.generate(prompt, max_new_tokens=L - prompt.shape[1],
                             temperature=0.0, eos_token_id=EOS)
        tail = np.asarray(out)[0, prompt.shape[1]:]
        want = [B] * (n - 1) + [EOS]
        got = tail[: len(want)].tolist()
        ok = got == want
        correct += ok
        print(f"  n={n}: continue a^{n} b -> {got} "
              f"{'OK' if ok else f'(want {want})'}")
    print(f"counting accuracy: {correct}/6")
    assert correct >= 5, "the LM must have learned to count"
    print("gpt_generate example OK")


if __name__ == "__main__":
    main()
