"""Annotate-then-run with paddle_tpu.distributed.auto_parallel.

The reference flow (auto_parallel/interface.py): build a ProcessMesh,
annotate a few key tensors with shard_tensor/shard_op, run — the planner
completes the rest.  Here GSPMD is the planner: annotations become
NamedSharding placements / with_sharding_constraint, and XLA's sharding
propagation completes every intermediate.  Run:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/auto_parallel_annotate.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh

force_virtual_cpu_mesh(8)

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import paddle_tpu.distributed as dist  # noqa: E402


def main():
    # 2 (data) x 4 (model) logical process topology
    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                            dim_names=["dp", "mp"])
    print(mesh, "->", mesh.jax_mesh)

    R = np.random.RandomState(0)
    w1 = dist.shard_tensor(                     # column-parallel
        jnp.asarray(R.randn(64, 128), jnp.float32),
        dist_attr={"process_mesh": mesh, "dims_mapping": [-1, 1]})
    w2 = dist.shard_tensor(                     # row-parallel
        jnp.asarray(R.randn(128, 64), jnp.float32),
        dist_attr={"process_mesh": mesh, "dims_mapping": [1, -1]})
    x = dist.shard_tensor(                      # batch-sharded
        jnp.asarray(R.randn(16, 64), jnp.float32),
        dist_attr={"process_mesh": mesh, "dims_mapping": [0, -1]})
    y = jnp.asarray(R.randn(16, 64), jnp.float32)

    def loss_fn(params, xb, yb):
        h = jnp.tanh(xb @ params["w1"])
        out = h @ params["w2"]
        return jnp.mean((out - yb) ** 2)

    step = jax.jit(jax.value_and_grad(loss_fn))
    params = {"w1": w1, "w2": w2}
    for i in range(5):
        loss, grads = step(params, x, y)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g,
                                        params, grads)
        print(f"step {i}: loss {float(loss):.4f}")

    # shard_op: annotate one op's inputs/outputs explicitly
    matmul = dist.shard_op(jnp.matmul, {
        "process_mesh": mesh,
        0: {"dims_mapping": [0, -1]},
        1: {"dims_mapping": [-1, 1]},
        "out_dims_mappings": [[0, 1]],
    })
    out = matmul(jnp.ones((8, 32)), jnp.ones((32, 16)))
    print("shard_op output sharding:", out.sharding.spec)

    # Engine: the annotate-then-run driver (reference engine.py:50) —
    # serial model in, one compiled SPMD program out
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as popt
    from paddle_tpu.distributed.auto_parallel import Engine

    pt.seed(0)
    net = nn.Sequential(nn.Linear(64, 128), nn.Tanh(), nn.Linear(128, 4))
    batches = [(jnp.asarray(R.randn(16, 64), jnp.float32),
                jnp.asarray(R.randint(0, 4, (16,)), jnp.int32))
               for _ in range(8)]
    eng = Engine(net, loss_fn=nn.functional.cross_entropy,
                 optimizer=popt.AdamW(learning_rate=1e-2),
                 process_mesh=mesh)
    history = eng.fit(batches, epochs=3, verbose=0)
    print("engine.fit loss per epoch:",
          [round(h["loss"], 4) for h in history])
    assert history[-1]["loss"] < history[0]["loss"]


if __name__ == "__main__":
    main()
