"""GPT pretraining with hybrid data+tensor parallelism.

The reference's north-star workload (BASELINE config #4) at toy scale: the
SAME script drives one chip, an 8-device CPU test mesh, or a TPU pod —
only the hybrid_configs degrees change.  Run:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_gpt_hybrid.py
"""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.framework import random as fw_random
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


def main():
    n_dev = len(jax.devices())
    mp = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    dp = n_dev // mp
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    print(f"mesh: dp={dp} mp={mp} on {n_dev} {jax.devices()[0].platform} "
          f"device(s)")

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny())
    model.train()
    model = fleet.distributed_model(model)
    params = model.state_dict()
    opt = fleet.distributed_optimizer(
        pt.optimizer.AdamW(learning_rate=3e-4, weight_decay=0.01))
    state = opt.init(params)

    B, S = 8, 128
    rng = np.random.RandomState(0)

    def train_step(params, state, ids, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                loss, _ = model.apply(p, ids, labels=ids)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.apply_gradients(grads, params, state)
        return loss, params, state

    jitted = jax.jit(train_step, donate_argnums=(0, 1))
    key = jax.random.key(0)
    for step in range(20):
        ids = dist.shard_batch(jnp.asarray(
            rng.randint(0, 1024, (B, S)), jnp.int32))
        loss, params, state = jitted(params, state, ids,
                                     jax.random.fold_in(key, step))
        if step % 5 == 0 or step == 19:
            print(f"step {step:3d}  loss {float(loss):.4f}")
    print("done — loss should be dropping from ~6.9")


if __name__ == "__main__":
    main()
