"""GPT pretraining with hybrid data+tensor parallelism.

The reference's north-star workload (BASELINE config #4) at toy scale: the
SAME script drives one chip, an 8-device CPU test mesh, or a TPU pod —
only the hybrid_configs degrees change.  Run:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_gpt_hybrid.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.framework import random as fw_random
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


def main():
    n_dev = len(jax.devices())
    mp = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    dp = n_dev // mp
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": 1}
    # ISSUE 8: ZERO1=1 is the one-config-line switch — the fleet
    # optimizer becomes a ZeRO-1 ShardedOptimizer (reduce-scatter grads,
    # 1/dp of the Adam state per replica, all-gather updated params);
    # the loss trajectory is identical to the replicated run
    if os.environ.get("ZERO1", "0") == "1":
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 1,
                                     "shard_weight_update": True}
    fleet.init(is_collective=True, strategy=strategy)
    print(f"mesh: dp={dp} mp={mp} on {n_dev} {jax.devices()[0].platform} "
          f"device(s)"
          + (" (ZeRO-1 weight-update sharding)" if strategy.sharding else ""))

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny())
    model.train()
    model = fleet.distributed_model(model)
    params = model.state_dict()
    opt = fleet.distributed_optimizer(
        pt.optimizer.AdamW(learning_rate=3e-4, weight_decay=0.01))
    state = opt.init(params)

    B, S = 8, 128
    rng = np.random.RandomState(0)

    def sample_batch():
        # learnable corpus: deterministic next-token rule 80% of the time
        # (uniform-random tokens would leave nothing to predict)
        ids = np.empty((B, S), np.int32)
        ids[:, 0] = rng.randint(0, 1024, B)
        for t in range(1, S):
            det = (ids[:, t - 1] * 31 + 7) % 1024
            noise = rng.randint(0, 1024, B)
            ids[:, t] = np.where(rng.rand(B) < 0.8, det, noise)
        return jnp.asarray(ids)

    def train_step(params, state, ids, key):
        def loss_fn(p):
            with fw_random.key_scope(key):
                loss, _ = model.apply(p, ids, labels=ids)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.apply_gradients(grads, params, state)
        return loss, params, state

    jitted = jax.jit(train_step, donate_argnums=(0, 1))
    key = jax.random.key(0)
    for step in range(30):
        # labels = input ids: the model applies the causal one-token
        # shift internally (standard causal-LM convention)
        ids = dist.shard_batch(sample_batch())
        loss, params, state = jitted(params, state, ids,
                                     jax.random.fold_in(key, step))
        if step % 5 == 0 or step == 29:
            print(f"step {step:3d}  loss {float(loss):.4f}")
    print("done — next-token loss dropping from ~ln(1024)=6.93 toward the "
          "~2.0 entropy of the 80/20 markov rule")


if __name__ == "__main__":
    main()
