#!/usr/bin/env bash
# CI entry (component E10 — the analog of paddle_build.sh + parallel_UT_rule):
#   tools/ci.sh [shard_index shard_count]
#
# Shards the test files deterministically across workers (sorted list,
# round-robin) so a CI fleet can split the suite; no args = everything.
# API-compat guard + bench smoke run in shard 0 only.
set -euo pipefail
cd "$(dirname "$0")/.."

SHARD=${1:-0}
SHARDS=${2:-1}

mapfile -t FILES < <(ls tests/test_*.py | sort)
SELECTED=()
for i in "${!FILES[@]}"; do
    if (( i % SHARDS == SHARD )); then
        SELECTED+=("${FILES[$i]}")
    fi
done

echo "shard ${SHARD}/${SHARDS}: ${#SELECTED[@]} files"
if (( ${#SELECTED[@]} )); then
    python -m pytest "${SELECTED[@]}" -q
else
    echo "shard ${SHARD} has no files — nothing to run"
fi

if (( SHARD == 0 )); then
    python tools/print_signatures.py --check
    python tools/lint_bare_except.py
    python tools/lint_print.py
    # resilience tier: the fault-injection suite must stay green even when
    # sharding happens to place its files elsewhere
    python -m pytest -q -m faults tests/test_fault_tolerance.py \
        tests/test_supervisor.py
    # telemetry tier (ISSUE 3/4): registry/tracing/sinks/aggregation +
    # compile/memory/doctor diagnosis + the e2e records contracts
    python -m pytest -q -m telemetry tests/test_observability.py \
        tests/test_doctor.py
    # run-doctor smoke (ISSUE 4): diagnose the checked-in degraded
    # fixture run; fail on nonzero exit or an empty diagnosis
    DOCTOR_TMP=$(mktemp -d)
    cp -r tests/fixtures/doctor_run "$DOCTOR_TMP/run"
    python -m paddle_tpu.observability.doctor "$DOCTOR_TMP/run"
    python - "$DOCTOR_TMP/run/diagnosis.json" <<'PYEOF'
import json, sys
diag = json.load(open(sys.argv[1]))
assert diag["findings"], "doctor smoke: empty diagnosis on degraded fixture"
kinds = {f["kind"] for f in diag["findings"]}
assert {"retrace_storm", "straggler"} <= kinds, f"doctor smoke: {kinds}"
PYEOF
    rm -rf "$DOCTOR_TMP"
    BENCH_CPU=1 BENCH_SKIP_SLICE=1 python bench.py > /dev/null
    echo "api-guard + lints + faults tier + telemetry tier + doctor" \
         "smoke + bench smoke ok"
fi
echo "shard ${SHARD} green"
