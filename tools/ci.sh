#!/usr/bin/env bash
# CI entry (component E10 — the analog of paddle_build.sh + parallel_UT_rule):
#   tools/ci.sh [shard_index shard_count]
#
# Shards the test files deterministically across workers (sorted list,
# round-robin) so a CI fleet can split the suite; no args = everything.
# API-compat guard + bench smoke run in shard 0 only.
set -euo pipefail
cd "$(dirname "$0")/.."

SHARD=${1:-0}
SHARDS=${2:-1}

mapfile -t FILES < <(ls tests/test_*.py | sort)
SELECTED=()
for i in "${!FILES[@]}"; do
    if (( i % SHARDS == SHARD )); then
        SELECTED+=("${FILES[$i]}")
    fi
done

echo "shard ${SHARD}/${SHARDS}: ${#SELECTED[@]} files"
if (( ${#SELECTED[@]} )); then
    python -m pytest "${SELECTED[@]}" -q
else
    echo "shard ${SHARD} has no files — nothing to run"
fi

if (( SHARD == 0 )); then
    python tools/print_signatures.py --check
    # static analysis (ISSUE 12): one engine, one AST parse — the three
    # legacy lints plus trace-safety / lock-discipline / knob inventory,
    # gated by tools/ptlint/baseline.json
    python -m tools.ptlint --all
    python -m pytest -q tests/test_ptlint.py
    # resilience tier: the fault-injection suite must stay green even when
    # sharding happens to place its files elsewhere
    python -m pytest -q -m faults tests/test_fault_tolerance.py \
        tests/test_supervisor.py
    # telemetry tier (ISSUE 3/4/5/18): registry/tracing/sinks/aggregation +
    # compile/memory/doctor diagnosis + live monitor/flight recorder +
    # the e2e records contracts + request-trace continuity (failover,
    # migration, router crash-recovery, preemption, quarantine)
    python -m pytest -q -m telemetry tests/test_observability.py \
        tests/test_doctor.py tests/test_monitor.py \
        tests/test_request_trace.py
    # request-trace chaos drill (ISSUE 18 acceptance): 8 ragged streams
    # through a 2-replica fleet, one replica SIGKILLed mid-stream —
    # every request must assemble into exactly ONE waterfall (the
    # victims stitched across both replicas), coverage >= 95%, and the
    # tail-latency doctor must name failover recompute as the dominant
    # p99 component
    JAX_PLATFORMS=cpu python examples/serve_fleet.py --trace_drill
    # live-monitor smoke (ISSUE 5): a supervised run with the status
    # server on an ephemeral port; scrape /healthz + /metrics mid-fit
    # and assert a known instrument is exposed
    MONITOR_TMP=$(mktemp -d)
    PTPU_MONITOR_PORT=0 JAX_PLATFORMS=cpu python - "$MONITOR_TMP" <<'PYEOF'
import json, sys, urllib.request
import numpy as np
import paddle_tpu as pt
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.supervisor import RunSupervisor

scraped = {}

class Scraper(Callback):
    def on_train_batch_end(self, step, logs=None):
        sup = self.model._supervisor
        if step == 2 and sup is not None and not scraped:
            base = f"http://127.0.0.1:{sup.status_server.port}"
            scraped["healthz"] = json.loads(
                urllib.request.urlopen(base + "/healthz", timeout=5).read())
            scraped["metrics"] = urllib.request.urlopen(
                base + "/metrics", timeout=5).read().decode()

net = pt.nn.Sequential(pt.nn.Linear(8, 4))
model = pt.Model(net)
model.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-3),
              loss=pt.nn.CrossEntropyLoss())
rng = np.random.RandomState(0)
data = list(zip(rng.randn(32, 8).astype("float32"),
                rng.randint(0, 4, (32,)).astype("int64")))
sup = RunSupervisor(sys.argv[1] + "/run", worker_id=0,
                    sigterm_handler=False)
model.fit(data, batch_size=8, epochs=1, verbose=0, supervisor=sup,
          callbacks=[Scraper()])
assert scraped["healthz"]["ok"] is True, scraped["healthz"]
assert "paddle_tpu_step_time_ms_count" in scraped["metrics"], \
    "monitor smoke: step.time_ms instrument missing from /metrics"
print("monitor smoke: /healthz ok, /metrics exposes step.time_ms")
PYEOF
    rm -rf "$MONITOR_TMP"
    # run-doctor smoke (ISSUE 4): diagnose the checked-in degraded
    # fixture run; fail on nonzero exit or an empty diagnosis
    DOCTOR_TMP=$(mktemp -d)
    cp -r tests/fixtures/doctor_run "$DOCTOR_TMP/run"
    python -m paddle_tpu.observability.doctor "$DOCTOR_TMP/run"
    python - "$DOCTOR_TMP/run/diagnosis.json" <<'PYEOF'
import json, sys
diag = json.load(open(sys.argv[1]))
assert diag["findings"], "doctor smoke: empty diagnosis on degraded fixture"
kinds = {f["kind"] for f in diag["findings"]}
assert {"retrace_storm", "straggler"} <= kinds, f"doctor smoke: {kinds}"
PYEOF
    rm -rf "$DOCTOR_TMP"
    # serving tier (ISSUE 6 + 15): paged-KV cache invariants, scheduler
    # policy, ragged-vs-dense numerics, compile contract, facade routing,
    # and the resilience layer (deadlines/cancel, quarantine, drain)
    python -m pytest -q -m serving tests/test_serving.py \
        tests/test_serving_resilience.py
    # serve smoke: engine + status server on an ephemeral port, 8
    # concurrent synthetic streams; /statusz must report nonzero TTFT
    # percentiles and KV occupancy mid-flight
    JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, urllib.request
import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM

pt.seed(0)
cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=2, num_heads=2,
                ffn_hidden_size=64, max_position_embeddings=32,
                hidden_dropout=0.0, attention_dropout=0.0)
model = GPTForCausalLM(cfg)
engine = ServingEngine(model, max_seqs=8, kv_block_size=4)
srv = engine.start_status_server(port=0, host="127.0.0.1")
for i in range(8):
    engine.submit([1 + i % 4] * (2 + i % 5), max_new_tokens=6)
# step until every stream produced its first token, then scrape mid-run
while any(s.first_token_time is None
          for s in engine.sched.running + list(engine.sched.waiting)):
    engine.step()
base = f"http://127.0.0.1:{srv.port}"
sz = json.loads(urllib.request.urlopen(base + "/statusz", timeout=5).read())
serving = sz["serving"]
assert serving["ttft_ms"]["count"] >= 8, serving["ttft_ms"]
assert serving["ttft_ms"]["p50"] > 0 and serving["ttft_ms"]["p99"] > 0
assert serving["kv_occupancy"] > 0, serving
hz = json.loads(urllib.request.urlopen(base + "/healthz", timeout=5).read())
assert hz["ok"] is True, hz
engine.run(max_steps=500)
engine.stop()
print("serve smoke: 8 streams, /statusz TTFT p50/p99 + KV occupancy ok")
PYEOF
    # serving chaos drill (ISSUE 15): poison one of 8 ragged streams →
    # exactly that request quarantined with a durable record, peers
    # token-exact, allocator back to baseline; then drain under load →
    # spill → fresh-engine resume to completion
    JAX_PLATFORMS=cpu python examples/gpt_generate.py --chaos_serve
    # drain-state smoke: /healthz must flip to 503 draining the moment
    # admission closes, then report a clean stop
    JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, urllib.request
import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM

pt.seed(0)
cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=2, num_heads=2,
                ffn_hidden_size=64, max_position_embeddings=32,
                hidden_dropout=0.0, attention_dropout=0.0)
engine = ServingEngine(GPTForCausalLM(cfg), max_seqs=4, kv_block_size=4)
srv = engine.start_status_server(port=0, host="127.0.0.1")
for i in range(4):
    engine.submit([1 + i] * 3, max_new_tokens=4)
base = f"http://127.0.0.1:{srv.port}"
for _ in range(4):
    engine.step()
engine.begin_drain()
try:
    urllib.request.urlopen(base + "/healthz", timeout=5)
    raise AssertionError("healthz should be 503 while draining")
except urllib.error.HTTPError as e:
    assert e.code == 503, e.code
    hz = json.loads(e.read())
    assert hz["state"] == "draining", hz
report = engine.drain(timeout=60.0)
assert not report["timed_out"] and report["spilled"] == 0, report
sz = json.loads(urllib.request.urlopen(base + "/statusz", timeout=5).read())
assert sz["serving"]["resilience"]["state"] == "stopped", sz["serving"]
engine.stop()
print("drain smoke: healthz 503 draining -> clean stop, 4 streams finished")
PYEOF
    # fleet tier (ISSUE 16): router dispatch/affinity/admission units,
    # journal-replay failover token-exactness, and the multi-process
    # drills — one replica SIGKILLed mid-stream (every client must
    # complete token-exact vs an uninterrupted single-engine reference,
    # fleet.failovers >= 1, survivor allocators clean) and a rolling
    # upgrade (drain each replica in turn under load, zero drops)
    python -m pytest -q -m serving tests/test_serve_fleet.py \
        tests/test_fleet_autonomy.py
    JAX_PLATFORMS=cpu python examples/serve_fleet.py --sigkill_drill
    JAX_PLATFORMS=cpu python examples/serve_fleet.py --rolling_upgrade
    # fleet autonomy drills (ISSUE 17): SIGKILL the *router* mid-stream
    # (the workers survive) — Router(recover=run_dir) must finish every
    # stream token-exact from the journal directory alone with zero
    # replica restarts; then the SLO autoscaler on fake time — burst ->
    # up, ceiling -> blocked_at_max, idle window -> drain + retire down
    JAX_PLATFORMS=cpu python examples/serve_fleet.py --router_crash_drill
    JAX_PLATFORMS=cpu python examples/serve_fleet.py --autoscale_drill
    # serve_fleet smoke row into the ledger (advisory gate on first rows)
    JAX_PLATFORMS=cpu python -m paddle_tpu.bench \
        --scenario serve_fleet --smoke
    # trace overhead bound (ISSUE 18 acceptance): request tracing must
    # cost < 1% of the router-pump step p50 — the row just appended
    # carries the metered emit-path cost (emission_cost), the
    # untraced-vs-traced p50s, and the assembled coverage
    python - <<'PYEOF'
import json
from paddle_tpu.bench.ledger import default_ledger_path
rows = [json.loads(l)
        for l in open(default_ledger_path(), encoding="utf-8")
        if l.strip()]
row = next(r for r in reversed(rows)
           if r.get("scenario") == "serve_fleet")
ex = row["extra"]
frac = ex["trace_overhead_frac"]
assert frac < 0.01, \
    f"request tracing overhead {frac:.3%} >= 1% of pump step p50"
assert ex["traces_assembled"] >= 4, ex
assert ex["traces_complete"] == ex["traces_assembled"], ex
assert ex["trace_orphan_spans"] == 0, ex
print(f"trace overhead: {frac:.3%} of pump step p50 (< 1% bound), "
      f"{ex['traces_complete']} traces complete, coverage p50 "
      f"{ex['trace_coverage_p50']:.0%}")
PYEOF
    # kernels tier (ISSUE 7): Pallas/fused-op parity — flash attention,
    # fused block (both routes), fused CE, rope cache
    python -m pytest -q -m kernels tests/test_ops.py tests/test_fused_block.py
    # fused-block A/B smoke: the fused path must show a step-time win on
    # the smoke model and must not retrace (one compile per shape, storm
    # records empty — the ISSUE 7 compile contract)
    JAX_PLATFORMS=cpu python - <<'PYEOF'
from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
force_virtual_cpu_mesh(1)
import bench
rows = bench._bench_fused_block_ab(artifact=False,
                                   **bench._SMOKE_FUSED_BLOCK_AB)
fused = rows["fused_block"]
assert fused["compiles"] == 1, f"fused step compiled {fused['compiles']}x"
assert fused["retraces"] == 0, f"fused step retraced: {fused}"
assert fused["storms"] == 0, f"retrace storm on the fused path: {fused}"
# threshold lives in benchmarks/golden.json (ISSUE 13), not this script:
# recalibration is a --write-golden diff, reviewed like any change
from paddle_tpu.bench.ledger import load_golden, threshold
min_speedup = threshold(load_golden(), "fused_block_min_speedup")
speedup = rows["speedup_fused_over_unfused"]
assert speedup > min_speedup, \
    f"fused block lost the A/B: {speedup:.2f}x <= {min_speedup:.2f}x"
print(f"fused-block smoke: {speedup:.2f}x over unfused "
      f"(floor {min_speedup:.2f}x), 1 compile, 0 retraces, 0 storms")
PYEOF
    # comm tier (ISSUE 8): blockwise quantization bounds, compressed
    # collectives, error-feedback sync, ZeRO-1 ShardedOptimizer parity
    # (uneven shapes / scalar leaves / mixed dtypes), fleet wiring,
    # doctor comm_bound
    python -m pytest -q -m comm tests/test_comm.py
    # comm smoke + MULTICHIP-style 8-device virtual-mesh drill (ISSUE 8
    # acceptance): the dp-comm A/B on the smoke GPT must compile once per
    # leg, the int8+error-feedback leg must ship >=3x fewer bytes and
    # land within 1% of the fp32 loss after 30 steps, and ZeRO-1 must
    # match replicated Adam params to dtype tolerance
    JAX_PLATFORMS=cpu python - <<'PYEOF'
from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
force_virtual_cpu_mesh(8)
import numpy as np
import jax, jax.numpy as jnp
import bench
import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.comm.config import set_default_comm_config

rows = bench._bench_comm_ab(artifact=False, **bench._SMOKE_COMM_AB)
for mode in ("fp32", "int8_ef", "zero1"):
    r = rows[mode]
    assert r["compiles"] == 1, f"{mode} leg compiled {r['compiles']}x"
    assert r["retraces"] == 0 and r["storms"] == 0, (mode, r)
# quality bounds read from benchmarks/golden.json (ISSUE 13) — the
# historical hard-coded constants are now the golden's defaults
from paddle_tpu.bench.ledger import load_golden, threshold
golden = load_golden()
min_ratio = threshold(golden, "comm_min_compress_ratio")
max_int8_loss = threshold(golden, "comm_int8_max_loss_rel")
max_zero1_loss = threshold(golden, "comm_zero1_max_loss_rel")
min_shrink = threshold(golden, "comm_zero1_min_state_shrink")
assert rows["int8_ef"]["compress_ratio"] >= min_ratio, \
    f"int8 leg ratio {rows['int8_ef']['compress_ratio']:.2f}x < {min_ratio}x"
assert rows["int8_vs_fp32_loss_rel"] < max_int8_loss, \
    f"int8+EF loss drifted {rows['int8_vs_fp32_loss_rel']:.2%} from fp32"
assert rows["zero1_vs_fp32_loss_rel"] < max_zero1_loss, \
    rows["zero1_vs_fp32_loss_rel"]
assert rows["zero1"]["opt_state_bytes_per_replica"] * min_shrink < \
    rows["fp32"]["opt_state_bytes_per_replica"], "ZeRO-1 state not sharded"

# param-level parity drill: ZeRO-1 through the fleet one-config-line
# switch vs replicated AdamW, 3 jitted steps on the dp=8 mesh
strategy = fleet.DistributedStrategy()
strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
strategy.sharding = True
strategy.sharding_configs = {"stage": 1, "shard_weight_update": True}
fleet.init(is_collective=True, strategy=strategy)
opt = fleet.distributed_optimizer(
    pt.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.01), strategy)
ref = pt.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.01)
rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(37, 19), jnp.float32),
          "b": jnp.asarray(rng.randn(11), jnp.float32)}
st, rst = opt.init(params), ref.init(params)
step = jax.jit(opt.apply_gradients)
p_z, p_r = params, params
for i in range(3):
    grads = {k: jnp.asarray(np.random.RandomState(i).randn(*v.shape),
                            jnp.float32) for k, v in params.items()}
    p_z, st = step(grads, p_z, st)
    p_r, rst = ref.apply_gradients(grads, p_r, rst)
for k in params:
    d = float(jnp.abs(p_z[k] - p_r[k]).max())
    assert d < 3e-6, f"ZeRO-1 {k} diverged from replicated AdamW: {d}"
set_default_comm_config(None)
print(f"comm smoke: 1 compile/leg, int8 ratio "
      f"{rows['int8_ef']['compress_ratio']:.2f}x, int8+EF loss within "
      f"{rows['int8_vs_fp32_loss_rel']:.3%} of fp32, ZeRO-1 == replicated "
      f"AdamW (8-device drill)")
PYEOF
    # elastic tier (ISSUE 9): world descriptor/fencing/relayout units +
    # the SIGKILL fault drills (marker `faults`; the subprocess drills
    # are `slow`, so tier-1 skips them — this is where they run)
    python -m pytest -q -m faults tests/test_elastic_fleet.py
    # launcher reconciliation smoke: SIGKILL worker 1 mid-run under
    # `launch --elastic 1:2` on the virtual-CPU mesh — the run must
    # complete (rc 0) with BOTH transitions (shrink + re-expand) and a
    # worker rewind to last_good_step in the reports
    ELASTIC_TMP=$(mktemp -d)
    JAX_PLATFORMS=cpu PTPU_HEARTBEAT_SECS=0.5 \
        PTPU_ELASTIC_RESPAWN_SECS=1.5 PTPU_TEST_SIGKILL_STEP=10 \
        PTPU_TEST_SIGKILL_RANK=1 \
        python -m paddle_tpu.distributed.launch --nnodes 2 \
        --elastic 1:2 --run_dir "$ELASTIC_TMP" \
        examples/train_elastic.py -- --steps 30 --save-interval 8 \
        --step-time 0.08
    python - "$ELASTIC_TMP" <<'PYEOF'
import json, sys
run = sys.argv[1]
report = json.load(open(run + "/launcher_report.json"))
dirs = [e["direction"] for e in report["events"]
        if e["kind"] == "elastic.resize"]
assert "shrink" in dirs and "grow" in dirs, dirs
(done,) = [e for e in report["events"] if e["kind"] == "elastic.done"]
assert done["returncode"] == 0, done
r0 = json.load(open(run + "/result-worker-0.json"))
assert r0["rewinds"] >= 1 and len(r0["losses"]) == 30, r0["rewinds"]
world = json.load(open(run + "/world.json"))
assert world["generation"] >= 2 and world["members"] == [0, 1], world
print("elastic smoke: SIGKILL drill — shrink + re-expand recorded, "
      f"worker rewound {r0['rewinds']}x, run completed at gen "
      f"{world['generation']}")
PYEOF
    rm -rf "$ELASTIC_TMP"
    # integrity tier (ISSUE 11): fingerprint/guard/heal units + the
    # cross-width relayout invariance drill in the elastic suite
    python -m pytest -q -m integrity tests/test_integrity.py \
        tests/test_elastic_fleet.py
    # integrity smoke (ISSUE 11 acceptance): 3 lockstep replicas, one
    # injected bitflip — detected within one interval, attributed to the
    # right worker by majority vote, classified hardware-SDC by the
    # replay audit, healed by resync, and the healed run's final state
    # must be bit-identical to an un-faulted reference
    INTEG_TMP=$(mktemp -d)
    JAX_PLATFORMS=cpu python - "$INTEG_TMP" <<'PYEOF'
import os, sys
import numpy as np
import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.distributed.fingerprint import digest_tree_host
from paddle_tpu.hapi import Model
from paddle_tpu.supervisor import RunSupervisor
from paddle_tpu.supervisor.integrity import IntegrityGuard
from paddle_tpu.testing.faults import bitflip

run_dir = sys.argv[1]

def worker(i, n):
    pt.seed(7)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    m = Model(net)
    m.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                         parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    guard = IntegrityGuard(run_dir, worker_id=i, every=2, expected=n,
                           action="resync", resync_timeout=5.0)
    sup = RunSupervisor(
        run_dir, worker_id=i, expected_workers=n, sigterm_handler=False,
        integrity=guard, report_path=os.path.join(
            run_dir, "supervisor_report.json" if i == 0
            else f"supervisor_report-{i}.json"))
    sup.attach(m)
    return m, sup

N, STEPS, FLIP = 3, 8, 4
workers = [worker(i, N) for i in range(N)]
fault = bitflip("params/0.weight", bit=13, step=FLIP, worker=2)
rng = np.random.RandomState(0)
batches = [(rng.randn(8, 8).astype("float32"),
            (np.arange(8) % 4).astype("int64")) for _ in range(STEPS)]
losses = {i: [] for i in range(N)}
for step0, (xs, ys) in enumerate(batches):
    for i, (m, sup) in enumerate(workers):
        losses[i].append(m.train_batch(xs, ys)[0])
        m._load_supervised_state(
            fault(step0 + 1, m._supervised_state(), worker=i))
        sup.note_step_ok(m._supervised_state())
    for m, sup in workers:
        sup.recheck_integrity()
    suspects = set()
    for m, sup in workers:
        if sup.pending_integrity is not None:
            suspects.update(sup.pending_integrity["suspects"])
    for i, (m, sup) in enumerate(workers):
        if sup.pending_integrity is not None and i not in suspects:
            m._supervised_integrity_heal(sup)
    for i, (m, sup) in enumerate(workers):
        if sup.pending_integrity is not None:
            m._supervised_integrity_heal(sup)
assert fault.fired == FLIP, "bitflip never fired"
desyncs = workers[0][1].report.of_kind("integrity.desync")
assert desyncs and desyncs[0]["step"] == FLIP, desyncs  # one interval
assert desyncs[0]["suspects"] == [2], desyncs[0]        # right worker
heals = workers[2][1].report.of_kind("integrity.heal")
resyncs = [h for h in heals if h.get("action") == "resync"]
assert resyncs and resyncs[0]["audit"]["verdict"] == "sdc_suspect", heals
finals = {digest_tree_host(m._supervised_state()).hex()
          for m, _ in workers}
assert len(finals) == 1, finals
pt.seed(7)
ref_net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
ref = Model(ref_net)
ref.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                       parameters=ref_net.parameters()),
            loss=nn.CrossEntropyLoss())
ref_losses = [ref.train_batch(xs, ys)[0] for xs, ys in batches]
assert digest_tree_host(ref._supervised_state()).hex() in finals, \
    "healed fleet diverged from the un-faulted reference"
assert losses[0][-1] == ref_losses[-1]
print(f"integrity smoke: bitflip at step {FLIP} detected same interval, "
      "attributed to worker 2 (sdc_suspect), resync-healed, final state "
      "bit-equal to un-faulted reference")
PYEOF
    rm -rf "$INTEG_TMP"
    # integrity overhead bound (ISSUE 11 acceptance): the per-check cost
    # amortized over the default interval must stay under 1% of step time
    JAX_PLATFORMS=cpu python - <<'PYEOF'
import bench
rows = bench._bench_integrity_overhead(artifact=False,
                                       **bench._SMOKE_INTEGRITY_AB)
frac = rows["integrity"]["overhead_frac"]
assert frac < 0.01, f"integrity overhead {frac:.3%} >= 1% of step time"
print(f"integrity overhead: {frac:.3%} of step time (< 1% bound)")
PYEOF
    BENCH_CPU=1 BENCH_SKIP_SLICE=1 python bench.py > /dev/null
    BENCH_CPU=1 python examples/gpt_generate.py --bench_serve > /dev/null
    # perf tier (ISSUE 13 → 14): the scenario matrix in smoke mode
    # appends this run's rows to the REAL ledger (benchmarks/
    # ledger.jsonl is the project's performance memory, not a throwaway),
    # then the trend engine + dashboard smokes and the noise-aware gate
    # run against the accumulated series (re-bless after an intentional
    # change: python -m paddle_tpu.bench.gate --write-golden)
    JAX_PLATFORMS=cpu python -m paddle_tpu.bench --all --smoke > /dev/null
    JAX_PLATFORMS=cpu python -m paddle_tpu.bench.trends
    JAX_PLATFORMS=cpu python -m paddle_tpu.bench.report
    JAX_PLATFORMS=cpu python - <<'PYEOF'
from paddle_tpu.bench.report import default_report_path
from paddle_tpu.bench.scenarios import names
doc = open(default_report_path(), encoding="utf-8").read()
assert doc.strip(), "dashboard rendered empty"
missing = [n for n in names() if n not in doc]
assert not missing, f"dashboard missing scenario(s): {missing}"
for banned in ("http://", "https://", "<script", "@import"):
    assert banned not in doc, f"dashboard not self-contained: {banned}"
print(f"dashboard: {len(doc)} bytes, all {len(names())} scenarios, "
      "self-contained")
PYEOF
    JAX_PLATFORMS=cpu python -m paddle_tpu.bench.gate
    JAX_PLATFORMS=cpu python -m paddle_tpu.bench.ledger --compact
    # MFU microscope (ISSUE 19): every smoke row just appended must carry
    # a roofline gap budget whose buckets (with residual) sum to the
    # measured step; the unexplained residual must stay under the honesty
    # bound even on the CPU smoke (advisory gap table printed)
    JAX_PLATFORMS=cpu python -m paddle_tpu.observability.roofline \
        --mode smoke
    # roofline drill: inject a synthetic memory_bound gap and assert the
    # doctor names exactly that sink — the alarm must fire for the right
    # reason, not merely fire
    JAX_PLATFORMS=cpu PTPU_ROOFLINE_TEST_INFLATE=memory_bound:0.6 \
        python - <<'PYEOF'
from paddle_tpu.bench import runner
from paddle_tpu.observability import doctor
row = runner.run_scenario("mnist", mode="smoke")
roof = row["roofline"]
assert roof["injected"], "inflation knob did not mark the block"
assert roof["dominant_sink"] == "memory_bound", roof["dominant_sink"]
total = sum(roof["buckets_ms"].values())
tol = max(0.01, 0.005 * roof["measured_step_ms"])
assert abs(total - roof["measured_step_ms"]) <= tol, (
    total, roof["measured_step_ms"])
rec = {"kind": "bench.row", "scenario": row["scenario"], "ts": 0.0,
       "mfu": row["mfu"], "roofline": roof}
(finding,) = doctor.check_mfu_gap({0: [rec]})
assert finding["data"]["dominant"] == "memory_bound", finding
assert finding["data"]["injected"] is True, finding
print("roofline drill: injected memory_bound gap -> doctor verdict:",
      finding["title"])
PYEOF
    # interconnect microscope (ISSUE 20): every smoke row just appended
    # must carry a comm sub-budget whose entries (with the unattributed
    # remainder) sum to the roofline's comm bucket — the reconciliation
    # gate that makes the attribution provable, not decorative
    JAX_PLATFORMS=cpu python -m paddle_tpu.observability.interconnect \
        --mode smoke
    # comm-inflation drill: inflate the comm bucket AND inject a named
    # (op, axis) into the sub-budget, then assert the doctor names
    # exactly that collective on exactly that axis — the alarm must fire
    # for the right reason, not merely fire
    JAX_PLATFORMS=cpu PTPU_ROOFLINE_TEST_INFLATE=comm:0.5 \
        PTPU_INTERCONNECT_TEST_INFLATE=all_to_all:ep:0.8 \
        python - <<'PYEOF'
from paddle_tpu.bench import runner
from paddle_tpu.observability import doctor
row = runner.run_scenario("mnist", mode="smoke")
ic = row["interconnect"]
assert ic["injected"] == {"op": "all_to_all", "axis": "ep",
                          "frac": 0.8}, ic["injected"]
entries = ic["entries"]
dom = max((e for e in entries if e["op"] != "(unattributed)"),
          key=lambda e: e["measured_ms"])
assert (dom["op"], dom["axis"]) == ("all_to_all", "ep"), dom
total = sum(e["measured_ms"] for e in entries)
tol = max(0.01, 0.005 * abs(ic["comm_bucket_ms"]))
assert abs(total - ic["comm_bucket_ms"]) <= tol, (
    total, ic["comm_bucket_ms"])
assert abs(ic["comm_bucket_ms"]
           - row["roofline"]["buckets_ms"]["comm"]) <= tol
rec = {"kind": "bench.row", "scenario": row["scenario"], "ts": 0.0,
       "roofline": {"measured_step_ms":
                    row["roofline"]["measured_step_ms"]},
       "interconnect": ic}
(finding,) = doctor.check_comm_budget({0: [rec]})
assert finding["data"]["op"] == "all_to_all", finding
assert finding["data"]["axis"] == "ep", finding
print("interconnect drill: injected all_to_all[axis=ep] -> doctor "
      "verdict:", finding["title"])
PYEOF
    # warm-start drill (ROADMAP 5a): the persistent-compile-cache test is
    # `slow` (two fresh jax processes), so tier-1 skips it — run it here
    python -m pytest -q -m slow tests/test_compile_cache.py
    echo "api-guard + ptlint + faults tier + telemetry tier + trace" \
         "drill + doctor smoke + monitor smoke + serving tier + serve" \
         "smoke + serve chaos drill + drain smoke + fleet tier + fleet" \
         "drills + trace overhead + kernels tier + fused-block smoke" \
         "+ comm tier + comm smoke + elastic tier + elastic smoke +" \
         "integrity tier + integrity smoke + integrity overhead +" \
         "bench smoke + perf tier + trends + dashboard + roofline" \
         "residual bound + roofline drill + interconnect reconciliation" \
         "+ interconnect drill + warm-start ok"
fi
echo "shard ${SHARD} green"
