#!/usr/bin/env bash
# CI entry (component E10 — the analog of paddle_build.sh + parallel_UT_rule):
#   tools/ci.sh [shard_index shard_count]
#
# Shards the test files deterministically across workers (sorted list,
# round-robin) so a CI fleet can split the suite; no args = everything.
# API-compat guard + bench smoke run in shard 0 only.
set -euo pipefail
cd "$(dirname "$0")/.."

SHARD=${1:-0}
SHARDS=${2:-1}

mapfile -t FILES < <(ls tests/test_*.py | sort)
SELECTED=()
for i in "${!FILES[@]}"; do
    if (( i % SHARDS == SHARD )); then
        SELECTED+=("${FILES[$i]}")
    fi
done

echo "shard ${SHARD}/${SHARDS}: ${#SELECTED[@]} files"
if (( ${#SELECTED[@]} )); then
    python -m pytest "${SELECTED[@]}" -q
else
    echo "shard ${SHARD} has no files — nothing to run"
fi

if (( SHARD == 0 )); then
    python tools/print_signatures.py --check
    python tools/lint_bare_except.py
    python tools/lint_print.py
    # resilience tier: the fault-injection suite must stay green even when
    # sharding happens to place its files elsewhere
    python -m pytest -q -m faults tests/test_fault_tolerance.py \
        tests/test_supervisor.py
    # telemetry tier (ISSUE 3): registry/tracing/sinks/aggregation + the
    # e2e step-breakdown/MFU records contract
    python -m pytest -q -m telemetry tests/test_observability.py
    BENCH_CPU=1 BENCH_SKIP_SLICE=1 python bench.py > /dev/null
    echo "api-guard + lints + faults tier + telemetry tier + bench smoke ok"
fi
echo "shard ${SHARD} green"
