"""One-off hardware tuning scan (round 5): block-size sweep for the flash
kernel plus a batch-size scan of the headline 125M config.  Serializes with
other chip users — run alone.  Results go to benchmarks/ via bench helpers.

Usage: python tools/hw_tune.py [sweep|batch|all]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402


def batch_scan():
    import bench
    from paddle_tpu.models import gpt_125m
    rows = {}
    for B in (8, 16, 32):
        cfg = gpt_125m(dtype="bfloat16", hidden_dropout=0.0,
                       attention_dropout=0.0, use_pallas_attention=True,
                       max_position_embeddings=2048)
        try:
            tok_s, mfu = bench._bench_config(cfg, B=B, S=2048, steps=8,
                                             warmup=3, tag=f"125m-B{B}")
            rows[f"B{B}"] = {"tok_s": tok_s, "mfu": mfu}
        except Exception as e:  # OOM at large B must not kill the scan
            rows[f"B{B}"] = {"error": repr(e)}
            print(f"[batch-scan B={B}] failed: {e!r}", file=sys.stderr)
    bench._write_artifact("batch_scan_125m.json", rows)


def main():
    what = sys.argv[1] if len(sys.argv) > 1 else "all"
    assert jax.devices()[0].platform == "tpu", jax.devices()
    import bench
    if what in ("sweep", "all"):
        bench._sweep_block_sizes()
    if what in ("batch", "all"):
        batch_scan()


if __name__ == "__main__":
    main()
