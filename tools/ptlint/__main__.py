"""CLI for ptlint: ``python -m tools.ptlint [--all | --pass NAME] [roots]``.

Exit status: 0 when no *new* findings (relative to the baseline), 1 when
new findings exist, 2 on usage errors.  ``--no-baseline`` compares
against an empty baseline (every finding fails); ``--write-baseline``
rewrites tools/ptlint/baseline.json from the current findings and exits
0.  ``--json`` emits one machine-readable object for tooling.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from .engine import (DEFAULT_BASELINE, Project, all_passes, load_baseline,
                     new_findings, run_passes, write_baseline)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_DEFAULT_ROOT = os.path.join(_REPO_ROOT, "paddle_tpu")


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ptlint",
        description="unified static analysis for the paddle_tpu package")
    parser.add_argument("roots", nargs="*", default=[],
                        help="files/directories to scan "
                             "(default: paddle_tpu/)")
    parser.add_argument("--all", action="store_true",
                        help="run every registered pass (default when no "
                             "--pass is given)")
    parser.add_argument("--pass", dest="passes", action="append",
                        default=[], metavar="NAME",
                        help="run one pass (repeatable); see --list")
    parser.add_argument("--list", action="store_true",
                        help="list registered passes and exit")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as one JSON object")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        metavar="PATH", help="baseline file to compare "
                        "against (default: tools/ptlint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline — every finding fails")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "and exit 0")
    parser.add_argument("--docs", default=None, metavar="PATH",
                        help="docs file for the knobs inventory "
                             "(default: docs/ARCHITECTURE.md)")
    args = parser.parse_args(argv)

    registry = all_passes()
    if args.list:
        width = max(len(n) for n in registry)
        for name in sorted(registry):
            print(f"{name:<{width}}  {registry[name].description}")  # noqa: print — CLI output
        return 0

    for name in args.passes:
        if name not in registry:
            print(f"ptlint: unknown pass {name!r} "  # noqa: print — CLI output
                  f"(known: {', '.join(sorted(registry))})",
                  file=sys.stderr)
            return 2
    names = args.passes or None  # None → all registered passes

    roots = args.roots or [_DEFAULT_ROOT]
    for root in roots:
        if not os.path.exists(root):
            print(f"ptlint: no such path: {root}", file=sys.stderr)  # noqa: print — CLI output
            return 2
    project = Project(roots, repo_root=_REPO_ROOT, docs_path=args.docs)
    findings = run_passes(project, names)

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        print(f"ptlint: wrote {len(findings)} fingerprint(s) to "  # noqa: print — CLI output
              f"{os.path.relpath(args.baseline, _REPO_ROOT)}")
        return 0

    baseline = load_baseline(args.baseline) if not args.no_baseline else None
    fresh = new_findings(findings, baseline) if baseline is not None \
        else list(findings)

    if args.json:
        fresh_ids = {id(f) for f in fresh}
        payload = {
            "passes": sorted(names or registry),
            "roots": [os.path.relpath(r, _REPO_ROOT) for r in
                      (os.path.abspath(r) for r in roots)],
            "findings": [dict(f.to_json(), new=(id(f) in fresh_ids))
                         for f in findings],
            "new": len(fresh),
            "baselined": len(findings) - len(fresh),
        }
        print(json.dumps(payload, indent=1))  # noqa: print — CLI output
    else:
        for f in fresh:
            print(f.render())  # noqa: print — CLI output
        if fresh:
            print(f"ptlint: {len(fresh)} new finding(s) "  # noqa: print — CLI output
                  f"({len(findings) - len(fresh)} baselined)",
                  file=sys.stderr)

    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
