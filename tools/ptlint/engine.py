"""ptlint engine (ISSUE 12): one parse per file, pluggable passes.

The three ad-hoc lints (bare-except, print, fsio) each re-walked the
package with their own ``ast.parse`` loop; the deep passes this engine
exists for (trace-safety, lock-discipline) additionally need a *project*
view — an intra-package call graph, the docs text, every class in one
index.  So the engine inverts the old structure: a :class:`Project`
parses every file exactly once into :class:`Module` objects, and each
registered :class:`LintPass` walks those shared trees.

Findings are structured (:class:`Finding`: path/line/pass/code/message/
symbol/severity) and every pass shares one allowlist grammar — a
``# noqa: <token>`` comment on the finding line (legacy tokens
``swallow``/``print``/``fsio`` still work for the absorbed lints).

The baseline (``tools/ptlint/baseline.json``) holds *fingerprints* of
known findings — ``path::pass::code::symbol``, deliberately line-free so
unrelated edits don't churn it.  A run fails only on findings whose
fingerprint count exceeds the baseline's; ``--write-baseline``
regenerates it.  See docs/ARCHITECTURE.md "Static analysis".
"""
from __future__ import annotations

import ast
import json
import os
import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

__all__ = ["Finding", "Module", "Project", "LintPass", "register",
           "all_passes", "get_pass", "run_passes", "load_baseline",
           "write_baseline", "new_findings", "DEFAULT_BASELINE"]

_NOQA_RE = re.compile(r"#\s*noqa:\s*([A-Za-z0-9_,\- ]+)")

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


@dataclass
class Finding:
    """One structured lint finding.

    ``symbol`` is the stable identity used for baseline fingerprints —
    a function/attribute/knob name rather than a line number, so the
    baseline survives unrelated edits to the same file.
    """
    path: str          # path relative to the scanned root's parent
    line: int
    pass_name: str
    code: str          # short finding kind, e.g. "impure-call"
    message: str
    symbol: str = ""
    severity: str = "error"   # "error" | "warning"

    @property
    def fingerprint(self) -> str:
        return f"{self.path}::{self.pass_name}::{self.code}::{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_name}/{self.code}] "
                f"{self.message}")

    def to_json(self) -> Dict[str, object]:
        return {"path": self.path, "line": self.line,
                "pass": self.pass_name, "code": self.code,
                "message": self.message, "symbol": self.symbol,
                "severity": self.severity,
                "fingerprint": self.fingerprint}


class Module:
    """One parsed source file — tree + lines, parsed exactly once."""

    def __init__(self, path: str, rel: str, source: bytes):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.decode("utf-8", errors="replace").splitlines()
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(source,
                                                        filename=path)
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
        self._noqa: Optional[Dict[int, set]] = None

    # -- noqa allowlist ----------------------------------------------------
    def _noqa_map(self) -> Dict[int, set]:
        if self._noqa is None:
            m: Dict[int, set] = {}
            for i, line in enumerate(self.lines, 1):
                hit = _NOQA_RE.search(line)
                if hit:
                    # "# noqa: swallow — reason" / "# noqa: print, fsio":
                    # first word of each comma-separated part is the token
                    toks = {part.split()[0] for part in
                            hit.group(1).split(",") if part.split()}
                    m[i] = toks
            self._noqa = m
        return self._noqa

    def noqa_at(self, linenos: Iterable[int],
                tokens: Sequence[str]) -> bool:
        """True when any of ``linenos`` carries ``# noqa: <tok>`` for one
        of ``tokens`` (an allowlisted finding site)."""
        m = self._noqa_map()
        want = set(tokens)
        return any(m.get(n, set()) & want for n in linenos)

    def node_lines(self, node: ast.AST) -> List[int]:
        """The line span a noqa comment may sit on for ``node``."""
        start = getattr(node, "lineno", 0) or 0
        end = getattr(node, "end_lineno", start) or start
        return list(range(start, end + 1))

    # -- package identity --------------------------------------------------
    @property
    def dotted(self) -> Optional[str]:
        """``paddle_tpu.observability.monitor`` for package files, else
        None — derived from the relative path."""
        rel = self.rel.replace(os.sep, "/")
        if not rel.endswith(".py"):
            return None
        parts = rel[:-3].split("/")
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts) if parts else None


class Project:
    """Every module of the scanned roots, parsed once and shared by all
    passes, plus the repo-level context (docs text) cross-file passes
    need."""

    def __init__(self, roots: Sequence[str], repo_root: Optional[str] = None,
                 docs_path: Optional[str] = None):
        self.roots = [os.path.abspath(r) for r in roots]
        self.repo_root = os.path.abspath(
            repo_root
            or os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        self.docs_path = docs_path or os.path.join(self.repo_root, "docs",
                                                   "ARCHITECTURE.md")
        self.modules: List[Module] = []
        self.by_dotted: Dict[str, Module] = {}
        self._docs_text: Optional[str] = None
        for root in self.roots:
            base = os.path.dirname(root.rstrip(os.sep))
            if os.path.isfile(root):
                self._add(root, os.path.relpath(root, base))
                continue
            for dirpath, _dirnames, filenames in os.walk(root):
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        self._add(full, os.path.relpath(full, base))
        for mod in self.modules:
            if mod.dotted:
                self.by_dotted[mod.dotted] = mod

    def _add(self, full: str, rel: str) -> None:
        try:
            with open(full, "rb") as f:
                self.modules.append(Module(full, rel, f.read()))
        except OSError:
            pass  # unreadable file: nothing to lint

    @property
    def docs_text(self) -> str:
        if self._docs_text is None:
            try:
                with open(self.docs_path, "rb") as f:
                    self._docs_text = f.read().decode("utf-8",
                                                      errors="replace")
            except OSError:
                self._docs_text = ""
        return self._docs_text

    def resolve(self, dotted: Optional[str]) -> Optional[Module]:
        return self.by_dotted.get(dotted) if dotted else None


# ---------------------------------------------------------------------------
# pass registry
# ---------------------------------------------------------------------------
class LintPass:
    """Base class for a ptlint pass.

    ``name`` is the registry key and the canonical ``# noqa:`` token;
    ``noqa`` may add legacy aliases (the absorbed lints keep their
    historical ``swallow``/``print``/``fsio`` comments working)."""

    name: str = ""
    noqa: Tuple[str, ...] = ()
    description: str = ""

    @property
    def tokens(self) -> Tuple[str, ...]:
        return (self.name,) + tuple(self.noqa)

    def run(self, project: Project) -> List[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[LintPass]] = {}


def register(cls: Type[LintPass]) -> Type[LintPass]:
    assert cls.name, f"{cls} has no pass name"
    _REGISTRY[cls.name] = cls
    return cls


def all_passes() -> Dict[str, Type[LintPass]]:
    _load_builtin()
    return dict(_REGISTRY)


def get_pass(name: str) -> Type[LintPass]:
    _load_builtin()
    if name not in _REGISTRY:
        known = ", ".join(sorted(_REGISTRY))
        raise KeyError(f"unknown ptlint pass {name!r} (known: {known})")
    return _REGISTRY[name]


def _load_builtin() -> None:
    from . import passes  # noqa: F401 — importing registers the passes
    assert passes is not None


def run_passes(project: Project,
               names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the named passes (all when None) over the shared project.

    Syntax errors surface as findings from a pseudo-pass ``parse`` so a
    broken file fails loudly exactly once rather than once per pass."""
    _load_builtin()
    findings: List[Finding] = []
    for mod in project.modules:
        if mod.syntax_error is not None:
            e = mod.syntax_error
            findings.append(Finding(
                mod.rel, getattr(e, "lineno", 0) or 0, "parse",
                "syntax-error", f"syntax error: {e.msg}",
                symbol=os.path.basename(mod.rel)))
    chosen = list(names) if names else sorted(_REGISTRY)
    for name in chosen:
        findings.extend(get_pass(name)().run(project))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_name, f.code))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def load_baseline(path: str = DEFAULT_BASELINE) -> Counter:
    try:
        with open(path, "rb") as f:
            data = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        return Counter()
    return Counter(data.get("fingerprints", []))


def write_baseline(findings: Sequence[Finding],
                   path: str = DEFAULT_BASELINE) -> None:
    payload = {"version": 1,
               "fingerprints": sorted(f.fingerprint for f in findings)}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:  # noqa: fsio — dev tool, not runtime durability
        json.dump(payload, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)  # noqa: fsio — dev tool, not runtime durability


def new_findings(findings: Sequence[Finding],
                 baseline: Counter) -> List[Finding]:
    """Findings whose fingerprint count exceeds the baseline's — the set
    that fails CI (pre-existing debt stays visible but non-blocking)."""
    budget = Counter(baseline)
    fresh: List[Finding] = []
    for f in findings:
        if budget[f.fingerprint] > 0:
            budget[f.fingerprint] -= 1
        else:
            fresh.append(f)
    return fresh
