"""The three absorbed single-file lints, now sharing one parse.

``bare_except`` / ``print`` / ``fsio`` keep their historical semantics
and their historical ``# noqa: swallow`` / ``# noqa: print`` /
``# noqa: fsio`` allowlist comments — the engine accepts both the pass
name and the legacy token, so no annotated call site had to change.
"""
from __future__ import annotations

import ast
import os
from typing import List

from ..engine import Finding, LintPass, Module, Project, register

_BROAD = {"Exception", "BaseException"}


def _is_swallow(node: ast.ExceptHandler) -> bool:
    """True for ``except Exception/BaseException [as e]: pass``."""
    if not (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)):
        return False
    t = node.type
    return (t is None or (isinstance(t, ast.Name) and t.id in _BROAD)
            or (isinstance(t, ast.Attribute) and t.attr in _BROAD))


def _context_name(mod: Module, node: ast.AST) -> str:
    """Nearest enclosing function/class name for a stable symbol."""
    target = node
    best = ""
    for parent in ast.walk(mod.tree):
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            if (parent.lineno <= getattr(target, "lineno", 0)
                    <= (parent.end_lineno or parent.lineno)):
                best = parent.name
    return best or os.path.basename(mod.rel)


@register
class BareExceptPass(LintPass):
    """A bare ``except:`` swallows KeyboardInterrupt/SystemExit and the
    SIGTERM-driven control flow the fault-tolerance layer depends on;
    ``except Exception: pass`` names what it catches and then discards
    it anyway.  Legacy allowlist: ``# noqa: swallow``."""

    name = "bare_except"
    noqa = ("swallow",)
    description = ("bare `except:` clauses and silent "
                   "`except Exception: pass` swallowing")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                sites = [node.lineno]
                if node.body:
                    sites.append(node.body[0].lineno)
                if node.type is None:
                    out.append(Finding(
                        mod.rel, node.lineno, self.name, "bare-except",
                        "bare except — name the exception (at minimum "
                        "`except Exception:`)",
                        symbol=_context_name(mod, node)))
                elif (_is_swallow(node)
                      and not mod.noqa_at(sites, self.tokens)):
                    out.append(Finding(
                        mod.rel, node.lineno, self.name, "swallow",
                        "swallowed exception (`except Exception: pass`) — "
                        "handle it, narrow it, or mark `# noqa: swallow`",
                        symbol=_context_name(mod, node)))
        return out


@register
class PrintPass(LintPass):
    """Bare ``print(`` bypasses framework.log and the observability
    sinks — it can't be silenced, filtered, or aggregated.  Deliberate
    console surfaces carry ``# noqa: print``."""

    name = "print"
    noqa = ()
    description = "bare print() calls outside the logging/metrics seams"

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "print"
                        and not mod.noqa_at([node.lineno], self.tokens)):
                    out.append(Finding(
                        mod.rel, node.lineno, self.name, "print",
                        "bare print() — route through framework.log / an "
                        "observability sink, or mark a deliberate console "
                        "surface with `# noqa: print`",
                        symbol=_context_name(mod, node)))
        return out


_WRITE_CHARS = set("wax+")
_FSIO_EXEMPT = (os.path.join("paddle_tpu", "utils", "fsio.py"),)


def _mode_of(call: ast.Call):
    if len(call.args) >= 2:
        arg = call.args[1]
    else:
        arg = next((kw.value for kw in call.keywords
                    if kw.arg == "mode"), None)
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def _is_write_open(node: ast.Call) -> bool:
    fn = node.func
    if not (isinstance(fn, ast.Name) and fn.id == "open"):
        return False
    mode = _mode_of(node)
    if mode is None:  # default "r", or dynamic (benefit of the doubt)
        return len(node.args) >= 2 or any(
            kw.arg == "mode" for kw in node.keywords)
    return bool(set(mode) & _WRITE_CHARS)


def _is_os_replace(node: ast.Call) -> bool:
    fn = node.func
    return (isinstance(fn, ast.Attribute) and fn.attr == "replace"
            and isinstance(fn.value, ast.Name) and fn.value.id == "os")


@register
class FsioPass(LintPass):
    """Durable bytes flow through ``utils/fsio`` — that seam is where
    fsync discipline, fault injection and the integrity guarantees live.
    Flags write-mode ``open()`` and bare ``os.replace``; deliberate
    bypasses carry ``# noqa: fsio``.  ``utils/fsio.py`` is exempt — it
    IS the seam."""

    name = "fsio"
    noqa = ()
    description = "durable writes bypassing the utils/fsio seam"

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            if mod.tree is None or any(mod.rel.endswith(e)
                                       for e in _FSIO_EXEMPT):
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                if mod.noqa_at(mod.node_lines(node), self.tokens):
                    continue
                if _is_write_open(node):
                    out.append(Finding(
                        mod.rel, node.lineno, self.name, "open-write",
                        "write-mode open() bypasses utils/fsio — use "
                        "fsio.write_bytes/atomic_write_bytes, or mark a "
                        "deliberate bypass `# noqa: fsio`",
                        symbol=_context_name(mod, node)))
                elif _is_os_replace(node):
                    out.append(Finding(
                        mod.rel, node.lineno, self.name, "os-replace",
                        "bare os.replace bypasses utils/fsio's rename+"
                        "fsync discipline — use fsio.atomic_write_bytes, "
                        "or mark a deliberate bypass `# noqa: fsio`",
                        symbol=_context_name(mod, node)))
        return out
