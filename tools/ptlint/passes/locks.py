"""Lock-discipline pass (ISSUE 12 tentpole, pass 2).

The package spawns daemon threads in a dozen places — watchdog monitor,
heartbeat writer, status server, live aggregator, serving callback
drain, async checkpoint commit — and every one of them shares instance
attributes with the main thread.  The GIL makes single-bytecode races
rare enough to survive tests and bite in production, which is exactly
the class of silent hazard PR 11's integrity guard catches *after* it
corrupts state.  This pass catches it before the code runs:

1. **Thread contexts.**  A method is thread-context when it is the
   ``target=`` of a ``threading.Thread(...)`` (``self.method`` or a
   function nested in a method — the async-commit pattern), the
   ``run()`` of a ``threading.Thread`` subclass, or transitively
   self-called from one of those.  Every other method (``__init__``
   excluded — it runs before any thread starts) is main-context; a
   method reachable from both (``poll`` called by the loop *and* by
   ``stop``) counts for both.

2. **Findings.**  An instance attribute *written* from a thread context
   and *also written* from a main context must carry a
   ``# guarded_by: <lockname>`` annotation on an assignment line of
   that attribute inside the class (idiomatically its ``__init__``
   line).  Unannotated dual-context writes are findings naming the
   attribute and both contexts.

3. **Enforcement.**  For an annotated attribute, every access site
   (read or write) outside ``__init__`` must be *lexically* inside a
   ``with self.<lockname>:`` block — dynamic "the caller holds it"
   discipline is exactly what rots — or carry ``# noqa: locks`` with a
   reason (e.g. a monotonic counter read for display only).

``threading.Condition`` counts as a lock (``with self._cond:`` is an
acquire).  Annotation grammar and the workflow live in
docs/ARCHITECTURE.md "Static analysis".
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..engine import Finding, LintPass, Module, Project, register

_GUARDED_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=#]+)?=[^#]*#\s*guarded_by:\s*(\w+)")
_GUARDED_BARE_RE = re.compile(r"#\s*guarded_by:\s*(\w+)\s*$")


def _is_thread_ctor(node: ast.Call) -> bool:
    f = node.func
    return ((isinstance(f, ast.Attribute) and f.attr == "Thread")
            or (isinstance(f, ast.Name) and f.id == "Thread"))


def _self_attr_store_root(target: ast.AST) -> Optional[str]:
    """'x' when ``target`` stores through ``self.x`` (directly, or via
    ``self.x[i] = .. / self.x.y = ..`` container mutation)."""
    node = target
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        parent = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(parent, ast.Name) and parent.id == "self"):
            return node.attr
        node = parent
    return None


@dataclass
class _MethodInfo:
    name: str
    node: ast.AST                      # FunctionDef (or nested thread body)
    self_names: Set[str] = field(default_factory=set)  # {'self', aliases}
    is_nested_thread_body: bool = False
    host: str = ""                     # enclosing method for nested bodies

    @property
    def label(self) -> str:
        return f"{self.host}.<locals>.{self.name}" \
            if self.is_nested_thread_body else self.name


class _ClassModel:
    def __init__(self, mod: Module, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.methods: Dict[str, _MethodInfo] = {}
        self.thread_roots: Set[str] = set()
        self._collect()

    # -- structure ---------------------------------------------------------
    def _collect(self) -> None:
        is_thread_subclass = any(
            (isinstance(b, ast.Name) and b.id == "Thread")
            or (isinstance(b, ast.Attribute) and b.attr == "Thread")
            for b in self.node.bases)
        for stmt in self.node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi = _MethodInfo(stmt.name, stmt, self_names={"self"})
                self.methods[stmt.name] = mi
        if is_thread_subclass and "run" in self.methods:
            self.thread_roots.add("run")
        # threading.Thread(target=...) sites inside methods
        for name, mi in list(self.methods.items()):
            aliases = self._self_aliases(mi.node)
            mi.self_names |= aliases
            for sub in ast.walk(mi.node):
                if not (isinstance(sub, ast.Call)
                        and _is_thread_ctor(sub)):
                    continue
                target = next((kw.value for kw in sub.keywords
                               if kw.arg == "target"), None)
                if target is None and sub.args:
                    target = sub.args[0]
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id in mi.self_names
                        and target.attr in self.methods):
                    self.thread_roots.add(target.attr)
                elif isinstance(target, ast.Name):
                    nested = self._nested_def(mi.node, target.id)
                    if nested is not None:
                        body = _MethodInfo(
                            target.id, nested,
                            self_names=set(mi.self_names),
                            is_nested_thread_body=True, host=name)
                        key = f"{name}.<locals>.{target.id}"
                        self.methods[key] = body
                        self.thread_roots.add(key)

    @staticmethod
    def _nested_def(method: ast.AST, name: str) -> Optional[ast.AST]:
        for sub in ast.walk(method):
            if isinstance(sub, (ast.FunctionDef,
                                ast.AsyncFunctionDef)) \
                    and sub.name == name and sub is not method:
                return sub
        return None

    @staticmethod
    def _self_aliases(method: ast.AST) -> Set[str]:
        """Names bound to ``self`` in the method (``server = self`` — the
        nested-handler/closure pattern)."""
        out: Set[str] = set()
        for sub in ast.walk(method):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"):
                out.add(sub.targets[0].id)
        return out

    # -- intra-class call graph --------------------------------------------
    def _calls_of(self, mi: _MethodInfo) -> Set[str]:
        out: Set[str] = set()
        for sub in ast.walk(mi.node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id in mi.self_names
                    and sub.func.attr in self.methods):
                out.add(sub.func.attr)
        return out

    def _closure(self, roots: Set[str]) -> Set[str]:
        seen: Set[str] = set()
        queue = [r for r in roots if r in self.methods]
        while queue:
            name = queue.pop()
            if name in seen:
                continue
            seen.add(name)
            queue.extend(self._calls_of(self.methods[name]) - seen)
        return seen

    def contexts(self) -> Tuple[Set[str], Set[str]]:
        """(thread_methods, main_methods) — method-name closures.

        Thread context is the closure of the thread roots.  Main roots
        are the methods *outside* that closure (a helper only ever
        self-called from the thread body is thread-only, not "any other
        method"); a thread-context method the main side also calls —
        ``stop() -> poll()`` — lands in both closures, which is exactly
        the dual-context case."""
        thread = self._closure(self.thread_roots)
        main_roots = {n for n in self.methods
                      if n != "__init__" and n not in thread}
        main = self._closure(main_roots)
        return thread, main

    # -- accesses ----------------------------------------------------------
    def writes(self, mi: _MethodInfo) -> List[Tuple[str, int]]:
        out: List[Tuple[str, int]] = []
        for sub in ast.walk(mi.node):
            targets: List[ast.AST] = []
            if isinstance(sub, ast.Assign):
                targets = list(sub.targets)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            for t in targets:
                # tuple/list unpack targets
                elts = t.elts if isinstance(t, (ast.Tuple,
                                                ast.List)) else [t]
                for e in elts:
                    attr = self._access_root(e, mi.self_names)
                    if attr is not None:
                        out.append((attr, sub.lineno))
        return out

    @staticmethod
    def _access_root(node: ast.AST, self_names: Set[str]) -> Optional[str]:
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            parent = node.value
            if (isinstance(node, ast.Attribute)
                    and isinstance(parent, ast.Name)
                    and parent.id in self_names):
                return node.attr
            node = parent
        return None

    def accesses(self, mi: _MethodInfo) -> List[Tuple[str, int, ast.AST,
                                                      List[ast.AST]]]:
        """Every (attr, line, node, with_stack) touch of ``self.<attr>``
        in the method, with the lexical ``with`` ancestry."""
        out: List[Tuple[str, int, ast.AST, List[ast.AST]]] = []

        def visit(node: ast.AST, withs: List[ast.AST]) -> None:
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in mi.self_names):
                out.append((node.attr, node.lineno, node, list(withs)))
            if isinstance(node, ast.With):
                for item in node.items:
                    visit(item.context_expr, withs)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, withs)
                inner = withs + [node]
                for child in node.body:
                    visit(child, inner)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, withs)

        visit(mi.node, [])
        return out

    # -- annotations -------------------------------------------------------
    def guarded_by(self) -> Dict[str, Tuple[str, int]]:
        """attr -> (lockname, annotation line) from ``# guarded_by:``
        comments on assignment lines inside the class body."""
        out: Dict[str, Tuple[str, int]] = {}
        start = self.node.lineno
        end = self.node.end_lineno or start
        for n in range(start, min(end, len(self.mod.lines)) + 1):
            line = self.mod.lines[n - 1]
            m = _GUARDED_RE.search(line)
            if m:
                out[m.group(1)] = (m.group(2), n)
        return out


def _with_holds(withs: List[ast.AST], lock: str,
                self_names: Set[str]) -> bool:
    """True when some enclosing ``with`` acquires ``self.<lock>`` (or a
    bare ``<lock>`` for module-level locks)."""
    for w in withs:
        for item in w.items:
            e = item.context_expr
            # with self._lock:  /  with LOCK:
            if (isinstance(e, ast.Attribute) and e.attr == lock
                    and isinstance(e.value, ast.Name)
                    and e.value.id in self_names):
                return True
            if isinstance(e, ast.Name) and e.id == lock:
                return True
            # with self._lock: wrapped — e.g. contextlib.nullcontext(..)
            # does NOT count; only the lock itself.
    return False


@register
class LockDisciplinePass(LintPass):
    name = "locks"
    noqa = ()
    description = ("unannotated cross-thread attribute writes + guarded "
                   "fields accessed outside their lock")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    out.extend(self._check_class(mod, node))
        return out

    def _check_class(self, mod: Module,
                     cls: ast.ClassDef) -> List[Finding]:
        model = _ClassModel(mod, cls)
        if not model.thread_roots:
            return []
        thread_ctx, main_ctx = model.contexts()
        guarded = model.guarded_by()
        findings: List[Finding] = []

        def allowed(line: int) -> bool:
            return mod.noqa_at([line], self.tokens)

        # 1) dual-context writes need an annotation
        thread_writes: Dict[str, Tuple[str, int]] = {}
        main_writes: Dict[str, Tuple[str, int]] = {}
        for name in sorted(model.methods):
            mi = model.methods[name]
            if mi.name == "__init__" and not mi.is_nested_thread_body:
                continue
            for attr, line in model.writes(mi):
                if allowed(line):
                    continue
                if name in thread_ctx:
                    thread_writes.setdefault(attr, (mi.label, line))
                if name in main_ctx:
                    main_writes.setdefault(attr, (mi.label, line))
        for attr in sorted(set(thread_writes) & set(main_writes)):
            if attr in guarded:
                continue
            tm, tline = thread_writes[attr]
            mm, mline = main_writes[attr]
            both = (f"thread context `{tm}` (line {tline}) and main "
                    f"context `{mm}` (line {mline})"
                    if tm != mm else
                    f"`{tm}` (line {tline}), which is reachable from "
                    f"both the thread body and the main thread")
            findings.append(Finding(
                mod.rel, tline, self.name, "unguarded-field",
                f"`self.{attr}` of `{cls.name}` is written from {both} "
                "with no `# guarded_by:` annotation — add the "
                "annotation + lock, or `# noqa: locks` with a reason",
                symbol=f"{cls.name}.{attr}"))

        # 2) annotated fields: every access outside __init__ must be
        # lexically under the lock
        if guarded:
            ann_lines = {line for _, line in guarded.values()}
            for name in sorted(model.methods):
                mi = model.methods[name]
                if mi.name == "__init__" and not mi.is_nested_thread_body:
                    continue
                for attr, line, _node, withs in model.accesses(mi):
                    if attr not in guarded or line in ann_lines:
                        continue
                    lock, _ = guarded[attr]
                    if _with_holds(withs, lock, mi.self_names):
                        continue
                    if allowed(line):
                        continue
                    findings.append(Finding(
                        mod.rel, line, self.name, "unlocked-access",
                        f"`self.{attr}` is `# guarded_by: {lock}` but "
                        f"this access in `{cls.name}.{mi.label}` is not "
                        f"lexically inside `with self.{lock}:` — hold "
                        "the lock, or `# noqa: locks` with a reason",
                        symbol=f"{cls.name}.{attr}:{mi.label}"))
        return findings
