"""Built-in ptlint passes — importing this package registers them all."""
from . import hygiene    # noqa: F401  bare_except / print / fsio
from . import trace_safety  # noqa: F401
from . import locks      # noqa: F401
from . import knobs      # noqa: F401

__all__ = ["hygiene", "trace_safety", "locks", "knobs"]
