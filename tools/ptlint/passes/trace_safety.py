"""Trace-safety pass (ISSUE 12 tentpole, pass 1).

``jax.jit`` runs the Python body once per trace signature and bakes the
result into XLA.  Host-impure code inside that body is therefore a
silent hazard class of its own: a ``time.time()`` becomes a constant
frozen at trace time (and a *different* constant after every retrace —
the PR 4 retrace storms turn nondeterministic), a seedless
``np.random`` draw de-synchronizes replicas (exactly the desync the
PR 11 integrity guard exists to catch at runtime), an ``os.environ``
read silently pins a knob at trace time, and ``float()/.item()`` on a
traced value either crashes or forces a device sync.

This pass finds the hazards *statically*: it resolves every jit
boundary in the package — ``jax.jit`` / ``pjit`` / ``to_static`` /
``pallas_call`` bodies, as calls or decorators (``partial(jax.jit,..)``
included) — then walks a lightweight intra-package call graph from
those roots (bare-name calls, ``self.method`` calls, calls through
intra-package import aliases, plus bare references to lexically nested
functions, which is how jax higher-order functions like
``value_and_grad(f)`` receive their callees).  ``custom_vjp`` /
``custom_jvp`` ops and their ``defvjp`` fwd/bwd registrations are also
roots — those bodies always trace under AD.  Each finding names the jit
entry point whose trace it poisons.

Known resolution boundary: dynamic Layer dispatch —
``self.network(...)`` through an instance attribute, or
``apply(..., method=...)`` — is not followed.  Impurity behind such a
call is caught only when its function is itself a jit/``defvjp`` root.

Allowlist: ``# noqa: trace`` on the offending line — for the rare
deliberate trace-time constant (document why on the same line).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..engine import Finding, LintPass, Module, Project, register

# host-impure call chains (dotted suffixes / exact chains)
_TIME_ATTRS = {"time", "time_ns", "monotonic", "monotonic_ns",
               "perf_counter", "perf_counter_ns", "process_time", "sleep"}
_DATETIME_ATTRS = {"now", "utcnow", "today", "fromtimestamp"}
_JIT_NAMES = {"jit", "pjit"}
_FSIO_MODULE = "paddle_tpu.utils.fsio"
_CONCRETIZE_CASTS = {"float", "int", "bool", "complex"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'time.time' for Attribute/Name chains, None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FuncInfo:
    """One function definition plus the scope context resolution needs."""
    node: ast.AST                       # FunctionDef / AsyncFunctionDef / Lambda
    module: Module
    name: str
    class_name: Optional[str] = None
    parent: Optional["FuncInfo"] = None  # lexically enclosing function
    nested: Dict[str, "FuncInfo"] = field(default_factory=dict)

    @property
    def params(self) -> Set[str]:
        a = self.node.args
        names = [x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)

    @property
    def qualname(self) -> str:
        if self.class_name:
            return f"{self.class_name}.{self.name}"
        return self.name


class ModuleIndex:
    """Defs, classes and intra-package imports of one module."""

    def __init__(self, mod: Module, package: Optional[str]):
        self.mod = mod
        self.top: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, Dict[str, FuncInfo]] = {}
        self.all_funcs: List[FuncInfo] = []
        # import alias -> dotted module; from-import name -> (module, attr)
        self.mod_alias: Dict[str, str] = {}
        self.from_import: Dict[str, Tuple[str, str]] = {}
        if mod.tree is None:
            return
        self._index_scope(mod.tree.body, parent=None, class_name=None)
        self._index_imports(mod.tree, package)

    def _index_scope(self, body, parent: Optional[FuncInfo],
                     class_name: Optional[str]) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(node, self.mod, node.name,
                              class_name=class_name, parent=parent)
                self.all_funcs.append(fi)
                if parent is not None:
                    parent.nested[node.name] = fi
                elif class_name is not None:
                    self.classes.setdefault(class_name, {})[node.name] = fi
                else:
                    self.top[node.name] = fi
                self._index_scope(node.body, parent=fi,
                                  class_name=class_name)
            elif isinstance(node, ast.ClassDef):
                self.classes.setdefault(node.name, {})
                self._index_scope(node.body, parent=None,
                                  class_name=node.name)
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For,
                                   ast.While)):
                # defs under conditionals/try (TYPE_CHECKING guards,
                # version forks) still belong to this scope
                for fld in ("body", "orelse", "finalbody"):
                    self._index_scope(getattr(node, fld, []) or [],
                                      parent=parent, class_name=class_name)
                for handler in getattr(node, "handlers", []) or []:
                    self._index_scope(handler.body, parent=parent,
                                      class_name=class_name)

    def _index_imports(self, tree: ast.Module,
                       package: Optional[str]) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.mod_alias[alias.asname
                                   or alias.name.split(".")[0]] = \
                        alias.name if alias.asname else alias.name.split(
                            ".")[0]
                    if alias.asname:
                        self.mod_alias[alias.asname] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    if package is None:
                        continue
                    parts = package.split(".")
                    if node.level > len(parts):
                        continue
                    parts = parts[:len(parts) - node.level + 1]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for alias in node.names:
                    local = alias.asname or alias.name
                    # could be a submodule or a function — record both
                    # interpretations; resolution tries each
                    self.from_import[local] = (base, alias.name)


class _CallGraph:
    """Project-wide lazy resolution over per-module indexes."""

    def __init__(self, project: Project):
        self.project = project
        self.indexes: Dict[str, ModuleIndex] = {}
        for mod in project.modules:
            pkg = None
            if mod.dotted:
                pkg = (mod.dotted if mod.rel.endswith("__init__.py")
                       else ".".join(mod.dotted.split(".")[:-1]) or None)
            self.indexes[mod.rel] = ModuleIndex(mod, pkg)

    def index(self, mod: Module) -> ModuleIndex:
        return self.indexes[mod.rel]

    def resolve_module(self, dotted: str) -> Optional[ModuleIndex]:
        m = self.project.resolve(dotted)
        if m is None:
            m = self.project.resolve(dotted + ".__init__")
        return self.indexes.get(m.rel) if m is not None else None

    # -- name resolution ---------------------------------------------------
    def resolve_name(self, idx: ModuleIndex, fi: Optional[FuncInfo],
                     name: str) -> Optional[FuncInfo]:
        # nested defs of the current function, then lexical ancestors
        cur = fi
        while cur is not None:
            if name in cur.nested:
                return cur.nested[name]
            cur = cur.parent
        # sibling methods when inside a class body resolve via self.*,
        # not bare names — skip straight to module scope
        if name in idx.top:
            return idx.top[name]
        hit = idx.from_import.get(name)
        if hit:
            base, attr = hit
            target = self.resolve_module(base)
            if target is not None and attr in target.top:
                return target.top[attr]
        return None

    def resolve_attr_call(self, idx: ModuleIndex, fi: Optional[FuncInfo],
                          node: ast.Attribute) -> Optional[FuncInfo]:
        # self.method() → method of the enclosing class
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and fi is not None and fi.class_name):
            methods = idx.classes.get(fi.class_name, {})
            return methods.get(node.attr)
        # alias.func() through an intra-package import
        chain = _dotted(node)
        if chain is None:
            return None
        head, _, tail = chain.rpartition(".")
        if not head:
            return None
        # `from .. import ops` → from_import maps the alias to a module
        root = head.split(".")[0]
        dotted_mod = None
        if root in idx.mod_alias and idx.mod_alias[root].startswith(
                "paddle_tpu"):
            dotted_mod = idx.mod_alias[root] + head[len(root):]
        elif root in idx.from_import:
            base, attr = idx.from_import[root]
            dotted_mod = (f"{base}.{attr}" if base else attr) \
                + head[len(root):]
        if dotted_mod is None:
            return None
        target = self.resolve_module(dotted_mod)
        if target is not None:
            return target.top.get(tail)
        return None


# ---------------------------------------------------------------------------
# jit-boundary discovery
# ---------------------------------------------------------------------------
def _is_jit_callable(node: ast.AST) -> Optional[str]:
    """'jax.jit' / 'to_static' / 'pallas_call' … when ``node`` is a jit
    wrapper reference, else None."""
    chain = _dotted(node)
    if chain is None:
        return None
    last = chain.split(".")[-1]
    if last in _JIT_NAMES or last == "to_static":
        return chain
    if last == "pallas_call":
        return chain
    # custom_vjp/jvp-decorated bodies are traced whenever the op is used
    # under a jax transform — a jit boundary in their own right
    if last in ("custom_vjp", "custom_jvp"):
        return chain
    return None


def _jit_arg(call: ast.Call) -> Optional[ast.AST]:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg in ("fun", "function", "kernel"):
            return kw.value
    return None


def _decorator_jit_kind(dec: ast.AST) -> Optional[str]:
    kind = _is_jit_callable(dec)
    if kind:
        return kind
    # @partial(jax.jit, static_argnums=...) / @functools.partial(jit, ..)
    if isinstance(dec, ast.Call):
        chain = _dotted(dec.func)
        if chain and chain.split(".")[-1] == "partial" and dec.args:
            return _is_jit_callable(dec.args[0])
        # @jax.jit(...)-style configured decorator
        return _is_jit_callable(dec.func)
    return None


@register
class TraceSafetyPass(LintPass):
    name = "trace"
    noqa = ("trace_safety",)
    description = ("host-impure calls / concretization / global mutation "
                   "reachable from a jit boundary")

    def run(self, project: Project) -> List[Finding]:
        graph = _CallGraph(project)
        roots: List[Tuple[FuncInfo, str]] = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            idx = graph.index(mod)
            roots.extend(self._find_roots(idx, graph))
        findings: List[Finding] = []
        # BFS over the call graph; first entry label to reach a function
        # owns its findings (stable + deterministic: roots are in file
        # order, traversal breadth-first)
        seen: Set[int] = set()
        queue: List[Tuple[FuncInfo, str]] = list(roots)
        while queue:
            fi, entry = queue.pop(0)
            if id(fi.node) in seen:
                continue
            seen.add(id(fi.node))
            findings.extend(self._check_function(fi, entry, graph))
            for callee in self._callees(fi, graph):
                if id(callee.node) not in seen:
                    queue.append((callee, entry))
        return findings

    # -- roots -------------------------------------------------------------
    def _find_roots(self, idx: ModuleIndex,
                    graph: _CallGraph) -> List[Tuple[FuncInfo, str]]:
        roots: List[Tuple[FuncInfo, str]] = []
        mod = idx.mod

        def entry_label(kind: str, fi: FuncInfo) -> str:
            return f"{kind}({mod.rel}::{fi.qualname})"

        # decorator form
        for fi in idx.all_funcs:
            for dec in getattr(fi.node, "decorator_list", []):
                kind = _decorator_jit_kind(dec)
                if kind:
                    roots.append((fi, entry_label(kind, fi)))
        # call form: jax.jit(f) / pl.pallas_call(kernel, ...)
        enclosing = self._enclosing_map(idx)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # op.defvjp(fwd, bwd): both bodies trace under AD
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "defvjp"):
                fi_scope = enclosing.get(id(node))
                for arg in node.args:
                    t = None
                    if isinstance(arg, ast.Name):
                        t = graph.resolve_name(idx, fi_scope, arg.id)
                    elif isinstance(arg, ast.Attribute):
                        t = graph.resolve_attr_call(idx, fi_scope, arg)
                    if t is not None:
                        roots.append((
                            t, entry_label(_dotted(node.func) or "defvjp",
                                           t)))
                continue
            kind = _is_jit_callable(node.func)
            if not kind:
                continue
            arg = _jit_arg(node)
            if arg is None:
                continue
            fi_scope = enclosing.get(id(node))
            target: Optional[FuncInfo] = None
            if isinstance(arg, ast.Call):
                # pallas_call(functools.partial(kernel, ...), ...) — the
                # idiomatic way kernels receive compile-time config
                inner_chain = _dotted(arg.func)
                if (inner_chain
                        and inner_chain.split(".")[-1] == "partial"
                        and arg.args):
                    arg = arg.args[0]
            if isinstance(arg, ast.Name):
                target = graph.resolve_name(idx, fi_scope, arg.id)
            elif isinstance(arg, ast.Lambda):
                target = FuncInfo(arg, mod,
                                  f"<lambda:{arg.lineno}>",
                                  parent=fi_scope)
            elif isinstance(arg, ast.Attribute):
                target = graph.resolve_attr_call(idx, fi_scope, arg)
            if target is not None:
                roots.append((target, entry_label(kind, target)))
        return roots

    def _enclosing_map(self, idx: ModuleIndex) -> Dict[int, FuncInfo]:
        """node id -> the FuncInfo whose body lexically contains it."""
        out: Dict[int, FuncInfo] = {}
        for fi in idx.all_funcs:
            for sub in ast.walk(fi.node):
                out.setdefault(id(sub), fi)
        return out

    # -- traversal ---------------------------------------------------------
    def _body_nodes(self, fi: FuncInfo):
        """Walk the function body, excluding nested function/class bodies
        (those are separate call-graph nodes)."""
        stack = list(ast.iter_child_nodes(fi.node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _callees(self, fi: FuncInfo,
                 graph: _CallGraph) -> List[FuncInfo]:
        idx = graph.index(fi.module)
        out: List[FuncInfo] = []
        for node in self._body_nodes(fi):
            if isinstance(node, ast.Call):
                target = None
                if isinstance(node.func, ast.Name):
                    target = graph.resolve_name(idx, fi, node.func.id)
                elif isinstance(node.func, ast.Attribute):
                    target = graph.resolve_attr_call(idx, fi, node.func)
                if target is not None:
                    out.append(target)
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                # bare reference to a nested def — how jax higher-order
                # fns (value_and_grad, scan, vmap) receive their callees
                cur: Optional[FuncInfo] = fi
                while cur is not None:
                    if node.id in cur.nested:
                        out.append(cur.nested[node.id])
                        break
                    cur = cur.parent
        return out

    # -- impurity checks ---------------------------------------------------
    def _check_function(self, fi: FuncInfo, entry: str,
                        graph: _CallGraph) -> List[Finding]:
        mod = fi.module
        idx = graph.index(mod)
        out: List[Finding] = []
        params = fi.params
        global_names: Set[str] = set()
        for node in self._body_nodes(fi):
            if isinstance(node, ast.Global):
                global_names.update(node.names)

        def emit(node, code, what, severity="error"):
            if mod.noqa_at(mod.node_lines(node), self.tokens):
                return
            out.append(Finding(
                mod.rel, node.lineno, self.name, code,
                f"{what} inside `{fi.qualname}` — poisons the trace of "
                f"jit entry {entry}",
                symbol=f"{fi.qualname}:{code}:{what}",
                severity=severity))

        for node in self._body_nodes(fi):
            if isinstance(node, ast.Call):
                self._check_call(node, fi, idx, params, emit)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in global_names:
                        emit(node, "global-mutation",
                             f"mutation of module global `{t.id}`")
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, ast.Load):
                chain = _dotted(node.value)
                if chain == "os.environ":
                    emit(node, "impure-call",
                         "`os.environ[...]` read (env pinned at trace "
                         "time, differs across retraces)")
        return out

    def _check_call(self, node: ast.Call, fi: FuncInfo,
                    idx: ModuleIndex, params: Set[str], emit) -> None:
        # bare-name calls first: _dotted() returns the plain name for
        # these too, so they must not fall into the chain logic
        if isinstance(node.func, ast.Name):
            name = node.func.id
            if name == "print":
                emit(node, "impure-call",
                     "`print()` host side effect (fires at trace time "
                     "only; use jax.debug.print)")
                return
            if name == "open":
                emit(node, "impure-call",
                     "`open()` file I/O inside a traced function")
                return
            if (name in _CONCRETIZE_CASTS and len(node.args) == 1
                    and self._param_rooted(node.args[0], params)):
                emit(node, "concretize",
                     f"`{name}()` on likely-traced "
                     f"`{_describe(node.args[0])}` (concretizes a "
                     "tracer)", severity="warning")
            return
        # `.item()` with an impure chain root (x.mean().item()): no
        # dotted chain, but the concretization is the same
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "item" and not node.args
                and _dotted(node.func) is None):
            emit(node, "concretize",
                 "`.item()` (forces a device sync / concretizes a "
                 "tracer)", severity="warning")
            return
        chain = _dotted(node.func)
        if chain:
            parts = chain.split(".")
            root, last = parts[0], parts[-1]
            if root == "time" and last in _TIME_ATTRS:
                emit(node, "impure-call",
                     f"`{chain}()` wall-clock read (frozen at trace "
                     "time)")
                return
            if "datetime" in parts[:-1] or root == "datetime":
                if last in _DATETIME_ATTRS:
                    emit(node, "impure-call",
                         f"`{chain}()` wall-clock read (frozen at trace "
                         "time)")
                    return
            if root == "random":
                emit(node, "impure-call",
                     f"`{chain}()` stdlib RNG draw without an explicit "
                     "key (replicas desynchronize)")
                return
            if (root in ("np", "numpy") and len(parts) >= 3
                    and parts[1] == "random"):
                emit(node, "impure-call",
                     f"`{chain}()` seedless host RNG draw (replicas "
                     "desynchronize; use jax.random with an explicit "
                     "key)")
                return
            if chain in ("os.environ.get", "os.getenv"):
                emit(node, "impure-call",
                     f"`{chain}()` env read (knob pinned at trace time, "
                     "differs across retraces)")
                return
            resolved_fsio = (
                root in idx.mod_alias
                and idx.mod_alias[root] == _FSIO_MODULE) or (
                root in idx.from_import
                and idx.from_import[root][0] == _FSIO_MODULE) or (
                root in idx.from_import
                and f"{idx.from_import[root][0]}."
                    f"{idx.from_import[root][1]}" == _FSIO_MODULE)
            if resolved_fsio:
                emit(node, "impure-call",
                     f"`{chain}()` file I/O inside a traced function")
                return
            if last == "item" and len(parts) >= 2 and not node.args:
                emit(node, "concretize",
                     f"`.item()` on `{'.'.join(parts[:-1])}` "
                     "(forces a device sync / concretizes a tracer)",
                     severity="warning")
                return
            if (root in ("np", "numpy") and last in ("asarray", "array")
                    and node.args and self._param_rooted(node.args[0],
                                                         params)):
                emit(node, "concretize",
                     f"`{chain}()` on a likely-traced argument "
                     "(concretizes a tracer)", severity="warning")
                return

    @staticmethod
    def _param_rooted(node: ast.AST, params: Set[str]) -> bool:
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return isinstance(node, ast.Name) and node.id in params


def _describe(node: ast.AST) -> str:
    d = _dotted(node)
    if d:
        return d
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else "<expr>"
