"""Env-knob inventory sub-pass (ISSUE 12 satellite).

Every ``PTPU_*`` environment variable the package reads is an operator
interface, and docs/ARCHITECTURE.md is its inventory — the knob tables
there are what someone debugging a run at 3am greps.  PR 9 and PR 11
both added knobs (elastic resize, fault-injection hooks) without adding
table rows; this pass makes that drift a finding: any ``PTPU_*`` string
literal in the package that does not appear (as a whole word) in the
docs fails.  Knobs that are deliberately undocumented — internal
test-only hooks — carry ``# noqa: knobs`` with a reason.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..engine import Finding, LintPass, Project, register

_KNOB_RE = re.compile(r"^PTPU_[A-Z0-9_]+$")


@register
class KnobInventoryPass(LintPass):
    name = "knobs"
    noqa = ()
    description = ("PTPU_* environment knobs missing from the "
                   "docs/ARCHITECTURE.md inventory tables")

    def run(self, project: Project) -> List[Finding]:
        docs = project.docs_text
        # first un-noqa'd site per knob name; one finding per knob
        sites: Dict[str, Tuple[str, int]] = {}
        for mod in project.modules:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and _KNOB_RE.match(node.value)):
                    continue
                if mod.noqa_at([node.lineno], self.tokens):
                    continue
                sites.setdefault(node.value, (mod.rel, node.lineno))
        out: List[Finding] = []
        for knob in sorted(sites):
            # whole-word: PTPU_ELASTIC must not ride on PTPU_ELASTIC_MIN
            if re.search(rf"\b{re.escape(knob)}\b", docs):
                continue
            rel, line = sites[knob]
            out.append(Finding(
                rel, line, self.name, "undocumented-knob",
                f"env knob `{knob}` is read here but has no row in the "
                "docs/ARCHITECTURE.md knob tables — document it, or mark "
                "an internal hook `# noqa: knobs` with a reason",
                symbol=knob))
        return out
