"""ptlint — the repo's unified static-analysis engine (ISSUE 12).

One AST parse per file, a pluggable pass registry, structured findings,
a shared ``# noqa:`` allowlist and a checked-in baseline.  Run it as
``python -m tools.ptlint --all`` from the repo root; see
docs/ARCHITECTURE.md "Static analysis" for the pass table and the
annotation grammar.
"""
from .engine import (DEFAULT_BASELINE, Finding, LintPass, Module, Project,
                     all_passes, get_pass, load_baseline, new_findings,
                     register, run_passes, write_baseline)

__all__ = ["Finding", "Module", "Project", "LintPass", "register",
           "all_passes", "get_pass", "run_passes", "load_baseline",
           "write_baseline", "new_findings", "DEFAULT_BASELINE"]
