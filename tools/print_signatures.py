"""Public-API signature inventory (component E10).

Reference: tools/print_signatures.py + paddle/fluid/API.spec — CI hashes
every public signature and diffs against the committed spec so API breaks
are explicit, reviewed events (tools/check_api_compatible.py).

Usage:
  python tools/print_signatures.py            # print current spec
  python tools/print_signatures.py --update   # rewrite API.spec

tests/test_api_spec.py diffs the live spec against the committed file.
"""
from __future__ import annotations

import argparse
import importlib
import inspect
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
SPEC_PATH = os.path.join(ROOT, "API.spec")

# the public surface: (module, recurse-into-classes)
_MODULES = [
    "paddle_tpu",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.nn.initializer",
    "paddle_tpu.optimizer",
    "paddle_tpu.optimizer.lr",
    "paddle_tpu.amp",
    "paddle_tpu.autograd",
    "paddle_tpu.io",
    "paddle_tpu.linalg",
    "paddle_tpu.metric",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.distributed.comm",
    "paddle_tpu.distributed.elastic",
    "paddle_tpu.distributed.auto_parallel",
    "paddle_tpu.vision.models",
    "paddle_tpu.vision.ops",
    "paddle_tpu.models",
    "paddle_tpu.ops",
    "paddle_tpu.hapi",
    "paddle_tpu.profiler",
    "paddle_tpu.quantization",
    "paddle_tpu.jit",
    "paddle_tpu.inference",
    "paddle_tpu.static",
    "paddle_tpu.sparse",
    "paddle_tpu.fft",
    "paddle_tpu.signal",
    "paddle_tpu.reader",
    "paddle_tpu.callbacks",
    "paddle_tpu.sysconfig",
    "paddle_tpu.hub",
    "paddle_tpu.distribution",
    "paddle_tpu.device",
    "paddle_tpu.text",
    "paddle_tpu.cost_model",
    "paddle_tpu.onnx",
    "paddle_tpu.incubate",
    "paddle_tpu.regularizer",
    "paddle_tpu.utils",
    "paddle_tpu.supervisor",
    "paddle_tpu.observability",
]


def _sig(obj) -> str:
    import re
    try:
        s = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # object reprs carry memory addresses — strip for determinism
    return re.sub(r" at 0x[0-9a-f]+", "", s)


def collect() -> list[str]:
    # the virtual CPU mesh keeps collection deterministic and TPU-free
    from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh
    force_virtual_cpu_mesh(1)
    lines = []
    for modname in _MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        def _local(n):
            # no __all__: cross-package re-exports (nn.ClipGrad*, the
            # top-level tensor surface) ARE the public API; only
            # framework-internal helpers (infermeta combinators, enforce,
            # error classes) leaking via imports are excluded
            src = getattr(vars(mod)[n], "__module__", None) or ""
            return not (src.startswith("paddle_tpu.framework")
                        and not modname.startswith("paddle_tpu.framework")
                        # the top level re-exports framework symbols on
                        # purpose (paddle.save/load/seed/...)
                        and modname != "paddle_tpu")

        names = getattr(mod, "__all__", None) or [
            n for n in vars(mod) if not n.startswith("_") and _local(n)]
        for name in sorted(set(names)):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                lines.append(f"{modname}.{name} class{_sig(obj)}")
                for mname, m in sorted(vars(obj).items()):
                    if mname.startswith("_") and mname != "__init__":
                        continue
                    if callable(m):
                        lines.append(
                            f"{modname}.{name}.{mname} {_sig(m)}")
            elif callable(obj):
                lines.append(f"{modname}.{name} {_sig(obj)}")
    return lines


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--update", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if the live API drifted from API.spec")
    args = ap.parse_args()
    spec = "\n".join(collect()) + "\n"
    if args.update:
        with open(SPEC_PATH, "w") as f:
            f.write(spec)
        print(f"wrote {SPEC_PATH} ({spec.count(chr(10))} entries)")
        return 0
    if args.check:
        with open(SPEC_PATH) as f:
            want = f.read()
        if spec != want:
            live = set(spec.splitlines())
            saved = set(want.splitlines())
            for line in sorted(live - saved)[:10]:
                print(f"+ {line}")
            for line in sorted(saved - live)[:10]:
                print(f"- {line}")
            print("API drifted from API.spec — run --update and commit")
            return 1
        print(f"API.spec up to date ({spec.count(chr(10))} entries)")
        return 0
    sys.stdout.write(spec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
