"""Host-side tokenizer throughput benchmark: native C core vs python
oracle (paddle_tpu/text/tokenizer.py; the faster_tokenizer analog).

Unlike the device benches in bench.py, CPU numbers are the CORRECT kind
of evidence here — tokenization is host-side work in both the reference
and this framework — so this tool records benchmarks/tokenizer_host.json
directly, labelled host_side.

Run: python tools/bench_tokenizer.py
"""
import json
import os
import pathlib
import sys
import time

import numpy as np

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def _make_vocab(n_words=8000, seed=0):
    """BERT-shaped vocab: specials, whole words, ##-continuations."""
    R = np.random.RandomState(seed)
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    alphabet = "abcdefghijklmnopqrstuvwxyz"
    seen = set(vocab)
    while len(vocab) < n_words:
        w = "".join(R.choice(list(alphabet), R.randint(2, 9)))
        for cand in (w, "##" + w[:max(1, len(w) // 2)]):
            if cand not in seen:
                seen.add(cand)
                vocab.append(cand)
    return vocab[:n_words]


def _make_text(vocab, n_words=200_000, seed=1):
    R = np.random.RandomState(seed)
    words = [v for v in vocab if not v.startswith("##") and v[0] != "["]
    # half in-vocab words, half random (exercises the UNK/continuation path)
    alphabet = "abcdefghijklmnopqrstuvwxyz"
    out = []
    for _ in range(n_words):
        if R.rand() < 0.5:
            out.append(words[R.randint(len(words))])
        else:
            out.append("".join(R.choice(list(alphabet), R.randint(2, 12))))
    return " ".join(out)


def _time_encode(tok, text, repeats=3):
    best = float("inf")
    ids = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        ids = tok.encode(text)
        best = min(best, time.perf_counter() - t0)
    return best, ids


def main():
    from paddle_tpu.text.tokenizer import WordPieceTokenizer

    vocab = _make_vocab()
    text = _make_text(vocab)
    n_bytes = len(text.encode("utf-8"))

    native = WordPieceTokenizer(vocab, use_native=True)
    python = WordPieceTokenizer(vocab, use_native=False)

    t_native, ids_n = _time_encode(native, text)
    t_python, ids_p = _time_encode(python, text)
    assert list(ids_n) == list(ids_p), "native/python parity violated"

    row = {
        "host_side": True,
        "corpus_mb": n_bytes / 1e6,
        "tokens": len(ids_n),
        "native_mb_per_s": n_bytes / 1e6 / t_native,
        "python_mb_per_s": n_bytes / 1e6 / t_python,
        "speedup_native_over_python": t_python / t_native,
        "_meta": {"recorded_unix": time.time(),
                  "note": "host-side component; CPU is the right platform"},
    }
    out = ROOT / "benchmarks" / "tokenizer_host.json"
    out.parent.mkdir(exist_ok=True)
    out.write_text(json.dumps(row, indent=2))
    print(f"[tokenizer] {n_bytes / 1e6:.1f}MB corpus, {len(ids_n)} tokens: "
          f"native {row['native_mb_per_s']:.1f}MB/s vs python "
          f"{row['python_mb_per_s']:.1f}MB/s "
          f"({row['speedup_native_over_python']:.1f}x)", file=sys.stderr)
    print(json.dumps({k: v for k, v in row.items() if k != "_meta"}))


if __name__ == "__main__":
    main()
