#!/usr/bin/env bash
# One-shot hardware-evidence run (VERDICT r4 #1): execute the moment the
# TPU tunnel is reachable.  Produces:
#   - tests/test_tpu_hw.py results (Mosaic lowering incl. round-5 paths)
#   - BENCH_hw_r05.json (raw bench stdout+stderr)
#   - benchmarks/flash_ab.json, benchmarks/flash_block_sweep.json
#   - the measured 1.3B full step + slice estimate in the bench stderr
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1

echo "== probing TPU =="
if ! timeout 180 python -c "import jax; assert jax.devices()[0].platform == 'tpu'"; then
    echo "TPU unreachable; aborting" >&2
    exit 1
fi

echo "== hardware kernel tests =="
python -m pytest tests/test_tpu_hw.py -q 2>&1 | tail -5
test_status=${PIPESTATUS[0]}

echo "== bench (headline + A/B + sweep + 1.3B measured) =="
python bench.py >BENCH_hw_r05.stdout.json 2>BENCH_hw_r05.stderr.log
bench_status=$?
python - <<'EOF'
import json
out = open("BENCH_hw_r05.stdout.json").read().strip()
err = open("BENCH_hw_r05.stderr.log").read()
try:
    headline = json.loads(out.splitlines()[-1]) if out else None
except Exception as e:   # truncated stdout must still leave an artifact
    headline = {"parse_error": repr(e), "raw": out.splitlines()[-3:]}
json.dump({"stdout": headline, "stderr_diagnostics": err.splitlines()},
          open("BENCH_hw_r05.json", "w"), indent=2)
print("wrote BENCH_hw_r05.json")
print(out)
EOF
exit $(( test_status || bench_status ))
