# makes tools/ importable so `python -m tools.ptlint` resolves from the
# repo root (the lint shims exec it that way)
