#!/usr/bin/env python
"""Reject bare ``except:`` clauses in paddle_tpu/ (resilience hygiene).

A bare except swallows KeyboardInterrupt/SystemExit and — worse for the
fault-tolerance layer — silently eats the SIGTERM-driven control flow and
corruption errors the restore fallback chain depends on seeing.  Every
handler must name what it catches (``except Exception:`` at minimum).

Usage: ``python tools/lint_bare_except.py [root ...]`` (default:
``paddle_tpu/``).  Exits 1 listing ``file:line`` for every violation.
"""
from __future__ import annotations

import ast
import os
import sys


def find_bare_excepts(path: str):
    with open(path, "rb") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [(getattr(e, "lineno", 0) or 0, f"syntax error: {e.msg}")]
    return [(node.lineno, "bare except") for node in ast.walk(tree)
            if isinstance(node, ast.ExceptHandler) and node.type is None]


def main(argv):
    roots = argv or [os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "paddle_tpu")]
    violations = []
    checked = 0
    for root in roots:
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                checked += 1
                for lineno, what in find_bare_excepts(full):
                    violations.append(f"{os.path.relpath(full)}:{lineno}: "
                                      f"{what}")
    if violations:
        print("\n".join(violations))
        print(f"\n{len(violations)} bare except clause(s) found — name the "
              "exception (at minimum `except Exception:`)")
        return 1
    print(f"bare-except lint: {checked} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
