#!/usr/bin/env python
"""Reject bare ``except:`` clauses — and silent ``except Exception: pass``
swallowing — in paddle_tpu/ (resilience hygiene).

A bare except swallows KeyboardInterrupt/SystemExit and — worse for the
fault-tolerance layer — silently eats the SIGTERM-driven control flow and
corruption errors the restore fallback chain depends on seeing.  Every
handler must name what it catches (``except Exception:`` at minimum).

An ``except Exception: pass`` (or ``except BaseException: pass``) names
what it catches and then discards it anyway — the run supervisor (ISSUE 2)
exists precisely because swallowed failures turn into silent hangs and
divergence.  Handlers that legitimately must swallow (finalizers,
best-effort shutdown paths) carry an explicit ``# noqa: swallow`` comment
on the ``except`` or ``pass`` line.

Usage: ``python tools/lint_bare_except.py [root ...]`` (default:
``paddle_tpu/``).  Exits 1 listing ``file:line`` for every violation.
"""
from __future__ import annotations

import ast
import os
import sys

_NOQA = "# noqa: swallow"
_BROAD = {"Exception", "BaseException"}


def _is_swallow(node: ast.ExceptHandler) -> bool:
    """True for ``except Exception/BaseException [as e]: pass``."""
    if not (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)):
        return False
    t = node.type
    return (t is None or (isinstance(t, ast.Name) and t.id in _BROAD)
            or (isinstance(t, ast.Attribute) and t.attr in _BROAD))


def find_violations(path: str):
    with open(path, "rb") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [(getattr(e, "lineno", 0) or 0, f"syntax error: {e.msg}")]
    lines = source.decode("utf-8", errors="replace").splitlines()

    def allowlisted(node: ast.ExceptHandler) -> bool:
        check = {node.lineno, node.body[0].lineno if node.body else 0}
        return any(_NOQA in lines[n - 1] for n in check
                   if 0 < n <= len(lines))

    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append((node.lineno, "bare except"))
        elif _is_swallow(node) and not allowlisted(node):
            out.append((node.lineno,
                        "swallowed exception (`except Exception: pass`) — "
                        "handle it, narrow it, or mark `# noqa: swallow`"))
    return out


# back-compat alias (pre-ISSUE-2 name)
find_bare_excepts = find_violations


def main(argv):
    roots = argv or [os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "paddle_tpu")]
    violations = []
    checked = 0
    for root in roots:
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                checked += 1
                for lineno, what in find_violations(full):
                    violations.append(f"{os.path.relpath(full)}:{lineno}: "
                                      f"{what}")
    if violations:
        print("\n".join(violations))
        print(f"\n{len(violations)} violation(s) found — name the "
              "exception (at minimum `except Exception:`) and don't "
              "swallow it silently")
        return 1
    print(f"bare-except/swallow lint: {checked} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
