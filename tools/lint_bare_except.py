#!/usr/bin/env python
"""Deprecated shim — this lint is now the ptlint ``bare_except`` pass.

The standalone walker was absorbed into the unified engine (one shared
AST parse for every pass; see tools/ptlint/ and docs/ARCHITECTURE.md
"Static analysis").  This file stays so muscle memory and old scripts
keep working; it just re-execs

    python -m tools.ptlint --no-baseline --pass bare_except [root ...]

preserving the exit status and ``path:line: message`` output contract.
"""
import os
import sys

_PASS = "bare_except"
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    # absolute roots: the shim may be invoked from any cwd, while the
    # engine resolves relative paths against its own repo root
    roots = [os.path.abspath(r) for r in sys.argv[1:]]
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    sys.stderr.write(
        f"note: tools/{os.path.basename(__file__)} is a shim - "
        f"use `python -m tools.ptlint --pass {_PASS}`\n")
    sys.stderr.flush()
    os.execve(sys.executable,
              [sys.executable, "-m", "tools.ptlint", "--no-baseline",
               "--pass", _PASS] + roots, env)


if __name__ == "__main__":
    main()
