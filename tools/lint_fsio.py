#!/usr/bin/env python
"""Reject durable writes that bypass the ``utils/fsio`` seam (ISSUE 11).

Every durable byte in this codebase is supposed to flow through
``paddle_tpu.utils.fsio`` — ``write_bytes`` / ``atomic_write_bytes`` /
``append_bytes`` — because that seam is where fsync discipline, the
fault injector (``testing/faults.FaultInjector``) and the integrity
guard's channel guarantees all live.  A raw ``open(path, "w")`` or a
bare ``os.replace`` sidesteps all three: the write isn't fsync'd (torn
on power loss), fault drills can't see it, and the restore fallback
chain can't reason about its commit point.

Flagged:

- ``open(..., mode)`` with any write mode (``w``, ``a``, ``x`` or
  ``+``) — reads are fine;
- ``os.replace(...)`` — the atomic-rename commit step must pair with a
  directory fsync, which only ``fsio`` and the checkpoint committer do.

Deliberate bypasses (the fault injector's corruption helpers, the
checkpoint committer's own rename+fsync pair) carry an explicit
``# noqa: fsio`` comment on the offending line.  ``utils/fsio.py``
itself is exempt — it IS the seam.

Usage: ``python tools/lint_fsio.py [root ...]`` (default:
``paddle_tpu/``).  Exits 1 listing ``file:line`` for every violation.
"""
from __future__ import annotations

import ast
import os
import sys

_NOQA = "# noqa: fsio"
_EXEMPT = {os.path.join("paddle_tpu", "utils", "fsio.py")}
_WRITE_CHARS = set("wax+")


def _mode_of(call: ast.Call):
    """The mode argument of an ``open()`` call, if literal."""
    if len(call.args) >= 2:
        arg = call.args[1]
    else:
        arg = next((kw.value for kw in call.keywords
                    if kw.arg == "mode"), None)
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def _is_write_open(node: ast.Call) -> bool:
    fn = node.func
    if not (isinstance(fn, ast.Name) and fn.id == "open"):
        return False
    mode = _mode_of(node)
    if mode is None:  # default "r", or dynamic (give it the benefit)
        return len(node.args) >= 2 or any(
            kw.arg == "mode" for kw in node.keywords)
    return bool(set(mode) & _WRITE_CHARS)


def _is_os_replace(node: ast.Call) -> bool:
    fn = node.func
    return (isinstance(fn, ast.Attribute) and fn.attr == "replace"
            and isinstance(fn.value, ast.Name) and fn.value.id == "os")


def find_violations(path: str):
    with open(path, "rb") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [(getattr(e, "lineno", 0) or 0, f"syntax error: {e.msg}")]
    lines = source.decode("utf-8", errors="replace").splitlines()

    def allowlisted(node: ast.Call) -> bool:
        span = range(node.lineno,
                     (getattr(node, "end_lineno", node.lineno)
                      or node.lineno) + 1)
        return any(_NOQA in lines[n - 1] for n in span
                   if 0 < n <= len(lines))

    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or allowlisted(node):
            continue
        if _is_write_open(node):
            out.append((node.lineno,
                        "write-mode open() bypasses utils/fsio — use "
                        "fsio.write_bytes/atomic_write_bytes, or mark a "
                        "deliberate bypass `# noqa: fsio`"))
        elif _is_os_replace(node):
            out.append((node.lineno,
                        "bare os.replace bypasses utils/fsio's "
                        "rename+fsync discipline — use "
                        "fsio.atomic_write_bytes, or mark a deliberate "
                        "bypass `# noqa: fsio`"))
    return out


def main(argv):
    roots = argv or [os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "paddle_tpu")]
    violations = []
    checked = 0
    for root in roots:
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full)
                if any(rel.endswith(e) for e in _EXEMPT):
                    continue
                checked += 1
                for lineno, what in find_violations(full):
                    violations.append(f"{rel}:{lineno}: {what}")
    if violations:
        print("\n".join(violations))
        print(f"\n{len(violations)} violation(s) found — durable bytes "
              "flow through utils/fsio (fsync discipline + fault "
              "injection + integrity guarantees)")
        return 1
    print(f"fsio lint: {checked} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
