#!/usr/bin/env python
"""Reject bare ``print(`` calls in paddle_tpu/ (telemetry hygiene).

With the unified telemetry layer (ISSUE 3) every signal has a proper
channel: human-readable lines go through ``framework.log`` (VLOG / the
package logger), machine-readable signals through
``observability.get_registry()`` sinks.  A bare ``print`` bypasses both
— it can't be silenced, filtered, redirected per-run, or aggregated, and
on a 256-host pod it turns stdout into noise no one can parse.

Deliberate console surfaces (the paddle-parity ``Model.summary`` /
``flops`` pretty-printers, ``ProgBarLogger``, ``version`` / ``run_check``
CLIs) carry an explicit ``# noqa: print`` on the call line.

Only plain-name ``print(...)`` calls are flagged — attribute calls like
``jax.debug.print`` are a different (traced) mechanism.

Usage: ``python tools/lint_print.py [root ...]`` (default:
``paddle_tpu/``).  Exits 1 listing ``file:line`` for every violation.
"""
from __future__ import annotations

import ast
import os
import sys

_NOQA = "# noqa: print"


def find_violations(path: str):
    with open(path, "rb") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [(getattr(e, "lineno", 0) or 0, f"syntax error: {e.msg}")]
    lines = source.decode("utf-8", errors="replace").splitlines()

    def allowlisted(node: ast.Call) -> bool:
        n = node.lineno
        return 0 < n <= len(lines) and _NOQA in lines[n - 1]

    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and not allowlisted(node)):
            out.append((node.lineno,
                        "bare print() — route through framework.log / an "
                        "observability sink, or mark a deliberate console "
                        "surface with `# noqa: print`"))
    return out


def main(argv):
    roots = argv or [os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "paddle_tpu")]
    violations = []
    checked = 0
    for root in roots:
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                checked += 1
                for lineno, what in find_violations(full):
                    violations.append(f"{os.path.relpath(full)}:{lineno}: "
                                      f"{what}")
    if violations:
        print("\n".join(violations))
        print(f"\n{len(violations)} violation(s) found — output belongs "
              "in framework.log or an observability sink")
        return 1
    print(f"print lint: {checked} files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
