"""Module-level __all__ parity against the reference package: every public
name the reference exports from its major modules must exist here (the
test-suite rendering of SURVEY C13's compat contract)."""
import ast
import importlib
import os

import pytest

R = "/root/reference/python/paddle/"

PAIRS = [
    ("paddle", R + "__init__.py", "paddle_tpu"),
    ("nn", R + "nn/__init__.py", "paddle_tpu.nn"),
    ("nn.functional", R + "nn/functional/__init__.py",
     "paddle_tpu.nn.functional"),
    ("nn.initializer", R + "nn/initializer/__init__.py",
     "paddle_tpu.nn.initializer"),
    ("optimizer", R + "optimizer/__init__.py", "paddle_tpu.optimizer"),
    ("optimizer.lr", R + "optimizer/lr.py", "paddle_tpu.optimizer.lr"),
    ("metric", R + "metric/__init__.py", "paddle_tpu.metric"),
    ("distribution", R + "distribution/__init__.py",
     "paddle_tpu.distribution"),
    ("linalg", R + "linalg.py", "paddle_tpu.linalg"),
    ("vision.ops", R + "vision/ops.py", "paddle_tpu.vision.ops"),
    ("vision.transforms", R + "vision/transforms/__init__.py",
     "paddle_tpu.vision.transforms"),
    ("vision", R + "vision/__init__.py", "paddle_tpu.vision"),
    ("distributed", R + "distributed/__init__.py",
     "paddle_tpu.distributed"),
    ("io", R + "io/__init__.py", "paddle_tpu.io"),
    ("amp", R + "amp/__init__.py", "paddle_tpu.amp"),
    ("jit", R + "jit/__init__.py", "paddle_tpu.jit"),
    ("static", R + "static/__init__.py", "paddle_tpu.static"),
    ("autograd", R + "autograd/__init__.py", "paddle_tpu.autograd"),
    ("utils", R + "utils/__init__.py", "paddle_tpu.utils"),
    ("sparse", R + "sparse/__init__.py", "paddle_tpu.sparse"),
    ("fft", R + "fft.py", "paddle_tpu.fft"),
    ("signal", R + "signal.py", "paddle_tpu.signal"),
    ("regularizer", R + "regularizer.py", "paddle_tpu.regularizer"),
    ("text", R + "text/__init__.py", "paddle_tpu.text"),
    ("incubate", R + "incubate/__init__.py", "paddle_tpu.incubate"),
    ("device", R + "device/__init__.py", "paddle_tpu.device"),
    ("inference", R + "inference/__init__.py", "paddle_tpu.inference"),
    ("profiler", R + "profiler/__init__.py", "paddle_tpu.profiler"),
    ("onnx", R + "onnx/__init__.py", "paddle_tpu.onnx"),
    ("fleet", R + "distributed/fleet/__init__.py",
     "paddle_tpu.distributed.fleet"),
    ("incubate.nn", R + "incubate/nn/__init__.py",
     "paddle_tpu.incubate.nn"),
    ("distribution.transform", R + "distribution/transform.py",
     "paddle_tpu.distribution"),
    ("nn.utils", R + "nn/utils/__init__.py", "paddle_tpu.nn.utils"),
    ("distributed.sharding", R + "distributed/sharding/__init__.py",
     "paddle_tpu.distributed.sharding"),
    ("distributed.utils", R + "distributed/utils.py",
     "paddle_tpu.distributed.utils"),
    ("utils.cpp_extension", R + "utils/cpp_extension/__init__.py",
     "paddle_tpu.utils.cpp_extension"),
    ("utils.unique_name", R + "utils/unique_name.py",
     "paddle_tpu.utils.unique_name"),
    ("utils.download", R + "utils/download.py",
     "paddle_tpu.utils.download"),
]


STATIC_NN_REF = R + "static/nn/__init__.py"


@pytest.mark.quick
@pytest.mark.skipif(not os.path.exists(R), reason="reference not present")
def test_static_nn_namespace_parity():
    """static.nn is a class namespace, not a module — checked apart."""
    import paddle_tpu.static as st
    names = _ref_all(STATIC_NN_REF)
    assert names
    missing = [n for n in names if not hasattr(st.nn, n)]
    assert not missing, missing


def _ref_all(path):
    tree = ast.parse(open(path).read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    return [ast.literal_eval(e) for e in node.value.elts]
    return []


@pytest.mark.quick
@pytest.mark.skipif(not os.path.exists(R), reason="reference not present")
@pytest.mark.parametrize("label,ref_path,module", PAIRS,
                         ids=[p[0] for p in PAIRS])
def test_module_all_parity(label, ref_path, module):
    names = _ref_all(ref_path)
    assert names, f"no __all__ parsed from {ref_path}"
    mod = importlib.import_module(module)
    missing = [n for n in names if not hasattr(mod, n)]
    assert not missing, f"{label}: missing {missing}"
