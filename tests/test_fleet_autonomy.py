"""Fleet-autonomy tests (ISSUE 17): crash-safe router WAL (unit +
random crash/recover property), circuit-breaker state machine, retry
budget, the flaky-replica drill, the SLO autoscaler control loop, and
the new doctor verdicts.
"""
import json
import os
import random

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.inference.fleet import (CircuitBreaker, FleetAutoscaler,
                                        JournalStore, LocalReplica,
                                        LocalReplicaManager, RetryBudget,
                                        Router, ServingSLO,
                                        default_drain_slack_secs,
                                        get_retry_budget,
                                        reset_retry_budget)
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.testing import faults

pytestmark = pytest.mark.serving


def tiny_model(max_pos=64):
    pt.seed(7)
    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=2,
                    num_heads=2, ffn_hidden_size=64,
                    max_position_embeddings=max_pos, hidden_dropout=0.0,
                    attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def dense_continuation(model, prompt, max_new, eos=None):
    out = model.generate(jnp.asarray([prompt], jnp.int32),
                         max_new_tokens=max_new, temperature=0.0,
                         eos_token_id=eos)
    return np.asarray(out)[0, len(prompt):].tolist()


def local_fleet(n=2, registry=None, max_pos=64, **engine_kw):
    reg = registry or MetricsRegistry()
    reps = [LocalReplica(ServingEngine(tiny_model(max_pos), registry=reg,
                                       replica_id=i, **engine_kw),
                         replica_id=i)
            for i in range(n)]
    return reps, reg


class CaptureSink:
    """Registry sink that keeps every emitted record (assertable)."""

    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(record)

    def flush(self):
        pass

    def close(self):
        pass


def fresh_budget(capacity=64, refill=0.0):
    return RetryBudget(capacity=capacity, refill_per_s=refill)


# ---------------------------------------------------------------------------
# JournalStore: the WAL itself
# ---------------------------------------------------------------------------
class TestJournalStore:
    def test_wal_roundtrip(self, tmp_path):
        store = JournalStore(str(tmp_path))
        store.open("r1", [1, 2, 3], 8, None, session="u1")
        store.append_tokens("r1", [4, 5])
        store.append_tokens("r1", [6])
        [rec] = store.recover()
        assert rec["request_id"] == "r1"
        assert rec["prompt"] == [1, 2, 3]
        assert rec["tokens"] == [4, 5, 6]
        assert rec["session"] == "u1"
        assert not rec["finished"]
        store.retire("r1", "length")
        assert store.live_count() == 0
        done = [n for n in os.listdir(store.directory)
                if n.endswith(".done")]
        assert len(done) == 1
        # retired streams still recover — as finished, for the client
        # that re-asks the recovered router just after completion
        [rec] = store.recover()
        assert rec["finished"] and rec["tokens"] == [4, 5, 6]

    def test_torn_tail_dropped_with_accounting(self, tmp_path):
        store = JournalStore(str(tmp_path))
        store.open("r1", [1, 2], 8, None)
        store.append_tokens("r1", [9, 9])
        with open(store._path("r1"), "ab") as f:
            f.write(b'{"kind": "tok", "t": [7')   # the torn append
        [rec] = store.recover()
        assert rec["tokens"] == [9, 9]            # complete lines only
        assert store.drops["torn_lines"] == 1

    def test_headerless_file_quarantined(self, tmp_path):
        store = JournalStore(str(tmp_path))
        store._append("ghost", {"kind": "tok", "t": [1]})
        assert store.recover() == []
        assert store.drops["corrupt_files"] == 1
        assert any(n.endswith(".corrupt")
                   for n in os.listdir(store.directory))

    def test_fin_line_survives_crash_before_rename(self, tmp_path):
        store = JournalStore(str(tmp_path))
        store.open("r1", [1], 4, None)
        store.append_tokens("r1", [2, 3])
        # crash between the fin append and the rename: simulate by
        # appending the fin line without retiring
        store._append("r1", {"kind": "fin", "reason": "length"})
        [rec] = store.recover()
        assert rec["finished"] and rec["reason"] == "length"

    def test_disp_line_names_last_replica(self, tmp_path):
        store = JournalStore(str(tmp_path))
        store.open("r1", [1], 4, None)
        store._append("r1", {"kind": "disp", "replica": 0})
        store._append("r1", {"kind": "disp", "replica": 1})
        [rec] = store.recover()
        assert rec["replica"] == 1                # last dispatch wins

    def test_gc_bounds_retired_files(self, tmp_path):
        store = JournalStore(str(tmp_path), keep=2)
        for i in range(5):
            store.open(f"r{i}", [1], 4, None)
            store.retire(f"r{i}", "length")
        done = [n for n in os.listdir(store.directory)
                if n.endswith(".done")]
        assert len(done) == 2

    def test_keep_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PTPU_FLEET_JOURNAL_KEEP", "3")
        assert JournalStore(str(tmp_path)).keep == 3

    def test_discard_removes_live_file(self, tmp_path):
        store = JournalStore(str(tmp_path))
        store.open("r1", [1], 4, None)
        store.discard("r1")
        assert store.live_count() == 0
        store.discard("r1")                       # idempotent


# ---------------------------------------------------------------------------
# router crash/recover: deterministic + property
# ---------------------------------------------------------------------------
class TestRouterRecovery:
    def test_recover_reattach_token_exact(self, tmp_path):
        model = tiny_model()
        want = {i: dense_continuation(model, [1, 2, 3 + i], 10)
                for i in range(4)}
        reps, reg = local_fleet(2, max_seqs=4, kv_block_size=4)
        router = Router(reps, registry=reg, run_dir=str(tmp_path),
                        retry_budget=fresh_budget())
        rids = [router.submit([1, 2, 3 + i], max_new_tokens=10)
                for i in range(4)]
        while any(len(router.journals[r].tokens) < 2 for r in rids):
            router.pump()
        del router                                # the "crash"
        r2 = Router(reps, registry=reg, recover=str(tmp_path),
                    retry_budget=fresh_budget())
        assert r2.recovered["streams"] == 4
        assert r2.recovered["reattached"] == 4    # replicas survived
        outs = [r2.collect(r, timeout=60) for r in rids]
        for i, out in enumerate(outs):
            assert out["tokens"] == want[i], (i, out)
        assert r2.store.live_count() == 0         # all retired
        for rep in reps:
            assert rep.engine.cache.leak_report()["leaked_blocks"] == 0

    def test_recover_redispatches_orphans(self, tmp_path):
        model = tiny_model()
        want = dense_continuation(model, [1, 2, 3], 10)
        reps, reg = local_fleet(2, max_seqs=4, kv_block_size=4)
        router = Router(reps, registry=reg, run_dir=str(tmp_path),
                        retry_budget=fresh_budget())
        rid = router.submit([1, 2, 3], max_new_tokens=10)
        while len(router.journals[rid].tokens) < 3:
            router.pump()
        victim = router.journals[rid].replica_id
        del router
        reps[victim].engine._state = "stopped"    # replica died too
        r2 = Router(reps, registry=reg, recover=str(tmp_path),
                    retry_budget=fresh_budget())
        assert r2.recovered["redispatched"] == 1
        out = r2.collect(rid, timeout=60)
        assert out["tokens"] == want              # recompute-prefill

    def test_recover_finished_stream_is_terminal(self, tmp_path):
        reps, reg = local_fleet(1, max_seqs=2, kv_block_size=4)
        router = Router(reps, registry=reg, run_dir=str(tmp_path))
        rid = router.submit([1, 2], max_new_tokens=3)
        out1 = router.collect(rid, timeout=60)
        # crash AFTER the fin append but BEFORE the rename: re-create
        # that window by re-journaling the finished stream
        store = JournalStore(str(tmp_path))
        store.open(rid, [1, 2], 3, None, tokens=out1["tokens"])
        store._append(rid, {"kind": "fin", "reason": "length"})
        r2 = Router(reps, registry=reg, recover=str(tmp_path))
        assert r2.recovered["finished"] == 1
        assert r2.collect(rid, timeout=5)["tokens"] == out1["tokens"]
        assert r2.store.live_count() == 0         # retire completed

    def test_recovered_router_accepts_new_anonymous_streams(self,
                                                            tmp_path):
        """The auto-id counter restarts at 0 after a crash but the
        recovered journals keep their fleet-N names — new submissions
        must skip past them instead of refusing as duplicates."""
        model = tiny_model()
        reps, reg = local_fleet(1, max_seqs=4, kv_block_size=4)
        router = Router(reps, registry=reg, run_dir=str(tmp_path),
                        retry_budget=fresh_budget())
        old = router.submit([1, 2, 3], max_new_tokens=6)   # fleet-0
        router.collect(old, timeout=60)
        del router
        r2 = Router(reps, registry=reg, recover=str(tmp_path),
                    retry_budget=fresh_budget())
        new = r2.submit([1, 2, 4], max_new_tokens=6)
        assert new != old
        want = dense_continuation(model, [1, 2, 4], 6)
        assert r2.collect(new, timeout=60)["tokens"] == want

    def test_shed_submission_leaves_no_ghost_journal(self, tmp_path):
        from paddle_tpu.inference.fleet import FleetOverloaded
        reps, reg = local_fleet(1, max_seqs=2, kv_block_size=4)
        router = Router(reps, registry=reg, run_dir=str(tmp_path),
                        shed_queue_depth=64,
                        retry_budget=fresh_budget())
        reps[0].engine._state = "stopped"
        with pytest.raises(FleetOverloaded):
            router.submit([1, 2], max_new_tokens=4)
        assert router.journals == {}
        assert router.store.live_count() == 0

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_wal_property_random_crash_recover(self, tmp_path, seed):
        """Random accept/crash/torn-truncate/recover interleavings:
        completions stay token-exact and allocators leak-free.  Torn
        truncation only ever shortens the accepted prefix — re-attach
        polls the replica from the journaled offset and greedy decode
        regenerates the identical tail."""
        rng = random.Random(seed)
        model = tiny_model()
        prompts = [[1, 2, 3 + i] for i in range(6)]
        want = [dense_continuation(model, p, 12) for p in prompts]
        reps, reg = local_fleet(2, max_seqs=4, kv_block_size=4)
        router = Router(reps, registry=reg, run_dir=str(tmp_path),
                        retry_budget=fresh_budget())
        rids = [router.submit(p, max_new_tokens=12) for p in prompts]
        for _round in range(rng.randint(1, 4)):
            for _ in range(rng.randint(1, 6)):
                router.pump()
            # crash the router; tear a random live journal's tail
            # (never into the header — a torn header is the separate
            # quarantine path, not the resume path)
            store = router.store
            del router
            live = [n for n in os.listdir(store.directory)
                    if n.endswith(".jsonl")]
            if live and rng.random() < 0.7:
                path = os.path.join(store.directory, rng.choice(live))
                raw = open(path, "rb").read()
                header_end = raw.index(b"\n") + 1
                if len(raw) > header_end:
                    cut = rng.randint(header_end, len(raw) - 1)
                    with open(path, "wb") as f:
                        f.write(raw[:cut])
            router = Router(reps, registry=reg, recover=str(tmp_path),
                            retry_budget=fresh_budget())
            assert router.recovered["streams"] == sum(
                1 for r in rids if r in router.journals)
        outs = [router.collect(r, timeout=120) for r in rids]
        for i, out in enumerate(outs):
            assert out["tokens"] == want[i], (seed, i, out)
        assert router.store.live_count() == 0
        for rep in reps:                          # empty leak report
            assert rep.engine.cache.leak_report()["leaked_blocks"] == 0


# ---------------------------------------------------------------------------
# circuit breaker + retry budget
# ---------------------------------------------------------------------------
class TestCircuitBreaker:
    def test_trips_at_n_failures_in_window(self):
        clk = faults.expire_clock(0.0)
        br = CircuitBreaker(failures=3, window_secs=10,
                            backoff_secs=2, clock=clk)
        br.record_failure()
        br.record_failure()
        assert br.state == "closed" and br.allow()
        br.record_failure()
        assert br.state == "open" and not br.allow()
        assert br.trips == 1

    def test_failures_age_out_of_window(self):
        clk = faults.expire_clock(0.0)
        br = CircuitBreaker(failures=3, window_secs=5,
                            backoff_secs=2, clock=clk)
        br.record_failure()
        clk.advance(6.0)                          # first failure ages out
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"

    def test_half_open_probe_success_closes(self):
        clk = faults.expire_clock(0.0)
        br = CircuitBreaker(failures=1, window_secs=10,
                            backoff_secs=2, clock=clk)
        br.record_failure()
        assert br.state == "open" and not br.allow()
        clk.advance(2.0)
        assert br.allow()                         # THE probe
        assert br.state == "half_open"
        assert not br.allow()                     # one probe at a time
        br.record_success()
        assert br.state == "closed"
        assert br.current_backoff() == 2.0        # consecutive trips reset

    def test_probe_failure_doubles_backoff_capped(self):
        clk = faults.expire_clock(0.0)
        br = CircuitBreaker(failures=1, window_secs=10,
                            backoff_secs=2, clock=clk)
        br.record_failure()
        for expect in (4.0, 8.0, 16.0, 32.0, 32.0, 32.0):
            clk.advance(br.current_backoff())
            assert br.allow()                     # half-open probe
            br.record_failure()                   # probe fails: reopen
            assert br.state == "open"
            assert br.current_backoff() == expect  # doubles, caps x16

    def test_transitions_fire_callback(self):
        seen = []
        clk = faults.expire_clock(0.0)
        br = CircuitBreaker(failures=1, window_secs=10, backoff_secs=1,
                            clock=clk,
                            on_transition=lambda p, n, _b: seen.append(
                                (p, n)))
        br.record_failure()
        clk.advance(1.0)
        br.allow()
        br.record_success()
        assert seen == [("closed", "open"), ("open", "half_open"),
                        ("half_open", "closed")]


class TestRetryBudget:
    def test_spend_and_deny(self):
        clk = faults.expire_clock(0.0)
        b = RetryBudget(capacity=3, refill_per_s=0.0, clock=clk)
        assert all(b.try_acquire() for _ in range(3))
        assert not b.try_acquire()
        assert b.spent == 3 and b.denied == 1

    def test_refill_restores_tokens(self):
        clk = faults.expire_clock(0.0)
        b = RetryBudget(capacity=2, refill_per_s=1.0, clock=clk)
        b.try_acquire(2)
        assert not b.try_acquire()
        clk.advance(1.5)
        assert b.try_acquire()                    # 1.5 tokens refilled
        assert not b.try_acquire()                # 0.5 left < 1

    def test_process_wide_singleton(self):
        reset_retry_budget()
        try:
            a = get_retry_budget()
            assert get_retry_budget() is a
            reset_retry_budget()
            assert get_retry_budget() is not a
        finally:
            reset_retry_budget()

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("PTPU_FLEET_RETRY_BUDGET", "7")
        monkeypatch.setenv("PTPU_FLEET_RETRY_REFILL_PER_S", "0.5")
        b = RetryBudget()
        assert b.capacity == 7.0 and b.refill_per_s == 0.5


# ---------------------------------------------------------------------------
# the flap drill: breaker + budget + census under flaky_replica
# ---------------------------------------------------------------------------
class TestFlapDrill:
    def test_flaky_replica_injector_restores(self):
        reps, _ = local_fleet(1, max_seqs=2, kv_block_size=4)
        with faults.flaky_replica(reps[0], error_rate=1.0,
                                  seed=0) as flake:
            assert "submit" in reps[0].__dict__   # transport wrapped
            with pytest.raises(ConnectionError, match="injected flake"):
                reps[0].submit({"request_id": "x", "prompt": [1],
                                "max_new_tokens": 1,
                                "eos_token_id": None})
            assert flake.injected_errors == 1
        assert "submit" not in reps[0].__dict__   # restored on exit
        assert reps[0].engine.sched.counts()["waiting"] == 0

    def test_breaker_opens_streams_complete_budget_bounded(self):
        model = tiny_model()
        prompts = [[1, 2, 3 + i] for i in range(6)]
        want = [dense_continuation(model, p, 10) for p in prompts]
        sink = CaptureSink()
        reps, reg = local_fleet(3, max_seqs=4, kv_block_size=4)
        reg.add_sink(sink)
        budget = RetryBudget(capacity=32, refill_per_s=0.0)
        router = Router(reps, registry=reg, retry_budget=budget,
                        breaker_kw={"failures": 3, "window_secs": 60.0,
                                    "backoff_secs": 1000.0})
        victim = 1
        with faults.flaky_replica(reps[victim], error_rate=0.3,
                                  seed=7) as flake:
            rids = [router.submit(p, max_new_tokens=10) for p in prompts]
            outs = [router.collect(r, timeout=120) for r in rids]
        for i, out in enumerate(outs):
            assert out["tokens"] == want[i], (i, out)
        assert flake.injected_errors > 0
        # the breaker opened on the flapping replica — and only it
        assert router.breakers[victim].trips >= 1
        for rid_ in (0, 2):
            assert router.breakers.get(rid_) is None \
                or router.breakers[rid_].trips == 0
        # flapping surfaced: census overlay + timeline records
        assert router.census()[victim] == "flapping"
        assert router.stats()["states"].get("flapping") == 1
        assert any(r["kind"] == "fleet.breaker"
                   and r["state"] == "open"
                   and r["replica"] == victim for r in sink.records)
        # no retry storm: every retry/failover spent the bounded budget
        assert budget.spent <= budget.capacity
        assert budget.spent == 32 - budget.available()
        # the doctor names the flapping replica from the records alone
        from paddle_tpu.observability.doctor import check_fleet_flapping
        [finding] = check_fleet_flapping({0: sink.records})
        assert finding["kind"] == "fleet_flapping"
        assert str(victim) in json.dumps(finding["data"]["trips"])

    def test_dry_budget_sheds_new_submissions(self):
        reps, reg = local_fleet(2, max_seqs=4, kv_block_size=4)
        from paddle_tpu.inference.fleet import FleetOverloaded
        router = Router(reps, registry=reg, retry_max=3,
                        retry_backoff_ms=0.0, sleep=lambda _t: None,
                        retry_budget=RetryBudget(capacity=0,
                                                 refill_per_s=0.0))
        router.dispatch_fault = faults.drop_dispatch(count=1)
        # first attempt is free; the drop forces a second send, which
        # needs a budget token — dry bucket degrades to load-shed
        with pytest.raises(FleetOverloaded, match="retry budget dry"):
            router.submit([1, 2], max_new_tokens=4)
        assert router.journals == {}

    def test_manager_census_gains_flapping_state(self):
        reg = MetricsRegistry()
        mgr = LocalReplicaManager(
            lambda i: ServingEngine(tiny_model(), registry=reg,
                                    replica_id=i, max_seqs=2,
                                    kv_block_size=4),
            replicas=2, registry=reg)
        mgr.set_flapping(1, True)
        assert mgr.poll_states()[1] == "flapping"
        snap = reg.snapshot()
        assert snap["fleet.replicas[state=flapping]"]["value"] == 1.0
        assert snap["fleet.replicas[state=healthy]"]["value"] == 1.0
        mgr.set_flapping(1, False)
        assert mgr.poll_states()[1] == "healthy"
        assert reg.snapshot()[
            "fleet.replicas[state=flapping]"]["value"] == 0.0


# ---------------------------------------------------------------------------
# autoscaler control loop (fake clock, real LocalReplicaManager)
# ---------------------------------------------------------------------------
class ScalableStub:
    """Replica stub with mutable pressure (autoscaler unit tests)."""

    def __init__(self, replica_id, pressure=0.0):
        self.replica_id = replica_id
        self.pressure = float(pressure)
        self.up = True

    def serving_stats(self):
        return {"queue_depth": self.pressure, "waiting": 0, "running": 0}

    def healthz(self):
        return (200, "serving")

    def alive(self):
        return self.up

    def stop(self):
        self.up = False


class StubManager:
    """Minimal actuator-protocol manager over :class:`ScalableStub`."""

    def __init__(self, n=1, pressure=0.0, registry=None):
        self.replicas = [ScalableStub(i, pressure) for i in range(n)]
        self._retired = set()
        self._registry = registry or MetricsRegistry()
        self.spawns = 0
        self.retires = []

    def poll_states(self):
        return {i: ("retired" if i in self._retired else "healthy")
                for i in range(len(self.replicas))}

    def spawn(self):
        for idx in sorted(self._retired):
            self._retired.discard(idx)
            self.replicas[idx] = ScalableStub(
                idx, self.replicas[0].pressure)
            self.spawns += 1
            return self.replicas[idx]
        self.replicas.append(ScalableStub(len(self.replicas),
                                          self.replicas[0].pressure))
        self.spawns += 1
        return self.replicas[-1]

    def retire(self, idx):
        self._retired.add(idx)
        self.retires.append(idx)

    def set_pressure(self, p):
        for r in self.replicas:
            r.pressure = float(p)


class TestAutoscaler:
    def mk(self, reg=None, **kw):
        clk = faults.expire_clock(0.0)
        mgr = StubManager(n=1, pressure=0.0, registry=reg)
        kw.setdefault("slo", ServingSLO(queue_depth=4))
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 3)
        kw.setdefault("window_secs", 10.0)
        kw.setdefault("cooldown_secs", 5.0)
        auto = FleetAutoscaler(mgr, registry=reg or MetricsRegistry(),
                               clock=clk, **kw)
        return auto, mgr, clk

    def drive(self, auto, clk, seconds, dt=1.0):
        actions = []
        t = 0.0
        while t < seconds:
            clk.advance(dt)
            t += dt
            a = auto.step()
            if a:
                actions.append(a)
        return actions

    def test_burst_scales_up_within_burn_window(self):
        sink = CaptureSink()
        reg = MetricsRegistry()
        reg.add_sink(sink)
        auto, mgr, clk = self.mk(reg=reg)
        mgr.set_pressure(10.0)                    # SLO burns (>4)
        actions = self.drive(auto, clk, 30)
        assert actions[:2] == ["up", "up"]        # 1 -> 3 replicas
        assert mgr.spawns == 2
        ups = [r for r in sink.records
               if r["kind"] == "fleet.autoscale" and r["action"] == "up"]
        assert len(ups) == 2
        assert all("queue_depth" in u["why"] for u in ups)

    def test_blocked_at_max_is_a_record(self):
        sink = CaptureSink()
        reg = MetricsRegistry()
        reg.add_sink(sink)
        auto, mgr, clk = self.mk(reg=reg, max_replicas=1)
        mgr.set_pressure(10.0)
        actions = self.drive(auto, clk, 20)
        assert "blocked_at_max" in actions
        assert mgr.spawns == 0
        blocked = [r for r in sink.records
                   if r["kind"] == "fleet.autoscale"
                   and r["action"] == "blocked_at_max"]
        assert blocked and blocked[0]["replicas"] == 1

    def test_idle_scales_down_after_cooldown(self):
        sink = CaptureSink()
        reg = MetricsRegistry()
        reg.add_sink(sink)
        auto, mgr, clk = self.mk(reg=reg)
        mgr.set_pressure(10.0)
        self.drive(auto, clk, 16)                 # scale up first
        assert len(mgr.replicas) >= 2
        mgr.set_pressure(0.0)                     # burst over
        actions = self.drive(auto, clk, 60)
        assert "down" in actions
        assert mgr.retires                        # a slot was retired
        downs = [r for r in sink.records
                 if r["kind"] == "fleet.autoscale"
                 and r["action"] == "down"]
        assert downs and "idle through window" in downs[0]["why"]
        # never below the floor
        active = [i for i, s in mgr.poll_states().items()
                  if s == "healthy"]
        assert len(active) >= auto.min_replicas

    def test_cooldown_rate_limits_actions(self):
        auto, mgr, clk = self.mk(cooldown_secs=30.0)
        mgr.set_pressure(10.0)
        actions = self.drive(auto, clk, 35)
        assert actions == ["up"]                  # second up still cooling

    def test_one_slow_sample_does_not_flap_the_fleet(self):
        auto, mgr, clk = self.mk()
        # 12 idle-ish samples, one burning blip: burn fraction stays
        # far under the threshold — no scale-up
        for i in range(12):
            clk.advance(1.0)
            mgr.set_pressure(10.0 if i == 5 else 1.0)
            assert auto.step() is None
        assert mgr.spawns == 0

    def test_scale_down_quiesces_through_router(self, tmp_path):
        """End-to-end against a real LocalReplicaManager: the victim's
        live stream migrates (drain) before the slot retires."""
        reg = MetricsRegistry()
        clk = faults.expire_clock(0.0)
        mgr = LocalReplicaManager(
            lambda i: ServingEngine(tiny_model(), registry=reg,
                                    replica_id=i, max_seqs=4,
                                    kv_block_size=4),
            replicas=2, registry=reg)
        router = Router(mgr.replicas, manager=mgr, registry=reg,
                        retry_budget=fresh_budget())
        model = tiny_model()
        want = dense_continuation(model, [1, 2, 3], 12)
        rid = router.submit([1, 2, 3], max_new_tokens=12)
        router.pump()
        auto = FleetAutoscaler(mgr, router=router,
                               slo=ServingSLO(queue_depth=50),
                               min_replicas=1, max_replicas=2,
                               window_secs=5.0, cooldown_secs=1.0,
                               registry=reg, clock=clk)
        # the fleet holds work, so it is never "idle" — finish first
        out = router.collect(rid, timeout=60)
        assert out["tokens"] == want
        for _ in range(8):
            clk.advance(1.0)
            auto.step()
        assert auto.actions["down"] == 1
        assert "retired" in mgr.poll_states().values()
        # spawn() reuses the retired slot — ids stay stable
        mgr.spawn()
        assert sorted(mgr.poll_states().values()) == [
            "healthy", "healthy"]

    def test_min_max_env_knobs(self, monkeypatch):
        monkeypatch.setenv("PTPU_FLEET_MIN", "2")
        monkeypatch.setenv("PTPU_FLEET_MAX", "5")
        auto = FleetAutoscaler(StubManager(n=2),
                               registry=MetricsRegistry(),
                               window_secs=1.0, cooldown_secs=1.0)
        assert auto.min_replicas == 2 and auto.max_replicas == 5


# ---------------------------------------------------------------------------
# doctor verdicts + satellite knobs
# ---------------------------------------------------------------------------
class TestDoctorVerdicts:
    def test_fleet_flapping_names_replica_and_budget_pressure(self):
        from paddle_tpu.observability.doctor import check_fleet_flapping
        recs = [{"kind": "fleet.breaker", "replica": 1,
                 "prev": "closed", "state": "open", "trips": 1},
                {"kind": "fleet.breaker", "replica": 1,
                 "prev": "half_open", "state": "open", "trips": 2},
                {"kind": "fleet.shed", "why": "retry_budget"},
                {"kind": "fleet.deferred", "request_id": "r1",
                 "why": "retry_budget"}]
        [f] = check_fleet_flapping({0: recs})
        assert f["kind"] == "fleet_flapping"
        assert f["data"]["trips"] == {"1": 2}
        assert f["data"]["budget_sheds"] == 1
        assert any("retry storm" in e for e in f["evidence"])
        # closed->closed noise alone: no verdict
        assert not check_fleet_flapping(
            {0: [{"kind": "fleet.breaker", "replica": 0,
                  "prev": "open", "state": "half_open"}]})

    def test_fleet_slo_burn_escalates_on_blocked_at_max(self):
        from paddle_tpu.observability.doctor import check_fleet_slo_burn
        ups = [{"kind": "fleet.autoscale", "action": "up",
                "replicas": 1, "target": 2, "burn": 0.8,
                "why": "replica 0: queue_depth 12 > 4"}]
        [mild] = check_fleet_slo_burn({0: ups})
        assert mild["kind"] == "fleet_slo_burn"
        blocked = ups + [{"kind": "fleet.autoscale",
                          "action": "blocked_at_max", "replicas": 2,
                          "target": 2, "burn": 1.0, "why": "still hot"}]
        [hot] = check_fleet_slo_burn({0: blocked})
        assert hot["severity"] > mild["severity"]
        assert any("PTPU_FLEET_MAX" in e for e in hot["evidence"])
        assert not check_fleet_slo_burn(
            {0: [{"kind": "fleet.autoscale", "action": "down"}]})

    def test_diagnose_surfaces_fleet_autonomy_verdicts(self, tmp_path):
        from paddle_tpu.observability import doctor
        from paddle_tpu.observability.sinks import (MetricsWriter,
                                                    metrics_dir)
        reg = MetricsRegistry()
        reg.add_sink(MetricsWriter(metrics_dir(str(tmp_path)),
                                   worker_id=0, flush_every=1))
        reg.emit("fleet.breaker", replica=0, prev="closed",
                 state="open", trips=1)
        reg.emit("fleet.autoscale", action="blocked_at_max", replicas=2,
                 target=2, burn=1.0, why="hot")
        reg.flush()
        diag = doctor.diagnose(str(tmp_path), write=False)
        kinds = {f["kind"] for f in diag["findings"]}
        assert {"fleet_flapping", "fleet_slo_burn"} <= kinds


class TestSatelliteKnobs:
    def test_drain_slack_env_knob(self, monkeypatch):
        assert default_drain_slack_secs() == 30.0
        monkeypatch.setenv("PTPU_FLEET_DRAIN_SLACK_SECS", "2.5")
        assert default_drain_slack_secs() == 2.5

    def test_http_drain_uses_slack(self, monkeypatch):
        from paddle_tpu.inference.fleet import HttpReplica
        monkeypatch.setenv("PTPU_FLEET_DRAIN_SLACK_SECS", "1.5")
        rep = HttpReplica(0, port=1)
        seen = {}

        def fake_call(path, payload=None, timeout=None):
            seen["timeout"] = timeout
            return {"finished": 0, "spilled_records": []}

        rep._call = fake_call
        rep.drain(timeout=2.0)
        assert seen["timeout"] == pytest.approx(3.5)

    def test_engine_stats_slo_section(self):
        reg = MetricsRegistry()
        eng = ServingEngine(tiny_model(), registry=reg, max_seqs=2,
                            kv_block_size=4)
        eng.generate([[1, 2, 3]], max_new_tokens=4)
        slo = eng.stats()["slo"]
        assert slo["ttft_ms"]["samples"] >= 1
        assert slo["ttft_ms"]["p99"] >= slo["ttft_ms"]["p50"] >= 0.0
        assert slo["tpot_ms"]["samples"] >= 1

    def test_admit_record_idempotent_on_duplicate_rid(self):
        reg = MetricsRegistry()
        eng = ServingEngine(tiny_model(), registry=reg, max_seqs=4,
                            kv_block_size=4)
        rec = {"request_id": "dup", "prompt": [1, 2],
               "max_new_tokens": 4, "eos_token_id": None, "output": []}
        assert eng.admit_record(rec) == "dup"
        assert eng.admit_record(dict(rec)) == "dup"   # no double admit
        counts = eng.sched.counts()
        assert counts["waiting"] + counts["running"] == 1
        assert reg.snapshot()["serve.readmit_dupes"]["value"] == 1.0
