"""InferMeta validation layer (component C8; reference paddle/phi/
infermeta/): bad call shapes raise typed InvalidArgumentError with the
offending shapes in the message, BEFORE any kernel runs."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu.nn.functional as F
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.framework.infermeta import infer_meta, meta_of

R = np.random.RandomState(0)


def _f(*shape):
    return jnp.asarray(R.rand(*shape), jnp.float32)


class TestRules:
    def test_linear_dim_mismatch(self):
        with pytest.raises(InvalidArgumentError, match="linear"):
            F.linear(_f(4, 8), _f(9, 16))
        with pytest.raises(InvalidArgumentError, match="bias"):
            F.linear(_f(4, 8), _f(8, 16), _f(17))
        assert F.linear(_f(4, 8), _f(8, 16), _f(16)).shape == (4, 16)

    def test_conv2d_channel_groups(self):
        with pytest.raises(InvalidArgumentError, match="channels"):
            F.conv2d(_f(1, 3, 8, 8), _f(8, 4, 3, 3))
        with pytest.raises(InvalidArgumentError, match="groups"):
            F.conv2d(_f(1, 4, 8, 8), _f(7, 2, 3, 3), groups=2)
        out = F.conv2d(_f(1, 4, 8, 8), _f(8, 2, 3, 3), groups=2,
                       padding=1)
        assert out.shape == (1, 8, 8, 8)

    def test_embedding_requires_int_ids(self):
        with pytest.raises(InvalidArgumentError, match="integer"):
            F.embedding(_f(4), _f(10, 8))
        ids = jnp.asarray([1, 2], jnp.int32)
        assert F.embedding(ids, _f(10, 8)).shape == (2, 8)

    def test_cross_entropy_label_meta(self):
        logits = _f(4, 10)
        with pytest.raises(InvalidArgumentError, match="integer"):
            F.cross_entropy(logits, _f(4))
        with pytest.raises(InvalidArgumentError, match="rank"):
            F.cross_entropy(logits,
                            jnp.zeros((4, 2, 2), jnp.int32))
        ok = F.cross_entropy(logits, jnp.zeros((4,), jnp.int32))
        assert np.isfinite(float(ok))

    def test_layer_norm_trailing_dims(self):
        with pytest.raises(InvalidArgumentError, match="normalized_shape"):
            F.layer_norm(_f(2, 8), normalized_shape=(9,))
        assert F.layer_norm(_f(2, 8), normalized_shape=(8,)).shape == (2, 8)

    def test_batch_norm_stat_shapes(self):
        with pytest.raises(InvalidArgumentError, match="running_mean"):
            F.batch_norm(_f(2, 3, 4, 4), jnp.zeros(4), jnp.ones(3))

    def test_error_message_carries_shapes(self):
        try:
            F.linear(_f(4, 8), _f(9, 16))
        except InvalidArgumentError as e:
            assert "[4, 8]" in str(e) and "[9, 16]" in str(e)
        else:
            raise AssertionError("expected InvalidArgumentError")


class TestDecorator:
    def test_rule_exposed_and_composable(self):
        def rule(x):
            m = meta_of(x, "x")
            if m.ndim != 1:
                raise InvalidArgumentError(f"need 1-D, got {m}")

        @infer_meta(rule)
        def op(x):
            return jnp.asarray(x) * 2

        assert op.__infermeta__ is rule
        np.testing.assert_allclose(np.asarray(op(jnp.ones(3))), 2.0)
        with pytest.raises(InvalidArgumentError):
            op(jnp.ones((2, 2)))
