"""CPU smoke coverage for the bench.py entry points (the reference keeps its
op_tester harness compiling even without GPUs — same doctrine here): the
bench helpers that only run inside bench.main()'s on-TPU branch get
tiny-shape CPU executions so a regression surfaces in the suite, not as a
silent '[...] failed:' stderr line during the one-shot hardware-evidence run.
Covered directly: _bench_resnet50, _bench_bert_base, _sweep_seqlen_ab,
_bench_slice_estimate (the 1.3B/6.7B slice methodology), _bench_config (the
headline path).  _bench_flash_ab / _sweep_block_sizes / _bench_1p3b_fullstep
are thin compositions of the same _build/_timed_steps/flash_attention pieces.

The real-config artifacts (benchmarks/*.json) must NOT be written by these
smoke shapes — that gating is asserted here too.
"""
import pathlib
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def _artifact_mtimes():
    d = REPO / "benchmarks"
    return {p.name: p.stat().st_mtime for p in d.glob("*.json")}


def test_bench_resnet_smoke_writes_no_artifact(monkeypatch):
    # the override makes _write_artifact willing to record from CPU, so
    # what this actually asserts is the CONFIG-level gate (smoke depth/hw
    # never produce an artifact), not the CPU-platform gate
    monkeypatch.setenv("BENCH_ALLOW_CPU_ARTIFACTS", "1")
    before = _artifact_mtimes()
    img_s = bench._bench_resnet50(B=2, hw=32, steps=2, warmup=1, depth=18)
    assert img_s > 0
    assert _artifact_mtimes() == before, (
        "smoke config must not overwrite the hardware resnet50.json")


def test_bench_bert_smoke_writes_no_artifact(monkeypatch):
    from paddle_tpu.models.bert import bert_tiny
    monkeypatch.setenv("BENCH_ALLOW_CPU_ARTIFACTS", "1")
    before = _artifact_mtimes()
    seq_s = bench._bench_bert_base(B=2, S=64, steps=2, warmup=1,
                                   cfg_factory=bert_tiny)
    assert seq_s > 0
    assert _artifact_mtimes() == before, (
        "smoke config must not overwrite the hardware bert_base.json")


def test_bench_seqlen_ab_smoke():
    before = _artifact_mtimes()
    results = bench._sweep_seqlen_ab(bh=2, d=8, seqlens=(128,), steps=1,
                                     artifact=False)
    assert results["128"]["flash"] is not None
    assert results["128"]["xla"] is not None
    assert _artifact_mtimes() == before


def test_bench_slice_estimate_smoke():
    """Drives the shared slice-differencing helper (the 1.3B/6.7B
    methodology) on a tiny config; no artifact recorded."""
    from paddle_tpu.models import gpt_tiny
    before = _artifact_mtimes()
    tok_s, mfu = bench._bench_slice_estimate(gpt_tiny, (1, 2), B=2, S=64,
                                             tag="smoke-slice")
    assert tok_s > 0 and mfu >= 0
    assert _artifact_mtimes() == before


def test_bench_fused_block_ab_smoke():
    """ISSUE 7: the fused-block A/B helper runs on tiny CPU shapes, the
    fused leg honors the compile contract, and no artifact is written."""
    from paddle_tpu.models import gpt_tiny
    before = _artifact_mtimes()
    rows = bench._bench_fused_block_ab(
        B=2, S=64, steps=2, warmup=1, artifact=False,
        cfg_factory=lambda **kw: gpt_tiny(max_position_embeddings=64, **kw))
    assert rows["fused_block"]["step_ms"] > 0
    assert rows["fused_block"]["compiles"] == 1
    assert rows["fused_block"]["retraces"] == 0
    assert rows["fused_block"]["storms"] == 0
    assert _artifact_mtimes() == before


def test_bench_fused_ce_ab_smoke():
    from paddle_tpu.models import gpt_tiny
    before = _artifact_mtimes()
    rows = bench._bench_fused_ce_ab(
        B=2, S=128, steps=2, warmup=1, artifact=False, op_memory=False,
        cfg_factory=lambda **kw: gpt_tiny(max_position_embeddings=128,
                                          hidden_dropout=0.0,
                                          attention_dropout=0.0, **kw))
    assert rows["fused_ce"]["step_ms"] > 0
    assert _artifact_mtimes() == before


def test_fused_ce_op_memory_smoke():
    """The op-level memory measurement must show the fused CE saving
    temp bytes once the chunked scan engages (small-shape rendering of
    the fused_ce_ab.json evidence)."""
    out = bench._fused_ce_op_memory(B=1, S=256, H=64, V=4096, chunk=128)
    if out["fused"] and out["unfused"]:       # memory analysis available
        assert out["temp_bytes_saved"] > 0, out


@pytest.mark.slow
def test_bench_gpt_smoke():
    """The headline path main() takes on CPU (gpt_tiny smoke)."""
    from paddle_tpu.models import gpt_tiny
    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    tok_s, mfu = bench._bench_config(cfg, B=2, S=128, steps=2, warmup=1,
                                     tag="suite-smoke")
    assert tok_s > 0 and mfu >= 0
