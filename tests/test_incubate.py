"""Tests: regularizer wiring, Assign/Dirac/Orthogonal initializers,
incubate LookAhead/ModelAverage optimizers, incubate.nn fused layers."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.regularizer import L1Decay, L2Decay
from paddle_tpu.nn import initializer as I


class TestRegularizer:
    def test_l2_decay_equals_float_weight_decay(self):
        p = {"w": jnp.asarray([1.0, -2.0, 3.0])}
        g = {"w": jnp.asarray([0.1, 0.1, 0.1])}
        o1 = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.0,
                                   weight_decay=0.01)
        o2 = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.0,
                                   weight_decay=L2Decay(0.01))
        p1, _ = o1.apply_gradients(g, p, o1.init(p))
        p2, _ = o2.apply_gradients(g, p, o2.init(p))
        np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                                   rtol=1e-6)

    def test_l1_decay_adds_sign_gradient(self):
        p = {"w": jnp.asarray([1.0, -2.0, 3.0])}
        g = {"w": jnp.zeros(3)}
        o = pt.optimizer.SGD(learning_rate=0.1, weight_decay=L1Decay(0.5))
        p1, _ = o.apply_gradients(g, p, o.init(p))
        # pure L1: p -= lr * 0.5 * sign(p)
        np.testing.assert_allclose(np.asarray(p1["w"]),
                                   [0.95, -1.95, 2.95], rtol=1e-6)


class TestInitializers:
    def test_assign(self):
        pt.seed(0)
        lin = nn.Linear(2, 2, weight_attr=pt.ParamAttr(
            initializer=I.Assign(np.asarray([[1., 2.], [3., 4.]]))))
        np.testing.assert_allclose(np.asarray(lin.weight.value),
                                   [[1, 2], [3, 4]])

    def test_dirac_preserves_identity(self):
        k = jax.random.key(0)
        w = I.Dirac()(k, (4, 4, 3, 3))
        x = jnp.asarray(np.random.RandomState(0).randn(1, 4, 8, 8),
                        jnp.float32)
        y = pt.nn.functional.conv2d(x, w, padding=1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-6)

    def test_orthogonal(self):
        k = jax.random.key(1)
        q = I.Orthogonal()(k, (10, 4))
        np.testing.assert_allclose(np.asarray(q.T @ q), np.eye(4),
                                   atol=1e-5)
        q2 = I.Orthogonal(gain=2.0)(k, (4, 10))
        np.testing.assert_allclose(np.asarray(q2 @ q2.T), 4 * np.eye(4),
                                   atol=1e-4)


def _quadratic():
    pt.seed(0)
    model = nn.Linear(4, 4, bias_attr=False)
    x = pt.randn((32, 4))
    y = pt.randn((32, 4))

    def loss_fn(params):
        return jnp.mean((model.apply(params, x) - y) ** 2)

    return model, loss_fn


class TestIncubateOptimizers:
    def test_lookahead_descends_and_syncs(self):
        from paddle_tpu.incubate.optimizer import LookAhead
        model, loss_fn = _quadratic()
        opt = LookAhead(pt.optimizer.SGD(learning_rate=0.1), alpha=0.5, k=5)
        params = model.trainable_variables()
        state = opt.init(params)
        l0 = float(loss_fn(params))

        @jax.jit
        def step(p, s):
            g = jax.grad(loss_fn)(p)
            return opt.apply_gradients(g, p, s)

        for _ in range(80):
            params, state = step(params, state)
        # the random quadratic has an irreducible least-squares floor;
        # halving the initial loss is well past it for this seed
        assert float(loss_fn(params)) < 0.5 * l0
        # after a sync step, fast == slow
        assert int(state["step"]) % 5 == 0
        for kp, s in zip(jax.tree_util.tree_leaves(params),
                         jax.tree_util.tree_leaves(state["slow"])):
            np.testing.assert_allclose(np.asarray(kp), np.asarray(s),
                                       rtol=1e-6)

    def test_model_average_tracks_mean(self):
        from paddle_tpu.incubate.optimizer import ModelAverage
        model, loss_fn = _quadratic()
        # rate=1.0: window == count, so the average is the exact mean
        opt = ModelAverage(pt.optimizer.SGD(learning_rate=0.05),
                           average_window_rate=1.0,
                           max_average_window=100)
        params = model.trainable_variables()
        state = opt.init(params)
        history = []
        for _ in range(10):
            g = jax.grad(loss_fn)(params)
            params, state = opt.apply_gradients(g, params, state)
            history.append(np.asarray(
                jax.tree_util.tree_leaves(params)[0]))
        avg = opt.average(state, params)
        np.testing.assert_allclose(
            np.asarray(jax.tree_util.tree_leaves(avg)[0]),
            np.mean(history, axis=0), rtol=1e-5)

    def test_model_average_window_rate_limits_window(self):
        """rate < 1 keeps a growing-window average: recent params dominate
        once count exceeds rate*count's clip."""
        from paddle_tpu.incubate.optimizer import ModelAverage
        model, loss_fn = _quadratic()
        opt = ModelAverage(pt.optimizer.SGD(learning_rate=0.0),
                           average_window_rate=0.2,
                           min_average_window=1, max_average_window=4)
        params = model.trainable_variables()
        state = opt.init(params)
        g = jax.tree_util.tree_map(jnp.zeros_like, params)
        # params never change (lr=0); run well past window saturation so
        # the streaming sum converges to window * param
        for _ in range(60):
            params, state = opt.apply_gradients(g, params, state)
        avg = opt.average(state, params)
        # constant params: windowed mean must equal the constant
        for a, p in zip(jax.tree_util.tree_leaves(avg),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(p),
                                       rtol=1e-4)


class TestIncubateNN:
    def test_fused_mha_matches_unfused(self):
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention
        pt.seed(3)
        layer = FusedMultiHeadAttention(32, 4, dropout_rate=0.0,
                                        attn_dropout_rate=0.0,
                                        normalize_before=True)
        layer.eval()
        x = jnp.asarray(np.random.RandomState(0).randn(2, 6, 32),
                        jnp.float32)
        out = layer(x)
        # manual recompute through the same parameters
        xn = pt.nn.functional.layer_norm(
            x, (32,), layer.norm.weight, layer.norm.bias)
        qkv = pt.nn.functional.linear(xn, layer.qkv_proj.weight,
                                      layer.qkv_proj.bias)
        qkv = qkv.reshape(2, 6, 3, 4, 8)
        q, k, v = (jnp.swapaxes(qkv[:, :, i], 1, 2) for i in range(3))
        att = pt.nn.functional.scaled_dot_product_attention(
            q, k, v, training=False)
        att = jnp.swapaxes(att, 1, 2).reshape(2, 6, 32)
        want = x + pt.nn.functional.linear(att, layer.out_proj.weight,
                                           layer.out_proj.bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_ffn_trains(self):
        from paddle_tpu.incubate.nn import FusedFeedForward
        pt.seed(4)
        ffn = FusedFeedForward(16, 32, dropout_rate=0.0,
                               normalize_before=True)
        ffn.train()
        params = ffn.state_dict()
        x = jnp.asarray(np.random.RandomState(1).randn(4, 5, 16),
                        jnp.float32)
        tgt = jnp.asarray(np.random.RandomState(2).randn(4, 5, 16),
                          jnp.float32)
        opt = pt.optimizer.Adam(learning_rate=1e-2)
        state = opt.init(params)

        @jax.jit
        def step(p, s):
            def lf(q):
                return jnp.mean((ffn.apply(q, x) - tgt) ** 2)
            loss, g = jax.value_and_grad(lf)(p)
            return (loss, *opt.apply_gradients(g, p, s))

        losses = []
        for _ in range(20):
            loss, params, state = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestDistributedFusedLamb:
    """Reference incubate/optimizer/distributed_fused_lamb.py:27 — the
    fused multi-tensor LAMB with sharded flat state."""

    @staticmethod
    def _params():
        R = np.random.RandomState(0)
        return {"w": jnp.asarray(R.randn(16, 8), jnp.float32),
                "b": jnp.asarray(R.randn(8), jnp.float32)}

    def test_matches_per_tensor_lamb(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb
        from paddle_tpu.optimizer import Lamb
        params = self._params()
        grads = jax.tree_util.tree_map(
            lambda p: jnp.ones_like(p) * 0.1, params)
        fused = DistributedFusedLamb(learning_rate=1e-2,
                                     lamb_weight_decay=0.01,
                                     alignment=1)
        st = fused.init(params)
        p1, st = fused.apply_gradients(grads, params, st)
        ref = Lamb(learning_rate=1e-2, lamb_weight_decay=0.01)
        rst = ref.init(params)
        p2, rst = ref.apply_gradients(grads, params, rst)
        for k in params:
            np.testing.assert_allclose(np.asarray(p1[k]),
                                       np.asarray(p2[k]),
                                       rtol=1e-5, atol=1e-6)

    def test_flat_state_sharded_over_mesh(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                                   "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        fused = DistributedFusedLamb(alignment=8)
        st = fused.init(self._params())
        spec = getattr(st["master"].sharding, "spec", ())
        assert "dp" in tuple(spec), spec

    def test_exclude_from_weight_decay(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb
        params = self._params()
        grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        # zero grads: any movement comes purely from weight decay
        wd_all = DistributedFusedLamb(learning_rate=1e-2,
                                      lamb_weight_decay=0.1, alignment=1)
        st = wd_all.init(params)
        moved, _ = wd_all.apply_gradients(grads, params, st)
        assert not np.allclose(np.asarray(moved["b"]),
                               np.asarray(params["b"]))
        # dotted-name paths, same convention as the base Optimizer's
        # apply_decay_param_fun (NOT jax keystr bracket format)
        wd_skip = DistributedFusedLamb(
            learning_rate=1e-2, lamb_weight_decay=0.1, alignment=1,
            exclude_from_weight_decay_fn=lambda name: name == "b")
        st2 = wd_skip.init(params)
        kept, _ = wd_skip.apply_gradients(grads, params, st2)
        np.testing.assert_allclose(np.asarray(kept["b"]),
                                   np.asarray(params["b"]))

    def test_skip_on_nonfinite_and_scale(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb
        params = self._params()
        fused = DistributedFusedLamb(learning_rate=1e-2, alignment=1)
        fused.set_scale(2.0)
        st = fused.init(params)
        bad = jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, jnp.inf), params)
        p1, st1 = fused.apply_gradients(bad, params, st)
        for k in params:
            np.testing.assert_array_equal(np.asarray(p1[k]),
                                          np.asarray(params[k]))
        assert int(st1["step"]) == 0

    def test_lr_scheduler_supported(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb
        from paddle_tpu.optimizer import lr as lr_mod
        params = {"w": jnp.ones((4, 4), jnp.float32)}
        sched = lr_mod.StepDecay(learning_rate=1.0, step_size=1, gamma=0.1)
        fused = DistributedFusedLamb(learning_rate=sched, alignment=1)
        st = fused.init(params)
        g = {"w": jnp.ones((4, 4)) * 0.1}
        p1, st = fused.apply_gradients(g, params, st)
        assert not np.allclose(np.asarray(p1["w"]), 1.0)

    def test_global_norm_clip_and_jit(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb
        from paddle_tpu.optimizer import ClipGradByGlobalNorm
        params = self._params()
        fused = DistributedFusedLamb(
            learning_rate=1e-2, grad_clip=ClipGradByGlobalNorm(0.5),
            alignment=8)
        st = fused.init(params)

        @jax.jit
        def step(g, p, s):
            return fused.apply_gradients(g, p, s)

        grads = jax.tree_util.tree_map(
            lambda p: jnp.ones_like(p) * 10.0, params)
        p1, st = step(grads, params, st)
        assert int(st["step"]) == 1
        assert all(bool(jnp.isfinite(v).all())
                   for v in jax.tree_util.tree_leaves(p1))

    def test_stateful_step_and_unsupported_flags(self):
        import pytest as _pytest
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb
        pt.seed(4)
        lin = nn.Linear(8, 8)
        fused = DistributedFusedLamb(learning_rate=1e-2, alignment=1,
                                     parameters=lin.parameters())
        before = np.asarray(lin.weight.value).copy()
        fused.step([jnp.ones_like(p.value) * 0.1
                    for p in lin.parameters()])
        assert not np.allclose(np.asarray(lin.weight.value), before)
        with _pytest.raises(Exception, match="clip_after_allreduce"):
            DistributedFusedLamb(clip_after_allreduce=False)
        with _pytest.raises(Exception, match="use_master_param_norm"):
            DistributedFusedLamb(use_master_param_norm=False)

    def test_scalar_bias_linear_still_works(self):
        import paddle_tpu.nn.functional as F
        out = F.linear(jnp.ones((4, 8)), jnp.ones((8, 16)),
                       jnp.asarray(0.5))
        np.testing.assert_allclose(np.asarray(out), 8.5)


class TestGraphSendRecv:
    """graph_send_recv (reference incubate/operators/graph_send_recv.py:22)
    — the docstring example plus all pool types vs a numpy oracle."""

    def test_reference_docstring_example(self):
        from paddle_tpu.incubate import graph_send_recv
        x = jnp.asarray([[0, 2, 3], [1, 4, 5], [2, 6, 7]], jnp.float32)
        src = jnp.asarray([0, 1, 2, 0], jnp.int32)
        dst = jnp.asarray([1, 2, 1, 0], jnp.int32)
        out = graph_send_recv(x, src, dst, pool_type="sum")
        np.testing.assert_array_equal(
            np.asarray(out), [[0, 2, 3], [2, 8, 10], [1, 4, 5]])

    @pytest.mark.parametrize("pool", ["sum", "mean", "max", "min"])
    def test_pools_vs_numpy(self, pool):
        from paddle_tpu.incubate import graph_send_recv
        R = np.random.RandomState(0)
        x = R.randn(6, 4).astype(np.float32)
        src = R.randint(0, 6, (12,)).astype(np.int32)
        dst = R.randint(0, 5, (12,)).astype(np.int32)   # row 5 stays empty
        out = np.asarray(graph_send_recv(x, src, dst, pool_type=pool))
        ref = np.zeros((6, 4), np.float32)
        for row in range(6):
            msgs = x[src[dst == row]]
            if len(msgs) == 0:
                continue
            ref[row] = {"sum": msgs.sum(0), "mean": msgs.mean(0),
                        "max": msgs.max(0), "min": msgs.min(0)}[pool]
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)
        np.testing.assert_array_equal(out[5], 0.0)      # empty row zeroed

    def test_out_size_and_jit(self):
        from paddle_tpu.incubate import graph_send_recv
        x = jnp.ones((4, 2))
        src = jnp.asarray([0, 1], jnp.int32)
        dst = jnp.asarray([0, 0], jnp.int32)
        out = jax.jit(lambda *a: graph_send_recv(*a, pool_type="sum",
                                                 out_size=2))(x, src, dst)
        assert out.shape == (2, 2)
        np.testing.assert_array_equal(np.asarray(out[0]), 2.0)


class TestASP:
    """ASP n:m pruning (reference fluid/contrib/sparsity; asp.py:289
    ASPHelper).  The speedup half is N/A on TPU (no sparse MXU mode); the
    capability half — masks, pruning, sparsity-preserving optimizer — is
    what these assert."""

    def test_mask_1d_reference_convention_n_is_zeros(self):
        # n:m = at least n ZEROS per 1 x m block (reference utils.py:181):
        # 1:4 zeroes one of every four -> density 0.75
        from paddle_tpu.incubate import sparsity
        mat = np.random.RandomState(5).randn(4, 8).astype(np.float32)
        mask = sparsity.get_mask_1d(mat, 1, 4)
        assert abs(sparsity.calculate_density(mask) - 0.75) < 1e-6
        assert sparsity.check_mask_1d(mat * mask, 1, 4)
        assert not sparsity.check_mask_1d(mat, 1, 4)  # dense fails

    def test_mask_1d_pattern_and_checkers(self):
        from paddle_tpu.incubate import sparsity
        R = np.random.RandomState(0)
        mat = R.randn(8, 16).astype(np.float32)
        mask = sparsity.get_mask_1d(mat, 2, 4)
        assert sparsity.check_mask_1d(mat * mask, 2, 4)
        assert abs(sparsity.calculate_density(mat * mask) - 0.5) < 1e-6
        # keeps the largest-magnitude pair of every group of 4
        groups = np.abs(mat).reshape(-1, 4)
        kept = (mask.reshape(-1, 4) > 0)
        for g, k in zip(groups, kept):
            assert set(np.argsort(-g)[:2]) == set(np.nonzero(k)[0])

    @pytest.mark.parametrize("algo", ["mask_2d_greedy", "mask_2d_best"])
    def test_mask_2d_valid(self, algo):
        from paddle_tpu.incubate import sparsity
        R = np.random.RandomState(1)
        mat = R.randn(8, 8).astype(np.float32)
        fn = getattr(sparsity, "get_" + algo)
        mask = fn(mat, 2, 4)
        assert sparsity.check_mask_2d(mat * mask, 2, 4)
        assert abs(sparsity.calculate_density(mask) - 0.5) < 1e-6

    def test_mask_2d_best_beats_or_ties_greedy(self):
        from paddle_tpu.incubate import sparsity
        R = np.random.RandomState(2)
        mat = R.randn(16, 16).astype(np.float32)
        g = np.abs(mat * sparsity.get_mask_2d_greedy(mat, 2, 4)).sum()
        b = np.abs(mat * sparsity.get_mask_2d_best(mat, 2, 4)).sum()
        assert b >= g - 1e-5

    def test_conv_weight_mask_shape(self):
        from paddle_tpu.incubate import sparsity
        w = np.random.RandomState(3).randn(8, 4, 3, 3).astype(np.float32)
        mask = sparsity.create_mask(w, sparsity.MaskAlgo.MASK_1D, 2, 4)
        assert mask.shape == w.shape
        assert sparsity.check_sparsity(w * mask,
                                       sparsity.CheckMethod.CHECK_1D, 2, 4)

    def test_prune_model_and_decorated_optimizer_preserve_sparsity(self):
        from paddle_tpu.incubate import sparsity
        sparsity.reset_excluded_layers()
        pt.seed(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(),
                              nn.Linear(32, 4))
        masks = sparsity.prune_model(model, mask_algo="mask_1d")
        assert len(masks) == 2              # the two Linear weights
        for name, p in model.named_parameters():
            if name in masks:
                assert sparsity.check_sparsity(np.asarray(p.value))

        opt = sparsity.decorate(
            pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                  weight_decay=0.01))
        params = model.trainable_variables()
        state = opt.init(params)
        R = np.random.RandomState(0)
        x = jnp.asarray(R.randn(8, 16), jnp.float32)
        y = jnp.asarray(R.randint(0, 4, (8,)), jnp.int32)
        for _ in range(3):
            def loss_fn(p):
                return nn.functional.cross_entropy(model.apply(p, x), y)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = opt.apply_gradients(grads, params, state)
        # momentum + weight decay would densify without the guard
        for name in masks:
            assert sparsity.check_sparsity(np.asarray(params[name])), name
        sparsity.reset_masks()

    def test_excluded_layers(self):
        from paddle_tpu.incubate import sparsity
        sparsity.reset_excluded_layers()
        pt.seed(0)
        model = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
        sparsity.set_excluded_layers(["0.weight"])
        masks = sparsity.prune_model(model)
        assert "0.weight" not in masks and "1.weight" in masks
        sparsity.reset_excluded_layers()
        sparsity.reset_masks()


class TestGraphSegmentOps:
    """Round-5 incubate gap fill (+ review-finding regressions)."""

    def test_segment_family(self):
        import paddle_tpu.incubate as inc
        d = jnp.asarray([1., 2., 3., 4.])
        ids = jnp.asarray([0, 0, 1, 1])
        np.testing.assert_allclose(np.asarray(inc.segment_sum(d, ids)), [3, 7])
        np.testing.assert_allclose(np.asarray(inc.segment_mean(d, ids)), [1.5, 3.5])
        np.testing.assert_allclose(np.asarray(inc.segment_max(d, ids)), [2, 4])
        np.testing.assert_allclose(np.asarray(inc.segment_min(d, ids)), [1, 3])

    def test_khop_sampler_reference_tuple_shape(self):
        import paddle_tpu.incubate as inc
        row = jnp.asarray([1, 2, 0, 0])
        colptr = jnp.asarray([0, 2, 3, 4])
        # the reference docstring unpack: 4 values (regression: was 3)
        es, ed, sample_index, reindex_nodes = inc.graph_khop_sampler(
            row, colptr, jnp.asarray([0]), [2])
        assert int(np.asarray(sample_index)[0]) == 0   # inputs lead
        assert int(np.asarray(reindex_nodes)[0]) == 0
        # with eids: 5 values
        out = inc.graph_khop_sampler(row, colptr, jnp.asarray([0]), [2],
                                     return_eids=True)
        assert len(out) == 5

    def test_sample_neighbors_reference_positional_order(self):
        import paddle_tpu.incubate as inc
        row = jnp.asarray([1, 2, 0, 0])
        colptr = jnp.asarray([0, 2, 3, 4])
        # reference order: (row, colptr, nodes, eids, perm_buffer, size)
        out, cnt = inc.graph_sample_neighbors(
            row, colptr, jnp.asarray([0]), None, None, 1)
        assert int(cnt[0]) == 1 and len(np.asarray(out)) == 1

    def test_graph_reindex_first_seen_order(self):
        import paddle_tpu.incubate as inc
        rn, rd, nodes = inc.graph_reindex(
            jnp.asarray([5, 9]), jnp.asarray([9, 7, 5]), jnp.asarray([2, 1]))
        np.testing.assert_array_equal(np.asarray(nodes), [5, 9, 7])
        np.testing.assert_array_equal(np.asarray(rn), [1, 2, 0])
        np.testing.assert_array_equal(np.asarray(rd), [0, 0, 1])

    def test_softmax_mask_fuse(self):
        import paddle_tpu.incubate as inc
        x = jnp.ones((2, 4))
        m = jnp.asarray([[0., 0., -1e9, -1e9]] * 2)
        out = np.asarray(inc.softmax_mask_fuse(x, m))
        np.testing.assert_allclose(out[:, :2], 0.5, rtol=1e-5)
        np.testing.assert_allclose(out[:, 2:], 0.0, atol=1e-6)


class TestCompatRegressions:
    def test_default_group_zero_exists(self):
        import paddle_tpu.distributed as dist
        g = dist.get_group()          # regression: raised before
        assert g.id == 0 and g.nranks >= 1

    def test_selu_layer_honors_params(self):
        from paddle_tpu import nn
        x = jnp.asarray(np.linspace(-2, 2, 9, dtype=np.float32))
        assert not np.allclose(np.asarray(nn.SELU(scale=2.0)(x)),
                               np.asarray(nn.SELU()(x)))
        with pytest.raises(TypeError):
            nn.SELU(1.0, 2.0, 3.0)
        with pytest.raises(TypeError):
            nn.Silu(bogus=1)

    def test_adaptive_max_pool_return_mask_rejected(self):
        from paddle_tpu import nn
        with pytest.raises(Exception, match="return_mask"):
            nn.AdaptiveMaxPool1D(4, return_mask=True)

    def test_image_load_cv2_is_bgr(self, tmp_path):
        import paddle_tpu.vision as pv
        from PIL import Image
        p = str(tmp_path / "red.png")
        Image.fromarray(np.dstack([
            np.full((2, 2), 200, np.uint8),
            np.zeros((2, 2), np.uint8),
            np.zeros((2, 2), np.uint8)])).save(p)
        bgr = pv.image_load(p, backend="cv2")
        assert bgr[0, 0, 2] == 200 and bgr[0, 0, 0] == 0
