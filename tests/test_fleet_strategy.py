"""DistributedStrategy flags must change program behavior (reference
fleet meta-optimizers: amp_optimizer.py, gradient_merge_optimizer.py,
recompute_optimizer.py, sharding_optimizer.py — composed by
fleet_base.py:875/:932).  One test per flag, plus a ported reference-style
fleet script end-to-end."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer as opt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.optimizer import HybridParallelOptimizer

R = np.random.RandomState(0)


def _mlp():
    pt.seed(7)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16))


def _init_fleet(**flags):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1}
    for k, v in flags.items():
        setattr(strategy, k, v)
    fleet.init(is_collective=True, strategy=strategy)
    return strategy


class TestAmpFlag:
    def test_model_forward_runs_in_bf16(self):
        _init_fleet(amp=True)
        model = fleet.distributed_model(_mlp())
        y = model(jnp.asarray(R.rand(4, 16), jnp.float32))
        assert y.dtype == jnp.bfloat16          # O1 white-listed matmul
        # without the flag the same model stays fp32
        _init_fleet()
        model2 = fleet.distributed_model(_mlp())
        y2 = model2(jnp.asarray(R.rand(4, 16), jnp.float32))
        assert y2.dtype == jnp.float32

    def test_fp16_scaler_skips_nonfinite_and_decays_scale(self):
        strategy = _init_fleet(amp=True)
        strategy.amp_configs = {"dtype": "float16",
                                "init_loss_scaling": 1024.0,
                                "decr_every_n_nan_or_inf": 1}
        o = fleet.distributed_optimizer(opt.SGD(learning_rate=0.1), strategy)
        assert isinstance(o, HybridParallelOptimizer)
        assert o.scaler.is_enable()
        params = {"w": jnp.ones((4,), jnp.float32)}
        st = o.init(params)
        assert float(st["amp"]["scale"]) == 1024.0
        # scaled grads (the fleet contract: loss was multiplied by scale)
        good = {"w": jnp.full((4,), 1024.0)}
        p1, st = o.apply_gradients(good, params, st)
        np.testing.assert_allclose(np.asarray(p1["w"]), 1.0 - 0.1 * 1.0)
        bad = {"w": jnp.asarray([jnp.inf, 1.0, 1.0, 1.0], jnp.float32)}
        p2, st = o.apply_gradients(bad, p1, st)
        # nonfinite step: params untouched, scale halved, inner step frozen
        np.testing.assert_array_equal(np.asarray(p2["w"]), np.asarray(p1["w"]))
        assert float(st["amp"]["scale"]) == 512.0
        assert int(st["inner"]["step"]) == 1

    def test_scale_loss_helper(self):
        strategy = _init_fleet(amp=True)
        strategy.amp_configs = {"dtype": "float16"}
        o = fleet.distributed_optimizer(opt.SGD(learning_rate=0.1), strategy)
        st = o.init({"w": jnp.ones((2,))})
        assert float(o.scale_loss(jnp.asarray(2.0), st)) == \
            2.0 * float(st["amp"]["scale"])


class TestRecomputeFlag:
    @staticmethod
    def _blocked():
        # block granularity is what the reference checkpoints; a block's
        # inner activations are recomputable so remat must drop them
        pt.seed(7)
        blk = lambda: nn.Sequential(nn.Linear(16, 32), nn.Tanh(),  # noqa
                                    nn.Linear(32, 16), nn.Tanh())
        return nn.Sequential(blk(), blk())

    def test_fewer_residuals_saved(self):
        from jax._src.ad_checkpoint import saved_residuals
        _init_fleet()
        plain = fleet.distributed_model(self._blocked())
        _init_fleet(recompute=True)
        rc = fleet.distributed_model(self._blocked())
        x = jnp.asarray(R.rand(4, 16), jnp.float32)

        def loss(m):
            sd = m.state_dict()
            return lambda p, xx: jnp.sum(m.apply(p, xx) ** 2), sd

        f_plain, sd = loss(plain)
        f_rc, sd_rc = loss(rc)
        n_plain = len(saved_residuals(f_plain, sd, x))
        n_rc = len(saved_residuals(f_rc, sd_rc, x))
        assert n_rc < n_plain, (n_rc, n_plain)
        # and the numerics are identical
        np.testing.assert_allclose(np.asarray(plain(x)), np.asarray(rc(x)),
                                   rtol=1e-6)
        g1 = jax.grad(f_plain)(sd, x)
        g2 = jax.grad(f_rc)(sd_rc, x)
        for k in g1:
            np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                       rtol=1e-5, atol=1e-6)

    def test_gpt_native_flag_flipped(self):
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        _init_fleet(recompute=True)
        m = fleet.distributed_model(GPTForCausalLM(gpt_tiny()))
        target = m.model if hasattr(m, "model") else m
        assert any(getattr(l, "_use_recompute", False)
                   for l in target.sublayers(include_self=True))


class TestGradientMergeFlag:
    def test_k_step_accumulation_matches_mean_grad(self):
        strategy = _init_fleet(gradient_merge=True)
        strategy.gradient_merge_configs = {"k_steps": 3, "avg": True}
        o = fleet.distributed_optimizer(opt.SGD(learning_rate=0.5), strategy)
        params = {"w": jnp.ones((4,), jnp.float32)}
        st = o.init(params)
        gs = [jnp.full((4,), float(i + 1)) for i in range(3)]
        p = params
        for i, g in enumerate(gs):
            p, st = o.apply_gradients({"w": g}, p, st)
            if i < 2:   # no update until the k-th micro step
                np.testing.assert_array_equal(np.asarray(p["w"]),
                                              np.asarray(params["w"]))
        want = 1.0 - 0.5 * float(np.mean([1.0, 2.0, 3.0]))
        np.testing.assert_allclose(np.asarray(p["w"]), want, rtol=1e-6)
        assert int(st["inner"]["step"]) == 1      # ONE real optimizer step
        assert int(st["gm"]["step"]) == 0         # counter reset

    def test_jit_safe(self):
        strategy = _init_fleet(gradient_merge=True)
        strategy.gradient_merge_configs = {"k_steps": 2}
        o = fleet.distributed_optimizer(opt.Adam(learning_rate=1e-2),
                                        strategy)
        params = {"w": jnp.ones((8,), jnp.float32)}
        st = o.init(params)

        @jax.jit
        def step(g, p, s):
            return o.apply_gradients(g, p, s)

        p = params
        for _ in range(4):
            p, st = step({"w": jnp.ones((8,))}, p, st)
        assert int(st["inner"]["step"]) == 2


class TestShardingFlag:
    def test_optimizer_state_sharded_over_dp(self):
        strategy = _init_fleet(sharding=True)
        o = fleet.distributed_optimizer(opt.Adam(learning_rate=1e-3),
                                        strategy)
        params = {"w": jnp.ones((16, 32), jnp.float32)}
        st = o.init(params)
        spec = st["inner"]["slots"]["w"]["moment1"].sharding.spec
        assert "dp" in tuple(spec), spec
        # without the flag: replicated
        _init_fleet()
        o2 = fleet.distributed_optimizer(opt.Adam(learning_rate=1e-3))
        st2 = o2.init(params)
        assert not isinstance(o2, HybridParallelOptimizer)
        assert getattr(st2["slots"]["w"]["moment1"].sharding, "spec",
                       ()) == ()  # single-device / replicated


class TestStatefulPath:
    def test_step_keeps_sharded_state(self):
        strategy = _init_fleet(sharding=True)
        pt.seed(7)
        lin = nn.Linear(16, 32)
        o = fleet.distributed_optimizer(
            opt.Adam(learning_rate=1e-3, parameters=lin.parameters()),
            strategy)
        g = [jnp.ones_like(p.value) for p in lin.parameters()]
        o.step(g)
        spec = o._hp_state["inner"]["slots"]["weight"]["moment1"].sharding
        assert "dp" in tuple(getattr(spec, "spec", ())), spec

    def test_state_dict_round_trips_scaler_and_gm(self):
        strategy = _init_fleet(amp=True, gradient_merge=True)
        strategy.amp_configs = {"dtype": "float16",
                                "init_loss_scaling": 1024.0,
                                "decr_every_n_nan_or_inf": 1}
        strategy.gradient_merge_configs = {"k_steps": 3}
        pt.seed(7)
        lin = nn.Linear(4, 4)
        o = fleet.distributed_optimizer(
            opt.SGD(learning_rate=0.1, parameters=lin.parameters()),
            strategy)
        bad = [jnp.full_like(p.value, jnp.inf) for p in lin.parameters()]
        o.step(bad)                                   # scale 1024 -> 512
        good = [jnp.full_like(p.value, 1024.0) for p in lin.parameters()]
        o.step(good)                                  # gm buffer non-empty
        assert float(o._hp_state["amp"]["scale"]) == 512.0
        sd = o.state_dict()
        assert "hybrid" in sd

        pt.seed(7)
        lin2 = nn.Linear(4, 4)
        o2 = fleet.distributed_optimizer(
            opt.SGD(learning_rate=0.1, parameters=lin2.parameters()),
            strategy)
        o2.set_state_dict(sd)
        assert float(o2._hp_state["amp"]["scale"]) == 512.0
        assert int(o2._hp_state["gm"]["step"]) == 1   # 1 accumulated step
        buf = o2._hp_state["gm"]["buf"]
        assert any(float(jnp.abs(v).max()) > 0
                   for v in jax.tree_util.tree_leaves(buf))


class TestRecomputeNesting:
    def test_outermost_container_only(self):
        _init_fleet(recompute=True)
        m = fleet.distributed_model(TestRecomputeFlag._blocked())
        blocks = list(m._sub_layers.values())
        assert all(getattr(b, "_fleet_recompute", False) for b in blocks)
        for b in blocks:   # leaves inside a wrapped block stay unwrapped
            assert not any(getattr(c, "_fleet_recompute", False)
                           for c in b._sub_layers.values())


class TestPortedFleetScript:
    def test_reference_style_script_trains(self):
        """The reference dygraph fleet recipe, ported verbatim: strategy
        flags → init → distributed_model/optimizer → scale/step loop."""
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                                   "pp_degree": 1}
        strategy.amp = True
        strategy.amp_configs = {"dtype": "float16",
                                "init_loss_scaling": 256.0}
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 2}
        strategy.recompute = True
        fleet.init(is_collective=True, strategy=strategy)

        model = fleet.distributed_model(_mlp())
        optimizer = fleet.distributed_optimizer(
            opt.Adam(learning_rate=5e-2), strategy)

        sd = model.state_dict()
        st = optimizer.init(sd)
        x = jnp.asarray(R.rand(32, 16), jnp.float32)
        y = jnp.asarray(R.rand(32, 16), jnp.float32)

        @jax.jit
        def train_step(p, s, xb, yb):
            def loss_fn(pp):
                out = model.apply(pp, xb).astype(jnp.float32)
                return optimizer.scale_loss(jnp.mean((out - yb) ** 2), s)
            scaled, grads = jax.value_and_grad(loss_fn)(p)
            newp, news = optimizer.apply_gradients(grads, p, s)
            # unscale with the PRE-update scale the loss was multiplied by
            return scaled / s["amp"]["scale"], newp, news

        losses = []
        p = sd
        for _ in range(40):
            loss, p, st = train_step(p, st, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::8]


class TestPortedImportPaths:
    def test_meta_parallel_and_utils_paths(self):
        """The reference's canonical import paths for hybrid scripts."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear,
            VocabParallelEmbedding, get_rng_state_tracker)
        from paddle_tpu.distributed.fleet.utils import recompute
        from paddle_tpu.distributed import mp_layers
        assert ColumnParallelLinear is mp_layers.ColumnParallelLinear
        assert RowParallelLinear is mp_layers.RowParallelLinear
        assert VocabParallelEmbedding is mp_layers.VocabParallelEmbedding
        assert callable(get_rng_state_tracker) and callable(recompute)
        # grad-sync helpers are accepted no-ops under GSPMD
        from paddle_tpu.distributed.fleet.utils import (
            broadcast_dp_parameters, fused_allreduce_gradients)
        assert fused_allreduce_gradients([], None) is None
        assert broadcast_dp_parameters(None, None) is None


class TestFleetFacadeCompat:
    """Reference fleet __all__ tail: Fleet class, UtilBase, role makers,
    data generators (round 5)."""

    def test_fleet_class_delegates_to_module(self):
        from paddle_tpu.distributed import fleet
        f = fleet.Fleet()
        assert f.init is fleet.init
        assert isinstance(f.util, fleet.UtilBase)

    def test_role_maker_identity(self):
        from paddle_tpu.distributed import fleet
        rm = fleet.PaddleCloudRoleMaker(is_collective=True)
        assert rm.worker_index() == 0 and rm.worker_num() == 1
        assert rm.is_first_worker() and rm._server_num() == 0
        assert fleet.Role.WORKER == 1

    def test_util_base_single_process(self):
        from paddle_tpu.distributed import fleet
        u = fleet.UtilBase()
        np.testing.assert_allclose(u.all_reduce(np.ones(3)), np.ones(3))
        assert u.all_gather(1)[0] == 1
        assert u.get_file_shard(["a", "b"]) == ["a", "b"]
        u.barrier()

    def test_multi_slot_data_generator_line_protocol(self):
        from paddle_tpu.distributed import fleet

        class Gen(fleet.MultiSlotDataGenerator):
            def generate_sample(self, line):
                def g():
                    ws = line.split()
                    yield [("len", [len(w) for w in ws]), ("label", [1])]
                return g

        assert Gen().run_from_memory(["ab cde"]) == ["2 2 3 1 1"]
