"""OpTest parity for the extended tensor corpus (tensor_ops.py + linalg.py):
numpy-reference forward checks and finite-difference gradient checks on the
differentiable members (reference doctrine: unittests/op_test.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from op_test import check_grad, check_output

R = np.random.RandomState(7)


class TestMathOps:
    def test_amax_amin(self):
        x = R.randn(3, 4).astype(np.float32)
        check_output(lambda a: pt.amax(a, axis=1), lambda a: a.max(1), [x])
        check_output(lambda a: pt.amin(a, axis=0), lambda a: a.min(0), [x])

    def test_addmm(self):
        i, a, b = (R.randn(3, 5).astype(np.float32),
                   R.randn(3, 4).astype(np.float32),
                   R.randn(4, 5).astype(np.float32))
        check_output(lambda i, a, b: pt.addmm(i, a, b, beta=0.5, alpha=2.0),
                     lambda i, a, b: 0.5 * i + 2.0 * (a @ b), [i, a, b])
        check_grad(lambda i, a, b: pt.addmm(i, a, b), [i, a, b],
                   wrt=(0, 1, 2))

    def test_deg2rad_rad2deg_roundtrip(self):
        x = R.randn(8).astype(np.float32) * 180
        np.testing.assert_allclose(
            np.asarray(pt.rad2deg(pt.deg2rad(x))), x, rtol=1e-5)

    def test_lerp(self):
        x, y = R.randn(4).astype(np.float32), R.randn(4).astype(np.float32)
        check_output(lambda x, y: pt.lerp(x, y, 0.3),
                     lambda x, y: x + 0.3 * (y - x), [x, y])
        check_grad(lambda x, y: pt.lerp(x, y, 0.3), [x, y], wrt=(0, 1))

    def test_logit_inverts_sigmoid(self):
        p = np.clip(R.rand(16).astype(np.float32), 0.05, 0.95)
        np.testing.assert_allclose(
            np.asarray(pt.sigmoid(pt.logit(p))), p, rtol=1e-4, atol=1e-5)

    def test_logsumexp(self):
        x = R.randn(3, 4).astype(np.float32)
        check_output(lambda a: pt.logsumexp(a, axis=1),
                     lambda a: np.log(np.sum(np.exp(a), axis=1)), [x])
        check_grad(lambda a: pt.logsumexp(a, axis=1), [x])

    def test_nan_reductions(self):
        x = np.asarray([[1.0, np.nan, 3.0], [np.nan, 5.0, 6.0]], np.float32)
        np.testing.assert_allclose(np.asarray(pt.nanmean(x, axis=1)),
                                   [2.0, 5.5])
        np.testing.assert_allclose(np.asarray(pt.nansum(x)), 15.0)
        np.testing.assert_allclose(np.asarray(pt.nanmedian(x, axis=1)),
                                   [2.0, 5.5])

    def test_trace_diag_family(self):
        x = R.randn(4, 4).astype(np.float32)
        check_output(pt.trace, np.trace, [x])
        check_output(lambda a: pt.diagonal(a, offset=1),
                     lambda a: np.diagonal(a, offset=1), [x])
        v = R.randn(3).astype(np.float32)
        check_output(pt.diagflat, np.diagflat, [v])

    def test_scale(self):
        x = R.randn(5).astype(np.float32)
        check_output(lambda a: pt.scale(a, scale=2.0, bias=1.0),
                     lambda a: a * 2 + 1, [x])
        check_output(
            lambda a: pt.scale(a, scale=2.0, bias=1.0,
                               bias_after_scale=False),
            lambda a: (a + 1) * 2, [x])

    def test_misc_elementwise(self):
        x = R.randn(6).astype(np.float32)
        y = R.randn(6).astype(np.float32)
        check_output(pt.hypot, np.hypot, [x, y])
        check_output(pt.copysign, np.copysign, [x, y])
        check_output(pt.frac, lambda a: a - np.trunc(a), [x])
        check_output(pt.stanh,
                     lambda a: 1.7159 * np.tanh(0.67 * a), [x])
        ints = R.randint(1, 30, (6,))
        jnts = R.randint(1, 30, (6,))
        check_output(pt.gcd, np.gcd, [ints, jnts])
        check_output(pt.lcm, np.lcm, [ints, jnts])


class TestComplexOps:
    def test_complex_roundtrip(self):
        re = R.randn(4).astype(np.float32)
        im = R.randn(4).astype(np.float32)
        c = pt.complex(re, im)
        assert pt.is_complex(c)
        np.testing.assert_allclose(np.asarray(pt.real(c)), re)
        np.testing.assert_allclose(np.asarray(pt.imag(c)), im)
        packed = pt.as_real(c)
        np.testing.assert_allclose(np.asarray(pt.as_complex(packed)),
                                   np.asarray(c))

    def test_angle_conj(self):
        c = np.asarray([1 + 1j, -1 + 0j], np.complex64)
        check_output(pt.angle, np.angle, [c])
        check_output(pt.conj, np.conj, [c])


class TestLinalg:
    def test_solve_det_inv(self):
        a = (R.randn(4, 4) + 4 * np.eye(4)).astype(np.float32)
        b = R.randn(4, 2).astype(np.float32)
        x = np.asarray(pt.linalg.solve(a, b))
        np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.linalg.det(a)),
                                   np.linalg.det(a), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.linalg.inv(a)),
                                   np.linalg.inv(a), rtol=1e-3, atol=1e-5)

    def test_svd_qr_reconstruct(self):
        a = R.randn(5, 3).astype(np.float32)
        u, s, vt = pt.linalg.svd(a)
        np.testing.assert_allclose(
            np.asarray(u) * np.asarray(s) @ np.asarray(vt), a,
            rtol=1e-4, atol=1e-5)
        q, r = pt.linalg.qr(a)
        np.testing.assert_allclose(np.asarray(q) @ np.asarray(r), a,
                                   rtol=1e-4, atol=1e-5)

    def test_cholesky_and_solve(self):
        m = R.randn(4, 4).astype(np.float32)
        a = m @ m.T + 4 * np.eye(4, dtype=np.float32)
        l = pt.linalg.cholesky(a)
        np.testing.assert_allclose(np.asarray(l) @ np.asarray(l).T, a,
                                   rtol=1e-4, atol=1e-4)
        b = R.randn(4, 1).astype(np.float32)
        x = pt.linalg.cholesky_solve(b, l)
        np.testing.assert_allclose(a @ np.asarray(x), b, rtol=1e-3,
                                   atol=1e-3)

    def test_eigh_symmetric(self):
        m = R.randn(4, 4).astype(np.float32)
        a = (m + m.T) / 2
        w, v = pt.linalg.eigh(a)
        np.testing.assert_allclose(
            a @ np.asarray(v), np.asarray(v) * np.asarray(w),
            rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.sort(np.asarray(pt.linalg.eigvalsh(a))),
                                   np.sort(np.linalg.eigvalsh(a)),
                                   rtol=1e-4, atol=1e-5)

    def test_matrix_power_rank_pinv(self):
        a = (R.randn(3, 3) + 3 * np.eye(3)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(pt.linalg.matrix_power(a, 3)),
                                   a @ a @ a, rtol=1e-3, atol=1e-3)
        lowrank = np.outer(R.randn(4), R.randn(4)).astype(np.float32)
        assert int(pt.linalg.matrix_rank(lowrank, tol=1e-4)) == 1
        # hermitian=True must count negative eigenvalues by magnitude
        q, _ = np.linalg.qr(R.randn(4, 4))
        herm = (q @ np.diag([3.0, -2.0, 1e-6, 0.0]) @ q.T).astype(np.float32)
        herm = (herm + herm.T) / 2
        assert int(pt.linalg.matrix_rank(herm, tol=1e-3,
                                         hermitian=True)) == 2
        p = np.asarray(pt.linalg.pinv(lowrank, rcond=1e-5))  # f32 noise floor
        np.testing.assert_allclose(lowrank @ p @ lowrank, lowrank,
                                   rtol=1e-3, atol=1e-3)

    def test_multi_dot_slogdet_cond(self):
        a, b, c = (R.randn(2, 3).astype(np.float32),
                   R.randn(3, 4).astype(np.float32),
                   R.randn(4, 2).astype(np.float32))
        np.testing.assert_allclose(np.asarray(pt.linalg.multi_dot([a, b, c])),
                                   a @ b @ c, rtol=1e-4, atol=1e-5)
        m = (R.randn(3, 3) + 3 * np.eye(3)).astype(np.float32)
        out = np.asarray(pt.linalg.slogdet(m))
        sign, logabs = np.linalg.slogdet(m)
        np.testing.assert_allclose(out[0], sign, rtol=1e-4)
        np.testing.assert_allclose(out[1], logabs, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.linalg.cond(m)),
                                   np.linalg.cond(m), rtol=1e-3)

    def test_triangular_solve_lstsq(self):
        a = np.triu(R.randn(3, 3).astype(np.float32) + 2 * np.eye(3, dtype=np.float32))
        b = R.randn(3, 2).astype(np.float32)
        x = np.asarray(pt.linalg.triangular_solve(a, b, upper=True))
        np.testing.assert_allclose(a @ x, b, rtol=1e-3, atol=1e-4)
        a2 = R.randn(6, 3).astype(np.float32)
        b2 = R.randn(6, 1).astype(np.float32)
        sol = np.asarray(pt.linalg.lstsq(a2, b2)[0])
        ref = np.linalg.lstsq(a2, b2, rcond=None)[0]
        np.testing.assert_allclose(sol, ref, rtol=1e-3, atol=1e-4)


class TestManipulation:
    def test_moveaxis_rot90_unbind(self):
        x = R.randn(2, 3, 4).astype(np.float32)
        check_output(lambda a: pt.moveaxis(a, 0, 2),
                     lambda a: np.moveaxis(a, 0, 2), [x])
        check_output(lambda a: pt.rot90(a, k=1, axes=(1, 2)),
                     lambda a: np.rot90(a, 1, (1, 2)), [x])
        parts = pt.unbind(x, axis=1)
        assert len(parts) == 3 and parts[0].shape == (2, 4)
        np.testing.assert_allclose(np.asarray(parts[1]), x[:, 1])

    def test_repeat_interleave_expand_as(self):
        x = np.asarray([[1, 2], [3, 4]], np.float32)
        check_output(lambda a: pt.repeat_interleave(a, 2, axis=1),
                     lambda a: np.repeat(a, 2, axis=1), [x])
        y = np.zeros((3, 2, 2), np.float32)
        assert pt.expand_as(x, y).shape == (3, 2, 2)

    def test_put_along_axis_modes(self):
        x = np.zeros((2, 3), np.float32)
        idx = np.asarray([[0], [2]])
        out = np.asarray(pt.put_along_axis(x, idx, 5.0, axis=1))
        assert out[0, 0] == 5.0 and out[1, 2] == 5.0 and out.sum() == 10.0
        out2 = np.asarray(pt.put_along_axis(out, idx, 1.0, axis=1,
                                            reduce="add"))
        assert out2[0, 0] == 6.0

    def test_index_sample_multiplex(self):
        x = R.randn(3, 5).astype(np.float32)
        idx = R.randint(0, 5, (3, 2))
        out = np.asarray(pt.index_sample(x, idx))
        for i in range(3):
            np.testing.assert_allclose(out[i], x[i, idx[i]])
        a, b = (R.randn(4, 2).astype(np.float32),
                R.randn(4, 2).astype(np.float32))
        sel = np.asarray([0, 1, 0, 1])
        out = np.asarray(pt.multiplex([a, b], sel))
        np.testing.assert_allclose(out[0], a[0])
        np.testing.assert_allclose(out[1], b[1])

    def test_unique_consecutive(self):
        x = np.asarray([1, 1, 2, 2, 2, 3, 1, 1])
        out, inv, counts = pt.unique_consecutive(
            x, return_inverse=True, return_counts=True)
        np.testing.assert_array_equal(np.asarray(out), [1, 2, 3, 1])
        np.testing.assert_array_equal(np.asarray(counts), [2, 3, 1, 2])
        np.testing.assert_array_equal(np.asarray(out)[np.asarray(inv)], x)

    def test_meshgrid_broadcast_helpers(self):
        a, b = np.arange(3), np.arange(4)
        gx, gy = pt.meshgrid(a, b)
        assert gx.shape == gy.shape == (3, 4)
        assert pt.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
        outs = pt.broadcast_tensors([np.zeros((2, 1)), np.zeros((1, 3))])
        assert outs[0].shape == outs[1].shape == (2, 3)

    def test_renorm_caps_norms(self):
        x = R.randn(4, 8).astype(np.float32) * 10
        out = np.asarray(pt.renorm(x, p=2.0, axis=0, max_norm=1.0))
        norms = np.linalg.norm(out, axis=1)
        assert np.all(norms <= 1.0 + 1e-4)

    def test_as_strided_view(self):
        x = np.arange(12, dtype=np.float32)
        out = np.asarray(pt.as_strided(x, (3, 4), (4, 1)))
        np.testing.assert_allclose(out, x.reshape(3, 4))
        assert pt.view(x, [4, 3]).shape == (4, 3)
        assert pt.tolist(np.asarray([1, 2])) == [1, 2]


class TestSearchSort:
    def test_kthvalue_median_quantile(self):
        x = R.randn(3, 7).astype(np.float32)
        val, idx = pt.kthvalue(x, 3, axis=1)
        np.testing.assert_allclose(np.asarray(val),
                                   np.sort(x, axis=1)[:, 2])
        np.testing.assert_allclose(np.asarray(pt.median(x, axis=1)),
                                   np.median(x, axis=1), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pt.quantile(x, 0.5, axis=1)),
                                   np.quantile(x, 0.5, axis=1), rtol=1e-5)

    def test_mode(self):
        x = np.asarray([[1, 3, 3, 2], [2, 2, 1, 1]], np.float32)
        val, idx = pt.mode(x, axis=-1)
        np.testing.assert_allclose(np.asarray(val), [3.0, 2.0])

    def test_searchsorted_bucketize(self):
        edges = np.asarray([1.0, 3.0, 5.0, 7.0], np.float32)
        vals = np.asarray([0.5, 3.0, 6.0, 9.0], np.float32)
        np.testing.assert_array_equal(
            np.asarray(pt.searchsorted(edges, vals)), [0, 1, 3, 4])
        np.testing.assert_array_equal(
            np.asarray(pt.searchsorted(edges, vals, right=True)),
            [0, 2, 3, 4])
        np.testing.assert_array_equal(
            np.asarray(pt.bucketize(vals, edges)), [0, 1, 3, 4])

    def test_histogram_bincount(self):
        x = np.asarray([0.1, 0.4, 0.6, 0.9, 0.4], np.float32)
        counts = np.asarray(pt.histogram(x, bins=2, min=0.0, max=1.0))
        np.testing.assert_array_equal(counts, [3, 2])
        ints = np.asarray([0, 1, 1, 3])
        np.testing.assert_array_equal(np.asarray(pt.bincount(ints)),
                                      [1, 2, 0, 1])


class TestLinalgAdjacent:
    def test_cross_inner_kron_mv(self):
        a = R.randn(3).astype(np.float32)
        b = R.randn(3).astype(np.float32)
        check_output(pt.cross, np.cross, [a, b])
        check_output(pt.inner, np.inner, [a, b])
        m = R.randn(2, 2).astype(np.float32)
        check_output(pt.kron, np.kron, [m, m])
        check_output(pt.mv, lambda m, v: m @ v,
                     [R.randn(3, 4).astype(np.float32),
                      R.randn(4).astype(np.float32)])

    def test_dist_tensordot(self):
        x = R.randn(3, 4).astype(np.float32)
        y = R.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(np.asarray(pt.dist(x, y, 2)),
                                   np.linalg.norm((x - y).ravel()),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pt.dist(x, y, float("inf"))),
            np.max(np.abs(x - y)), rtol=1e-6)
        a = R.randn(2, 3, 4).astype(np.float32)
        b = R.randn(3, 4, 5).astype(np.float32)
        check_output(lambda a, b: pt.tensordot(a, b, axes=2),
                     lambda a, b: np.tensordot(a, b, axes=2), [a, b],
                     rtol=1e-4, atol=1e-4)


class TestRandomOps:
    def test_multinomial_respects_support(self):
        pt.seed(0)
        probs = np.asarray([0.0, 0.3, 0.7], np.float32)
        draws = np.asarray(pt.multinomial(probs, 64, replacement=True))
        assert draws.shape == (64,)
        assert set(np.unique(draws)).issubset({1, 2})
        noreplace = np.asarray(pt.multinomial(probs + 0.1, 3,
                                              replacement=False))
        assert sorted(noreplace.tolist()) == [0, 1, 2]

    def test_multinomial_batched(self):
        pt.seed(5)
        probs = np.tile(np.asarray([0.0, 0.5, 0.5], np.float32), (4, 1))
        draws = np.asarray(pt.multinomial(probs, 6, replacement=True))
        assert draws.shape == (4, 6)
        assert set(np.unique(draws)).issubset({1, 2})

    def test_linalg_norm_any_rank_default(self):
        x = R.randn(2, 3, 4).astype(np.float32)
        np.testing.assert_allclose(np.asarray(pt.linalg.norm(x)),
                                   np.linalg.norm(x.ravel()), rtol=1e-5)
        v = R.randn(5).astype(np.float32)
        np.testing.assert_allclose(np.asarray(pt.linalg.norm(v)),
                                   np.linalg.norm(v), rtol=1e-6)

    def test_scale_applies_activation(self):
        x = np.asarray([-2.0, 0.5], np.float32)
        out = np.asarray(pt.scale(x, scale=2.0, act="relu"))
        np.testing.assert_allclose(out, [0.0, 1.0])

    def test_cross_without_3dim_raises(self):
        with pytest.raises(Exception):
            pt.cross(np.ones((2, 4), np.float32),
                     np.ones((2, 4), np.float32))

    def test_randint_like_matches_dtype(self):
        ref = np.zeros((2, 2), np.float32)
        out = pt.randint_like(ref, 5)
        assert out.dtype == jnp.float32

    def test_standard_normal_poisson_randint_like(self):
        pt.seed(1)
        z = np.asarray(pt.standard_normal((2000,)))
        assert abs(z.mean()) < 0.1 and abs(z.std() - 1.0) < 0.1
        lam = np.full((2000,), 4.0, np.float32)
        p = np.asarray(pt.poisson(lam))
        assert abs(p.mean() - 4.0) < 0.3
        ref = np.zeros((3, 3), np.float32)
        ri = np.asarray(pt.randint_like(ref, 5))
        assert ri.shape == (3, 3) and ri.min() >= 0 and ri.max() < 5
        e = np.asarray(pt.exponential(np.zeros(2000, np.float32), lam=2.0))
        assert abs(e.mean() - 0.5) < 0.1
