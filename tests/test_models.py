"""ResNet + BERT model tests (BASELINE configs #2/#3).

The reference's model-zoo tests (python/paddle/tests/test_vision_models.py
doctrine) check construction + forward shapes; here we add the golden-loss
training check and, for BERT, the TP parallel == serial invariant."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.framework import random as fw_random


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.set_hybrid_communicate_group(None)


class TestResNet:
    def test_forward_shapes_all_depths(self):
        from paddle_tpu.vision.models import (resnet18, resnet50,
                                              wide_resnet50_2)
        pt.seed(0)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 64, 64),
                        jnp.float32)
        for ctor, feat in ((resnet18, 512), (resnet50, 2048)):
            m = ctor(num_classes=10)
            m.eval()
            out = m(x)
            assert out.shape == (2, 10), (ctor.__name__, out.shape)
        m = wide_resnet50_2(num_classes=0, with_pool=True)
        m.eval()
        assert m(x).shape == (2, 2048, 1, 1)

    def test_resnet18_trains_on_toy_batch(self):
        from paddle_tpu.vision.models import resnet18
        pt.seed(1)
        model = resnet18(num_classes=4)
        model.train()
        params = model.state_dict()
        opt = pt.optimizer.Adam(learning_rate=1e-3)
        state = opt.init(params)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 3, 32, 32), jnp.float32)
        y = jnp.asarray(rng.randint(0, 4, (8,)), jnp.int32)

        buf_names = {name for name, _ in model.named_buffers()}

        def step(p, s):
            def loss_fn(q):
                out, newvars = model.apply(q, x, mutable=True)
                loss = jnp.mean(pt.nn.functional.cross_entropy(out, y))
                return loss, newvars
            (loss, newvars), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(p)
            p2, s2 = opt.apply_gradients(grads, p, s)
            # fold updated BN running stats back into the train state
            # (type-preserving: the optimizer state treedef is OrderedDict)
            for k in buf_names:
                p2[k] = newvars[k]
            return loss, p2, s2

        jitted = jax.jit(step)
        losses = []
        for _ in range(6):
            loss, params, state = jitted(params, state)
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], losses

    def test_batchnorm_running_stats_update(self):
        from paddle_tpu.vision.models import resnet18
        pt.seed(2)
        model = resnet18(num_classes=4)
        model.train()
        params = model.state_dict()
        x = jnp.asarray(np.random.RandomState(1).randn(4, 3, 32, 32) * 3 + 1,
                        jnp.float32)
        _, newvars = model.apply(params, x, mutable=True)
        k = "bn1._mean"
        assert k in newvars
        assert not np.allclose(np.asarray(newvars[k]),
                               np.asarray(params[k]))


class TestBert:
    def _data(self, cfg, B=4, S=32, seed=0):
        rng = np.random.RandomState(seed)
        ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
        types = (rng.rand(B, S) > 0.5).astype(np.int32)
        mask = np.ones((B, S), np.int32)
        mask[:, S - 4:] = 0                      # padded tail
        mlm = np.where(rng.rand(B, S) < 0.15, ids, -100).astype(np.int32)
        nsp = rng.randint(0, 2, (B,)).astype(np.int32)
        return (jnp.asarray(ids), jnp.asarray(types), jnp.asarray(mask),
                jnp.asarray(mlm), jnp.asarray(nsp))

    def test_pretraining_forward_and_loss(self):
        from paddle_tpu.models import BertForPretraining, bert_tiny
        pt.seed(3)
        cfg = bert_tiny()
        model = BertForPretraining(cfg)
        model.eval()
        params = model.state_dict()
        ids, types, mask, mlm, nsp = self._data(cfg)
        logits, nsp_logits = model.apply(params, ids, types, mask)
        assert logits.shape == (4, 32, cfg.vocab_size)
        assert nsp_logits.shape == (4, 2)
        loss, _ = model.apply(params, ids, types, mask, mlm_labels=mlm,
                              nsp_labels=nsp)
        assert np.isfinite(float(loss))

    def test_pretraining_loss_decreases(self):
        from paddle_tpu.models import BertForPretraining, bert_tiny
        pt.seed(4)
        cfg = bert_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        model = BertForPretraining(cfg)
        model.train()
        params = model.state_dict()
        opt = pt.optimizer.AdamW(learning_rate=1e-3)
        state = opt.init(params)
        ids, types, mask, mlm, nsp = self._data(cfg)

        def step(p, s, key):
            def loss_fn(q):
                with fw_random.key_scope(key):
                    loss, _ = model.apply(q, ids, types, mask,
                                          mlm_labels=mlm, nsp_labels=nsp)
                return loss
            loss, grads = jax.value_and_grad(loss_fn)(p)
            p2, s2 = opt.apply_gradients(grads, p, s)
            return loss, p2, s2

        jitted = jax.jit(step)
        losses = []
        for i in range(5):
            loss, params, state = jitted(
                params, state, jax.random.fold_in(jax.random.key(0), i))
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], losses

    @pytest.mark.skipif(jax.device_count() < 8,
                        reason="needs the 8-device CPU mesh")
    def test_tp_parallel_matches_serial(self):
        from paddle_tpu.models import BertForPretraining, bert_tiny
        pt.seed(5)
        cfg = bert_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        model = BertForPretraining(cfg)
        model.eval()
        params = model.state_dict()
        ids, types, mask, mlm, nsp = self._data(cfg)
        loss_s, _ = model.apply(params, ids, types, mask, mlm_labels=mlm,
                                nsp_labels=nsp)

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        fleet.distributed_model(model)
        params_d = model.state_dict()
        assert params_d[
            "bert.embeddings.word_embeddings.weight"
        ].sharding.spec == P("mp", None)
        loss_p, _ = jax.jit(
            lambda v: model.apply(v, dist.shard_batch(ids),
                                  dist.shard_batch(types),
                                  dist.shard_batch(mask),
                                  mlm_labels=dist.shard_batch(mlm),
                                  nsp_labels=dist.shard_batch(nsp))
        )(params_d)
        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=2e-5)

    def test_sequence_classification(self):
        from paddle_tpu.models import (BertForSequenceClassification,
                                       bert_tiny)
        pt.seed(6)
        cfg = bert_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        model = BertForSequenceClassification(cfg, num_classes=3)
        model.eval()
        params = model.state_dict()
        ids, types, mask, _, _ = self._data(cfg)
        labels = jnp.asarray([0, 1, 2, 1], jnp.int32)
        loss, logits = model.apply(params, ids, types, mask, labels=labels)
        assert logits.shape == (4, 3)
        assert np.isfinite(float(loss))


class TestGPTGenerate:
    def test_pallas_decode_kernel_matches_xla_cache_path(self):
        """Single-token decode through flash_attention_kvcache must produce
        the same greedy continuation as the masked XLA cache path."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        outs = {}
        for pallas in (False, True):
            pt.seed(11)   # identical weights across the two paths
            cfg = GPTConfig(hidden_size=64, num_layers=2, num_heads=4,
                            max_position_embeddings=64, vocab_size=256,
                            hidden_dropout=0.0, attention_dropout=0.0,
                            use_pallas_attention=pallas)
            model = GPTForCausalLM(cfg)
            model.eval()
            prompt = jnp.asarray(
                np.random.RandomState(0).randint(0, 256, (2, 8)), jnp.int32)
            outs[pallas] = np.asarray(
                model.generate(prompt, max_new_tokens=8, temperature=0.0))
        np.testing.assert_array_equal(outs[False], outs[True])

    def test_greedy_matches_full_recompute(self):
        """Incremental static-cache decode == rerunning the full forward at
        every step (the CacheKV correctness invariant)."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        pt.seed(9)
        cfg = GPTConfig(hidden_size=64, num_layers=2, num_heads=4,
                        max_position_embeddings=64, vocab_size=256,
                        hidden_dropout=0.0, attention_dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        rng = np.random.RandomState(0)
        prompt = jnp.asarray(rng.randint(0, 256, (2, 8)), jnp.int32)

        out = model.generate(prompt, max_new_tokens=8, temperature=0.0)
        assert out.shape == (2, 16)

        # naive: full forward each step, argmax last logit
        ids = prompt
        for _ in range(8):
            logits = model(ids)
            nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
            ids = jnp.concatenate([ids, nxt[:, None].astype(jnp.int32)],
                                  axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))

    def test_eos_early_stop_and_sampling(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        pt.seed(10)
        cfg = GPTConfig(hidden_size=32, num_layers=1, num_heads=2,
                        max_position_embeddings=64, vocab_size=64,
                        hidden_dropout=0.0, attention_dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        out = model.generate(prompt, max_new_tokens=20, temperature=1.0,
                             top_k=8, key=jax.random.key(0))
        assert out.shape[1] <= 23
        # deterministic per key
        out2 = model.generate(prompt, max_new_tokens=20, temperature=1.0,
                              top_k=8, key=jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
