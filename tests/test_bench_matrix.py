"""Performance observatory (ISSUE 13): row schema, ledger semantics,
perfdiff attribution, and the CI gate's edge cases."""
import json
import os

import pytest

from paddle_tpu.bench import diff as perfdiff
from paddle_tpu.bench import gate, harness, ledger, schema
from paddle_tpu.utils import fsio


def _mk_row(scenario="gpt_pretrain_fused", mode="smoke", p50=40.0,
            phases=None, **kw):
    """A synthetic but schema-valid row (steady step series around p50)."""
    kw.setdefault("compile_stats",
                  {"wall_ms": 2000.0, "traces": 1, "retraces": 0,
                   "storms": 0, "cache_hits": 3,
                   "persistent_hits": 0, "persistent_requests": 0})
    return schema.new_row(
        scenario, mode,
        step_times_ms=[p50 * 0.98, p50, p50 * 1.02, p50],
        phases_ms=phases or {"data": 1.0, "compute": p50 - 2.0,
                             "readback": 0.5, "collective": 0.5},
        config={"batch": 2},
        tokens_per_sec=1000.0, mfu=0.01,
        bytes_on_wire=0, peak_hbm_bytes=1 << 20, **kw)


# -- schema -----------------------------------------------------------------
def test_new_row_is_schema_valid():
    row = _mk_row()
    assert schema.validate_row(row) == []
    assert row["schema_version"] == schema.SCHEMA_VERSION
    assert row["steps"] == 4
    assert row["step_time_ms"]["p50"] == pytest.approx(40.0)
    assert set(row["phases_ms"]) == set(schema.PHASES)
    assert row["fingerprint"]["platform"] == "cpu"
    assert row["device_kind"]


def test_validate_row_catches_violations():
    assert schema.validate_row("nope") == ["row is not an object"]
    row = _mk_row()
    bad = dict(row, schema_version=99)
    assert any("schema_version" in e for e in schema.validate_row(bad))
    bad = dict(row, mode="bogus")
    assert any("mode" in e for e in schema.validate_row(bad))
    bad = dict(row, phases_ms={"data": 1.0})  # missing phases
    assert any("phases_ms.compute" in e for e in schema.validate_row(bad))
    bad = dict(row, step_time_ms={})
    assert any("p50" in e for e in schema.validate_row(bad))
    bad = dict(row, fallback_reason=123)
    assert any("fallback_reason" in e for e in schema.validate_row(bad))
    bad = dict(row, bytes_on_wire="lots")
    assert any("bytes_on_wire" in e for e in schema.validate_row(bad))


def test_fallback_reason_is_a_field_not_prose():
    row = _mk_row(fallback_reason="tpu_unreachable")
    assert schema.validate_row(row) == []
    assert row["fallback_reason"] == "tpu_unreachable"
    assert row["device_kind"]  # what actually ran is always stamped


def test_pct_matches_aggregate_definition():
    from paddle_tpu.observability.aggregate import _pct
    series = sorted([5.0, 1.0, 3.0, 2.0, 4.0])
    for p in (0, 50, 90, 99, 100):
        assert harness.pct(series, p) == _pct(series, p)


# -- ledger -----------------------------------------------------------------
def test_append_round_trip(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    r1, r2 = _mk_row(p50=40.0), _mk_row(scenario="moe", p50=60.0)
    ledger.append_row(r1, path)
    ledger.append_row(r2, path)
    rows = ledger.read_ledger(path)
    assert [r["scenario"] for r in rows] == ["gpt_pretrain_fused", "moe"]
    assert rows[0]["step_time_ms"]["p50"] == pytest.approx(40.0)


def test_append_rejects_invalid_row(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with pytest.raises(ValueError, match="invalid ledger row"):
        ledger.append_row({"scenario": "x"}, path)
    assert not os.path.exists(path)  # nothing poisoned the history


def test_torn_tail_and_foreign_schema_tolerated(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    good = _mk_row()
    foreign = dict(_mk_row(scenario="from_the_future"), schema_version=99)
    fsio.append_bytes(path, (json.dumps(good) + "\n").encode())
    fsio.append_bytes(path, (json.dumps(foreign) + "\n").encode())
    # a mid-append death leaves a torn trailing line
    fsio.append_bytes(path, json.dumps(good)[: 40].encode())
    drops = {}
    rows = ledger.read_ledger(path, drops=drops)
    assert len(rows) == 1 and rows[0]["scenario"] == good["scenario"]
    assert drops == {"torn_lines": 1, "unknown_schema": 1}


def test_latest_rows_newest_wins(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    ledger.append_row(_mk_row(p50=40.0), path)
    ledger.append_row(_mk_row(p50=44.0), path)
    ledger.append_row(_mk_row(scenario="moe", mode="full", p50=9.0), path)
    latest = ledger.latest_rows(ledger.read_ledger(path))
    assert latest["gpt_pretrain_fused"]["step_time_ms"]["p50"] == \
        pytest.approx(44.0)
    assert ledger.latest_rows(ledger.read_ledger(path),
                              mode="smoke").keys() == {"gpt_pretrain_fused"}


def test_golden_round_trip_and_thresholds(tmp_path):
    gpath = str(tmp_path / "golden.json")
    golden = ledger.golden_from_rows({"moe": _mk_row(scenario="moe")},
                                     thresholds={"step_time_regression_frac":
                                                 0.25})
    ledger.write_golden(golden, gpath)
    loaded = ledger.load_golden(gpath)
    assert loaded["scenarios"]["moe"]["scenario"] == "moe"
    # explicit override wins; unknown name raises; default backfills
    assert ledger.threshold(loaded, "step_time_regression_frac") == 0.25
    assert ledger.threshold(loaded, "comm_min_compress_ratio") == 3.0
    with pytest.raises(KeyError):
        ledger.threshold(loaded, "not_a_threshold")
    assert ledger.load_golden(str(tmp_path / "absent.json")) is None


# -- perfdiff attribution ---------------------------------------------------
@pytest.mark.parametrize("phase", schema.PHASES)
def test_attribution_names_the_inflated_phase(phase):
    base = _mk_row(phases={"data": 5.0, "compute": 30.0, "readback": 2.0,
                           "collective": 3.0})
    cur_phases = dict(base["phases_ms"])
    cur_phases[phase] *= 2.0  # inflate exactly one phase
    cur = _mk_row(p50=40.0 + cur_phases[phase] / 2.0, phases=cur_phases)
    att = perfdiff.attribute(base, cur)
    assert att["dominant"] == phase
    assert att["movers"][0]["phase"] == phase
    assert att["movers"][0]["delta_ms"] == pytest.approx(
        base["phases_ms"][phase])


def test_diff_rows_regression_verdict_and_render():
    base = _mk_row(p50=40.0)
    cur = _mk_row(p50=48.0,
                  phases={"data": 1.0, "compute": 46.0, "readback": 0.5,
                          "collective": 0.5})
    rep = perfdiff.diff_rows(base, cur, 0.10)
    assert rep["regression"] and rep["ratio"] == pytest.approx(1.2)
    assert rep["attribution"]["dominant"] == "compute"
    text = perfdiff.render(rep)
    assert "REGRESSION" in text and "compute" in text
    assert "dominant" in text
    # improvement: no regression, no dominant mover
    rep2 = perfdiff.diff_rows(cur, base, 0.10)
    assert not rep2["regression"]


def test_diff_compile_wall_reported_separately():
    base = _mk_row()
    cur = _mk_row(compile_stats={"wall_ms": 9000.0, "traces": 3,
                                 "retraces": 2, "storms": 0,
                                 "cache_hits": 0, "persistent_hits": 0,
                                 "persistent_requests": 0})
    att = perfdiff.attribute(base, cur)
    assert att["compile_wall_delta_ms"] == pytest.approx(7000.0)
    # compile is not a step phase: it never becomes the dominant mover
    assert att["dominant"] is None


def test_diff_cli_two_row_files(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_mk_row(p50=40.0)))
    b.write_text(json.dumps(_mk_row(p50=60.0, phases={
        "data": 1.0, "compute": 58.0, "readback": 0.5, "collective": 0.5})))
    assert perfdiff.main([str(a), str(b)]) == 1  # regression → rc 1
    assert perfdiff.main([str(b), str(a)]) == 0


# -- gate edge cases --------------------------------------------------------
def _setup_gate(tmp_path, base_p50=40.0, cur_p50=40.0, scenario="moe"):
    lpath = str(tmp_path / "ledger.jsonl")
    gpath = str(tmp_path / "golden.json")
    ledger.write_golden(ledger.golden_from_rows(
        {scenario: _mk_row(scenario=scenario, p50=base_p50)}), gpath)
    # three identical prior rows give the noise-aware gate its history:
    # trailing median = base_p50, MAD = 0, so the threshold collapses to
    # the golden fraction and the edge-case contracts below stay exact
    for _ in range(3):
        ledger.append_row(_mk_row(scenario=scenario, p50=base_p50), lpath)
    ledger.append_row(_mk_row(scenario=scenario, p50=cur_p50), lpath)
    return lpath, gpath


def test_gate_passes_when_flat(tmp_path, capsys):
    lpath, gpath = _setup_gate(tmp_path)
    assert gate.run_gate(lpath, gpath) == 0
    assert "ok" in capsys.readouterr().out


def test_gate_exactly_at_threshold_passes(tmp_path):
    # strictly-greater contract: exactly +10% is NOT a regression
    lpath, gpath = _setup_gate(tmp_path, base_p50=40.0, cur_p50=44.0)
    assert gate.run_gate(lpath, gpath) == 0
    lpath2, gpath2 = _setup_gate(tmp_path / "b", base_p50=40.0,
                                 cur_p50=44.01)
    assert gate.run_gate(lpath2, gpath2) == 1


def test_gate_regression_fails_with_attribution(tmp_path, capsys):
    lpath, gpath = _setup_gate(tmp_path, base_p50=40.0, cur_p50=48.0)
    assert gate.run_gate(lpath, gpath) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "moe" in out
    assert "dominant" in out  # the perfdiff report names the mover
    assert "FAIL" in out


def test_gate_golden_missing_passes_advisory(tmp_path, capsys):
    lpath = str(tmp_path / "ledger.jsonl")
    ledger.append_row(_mk_row(), lpath)
    rc = gate.run_gate(lpath, str(tmp_path / "no_golden.json"))
    assert rc == 0
    assert "--write-golden" in capsys.readouterr().out


def test_gate_new_scenario_passes_until_blessed(tmp_path, capsys):
    lpath, gpath = _setup_gate(tmp_path)
    # a scenario in the ledger but absent from golden: pass with a note
    ledger.append_row(_mk_row(scenario="brand_new", p50=500.0), lpath)
    assert gate.run_gate(lpath, gpath) == 0
    assert "not in golden" in capsys.readouterr().out


def test_gate_write_golden_blesses_latest(tmp_path, capsys):
    lpath = str(tmp_path / "ledger.jsonl")
    gpath = str(tmp_path / "golden.json")
    ledger.append_row(_mk_row(p50=40.0), lpath)
    ledger.append_row(_mk_row(p50=42.0), lpath)
    assert gate.run_gate(lpath, gpath, write_golden=True) == 0
    golden = ledger.load_golden(gpath)
    assert golden["scenarios"]["gpt_pretrain_fused"]["step_time_ms"][
        "p50"] == pytest.approx(42.0)
    assert golden["thresholds"]["step_time_regression_frac"] == 0.10
    # re-blessing preserves threshold overrides already in the file
    golden["thresholds"]["step_time_regression_frac"] = 0.33
    ledger.write_golden(golden, gpath)
    assert gate.run_gate(lpath, gpath, write_golden=True) == 0
    assert ledger.load_golden(gpath)["thresholds"][
        "step_time_regression_frac"] == 0.33


def test_gate_empty_ledger_advisory(tmp_path):
    _, gpath = _setup_gate(tmp_path)
    assert gate.run_gate(str(tmp_path / "empty.jsonl"), gpath) == 0
    assert gate.run_gate(str(tmp_path / "empty.jsonl"),
                         str(tmp_path / "x.json"), write_golden=True) == 2


def test_gate_tolerates_torn_ledger_tail(tmp_path, capsys):
    lpath, gpath = _setup_gate(tmp_path)
    fsio.append_bytes(lpath, b'{"torn...')
    assert gate.run_gate(lpath, gpath) == 0
    assert "torn" in capsys.readouterr().out


# -- doctor / statusz verdict ----------------------------------------------
def test_doctor_check_perf_regression_names_dominant_mover():
    from paddle_tpu.observability.doctor import check_perf_regression
    base = _mk_row(scenario="moe", p50=40.0)
    golden = ledger.golden_from_rows({"moe": base})
    rec = {"kind": "bench.row", "scenario": "moe",
           "step_time_p50_ms": 55.0,
           "phases_ms": {"data": 1.0, "compute": 53.0, "readback": 0.5,
                         "collective": 0.5},
           "compile_wall_ms": 2000.0, "device_kind": "cpu"}
    findings = check_perf_regression({0: [rec]}, golden=golden)
    assert len(findings) == 1
    f = findings[0]
    assert f["kind"] == "perf_regression"
    assert f["data"]["scenario"] == "moe"
    assert f["data"]["dominant"] == "compute"
    assert any("dominant mover: compute" in e for e in f["evidence"])
    # within threshold → silent; no golden → silent
    ok = dict(rec, step_time_p50_ms=41.0)
    assert check_perf_regression({0: [ok]}, golden=golden) == []
    assert check_perf_regression({0: [rec]}, golden={}) == []


def test_statusz_surfaces_perf_section(tmp_path, monkeypatch):
    from paddle_tpu.bench import runner
    from paddle_tpu.observability.monitor import StatusServer
    from paddle_tpu.observability.registry import get_registry
    reg = get_registry()
    reg.gauge("perf.step_time_ms[scenario=moe]").set(55.0)
    reg.gauge("perf.phase_ms[scenario=moe,phase=compute]").set(53.0)
    gpath = str(tmp_path / "golden.json")
    ledger.write_golden(ledger.golden_from_rows(
        {"moe": _mk_row(scenario="moe", p50=40.0)}), gpath)
    monkeypatch.setattr(ledger, "default_golden_path", lambda: gpath)
    try:
        status = StatusServer(port=0).statusz()
        perf = status["perf"]
        assert perf["scenarios"]["moe"]["step_time_ms"] == 55.0
        assert perf["scenarios"]["moe"]["phases_ms"]["compute"] == 53.0
        verdicts = perf["perf_regression"]
        assert verdicts and verdicts[0]["scenario"] == "moe"
        assert verdicts[0]["dominant"] == "compute"
    finally:
        reg.gauge("perf.step_time_ms[scenario=moe]").set(0.0)


# -- the matrix itself ------------------------------------------------------
def test_scenario_registry_covers_the_matrix():
    from paddle_tpu.bench import scenarios
    have = set(scenarios.names())
    assert {"gpt_pretrain_fused", "gpt_pretrain_unfused", "moe",
            "long_context", "resnet", "mnist", "serve"} <= have
    with pytest.raises(KeyError, match="unknown scenario"):
        scenarios.get("nope")


def test_run_scenario_emits_valid_row(tmp_path):
    # one in-process matrix entry end to end: scenario → row → ledger.
    # mnist is the cheapest registered scenario.
    from paddle_tpu.bench.runner import run_scenario
    row = run_scenario("mnist", "smoke")
    assert schema.validate_row(row) == []
    assert row["scenario"] == "mnist"
    assert row["compile"]["traces"] >= 1
    assert row["extra"]["images_per_sec"] > 0
    path = ledger.append_row(row, str(tmp_path / "ledger.jsonl"))
    assert ledger.read_ledger(path)[0]["scenario"] == "mnist"
