"""nn.utils tests: weight_norm/remove_weight_norm reparameterization,
spectral_norm hook, parameter vector round trip."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn.utils import (parameters_to_vector, remove_weight_norm,
                                 spectral_norm, vector_to_parameters,
                                 weight_norm)


def test_weight_norm_preserves_forward_then_scales():
    pt.seed(0)
    lin = nn.Linear(8, 4)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8), jnp.float32)
    y0 = np.asarray(lin(x))
    weight_norm(lin, dim=1)
    assert "weight_g" in lin._parameters and "weight_v" in lin._parameters
    np.testing.assert_allclose(np.asarray(lin(x)), y0, rtol=1e-5,
                               atol=1e-5)
    # doubling g doubles the pre-bias output
    lin._parameters["weight_g"].value = \
        lin._parameters["weight_g"].value * 2.0
    b = np.asarray(lin.bias.value)
    np.testing.assert_allclose(np.asarray(lin(x)) - b, 2 * (y0 - b),
                               rtol=1e-4, atol=1e-4)
    remove_weight_norm(lin)
    assert "weight_v" not in lin._parameters
    np.testing.assert_allclose(np.asarray(lin(x)) - b, 2 * (y0 - b),
                               rtol=1e-4, atol=1e-4)


def test_weight_norm_trains():
    """g/v parameterization: gradients flow into both factors."""
    pt.seed(1)
    lin = nn.Linear(4, 4, bias_attr=False)
    weight_norm(lin)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 4), jnp.float32)
    tgt = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)
    params = lin.state_dict()
    assert set(params) == {"weight_g", "weight_v"}
    opt = pt.optimizer.Adam(learning_rate=5e-2)
    state = opt.init(params)

    def loss_fn(p):
        return jnp.mean((lin.apply(p, x) - tgt) ** 2)

    l0 = float(loss_fn(params))
    for _ in range(40):
        g = jax.grad(loss_fn)(params)
        params, state = opt.apply_gradients(g, params, state)
    assert float(loss_fn(params)) < 0.5 * l0


def test_spectral_norm_caps_sigma():
    pt.seed(2)
    lin = nn.Linear(8, 8)
    lin.weight.value = lin.weight.value * 10.0
    spectral_norm(lin)
    lin.train()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8), jnp.float32)
    for _ in range(20):
        lin(x)
    s = np.linalg.svd(np.asarray(lin.weight.value), compute_uv=False)
    assert abs(s[0] - 1.0) < 5e-2


def test_spectral_norm_survives_jit_then_eager():
    """Tracing apply() must not leak tracers into the power-iteration
    buffers (regression: eager forward after jit crashed)."""
    pt.seed(4)
    lin = nn.Linear(6, 6)
    spectral_norm(lin)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 6), jnp.float32)
    params = lin.state_dict()
    _ = jax.jit(lambda p, x: lin.apply(p, x))(params, x)
    y = lin(x)                       # would raise UnexpectedTracerError
    assert np.all(np.isfinite(np.asarray(y)))


def test_hook_handles_never_reused():
    pt.seed(5)
    lin = nn.Linear(2, 2)
    weight_norm(lin)
    calls = []
    lin.register_forward_pre_hook(lambda l, a: calls.append(1))
    remove_weight_norm(lin)
    weight_norm(lin)                 # must NOT clobber the user hook
    lin(jnp.zeros((1, 2)))
    assert calls == [1]


def test_parameter_vector_roundtrip():
    pt.seed(3)
    lin = nn.Linear(3, 2)
    vec = parameters_to_vector(lin.parameters())
    assert vec.shape == (3 * 2 + 2,)
    vector_to_parameters(vec * 2.0, lin.parameters())
    np.testing.assert_allclose(
        np.asarray(parameters_to_vector(lin.parameters())),
        np.asarray(vec) * 2.0, rtol=1e-6)
