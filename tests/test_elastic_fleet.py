"""Elastic fleet proof (ISSUE 9): resize a live run instead of rolling
it back.

- world descriptor + generation fencing: a worker the fleet retired
  cannot commit a checkpoint (StaleGeneration), a still-member worker
  can;
- cross-width checkpoint relayout: model + ZeRO-1 flat master saved on
  an 8-way dp mesh restore onto 4- and 2-way meshes with the gathered
  values preserved BITWISE (only zero padding moves);
- the coordinator's full resize arc: quiesce → fence → remesh →
  reshard → rewind to last_good_step → reseed, with elastic.resize /
  elastic.ef_reset events and a loss trajectory matching a fixed-width
  run after the rewind point;
- supervisor/hapi wiring: a scale signal mid-`fit` resizes and the run
  completes;
- launcher reconciliation (`launch --elastic min:max`): SIGKILL a
  worker mid-run → the run completes at reduced width, resumes from
  last_good_step (one interval lost), re-expands when the worker
  returns — both transitions recorded (subprocess drills marked slow;
  ci.sh runs them in the elastic tier).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import distributed as dist
from paddle_tpu.distributed import elastic as el
from paddle_tpu.distributed.comm import ShardedOptimizer, repack_flat
from paddle_tpu.distributed.topology import get_mesh
from paddle_tpu.supervisor import RunSupervisor
from paddle_tpu.testing import faults

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_mesh():
    # the coordinator installs a process-global hybrid mesh; reset BEFORE
    # each test too — earlier files in a full run may leave one installed
    dist.set_hybrid_communicate_group(None)
    yield
    dist.set_hybrid_communicate_group(None)


def _events(sink_list):
    return [k for k, _ in sink_list]


# -- world descriptor ------------------------------------------------------
class TestWorldDescriptor:
    def test_roundtrip(self, tmp_path):
        d = str(tmp_path)
        desc = el.write_world(d, generation=3, members=[2, 0, 1],
                              min_size=1, max_size=4, reason="test")
        got = el.read_world(d)
        assert got == desc
        assert got["members"] == [0, 1, 2]  # sorted
        assert got["world_size"] == 3

    def test_absent_reads_none(self, tmp_path):
        assert el.read_world(str(tmp_path / "nope")) is None


# -- generation fencing ----------------------------------------------------
class TestGenerationFencing:
    def _state(self):
        return {"w": jnp.arange(8.0)}

    def test_stale_worker_cannot_commit(self, tmp_path):
        run = str(tmp_path)
        el.write_world(run, generation=0, members=[0, 1])
        events = []
        mgr = el.ElasticTrainState(os.path.join(run, "ck"),
                                   install_sigterm_handler=False,
                                   event_sink=lambda k, **f:
                                   events.append((k, f)))
        mgr.bind_world(run)
        mgr.save(5, self._state(), use_async=False)   # current gen: fine
        assert mgr.last_good_step() == 5
        # the fleet moves on without this worker
        el.write_world(run, generation=1, members=[1],
                       reason="lost-worker:0")
        with pytest.raises(el.StaleGeneration):
            mgr.save(7, self._state(), use_async=False)
        assert mgr.last_good_step() == 5       # nothing new committed
        assert "elastic.fence_rejected" in _events(events)
        # and no step-7 debris is eligible for restore
        assert all("step-7" not in os.path.basename(p)
                   for p in el.committed_checkpoints(mgr.directory))

    def test_async_commit_fence_surfaces_on_wait(self, tmp_path):
        run = str(tmp_path)
        el.write_world(run, generation=0, members=[0])
        mgr = el.ElasticTrainState(os.path.join(run, "ck"),
                                   install_sigterm_handler=False)
        mgr.bind_world(run)
        el.write_world(run, generation=2, members=[], reason="retired")
        mgr.save(3, self._state(), use_async=True)
        with pytest.raises(el.StaleGeneration):
            mgr.wait()
        assert mgr.last_good_step() == -1

    def test_member_of_newer_world_may_commit(self, tmp_path):
        # a still-member that hasn't polled the bump yet is NOT a zombie
        run = str(tmp_path)
        el.write_world(run, generation=0, members=[0, 1])
        mgr = el.ElasticTrainState(os.path.join(run, "ck"),
                                   install_sigterm_handler=False)
        mgr.bind_world(run, worker_id=0)
        el.write_world(run, generation=1, members=[0],
                       reason="lost-worker:1")
        mgr.save(4, self._state(), use_async=False)    # allowed
        assert mgr.last_good_step() == 4
        # ... until the fleet retires it too
        el.write_world(run, generation=2, members=[1], reason="swap")
        with pytest.raises(el.StaleGeneration):
            mgr.save(6, self._state(), use_async=False)


# -- corrupt-quarantine GC bound -------------------------------------------
class TestCorruptGcBound:
    def test_keeps_newest_k_quarantines(self, tmp_path):
        d = str(tmp_path / "ck")
        os.makedirs(d)
        for step in (1, 2, 3, 4, 5):
            os.makedirs(os.path.join(d, f"step-{step}.corrupt"))
        mgr = el.ElasticTrainState(d, keep=2, corrupt_keep=2,
                                   install_sigterm_handler=False)
        mgr.save(10, {"w": jnp.ones(4)}, use_async=False)  # triggers gc
        left = sorted(n for n in os.listdir(d) if n.endswith(".corrupt"))
        assert left == ["step-4.corrupt", "step-5.corrupt"]

    def test_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PTPU_CORRUPT_KEEP", "1")
        mgr = el.ElasticTrainState(str(tmp_path),
                                   install_sigterm_handler=False)
        assert mgr.corrupt_keep == 1


# -- flat repack -----------------------------------------------------------
class TestRepackFlat:
    def test_shrink_drops_only_zero_padding(self):
        saved = np.zeros(16, np.float32)
        saved[:10] = np.arange(10) + 1
        out = repack_flat(saved, 12)
        assert out.shape == (12,)
        np.testing.assert_array_equal(out[:10], saved[:10])

    def test_grow_pads_zeros(self):
        out = repack_flat(np.arange(6, dtype=np.float32), 8)
        np.testing.assert_array_equal(out, [0, 1, 2, 3, 4, 5, 0, 0])

    def test_refuses_to_drop_real_elements(self):
        with pytest.raises(Exception, match="nonzero"):
            repack_flat(np.arange(8, dtype=np.float32) + 1, 6)

    def test_bitwise_roundtrip(self):
        rng = np.random.RandomState(0)
        base = np.zeros(720, np.float32)
        base[:714] = rng.randn(714).astype(np.float32)
        down = repack_flat(base, 716)
        up = repack_flat(down, 720)
        np.testing.assert_array_equal(up, base)


# -- cross-width ZeRO-1 relayout (the acceptance drill) --------------------
def _grad_like(params, seed):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    rng = np.random.RandomState(seed)
    return jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(rng.randn(*np.shape(p)).astype(np.float32))
                  for p in leaves])


class TestZero1CrossWidth:
    """Save model + ZeRO-1 flat master on dp=8; restore onto dp=4 and
    dp=2: gathered params and the real (unpadded) master elements must
    be BITWISE equal; continued training stays on the fp32 trajectory."""

    TOTAL = 37 * 19 + 11     # 714: padded differs per width (720/716/714)

    def _params(self):
        rng = np.random.RandomState(0)
        return {"w": jnp.asarray(rng.randn(37, 19), jnp.float32),
                "b": jnp.asarray(rng.randn(11), jnp.float32)}

    def _train(self, opt, params, state, steps, seed0=100):
        step_fn = jax.jit(opt.apply_gradients)
        for i in range(steps):
            params, state = step_fn(_grad_like(params, seed0 + i),
                                    params, state)
        return params, state

    @pytest.mark.parametrize("new_dp", [4, 2])
    def test_restore_reduced_width_bitwise(self, tmp_path, new_dp):
        mgr = el.ElasticTrainState(str(tmp_path / "ck"),
                                   install_sigterm_handler=False)
        coord = el.ElasticCoordinator(mgr, mp=1, pp=1)
        coord.form_mesh(8)
        params = self._params()
        opt8 = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3),
                                axis="dp")
        state = opt8.init(params)
        assert np.asarray(state["flat"]).shape == (720,)
        params, state = self._train(opt8, params, state, 3)
        saved_params = jax.tree_util.tree_map(np.asarray, params)
        saved_flat = np.asarray(state["flat"])
        mgr.save(3, {"params": params, "opt": state}, use_async=False)

        def template_fn():
            opt_new = ShardedOptimizer(pt.optimizer.Adam(
                learning_rate=1e-3), axis="dp").bind_mesh(get_mesh())
            return {"params": self._params(),
                    "opt": opt_new.init(self._params())}

        restored, start = coord.resize(new_dp, template_fn,
                                       reason="lost-worker")
        assert start == 4
        padded_new = -(-self.TOTAL // new_dp) * new_dp
        flat_new = np.asarray(restored["opt"]["flat"])
        assert flat_new.shape == (padded_new,)
        # bitwise: the gathered params and every real master element
        for name in ("w", "b"):
            np.testing.assert_array_equal(
                np.asarray(restored["params"][name]), saved_params[name])
        np.testing.assert_array_equal(flat_new[:self.TOTAL],
                                      saved_flat[:self.TOTAL])
        for slot in jax.tree_util.tree_leaves(restored["opt"]["slots"]):
            assert np.asarray(slot).shape == (padded_new,)
        assert int(restored["opt"]["step"]) == 3

    def test_continued_training_parity(self, tmp_path):
        """The continued-training drill: restore at dp=4 and keep
        stepping — trajectory matches staying at dp=8."""
        mgr = el.ElasticTrainState(str(tmp_path / "ck"),
                                   install_sigterm_handler=False)
        coord = el.ElasticCoordinator(mgr, mp=1, pp=1)
        coord.form_mesh(8)
        params = self._params()
        opt8 = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3),
                                axis="dp")
        state = opt8.init(params)
        params, state = self._train(opt8, params, state, 3)
        mgr.save(3, {"params": params, "opt": state}, use_async=False)
        # baseline: stay at width 8 for 2 more steps
        base_params, _ = self._train(opt8, params, state, 2, seed0=200)

        def template_fn():
            opt_new = ShardedOptimizer(pt.optimizer.Adam(
                learning_rate=1e-3), axis="dp").bind_mesh(get_mesh())
            return {"params": self._params(),
                    "opt": opt_new.init(self._params())}

        restored, _start = coord.resize(4, template_fn,
                                        reason="lost-worker")
        opt4 = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3),
                                axis="dp").bind_mesh(get_mesh())
        got_params, _ = self._train(opt4, restored["params"],
                                    restored["opt"], 2, seed0=200)
        for name in ("w", "b"):
            np.testing.assert_allclose(np.asarray(got_params[name]),
                                       np.asarray(base_params[name]),
                                       rtol=0, atol=1e-6)

    def test_relayout_state_direct(self):
        """Unit form of the repack: relayout_state re-packs a host ZeRO
        state onto the currently-bound shard count."""
        coordless_mesh = None
        dist.set_hybrid_communicate_group(coordless_mesh)
        params = self._params()
        opt8 = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3),
                                axis="dp", num_shards=8)
        state = opt8.init(params)
        host = {"step": np.asarray(state["step"]),
                "flat": np.asarray(state["flat"]),
                "slots": jax.tree_util.tree_map(np.asarray,
                                                state["slots"])}
        opt4 = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3),
                                axis="dp", num_shards=4)
        out = opt4.relayout_state(host, params)
        assert np.asarray(out["flat"]).shape == (716,)
        np.testing.assert_array_equal(np.asarray(out["flat"])[:714],
                                      host["flat"][:714])

    @pytest.mark.integrity
    def test_fingerprint_invariant_across_width_relayout(self):
        """ISSUE 11: the tree digest is IDENTICAL across dp-width
        relayouts of the same logical state — zero lanes contribute
        nothing to the multilinear hash, so the 720-, 716- and 714-wide
        flats hash alike and cross-width desync comparison (a shrunk
        fleet voting against pre-shrink boards) compares apples to
        apples."""
        from paddle_tpu.distributed.fingerprint import digest_tree_host
        dist.set_hybrid_communicate_group(None)
        params = self._params()
        opt8 = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3),
                                axis="dp", num_shards=8)
        state = opt8.init(params)
        params, state = self._train(opt8, params, state, 3)
        host = {"step": np.asarray(state["step"]),
                "flat": np.asarray(state["flat"]),
                "slots": jax.tree_util.tree_map(np.asarray,
                                                state["slots"])}
        digests = {8: digest_tree_host(
            {"params": params, "opt": host}).hex()}
        for dp in (4, 2):
            opt = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3),
                                   axis="dp", num_shards=dp)
            re = opt.relayout_state(host, params)
            assert np.asarray(re["flat"]).shape != host["flat"].shape
            digests[dp] = digest_tree_host(
                {"params": params, "opt": re}).hex()
        assert len(set(digests.values())) == 1, digests


# -- coordinator resize arc ------------------------------------------------
class TestCoordinatorResize:
    def test_ef_residuals_reset_on_width_change(self, tmp_path):
        events = []
        mgr = el.ElasticTrainState(str(tmp_path / "ck"),
                                   install_sigterm_handler=False)
        coord = el.ElasticCoordinator(
            mgr, mp=1, pp=1,
            event_sink=lambda k, **f: events.append((k, f)))
        mesh8 = coord.form_mesh(8)
        resid = jax.device_put(
            np.random.RandomState(0).randn(8 * 4, 3).astype(np.float32),
            NamedSharding(mesh8, P("dp", None)))
        w = jax.device_put(np.arange(32.0, dtype=np.float32).reshape(8, 4),
                           NamedSharding(mesh8, P("dp", None)))
        mgr.save(7, {"w": w, "resid": resid}, use_async=False)

        def template_fn():
            m = get_mesh()
            sds = jax.ShapeDtypeStruct
            return {"w": sds((8, 4), jnp.float32,
                             sharding=NamedSharding(m, P("dp", None))),
                    "resid": sds((4 * 4, 3), jnp.float32,
                                 sharding=NamedSharding(m, P("dp", None)))}

        state, start = coord.resize(4, template_fn, reason="lost-worker:5")
        assert start == 8
        assert not np.asarray(state["resid"]).any()      # dropped
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.arange(32.0).reshape(8, 4))
        kinds = _events(events)
        assert "elastic.ef_reset" in kinds
        assert "elastic.resize" in kinds
        (resize,) = [f for k, f in events if k == "elastic.resize"]
        assert resize["old_dp"] == 8 and resize["new_dp"] == 4
        assert resize["generation"] == 1
        assert coord.resizes == 1 and coord.dp == 4

    def test_same_width_keeps_ef(self, tmp_path):
        mgr = el.ElasticTrainState(str(tmp_path / "ck"),
                                   install_sigterm_handler=False)
        coord = el.ElasticCoordinator(mgr, mp=1, pp=1)
        mesh8 = coord.form_mesh(8)
        resid = jax.device_put(
            np.random.RandomState(0).randn(8 * 2, 3).astype(np.float32),
            NamedSharding(mesh8, P("dp", None)))
        mgr.save(2, {"resid": resid}, use_async=False)

        def template_fn():
            m = get_mesh()
            return {"resid": jax.ShapeDtypeStruct(
                (8 * 2, 3), jnp.float32,
                sharding=NamedSharding(m, P("dp", None)))}

        state, _ = coord.resize(8, template_fn, reason="restart")
        np.testing.assert_array_equal(np.asarray(state["resid"]),
                                      np.asarray(resid))

    def test_reseed_hook_and_bounds(self, tmp_path):
        calls = []
        mgr = el.ElasticTrainState(str(tmp_path / "ck"),
                                   install_sigterm_handler=False)
        coord = el.ElasticCoordinator(
            mgr, mp=1, pp=1, min_dp=2, max_dp=8,
            reseed=lambda start, dp: calls.append((start, dp)))
        coord.form_mesh(8)
        mgr.save(5, {"w": jnp.ones(4)}, use_async=False)
        _state, start = coord.resize(1, lambda: {"w": jnp.zeros(4)},
                                     reason="over-shrink")
        assert coord.dp == 2              # clamped to min_dp
        assert calls == [(start, 2)]

    def test_loss_trajectory_matches_fixed_width_after_rewind(
            self, tmp_path):
        """The in-process fault drill: train on dp=8, lose workers at
        step 13, resize to 4, re-expand to 8 — every recomputed loss
        matches the uninterrupted fixed-width run."""
        def make_batch(step):
            rng = np.random.RandomState(500 + step)
            x = rng.randn(16, 8).astype(np.float32)
            y = (x @ np.linspace(-1, 1, 8).astype(np.float32)
                 + 0.01 * rng.randn(16).astype(np.float32))
            return jnp.asarray(x), jnp.asarray(y)

        @jax.jit
        def step_fn(w, x, y):
            def loss_fn(w):
                return jnp.mean((x @ w - y) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(w)
            return w - 0.1 * g, loss

        def run_fixed(total):
            w = jnp.zeros((8,), jnp.float32)
            losses = {}
            for s in range(total):
                x, y = make_batch(s)
                w, loss = step_fn(w, x, y)
                losses[s] = float(loss)
            return losses

        baseline = run_fixed(20)

        mgr = el.ElasticTrainState(str(tmp_path / "ck"),
                                   save_interval_steps=5,
                                   install_sigterm_handler=False)
        coord = el.ElasticCoordinator(mgr, mp=1, pp=1)
        coord.form_mesh(8)

        def template_fn():
            m = get_mesh()
            return {"w": jax.ShapeDtypeStruct(
                (8,), jnp.float32, sharding=NamedSharding(m, P()))}

        losses = {}
        w = jnp.zeros((8,), jnp.float32)
        step = 0
        resize_plan = {13: (4, "lost-worker:4-7"),
                       16: (8, "workers-returned")}
        while step < 20:
            if step in resize_plan:
                dp, reason = resize_plan.pop(step)
                state, start = coord.resize(dp, template_fn,
                                            reason=reason)
                w = state["w"]
                if reason.startswith("lost"):
                    # we were at 13, the newest commit was at 10 — one
                    # checkpoint interval lost, not the run
                    assert start == 11
                step = start
                continue
            x, y = make_batch(step)
            w, loss = step_fn(w, x, y)
            losses[step] = float(loss)
            mgr.maybe_save(step, {"w": w})
            step += 1
        mgr.wait()
        assert coord.generation == 2 and coord.resizes == 2
        for s in range(20):
            np.testing.assert_allclose(losses[s], baseline[s],
                                       rtol=0, atol=1e-6)


# -- heartbeat membership --------------------------------------------------
class TestHeartbeatMembership:
    def test_retired_workers_stale_beat_is_ignored(self, tmp_path):
        from paddle_tpu.supervisor.heartbeat import (HeartbeatMonitor,
                                                     HeartbeatWriter,
                                                     RunState)
        clock = [1000.0]
        run = str(tmp_path)
        for wid in (0, 1):
            HeartbeatWriter(run, worker_id=wid,
                            clock=lambda: clock[0]).beat()
        mon = HeartbeatMonitor(run, stale_after=5, lost_after=10,
                               expected={0, 1}, clock=lambda: clock[0])
        assert mon.poll()["state"] == RunState.HEALTHY
        clock[0] += 60.0                      # both beats go stale
        HeartbeatWriter(run, worker_id=0,
                        clock=lambda: clock[0]).beat()   # 0 still alive
        assert mon.poll()["state"] == RunState.LOST_WORKER
        mon.set_expected({0})                 # the fleet retired 1
        detail = mon.poll()
        assert detail["state"] == RunState.HEALTHY
        assert detail["workers"] == [0]

    def test_generation_stamped_beats(self, tmp_path):
        from paddle_tpu.supervisor.heartbeat import HeartbeatWriter
        hb = HeartbeatWriter(str(tmp_path), worker_id=3)
        hb.generation = 7
        hb.beat(step=11)
        payload = json.loads(open(hb.path).read())
        assert payload["generation"] == 7 and payload["step"] == 11


# -- supervisor / hapi wiring ----------------------------------------------
class TestSupervisedElasticFit:
    def test_scale_signal_mid_fit_resizes_and_completes(self, tmp_path):
        from paddle_tpu import nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import Callback
        from paddle_tpu.io import TensorDataset

        pt.seed(0)
        model = Model(nn.Linear(4, 2))
        model.prepare(optimizer=pt.optimizer.SGD(learning_rate=1e-2),
                      loss=lambda out, y: jnp.mean((out - y) ** 2))
        rng = np.random.RandomState(0)
        ds = TensorDataset([rng.randn(24, 4).astype(np.float32),
                            rng.randn(24, 2).astype(np.float32)])
        run = str(tmp_path / "run")
        mgr = el.ElasticTrainState(os.path.join(run, "checkpoints"),
                                   save_interval_steps=4,
                                   install_sigterm_handler=False)
        coord = el.ElasticCoordinator(mgr, mp=1, pp=1)
        coord.form_mesh(8)
        sup = RunSupervisor(run, elastic=mgr, coordinator=coord,
                            watchdog_secs=60.0, heartbeat_secs=60.0,
                            sigterm_handler=False)

        class ScaleSignal(Callback):
            fired = False

            def on_train_batch_end(self, step, logs=None):
                if step == 12 and not ScaleSignal.fired:
                    ScaleSignal.fired = True
                    sup.request_resize(4, reason="preemption-notice")

        history = model.fit(ds, batch_size=1, epochs=1, verbose=0,
                            supervisor=sup, callbacks=[ScaleSignal()])
        assert np.isfinite(history["loss"][-1])
        assert coord.resizes == 1 and coord.dp == 4
        counts = sup.report.counts()
        assert counts["elastic.resize_requested"] == 1
        assert counts["elastic.resize"] == 1
        assert counts.get("rollback") is None     # resize, NOT rollback
        (resize,) = sup.report.of_kind("elastic.resize")
        # rewound to the newest commit: one interval lost, run completed
        assert resize["start_step"] <= 13

    def test_statusz_elastic_section(self, tmp_path):
        from paddle_tpu.observability.monitor import StatusServer
        run = str(tmp_path / "run")
        mgr = el.ElasticTrainState(os.path.join(run, "checkpoints"),
                                   install_sigterm_handler=False)
        coord = el.ElasticCoordinator(mgr, mp=1, pp=1)
        coord.form_mesh(8)
        mgr.save(3, {"w": jnp.ones(4)}, use_async=False)
        coord.resize(4, lambda: {"w": jnp.zeros(4)}, reason="drill")
        sup = RunSupervisor(run, elastic=mgr, coordinator=coord,
                            sigterm_handler=False)
        page = StatusServer(supervisor=sup).statusz()
        ela = page["elastic"]
        assert ela["dp"] == 4 and ela["generation"] == 1
        assert ela["resizes"] == 1
        assert ela["last_resize"]["reason"] == "drill"
        assert ela["min_dp"] == 1 and ela["max_dp"] == 8


# -- launcher reconciliation (subprocess drills) ---------------------------
def _launch_elastic(run_dir, extra_env, script_args, nnodes=2,
                    elastic="1:2", timeout=300):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", str(nnodes), "--elastic", elastic,
         "--run_dir", run_dir,
         os.path.join(REPO, "examples", "train_elastic.py"), "--",
         ] + script_args,
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO)


class TestParseElastic:
    def test_parse(self):
        from paddle_tpu.distributed.launch import _parse_elastic
        assert _parse_elastic("1:4", 2) == (1, 4)
        assert _parse_elastic("2", 2) == (2, 2)
        with pytest.raises(SystemExit):
            _parse_elastic("3:4", 2)      # nnodes below MIN
        with pytest.raises(SystemExit):
            _parse_elastic("1:2", 4)      # nnodes above MAX


@pytest.mark.slow
class TestLauncherSigkillDrill:
    def test_sigkill_worker_midrun_shrinks_then_reexpands(self, tmp_path):
        """THE acceptance drill: SIGKILL worker 1 at its step 10 → the
        run completes at reduced width from last_good_step (≤ one
        save-interval lost), re-expands when the launcher respawns the
        worker, and both transitions land in launcher_report.json."""
        run = str(tmp_path / "run")
        save_interval = 8
        r = _launch_elastic(
            run,
            {"PTPU_HEARTBEAT_SECS": "0.5",
             "PTPU_ELASTIC_RESPAWN_SECS": "1.5",
             "PTPU_TEST_SIGKILL_STEP": "10",
             "PTPU_TEST_SIGKILL_RANK": "1"},
            ["--steps", "30", "--save-interval", str(save_interval),
             "--step-time", "0.08"])
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]

        report = json.loads(
            open(os.path.join(run, "launcher_report.json")).read())
        kinds = [e["kind"] for e in report["events"]]
        assert kinds.count("elastic.resize") >= 2
        resizes = [e for e in report["events"]
                   if e["kind"] == "elastic.resize"]
        shrink = next(e for e in resizes if e["direction"] == "shrink")
        grow = next(e for e in resizes if e["direction"] == "grow")
        assert shrink["changed"] == [1] and shrink["world_size"] == 1
        assert grow["changed"] == [1] and grow["world_size"] == 2
        assert grow["generation"] > shrink["generation"] >= 1
        (lost,) = [e for e in report["events"]
                   if e["kind"] == "elastic.worker_lost"]
        assert lost["rank"] == 1 and lost["returncode"] == -9
        (done,) = [e for e in report["events"]
                   if e["kind"] == "elastic.done"]
        assert done["returncode"] == 0 and done["respawns"] == {"1": 1}

        world = el.read_world(run)
        assert world["generation"] >= 2 and world["members"] == [0, 1]

        # the surviving chief rewound to last_good_step: at most one
        # checkpoint interval recomputed
        r0 = json.loads(
            open(os.path.join(run, "result-worker-0.json")).read())
        assert r0["rewinds"] >= 1
        w0 = json.loads(open(os.path.join(
            run, "reports", "worker-0.json")).read())
        rewinds = [e for e in w0["events"]
                   if e["kind"] == "elastic.rewind"]
        assert rewinds
        for e in rewinds:
            assert e["to_step"] <= e["from_step"]
            assert e["from_step"] - e["to_step"] <= save_interval + 1

        # loss-trajectory parity with a fixed-width run: recompute the
        # deterministic reference and compare every recorded loss
        sys.path.insert(0, os.path.join(REPO, "examples"))
        try:
            import train_elastic as te
        finally:
            sys.path.pop(0)
        w = jnp.zeros((te.DIM,), jnp.float32)
        for s in range(30):
            x, y = te.make_batch(s)
            w, loss = te.train_step(w, x, y, 0.1)
            if str(s) in r0["losses"]:
                np.testing.assert_allclose(r0["losses"][str(s)],
                                           float(loss), rtol=0, atol=1e-5)
        assert len(r0["losses"]) == 30

    def test_below_min_fails_loudly(self, tmp_path):
        run = str(tmp_path / "run")
        r = _launch_elastic(
            run,
            {"PTPU_HEARTBEAT_SECS": "0.5",
             "PTPU_ELASTIC_MAX_RESPAWNS": "0",
             "PTPU_TEST_SIGKILL_STEP": "5",
             "PTPU_TEST_SIGKILL_RANK": "0"},
            ["--steps", "25", "--save-interval", "6",
             "--step-time", "0.08"],
            elastic="2:2")
        assert r.returncode == 1
        report = json.loads(
            open(os.path.join(run, "launcher_report.json")).read())
        kinds = [e["kind"] for e in report["events"]]
        assert "elastic.failed" in kinds
        (failed,) = [e for e in report["events"]
                     if e["kind"] == "elastic.failed"]
        assert failed["reason"] == "below-min"
