"""jit.save / jit.load / inference predictor tests (E1/E5 parity:
paddle.jit.save -> inference model -> AnalysisPredictor run)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn


class TestJitSaveLoad:
    def _model(self):
        pt.seed(0)
        return nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))

    def test_roundtrip_matches_eager(self, tmp_path):
        model = self._model()
        model.eval()
        x = jnp.asarray(np.random.RandomState(0).randn(2, 8), jnp.float32)
        want = np.asarray(model(x))

        path = str(tmp_path / "exported")
        pt.jit.save(model, path, input_spec=[pt.jit.InputSpec((2, 8))])
        loaded = pt.jit.load(path)
        got = np.asarray(loaded(x))
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_gpt_export(self, tmp_path):
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        pt.seed(1)
        model = GPTForCausalLM(gpt_tiny(hidden_dropout=0.0,
                                        attention_dropout=0.0))
        model.eval()
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 1024, (2, 16)),
                          jnp.int32)
        want = np.asarray(model(ids))
        path = str(tmp_path / "gpt")
        pt.jit.save(model, path,
                    input_spec=[pt.jit.InputSpec((2, 16), "int32")])
        got = np.asarray(pt.jit.load(path)(ids))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)

    def test_predictor_facade(self, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        model = self._model()
        model.eval()
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        want = np.asarray(model(jnp.asarray(x)))
        path = str(tmp_path / "pred")
        pt.jit.save(model, path,
                    input_spec=[pt.jit.InputSpec((2, 8), name="x")])

        config = Config(path)
        predictor = create_predictor(config)
        names = predictor.get_input_names()
        assert names == ["x"]
        predictor.get_input_handle("x").copy_from_cpu(x)
        predictor.run()
        out = predictor.get_output_handle(
            predictor.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_to_static_alias(self):
        @pt.jit.to_static
        def f(a):
            return a * 2
        np.testing.assert_array_equal(np.asarray(f(jnp.ones(3))),
                                      2 * np.ones(3))

    def test_dynamic_batch_dim(self, tmp_path):
        """InputSpec None dims export as symbolic shapes: the loaded model
        serves any batch size (the paddle dynamic-dim contract)."""
        model = self._model()
        model.eval()
        path = str(tmp_path / "dyn")
        pt.jit.save(model, path,
                    input_spec=[pt.jit.InputSpec((None, 8))])
        loaded = pt.jit.load(path)
        for b in (1, 3, 16):
            x = jnp.asarray(np.random.RandomState(b).randn(b, 8),
                            jnp.float32)
            np.testing.assert_allclose(np.asarray(loaded(x)),
                                       np.asarray(model(x)), rtol=1e-6)
