"""cost_model / onnx-gating / WeightedRandomSampler tests."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn


def test_cost_model_profiles_flops_and_time():
    from paddle_tpu.cost_model import CostModel

    def fn(a, b):
        return (a @ b).sum()

    a = jnp.ones((64, 64))
    b = jnp.ones((64, 64))
    out = CostModel().profile_measure(fn, (a, b))
    assert out["flops"] >= 2 * 64 ** 3 * 0.9
    assert out["time"] > 0
    assert out["bytes_accessed"] > 0


def test_onnx_export_gated_with_guidance():
    pt.seed(0)
    net = nn.Linear(2, 2)
    if pt.onnx.onnx_available():
        pytest.skip("onnx installed; gate test not applicable")
    with pytest.raises(RuntimeError, match="jit.save"):
        pt.onnx.export(net, "/tmp/x.onnx")


def test_weighted_random_sampler_respects_weights():
    from paddle_tpu.io import WeightedRandomSampler
    np.random.seed(0)
    s = WeightedRandomSampler([0.0, 1.0, 9.0], num_samples=3000,
                              replacement=True)
    draws = np.asarray(list(iter(s)))
    assert len(s) == 3000 and draws.shape == (3000,)
    assert 0 not in np.unique(draws)
    frac2 = np.mean(draws == 2)
    assert 0.85 < frac2 < 0.95

    s2 = WeightedRandomSampler([1.0, 1.0], num_samples=2,
                               replacement=False)
    assert sorted(list(iter(s2))) == [0, 1]
    with pytest.raises(Exception, match="without replacement"):
        WeightedRandomSampler([1.0], num_samples=2, replacement=False)
