"""hapi callbacks tests (≙ reference test_callbacks.py doctrine)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.hapi import (Callback, EarlyStopping, LRScheduler, Model,
                             ModelCheckpoint, ProgBarLogger)


def _toy_model():
    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    model = Model(net)
    model.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-2),
                  loss=lambda out, y: jnp.mean(
                      pt.nn.functional.cross_entropy(out, y)))
    return model


def _toy_data(n=32):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    from paddle_tpu.io import TensorDataset
    return TensorDataset([x, y])


class TestCallbacks:
    def test_hooks_fire_in_order(self):
        events = []

        class Recorder(Callback):
            def on_train_begin(self, logs=None):
                events.append("train_begin")

            def on_epoch_begin(self, epoch, logs=None):
                events.append(f"epoch_begin{epoch}")

            def on_train_batch_end(self, step, logs=None):
                events.append("batch")

            def on_epoch_end(self, epoch, logs=None):
                events.append(f"epoch_end{epoch}")
                assert "loss" in (logs or {})

            def on_train_end(self, logs=None):
                events.append("train_end")

        model = _toy_model()
        model.fit(_toy_data(), batch_size=16, epochs=2, verbose=0,
                  callbacks=[Recorder()])
        assert events[0] == "train_begin" and events[-1] == "train_end"
        assert events.count("epoch_begin0") == 1
        assert events.count("batch") == 4  # 2 epochs x 2 steps

    def test_early_stopping_stops(self):
        model = _toy_model()
        es = EarlyStopping(monitor="loss", patience=0, baseline=0.0,
                           mode="min")
        model.fit(_toy_data(), batch_size=16, epochs=10, verbose=0,
                  callbacks=[es])
        # loss never beats baseline 0.0 → stops after epoch 0 (patience 0)
        assert es.stopped_epoch == 0
        assert model.stop_training

    def test_model_checkpoint_saves(self, tmp_path):
        model = _toy_model()
        model.fit(_toy_data(), batch_size=16, epochs=2, verbose=0,
                  callbacks=[ModelCheckpoint(save_freq=1,
                                             save_dir=str(tmp_path))])
        assert os.path.exists(str(tmp_path / "epoch_0.pdparams")) or \
            os.path.exists(str(tmp_path / "epoch_0"))

    def test_lr_scheduler_callback_changes_applied_lr(self):
        """The scheduled lr must reach the actual update, not just the
        scheduler's bookkeeping: with loss = mean(w) the SGD step size IS
        the applied lr (grad = 1/numel elementwise, scaled back up)."""
        pt.seed(0)
        net = nn.Sequential(nn.Linear(1, 1, bias_attr=False))
        model = Model(net)
        sched = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                          gamma=0.5)
        model.prepare(optimizer=pt.optimizer.SGD(learning_rate=sched),
                      loss=lambda out, y: jnp.sum(out))
        from paddle_tpu.io import TensorDataset
        x = np.ones((2, 1), np.float32)
        ds = TensorDataset([x, x.copy()])
        w = [float(net[0].weight.value[0, 0])]

        class Track(Callback):
            def on_train_batch_end(self, step, logs=None):
                w.append(float(net[0].weight.value[0, 0]))

        # fit auto-appends the by_step LRScheduler callback
        model.fit(ds, batch_size=1, epochs=1, shuffle=False, verbose=0,
                  callbacks=[Track()])
        # d(loss)/dw = sum over batch of x = 1 per sample (batch 1)
        step1, step2 = w[0] - w[1], w[1] - w[2]
        np.testing.assert_allclose(step1, 0.1, rtol=1e-5)
        np.testing.assert_allclose(step2, 0.05, rtol=1e-5)
        assert sched.last_epoch >= 1

    def test_eval_metrics_reach_epoch_end(self):
        from paddle_tpu.metric import Accuracy
        pt.seed(0)
        net = nn.Sequential(nn.Linear(4, 2))
        model = Model(net)
        model.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-2),
                      loss=lambda out, y: jnp.mean(
                          pt.nn.functional.cross_entropy(out, y)),
                      metrics=Accuracy())
        seen = {}

        class Grab(Callback):
            def on_epoch_end(self, epoch, logs=None):
                seen.update(logs or {})

            def on_eval_end(self, logs=None):
                seen["eval_end_fired"] = True

        model.fit(_toy_data(), eval_data=_toy_data(), batch_size=16,
                  epochs=1, verbose=0, callbacks=[Grab()])
        assert seen.get("eval_end_fired")
        assert "eval_loss" in seen and "acc" in seen
