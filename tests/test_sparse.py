"""paddle.sparse subsystem: OpTest-style parity vs scipy.sparse
(reference phi/kernels/sparse corpus + python/paddle/sparse API)."""
import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

import paddle_tpu.sparse as psp

R = np.random.RandomState(0)


def _rand_csr(m=6, n=5, density=0.4, seed=1):
    rs = np.random.RandomState(seed)
    return sp.random(m, n, density=density, format="csr",
                     random_state=rs, dtype=np.float32)


def _to_pt_coo(s):
    coo = s.tocoo()
    return psp.sparse_coo_tensor(
        np.stack([coo.row, coo.col]), coo.data, coo.shape)


class TestCreationAndConversion:
    def test_coo_roundtrip(self):
        s = _rand_csr()
        t = _to_pt_coo(s)
        np.testing.assert_allclose(np.asarray(t.to_dense()), s.toarray())
        assert t.nnz() == s.nnz

    def test_csr_roundtrip(self):
        s = _rand_csr()
        t = psp.sparse_csr_tensor(s.indptr, s.indices, s.data, s.shape)
        assert t.layout == "csr"
        np.testing.assert_allclose(np.asarray(t.to_dense()), s.toarray())
        np.testing.assert_array_equal(np.asarray(t.crows()), s.indptr)
        np.testing.assert_array_equal(np.asarray(t.cols()), s.indices)

    def test_dense_to_sparse_and_back(self):
        d = s = _rand_csr().toarray()
        t = psp.to_sparse_coo(d)
        np.testing.assert_allclose(np.asarray(t.to_dense()), d)
        tc = psp.to_sparse_csr(d)
        assert tc.layout == "csr"
        np.testing.assert_allclose(np.asarray(tc.to_dense()), s)

    def test_csr_view_consistent_for_unsorted_coo(self):
        # insertion order (1,0) then (0,1): crows/cols/csr_values must
        # decode to the SAME matrix, not a silently-permuted one
        t = psp.sparse_coo_tensor([[1, 0], [0, 1]], [5.0, 7.0], (2, 2))
        import scipy.sparse as sp2
        rebuilt = sp2.csr_matrix(
            (np.asarray(t.csr_values()), np.asarray(t.cols()),
             np.asarray(t.crows())), shape=(2, 2)).toarray()
        np.testing.assert_allclose(rebuilt, np.asarray(t.to_dense()))

    def test_empty_dense_has_zero_nnz(self):
        t = psp.to_sparse_coo(np.zeros((4, 4), np.float32))
        assert t.nnz() == 0
        np.testing.assert_allclose(np.asarray(t.to_dense()), 0.0)

    def test_softmax_jittable(self):
        import jax as _jax
        s = _rand_csr(5, 6, density=0.5, seed=12)
        t = _to_pt_coo(s)

        @_jax.jit
        def f(vals):
            tt = psp.SparseTensor(
                psp.jsparse.BCOO((vals, t.bcoo().indices),
                                 shape=t.shape))
            return psp.softmax(tt).to_dense()

        out = f(t.values())
        ref = psp.softmax(t).to_dense()
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)

    def test_coalesce_merges_duplicates(self):
        t = psp.sparse_coo_tensor([[0, 0, 1], [1, 1, 2]],
                                  [1.0, 2.0, 3.0], (2, 3))
        c = psp.coalesce(t)
        dense = np.zeros((2, 3), np.float32)
        dense[0, 1] = 3.0
        dense[1, 2] = 3.0
        np.testing.assert_allclose(np.asarray(c.to_dense()), dense)


class TestElementwise:
    def test_add_subtract(self):
        a, b = _rand_csr(seed=1), _rand_csr(seed=2)
        ta, tb = _to_pt_coo(a), _to_pt_coo(b)
        np.testing.assert_allclose(
            np.asarray(psp.add(ta, tb).to_dense()), (a + b).toarray(),
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(psp.subtract(ta, tb).to_dense()),
            (a - b).toarray(), rtol=1e-6)

    def test_multiply_divide(self):
        a, b = _rand_csr(seed=3), _rand_csr(seed=4)
        b.data += 2.0   # keep divisors away from zero on a's pattern
        ta, tb = _to_pt_coo(a), _to_pt_coo(b)
        np.testing.assert_allclose(
            np.asarray(psp.multiply(ta, tb).to_dense()),
            a.multiply(b).toarray(), rtol=1e-6)
        got = np.asarray(psp.divide(ta, tb).to_dense())
        bd = b.toarray()
        want = np.where(a.toarray() != 0,
                        np.divide(a.toarray(), np.where(bd == 0, 1.0, bd)),
                        0.0)
        # only positions where b is nonzero are comparable (else inf/nan)
        m = (a.toarray() != 0) & (bd != 0)
        np.testing.assert_allclose(got[m], want[m], rtol=1e-5)

    @pytest.mark.parametrize("name", ["relu", "sin", "tanh", "sqrt",
                                      "square", "log1p", "abs", "expm1",
                                      "neg"])
    def test_valuewise_unaries(self, name):
        s = _rand_csr(seed=5)
        t = _to_pt_coo(s)
        np_ref = {"relu": lambda v: np.maximum(v, 0), "sin": np.sin,
                  "tanh": np.tanh, "sqrt": np.sqrt, "square": np.square,
                  "log1p": np.log1p, "abs": np.abs, "expm1": np.expm1,
                  "neg": np.negative}[name]
        out = getattr(psp, name)(t)
        want = s.toarray().copy()
        want[want != 0] = np_ref(want[want != 0])
        np.testing.assert_allclose(np.asarray(out.to_dense()), want,
                                   rtol=1e-5, atol=1e-6)


class TestLinalg:
    def test_matmul_vs_scipy(self):
        s = _rand_csr(6, 5, seed=6)
        d = R.randn(5, 4).astype(np.float32)
        t = _to_pt_coo(s)
        np.testing.assert_allclose(np.asarray(psp.matmul(t, d)), s @ d,
                                   rtol=1e-5, atol=1e-5)

    def test_mv_addmm(self):
        s = _rand_csr(6, 5, seed=7)
        t = _to_pt_coo(s)
        x = R.randn(5).astype(np.float32)
        np.testing.assert_allclose(np.asarray(psp.mv(t, x)), s @ x,
                                   rtol=1e-5, atol=1e-5)
        inp = R.randn(6, 4).astype(np.float32)
        y = R.randn(5, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(psp.addmm(inp, t, y, beta=0.5, alpha=2.0)),
            0.5 * inp + 2.0 * (s @ y), rtol=1e-5, atol=1e-5)

    def test_transpose(self):
        s = _rand_csr(6, 5, seed=8)
        t = psp.transpose(_to_pt_coo(s))
        np.testing.assert_allclose(np.asarray(t.to_dense()),
                                   s.T.toarray())
        # explicit perms are honored, including identity
        same = psp.transpose(_to_pt_coo(s), perm=[0, 1])
        np.testing.assert_allclose(np.asarray(same.to_dense()),
                                   s.toarray())
        tt = psp.transpose(_to_pt_coo(s), perm=[1, 0])
        np.testing.assert_allclose(np.asarray(tt.to_dense()),
                                   s.T.toarray())

    def test_masked_matmul_sddmm(self):
        a = R.randn(6, 8).astype(np.float32)
        b = R.randn(8, 5).astype(np.float32)
        mask = _to_pt_coo(_rand_csr(6, 5, seed=9))
        out = psp.masked_matmul(a, b, mask)
        full = a @ b
        want = np.where(np.asarray(mask.to_dense()) != 0, full, 0.0)
        np.testing.assert_allclose(np.asarray(out.to_dense()), want,
                                   rtol=1e-5, atol=1e-5)

    def test_softmax_rowwise_over_stored(self):
        s = _rand_csr(5, 6, density=0.5, seed=10)
        t = _to_pt_coo(s)
        out = np.asarray(psp.softmax(t).to_dense())
        d = s.toarray()
        for i in range(d.shape[0]):
            nz = d[i] != 0
            if nz.sum() == 0:
                continue
            e = np.exp(d[i][nz] - d[i][nz].max())
            np.testing.assert_allclose(out[i][nz], e / e.sum(), rtol=1e-5)
            assert np.all(out[i][~nz] == 0)


class TestSparseNN:
    def test_relu_layer(self):
        s = _rand_csr(seed=11)
        layer = psp.nn.ReLU()
        out = layer(_to_pt_coo(s))
        np.testing.assert_allclose(np.asarray(out.to_dense()),
                                   np.maximum(s.toarray(), 0))

    def test_attention_matches_csr_entry_point(self):
        """sparse.nn.functional.attention (subsystem primitives) must agree
        with nn.functional.sparse_attention (batched CSR entry point)."""
        import paddle_tpu.nn.functional as F
        S, D = 8, 4
        q = R.randn(S, D).astype(np.float32)
        k = R.randn(S, D).astype(np.float32)
        v = R.randn(S, D).astype(np.float32)
        # lower-triangular pattern
        rows, cols = np.tril_indices(S)
        mask = psp.sparse_coo_tensor(np.stack([rows, cols]),
                                     np.ones(len(rows), np.float32),
                                     (S, S))
        out = psp.nn.functional.attention(q, k, v, mask)
        # CSR form of the same pattern for the batched entry point
        crows = np.concatenate([[0], np.cumsum(np.arange(1, S + 1))])
        ccols = np.concatenate([np.arange(i + 1) for i in range(S)])
        ref = F.sparse_attention(
            jnp.asarray(q)[None, None], jnp.asarray(k)[None, None],
            jnp.asarray(v)[None, None],
            jnp.asarray(crows, jnp.int32)[None, None],
            jnp.asarray(ccols, jnp.int32)[None, None])
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(ref)[0, 0], rtol=1e-5,
                                   atol=1e-5)

    def test_attention_grads_flow(self):
        S, D = 8, 4
        q = jnp.asarray(R.randn(S, D), jnp.float32)
        k = jnp.asarray(R.randn(S, D), jnp.float32)
        v = jnp.asarray(R.randn(S, D), jnp.float32)
        rows, cols = np.tril_indices(S)
        mask = psp.sparse_coo_tensor(np.stack([rows, cols]),
                                     np.ones(len(rows), np.float32),
                                     (S, S))
        g = jax.grad(lambda q_: jnp.sum(
            psp.nn.functional.attention(q_, k, v, mask) ** 2))(q)
        assert g.shape == q.shape and bool(jnp.isfinite(g).all())
