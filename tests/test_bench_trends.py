"""Perf trend engine (ISSUE 14): series view of the ledger, changepoint
detection + attribution, the noise-aware gate, trailing-median perfdiff,
the compactor, and the self-contained HTML dashboard."""
import json
import os

import pytest

from paddle_tpu.bench import diff as perfdiff
from paddle_tpu.bench import gate, ledger, report, schema, trends
from paddle_tpu.observability import interconnect, roofline
from paddle_tpu.utils import fsio

_FP = {"platform": "cpu", "device_kind": "cpu", "device_count": 8,
       "jax": "0.0-test", "python": "3.10.0"}


def _row(scenario="moe", mode="smoke", p50=50.0, phases=None, sha="aaaa1111",
         ts=1.0, fingerprint=None, mfu=0.1, compile_wall=100.0):
    """A schema-valid row with *controlled* sha/ts/fingerprint (new_row
    stamps the real repo sha, which these drills must not depend on)."""
    phases = phases or {"data": 5.0, "compute": p50 - 10.0,
                        "readback": 3.0, "collective": 2.0}
    roof = roofline.degraded_block(
        p50, {k: float(v) for k, v in phases.items()},
        reason="trends drill row")
    return {
        "schema_version": schema.SCHEMA_VERSION,
        "scenario": scenario, "mode": mode, "ts": float(ts),
        "git_sha": sha, "device_kind": "cpu", "fallback_reason": None,
        "fingerprint": dict(fingerprint or _FP), "config": {}, "steps": 4,
        "step_time_ms": {"p50": p50, "p99": p50 * 1.05, "mean": p50,
                         "min": p50 * 0.95},
        "phases_ms": {k: float(v) for k, v in phases.items()},
        "tokens_per_sec": 1000.0, "mfu": mfu,
        "compile": {"wall_ms": compile_wall},
        "bytes_on_wire": 0, "peak_hbm_bytes": 1 << 20,
        # schema v2: every row carries a gap budget; the degraded
        # phase-only block keeps these drills schema-valid
        "roofline": roof,
        # schema v3: every row carries a comm sub-budget; bucket must
        # match the roofline comm bucket for _validate_interconnect
        "interconnect": interconnect.degraded_block(
            float(roof["buckets_ms"].get("comm", 0.0)),
            reason="trends drill row"),
        "extra": {},
    }


def _moe_drill_rows(jitter=None, shift=True):
    """The acceptance drill: 12 rows across 3 shas; sha B inflates the
    moe compute phase by 1.2x (and C keeps it).  ``shift=False`` drops
    the inflation (the flat variant); ``jitter`` (len 12) multiplies
    each row's times."""
    base = {"data": 5.0, "compute": 40.0, "readback": 3.0,
            "collective": 2.0}
    infl = dict(base, compute=48.0) if shift else base
    rows = []
    ts = 0.0
    for sha, ph in (("aaaa1111", base), ("bbbb2222", infl),
                    ("cccc3333", infl)):
        for _ in range(4):
            ts += 1.0
            j = jitter[len(rows)] if jitter else 1.0
            rows.append(_row(p50=sum(ph.values()) * j,
                             phases={k: v * j for k, v in ph.items()},
                             sha=sha, ts=ts))
    return rows


# -- read_series ------------------------------------------------------------
def test_read_series_dedupes_sha_newest_wins(tmp_path):
    lpath = str(tmp_path / "l.jsonl")
    for i, (sha, p50) in enumerate([("a", 50.0), ("a", 52.0),
                                    ("b", 60.0)]):
        ledger.append_row(_row(p50=p50, sha=sha, ts=float(i)), lpath)
    pts = ledger.read_series("moe", "smoke", path=lpath)
    assert [(p["sha"], p["value"]) for p in pts] == [("a", 52.0),
                                                     ("b", 60.0)]
    # run-level view keeps every row (the gate's statistics need reruns)
    pts = ledger.read_series("moe", "smoke", path=lpath,
                             dedupe_sha=False)
    assert [p["value"] for p in pts] == [50.0, 52.0, 60.0]


def test_read_series_partitions_by_fingerprint(tmp_path):
    lpath = str(tmp_path / "l.jsonl")
    tpu_fp = dict(_FP, platform="tpu", device_kind="TPU v5e",
                  device_count=64)
    ledger.append_row(_row(p50=5.0, sha="t1", ts=1.0,
                           fingerprint=tpu_fp), lpath)
    ledger.append_row(_row(p50=50.0, sha="c1", ts=2.0), lpath)
    ledger.append_row(_row(p50=51.0, sha="c2", ts=3.0), lpath)
    # default partition = the newest row's (cpu): the TPU point is out
    pts = ledger.read_series("moe", "smoke", path=lpath)
    assert [p["value"] for p in pts] == [50.0, 51.0]
    # explicit partition selects the TPU series
    pts = ledger.read_series("moe", "smoke", path=lpath,
                             partition="tpu/TPU v5e/x64")
    assert [p["value"] for p in pts] == [5.0]


def test_read_series_skips_rows_missing_the_metric(tmp_path):
    lpath = str(tmp_path / "l.jsonl")
    r1 = _row(p50=50.0, sha="a", ts=1.0, mfu=None)
    r2 = _row(p50=51.0, sha="b", ts=2.0, mfu=0.2)
    ledger.append_row(r1, lpath)
    ledger.append_row(r2, lpath)
    assert len(ledger.read_series("moe", "smoke", "step_p50",
                                  path=lpath)) == 2
    mfu = ledger.read_series("moe", "smoke", "mfu", path=lpath)
    assert [(p["sha"], p["value"]) for p in mfu] == [("b", 0.2)]
    with pytest.raises(KeyError):
        schema.metric_value(r1, "bogus_metric")


# -- compaction -------------------------------------------------------------
def test_compact_ledger_bounds_per_scenario_history(tmp_path):
    lpath = str(tmp_path / "l.jsonl")
    for i in range(10):
        ledger.append_row(_row(scenario="a", p50=40.0 + i, ts=float(i)),
                          lpath)
    for i in range(3):
        ledger.append_row(_row(scenario="b", p50=90.0 + i,
                               ts=float(100 + i)), lpath)
    kept, dropped = ledger.compact_ledger(lpath, keep=4)
    assert (kept, dropped) == (7, 6)
    rows = ledger.read_ledger(lpath)
    a = [r for r in rows if r["scenario"] == "a"]
    assert [r["step_time_ms"]["p50"] for r in a] == [46.0, 47.0, 48.0,
                                                     49.0]  # newest 4
    assert len([r for r in rows if r["scenario"] == "b"]) == 3


def test_compact_ledger_env_knob_and_validation(tmp_path, monkeypatch):
    lpath = str(tmp_path / "l.jsonl")
    for i in range(5):
        ledger.append_row(_row(p50=40.0, ts=float(i)), lpath)
    monkeypatch.setenv("PTPU_LEDGER_KEEP", "2")
    assert ledger.compact_ledger(lpath) == (2, 3)
    with pytest.raises(ValueError):
        ledger.compact_ledger(lpath, keep=0)
    # an absent ledger compacts to nothing and is NOT created
    missing = str(tmp_path / "nope.jsonl")
    assert ledger.compact_ledger(missing) == (0, 0)
    assert not os.path.exists(missing)


def test_ledger_cli_compact_and_summary(tmp_path, capsys):
    lpath = str(tmp_path / "l.jsonl")
    for i in range(4):
        ledger.append_row(_row(p50=40.0, ts=float(i)), lpath)
    assert ledger.main(["--ledger", lpath]) == 0
    assert "4 row(s)" in capsys.readouterr().out
    assert ledger.main(["--ledger", lpath, "--compact",
                        "--keep", "1"]) == 0
    assert "dropped 3" in capsys.readouterr().out
    assert len(ledger.read_ledger(lpath)) == 1


# -- robust statistics ------------------------------------------------------
def test_median_mad_theil_sen():
    assert trends.median([3.0, 1.0, 2.0]) == 2.0
    assert trends.median([1.0, 2.0, 3.0, 4.0]) == 2.5
    assert trends.median([]) is None
    assert trends.mad([1.0, 1.0, 5.0]) == 0.0  # median dev from 1.0
    assert trends.mad([1.0, 2.0, 3.0, 100.0]) == 1.0
    assert trends.theil_sen([1.0, 2.0, 3.0, 4.0]) == pytest.approx(1.0)
    # one outlier does not move the Theil-Sen slope much
    assert trends.theil_sen([1.0, 2.0, 50.0, 4.0, 5.0]) == pytest.approx(
        1.0, abs=0.5)


def test_sigma_from_diffs_is_shift_immune():
    flat = [50.0, 50.4, 49.8, 50.2, 49.9, 50.1]
    sigma = trends.sigma_from_diffs(flat)
    assert sigma is not None and sigma < 1.0
    # a 20% mean shift contaminates one diff; the MAD shrugs it off
    shifted = flat + [60.0, 60.3, 59.8, 60.1]
    assert trends.sigma_from_diffs(shifted) < 1.0
    assert trends.sigma_from_diffs([1.0, 2.0]) is None  # too short


# -- changepoints -----------------------------------------------------------
def test_changepoint_detected_on_clean_step():
    cps = trends.detect_changepoints([50.0, 60.0, 60.0])
    assert len(cps) == 1 and cps[0]["index"] == 1
    assert cps[0]["delta_frac"] == pytest.approx(0.20)
    assert cps[0]["direction"] == "up"
    cps = trends.detect_changepoints([50.0, 50.0, 50.0, 40.0, 40.0])
    assert len(cps) == 1 and cps[0]["index"] == 3
    assert cps[0]["direction"] == "down"


def test_changepoint_detected_under_jitter():
    vals = ([50.0, 51.2, 49.1, 50.6, 48.9, 50.3, 49.5, 51.0]
            + [60.4, 59.2, 61.1, 60.0, 59.5, 60.8])
    cps = trends.detect_changepoints(vals)
    assert len(cps) == 1 and cps[0]["index"] == 8
    assert cps[0]["delta_frac"] == pytest.approx(0.20, abs=0.04)


def test_pure_noise_yields_zero_changepoints():
    # hand-picked +-8% zero-mean jitter around 50 (deterministic)
    mults = [1.03, 0.95, 1.06, 0.97, 1.01, 0.94, 1.05, 0.99,
             1.02, 0.96, 1.07, 0.93, 1.00, 1.04, 0.98]
    vals = [50.0 * m for m in mults]
    assert trends.detect_changepoints(vals) == []
    # the tiny-series variant (3 deduped shas, jittered, no shift)
    assert trends.detect_changepoints([51.5, 47.5, 53.0]) == []


def test_small_series_demands_a_loud_shift():
    # below the small-series floor (12%): not evidence on 3 points
    assert trends.detect_changepoints([50.0, 55.0, 55.0]) == []
    # above it: evidence
    assert trends.detect_changepoints([50.0, 57.0, 57.0]) != []


def test_slow_linear_drift_is_flagged_not_missed():
    # +1.2%/point over 16 points crosses the floor; residual noise tiny
    vals = [50.0 * (1 + 0.012 * i) for i in range(16)]
    pts = [{"sha": f"s{i:02d}", "ts": float(i), "value": v, "row": {}}
           for i, v in enumerate(vals)]
    a = trends.analyze_series(pts)
    assert a["drift"] is not None and a["drift"]["flagged"]
    assert a["drift"]["direction"] == "up"
    assert a["drift"]["total_frac"] == pytest.approx(0.18, abs=0.03)
    # a flat jittery series has no flagged drift
    flat = [{"sha": f"s{i}", "ts": float(i), "value": 50.0 + (i % 3),
             "row": {}} for i in range(16)]
    flat_a = trends.analyze_series(flat)
    assert not (flat_a["drift"] and flat_a["drift"]["flagged"])


def test_analyze_series_trend_direction_and_sha_range():
    pts = [{"sha": f"s{i}", "ts": float(i), "value": v, "row": {}}
           for i, v in enumerate([50.0, 50.2, 49.8, 50.1, 60.0])]
    a = trends.analyze_series(pts)
    assert a["trend"] == "up"
    assert a["changepoints"], "the jump must register"
    assert a["changepoints"][-1]["sha_range"] == ("s3", "s4")
    down = [{"sha": f"s{i}", "ts": float(i), "value": v, "row": {}}
            for i, v in enumerate([50.0, 50.2, 49.8, 50.1, 40.0])]
    assert trends.analyze_series(down)["trend"] == "down"
    flat = [{"sha": f"s{i}", "ts": float(i), "value": 50.0, "row": {}}
            for i in range(5)]
    assert trends.analyze_series(flat)["trend"] == "flat"


def test_median_row_carries_perfdiff_fields():
    rows = [_row(p50=p, sha=s, ts=t,
                 phases={"data": d, "compute": p - d - 5.0,
                         "readback": 3.0, "collective": 2.0})
            for p, d, s, t in [(40.0, 4.0, "a", 1.0),
                               (50.0, 5.0, "b", 2.0),
                               (60.0, 6.0, "c", 3.0)]]
    mr = trends.median_row(rows)
    assert mr["step_time_ms"]["p50"] == 50.0
    assert mr["phases_ms"]["data"] == 5.0
    assert mr["git_sha"] == "median:3"
    assert mr["scenario"] == "moe" and mr["device_kind"] == "cpu"
    with pytest.raises(ValueError):
        trends.median_row([])


# -- the acceptance drill ---------------------------------------------------
def test_drill_shift_named_with_sha_range_and_phase(tmp_path, capsys):
    lpath = str(tmp_path / "l.jsonl")
    for r in _moe_drill_rows():
        ledger.append_row(r, lpath)
    analyses = trends.scan_ledger(path=lpath)
    assert [a["scenario"] for a in analyses] == ["moe"]
    step = analyses[0]["metrics"]["step_p50"]
    assert step["n"] == 3  # 12 rows, 3 shas, deduped
    cps = step["changepoints"]
    assert len(cps) == 1
    assert cps[0]["sha_range"] == ("aaaa1111", "bbbb2222")
    assert cps[0]["delta_frac"] == pytest.approx(0.16, abs=0.02)
    assert cps[0]["dominant_phase"] == "compute"
    # the CLI names all of it
    assert trends.main(["--ledger", lpath]) == 0
    out = capsys.readouterr().out
    assert "moe" in out and "aaaa1111..bbbb2222" in out
    assert "compute" in out and "+16" in out


def test_drill_jitter_no_shift_is_quiet_and_gate_green(tmp_path, capsys):
    # +-8% zero-mean jitter, no real shift anywhere
    jitter = [1.03, 0.95, 1.06, 0.97, 0.92, 1.01, 1.08, 0.99,
              1.02, 0.96, 1.05, 0.94]
    lpath = str(tmp_path / "l.jsonl")
    gpath = str(tmp_path / "g.json")
    rows = _moe_drill_rows(jitter=jitter, shift=False)
    for r in rows:
        ledger.append_row(r, lpath)
    analyses = trends.scan_ledger(path=lpath)
    assert analyses[0]["metrics"]["step_p50"]["changepoints"] == []
    # noise-aware gate: green (the trailing median + k*MAD absorbs it)
    ledger.write_golden(ledger.golden_from_rows(
        {"moe": rows[0]}), gpath)
    assert gate.run_gate(lpath, gpath) == 0
    assert "ok" in capsys.readouterr().out


def test_report_html_renders_both_series_self_contained(tmp_path):
    lpath = str(tmp_path / "l.jsonl")
    for r in _moe_drill_rows():                       # shifted series
        ledger.append_row(r, lpath)
    for i, m in enumerate([1.03, 0.95, 1.06, 0.97, 1.01, 0.99]):
        ledger.append_row(_row(scenario="gpt_pretrain_fused",
                               p50=40.0 * m, sha=f"sha{i}",
                               ts=100.0 + i), lpath)  # jittery-flat
    out = str(tmp_path / "report.html")
    assert report.write_report(path=out, ledger_path=lpath) == out
    doc = fsio.read_bytes(out).decode("utf-8")
    assert doc.strip()
    assert "moe" in doc and "gpt_pretrain_fused" in doc
    assert "<svg" in doc and "<polyline" in doc
    # the changepoint marker (dashed rule + dot) is drawn
    assert "stroke-dasharray" in doc and "<circle" in doc
    assert "aaaa1111..bbbb2222" in doc
    # self-contained: no network fetches, no scripts, no imports
    for banned in ("http://", "https://", "<script", "@import",
                   "url(", "src="):
        assert banned not in doc, banned
    # CLI round-trip
    assert report.main(["--ledger", lpath, "--out", out]) == 0


# -- the noise-aware gate ---------------------------------------------------
def _seed_gate(tmp_path, prior_p50s, cur_p50, scenario="moe"):
    lpath = str(tmp_path / "l.jsonl")
    gpath = str(tmp_path / "g.json")
    for i, p in enumerate(prior_p50s):
        ledger.append_row(_row(scenario=scenario, p50=p, ts=float(i)),
                          lpath)
    ledger.append_row(_row(scenario=scenario, p50=cur_p50,
                           ts=float(len(prior_p50s))), lpath)
    ledger.write_golden(ledger.golden_from_rows(
        {scenario: _row(scenario=scenario, p50=prior_p50s[0])}), gpath)
    return lpath, gpath


def test_gate_noise_aware_passes_jittery_but_flat(tmp_path, capsys):
    # priors jitter +-8% around 50 (MAD 3ms); the newest lands 12% above
    # the trailing median — the fixed 10% rule WOULD fail this
    priors = [46.0, 47.0, 48.0, 49.0, 50.0, 51.0, 52.0, 53.0, 54.0,
              46.5, 53.5]
    med = trends.median(priors)
    cur = 56.0
    assert cur > 1.10 * med           # the fixed rule's verdict: FAIL
    lpath, gpath = _seed_gate(tmp_path, priors, cur)
    assert gate.run_gate(lpath, gpath) == 0      # noise-aware: green
    out = capsys.readouterr().out
    assert "noise-raised" in out
    # ... and an explicit --threshold still means what it says
    assert gate.run_gate(lpath, gpath, threshold_frac=0.10) == 1


def test_gate_quiet_scenario_still_fails_on_regression(tmp_path, capsys):
    lpath, gpath = _seed_gate(tmp_path, [50.0, 50.1, 49.9, 50.0], 58.0)
    assert gate.run_gate(lpath, gpath) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "FAIL" in out


def test_gate_insufficient_history_is_advisory_rc0(tmp_path, capsys):
    # 2 rows < MIN_HISTORY: advisory, NOT a silent golden comparison —
    # even though the newest row is 50% up (would fail any raw compare)
    lpath, gpath = _seed_gate(tmp_path, [40.0], 60.0)
    assert gate.run_gate(lpath, gpath) == 0
    out = capsys.readouterr().out
    assert "insufficient history" in out
    assert "REGRESSION" not in out


# -- perfdiff --baseline median:N ------------------------------------------
def test_diff_baseline_median_compares_vs_trailing_median(tmp_path,
                                                          capsys):
    lpath = str(tmp_path / "l.jsonl")
    for i, p in enumerate([40.0, 41.0, 39.0, 40.5, 39.5]):
        ledger.append_row(_row(p50=p, sha=f"s{i}", ts=float(i)), lpath)
    ledger.append_row(_row(p50=48.0, sha="s9", ts=9.0), lpath)
    rc = perfdiff.main(["--baseline", "median:4", "--ledger", lpath])
    out = capsys.readouterr().out
    assert rc == 1                      # 48 vs ~40 median: regression
    assert "median:4" in out            # the pseudo-row names itself
    assert "REGRESSION" in out
    # median window of 1 = newest prior row only
    rc = perfdiff.main(["--baseline", "median:1", "--ledger", lpath,
                        "--scenario", "moe"])
    assert rc == 1
    capsys.readouterr()


def test_diff_baseline_median_needs_two_rows(tmp_path, capsys):
    lpath = str(tmp_path / "l.jsonl")
    ledger.append_row(_row(p50=40.0, ts=1.0), lpath)
    rc = perfdiff.main(["--baseline", "median:4", "--ledger", lpath])
    assert rc == 0
    assert "fewer than 2 rows" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        perfdiff.main(["--baseline", "median:0"])


# -- doctor / statusz wiring ------------------------------------------------
def test_doctor_perf_trend_names_scenario_sha_and_phase():
    from paddle_tpu.observability.doctor import check_perf_trend
    rows = _moe_drill_rows()
    workers = {0: [{"kind": "bench.row", "scenario": "moe",
                    "ts": 1.0}]}
    findings = check_perf_trend(workers, rows=rows)
    assert len(findings) == 1
    f = findings[0]
    assert f["kind"] == "perf_trend"
    assert "moe" in f["title"] and "bbbb2222" in f["title"]
    assert f["data"]["dominant"] == "compute"
    assert f["data"]["sha_range"] == ("aaaa1111", "bbbb2222")
    assert f["data"]["delta_frac"] == pytest.approx(0.16, abs=0.02)
    assert any("compute" in ev for ev in f["evidence"])


def test_doctor_perf_trend_gated_on_bench_rows():
    from paddle_tpu.observability.doctor import check_perf_trend
    rows = _moe_drill_rows()
    # no bench.row records in the window: the global ledger is someone
    # else's history — no findings
    workers = {0: [{"kind": "step", "step_time_ms": 50.0}]}
    assert check_perf_trend(workers, rows=rows) == []
    # benched a different scenario: still quiet
    workers = {0: [{"kind": "bench.row", "scenario": "mnist"}]}
    assert check_perf_trend(workers, rows=rows) == []


def test_trend_knobs_read_from_env(monkeypatch):
    monkeypatch.setenv("PTPU_TREND_WINDOW", "4")
    monkeypatch.setenv("PTPU_TREND_K", "9.0")
    assert trends.trend_window() == 4
    assert trends.trend_k() == 9.0
    monkeypatch.delenv("PTPU_TREND_WINDOW")
    monkeypatch.delenv("PTPU_TREND_K")
    assert trends.trend_window() == trends.DEFAULT_WINDOW
    assert trends.trend_k() == trends.DEFAULT_K


def test_trends_cli_json_mode(tmp_path, capsys):
    lpath = str(tmp_path / "l.jsonl")
    for r in _moe_drill_rows():
        ledger.append_row(r, lpath)
    assert trends.main(["--ledger", lpath, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload[0]["scenario"] == "moe"
    assert payload[0]["metrics"]["step_p50"]["n"] == 3
    # an empty ledger renders the hint, not a crash
    assert trends.main(["--ledger", str(tmp_path / "empty.jsonl")]) == 0
    assert "no ledger series" in capsys.readouterr().out
