"""State-integrity guard tests (ISSUE 11).

Four layers, bottom-up:

- the tree fingerprint: device/host bit-identical digests, single-bit
  detection, zero-padding (dp-width) invariance, rank-private exclusion
  (the cross-width relayout invariance drill piggybacks on
  test_elastic_fleet.py's ZeRO-1 fixtures);
- checkpoint round-trip verification: the live-tree digest stamped into
  the manifest catches corruption that happened BETWEEN the in-memory
  hash and the on-disk CRC computation — the window CRCs can't see;
- the guard: board publication, majority-vote attribution, replay-audit
  classification (nondeterminism / sdc_suspect / desync), the healing
  ladder (resync → rollback → evict);
- the e2e drill: a 3-replica fleet, one cosmic ray, detection within
  one interval, correct attribution, a resync heal, and a final loss
  bit-equal to the un-faulted reference.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.distributed import elastic as el
from paddle_tpu.distributed.checkpoint import (DigestMismatch, load_sharded,
                                               read_integrity, save_sharded,
                                               verify_sharded)
from paddle_tpu.distributed.fingerprint import (DEFAULT_EXCLUDE,
                                                TreeFingerprint,
                                                digest_tree_host,
                                                leaf_name_weight,
                                                tree_digest)
from paddle_tpu.hapi import Model
from paddle_tpu.observability.doctor import check_integrity
from paddle_tpu.supervisor import RunSupervisor
from paddle_tpu.supervisor.integrity import IntegrityGuard
from paddle_tpu.testing.faults import bitflip, flip_tree_bit

pytestmark = pytest.mark.integrity


def _tree(seed=0):
    rng = np.random.RandomState(seed)
    return {"params": {"w": jnp.asarray(rng.randn(37, 19), jnp.float32),
                       "b": jnp.asarray(rng.randn(11), jnp.float32),
                       "emb": jnp.asarray(rng.randn(24, 8), jnp.bfloat16)},
            "opt": {"step": jnp.asarray(3, jnp.int32),
                    "m": jnp.asarray(rng.randn(64), jnp.float32)}}


class TestFingerprint:
    def test_device_host_bit_identical(self):
        tree = _tree()
        fp = TreeFingerprint()
        dev = fp.digest(tree)
        host = digest_tree_host(tree)
        assert dev.hex() == host.hex()
        assert dev.leaf_digests() == host.leaf_digests()

    @pytest.mark.parametrize("dtype,bit", [
        ("float32", 0), ("float32", 17), ("float32", 31),
        ("bfloat16", 0), ("bfloat16", 15),
        ("int8", 3), ("bool", 0), ("uint8", 7), ("int32", 30),
    ])
    def test_single_bit_flip_detected_and_attributed(self, dtype, bit):
        rng = np.random.RandomState(1)
        x = rng.randn(33) * 3
        leaf = (jnp.asarray(x > 0) if dtype == "bool"
                else jnp.asarray(x, dtype))
        tree = {"a": jnp.asarray(rng.randn(7), jnp.float32), "victim": leaf}
        d0 = digest_tree_host(tree)
        flipped = flip_tree_bit(tree, "victim", bit=bit, index=5)
        d1 = digest_tree_host(flipped)
        assert d0.tree != d1.tree
        assert d0.diff(d1) == ["victim"]

    def test_trailing_zero_padding_invariance(self):
        """The ZeRO-1 relayout invariance: zero lanes contribute nothing,
        so the same real elements padded to different widths hash
        identically (repack_flat's contract: padding is trailing
        zeros)."""
        rng = np.random.RandomState(2)
        real = rng.randn(714).astype(np.float32)
        digests = set()
        for padded in (714, 716, 720):
            flat = np.zeros(padded, np.float32)
            flat[:714] = real
            digests.add(tree_digest({"flat": jnp.asarray(flat)}))
        assert len(digests) == 1

    def test_rank_private_leaves_excluded_with_accounting(self):
        tree = _tree()
        tree["resid"] = {"w": jnp.asarray(np.ones(5), jnp.float32)}
        fp = TreeFingerprint()
        d0 = fp.digest(tree)
        assert "resid/w" in d0.excluded
        assert "resid/w" not in d0.names
        # changing a rank-private leaf does not move the digest
        tree["resid"]["w"] = jnp.asarray(np.full(5, 9.0), jnp.float32)
        assert fp.digest(tree).hex() == d0.hex()
        # ... but it IS accounted in the meta
        meta = d0.meta()
        assert meta["excluded"] == ["resid/w"]
        assert meta["algo"] == "mlh32/1"

    def test_insertion_order_invariance(self):
        rng = np.random.RandomState(3)
        a = jnp.asarray(rng.randn(4), jnp.float32)
        b = jnp.asarray(rng.randn(6), jnp.float32)
        assert tree_digest({"a": a, "b": b}) == tree_digest({"b": b, "a": a})

    def test_empty_tree(self):
        fp = TreeFingerprint()
        assert fp.digest({}).tree == 0

    def test_name_weight_is_odd(self):
        for name in ("params/w", "opt/m", "x"):
            assert leaf_name_weight(name) % 2 == 1


class TestCheckpointDigest:
    def _save(self, tmp_path, tree):
        fp = TreeFingerprint()
        meta = fp.digest(tree).meta()
        meta["exclude"] = list(fp.exclude)
        path = str(tmp_path / "step-1")
        save_sharded(tree, path, integrity=meta)
        return path, meta

    def test_round_trip_verified(self, tmp_path):
        tree = _tree()
        path, meta = self._save(tmp_path, tree)
        stamped = read_integrity(path)
        assert stamped["tree"] == meta["tree"]
        restored = load_sharded(path, jax.tree_util.tree_map(
            lambda x: x, tree))
        assert digest_tree_host(restored).hex() == meta["tree"]

    def test_corruption_between_hash_and_crc(self, tmp_path):
        """The acceptance scenario: state corrupted AFTER the digest was
        computed but BEFORE the shard bytes + CRCs were written.  The
        CRCs cover the corrupt bytes (verify_sharded passes) — only the
        stamped live-tree digest catches it, naming the leaf."""
        tree = _tree()
        fp = TreeFingerprint()
        meta = fp.digest(tree).meta()
        meta["exclude"] = list(fp.exclude)
        corrupt = flip_tree_bit(tree, "params/w", bit=9, index=11)
        path = str(tmp_path / "step-1")
        save_sharded(corrupt, path, integrity=meta)
        assert verify_sharded(path) == []     # CRCs are consistent...
        with pytest.raises(DigestMismatch) as ei:
            load_sharded(path, jax.tree_util.tree_map(lambda x: x, tree))
        assert "params/w" in str(ei.value)    # ...the digest names the leaf

    def test_verify_digest_off_loads_corrupt(self, tmp_path):
        tree = _tree()
        fp = TreeFingerprint()
        meta = fp.digest(tree).meta()
        corrupt = flip_tree_bit(tree, "params/w", bit=9)
        path = str(tmp_path / "step-1")
        save_sharded(corrupt, path, integrity=meta)
        restored = load_sharded(path, jax.tree_util.tree_map(
            lambda x: x, tree), verify_digest=False)
        assert restored is not None

    def test_unstamped_checkpoint_loads(self, tmp_path):
        tree = _tree()
        path = str(tmp_path / "step-1")
        save_sharded(tree, path)
        assert read_integrity(path) is None
        load_sharded(path, jax.tree_util.tree_map(lambda x: x, tree))


class TestRestoreFallback:
    def _mgr(self, tmp_path, events):
        mgr = el.ElasticTrainState(str(tmp_path / "ck"),
                                   install_sigterm_handler=False,
                                   fingerprint=TreeFingerprint())
        mgr.set_event_sink(lambda kind, **f: events.append((kind, f)))
        return mgr

    def test_digest_mismatch_quarantined_and_named(self, tmp_path):
        events = []
        mgr = self._mgr(tmp_path, events)
        tree = _tree()
        mgr.save(10, tree, use_async=False)
        mgr.save(20, tree, use_async=False)
        # rewrite step-20's stamped digest: the state no longer matches
        man = os.path.join(mgr.directory, "step-20", "manifest-p0.json")
        payload = json.loads(open(man).read())
        payload["integrity"]["tree"] = "deadbeef"
        with open(man, "w") as f:  # noqa: fsio — deliberate corruption
            f.write(json.dumps(payload))
        state, start = mgr.restore_or(
            lambda: _tree(), lambda: jax.tree_util.tree_map(
                lambda x: x, tree))
        assert start == 11                      # fell back to step 10
        fallbacks = [f for k, f in events if k == "restore.fallback"]
        assert any(f["reason"] == "digest mismatch" and f["step"] == 20
                   for f in fallbacks), fallbacks
        assert os.path.isdir(os.path.join(mgr.directory, "step-20.corrupt"))

    def test_missing_committed_marker_reported(self, tmp_path):
        events = []
        mgr = self._mgr(tmp_path, events)
        tree = _tree()
        mgr.save(10, tree, use_async=False)
        # a torn save: step dir without the COMMITTED marker
        os.makedirs(os.path.join(mgr.directory, "step-20"))
        state, start = mgr.restore_or(
            lambda: _tree(), lambda: jax.tree_util.tree_map(
                lambda x: x, tree))
        assert start == 11
        fallbacks = [f for k, f in events if k == "restore.fallback"]
        assert any(f["reason"] == "missing COMMITTED" and f["step"] == 20
                   for f in fallbacks), fallbacks


class TestGuardCompare:
    def _guards(self, tmp_path, n=3, **kw):
        return [IntegrityGuard(str(tmp_path), worker_id=i, every=2,
                               expected=n, action="resync", **kw)
                for i in range(n)]

    def test_majority_names_minority(self, tmp_path):
        g0, g1, g2 = self._guards(tmp_path)
        tree = _tree()
        bad = flip_tree_bit(tree, "params/w", bit=3)
        g0.publish(4, g0.fingerprint.digest(tree))
        g1.publish(4, g1.fingerprint.digest(tree))
        g2.publish(4, g2.fingerprint.digest(bad))
        v = g0.compare()
        assert not v.ok and v.suspects == [2] and not v["ambiguous"]
        assert v["majority"] == g0.fingerprint.digest(tree).hex()

    def test_two_way_split_is_ambiguous(self, tmp_path):
        g0, g1 = self._guards(tmp_path, n=2)
        tree = _tree()
        bad = flip_tree_bit(tree, "params/w", bit=3)
        g0.publish(4, g0.fingerprint.digest(tree))
        g1.publish(4, g1.fingerprint.digest(bad))
        v = g0.compare()
        assert not v.ok and v["ambiguous"] and v.suspects == []

    def test_waits_for_all_expected_members(self, tmp_path):
        g0, g1, g2 = self._guards(tmp_path)
        g0.publish(4, g0.fingerprint.digest(_tree()))
        v = g0.compare()
        assert v.ok and v["step"] is None       # nobody else published yet

    def test_history_finds_common_step_across_skew(self, tmp_path):
        g0, g1, g2 = self._guards(tmp_path)
        tree = _tree()
        for g in (g0, g1, g2):
            g.publish(2, g.fingerprint.digest(tree))
        g0.publish(4, g0.fingerprint.digest(tree))  # g0 ran ahead
        v = g0.compare()
        assert v.ok and v["step"] == 2          # newest ALL have

    def test_maybe_check_interval_gating(self, tmp_path):
        (g,) = self._guards(tmp_path, n=1)
        tree = _tree()
        assert g.maybe_check(1, tree) is None
        assert g.maybe_check(2, tree) is not None
        assert g.checks == 1

    def test_disabled_guard(self, tmp_path):
        g = IntegrityGuard(str(tmp_path), every=0)
        assert not g.enabled
        assert g.maybe_check(2, _tree()) is None


class TestReplayAudit:
    def test_classification(self, tmp_path):
        g = IntegrityGuard(str(tmp_path), every=2, expected=1)
        tree = _tree()
        g.last_fingerprint = g.fingerprint.digest(tree)
        g.stash_replay(2, tree, None)
        # replays reproduce the live state → desync (upstream divergence)
        assert g.audit(lambda s, i: s)["verdict"] == "desync"
        # replays agree with each other, not with live → hardware SDC
        other = flip_tree_bit(tree, "params/w", bit=3)
        assert g.audit(lambda s, i: other)["verdict"] == "sdc_suspect"
        # replays disagree with each other → software nondeterminism
        seq = [tree, other]
        assert g.audit(
            lambda s, i: seq.pop(0))["verdict"] == "nondeterminism"

    def test_unavailable_without_stash_or_fn(self, tmp_path):
        g = IntegrityGuard(str(tmp_path), every=2)
        assert g.audit()["verdict"] == "unavailable"
        g.stash_replay(2, _tree(), None)
        assert g.audit()["verdict"] == "unavailable"


class TestHealingLadder:
    def test_offer_and_take_resync(self, tmp_path):
        g0 = IntegrityGuard(str(tmp_path), worker_id=0, every=2, expected=2,
                            action="resync", resync_timeout=2.0)
        g2 = IntegrityGuard(str(tmp_path), worker_id=2, every=2, expected=2,
                            action="resync", resync_timeout=2.0)
        tree = _tree()
        tree["resid"] = {"w": jnp.asarray(np.ones(5), jnp.float32)}
        g0.offer_resync(4, tree)
        healed = g2.take_resync(4, lambda: jax.tree_util.tree_map(
            lambda x: x, tree))
        assert healed is not None
        assert digest_tree_host(healed).hex() == \
            digest_tree_host(tree).hex()
        # adopted state has rank-private leaves RESET, not copied
        np.testing.assert_array_equal(np.asarray(healed["resid"]["w"]),
                                      np.zeros(5, np.float32))

    def test_take_resync_times_out(self, tmp_path):
        g = IntegrityGuard(str(tmp_path), worker_id=1, every=2,
                           resync_timeout=0.2)
        assert g.take_resync(4, lambda: _tree()) is None

    def test_resync_offers_gc_to_newest_two(self, tmp_path):
        g = IntegrityGuard(str(tmp_path), worker_id=0, every=2)
        tree = _tree()
        for step in (2, 4, 6):
            g.offer_resync(step, tree)
        left = sorted(n for n in os.listdir(str(tmp_path / "integrity"))
                      if n.startswith("resync-step-"))
        assert left == ["resync-step-4", "resync-step-6"]


class TestDoctorVerdicts:
    def test_desync_and_sdc_findings(self):
        events = [
            {"kind": "integrity.desync", "step": 4,
             "digests": {"0": "aa", "1": "aa", "2": "bb"},
             "majority": "aa", "suspects": [2], "ambiguous": False},
            {"kind": "integrity.audit", "verdict": "sdc_suspect",
             "step": 4, "replay": "aa", "replay2": "aa", "live": "bb"},
            {"kind": "integrity.heal", "step": 4, "action": "resync",
             "suspect": True},
        ]
        findings = check_integrity(events)
        kinds = {f["kind"] for f in findings}
        assert kinds == {"desync", "sdc_suspect"}
        sdc = next(f for f in findings if f["kind"] == "sdc_suspect")
        desync = next(f for f in findings if f["kind"] == "desync")
        assert sdc["severity"] > desync["severity"]
        assert any("worker 2" in ev for ev in desync["evidence"])
        assert any("resync" in ev for ev in desync["evidence"])

    def test_healthy_run_no_findings(self):
        assert check_integrity([{"kind": "integrity.check", "ok": True}]) \
            == []


# -- the e2e drill ---------------------------------------------------------
class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)

    def forward(self, x):
        return self.fc(x)


def _make_worker(run_dir, worker_id, n_workers):
    pt.seed(7)                    # identical init across replicas
    net = _Net()
    m = Model(net)
    m.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                         parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    guard = IntegrityGuard(run_dir, worker_id=worker_id, every=2,
                           expected=n_workers, action="resync",
                           resync_timeout=5.0)
    sup = RunSupervisor(
        run_dir, worker_id=worker_id, expected_workers=n_workers,
        sigterm_handler=False, integrity=guard,
        report_path=os.path.join(
            run_dir, "supervisor_report.json" if worker_id == 0
            else f"supervisor_report-{worker_id}.json"))
    m._supervisor = sup
    return m, sup


class TestE2EDrill:
    def test_bitflip_detected_attributed_healed(self, tmp_path):
        """The ISSUE 11 acceptance drill: 3 replicas in lockstep, one
        bit flipped on worker 2 between a step and its digest.  The
        interval check must catch it at the very next boundary, the
        vote must name worker 2, the replay audit must classify
        hardware SDC (the replays agree with each other, not with the
        live state), the resync heal must complete the run, and the
        final loss must be bit-equal to an un-faulted reference."""
        run_dir = str(tmp_path / "run")
        N_WORKERS, STEPS, FLIP_STEP = 3, 8, 4
        workers = [_make_worker(run_dir, i, N_WORKERS)
                   for i in range(N_WORKERS)]
        fault = bitflip("params/fc.weight", bit=13, step=FLIP_STEP,
                        worker=2)
        rng = np.random.RandomState(0)
        batches = [(rng.randn(8, 8).astype("float32"),
                    (np.arange(8) % 4).astype("int64"))
                   for _ in range(STEPS)]
        losses = {i: [] for i in range(N_WORKERS)}
        for step0, (xs, ys) in enumerate(batches):
            step = step0 + 1
            for i, (m, sup) in enumerate(workers):
                loss, _ = m.train_batch(xs, ys)
                losses[i].append(loss)
                # the cosmic ray: flip AFTER the computed step, BEFORE
                # the digest — the replay-auditable SDC signature
                st = fault(step, m._supervised_state(), worker=i)
                m._load_supervised_state(st)
                sup.note_step_ok(m._supervised_state())
            # fleet barrier: re-vote now that every board landed
            for m, sup in workers:
                sup.recheck_integrity()
            # healing pass, majority members first (they serve the offer)
            suspects = set()
            for m, sup in workers:
                if sup.pending_integrity is not None:
                    suspects.update(sup.pending_integrity["suspects"])
            for i, (m, sup) in enumerate(workers):
                if sup.pending_integrity is not None and i not in suspects:
                    m._supervised_integrity_heal(sup)
            for i, (m, sup) in enumerate(workers):
                if sup.pending_integrity is not None:
                    m._supervised_integrity_heal(sup)
        assert fault.fired == FLIP_STEP
        # detection within ONE interval of the flip
        g2 = workers[2][1].integrity
        assert g2.mismatches >= 1
        desyncs = workers[0][1].report.of_kind("integrity.desync")
        assert desyncs and desyncs[0]["step"] == FLIP_STEP
        assert desyncs[0]["suspects"] == [2]        # correct attribution
        # the replay audit pinned it as hardware SDC on the suspect
        heals = workers[2][1].report.of_kind("integrity.heal")
        healed = [h for h in heals if h.get("action") == "resync"]
        assert healed and healed[0]["audit"]["verdict"] == "sdc_suspect"
        # majority members served the offer
        assert any(h.get("action") == "offer" for h in
                   workers[0][1].report.of_kind("integrity.heal"))
        # post-heal: every replica converged to the same state...
        finals = [digest_tree_host(m._supervised_state()).hex()
                  for m, _ in workers]
        assert len(set(finals)) == 1, finals
        # ...and no further mismatches after the heal interval
        assert all(w[1].integrity.last_verdict.ok for w in workers)
        # loss parity with the un-faulted reference, bit-equal
        pt.seed(7)
        ref_net = _Net()
        ref = Model(ref_net)
        ref.prepare(optimizer=pt.optimizer.SGD(
            learning_rate=0.1, parameters=ref_net.parameters()),
            loss=nn.CrossEntropyLoss())
        ref_losses = [ref.train_batch(xs, ys)[0] for xs, ys in batches]
        assert ref_losses[-1] == losses[0][-1]
        assert digest_tree_host(ref._supervised_state()).hex() == finals[0]
        # the healed worker diverged only inside the detection window
        assert losses[2][:FLIP_STEP] == ref_losses[:FLIP_STEP]
        assert losses[2][-1] == ref_losses[-1]

    def test_statusz_integrity_section(self, tmp_path):
        from paddle_tpu.observability.monitor import StatusServer
        run_dir = str(tmp_path / "run")
        m, sup = _make_worker(run_dir, 0, 1)
        xs = np.random.RandomState(0).randn(8, 8).astype("float32")
        ys = (np.arange(8) % 4).astype("int64")
        for _ in range(4):
            m.train_batch(xs, ys)
            sup.note_step_ok(m._supervised_state())
        sz = StatusServer(supervisor=sup).statusz()
        integ = sz["integrity"]
        assert integ["enabled"] and integ["interval"] == 2
        assert integ["checks"] == 2 and integ["mismatches"] == 0
        assert integ["last_digest"] is not None
        assert integ["last_verdict"]["ok"] is True
