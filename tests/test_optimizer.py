"""Optimizer/LR/clip/AMP tests (reference: unittests/test_adam_op.py family)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import amp, nn, optimizer as opt


def _quadratic_setup():
    """Minimize ||Wx - y||^2 over W; convex, any optimizer should descend.

    Returns the problem's OPTIMAL loss too: with random y the optimum is
    a large irreducible residual that depends on the draw (and therefore
    on the jax version's key stream), so descent must be judged on the
    excess loss above it, not on the raw value."""
    model = nn.Linear(4, 4, bias_attr=False)
    x = pt.randn((32, 4))
    y = pt.randn((32, 4))
    w_opt, *_ = np.linalg.lstsq(np.asarray(x), np.asarray(y), rcond=None)
    l_opt = float(np.mean((np.asarray(x) @ w_opt - np.asarray(y)) ** 2))

    def loss_fn(params):
        return jnp.mean((model.apply(params, x) - y) ** 2)

    return model, loss_fn, l_opt


@pytest.mark.parametrize("cls,kwargs", [
    (opt.SGD, dict(learning_rate=0.1)),
    (opt.Momentum, dict(learning_rate=0.05, momentum=0.9)),
    (opt.Adam, dict(learning_rate=0.05)),
    (opt.AdamW, dict(learning_rate=0.05, weight_decay=0.01)),
    (opt.Lamb, dict(learning_rate=0.05)),
    (opt.RMSProp, dict(learning_rate=0.01)),
    (opt.Adagrad, dict(learning_rate=0.1)),
    (opt.AdamMax, dict(learning_rate=0.05)),
])
def test_optimizer_descends(cls, kwargs):
    model, loss_fn, l_opt = _quadratic_setup()
    o = cls(**kwargs)
    params = model.trainable_variables()
    state = o.init(params)
    l0 = float(loss_fn(params))
    for _ in range(60):
        grads = jax.grad(loss_fn)(params)
        params, state = o.apply_gradients(grads, params, state)
    # at least halve the excess loss over the analytic optimum
    assert float(loss_fn(params)) - l_opt < 0.5 * (l0 - l_opt)


def test_adam_matches_reference_formula():
    """Single-step Adam vs hand-computed update (reference adam_op.cc)."""
    p = jnp.asarray([1.0, -2.0, 3.0])
    g = jnp.asarray([0.1, 0.2, -0.3])
    o = opt.Adam(learning_rate=0.1, beta1=0.9, beta2=0.999, epsilon=1e-8)
    state = o.init({"p": p})
    newp, state = o.apply_gradients({"p": g}, {"p": p}, state)
    m = 0.1 * np.asarray(g)
    v = 0.001 * np.asarray(g) ** 2
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    want = np.asarray(p) - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(np.asarray(newp["p"]), want, rtol=1e-6)


def test_stateful_step_updates_parameters():
    model = nn.Linear(3, 2)
    o = opt.SGD(learning_rate=1.0, parameters=model.parameters())
    w_before = model.weight.numpy().copy()
    grads = [jnp.ones_like(p.value) for p in model.parameters()]
    o.step(grads)
    np.testing.assert_allclose(model.weight.numpy(), w_before - 1.0, rtol=1e-6)


def test_master_weights_bf16():
    """multi_precision: bf16 params keep an fp32 master copy; tiny updates
    accumulate instead of being rounded away (reference multi_precision attr)."""
    p = jnp.asarray([1.0], jnp.bfloat16)
    o = opt.SGD(learning_rate=1e-4, multi_precision=True)
    params = {"p": p}
    state = o.init(params)
    assert state["master"]["p"].dtype == jnp.float32
    for _ in range(10):
        params, state = o.apply_gradients({"p": jnp.ones_like(p)}, params, state)
    # master tracked 10 * 1e-4 even though single bf16 step would round to no-op
    np.testing.assert_allclose(float(state["master"]["p"][0]), 1.0 - 1e-3,
                               rtol=1e-4)


def test_grad_clip_by_global_norm():
    clip = opt.ClipGradByGlobalNorm(1.0)
    grads = {"a": jnp.asarray([3.0, 4.0]), "b": jnp.asarray([0.0])}
    out = clip(grads)
    total = float(jnp.sqrt(sum(jnp.sum(v ** 2) for v in out.values())))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_lr_schedules():
    s = opt.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
    assert abs(float(s(5)) - 0.05) < 1e-6
    assert abs(float(s(20)) - 0.1) < 1e-6
    c = opt.lr.CosineAnnealingDecay(1.0, T_max=100)
    assert abs(float(c(0)) - 1.0) < 1e-6
    assert float(c(100)) < 1e-6
    n = opt.lr.NoamDecay(d_model=512, warmup_steps=4000)
    assert float(n(1)) < float(n(4000))
    # stateful parity
    st = opt.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    st.step(); st.step()
    assert abs(st.get_lr() - 0.05) < 1e-9


def test_scheduler_inside_optimizer():
    model, loss_fn, _ = _quadratic_setup()
    sched = opt.lr.StepDecay(0.1, step_size=5, gamma=0.5)
    o = opt.SGD(learning_rate=sched)
    params = model.trainable_variables()
    state = o.init(params)
    grads = jax.grad(loss_fn)(params)
    p1, state = o.apply_gradients(grads, params, state)
    assert np.isfinite(np.asarray(p1["weight"])).all()


class TestAmp:
    def test_auto_cast_o1_casts_matmul(self):
        x = jnp.ones((4, 4), jnp.float32)
        with amp.auto_cast(level="O1"):
            y = nn.functional.matmul(x, x)
        assert y.dtype == jnp.bfloat16
        # black-list op stays fp32
        with amp.auto_cast(level="O1"):
            s = nn.functional.softmax(jnp.ones((4,), jnp.bfloat16))
        assert s.dtype == jnp.float32

    def test_no_cast_outside_context(self):
        x = jnp.ones((4, 4), jnp.float32)
        y = nn.functional.matmul(x, x)
        assert y.dtype == jnp.float32

    def test_grad_scaler_state_machine(self):
        sc = amp.GradScaler(init_loss_scaling=8.0, incr_every_n_steps=2,
                            decr_every_n_nan_or_inf=1, incr_ratio=2.0,
                            decr_ratio=0.5)
        st = sc.init_state()
        # two good steps -> scale doubles
        st = sc.update_state(st, jnp.asarray(False))
        st = sc.update_state(st, jnp.asarray(False))
        assert float(st["scale"]) == 16.0
        # one bad step -> halves
        st = sc.update_state(st, jnp.asarray(True))
        assert float(st["scale"]) == 8.0

    def test_grad_scaler_detects_inf(self):
        sc = amp.GradScaler(init_loss_scaling=4.0)
        st = sc.init_state()
        grads = {"w": jnp.asarray([1.0, np.inf])}
        _, found = sc.unscale_and_check(grads, st)
        assert bool(found)
        grads = {"w": jnp.asarray([4.0, 8.0])}
        unscaled, found = sc.unscale_and_check(grads, st)
        assert not bool(found)
        np.testing.assert_allclose(np.asarray(unscaled["w"]), [1.0, 2.0])

    def test_scaled_training_step_bf16(self):
        model = nn.Linear(4, 4, bias_attr=False)
        amp.decorate(model, level="O2")
        assert model.weight.dtype == jnp.bfloat16
        x = pt.randn((8, 4)).astype(jnp.bfloat16)
        y = pt.randn((8, 4)).astype(jnp.bfloat16)
        o = opt.Adam(learning_rate=0.01, multi_precision=True)
        sc = amp.GradScaler(enable=False)  # bf16: no scaling needed
        params = model.trainable_variables()
        state = o.init(params)

        @jax.jit
        def step(params, state):
            def loss_fn(p):
                with amp.auto_cast(level="O2"):
                    out = model.apply(p, x)
                return jnp.mean((out.astype(jnp.float32) -
                                 y.astype(jnp.float32)) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = o.apply_gradients(grads, params, state)
            return loss, params, state

        l0, params, state = step(params, state)
        for _ in range(20):
            loss, params, state = step(params, state)
        assert float(loss) < float(l0)


def test_pylayer_custom_grad():
    class Cube(pt.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x ** 3

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensor
            return 3 * x ** 2 * g

    x = jnp.asarray(2.0)
    g = jax.grad(lambda x: Cube.apply(x))(x)
    np.testing.assert_allclose(float(g), 12.0)


def test_save_load_roundtrip(tmp_path):
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path / "model.pdparams")
    pt.save(model.state_dict(), path)
    model2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model2.set_state_dict(pt.load(path))
    x = pt.randn((2, 4))
    np.testing.assert_allclose(np.asarray(model(x)), np.asarray(model2(x)))


def test_save_load_bf16(tmp_path):
    sd = {"w": jnp.ones((3,), jnp.bfloat16)}
    path = str(tmp_path / "bf16.pdparams")
    pt.save(sd, path)
    back = pt.load(path)
    assert back["w"].dtype == jnp.bfloat16


def test_scheduler_stateful_step_uses_scheduler_epoch():
    """Stateful path honors the user-driven scheduler (paddle convention),
    not the optimizer's internal iteration count."""
    model = nn.Linear(2, 2, bias_attr=False)
    sched = opt.lr.StepDecay(1.0, step_size=1, gamma=0.1)
    o = opt.SGD(learning_rate=sched, parameters=model.parameters())
    g = [jnp.ones_like(p.value) for p in model.parameters()]
    w0 = model.weight.numpy().copy()
    o.step(g)  # epoch 0 -> lr 1.0
    np.testing.assert_allclose(model.weight.numpy(), w0 - 1.0, rtol=1e-6)
    sched.step()  # user advances an epoch -> lr 0.1
    w1 = model.weight.numpy().copy()
    o.step(g)
    np.testing.assert_allclose(model.weight.numpy(), w1 - 0.1, rtol=1e-5)


def test_adamw_decay_param_fun_gets_names():
    params = {"linear.weight": jnp.ones((2, 2)), "linear.bias": jnp.ones((2,))}
    seen = []
    def decay(name):
        seen.append(name)
        return "bias" not in name
    o = opt.AdamW(learning_rate=0.1, weight_decay=0.5,
                  apply_decay_param_fun=decay)
    state = o.init(params)
    g = {k: jnp.zeros_like(v) for k, v in params.items()}
    newp, _ = o.apply_gradients(g, params, state)
    assert any("linear.weight" in s for s in seen)
    # zero grads: only decayed params move
    assert float(jnp.abs(newp["linear.bias"] - 1.0).max()) < 1e-7
    assert float(jnp.abs(newp["linear.weight"] - 1.0).max()) > 1e-4


def test_grad_scaler_step_pulls_param_grads():
    model = nn.Linear(2, 2, bias_attr=False)
    o = opt.SGD(learning_rate=1.0, parameters=model.parameters())
    sc = amp.GradScaler(init_loss_scaling=4.0)
    w0 = model.weight.numpy().copy()
    model.weight._grad = jnp.full((2, 2), 4.0)  # pretend scaled grads
    sc.step(o)
    np.testing.assert_allclose(model.weight.numpy(), w0 - 1.0, rtol=1e-6)


def test_missing_keys_strict():
    m = nn.Linear(2, 2)
    with pytest.raises(KeyError, match="missing"):
        m.set_state_dict({"weight": jnp.zeros((2, 2))})


class TestLars:
    def test_converges_on_quadratic(self):
        import paddle_tpu as pt
        w = jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32)
        target = jnp.asarray(np.random.RandomState(1).randn(8, 4),
                             jnp.float32)
        opt = pt.optimizer.Lars(learning_rate=1.0, momentum=0.9,
                                lars_coeff=0.002, lars_weight_decay=0.0)
        params = {"w": w}
        state = opt.init(params)
        loss0 = float(jnp.sum((w - target) ** 2))
        for _ in range(400):
            grads = jax.grad(
                lambda p: jnp.sum((p["w"] - target) ** 2))(params)
            params, state = opt.apply_gradients(grads, params, state)
        # LARS steps have magnitude ~ lars_coeff*||w|| (independent of the
        # residual), so assert strong loss reduction, not tight convergence
        loss = float(jnp.sum((params["w"] - target) ** 2))
        assert loss < 0.02 * loss0, (loss0, loss)

    def test_layerwise_trust_ratio_scales_update(self):
        import paddle_tpu as pt
        # two params, same gradient, very different norms → different
        # effective lrs (the LARS property)
        big = {"w": jnp.full((4,), 100.0)}
        small = {"w": jnp.full((4,), 0.01)}
        g = {"w": jnp.ones((4,))}
        opt = pt.optimizer.Lars(learning_rate=1.0, momentum=0.0,
                                lars_weight_decay=0.0)
        sb = opt.init(big)
        ss = opt.init(small)
        nb, _ = opt.apply_gradients(g, big, sb)
        ns, _ = opt.apply_gradients(g, small, ss)
        step_big = float(jnp.abs(nb["w"] - big["w"])[0])
        step_small = float(jnp.abs(ns["w"] - small["w"])[0])
        assert step_big > 100 * step_small


class TestOptimizerMethodParity:
    """Reference Optimizer public-method contract, round-5 completion:
    backward/minimize (callable-loss form), append_regularization_ops,
    get_opti_var_name_list."""

    def test_reference_optimizer_methods_all_present(self):
        import ast
        import os
        ref = "/root/reference/python/paddle/optimizer/optimizer.py"
        if not os.path.exists(ref):
            pytest.skip("reference not present")
        tree = ast.parse(open(ref).read())
        names = [n.name for node in ast.walk(tree)
                 if isinstance(node, ast.ClassDef)
                 and node.name == "Optimizer"
                 for n in node.body if isinstance(n, ast.FunctionDef)
                 and not n.name.startswith("_")]
        from paddle_tpu.optimizer import Optimizer
        missing = [m for m in names if not hasattr(Optimizer, m)]
        assert not missing, missing

    def test_minimize_trains_callable_loss(self):
        pt.seed(0)
        lin = nn.Linear(3, 1)
        params = [p for _, p in lin.named_parameters()]
        o = pt.optimizer.SGD(learning_rate=0.3, parameters=params)
        x = jnp.asarray(np.random.RandomState(0).randn(32, 3),
                        jnp.float32)
        y = x @ jnp.asarray([1.0, -2.0, 0.5])

        def loss_fn(values):
            return jnp.mean(
                (x @ values["weight"] + values["bias"] - y[:, None]) ** 2)

        first = float(loss_fn({"weight": lin.weight.value,
                               "bias": lin.bias.value}))
        for _ in range(80):
            _, pg = o.minimize(loss_fn)
        assert len(pg) == 2
        last = float(loss_fn({"weight": lin.weight.value,
                              "bias": lin.bias.value}))
        assert last < first * 0.01

    def test_backward_tensor_raises_with_recipe(self):
        o = pt.optimizer.SGD(parameters=[nn.Linear(2, 2).weight])
        with pytest.raises(RuntimeError, match="tape"):
            o.backward(jnp.asarray(1.0))

    def test_append_regularization_ops(self):
        from paddle_tpu.regularizer import L1Decay, L2Decay
        p = pt.create_parameter([3], "float32",
                                default_initializer=nn.initializer.Constant(2.0))
        g = jnp.zeros(3)
        (_, g2), = pt.optimizer.SGD(parameters=[p]).append_regularization_ops(
            [(p, g)], L2Decay(0.5))
        np.testing.assert_allclose(np.asarray(g2), 1.0)  # 0.5 * 2.0
        (_, g1), = pt.optimizer.SGD(parameters=[p]).append_regularization_ops(
            [(p, g)], L1Decay(0.5))
        np.testing.assert_allclose(np.asarray(g1), 0.5)  # 0.5 * sign(2)
