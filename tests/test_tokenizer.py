"""WordPiece tokenizer: canonical BERT segmentation, native C core ==
python oracle on every input (property parity), round-trip decode."""
import numpy as np
import pytest

from paddle_tpu.text import WordPieceTokenizer

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "quick", "brown",
         "fox", "jump", "##ed", "##s", "##ing", "over", "lazy", "dog",
         "un", "##aff", "##able", "runn", "hello", "world", ",", ".",
         "!", "?", "'", "a", "##b", "##c", "ab"]


@pytest.fixture(scope="module")
def tok():
    return WordPieceTokenizer(VOCAB, unk_token="[UNK]")


class TestSemantics:
    def test_canonical_bert_example(self, tok):
        # the classic wordpiece example
        assert [tok.inv_vocab[i] for i in tok.encode("unaffable")] == \
            ["un", "##aff", "##able"]

    def test_greedy_longest_match(self, tok):
        # "ab" in vocab beats "a"+"##b"
        assert [tok.inv_vocab[i] for i in tok.encode("ab")] == ["ab"]
        assert [tok.inv_vocab[i] for i in tok.encode("abc")] == \
            ["ab", "##c"]

    def test_punct_isolated_and_lowercase(self, tok):
        ids = tok.encode("The quick, brown fox!")
        toks = [tok.inv_vocab[i] for i in ids]
        assert toks == ["the", "quick", ",", "brown", "fox", "!"]

    def test_unsegmentable_word_is_single_unk(self, tok):
        assert [tok.inv_vocab[i] for i in tok.encode("zzz quick")] == \
            ["[UNK]", "quick"]

    def test_decode_round_trip(self, tok):
        ids = tok.encode("the quick brown fox jumped over the lazy dog")
        assert tok.decode(ids) == \
            "the quick brown fox jumped over the lazy dog"


class TestNativeParity:
    def test_native_active(self, tok):
        assert tok.uses_native, "C core failed to build"

    def test_matches_python_oracle(self, tok):
        rng = np.random.RandomState(0)
        pieces = ["the", "quick", "unaffable", "zzz", "ab", "abc",
                  "jumping", "runns", ",", "!", "hello", "world'",
                  "dog.", "a", "+++", "日本語"]
        for _ in range(200):
            text = " ".join(rng.choice(pieces,
                                       size=rng.randint(1, 12)))
            got = tok.encode(text)
            want = tok._encode_py(text.lower())
            assert got == want, (text, got, want)

    def test_python_fallback_equivalent(self):
        t2 = WordPieceTokenizer(VOCAB, use_native=False)
        t1 = WordPieceTokenizer(VOCAB)
        s = "the unaffable fox jumped, quick! zzz"
        assert t1.encode(s) == t2.encode(s)


class TestMultibyteAndLimits:
    def test_multibyte_segmentation_parity(self):
        # byte-greedy matching must not split multibyte chars wrongly
        t = WordPieceTokenizer(["[UNK]", "a", "##é"])
        for tok in (t, WordPieceTokenizer(["[UNK]", "a", "##é"],
                                          use_native=False)):
            ids = tok.encode("aé")
            assert [tok.inv_vocab[i] for i in ids] == ["a", "##é"]

    def test_long_word_cap_identical_both_paths(self):
        t_native = WordPieceTokenizer(["[UNK]", "a", "##a"],
                                      max_word_len=2000)
        t_py = WordPieceTokenizer(["[UNK]", "a", "##a"],
                                  max_word_len=2000, use_native=False)
        long_word = "a" * 600
        assert t_native.encode(long_word) == t_py.encode(long_word) == \
            [0]   # both clamp to the same byte cap -> single [UNK]
