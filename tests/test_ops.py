"""OpTest-style parity tests for the fused-op family (reference test model:
unittests/op_test.py — numpy/XLA reference forward + gradient comparison,
dtype sweep)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import flags
from paddle_tpu import ops

pytestmark = pytest.mark.kernels


def _sdpa_ref(q, k, v, causal):
    # straight einsum reference (no pallas routing)
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (d ** -0.5)
    s = s.astype(jnp.float32)
    if causal:
        ql, kl = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        s = jnp.where(mask, s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _rand_qkv(b=2, h=2, s=128, d=32, dtype=jnp.float32, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda i: jnp.asarray(r.randn(b, h, s, d) * 0.5, dtype)
    return mk(0), mk(1), mk(2)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_xla(self, causal):
        q, k, v = _rand_qkv()
        out = ops.flash_attention(q, k, v, causal=causal)
        ref = _sdpa_ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_forward_multi_block(self):
        # seq > block size exercises the online-softmax recurrence
        q, k, v = _rand_qkv(b=1, h=2, s=256, d=32)
        out = ops.flash_attention(q, k, v, causal=True)
        ref = _sdpa_ref(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_decode_cache_alignment(self):
        # q_len < kv_len: bottom-right causal alignment (decode semantics)
        b, h, d = 1, 2, 32
        r = np.random.RandomState(3)
        q = jnp.asarray(r.randn(b, h, 128, d), jnp.float32)
        k = jnp.asarray(r.randn(b, h, 256, d), jnp.float32)
        v = jnp.asarray(r.randn(b, h, 256, d), jnp.float32)
        out = ops.flash_attention(q, k, v, causal=True)
        ref = _sdpa_ref(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_xla(self, causal):
        q, k, v = _rand_qkv(b=1, h=2, s=128, d=32)

        def loss_flash(q, k, v):
            return jnp.sum(ops.flash_attention(q, k, v, causal=causal) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(_sdpa_ref(q, k, v, causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=3e-4, atol=3e-4,
                                       err_msg=f"d{name}")

    def test_bf16(self):
        q, k, v = _rand_qkv(s=128, d=32, dtype=jnp.bfloat16)
        out = ops.flash_attention(q, k, v, causal=True)
        ref = _sdpa_ref(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=2e-2, atol=2e-2)
        assert out.dtype == jnp.bfloat16

    def test_sdpa_routes_to_flash_under_flag(self):
        q, k, v = _rand_qkv(s=128, d=32)
        try:
            # routing is TPU-only by default; force interpret routing on CPU
            flags.set_flags({"use_pallas_kernels": True,
                             "pallas_interpret_routing": True})
            out_flash = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            flags.set_flags({"use_pallas_kernels": False})
            out_xla = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        finally:
            flags.set_flags({"use_pallas_kernels": True,
                             "pallas_interpret_routing": False})
        np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_xla),
                                   rtol=2e-5, atol=2e-5)

    def test_jit_compatible(self):
        q, k, v = _rand_qkv(s=128, d=32)
        f = jax.jit(lambda q, k, v: ops.flash_attention(q, k, v, causal=True))
        out = f(q, k, v)
        assert out.shape == q.shape


class TestFusedEpilogues:
    def test_bias_dropout_residual_ln_eval(self):
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(4, 16), jnp.float32)
        res = jnp.asarray(r.randn(4, 16), jnp.float32)
        b = jnp.asarray(r.randn(16), jnp.float32)
        g = jnp.ones(16); beta = jnp.zeros(16)
        out = ops.fused_bias_dropout_residual_layer_norm(
            x, res, b, g, beta, dropout_rate=0.0, training=False)
        ref = F.layer_norm(res + x + b, (16,), g, beta)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)

    def test_fused_feedforward_matches_unfused(self):
        r = np.random.RandomState(1)
        x = jnp.asarray(r.randn(2, 8, 16), jnp.float32)
        w1 = jnp.asarray(r.randn(16, 32) * 0.1, jnp.float32)
        b1 = jnp.zeros(32)
        w2 = jnp.asarray(r.randn(32, 16) * 0.1, jnp.float32)
        b2 = jnp.zeros(16)
        g = jnp.ones(16); beta = jnp.zeros(16)
        out = ops.fused_feedforward(x, w1, b1, w2, b2, g, beta,
                                    training=False)
        h = F.gelu(F.linear(F.layer_norm(x, (16,), g, beta), w1, b1))
        ref = x + F.linear(h, w2, b2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


class TestRope:
    def test_rotation_preserves_norm(self):
        q, k, _ = _rand_qkv(s=16, d=32)
        qr, kr = ops.rotary_position_embedding(q, k)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(qr), axis=-1),
            np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-5)

    def test_position_zero_identity(self):
        q, k, _ = _rand_qkv(s=4, d=8)
        pos = jnp.zeros((1, 4), jnp.int32)
        qr, kr = ops.rotary_position_embedding(q, k, position_ids=pos)
        np.testing.assert_allclose(np.asarray(qr), np.asarray(q), rtol=1e-6)

    def test_cached_tables_numerics_identical(self):
        """ISSUE 7 satellite: the lru-cached cos/sin tables must be
        numerically IDENTICAL to the from-scratch computation (same f32
        jnp expressions, evaluated once instead of per layer per call)."""
        from paddle_tpu.ops.fused import _rope_tables
        q, k, _ = _rand_qkv(s=48, d=32)
        b, h, s, d = q.shape

        def scratch(q, k, pos):
            # the pre-cache implementation, verbatim
            inv_freq = 1.0 / (10000.0 ** (jnp.arange(0, d, 2,
                                                     jnp.float32) / d))
            ang = pos[..., None].astype(jnp.float32) * inv_freq
            cos, sin = jnp.cos(ang)[:, None], jnp.sin(ang)[:, None]

            def rot(x):
                x1, x2 = x[..., :d // 2], x[..., d // 2:]
                f1, f2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
                return jnp.concatenate(
                    [f1 * cos - f2 * sin, f2 * cos + f1 * sin],
                    -1).astype(x.dtype)

            return rot(q), rot(k)

        hits0 = _rope_tables.cache_info().hits
        got_q, got_k = ops.rotary_position_embedding(q, k)
        ref_q, ref_k = scratch(q, k, jnp.arange(s)[None, :])
        np.testing.assert_array_equal(np.asarray(got_q), np.asarray(ref_q))
        np.testing.assert_array_equal(np.asarray(got_k), np.asarray(ref_k))
        # second call is served from the cache (two multiplies, no
        # inv_freq/cos/sin recomputation)
        ops.rotary_position_embedding(q, k)
        assert _rope_tables.cache_info().hits > hits0
        # concrete position_ids gather from the cached table, same numbers
        pos = jnp.arange(s)[None, :] + 3
        got_q2, _ = ops.rotary_position_embedding(q, k, position_ids=pos)
        ref_q2, _ = scratch(q, k, pos)
        np.testing.assert_array_equal(np.asarray(got_q2),
                                      np.asarray(ref_q2))
        # traced ids still work (on-the-fly fallback)
        f = jax.jit(lambda p: ops.rotary_position_embedding(
            q, k, position_ids=p)[0])
        np.testing.assert_allclose(np.asarray(f(pos)), np.asarray(ref_q2),
                                   rtol=1e-6, atol=1e-6)

    def test_relative_phase(self):
        # attention scores depend only on relative positions after rope
        r = np.random.RandomState(5)
        q = jnp.asarray(r.randn(1, 1, 8, 16), jnp.float32)
        k = jnp.asarray(r.randn(1, 1, 8, 16), jnp.float32)
        q1, k1 = ops.rotary_position_embedding(q, k)
        # shift both positions by a constant: scores unchanged
        pos = jnp.arange(8)[None, :] + 5
        q2, k2 = ops.rotary_position_embedding(q, k, position_ids=pos)
        s1 = jnp.einsum("bhqd,bhkd->bhqk", q1, k1)
        s2 = jnp.einsum("bhqd,bhkd->bhqk", q2, k2)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-4, atol=1e-4)


class TestFlashDropout:
    """In-kernel counter-based attention dropout (reference Philox seeds,
    fused_attention_op.cc:292-311): fused path, deterministic per seed."""

    def test_deterministic_given_seed(self):
        q, k, v = _rand_qkv()
        a = ops.flash_attention(q, k, v, dropout_p=0.3, seed=42)
        b = ops.flash_attention(q, k, v, dropout_p=0.3, seed=42)
        c = ops.flash_attention(q, k, v, dropout_p=0.3, seed=43)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))

    def test_eval_mode_disables(self):
        q, k, v = _rand_qkv()
        out = ops.flash_attention(q, k, v, dropout_p=0.3, training=False)
        ref = ops.flash_attention(q, k, v, dropout_p=0.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))

    def test_mean_preserved(self):
        # E[dropout(P)] = P: averaging over seeds approaches no-dropout
        q, k, v = _rand_qkv(b=1, h=2, s=64, d=16)
        ref = np.asarray(ops.flash_attention(q, k, v, dropout_p=0.0))
        acc = np.zeros_like(ref)
        n = 24
        for s in range(n):
            acc += np.asarray(ops.flash_attention(q, k, v, dropout_p=0.3,
                                                  seed=s))
        err = np.abs(acc / n - ref).max() / np.abs(ref).max()
        assert err < 0.25, err

    def test_grad_matches_numeric_with_fixed_seed(self):
        # mask is deterministic given seed, so finite differences are valid
        r = np.random.RandomState(0)
        q, k, v = _rand_qkv(b=1, h=1, s=16, d=8)

        def loss(q_, k_, v_):
            return jnp.sum(ops.flash_attention(q_, k_, v_, causal=True,
                                               dropout_p=0.4, seed=7) ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        eps = 1e-3
        for argi, g in enumerate(grads):
            g = np.asarray(g)
            for _ in range(4):   # spot-check 4 random coordinates
                idx = tuple(r.randint(0, s) for s in g.shape)
                args_hi = [np.array(a) for a in (q, k, v)]
                args_lo = [np.array(a) for a in (q, k, v)]
                args_hi[argi][idx] += eps
                args_lo[argi][idx] -= eps
                num = (float(loss(*map(jnp.asarray, args_hi)))
                       - float(loss(*map(jnp.asarray, args_lo)))) / (2 * eps)
                np.testing.assert_allclose(g[idx], num, rtol=2e-2,
                                           atol=2e-3)

    def test_dropout_stays_on_fused_path(self, monkeypatch):
        # dropout>0 must NOT fall back to the XLA path anymore
        import importlib
        fa = importlib.import_module("paddle_tpu.ops.flash_attention")
        calls = []
        orig = fa._flash_fwd

        def spy(*args, **kw):
            calls.append(1)
            return orig(*args, **kw)

        monkeypatch.setattr(fa, "_flash_fwd", spy)
        q, k, v = _rand_qkv(b=1, h=1, s=128, d=16)
        out = ops.flash_attention(q, k, v, dropout_p=0.2, seed=3)
        assert calls, "dropout>0 fell off the fused kernel path"
        assert np.isfinite(np.asarray(out)).all()

    def test_jitted_steps_vary_mask_via_key_scope(self):
        # under key_scope the auto-drawn seed is traced, not a constant
        import paddle_tpu as pt
        q, k, v = _rand_qkv(b=1, h=1, s=64, d=16)

        @jax.jit
        def step(key, q_, k_, v_):
            with pt.key_scope(key):
                return ops.flash_attention(q_, k_, v_, dropout_p=0.3)

        o1 = step(jax.random.key(1), q, k, v)
        o2 = step(jax.random.key(2), q, k, v)
        assert not np.allclose(np.asarray(o1), np.asarray(o2))


class TestFlashRagged:
    """Auto-padding for non-block-multiple sequence lengths."""

    @pytest.mark.parametrize("sq,sk", [(100, 100), (37, 37), (60, 200),
                                       (130, 130)])
    def test_ragged_matches_xla(self, sq, sk):
        r = np.random.RandomState(1)
        q = jnp.asarray(r.randn(1, 2, sq, 16) * 0.5, jnp.float32)
        k = jnp.asarray(r.randn(1, 2, sk, 16) * 0.5, jnp.float32)
        v = jnp.asarray(r.randn(1, 2, sk, 16) * 0.5, jnp.float32)
        for causal in (True, False):
            out = ops.flash_attention(q, k, v, causal=causal)
            ref = _sdpa_ref(q, k, v, causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-4)

    def test_ragged_grads(self):
        r = np.random.RandomState(2)
        q = jnp.asarray(r.randn(1, 1, 50, 8) * 0.5, jnp.float32)
        k = jnp.asarray(r.randn(1, 1, 70, 8) * 0.5, jnp.float32)
        v = jnp.asarray(r.randn(1, 1, 70, 8) * 0.5, jnp.float32)

        def f_flash(q_, k_, v_):
            return jnp.sum(ops.flash_attention(q_, k_, v_, causal=True) ** 2)

        def f_ref(q_, k_, v_):
            return jnp.sum(_sdpa_ref(q_, k_, v_, True) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)


class TestFlashKVCache:
    """Decode kernel vs full attention over the cache prefix (reference
    CacheKV, fused_attention_op.cc:235)."""

    def test_matches_prefix_attention(self):
        r = np.random.RandomState(3)
        smax, used = 128, 77
        q = jnp.asarray(r.randn(2, 2, 1, 16) * 0.5, jnp.float32)
        kc = jnp.asarray(r.randn(2, 2, smax, 16) * 0.5, jnp.float32)
        vc = jnp.asarray(r.randn(2, 2, smax, 16) * 0.5, jnp.float32)
        out = ops.flash_attention_kvcache(q, kc, vc, used)
        ref = _sdpa_ref(q, kc[:, :, :used], vc[:, :, :used], causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_traced_seqlen_one_program(self):
        # one compiled program serves every decode position
        r = np.random.RandomState(4)
        q = jnp.asarray(r.randn(1, 2, 1, 16), jnp.float32)
        kc = jnp.asarray(r.randn(1, 2, 64, 16), jnp.float32)
        vc = jnp.asarray(r.randn(1, 2, 64, 16), jnp.float32)

        @jax.jit
        def step(qq, ln):
            return ops.flash_attention_kvcache(qq, kc, vc, ln)

        for used in (8, 23, 64):
            out = step(q, jnp.asarray(used, jnp.int32))
            ref = _sdpa_ref(q, kc[:, :, :used], vc[:, :, :used], False)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-4)


class TestFusionEvidence:
    """Recorded compiler evidence for the 'XLA fusion suffices' design
    claim in ops/fused.py (VERDICT r4 weak #2): the whole
    bias+dropout+residual+LayerNorm epilogue must compile to a handful of
    fused kernels, not one HBM round-trip per elementwise op."""

    def test_epilogue_fuses_to_few_kernels(self):
        from paddle_tpu.ops.fused import (
            fused_bias_dropout_residual_layer_norm as fe)
        x = jnp.ones((4, 256, 512), jnp.float32)
        r = jnp.ones((4, 256, 512), jnp.float32)
        b = jnp.ones((512,))
        s = jnp.ones((512,))
        bb = jnp.zeros((512,))
        f = jax.jit(lambda x, r, b, s, bb, k: fe(
            x, r, b, s, bb, dropout_rate=0.1, training=True, key=k))
        hlo = f.lower(x, r, b, s, bb,
                      jax.random.key(0)).compile().as_text()
        entry = hlo.split("ENTRY")[-1]
        producing = [l for l in entry.splitlines()
                     if "f32[4,256,512]" in l and "=" in l
                     and "parameter" not in l]
        # unfused, the chain (bias add, dropout select, residual add,
        # mean-subtract, var-normalize, scale, shift) would write the
        # full tensor 7+ times; fused it is a handful of kernel outputs
        # (4 on current XLA, 5 on the 0.4.x CPU backend which splits the
        # select+add epilogue into its own fusion)
        assert len(producing) <= 5, (len(producing), producing)


class TestLinearCrossEntropy:
    """ops/fused.py linear_softmax_cross_entropy — the memory-efficient LM
    loss (c_softmax_with_cross_entropy objective without materialized
    logits; see benchmarks/batch_scan_125m.json for the motivating OOM)."""

    def _ref(self, hid, W, lab, ignore=-100):
        logits = jnp.einsum("bsh,vh->bsv", hid, W).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, -1)
        v = W.shape[0]
        picked = jnp.take_along_axis(
            logits, jnp.clip(lab, 0, v - 1)[..., None], -1)[..., 0]
        tok = jnp.where(lab != ignore, lse - picked, 0.0)
        return jnp.sum(tok) / jnp.sum((lab != ignore).astype(jnp.float32))

    @pytest.mark.quick
    def test_loss_and_grad_parity(self):
        from paddle_tpu.ops.fused import linear_softmax_cross_entropy
        rng = np.random.RandomState(0)
        hid = jnp.asarray(rng.randn(2, 256, 32) * 0.4, jnp.float32)
        W = jnp.asarray(rng.randn(97, 32) * 0.4, jnp.float32)
        lab = rng.randint(0, 97, (2, 256))
        lab[0, :9] = -100                      # ignore_index tokens
        lab = jnp.asarray(lab, jnp.int32)
        with jax.default_matmul_precision("highest"):
            got = linear_softmax_cross_entropy(hid, W, lab)
            want = self._ref(hid, W, lab)
            assert abs(float(got - want)) < 1e-6
            g = jax.grad(lambda h, w: linear_softmax_cross_entropy(
                h, w, lab), argnums=(0, 1))(hid, W)
            gr = jax.grad(lambda h, w: self._ref(h, w, lab),
                          argnums=(0, 1))(hid, W)
            for a, b in zip(g, gr):
                assert float(jnp.max(jnp.abs(a - b))) < 1e-6

    def test_reductions_and_fallback(self):
        from paddle_tpu.ops.fused import linear_softmax_cross_entropy
        rng = np.random.RandomState(1)
        hid = jnp.asarray(rng.randn(1, 128, 16) * 0.4, jnp.float32)
        W = jnp.asarray(rng.randn(33, 16) * 0.4, jnp.float32)
        lab = jnp.asarray(rng.randint(0, 33, (1, 128)), jnp.int32)
        with jax.default_matmul_precision("highest"):
            tok = linear_softmax_cross_entropy(hid, W, lab, reduction="none")
            assert tok.shape == (1, 128)
            s = linear_softmax_cross_entropy(hid, W, lab, reduction="sum")
            assert abs(float(jnp.sum(tok) - s)) < 1e-5
            # s=100 has no 128-chunking -> unfused fallback, same numbers
            f = linear_softmax_cross_entropy(hid[:, :100], W, lab[:, :100])
            r = self._ref(hid[:, :100], W, lab[:, :100])
            assert abs(float(f - r)) < 1e-6

    def test_gpt_fused_flag_parity(self):
        """Model-level: fused_lm_loss=True must match the unfused path
        (loss AND a parameter gradient) on a tiny config."""
        import paddle_tpu as pt
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        rng = np.random.RandomState(2)
        ids = jnp.asarray(rng.randint(0, 1024, (2, 128)), jnp.int32)
        losses, grads = {}, {}
        for fused in (True, False):
            pt.seed(0)
            m = GPTForCausalLM(gpt_tiny(max_position_embeddings=128,
                                        hidden_dropout=0.0,
                                        attention_dropout=0.0,
                                        fused_lm_loss=fused))
            m.train()
            params = m.state_dict()

            def lf(p):
                loss, _ = m.apply(p, ids, labels=ids)
                return loss

            with jax.default_matmul_precision("highest"):
                losses[fused] = float(lf(params))
                g = jax.grad(lf)(params)
            grads[fused] = g["gpt.wte.weight"]
        assert abs(losses[True] - losses[False]) < 1e-5, losses
        err = float(jnp.max(jnp.abs(grads[True] - grads[False])))
        assert err < 1e-5, err

    def test_bf16_path_finite_and_close(self):
        from paddle_tpu.ops.fused import linear_softmax_cross_entropy
        rng = np.random.RandomState(3)
        hid = jnp.asarray(rng.randn(2, 256, 32) * 0.4, jnp.bfloat16)
        W = jnp.asarray(rng.randn(97, 32) * 0.4, jnp.bfloat16)
        lab = jnp.asarray(rng.randint(0, 97, (2, 256)), jnp.int32)
        got = linear_softmax_cross_entropy(hid, W, lab)
        want = self._ref(hid.astype(jnp.float32),
                         W.astype(jnp.float32), lab)
        assert bool(jnp.isfinite(got))
        assert abs(float(got - want)) < 5e-2
        g = jax.grad(lambda h: linear_softmax_cross_entropy(h, W, lab))(hid)
        assert bool(jnp.isfinite(g.astype(jnp.float32)).all())
