"""fft / distribution / sparse surface tests (L7 parity rows; reference
python/paddle/{fft.py,distribution/,sparse/})."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt


class TestFFT:
    def test_fft_roundtrip(self):
        x = jnp.asarray(np.random.RandomState(0).randn(16), jnp.float32)
        back = pt.fft.ifft(pt.fft.fft(x))
        np.testing.assert_allclose(np.asarray(back.real), np.asarray(x),
                                   atol=1e-5)

    def test_rfft_matches_numpy(self):
        x = np.random.RandomState(1).randn(3, 32).astype(np.float32)
        np.testing.assert_allclose(np.asarray(pt.fft.rfft(x)),
                                   np.fft.rfft(x), rtol=1e-4, atol=1e-4)

    def test_fft2_shift(self):
        x = np.random.RandomState(2).randn(8, 8).astype(np.float32)
        got = pt.fft.fftshift(pt.fft.fft2(x))
        want = np.fft.fftshift(np.fft.fft2(x))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=1e-4)


class TestDistribution:
    def test_normal_logprob_entropy_kl(self):
        from paddle_tpu.distribution import Normal, kl_divergence
        d = Normal(0.0, 1.0)
        np.testing.assert_allclose(
            float(d.log_prob(0.0)), -0.5 * np.log(2 * np.pi), rtol=1e-6)
        d2 = Normal(1.0, 2.0)
        kl = float(kl_divergence(d, d2))
        # closed form: log(s2/s1) + (s1^2 + (m1-m2)^2)/(2 s2^2) - 1/2
        want = np.log(2.0) + (1 + 1) / 8 - 0.5
        np.testing.assert_allclose(kl, want, rtol=1e-6)
        pt.seed(0)
        s = d.sample((10000,))
        assert abs(float(jnp.mean(s))) < 0.05

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical
        c = Categorical(logits=jnp.log(jnp.asarray([0.1, 0.2, 0.7])))
        np.testing.assert_allclose(np.asarray(c.probs), [0.1, 0.2, 0.7],
                                   rtol=1e-5)
        np.testing.assert_allclose(float(c.log_prob(2)), np.log(0.7),
                                   rtol=1e-5)
        pt.seed(1)
        s = np.asarray(c.sample((20000,)))
        np.testing.assert_allclose((s == 2).mean(), 0.7, atol=0.02)

    def test_beta_dirichlet_bernoulli(self):
        from paddle_tpu.distribution import Bernoulli, Beta, Dirichlet
        b = Beta(2.0, 3.0)
        np.testing.assert_allclose(float(b.mean), 0.4, rtol=1e-6)
        d = Dirichlet(jnp.asarray([1.0, 2.0, 3.0]))
        v = jnp.asarray([0.2, 0.3, 0.5])
        # manual dirichlet logpdf
        from jax.scipy.special import gammaln
        want = (float(jnp.sum((d.concentration - 1) * jnp.log(v)))
                - float(jnp.sum(gammaln(d.concentration))
                        - gammaln(jnp.sum(d.concentration))))
        np.testing.assert_allclose(float(d.log_prob(v)), want, rtol=1e-5)
        bern = Bernoulli(0.3)
        np.testing.assert_allclose(float(bern.log_prob(1.0)), np.log(0.3),
                                   rtol=1e-5)


class TestSparse:
    def test_coo_roundtrip_and_matmul(self):
        from paddle_tpu import sparse
        indices = [[0, 1, 2], [1, 0, 2]]
        values = [1.0, 2.0, 3.0]
        s = sparse.sparse_coo_tensor(indices, values, (3, 3))
        dense = np.zeros((3, 3), np.float32)
        dense[0, 1], dense[1, 0], dense[2, 2] = 1, 2, 3
        np.testing.assert_array_equal(np.asarray(s.to_dense()), dense)
        assert s.nnz() == 3
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(np.asarray(sparse.matmul(s, x)),
                                   dense @ x, rtol=1e-5)

    def test_csr_and_ops(self):
        from paddle_tpu import sparse
        # 2x3 matrix [[1,0,2],[0,-3,0]]
        s = sparse.sparse_csr_tensor([0, 2, 3], [0, 2, 1], [1.0, 2.0, -3.0],
                                     (2, 3))
        dense = np.array([[1, 0, 2], [0, -3, 0]], np.float32)
        np.testing.assert_array_equal(np.asarray(s.to_dense()), dense)
        r = sparse.relu(s)
        np.testing.assert_array_equal(np.asarray(r.to_dense()),
                                      np.maximum(dense, 0))

    def test_add_and_masked_matmul(self):
        from paddle_tpu import sparse
        a = sparse.sparse_coo_tensor([[0, 1], [0, 1]], [1.0, 2.0], (2, 2))
        b = sparse.sparse_coo_tensor([[0, 1], [0, 0]], [5.0, 7.0], (2, 2))
        out = sparse.add(a, b).to_dense()
        np.testing.assert_array_equal(np.asarray(out),
                                      [[6, 0], [7, 2]])
        x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        y = np.random.RandomState(2).randn(4, 2).astype(np.float32)
        mask = sparse.sparse_coo_tensor([[0, 1], [1, 0]], [1.0, 1.0], (2, 2))
        got = sparse.masked_matmul(x, y, mask).to_dense()
        full = x @ y
        want = np.zeros((2, 2), np.float32)
        want[0, 1], want[1, 0] = full[0, 1], full[1, 0]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


class TestDevice:
    def test_introspection(self):
        import paddle_tpu.device as device
        assert device.device_count() >= 1
        props = device.get_device_properties()
        assert "platform" in props and props["id"] == 0
        # CPU backend: stats may be empty; the calls must not raise
        assert device.memory_allocated() >= 0
        assert device.cuda.max_memory_allocated() >= 0


class TestStaticFacade:
    def test_program_executor_roundtrip(self):
        import paddle_tpu.static as static
        prog = static.Program("toy").set_fn(
            lambda x, y: {"z": x @ y})
        exe = static.Executor()
        x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        y = np.random.RandomState(1).randn(3, 2).astype(np.float32)
        (z,) = exe.run(prog, feed={"x": x, "y": y}, fetch_list=["z"])
        np.testing.assert_allclose(z, x @ y, rtol=1e-5)

    def test_static_nn_namespace(self):
        import jax.numpy as jnp
        import paddle_tpu.static as static
        pt.seed(0)
        x = jnp.asarray(np.random.RandomState(0).randn(4, 8), np.float32)
        y = static.nn.fc(x, 16, activation="relu")
        assert y.shape == (4, 16) and float(jnp.min(y)) >= 0
        e = static.nn.embedding(jnp.asarray([[1, 2]]), size=(10, 6))
        assert e.shape == (1, 2, 6)
        bn = static.nn.batch_norm(jnp.ones((2, 3, 4, 4)))
        assert bn.shape == (2, 3, 4, 4)

    def test_program_guard_swaps_default(self):
        import paddle_tpu.static as static
        p = static.Program("alt")
        with static.program_guard(p):
            assert static.default_main_program() is p
        assert static.default_main_program() is not p

    def test_save_load_inference_model(self, tmp_path):
        import paddle_tpu.static as static
        from paddle_tpu import nn
        pt.seed(0)
        model = nn.Sequential(nn.Linear(4, 2))
        model.eval()
        spec = [static.data("x", (2, 4))]
        path = str(tmp_path / "static_export")
        static.save_inference_model(path, spec, None, None, layer=model,
                                    input_spec=spec)
        loaded, feeds, _ = static.load_inference_model(path)
        assert feeds == ["x"]
        x = jnp.asarray(np.random.RandomState(2).randn(2, 4), jnp.float32)
        np.testing.assert_allclose(np.asarray(loaded(x)),
                                   np.asarray(model(x)), rtol=1e-6)


class TestViterbi:
    def _np_viterbi(self, pot, trans, length, bos_eos):
        # brute force over all paths
        import itertools
        T, N = pot.shape
        n_tags = N - 2 if bos_eos else N
        best, best_path = -1e30, None
        for path in itertools.product(range(n_tags), repeat=length):
            s = pot[0, path[0]]
            if bos_eos:
                s += trans[N - 2, path[0]]
            for t in range(1, length):
                s += trans[path[t - 1], path[t]] + pot[t, path[t]]
            if bos_eos:
                s += trans[path[-1], N - 1]
            if s > best:
                best, best_path = s, path
        return best, list(best_path)

    @pytest.mark.parametrize("bos_eos", [True, False])
    def test_matches_bruteforce(self, bos_eos):
        from paddle_tpu.text import viterbi_decode
        rng = np.random.RandomState(0)
        B, T, N = 3, 5, 6
        pot = rng.randn(B, T, N).astype(np.float32)
        if bos_eos:
            pot[:, :, -2:] = -1e4  # emissions never pick BOS/EOS tags
        trans = rng.randn(N, N).astype(np.float32)
        lengths = np.asarray([5, 3, 4], np.int32)
        scores, paths = viterbi_decode(pot, trans, lengths,
                                       include_bos_eos_tag=bos_eos)
        for b in range(B):
            want_s, want_p = self._np_viterbi(pot[b], trans,
                                              int(lengths[b]), bos_eos)
            np.testing.assert_allclose(float(scores[b]), want_s, rtol=1e-5)
            got = list(np.asarray(paths[b][: lengths[b]]))
            assert got == want_p, (b, got, want_p)


class TestExecutionMode:
    """enable_static/disable_static/in_dynamic_mode + grad-mode toggles
    (reference fluid/framework.py + dygraph/base.py): recorded state over
    the one-codepath design — ported scripts' mode calls run unchanged."""

    def test_static_toggle(self):
        import paddle_tpu as pt
        assert pt.in_dynamic_mode()
        pt.enable_static()
        try:
            assert not pt.in_dynamic_mode()
        finally:
            pt.disable_static()
        assert pt.in_dynamic_mode()

    def test_grad_mode_interop_with_no_grad(self):
        import paddle_tpu as pt
        assert pt.is_grad_enabled()
        with pt.no_grad():
            assert not pt.is_grad_enabled()
            with pt.set_grad_enabled(True):
                assert pt.is_grad_enabled()
            assert not pt.is_grad_enabled()
        assert pt.is_grad_enabled()

    def test_set_grad_enabled_reenterable(self):
        import paddle_tpu as pt
        cm = pt.set_grad_enabled(False)
        with cm:
            assert not pt.is_grad_enabled()
        assert pt.is_grad_enabled()       # construction alone must not flip
        with cm:
            assert not pt.is_grad_enabled()
        assert pt.is_grad_enabled()

    def test_no_grad_decorator_stops_gradients(self):
        import jax, jax.numpy as jnp
        import paddle_tpu as pt

        @pt.no_grad()
        def f(x):
            return x * 3.0

        g = jax.grad(lambda x: f(x).sum())(jnp.ones((2,)))
        assert float(jnp.abs(g).sum()) == 0.0

    def test_compiled_with_family_and_model(self):
        import paddle_tpu as pt
        assert not pt.is_compiled_with_cuda()
        assert not pt.is_compiled_with_rocm()
        assert not pt.is_compiled_with_xpu()
        assert pt.Model is pt.hapi.Model


class TestGradModeNesting:
    def test_same_instance_reentry_restores_state(self):
        # regression: a per-instance _prev slot corrupted global grad
        # mode when one cm/no_grad instance was entered while active
        assert pt.is_grad_enabled()
        ng = pt.no_grad()
        with ng:
            with ng:
                assert not pt.is_grad_enabled()
            assert not pt.is_grad_enabled()
        assert pt.is_grad_enabled()

        cm = pt.set_grad_enabled(False)
        with cm:
            with cm:
                pass
        assert pt.is_grad_enabled()

    def test_recursive_no_grad_decorated_fn(self):
        @pt.no_grad()
        def fact(n):
            return 1 if n <= 1 else n * fact(n - 1)

        assert fact(5) == 120
        assert pt.is_grad_enabled()


class TestTopLevelParityFill:
    """Round-5 fill of the last reference __init__.__all__ gaps."""

    # The reference source tree is an environment, not a code,
    # dependency — same doctrine as the launch_nnodes2 backend skip: its
    # absence must not read as a regression in the tier-1 red count.
    @pytest.mark.skipif(
        not os.path.isdir("/root/reference"),
        reason="reference tree /root/reference not present in this "
               "container")
    def test_all_reference_top_level_names_exist(self):
        import ast
        tree = ast.parse(open(
            "/root/reference/python/paddle/__init__.py").read())
        names = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        names = [ast.literal_eval(e)
                                 for e in node.value.elts]
        missing = [n for n in names if not hasattr(pt, n)]
        assert not missing, missing

    def test_manipulation_ops(self):
        import numpy as np
        x = pt.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
        assert int(pt.rank(x)) == 3
        parts = pt.unstack(x, axis=1)
        assert len(parts) == 3 and parts[0].shape == (2, 4)
        np.testing.assert_array_equal(
            np.asarray(pt.reverse(x, [0])), np.asarray(x)[::-1])
        np.testing.assert_array_equal(
            np.asarray(pt.slice(x, [1, 2], [1, 0], [3, 2])),
            np.asarray(x)[:, 1:3, 0:2])
        np.testing.assert_array_equal(
            np.asarray(pt.strided_slice(x, [2], [0], [4], [2])),
            np.asarray(x)[:, :, ::2])
        np.testing.assert_array_equal(
            np.asarray(pt.crop(x, shape=[2, 2, -1], offsets=[0, 1, 0])),
            np.asarray(x)[:, 1:3, :])
        assert bool(pt.is_empty(pt.to_tensor(np.zeros((0, 3)))))
        assert not bool(pt.is_empty(x))
        s = pt.add_n([x, x, x])
        np.testing.assert_allclose(np.asarray(s), np.asarray(x) * 3)
        y = pt.increment(pt.to_tensor([5.0]), 2.5)
        assert float(y[0]) == 7.5

    def test_scatter_nd_and_shard_index(self):
        import numpy as np
        idx = pt.to_tensor(np.array([[1], [1], [3]], np.int64))
        upd = pt.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        out = pt.scatter_nd(idx, upd, [5])
        np.testing.assert_allclose(np.asarray(out), [0, 3, 0, 3, 0])
        base = pt.ones([5], "float32")
        out2 = pt.scatter_nd_add(base, idx, upd)
        np.testing.assert_allclose(np.asarray(out2), [1, 4, 1, 4, 1])
        # reference example: 20 classes, 2 shards
        labels = pt.to_tensor(np.array([1, 9, 10, 19], np.int64))
        np.testing.assert_array_equal(
            np.asarray(pt.shard_index(labels, 20, 2, 0)), [1, 9, -1, -1])
        np.testing.assert_array_equal(
            np.asarray(pt.shard_index(labels, 20, 2, 1)), [-1, -1, 0, 9])

    def test_math_fill(self):
        import numpy as np
        from scipy import special
        x = pt.to_tensor(np.array([1.5, 2.5], np.float32))
        np.testing.assert_allclose(np.asarray(pt.lgamma(x)),
                                   special.gammaln([1.5, 2.5]), rtol=1e-5)
        np.testing.assert_allclose(float(pt.asinh(pt.to_tensor(1.0))),
                                   np.arcsinh(1.0), rtol=1e-6)
        np.testing.assert_allclose(float(pt.acosh(pt.to_tensor(2.0))),
                                   np.arccosh(2.0), rtol=1e-6)
        np.testing.assert_allclose(float(pt.atanh(pt.to_tensor(0.5))),
                                   np.arctanh(0.5), rtol=1e-6)
        assert float(pt.floor_mod(pt.to_tensor(7.0), pt.to_tensor(3.0))) == 1.0
        assert int(pt.bitwise_not(pt.to_tensor(np.int32(0)))) == -1

    def test_inplace_aliases_return_result(self):
        import numpy as np
        x = pt.to_tensor(np.zeros((2, 3), np.float32))
        assert pt.reshape_(x, [3, 2]).shape == (3, 2)
        assert pt.unsqueeze_(x, 0).shape == (1, 2, 3)
        assert pt.squeeze_(pt.to_tensor(np.zeros((1, 2))), 0).shape == (2,)
        assert pt.tanh_(x).shape == (2, 3)

    def test_default_dtype_and_printoptions(self):
        assert pt.get_default_dtype() == "float32"
        pt.set_default_dtype("float64")
        try:
            assert pt.get_default_dtype() == "float64"
        finally:
            pt.set_default_dtype("float32")
        pt.set_printoptions(precision=4)
        assert pt.dtype("float32") == pt.float32

    def test_places_and_rng_compat(self):
        p = pt.CUDAPlace(0)      # maps to the accelerator place
        assert p.device is not None
        assert pt.CUDAPinnedPlace().device.platform == "cpu"
        st = pt.get_cuda_rng_state()
        pt.set_cuda_rng_state(st)
        pt.disable_signal_handler()

    def test_create_parameter_and_data_parallel(self):
        import numpy as np
        w = pt.create_parameter([4, 8], "float32")
        assert w.shape == (4, 8) and float(jnp.std(w.value)) > 0
        b = pt.create_parameter([8], "float32", is_bias=True)
        np.testing.assert_array_equal(np.asarray(b.value), np.zeros(8))

        from paddle_tpu import nn
        m = nn.Linear(4, 2)
        dp = pt.DataParallel(m)
        x = pt.randn([3, 4])
        np.testing.assert_allclose(np.asarray(dp(x)), np.asarray(m(x)))
        assert dp.scale_loss(1.5) == 1.5
        dp.apply_collective_grads()
        assert set(dp.state_dict()) == set(m.state_dict())
