"""MFU microscope (ISSUE 19): HLO parsing, the per-device roofline fit,
the gap budget's sum-to-measured invariant, schema v2 plumbing, the
synthetic drill, HLO dumping, and the doctor's ``mfu_gap`` verdict."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.bench import diff as perfdiff
from paddle_tpu.bench import harness, ledger, schema, trends
from paddle_tpu.observability import doctor, roofline
from paddle_tpu.observability.compilation import get_tracker, track_jit
from paddle_tpu.observability.mfu import DEVICE_SPECS, device_spec


@pytest.fixture(autouse=True)
def _clean_observatory():
    roofline.reset_observatory()
    yield
    roofline.reset_observatory()


def _mk_row(p50=10.0, roofline_block=None, **kw):
    phases = kw.pop("phases_ms", {"data": 1.0, "compute": 7.0,
                                  "readback": 1.0, "collective": 1.0})
    return schema.new_row(
        kw.pop("scenario", "gpt_pretrain_fused"), kw.pop("mode", "smoke"),
        step_times_ms=[p50 * 0.99, p50, p50 * 1.01],
        phases_ms=phases, config={"batch": 2},
        tokens_per_sec=1000.0, mfu=0.01,
        roofline=roofline_block, **kw)


# -- taxonomy pins ----------------------------------------------------------
def test_sink_taxonomy_is_pinned_across_modules():
    # schema.GAP_SINKS is a literal (no bench→observability import at
    # module scope); this is the cross-check that keeps them identical
    assert schema.GAP_SINKS == roofline.SINKS
    assert "mxu" in roofline.SINKS and "residual" in roofline.SINKS


def test_device_spec_known_and_unknown():
    spec = device_spec("TPU v5e chip")
    assert spec["known"] and spec["gen"] == "v5e"
    assert spec["bf16_tflops"] == DEVICE_SPECS["v5e"]["bf16_tflops"]
    assert spec["int8_tops"] > spec["bf16_tflops"]  # v5e: 2x int8
    unk = device_spec("Frobnicator 9000")
    assert not unk["known"]
    assert unk["hbm_gbps"] > 0  # nominal fallback still usable


# -- HLO parsing ------------------------------------------------------------
def test_parse_hlo_ops_on_real_compiled_dot():
    @jax.jit
    def f(a, b):
        return jnp.tanh(a @ b)

    a = jax.ShapeDtypeStruct((64, 128), jnp.float32)
    b = jax.ShapeDtypeStruct((128, 32), jnp.float32)
    text = f.lower(a, b).compile().as_text()
    ops = roofline.parse_hlo_ops(text)
    assert ops, "no ops parsed from compiled HLO"
    dots = [o for o in ops if o["klass"] == "mxu"]
    assert dots, f"no MXU op found in {[o['opcode'] for o in ops]}"
    # 2 * M*N*K exactly, from lhs_contracting_dims
    assert any(o["flops"] == 2 * 64 * 32 * 128 for o in dots)
    assert all(o["bytes"] is None or o["bytes"] >= 0 for o in ops)
    classes = {o["klass"] for o in ops}
    assert classes <= {"mxu", "hbm", "comm", "host"}


def test_parse_hlo_ops_garbage_degrades_to_empty():
    assert roofline.parse_hlo_ops("") == []
    assert roofline.parse_hlo_ops("not hlo at all\n{}\n") == []


def test_normalize_cost_analysis_sparse_and_absent():
    n = roofline._normalize_cost_analysis
    assert n(None) == {"flops": None, "bytes_accessed": None,
                       "transcendentals": None}
    assert n([]) == n(None)
    assert n([{}]) == n(None)          # CPU backends may omit every key
    got = n([{"flops": 7.0, "bytes accessed": 3.0}])
    assert got["flops"] == 7.0 and got["bytes_accessed"] == 3.0
    assert n({"flops": 1.0})["flops"] == 1.0  # dict form tolerated


def test_fit_roofline_counts_unmodeled_ops():
    spec = device_spec("TPU v5e")
    ops = [{"name": "a", "opcode": "dot", "klass": "mxu",
            "flops": 1e9, "bytes": 1e6, "integer": False},
           {"name": "b", "opcode": "mystery", "klass": "hbm",
            "flops": None, "bytes": None, "integer": False}]
    fit = roofline.fit_roofline(ops, spec)
    assert fit["ops_modeled"] == 1 and fit["ops_unmodeled"] == 1
    assert fit["mxu_s"] > 0


# -- gap budget -------------------------------------------------------------
_PHASES = {"data": 1.0, "compute": 7.0, "readback": 0.5, "collective": 1.5}


def test_gap_budget_sums_to_measured_unknown_device():
    spec = device_spec("Frobnicator 9000")
    blk = roofline.gap_budget(10.0, _PHASES, padding_frac=0.1, spec=spec)
    b = blk["buckets_ms"]
    assert abs(sum(b.values()) - 10.0) < 1e-6
    # unknown device: compute minus padding is explicitly unattributable
    assert b["mxu"] == 0.0 and b["memory_bound"] == 0.0
    assert b["unknown_device"] == pytest.approx(7.0 - 0.7)
    assert b["padding"] == pytest.approx(0.7)
    assert b["comm"] == pytest.approx(1.5)
    assert b["host"] == pytest.approx(1.5)
    assert blk["dominant_sink"] == "unknown_device"
    assert 0.0 <= blk["coverage"] <= 1.0
    assert not blk["device"]["known"]


def test_gap_budget_known_device_uses_fit():
    spec = device_spec("TPU v5e")
    analyses = {"step": {"name": "step", "error": None, "cost": {},
                         "fit": {"mxu_s": 0.004, "memory_s": 0.002,
                                 "comm_s": 0.0, "flops": 1e12,
                                 "bytes": 1e9, "comm_bytes": 0,
                                 "ops_modeled": 3, "ops_unmodeled": 0}}}
    blk = roofline.gap_budget(10.0, _PHASES, analyses=analyses,
                              calls={"step": 5}, spec=spec)
    b = blk["buckets_ms"]
    assert b["mxu"] == pytest.approx(4.0)
    assert b["memory_bound"] == pytest.approx(2.0)
    assert b["unknown_device"] == 0.0
    assert abs(sum(b.values()) - 10.0) < 1e-6
    assert blk["modeled_step_ms"] == pytest.approx(4.0 + 2.0 + 1.5 + 1.5)
    assert blk["programs"]["step"]["share"] == 1.0
    assert blk["ops"]["modeled"] == 3


def test_gap_budget_call_share_weighting():
    spec = device_spec("TPU v5e")
    fit_a = {"mxu_s": 0.004, "memory_s": 0.0, "comm_s": 0.0,
             "flops": 0, "bytes": 0, "comm_bytes": 0,
             "ops_modeled": 1, "ops_unmodeled": 0}
    fit_b = dict(fit_a, mxu_s=0.008)
    blk = roofline.gap_budget(
        10.0, _PHASES,
        analyses={"a": {"fit": fit_a}, "b": {"fit": fit_b}},
        calls={"a": 3, "b": 1}, spec=spec)
    # 3/4 * 4ms + 1/4 * 8ms = 5ms
    assert blk["buckets_ms"]["mxu"] == pytest.approx(5.0)


def test_inflation_drill_marks_injected(monkeypatch):
    monkeypatch.setenv(roofline.INFLATE_ENV, "memory_bound:0.6")
    blk = roofline.gap_budget(10.0, _PHASES,
                              spec=device_spec("Frobnicator"))
    b = blk["buckets_ms"]
    assert blk["injected"] == {"sink": "memory_bound", "frac": 0.6}
    assert b["memory_bound"] == pytest.approx(6.0)
    assert abs(sum(b.values()) - 10.0) < 1e-6
    assert blk["dominant_sink"] == "memory_bound"


def test_inflation_drill_bad_values_ignored(monkeypatch):
    for bad in ("nonsense", "memory_bound", "notasink:0.5", ":"):
        monkeypatch.setenv(roofline.INFLATE_ENV, bad)
        blk = roofline.gap_budget(10.0, _PHASES,
                                  spec=device_spec("Frobnicator"))
        assert blk["injected"] is None, bad


# -- schema v2 plumbing -----------------------------------------------------
def test_new_row_synthesizes_degraded_block():
    row = _mk_row()   # no roofline passed by the producer
    assert schema.validate_row(row) == []
    roof = row["roofline"]
    assert roof["degraded"]
    assert abs(sum(roof["buckets_ms"].values())
               - roof["measured_step_ms"]) < 1e-6


def test_validate_row_rejects_broken_roofline():
    row = _mk_row()
    bad = json.loads(json.dumps(row))
    bad["roofline"]["buckets_ms"]["host"] += 5.0
    assert any("sum" in e for e in schema.validate_row(bad))
    bad = json.loads(json.dumps(row))
    del bad["roofline"]["buckets_ms"]["comm"]
    assert any("comm" in e for e in schema.validate_row(bad))
    bad = json.loads(json.dumps(row))
    bad["roofline"]["dominant_sink"] = "gremlins"
    assert any("dominant_sink" in e for e in schema.validate_row(bad))
    bad = json.loads(json.dumps(row))
    bad["roofline"] = None
    assert any("roofline" in e for e in schema.validate_row(bad))


def test_v1_rows_stay_readable_and_gap_metrics_none(tmp_path):
    row = _mk_row()
    v1 = {k: v for k, v in row.items() if k != "roofline"}
    v1["schema_version"] = 1
    assert schema.validate_row(v1) == []    # old rows remain valid
    assert schema.metric_value(v1, "gap_host_ms") is None
    assert schema.metric_value(v1, "roofline_coverage") is None
    assert schema.metric_value(row, "gap_host_ms") is not None
    assert schema.metric_value(
        row, "roofline_coverage") == row["roofline"]["coverage"]
    # a mixed-version ledger round-trips: v1 rows are not rejected
    path = str(tmp_path / "ledger.jsonl")
    ledger.append_row(v1, path)
    ledger.append_row(row, path)
    assert len(ledger.read_ledger(path)) == 2


def test_gap_metrics_are_trendable_axes():
    assert "gap_host_ms" in schema.METRICS
    assert "roofline_coverage" in schema.METRICS
    assert "gap_mxu_ms" not in schema.METRICS  # mxu is work, not gap


# -- perfdiff / trends integration ------------------------------------------
def test_diff_attribution_gains_gap_movers():
    base = _mk_row()
    cur = json.loads(json.dumps(base))
    cur["roofline"]["buckets_ms"]["comm"] += 2.0
    att = perfdiff.attribute(base, cur)
    assert att["gap_dominant"] == "comm"
    sinks = [m["sink"] for m in att["gap_movers"]]
    assert "mxu" not in sinks
    text = perfdiff.render(perfdiff.diff_rows(base, cur))
    assert "MFU-gap sinks" in text and "comm" in text


def test_diff_attribution_guards_missing_roofline():
    base = _mk_row()
    v1 = {k: v for k, v in base.items() if k != "roofline"}
    att = perfdiff.attribute(v1, base)
    assert "gap_movers" not in att
    perfdiff.render(perfdiff.diff_rows(v1, base))  # must not raise


def test_median_row_carries_roofline_medians():
    rows = [_mk_row(p50=10.0), _mk_row(p50=12.0), _mk_row(p50=14.0)]
    med = trends.median_row(rows)
    assert med["roofline"] is not None
    assert set(med["roofline"]["buckets_ms"]) == set(schema.GAP_SINKS)
    att = perfdiff.attribute(med, rows[-1])
    assert "gap_movers" in att
    # v1-only windows produce no pseudo-roofline
    v1s = [{k: v for k, v in r.items() if k != "roofline"} for r in rows]
    assert trends.median_row(v1s)["roofline"] is None


# -- track_jit -> observatory -> block (e2e on CPU) -------------------------
def test_capture_window_end_to_end():
    def _step(a, b):
        return jnp.tanh(a @ b).sum()

    step = track_jit(jax.jit(_step), name="roof_step")

    a = jnp.ones((32, 64), jnp.float32)
    b = jnp.ones((64, 16), jnp.float32)
    with roofline.capture_window() as rw:
        for _ in range(3):
            step(a, b).block_until_ready()
    entries = roofline.get_observatory().entries()
    assert "roof_step" in entries
    blk = rw.build_block(10.0, _PHASES, padding_frac=0.0)
    assert blk["degraded"] is None
    prog = blk["programs"]["roof_step"]
    assert prog["error"] is None
    assert prog["flops"] and prog["flops"] >= 2 * 32 * 16 * 64
    assert abs(sum(blk["buckets_ms"].values()) - 10.0) < 1e-6
    # CPU is not in the device table → honest unknown_device routing
    assert not blk["device"]["known"]
    assert blk["buckets_ms"]["unknown_device"] > 0
    # outside the window nothing is captured
    assert not roofline.capture_active()


def test_capture_window_without_programs_degrades():
    with roofline.capture_window() as rw:
        pass
    blk = rw.build_block(10.0, _PHASES)
    assert blk["degraded"] == "no jitted step captured"
    assert abs(sum(blk["buckets_ms"].values()) - 10.0) < 1e-6


def test_harness_roofline_window_block():
    with harness.RooflineWindow() as rw:
        pass
    blk = rw.block([9.0, 10.0, 11.0], _PHASES, padding_frac=0.2)
    assert blk["measured_step_ms"] == pytest.approx(10.0)
    assert blk["padding_frac"] == pytest.approx(0.2)
    assert schema.validate_row(_mk_row(roofline_block=blk)) == []


# -- HLO dump knob ----------------------------------------------------------
def test_hlo_dump_and_gc(tmp_path, monkeypatch):
    monkeypatch.setenv(roofline.HLO_DUMP_ENV, str(tmp_path))
    monkeypatch.setenv(roofline.HLO_DUMP_KEEP_ENV, "2")

    @jax.jit
    def g(x):
        return x * 2.0

    obs = roofline.get_observatory()
    obs.enable()
    for i in range(4):
        arg = jax.ShapeDtypeStruct((8, 8 + i), jnp.float32)
        obs.record(f"fn{i}", g, (arg,), {}, sig_key=1000 + i, miss=True)
    names = sorted(os.listdir(str(tmp_path)))
    lowered = [n for n in names if n.endswith(".lowered.txt")]
    compiled = [n for n in names if n.endswith(".compiled.txt")]
    assert len(lowered) == 2 and len(compiled) == 2, names
    # sig-keyed filenames: the key is embedded as zero-padded hex
    assert any(f"{1003:016x}" in n for n in names)
    body = (tmp_path / compiled[-1]).read_text()
    assert body.strip(), "compiled dump is empty"


def test_capture_active_follows_dump_knob(monkeypatch):
    assert not roofline.capture_active()
    monkeypatch.setenv(roofline.HLO_DUMP_ENV, "/tmp/somewhere")
    assert roofline.capture_active()


# -- doctor verdict ---------------------------------------------------------
def _bench_rec(scenario="moe", dominant="comm", share=0.4, injected=False,
               measured=10.0, ts=1.0):
    buckets = {s: 0.0 for s in schema.GAP_SINKS}
    buckets[dominant] = share * measured
    buckets["mxu"] = measured - share * measured
    return {"kind": "bench.row", "scenario": scenario, "ts": ts,
            "mfu": 0.3,
            "roofline": {"buckets_ms": buckets,
                         "measured_step_ms": measured,
                         "dominant_sink": dominant, "coverage": 0.95,
                         "injected": injected}}


def test_check_mfu_gap_names_dominant_sink():
    (f,) = doctor.check_mfu_gap({0: [_bench_rec(dominant="comm")]})
    assert f["kind"] == "mfu_gap"
    assert f["data"]["dominant"] == "comm"
    assert "comm" in f["title"] and "moe" in f["title"]
    assert any("coverage" in e for e in f["evidence"])


def test_check_mfu_gap_threshold_and_mxu_quiet(monkeypatch):
    # below the default 25% share: no finding
    assert doctor.check_mfu_gap({0: [_bench_rec(share=0.1)]}) == []
    # mxu-dominant is the healthy case, never a finding
    rec = _bench_rec(share=0.4)
    rec["roofline"]["dominant_sink"] = "mxu"
    assert doctor.check_mfu_gap({0: [rec]}) == []
    # threshold is tunable
    monkeypatch.setenv("PTPU_MFU_GAP_FRAC", "0.05")
    assert doctor.check_mfu_gap({0: [_bench_rec(share=0.1)]})


def test_check_mfu_gap_unknown_device_wording_and_drill_flag():
    (f,) = doctor.check_mfu_gap(
        {0: [_bench_rec(dominant="unknown_device")]})
    assert "DEVICE_SPECS" in f["title"] or any(
        "DEVICE_SPECS" in e for e in f["evidence"])
    (f2,) = doctor.check_mfu_gap({0: [_bench_rec(injected=True)]})
    assert f2["data"]["injected"] is True
    assert any("PTPU_ROOFLINE_TEST_INFLATE" in e for e in f2["evidence"])


def test_check_mfu_gap_uses_newest_row_per_scenario():
    old = _bench_rec(dominant="comm", ts=1.0)
    new = _bench_rec(dominant="host", ts=2.0)
    (f,) = doctor.check_mfu_gap({0: [old, new]})
    assert f["data"]["dominant"] == "host"


def test_check_mfu_gap_ignores_rows_without_block():
    assert doctor.check_mfu_gap(
        {0: [{"kind": "bench.row", "scenario": "x"}]}) == []


# -- /statusz ---------------------------------------------------------------
def test_statusz_roofline_section_from_gauges():
    from paddle_tpu.observability.monitor import StatusServer
    from paddle_tpu.observability.registry import MetricsRegistry
    reg = MetricsRegistry()
    buckets = {"mxu": 2.0, "memory_bound": 5.0, "comm": 1.0, "host": 1.0,
               "padding": 0.5, "unknown_device": 0.0, "residual": 0.5}
    for sink, ms in buckets.items():
        reg.gauge(f"roofline.bucket_ms[scenario=moe,sink={sink}]").set(ms)
    reg.gauge("roofline.coverage[scenario=moe]").set(0.95)
    reg.gauge("roofline.modeled_step_ms[scenario=moe]").set(8.0)
    st = StatusServer(port=0, registry=reg).statusz()
    roof = st["roofline"]
    assert roof["scenarios"]["moe"]["buckets_ms"] == buckets
    assert roof["scenarios"]["moe"]["coverage"] == 0.95
    (verdict,) = roof["mfu_gap"]
    assert verdict["dominant"] == "memory_bound"
    # no roofline gauges at all -> section absent, statusz still renders
    st2 = StatusServer(port=0, registry=MetricsRegistry()).statusz()
    assert st2["roofline"] is None


# -- CLI --------------------------------------------------------------------
def test_roofline_cli_residual_bound(tmp_path, capsys):
    path = str(tmp_path / "ledger.jsonl")
    ledger.append_row(_mk_row(scenario="moe"), path)
    assert roofline.main(["--ledger", path, "--mode", "smoke"]) == 0
    out = capsys.readouterr().out
    assert "moe" in out and "residual" in out
    # a row whose residual busts the bound fails the check
    row = _mk_row(scenario="moe")
    row["roofline"]["buckets_ms"] = {s: 0.0 for s in schema.GAP_SINKS}
    row["roofline"]["buckets_ms"]["residual"] = row["roofline"][
        "measured_step_ms"]
    bad_path = str(tmp_path / "bad.jsonl")
    with open(bad_path, "w") as fh:
        fh.write(json.dumps(row) + "\n")
    assert roofline.main(["--ledger", bad_path,
                          "--max-residual-frac", "0.35"]) != 0
