"""Quantization tests (E7): fake-quant numerics, QAT layer swap + training,
PTQ calibration, int8 conversion accuracy.

Doctrine follows the reference's imperative-QAT tests
(test_imperative_qat.py pattern: quantize a small model, train, check it
still learns and converted inference stays close to fp32).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu import quantization as Q
from paddle_tpu.nn import functional as F


def test_quant_dequant_values_and_ste_gradient():
    x = jnp.asarray([-1.0, -0.5, 0.0, 0.3, 1.0])
    y = Q.quant_dequant(x, jnp.asarray(1.0), bits=8)
    # symmetric int8: q = round(x*127)/127
    np.testing.assert_allclose(
        np.asarray(y), np.round(np.asarray(x) * 127) / 127, atol=1e-7)
    # straight-through: gradient of sum(qdq(x)) is all-ones
    g = jax.grad(lambda t: jnp.sum(Q.quant_dequant(t, jnp.asarray(1.0))))(x)
    np.testing.assert_allclose(np.asarray(g), np.ones(5), atol=1e-7)


def test_channel_wise_weight_quant():
    w = jnp.asarray(np.random.RandomState(0).randn(4, 8) *
                    np.asarray([0.1, 1.0, 10.0, 100.0])[:, None],
                    jnp.float32)
    fq = Q.FakeQuantChannelWiseAbsMax(bits=8, channel_axis=0)
    y = np.asarray(fq(w))
    # each row quantized against its own absmax: error bounded by scale/254
    for i in range(4):
        row_scale = float(np.max(np.abs(np.asarray(w)[i])))
        assert np.max(np.abs(y[i] - np.asarray(w)[i])) <= row_scale / 254 + 1e-7


def _mlp():
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def test_qat_swaps_layers_and_trains():
    pt.seed(0)
    model = _mlp()
    Q.ImperativeQuantAware().quantize(model)
    assert isinstance(model._sub_layers["0"], Q.QuantizedLinear)
    assert isinstance(model._sub_layers["2"], Q.QuantizedLinear)

    model.train()
    params = model.state_dict()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 8), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, (16,)), jnp.int32)
    opt = pt.optimizer.Adam(learning_rate=5e-3)
    state = opt.init(params)

    def step(p, s):
        def loss_fn(q):
            logits, newvars = model.apply(q, x, mutable=True)
            return F.cross_entropy(logits, y), newvars
        (loss, newvars), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p)
        p2, s2 = opt.apply_gradients(grads, p, s)
        for name, _ in model.named_buffers():
            p2[name] = newvars[name]
        return loss, p2, s2

    jitted = jax.jit(step)
    losses = []
    for _ in range(25):
        loss, params, state = jitted(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # the EMA activation scale buffer moved off its init value
    scale_keys = [k for k in params if "input_quanter.scale" in k]
    assert scale_keys and any(
        abs(float(params[k]) - 1.0) > 1e-3 for k in scale_keys)


def test_ptq_calibrates_and_converts_close_to_fp32():
    pt.seed(3)
    model = _mlp()
    model.eval()
    rng = np.random.RandomState(1)
    calib = [jnp.asarray(rng.randn(32, 8), jnp.float32) for _ in range(8)]
    x = jnp.asarray(rng.randn(64, 8), jnp.float32)
    ref = np.asarray(model(x))

    ptq = Q.PostTrainingQuantization()
    ptq.quantize(model, calib)
    ptq.convert(model)
    assert isinstance(model._sub_layers["0"], Q.Int8Linear)
    model.eval()
    got = np.asarray(model(x))
    # int8 per-channel weights + calibrated activations: a few % of range
    scale = np.max(np.abs(ref))
    assert np.max(np.abs(got - ref)) / scale < 0.05


def test_ptq_conv_model_preserves_bn_and_converts_conv():
    """Calibration must not touch BN running stats or enable dropout
    (model stays in eval), and convert() must swap convs to Int8Conv2D."""
    pt.seed(9)
    model = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8), nn.ReLU(),
        nn.Dropout(0.5), nn.Conv2D(8, 4, 1), nn.Flatten(),
        nn.Linear(4 * 8 * 8, 4))
    model.eval()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 3, 8, 8), jnp.float32)
    ref = np.asarray(model(x))
    bn_mean_before = np.asarray(model._sub_layers["1"]._buffers["_mean"])

    ptq = Q.PostTrainingQuantization()
    ptq.quantize(model, [x])
    bn_mean_after = np.asarray(model._sub_layers["1"]._buffers["_mean"])
    np.testing.assert_array_equal(bn_mean_before, bn_mean_after)

    ptq.convert(model)
    assert isinstance(model._sub_layers["0"], Q.Int8Conv2D)
    assert isinstance(model._sub_layers["4"], Q.Int8Conv2D)
    assert isinstance(model._sub_layers["6"], Q.Int8Linear)
    model.eval()
    got = np.asarray(model(x))
    scale = np.max(np.abs(ref))
    assert np.max(np.abs(got - ref)) / scale < 0.05


def test_convert_without_calibration_raises():
    """convert() must refuse uncalibrated models instead of silently
    using in_scale=1.0 (which clips any |x|>1 activation)."""
    import pytest
    pt.seed(3)
    model = _mlp()
    ptq = Q.PostTrainingQuantization()
    # quantize with zero calibration batches: observers stay at scale 0
    ptq.quantize(model, [])
    with pytest.raises(ValueError, match="never calibrated"):
        ptq.convert(model)
    # QAT wrappers with an abs_max input quanter also must not convert
    model2 = _mlp()
    qat = Q.ImperativeQuantAware(activation_quantize_type="abs_max")
    qat.quantize(model2)
    with pytest.raises(ValueError, match="calibrated input observer"):
        Q.PostTrainingQuantization().convert(model2)


def test_wide_bits_use_wider_storage():
    """bits > 8 must widen the storage dtype, not wrap modulo 256."""
    w = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)
    q, s = Q.quantize_weight_to_int(w, bits=12)
    assert q.dtype == jnp.int16
    back = np.asarray(q, np.float32) * float(np.asarray(s))
    assert np.max(np.abs(back - np.asarray(w))) <= float(np.asarray(s)) + 1e-7
    # end-to-end: 12-bit PTQ stays accurate
    pt.seed(2)
    model = _mlp()
    model.eval()
    x = jnp.asarray(np.random.RandomState(3).randn(16, 8), jnp.float32)
    ref = np.asarray(model(x))
    ptq = Q.PostTrainingQuantization(activation_bits=12, weight_bits=12)
    ptq.quantize(model, [x])
    ptq.convert(model)
    model.eval()
    got = np.asarray(model(x))
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 0.01


def test_ptq_conv_string_padding():
    """Conv2D(padding='same') must survive conversion (regression: the
    int8 conv once assumed numeric padding)."""
    pt.seed(4)
    model = nn.Sequential(nn.Conv2D(3, 4, 3, padding="same"), nn.Flatten(),
                          nn.Linear(4 * 8 * 8, 2))
    model.eval()
    x = jnp.asarray(np.random.RandomState(5).randn(2, 3, 8, 8), jnp.float32)
    ref = np.asarray(model(x))
    ptq = Q.PostTrainingQuantization()
    ptq.quantize(model, [x])
    ptq.convert(model)
    model.eval()
    got = np.asarray(model(x))
    assert got.shape == ref.shape
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 0.05


def test_quantize_weight_to_int_roundtrip():
    w = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)
    q, s = Q.quantize_weight_to_int(w, bits=8, channel_axis=1)
    assert q.dtype == jnp.int8
    back = np.asarray(q, np.float32) * np.asarray(s)
    err = np.max(np.abs(back - np.asarray(w)))
    assert err <= float(np.max(np.asarray(s))) / 2 + 1e-7


def test_int8_linear_matmul_path():
    """Int8Linear's dot runs in int8→int32 and matches fp32 within quant
    error on well-scaled inputs."""
    pt.seed(5)
    lin = nn.Linear(32, 16)
    x = jnp.asarray(np.random.RandomState(2).randn(4, 32), jnp.float32)
    ref = np.asarray(lin(x))
    int8 = Q.Int8Linear(lin)
    int8._buffers["in_scale"] = jnp.max(jnp.abs(x))
    got = np.asarray(int8(x))
    scale = np.max(np.abs(ref))
    assert np.max(np.abs(got - ref)) / scale < 0.05
