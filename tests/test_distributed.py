"""Distributed-layer tests on the 8-device virtual CPU mesh — the analog of
the reference's localhost multi-process distributed tests (SURVEY.md §4:
hybrid_parallel_mp_layers.py, dist_allreduce_op.py... all assert
parallel == serial numerics)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet

from paddle_tpu.distributed.sequence_parallel import shard_map

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")


def make_mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    dist.set_hybrid_communicate_group(None)
    dist.get_rng_state_tracker().reset()


class TestTopology:
    def test_coords(self):
        topo = dist.CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, model=1) == 5
        assert topo.get_coord(5) == (1, 0, 1)
        assert topo.get_axis_list("model", 0) == [0, 2, 4, 6]
        comm = topo.get_comm_list("model")
        assert [0, 1] in comm and [6, 7] in comm and len(comm) == 4

    def test_hcg_mesh(self):
        topo = dist.CommunicateTopology(["data", "model"], [4, 2])
        hcg = dist.HybridCommunicateGroup(topo)
        assert hcg.mesh.shape["dp"] == 4 and hcg.mesh.shape["mp"] == 2
        assert hcg.get_data_parallel_world_size() == 4
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_parallel_mode() == "tensor"

    def test_fleet_init(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        mesh = fleet.get_mesh()
        assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 4
        assert dist.get_world_size() == 8


class TestCollectives:
    def test_all_reduce_sum(self):
        mesh = make_mesh((8,), ("dp",))
        x = jnp.arange(8.0)
        f = shard_map(lambda v: dist.all_reduce(v, group="dp"),
                      mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        np.testing.assert_allclose(f(x), np.full(8, x.sum()))

    def test_all_reduce_quantized_close_to_exact(self):
        """EQuARX-style int8 allreduce: ~4x less wire traffic, numerics
        within the int8 quantization error of the exact psum."""
        mesh = make_mesh((8,), ("dp",))
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 512), jnp.float32)
        exact = shard_map(lambda v: dist.all_reduce(v, group="dp"),
                          mesh=mesh, in_specs=P("dp", None),
                          out_specs=P("dp", None))(x)
        quant = shard_map(
            lambda v: dist.all_reduce_quantized(v, group="dp"),
            mesh=mesh, in_specs=P("dp", None),
            out_specs=P("dp", None))(x)
        scale = float(jnp.max(jnp.abs(exact)))
        err = float(jnp.max(jnp.abs(quant - exact))) / scale
        assert err < 0.05, err
        # gradient-sync usage: mean over the group stays close too
        np.testing.assert_allclose(
            np.asarray(quant) / 8, np.asarray(exact) / 8,
            atol=0.05 * scale / 8)
        # IN-mesh non-divisible block size exercises the pad/unpad path
        y = jnp.asarray(rng.randn(8, 33), jnp.float32)
        exact_y = shard_map(lambda v: dist.all_reduce(v, group="dp"),
                            mesh=mesh, in_specs=P("dp", None),
                            out_specs=P("dp", None))(y)
        quant_y = shard_map(
            lambda v: dist.all_reduce_quantized(v, group="dp"),
            mesh=mesh, in_specs=P("dp", None),
            out_specs=P("dp", None))(y)
        sy = float(jnp.max(jnp.abs(exact_y)))
        assert float(jnp.max(jnp.abs(quant_y - exact_y))) / sy < 0.05
        # outside a mesh the op is the identity (paddle group semantics)
        z = jnp.asarray(rng.randn(33), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(dist.all_reduce_quantized(z)), np.asarray(z))

    def test_all_gather_tiled(self):
        mesh = make_mesh((8,), ("dp",))
        x = jnp.arange(8.0)
        # all_gather output is device-varying by VMA typing even though the
        # values coincide — disable the static replication check
        import inspect
        no_rep_check = ("check_vma" if "check_vma" in inspect.signature(
            shard_map).parameters else "check_rep")  # renamed in jax 0.6
        f = shard_map(lambda v: dist.all_gather(v, group="dp"),
                      mesh=mesh, in_specs=P("dp"), out_specs=P(None),
                      **{no_rep_check: False})
        out = f(x)  # every shard holds the full vector
        np.testing.assert_allclose(out, x)

    def test_reduce_scatter(self):
        mesh = make_mesh((8,), ("dp",))
        x = jnp.ones((8, 8))
        f = shard_map(lambda v: dist.reduce_scatter(v, group="dp"),
                      mesh=mesh, in_specs=P(None, None), out_specs=P("dp", None))
        np.testing.assert_allclose(f(x), np.full((8, 8), 8.0))

    def test_broadcast(self):
        mesh = make_mesh((8,), ("dp",))
        x = jnp.arange(8.0)
        f = shard_map(lambda v: dist.broadcast(v, src=3, group="dp"),
                      mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        np.testing.assert_allclose(f(x), np.full(8, 3.0))

    def test_all_to_all(self):
        mesh = make_mesh((4,), ("ep",))
        x = jnp.arange(16.0).reshape(4, 4)
        # tiled all_to_all is a distributed resharding: row-sharded input
        # becomes column-sharded, values unchanged (rank j ends up holding
        # column j) — the global_scatter/gather dispatch backbone
        f = shard_map(lambda v: dist.all_to_all(v, group="ep",
                                                split_axis=1, concat_axis=0),
                      mesh=mesh, in_specs=P("ep", None), out_specs=P(None, "ep"))
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))

    def test_p2p_push_ring(self):
        mesh = make_mesh((4,), ("pp",))
        x = jnp.arange(4.0)
        f = shard_map(lambda v: dist.p2p_push(v, offset=1, group="pp"),
                      mesh=mesh, in_specs=P("pp"), out_specs=P("pp"))
        np.testing.assert_allclose(f(x), [3.0, 0.0, 1.0, 2.0])

    def test_outside_mesh_identity(self):
        x = jnp.arange(4.0)
        np.testing.assert_allclose(dist.all_reduce(x, group="dp"), x)
        np.testing.assert_allclose(dist.all_gather(x, group="dp"), x)


class TestVocabParallelOps:
    def test_parallel_cross_entropy_matches_serial(self):
        mesh = make_mesh((4,), ("mp",))
        B, V = 6, 32
        logits = jnp.asarray(np.random.RandomState(0).randn(B, V), jnp.float32)
        label = jnp.asarray(np.random.RandomState(1).randint(0, V, (B,)))

        f = shard_map(
            lambda lg, lb: dist.parallel_cross_entropy(lg, lb, mp_axis="mp"),
            mesh=mesh, in_specs=(P(None, "mp"), P(None)),
            out_specs=P(None))
        par = f(logits, label)
        ser = F.cross_entropy(logits, label, reduction="none")
        np.testing.assert_allclose(par, ser, rtol=1e-5)

    def test_parallel_ce_gspmd_mode(self):
        # outside shard_map: plain stable CE
        B, V = 4, 16
        logits = jnp.asarray(np.random.RandomState(0).randn(B, V), jnp.float32)
        label = jnp.asarray([1, 5, 7, 15])
        out = dist.parallel_cross_entropy(logits, label)
        ser = F.cross_entropy(logits, label, reduction="none")
        np.testing.assert_allclose(out, ser, rtol=1e-5)

    def test_vocab_parallel_embedding(self):
        mesh = make_mesh((4,), ("mp",))
        V, H = 16, 8
        table = jnp.asarray(np.random.RandomState(0).randn(V, H), jnp.float32)
        ids = jnp.asarray([0, 3, 7, 12, 15])
        f = shard_map(
            lambda t, i: dist.vocab_parallel_embedding(i, t, mp_axis="mp"),
            mesh=mesh, in_specs=(P("mp", None), P(None)), out_specs=P(None, None))
        np.testing.assert_allclose(f(table, ids), jnp.take(table, ids, axis=0),
                                   rtol=1e-6)


class TestTPLayersGSPMD:
    def _mlp(self):
        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = dist.ColumnParallelLinear(16, 32, gather_output=False)
                self.fc2 = dist.RowParallelLinear(32, 16, input_is_parallel=True)

            def forward(self, x):
                return self.fc2(F.gelu(self.fc1(x)))
        return MLP()

    def test_tp_forward_matches_serial(self):
        pt.seed(7)
        model = self._mlp()
        x = jnp.asarray(np.random.RandomState(2).randn(4, 16), jnp.float32)
        variables = model.state_dict()
        serial = model.apply(variables, x)

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        fleet.init(strategy=strategy)
        fleet.distributed_model(model)  # places params per pspec
        mesh = fleet.get_mesh()
        sharded_vars = model.state_dict()
        assert sharded_vars["fc1.weight"].sharding.spec == P(None, "mp")

        @jax.jit
        def fwd(v, xx):
            return model.apply(v, xx)

        xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        out = fwd(sharded_vars, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(serial),
                                   rtol=2e-5, atol=2e-6)

    def test_dp_tp_train_step_matches_serial(self):
        """The core §4 invariant: one hybrid-sharded jitted train step
        produces the same loss and updated params as the serial step."""
        pt.seed(11)
        model = self._mlp()
        opt = pt.optimizer.Adam(learning_rate=1e-2)
        x = jnp.asarray(np.random.RandomState(3).randn(8, 16), jnp.float32)
        y = jnp.asarray(np.random.RandomState(4).randn(8, 16), jnp.float32)

        def loss_fn(params, xx, yy):
            out = model.apply(params, xx)
            return jnp.mean(jnp.square(out - yy))

        params0 = model.state_dict()
        opt_state = opt.init(params0)

        def step(params, state, xx, yy):
            loss, grads = jax.value_and_grad(loss_fn)(params, xx, yy)
            new_params, state = opt.apply_gradients(grads, params, state)
            return loss, new_params, state

        loss_s, params_s, _ = step(params0, opt_state, x, y)

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        fleet.init(strategy=strategy)
        mesh = fleet.get_mesh()
        fleet.distributed_model(model)
        params_d = model.state_dict()
        opt_state_d = opt.init(params_d)
        xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        ys = jax.device_put(y, NamedSharding(mesh, P("dp", None)))
        loss_p, params_p, _ = jax.jit(step)(params_d, opt_state_d, xs, ys)

        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=1e-6)
        for k in params_s:
            np.testing.assert_allclose(np.asarray(params_p[k]),
                                       np.asarray(params_s[k]),
                                       rtol=3e-5, atol=3e-6)


class TestRNGTracker:
    def test_per_rank_distinct_masks(self):
        # stochastic ops consult the GLOBAL tracker (the one functional's
        # op_key provider reads), as in the reference's module-level
        # get_rng_state_tracker()
        tracker = dist.get_rng_state_tracker()
        tracker.reset()
        tracker.add("global", 123)
        tracker.add("local", 123, local_axis="mp")
        mesh = make_mesh((4,), ("mp",))

        def body(x):
            with tracker.rng_state("local"):
                return F.dropout(x, p=0.5, training=True)

        f = shard_map(body, mesh=mesh, in_specs=P(None, None),
                      out_specs=P("mp", None))
        # replicate input; per-rank outputs stacked along axis 0
        out = f(jnp.ones((1, 64)))
        masks = np.asarray(out != 0)
        # at least one pair of ranks must differ (p≈1-2^-64 with same seed
        # they'd be identical without the axis fold-in)
        assert any(not np.array_equal(masks[0], masks[i]) for i in range(1, 4))

    def test_global_state_same_mask(self):
        tracker = dist.get_rng_state_tracker()
        tracker.reset()
        tracker.add("g", 5)

        def body(x):
            with tracker.rng_state("g"):
                return F.dropout(x, p=0.5, training=True)

        mesh = make_mesh((4,), ("mp",))
        f = shard_map(body, mesh=mesh, in_specs=P(None, None),
                      out_specs=P("mp", None))
        out = np.asarray(f(jnp.ones((1, 64))) != 0)
        assert all(np.array_equal(out[0], out[i]) for i in range(1, 4))

    def test_duplicate_name_raises(self):
        tracker = dist.RNGStatesTracker()
        tracker.add("x", 1)
        with pytest.raises(Exception):
            tracker.add("x", 2)

    def test_tracker_composes_with_jitted_key_scope(self):
        """Under jit, a tracker scope must not bake a constant key: the
        per-step key_scope key is the traced base, so masks change across
        steps of one compiled program."""
        from paddle_tpu.framework import random as fw_random
        tracker = dist.get_rng_state_tracker()
        tracker.reset()
        tracker.add("mp_rng", 77)

        @jax.jit
        def step(key):
            with fw_random.key_scope(key):
                with tracker.rng_state("mp_rng"):
                    return F.dropout(jnp.ones((64,)), p=0.5, training=True)

        m1 = np.asarray(step(jax.random.key(0)) != 0)
        m2 = np.asarray(step(jax.random.key(1)) != 0)
        assert not np.array_equal(m1, m2)
        # and deterministic for the same step key
        m1b = np.asarray(step(jax.random.key(0)) != 0)
        assert np.array_equal(m1, m1b)


class TestRecompute:
    def test_recompute_same_value_and_grad(self):
        w = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)
        x = jnp.ones((2, 8), jnp.float32)

        def block(w, x):
            return jnp.sum(jnp.tanh(x @ w) ** 2)

        direct_v, direct_g = jax.value_and_grad(block)(w, x)
        rc_v, rc_g = jax.value_and_grad(
            lambda w, x: fleet.recompute(block, w, x))(w, x)
        # rtol covers XLA-version fusion differences between the recompute
        # and direct paths (observed 3.4e-6 on the 0.4.x CPU backend)
        np.testing.assert_allclose(rc_v, direct_v, rtol=2e-5)
        np.testing.assert_allclose(rc_g, direct_g, rtol=2e-5)


class TestShardBatch:
    def test_shard_batch_places_on_dp(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8}
        fleet.init(strategy=strategy)
        x = np.random.randn(16, 4).astype(np.float32)
        xs = dist.shard_batch(x)
        assert xs.sharding.spec == P("dp", None)
        np.testing.assert_allclose(np.asarray(xs), x)


class TestGPTShardingHygiene:
    def test_no_activation_all_gather_in_train_step(self):
        """The dp×mp train step must not all-gather activations: the fused
        qkv reshape is head-major precisely so GSPMD keeps the mp sharding
        through it (regression for the involuntary-full-rematerialization
        XLA warning the round-3 dryrun logged)."""
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        pt.seed(5)
        model = GPTForCausalLM(gpt_tiny(hidden_dropout=0.0,
                                        attention_dropout=0.0))
        model.eval()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        fleet.distributed_model(model)
        params = model.state_dict()

        B, S = 8, 32
        rng = np.random.RandomState(0)
        ids = dist.shard_batch(rng.randint(0, 1024, (B, S)).astype(np.int32))

        def step(p, ids):
            return jax.grad(lambda q: model.apply(q, ids, labels=ids)[0])(p)

        txt = jax.jit(step).lower(params, ids).compile().as_text()
        cfg = model.config
        # the remat signature: an all-gather materializing the full fused-qkv
        # activation, flat or factored
        bad_shapes = [f"[{B},{S},{3 * cfg.hidden_size}]",
                      f"[{B},{S},{cfg.num_heads},3,{cfg.head_dim}]"]
        offending = [l for l in txt.splitlines() if "all-gather" in l
                     and any(s in l for s in bad_shapes)]
        assert not offending, offending[:3]


class TestMultiSliceTopology:
    """DCN-aware device placement (the multi-slice comm-backend layer;
    ≙ the reference's hierarchical-allreduce / fleet_executor DCN split)."""

    class _FakeDev:
        def __init__(self, i, slice_index):
            self.id = i
            self.slice_index = slice_index
            self.process_index = slice_index
            self.platform = "tpu"
            self.device_kind = "fake TPU"
            self.coords = (i % 4, 0, 0)
            self.core_on_chip = 0

        def __repr__(self):
            return f"fake(id={self.id},slice={self.slice_index})"

    def test_dcn_axis_spans_slices(self):
        # 2 slices × 4 devices: dp=4 with dcn_dp=2 → dp splits (2 dcn, 2 ici)
        devs = [self._FakeDev(i, i // 4) for i in range(8)]
        topo = dist.CommunicateTopology(["data", "model"], [4, 2])
        hcg = dist.HybridCommunicateGroup(topo, devices=devs,
                                          dcn_dims={"data": 2})
        arr = hcg.mesh.devices
        assert arr.shape == (4, 2)
        # each mp pair must sit inside ONE slice (mp rides ICI)...
        for i in range(4):
            assert len({d.slice_index for d in arr[i]}) == 1
        # ...and the dp axis must cross slices (dp rides DCN)
        assert len({d.slice_index for d in arr[:, 0]}) == 2

    def test_mismatched_dcn_factors_raise(self):
        devs = [self._FakeDev(i, i // 4) for i in range(8)]
        topo = dist.CommunicateTopology(["data", "model"], [4, 2])
        with pytest.raises(Exception):
            dist.HybridCommunicateGroup(topo, devices=devs,
                                        dcn_dims={"data": 4})

    def test_single_slice_unchanged(self):
        topo = dist.CommunicateTopology(["data", "model"], [4, 2])
        hcg = dist.HybridCommunicateGroup(topo, dcn_dims={"data": 2})
        assert hcg.mesh.devices.shape == (4, 2)  # CPU devices: 1 slice
