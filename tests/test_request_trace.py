"""Fleet request tracing (ISSUE 18): knob/sampling semantics, the
TraceAssembler (amortized decode, orphan detection, coverage, chrome
export), end-to-end continuity across failover / drain-migration /
router crash-recovery / preemption-recompute / quarantine (every
request yields exactly ONE assembled trace, no orphan spans), the
router's client-observed TTFT/TPOT histograms + slow-request table,
the autoscaler's PTPU_FLEET_SLO_SOURCE switch, and the doctor's
tail_latency verdict."""
import os
import re

import pytest

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.inference.fleet import (FleetAutoscaler, FleetOverloaded,
                                        LocalReplica, Router, ServingSLO)
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability import doctor, requesttrace
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.testing import faults

pytestmark = pytest.mark.telemetry


def tiny_model(max_pos=64):
    pt.seed(7)
    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=2,
                    num_heads=2, ffn_hidden_size=64,
                    max_position_embeddings=max_pos, hidden_dropout=0.0,
                    attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class Capture:
    """List sink: every emitted record, in order."""

    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)

    def flush(self):
        pass

    def close(self):
        pass


def capture_registry():
    reg = MetricsRegistry()
    cap = Capture()
    reg.add_sink(cap)
    return reg, cap


def local_fleet(n=2, registry=None, **engine_kw):
    reg = registry or MetricsRegistry()
    reps = [LocalReplica(ServingEngine(tiny_model(), registry=reg,
                                       replica_id=i, **engine_kw),
                         replica_id=i)
            for i in range(n)]
    return reps, reg


def assemble(records):
    return requesttrace.TraceAssembler().from_records(records)


def assert_one_complete_trace_per_request(result, rids):
    traces = result["traces"]
    assert len(traces) == len(rids), \
        f"{len(traces)} traces for {len(rids)} requests"
    assert {t["request_id"] for t in traces} == set(rids)
    assert result["complete"] == len(rids), result
    assert not result["orphan_spans"], result["orphan_spans"]
    return traces


# ---------------------------------------------------------------------------
# knobs & sampling
# ---------------------------------------------------------------------------
class TestKnobs:
    def test_default_on(self, monkeypatch):
        monkeypatch.delenv(requesttrace.TRACE_REQUESTS_ENV,
                           raising=False)
        monkeypatch.delenv(requesttrace.TRACE_SAMPLE_ENV, raising=False)
        assert requesttrace.tracing_enabled()
        assert requesttrace.mint_trace_id("r1") is not None

    def test_disabled_by_env(self, monkeypatch):
        for off in ("0", "false", "no", "off"):
            monkeypatch.setenv(requesttrace.TRACE_REQUESTS_ENV, off)
            assert not requesttrace.tracing_enabled()
            assert requesttrace.mint_trace_id("r1") is None

    def test_sampling_deterministic_per_request_id(self, monkeypatch):
        monkeypatch.setenv(requesttrace.TRACE_SAMPLE_ENV, "0.5")
        decisions = {f"req-{i}": requesttrace.sampled(f"req-{i}")
                     for i in range(64)}
        # deterministic: re-asking gives the same answer, no RNG state
        assert all(requesttrace.sampled(r) == d
                   for r, d in decisions.items())
        # a 50% sample actually splits the id space
        assert 0 < sum(decisions.values()) < len(decisions)
        monkeypatch.setenv(requesttrace.TRACE_SAMPLE_ENV, "0.0")
        assert not any(requesttrace.sampled(r) for r in decisions)
        monkeypatch.setenv(requesttrace.TRACE_SAMPLE_ENV, "1.0")
        assert all(requesttrace.sampled(r) for r in decisions)

    def test_component_buckets_fold_recompute_causes(self):
        bucket = requesttrace.component_bucket
        assert bucket("preempt") == "preempt_recompute"
        assert bucket("failover") == "failover_recompute"
        assert bucket("migration_recompute") == "migration"
        assert bucket("retry_backoff") == "retry_backoff"
        assert bucket("something_new") == "something_new"

    def test_untraced_engine_emits_no_spans(self, monkeypatch):
        monkeypatch.setenv(requesttrace.TRACE_REQUESTS_ENV, "0")
        reg, cap = capture_registry()
        eng = ServingEngine(tiny_model(), max_seqs=2, kv_block_size=4,
                            registry=reg)
        rid = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run(max_steps=50)
        assert eng.collect(rid)["tokens"]
        assert not [r for r in cap.records
                    if r["kind"].startswith("trace.")]

    def test_emission_cost_meter(self):
        reg, cap = capture_registry()
        cost = requesttrace.emission_cost
        # off by default: emits are free of accounting
        assert not cost.enabled
        requesttrace.emit_span(reg, "t1", "r1", "prefill", "prefill",
                               1.0, 2.0, "replica-0")
        assert cost.count == 0 and cost.seconds == 0.0
        cost.start()
        try:
            requesttrace.emit_span(reg, "t1", "r1", "decode", "decode",
                                   2.0, 3.0, "replica-0")
            requesttrace.emit_decode_span(reg, [("r1", "t1")], 2,
                                          3.0, 4.0, "replica-0")
            # no-op calls (untraced) are metered too — they are still
            # hot-path cost the serving loop pays
            requesttrace.emit_span(reg, None, "r2", "decode", "decode",
                                   2.0, 3.0, "replica-0")
        finally:
            cost.stop()
        assert cost.count == 3
        assert cost.seconds > 0.0
        # start() resets the accumulator
        cost.start()
        cost.stop()
        assert cost.count == 0 and cost.seconds == 0.0
        assert len([r for r in cap.records
                    if r["kind"] == "trace.span"]) == 3


# ---------------------------------------------------------------------------
# assembler units
# ---------------------------------------------------------------------------
def span(tid, rid, name, comp, t0, t1, proc, **kw):
    return {"kind": "trace.span", "trace_id": tid, "request_id": rid,
            "name": name, "component": comp, "t0": t0, "t1": t1,
            "dur_ms": (t1 - t0) * 1e3, "proc": proc, **kw}


class TestAssembler:
    def test_amortized_decode_share(self):
        recs = [
            {"kind": "trace.request", "trace_id": "t1",
             "request_id": "r1", "t0": 0.0, "prompt_len": 3,
             "proc": "router"},
            {"kind": "trace.request", "trace_id": "t2",
             "request_id": "r2", "t0": 0.0, "prompt_len": 3,
             "proc": "router"},
            {"kind": "trace.span", "name": "decode_batch",
             "component": "decode", "t0": 0.0, "t1": 0.1,
             "dur_ms": 100.0, "proc": "replica-0", "residents": 4,
             "requests": [["r1", "t1"], ["r2", "t2"]]},
            {"kind": "trace.request_end", "trace_id": "t1",
             "request_id": "r1", "t1": 0.1, "reason": "length",
             "tokens": 4, "proc": "router"},
            {"kind": "trace.request_end", "trace_id": "t2",
             "request_id": "r2", "t1": 0.1, "reason": "length",
             "tokens": 4, "proc": "router"},
        ]
        result = assemble(recs)
        traces = assert_one_complete_trace_per_request(
            result, ["r1", "r2"])
        for t in traces:
            # 100ms batch over 4 residents -> 25ms amortized share
            assert t["components"]["decode"] == pytest.approx(25.0)
            assert t["coverage"] == pytest.approx(1.0)

    def test_orphan_span_detected(self):
        recs = [span("ghost", "rg", "prefill", "prefill",
                     0.0, 0.1, "replica-0")]
        result = assemble(recs)
        assert result["orphan_spans"] == ["ghost"]
        assert result["complete"] == 0

    def test_coverage_is_union_of_span_intervals(self):
        recs = [
            {"kind": "trace.request", "trace_id": "t1",
             "request_id": "r1", "t0": 0.0, "prompt_len": 1,
             "proc": "router"},
            # two overlapping spans covering [0, 0.5] of a 1s window
            span("t1", "r1", "prefill", "prefill", 0.0, 0.4,
                 "replica-0"),
            span("t1", "r1", "queue", "queue", 0.3, 0.5, "replica-0"),
            {"kind": "trace.request_end", "trace_id": "t1",
             "request_id": "r1", "t1": 1.0, "reason": "length",
             "tokens": 1, "proc": "router"},
        ]
        (trace,) = assemble(recs)["traces"]
        assert trace["coverage"] == pytest.approx(0.5)
        assert trace["latency_ms"] == pytest.approx(1000.0)

    def test_chrome_export_process_and_thread_metadata(self):
        recs = [
            {"kind": "trace.request", "trace_id": "t1",
             "request_id": "r1", "t0": 0.0, "prompt_len": 1,
             "proc": "router"},
            span("t1", "r1", "dispatch", "dispatch", 0.0, 0.01,
                 "router"),
            span("t1", "r1", "prefill", "prefill", 0.01, 0.1,
                 "replica-0"),
            {"kind": "trace.request_end", "trace_id": "t1",
             "request_id": "r1", "t1": 0.1, "reason": "length",
             "tokens": 1, "proc": "router"},
        ]
        events = requesttrace.chrome_trace_events(
            assemble(recs)["traces"])
        meta = [e for e in events if e["ph"] == "M"]
        names = {(e["name"], e["args"]["name"]) for e in meta}
        assert ("process_name", "router") in names
        assert ("process_name", "replica-0") in names
        assert any(e["name"] == "thread_name" and
                   e["args"]["name"] == "r1" for e in meta)
        xs = [e for e in events if e["ph"] == "X"]
        # spans land in their own process's track
        pid_by_proc = {e["args"]["name"]: e["pid"] for e in meta
                       if e["name"] == "process_name"}
        assert {e["pid"] for e in xs} == set(pid_by_proc.values())

    def test_aggregate_chrome_merge_disambiguates_workers(self, tmp_path):
        import json
        from paddle_tpu.observability.aggregate import export_chrome_trace
        from paddle_tpu.observability.sinks import metrics_dir
        mdir = metrics_dir(str(tmp_path))
        os.makedirs(mdir)
        with open(os.path.join(mdir, "worker-0.jsonl"), "w") as f:
            f.write(json.dumps(span("t1", "r1", "dispatch", "dispatch",
                                    0.0, 0.01, "router")) + "\n")
        with open(os.path.join(mdir, "worker-1.jsonl"), "w") as f:
            f.write(json.dumps(span("t1", "r1", "prefill", "prefill",
                                    0.01, 0.1, "replica-0")) + "\n")
            f.write(json.dumps({"kind": "step", "step": 1, "ts": 0.2,
                                "step_time_ms": 50.0}) + "\n")
        n = export_chrome_trace(str(tmp_path))
        assert n and n >= 5          # 2 proc meta + >=2 thread meta + X
        payload = json.loads(
            open(os.path.join(mdir, "trace.json")).read())
        events = payload["traceEvents"]
        procs = {e["args"]["name"]: e["pid"] for e in events
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        # one pid per worker stream, labeled from the stream's own proc
        assert procs == {"router": 0, "replica-0": 1}
        xs = [e for e in events if e.get("ph") == "X"]
        assert {e["pid"] for e in xs} == {0, 1}
        assert any(e["cat"] == "step" for e in xs)


# ---------------------------------------------------------------------------
# end-to-end continuity: engine-owned traces
# ---------------------------------------------------------------------------
class TestEngineTraces:
    def test_direct_submission_yields_one_complete_trace(self):
        reg, cap = capture_registry()
        eng = ServingEngine(tiny_model(), max_seqs=2, kv_block_size=4,
                            registry=reg)
        rid = eng.submit([1, 2, 3], max_new_tokens=6)
        eng.run(max_steps=100)
        assert eng.collect(rid)["tokens"]
        result = assemble(cap.records)
        (trace,) = assert_one_complete_trace_per_request(result, [rid])
        assert trace["reason"] == "max_new_tokens"
        comps = trace["components"]
        assert comps.get("prefill", 0) > 0
        assert comps.get("decode", 0) > 0
        assert trace["procs"] == ["replica-0"]

    def test_preemption_recompute_traced(self):
        reg, cap = capture_registry()
        # pool far too small for 4 concurrent streams -> preemptions
        eng = ServingEngine(tiny_model(), max_seqs=4, kv_block_size=4,
                            num_kv_blocks=5, registry=reg)
        prompts = [[1, 2, 3, 4], [5, 6, 7], [8, 9],
                   [10, 11, 12, 13, 14]]
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        while eng.has_work():
            eng.step()
        for r in rids:
            assert eng.collect(r)["tokens"]
        assert eng.sched.preemptions > 0
        result = assemble(cap.records)
        traces = assert_one_complete_trace_per_request(result, rids)
        # the evicted stream's re-queue + re-prefill is attributed to
        # preempt_recompute, not generic queue/prefill
        assert any(t["components"].get("preempt_recompute", 0) > 0
                   for t in traces)

    def test_quarantine_traced_to_poisoned_end(self, tmp_path):
        reg, cap = capture_registry()
        injector = faults.poison_request(1, mode="raise",
                                         kinds=("decode",))
        eng = ServingEngine(tiny_model(), max_seqs=3, kv_block_size=4,
                            registry=reg, step_fault=injector,
                            run_dir=str(tmp_path))
        rids = [eng.submit([1 + i, 2, 3 + i], max_new_tokens=6)
                for i in range(3)]
        eng.run(max_steps=500)
        bad = eng._submit_order[1]
        assert list(eng.quarantined) == [bad]
        result = assemble(cap.records)
        traces = assert_one_complete_trace_per_request(result, rids)
        by_rid = {t["request_id"]: t for t in traces}
        assert by_rid[bad]["reason"] == "poisoned"
        assert by_rid[bad]["components"].get("quarantine", 0) > 0
        for r in rids:
            if r != bad:
                assert by_rid[r]["reason"] == "max_new_tokens"


# ---------------------------------------------------------------------------
# end-to-end continuity: router-owned traces across fleet chaos
# ---------------------------------------------------------------------------
class TestFleetTraces:
    def test_failover_stitches_one_trace_across_replicas(self):
        reps, _ = local_fleet(2, max_seqs=4, kv_block_size=4)
        reg, cap = capture_registry()
        # one registry for router + engines so the capture sees all
        for rep in reps:
            rep.engine._registry = reg
        router = Router(reps, registry=reg)
        rids = [router.submit([1, 2, 3 + i], max_new_tokens=10)
                for i in range(3)]
        while len(router.journals[rids[0]].tokens) < 3:
            router.pump()
        victim = router.journals[rids[0]].replica_id
        reps[victim].engine._state = "stopped"
        outs = [router.collect(r, timeout=60) for r in rids]
        assert all(len(o["tokens"]) == 10 for o in outs)
        assert router.failovers >= 1
        result = assemble(cap.records)
        traces = assert_one_complete_trace_per_request(result, rids)
        moved = [t for t in traces if len(
            [p for p in t["procs"] if p.startswith("replica-")]) == 2]
        assert moved, "no trace stitched across both replicas"
        for t in moved:
            assert t["components"].get("failover_recompute", 0) > 0

    def test_deliver_spans_coalesced_and_flushed_at_finish(self):
        reps, _ = local_fleet(1, max_seqs=2, kv_block_size=4)
        reg, cap = capture_registry()
        reps[0].engine._registry = reg
        router = Router(reps, registry=reg)
        rid = router.submit([1, 2, 3], max_new_tokens=6)
        router.collect(rid, timeout=60)
        journal = router.journals[rid]
        deliver = sorted(
            (r for r in cap.records if r["kind"] == "trace.span"
             and r.get("name") == "deliver"
             and r["request_id"] == rid),
            key=lambda r: r["t0"])
        # coalesced: far fewer spans than polls — at most one per
        # DELIVER_FLUSH_S stretch plus the finish flush
        wall = journal.end_wall - journal.submit_wall
        from paddle_tpu.inference.fleet.router import DELIVER_FLUSH_S
        assert 1 <= len(deliver) <= int(wall / DELIVER_FLUSH_S) + 2
        # contiguous chain from dispatch (the dispatch span covers
        # submit → dispatch) through finish: the residue bucket needs
        # the full client-observed window covered
        assert deliver[0]["t0"] >= journal.submit_wall - 1e-6
        assert deliver[0]["t0"] <= journal.first_token_wall + 1e-6
        assert abs(deliver[-1]["t1"] - journal.end_wall) < 1e-6
        for prev, nxt in zip(deliver, deliver[1:]):
            assert nxt["t0"] <= prev["t1"] + 1e-6

    def test_drain_migration_traced(self, tmp_path):
        reps, _ = local_fleet(2, max_seqs=4, kv_block_size=4,
                              run_dir=str(tmp_path))
        reg, cap = capture_registry()
        for rep in reps:
            rep.engine._registry = reg
        router = Router(reps, registry=reg)
        rids = [router.submit([1, 2, 3 + i], max_new_tokens=12)
                for i in range(4)]
        router.pump()
        moved = router.drain_replica(0, timeout=0.0)
        outs = [router.collect(r, timeout=60) for r in rids]
        assert all(len(o["tokens"]) == 12 for o in outs)
        result = assemble(cap.records)
        traces = assert_one_complete_trace_per_request(result, rids)
        if moved:
            assert any(t["components"].get("migration", 0) > 0
                       for t in traces)

    def test_router_crash_recovery_preserves_trace_id(self, tmp_path):
        reps, _ = local_fleet(2, max_seqs=4, kv_block_size=4)
        reg1, cap1 = capture_registry()
        for rep in reps:
            rep.engine._registry = reg1
        router = Router(reps, registry=reg1, run_dir=str(tmp_path))
        rids = [router.submit([1, 2, 3 + i], max_new_tokens=10)
                for i in range(3)]
        while any(len(j.tokens) < 2 for j in router.journals.values()):
            router.pump()
        want_tids = {r: router.journals[r].trace_id for r in rids}
        assert all(want_tids.values())
        # simulated router SIGKILL: no drain, no retire — a FRESH
        # router recovers from the journal directory alone
        del router
        reg2, cap2 = capture_registry()
        for rep in reps:
            rep.engine._registry = reg2
        recovered = Router(reps, registry=reg2, recover=str(tmp_path))
        for r in rids:
            assert recovered.journals[r].trace_id == want_tids[r], \
                "recovery minted a new trace_id"
        outs = [recovered.collect(r, timeout=60) for r in rids]
        assert all(len(o["tokens"]) == 10 for o in outs)
        # the two router incarnations' records merge into ONE trace
        # per request (same ids), nothing orphaned
        result = assemble(cap1.records + cap2.records)
        assert_one_complete_trace_per_request(result, rids)

    def test_shed_stream_is_a_complete_trace(self):
        from paddle_tpu.inference.fleet import DispatchExhausted

        class Unreachable:
            """Passes admission (idle stats) but every dispatch fails."""
            replica_id = 0

            def serving_stats(self):
                return {"queue_depth": 0, "waiting": 0, "running": 0}

            def healthz(self):
                return (200, "serving")

            def alive(self):
                return True

            def submit(self, record):
                raise ConnectionError("refused")

        reg, cap = capture_registry()
        router = Router([Unreachable()], registry=reg, retry_max=1,
                        sleep=lambda t: None)
        with pytest.raises((FleetOverloaded, DispatchExhausted)):
            router.submit([1, 2], max_new_tokens=4)
        result = assemble(cap.records)
        # the refusal still closed the lifecycle: one complete trace
        # with reason "shed", nothing orphaned
        assert result["complete"] == len(result["traces"]) == 1
        assert result["traces"][0]["reason"] == "shed"
        assert not result["orphan_spans"]

    def test_wal_cross_check_in_assemble_run(self, tmp_path):
        from paddle_tpu.observability.sinks import (MetricsWriter,
                                                    metrics_dir)
        reps, _ = local_fleet(1, max_seqs=2, kv_block_size=4)
        reg = MetricsRegistry()
        writer = reg.add_sink(MetricsWriter(metrics_dir(str(tmp_path)),
                                            worker_id=0, flush_every=1))
        reps[0].engine._registry = reg
        router = Router(reps, registry=reg, run_dir=str(tmp_path))
        rid = router.submit([1, 2, 3], max_new_tokens=6)
        router.collect(rid, timeout=60)
        reg.remove_sink(writer)
        result = requesttrace.assemble_run(str(tmp_path))
        assert_one_complete_trace_per_request(result, [rid])
        assert result["wal_streams"] == 1
        assert result["wal_matched"] == 1


# ---------------------------------------------------------------------------
# router SLO surfaces + autoscaler source switch
# ---------------------------------------------------------------------------
class TestRouterSLO:
    def _run_streams(self, n=3, max_new=8):
        reps, _ = local_fleet(2, max_seqs=4, kv_block_size=4)
        reg, cap = capture_registry()
        for rep in reps:
            rep.engine._registry = reg
        router = Router(reps, registry=reg)
        rids = [router.submit([1, 2, 3 + i], max_new_tokens=max_new)
                for i in range(n)]
        for r in rids:
            router.collect(r, timeout=60)
        return router, reg

    def test_ttft_tpot_histograms_and_slo_stats(self):
        router, reg = self._run_streams()
        snap = reg.snapshot()
        assert snap["fleet.ttft_ms"]["count"] == 3
        assert snap["fleet.ttft_ms"]["p50"] > 0
        assert snap["fleet.tpot_ms"]["count"] > 0
        slo = router.slo_stats()["slo"]
        assert slo["ttft_ms"]["samples"] == 3
        assert slo["ttft_ms"]["p99"] >= slo["ttft_ms"]["p50"] > 0
        assert slo["tpot_ms"]["samples"] > 0

    def test_slow_requests_table_in_stats(self):
        router, _ = self._run_streams()
        stats = router.stats()
        rows = stats["slow_requests"]
        assert rows and len(rows) <= 8
        top = rows[0]
        for field in ("request_id", "trace_id", "state", "latency_ms",
                      "ttft_ms", "tokens", "components"):
            assert field in top, field
        # sorted by latency, slowest first
        lats = [r["latency_ms"] for r in rows]
        assert lats == sorted(lats, reverse=True)
        assert stats["slo"]["ttft_ms"]["samples"] == 3

    def test_autoscaler_burns_on_router_tails(self):
        router, reg = self._run_streams()

        class Mgr:
            replicas = router.replicas

            def poll_states(self):
                return {0: "healthy", 1: "healthy"}

        scaler = FleetAutoscaler(
            Mgr(), router=router,
            slo=ServingSLO(queue_depth=None, ttft_p99_ms=0.0001),
            slo_source="router", registry=reg, clock=lambda: 0.0)
        sample = scaler.sample()
        assert sample["burning"]
        assert "router" in sample["violations"]
        assert any("ttft_p99" in v
                   for v in sample["violations"]["router"])
        assert scaler.stats()["slo_source"] == "router"

    def test_slo_source_env_default(self, monkeypatch):
        from paddle_tpu.inference.fleet.autoscaler import (
            SLO_SOURCE_ENV, default_slo_source)
        monkeypatch.delenv(SLO_SOURCE_ENV, raising=False)
        assert default_slo_source() == "engine"
        monkeypatch.setenv(SLO_SOURCE_ENV, "router")
        assert default_slo_source() == "router"
        monkeypatch.setenv(SLO_SOURCE_ENV, "bogus")
        with pytest.raises(Exception):
            default_slo_source()

    def test_router_slo_source_requires_router(self):
        class Mgr:
            replicas = []

            def poll_states(self):
                return {}

        with pytest.raises(Exception):
            FleetAutoscaler(Mgr(), slo_source="router",
                            registry=MetricsRegistry())


# ---------------------------------------------------------------------------
# doctor: tail_latency verdict
# ---------------------------------------------------------------------------
def _lifecycle(tid, rid, t0, t1, reason="length"):
    return [{"kind": "trace.request", "trace_id": tid,
             "request_id": rid, "t0": t0, "prompt_len": 3,
             "proc": "router"},
            {"kind": "trace.request_end", "trace_id": tid,
             "request_id": rid, "t1": t1, "reason": reason,
             "tokens": 8, "proc": "router"}]


class TestDoctorTailLatency:
    def _workers(self, slow_extra=2.0):
        recs = []
        for i in range(7):                 # healthy herd: 1s each
            tid, rid = f"t{i}", f"r{i}"
            recs += _lifecycle(tid, rid, 0.0, 1.0)
            recs.append(span(tid, rid, "decode_batch", "decode",
                             0.0, 1.0, "replica-0"))
        # one tail request: same decode, big failover recompute
        recs += _lifecycle("t9", "r9", 0.0, 1.0 + slow_extra)
        recs.append(span("t9", "r9", "decode_batch", "decode",
                         0.0, 1.0, "replica-0"))
        recs.append(span("t9", "r9", "prefill", "failover",
                         1.0, 1.0 + slow_extra, "replica-1"))
        return {0: recs}

    def test_names_dominant_tail_component(self):
        findings = doctor.check_tail_latency(self._workers())
        assert len(findings) == 1
        f = findings[0]
        assert f["kind"] == "tail_latency"
        assert f["data"]["dominant"] == "failover_recompute"
        assert f["data"]["p99_ms"] > f["data"]["median_ms"]
        assert any("failover_recompute" in line
                   for line in f["evidence"])

    def test_flat_tail_is_silent(self):
        findings = doctor.check_tail_latency(
            self._workers(slow_extra=0.05))
        assert findings == []

    def test_diagnose_includes_tail_latency(self, tmp_path):
        import json
        from paddle_tpu.observability.sinks import metrics_dir
        mdir = metrics_dir(str(tmp_path))
        os.makedirs(mdir)
        with open(os.path.join(mdir, "worker-0.jsonl"), "w") as f:
            for rec in self._workers()[0]:
                f.write(json.dumps(rec) + "\n")
        report = doctor.diagnose(str(tmp_path))
        kinds = [f["kind"] for f in report["findings"]]
        assert "tail_latency" in kinds

    def test_no_traces_no_finding(self):
        assert doctor.check_tail_latency({0: [
            {"kind": "step", "step": 1, "step_time_ms": 5.0}]}) == []
