"""Fused transformer-block execution (ISSUE 7, ops/fused_block.py):
OpTest-style parity of the fused attention/FFN block halves against the
unfused oracle composition — forward AND gradients, on both the jnp
reference route and the Pallas route (interpret mode on CPU) — plus the
decode/kv-cache variant, dropout-on determinism under a fixed seed, and
the compile contract (one compilation per step shape, zero storms)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.nn.functional as F
from paddle_tpu import ops
from paddle_tpu.ops import fused_block as fb

pytestmark = pytest.mark.kernels


@pytest.fixture(autouse=True)
def _no_mesh():
    # the eligibility gate (and the model parity below) assumes no active
    # hybrid mesh; reset BEFORE each test too — earlier files in a full
    # tier-1 run (e.g. test_fleet_strategy) leave one installed
    dist.set_hybrid_communicate_group(None)
    yield
    dist.set_hybrid_communicate_group(None)

EPS = 1e-5


def _params(h, ffn=None, seed=0):
    r = np.random.RandomState(seed)
    ffn = ffn or 4 * h
    a = lambda *s: jnp.asarray(r.randn(*s) * 0.07, jnp.float32)  # noqa: E731
    return dict(qkv_w=a(h, 3 * h), qkv_b=a(3 * h), out_w=a(h, h),
                out_b=a(h), w1=a(h, ffn), b1=a(ffn), w2=a(ffn, h),
                b2=a(h), g=jnp.asarray(1 + 0.1 * r.randn(h), jnp.float32),
                beta=a(h))


def _x(b=2, s=64, h=128, seed=1):
    r = np.random.RandomState(seed)
    return jnp.asarray(r.randn(b, s, h) * 0.5, jnp.float32)


def _oracle_attn_block(x, p, num_heads, rotary=False):
    """The unfused module-path math (GPTDecoderLayer attention half)."""
    b, s, h = x.shape
    d = h // num_heads
    ln = F.layer_norm(x, (h,), p["g"], p["beta"], EPS)
    qkv = F.linear(ln, p["qkv_w"], p["qkv_b"]).reshape(b, s, num_heads,
                                                       3, d)
    q = qkv[:, :, :, 0].transpose(0, 2, 1, 3)
    k = qkv[:, :, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, :, 2].transpose(0, 2, 1, 3)
    if rotary:
        q, k = ops.rotary_position_embedding(q, k)
    o = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                       training=False)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, h)
    return x + F.linear(o, p["out_w"], p["out_b"])


def _oracle_ffn_block(x, p):
    h = x.shape[-1]
    ln = F.layer_norm(x, (h,), p["g"], p["beta"], EPS)
    return x + F.linear(F.gelu(F.linear(ln, p["w1"], p["b1"])),
                        p["w2"], p["b2"])


@pytest.fixture(params=["reference", "pallas"])
def route(request, monkeypatch):
    monkeypatch.setenv(fb.FUSED_BLOCK_ENV, request.param)
    return request.param


class TestFusedAttentionBlock:
    def test_forward_matches_oracle(self, route):
        x, p = _x(), _params(128)
        got = ops.fused_attention_block(
            x, p["qkv_w"], p["qkv_b"], p["out_w"], p["out_b"], p["g"],
            p["beta"], num_heads=4, epsilon=EPS, training=False)
        ref = _oracle_attn_block(x, p, 4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_rotary_matches_oracle(self, route):
        x, p = _x(), _params(128)
        got = ops.fused_attention_block(
            x, p["qkv_w"], p["qkv_b"], p["out_w"], p["out_b"], p["g"],
            p["beta"], num_heads=4, epsilon=EPS, rotary=True,
            training=False)
        ref = _oracle_attn_block(x, p, 4, rotary=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=3e-5, atol=3e-5)

    def test_grads_match_oracle(self, route):
        x, p = _x(b=1, s=32, h=128), _params(128)

        def loss_fused(x_, qkv_w, out_w, g):
            pp = dict(p, qkv_w=qkv_w, out_w=out_w, g=g)
            return jnp.sum(ops.fused_attention_block(
                x_, pp["qkv_w"], pp["qkv_b"], pp["out_w"], pp["out_b"],
                pp["g"], pp["beta"], num_heads=4, epsilon=EPS,
                training=False) ** 2)

        def loss_ref(x_, qkv_w, out_w, g):
            pp = dict(p, qkv_w=qkv_w, out_w=out_w, g=g)
            return jnp.sum(_oracle_attn_block(x_, pp, 4) ** 2)

        args = (x, p["qkv_w"], p["out_w"], p["g"])
        gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(*args)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(*args)
        for a, b, name in zip(gf, gr, ("dx", "dqkv_w", "dout_w", "dg")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4, err_msg=name)


class TestFusedFFNBlock:
    def test_forward_matches_oracle(self, route):
        x, p = _x(), _params(128, ffn=256)
        got = ops.fused_ffn_block(x, p["w1"], p["b1"], p["w2"], p["b2"],
                                  p["g"], p["beta"], epsilon=EPS,
                                  training=False)
        ref = _oracle_ffn_block(x, p)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_oracle(self, route):
        x, p = _x(b=1, s=32, h=128), _params(128, ffn=256)

        def loss_fused(x_, w1, w2, beta):
            return jnp.sum(ops.fused_ffn_block(
                x_, w1, p["b1"], w2, p["b2"], p["g"], beta, epsilon=EPS,
                training=False) ** 2)

        def loss_ref(x_, w1, w2, beta):
            pp = dict(p, w1=w1, w2=w2, beta=beta)
            return jnp.sum(_oracle_ffn_block(x_, pp) ** 2)

        args = (x, p["w1"], p["w2"], p["beta"])
        gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(*args)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(*args)
        for a, b, name in zip(gf, gr, ("dx", "dw1", "dw2", "dbeta")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4, err_msg=name)

    def test_relu_and_inner_dropout(self, route):
        # relu activation + dropout1 engage the kernel's act/drop1 branch;
        # determinism given a seed is the only exact cross-call contract
        x, p = _x(), _params(128, ffn=256)
        kw = dict(activation="relu", dropout1=0.3, dropout2=0.2,
                  epsilon=EPS, training=True, seed=11)
        a = ops.fused_ffn_block(x, p["w1"], p["b1"], p["w2"], p["b2"],
                                p["g"], p["beta"], **kw)
        b = ops.fused_ffn_block(x, p["w1"], p["b1"], p["w2"], p["b2"],
                                p["g"], p["beta"], **kw)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.isfinite(np.asarray(a)).all()


class TestFusedBlockDropout:
    """The whole block's dropout rides the counter-hash streams (the
    reference's Philox-offset design): deterministic per seed, distinct
    across seeds, regenerated identically in backward."""

    def _run(self, seed, x, p):
        return ops.fused_attention_block(
            x, p["qkv_w"], p["qkv_b"], p["out_w"], p["out_b"], p["g"],
            p["beta"], num_heads=4, epsilon=EPS, attn_dropout=0.3,
            hidden_dropout=0.2, training=True, seed=seed)

    def test_deterministic_given_seed(self, route):
        x, p = _x(), _params(128)
        a, b, c = self._run(7, x, p), self._run(7, x, p), self._run(8, x, p)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))

    def test_eval_disables(self, route):
        x, p = _x(), _params(128)
        a = ops.fused_attention_block(
            x, p["qkv_w"], p["qkv_b"], p["out_w"], p["out_b"], p["g"],
            p["beta"], num_heads=4, epsilon=EPS, attn_dropout=0.5,
            hidden_dropout=0.5, training=False, seed=3)
        ref = _oracle_attn_block(x, p, 4)
        np.testing.assert_allclose(np.asarray(a), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_routes_agree_given_seed(self, monkeypatch):
        # same hash streams on both routes → the reference really is the
        # interpret-mode oracle even with dropout on
        x, p = _x(), _params(128)
        outs = {}
        for r in ("reference", "pallas"):
            monkeypatch.setenv(fb.FUSED_BLOCK_ENV, r)
            outs[r] = self._run(7, x, p)
        np.testing.assert_allclose(np.asarray(outs["reference"]),
                                   np.asarray(outs["pallas"]),
                                   rtol=1e-4, atol=1e-4)

    def test_jitted_steps_vary_via_key_scope(self, route):
        x, p = _x(b=1, s=32, h=128), _params(128)

        @jax.jit
        def step(key, x_):
            with pt.key_scope(key):
                return ops.fused_attention_block(
                    x_, p["qkv_w"], p["qkv_b"], p["out_w"], p["out_b"],
                    p["g"], p["beta"], num_heads=4, epsilon=EPS,
                    attn_dropout=0.3, hidden_dropout=0.2, training=True)

        o1 = step(jax.random.key(1), x)
        o2 = step(jax.random.key(2), x)
        assert not np.allclose(np.asarray(o1), np.asarray(o2))

    def test_grads_agree_across_routes_with_dropout(self, monkeypatch):
        # the pallas route's custom backward regenerates the hash masks in
        # recompute; given one seed it must produce the same gradients as
        # plain autodiff through the reference composition
        x, p = _x(b=1, s=32, h=128), _params(128)

        def loss(x_, w):
            return jnp.sum(ops.fused_attention_block(
                x_, p["qkv_w"], p["qkv_b"], w, p["out_b"],
                p["g"], p["beta"], num_heads=4, epsilon=EPS,
                attn_dropout=0.3, hidden_dropout=0.2, training=True,
                seed=5) ** 2)

        grads = {}
        for r in ("reference", "pallas"):
            monkeypatch.setenv(fb.FUSED_BLOCK_ENV, r)
            grads[r] = jax.grad(loss, argnums=(0, 1))(x, p["out_w"])
        for a, b, name in zip(grads["pallas"], grads["reference"],
                              ("dx", "dout_w")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4, err_msg=name)


class TestFusedDecode:
    """The decode/kv-cache variant (reference CacheKV path) and its parity
    with the train-path block."""

    def test_prefill_matches_train_block(self, route):
        x, p = _x(), _params(128)
        b, s, h = x.shape
        kb = jnp.zeros((b, 4, 128, h // 4))
        vb = jnp.zeros((b, 4, 128, h // 4))
        y, kb, vb = ops.fused_attention_block_kvcache(
            x, p["qkv_w"], p["qkv_b"], p["out_w"], p["out_b"], p["g"],
            p["beta"], kb, vb, jnp.asarray(0, jnp.int32), num_heads=4,
            epsilon=EPS)
        ref = _oracle_attn_block(x, p, 4)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_incremental_decode_matches_full(self, route):
        # prefill s tokens, then decode one more: the decode step's output
        # must equal the train-path block's last position over s+1 tokens
        p = _params(128)
        full = _x(b=1, s=33, h=128, seed=4)
        x, nxt = full[:, :32], full[:, 32:]
        kb = jnp.zeros((1, 4, 64, 32))
        vb = jnp.zeros((1, 4, 64, 32))
        _, kb, vb = ops.fused_attention_block_kvcache(
            x, p["qkv_w"], p["qkv_b"], p["out_w"], p["out_b"], p["g"],
            p["beta"], kb, vb, jnp.asarray(0, jnp.int32), num_heads=4,
            epsilon=EPS)
        y, _, _ = ops.fused_attention_block_kvcache(
            nxt, p["qkv_w"], p["qkv_b"], p["out_w"], p["out_b"], p["g"],
            p["beta"], kb, vb, jnp.asarray(32, jnp.int32), num_heads=4,
            epsilon=EPS)
        ref = _oracle_attn_block(full, p, 4)[:, 32:]
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=3e-5, atol=3e-5)


class TestFusedModelParity:
    """GPTConfig.use_fused_block end-to-end: loss, gradients, greedy
    decode, and the serving engine must match the unfused path."""

    def _models(self, **kw):
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        out = {}
        for fused in (True, False):
            pt.seed(0)
            out[fused] = GPTForCausalLM(gpt_tiny(
                max_position_embeddings=128, hidden_dropout=0.0,
                attention_dropout=0.0, use_fused_block=fused, **kw))
        return out

    def test_loss_and_grad_parity(self):
        rng = np.random.RandomState(2)
        ids = jnp.asarray(rng.randint(0, 1024, (2, 64)), jnp.int32)
        models = self._models()
        losses, grads = {}, {}
        for fused, m in models.items():
            m.train()
            params = m.state_dict()

            def lf(p):
                loss, _ = m.apply(p, ids, labels=ids)
                return loss

            losses[fused] = float(lf(params))
            grads[fused] = jax.grad(lf)(params)
        assert abs(losses[True] - losses[False]) < 1e-5, losses
        err = max(float(jnp.max(jnp.abs(grads[True][k] - grads[False][k])))
                  for k in grads[True])
        assert err < 1e-5, err

    def test_recompute_composes(self):
        # the fused block must run (and differentiate) under jax.checkpoint
        rng = np.random.RandomState(3)
        ids = jnp.asarray(rng.randint(0, 1024, (2, 64)), jnp.int32)
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        losses = {}
        for remat in (True, False):
            pt.seed(0)
            m = GPTForCausalLM(gpt_tiny(
                max_position_embeddings=128, hidden_dropout=0.0,
                attention_dropout=0.0, use_fused_block=True,
                use_recompute=remat))
            m.train()

            def lf(p):
                loss, _ = m.apply(p, ids, labels=ids)
                return loss

            params = m.state_dict()
            losses[remat] = (float(lf(params)),
                             float(jnp.max(jnp.abs(
                                 jax.grad(lf)(params)["gpt.wte.weight"]))))
        np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)

    def test_greedy_decode_parity(self):
        rng = np.random.RandomState(5)
        ids = jnp.asarray(rng.randint(0, 1024, (2, 8)), jnp.int32)
        models = self._models()
        toks = {}
        for fused, m in models.items():
            m.eval()
            toks[fused] = np.asarray(m.generate(ids, max_new_tokens=8))
        np.testing.assert_array_equal(toks[True], toks[False])

    @pytest.mark.serving
    def test_serving_engine_parity(self):
        from paddle_tpu.inference import ServingEngine
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        outs = {}
        for fused in (True, False):
            pt.seed(0)
            cfg = gpt_tiny(vocab_size=64, hidden_size=32, num_layers=2,
                           num_heads=2, ffn_hidden_size=64,
                           max_position_embeddings=32, hidden_dropout=0.0,
                           attention_dropout=0.0, use_fused_block=fused)
            engine = ServingEngine(GPTForCausalLM(cfg), max_seqs=4,
                                   kv_block_size=4)
            rids = [engine.submit([1 + i] * (2 + i), max_new_tokens=4)
                    for i in range(2)]
            engine.run(max_steps=100)
            outs[fused] = [engine.collect(r)["tokens"] for r in rids]
        assert outs[True] == outs[False], outs

    def test_moe_and_sp_stay_unfused(self):
        # eligibility gate: MoE layers and sp/cp configs must not take the
        # fused route (it has no aux-loss or sharded-layout support)
        from paddle_tpu.models import gpt_tiny
        from paddle_tpu.models.gpt import GPTDecoderLayer
        pt.seed(0)
        moe = GPTDecoderLayer(gpt_tiny(use_fused_block=True,
                                       moe_num_experts=2, moe_every=1), 0)
        assert not moe._fused_block_ok()
        sp = GPTDecoderLayer(gpt_tiny(use_fused_block=True,
                                      sequence_parallel=True), 0)
        assert not sp._fused_block_ok()
        plain = GPTDecoderLayer(gpt_tiny(use_fused_block=True), 0)
        assert plain._fused_block_ok()


class TestFusedCompileContract:
    """ISSUE 7 acceptance: exactly one compilation per step shape across a
    fused train run, zero retrace storms (PR 4 compile tracker)."""

    def test_one_compile_per_shape(self):
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        from paddle_tpu.observability.compilation import (CompileTracker,
                                                          track_jit)
        pt.seed(0)
        m = GPTForCausalLM(gpt_tiny(max_position_embeddings=128,
                                    hidden_dropout=0.1,
                                    attention_dropout=0.1,
                                    use_fused_block=True))
        m.train()
        params = m.state_dict()
        from paddle_tpu.framework import random as fw_random

        def step(p, ids, key):
            with fw_random.key_scope(key):
                loss, _ = m.apply(p, ids, labels=ids)
            return loss

        tracker = CompileTracker()
        jitted = track_jit(jax.jit(step), name="fused_step",
                           arg_names=("params", "ids", "key"),
                           tracker=tracker)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, 1024, (2, 64)), jnp.int32)
        key = jax.random.key(0)
        for i in range(4):
            jitted(params, ids, jax.random.fold_in(key, i))
        st = tracker.stats("fused_step")
        assert st["traces"] == 1 and st["retraces"] == 0, st
        assert st["storms"] == 0, st
        # a second shape is ONE more compile — and still no storm
        ids2 = jnp.asarray(rng.randint(0, 1024, (4, 64)), jnp.int32)
        for i in range(3):
            jitted(params, ids2, jax.random.fold_in(key, 10 + i))
        st = tracker.stats("fused_step")
        assert st["traces"] == 2 and st["retraces"] == 1, st
        assert st["storms"] == 0, st
