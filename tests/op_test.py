"""OpTest harness — the analog of the reference's workhorse test base
(python/paddle/fluid/tests/unittests/op_test.py:289): compare op outputs to a
numpy reference and analytic gradients to numeric finite differences
(get_numeric_gradient, op_test.py:120), swept over dtypes.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def numeric_grad(fn: Callable, args: Sequence[np.ndarray], wrt: int,
                 eps: float = 1e-3) -> np.ndarray:
    """Central finite differences of sum(fn(*args)) w.r.t. args[wrt]."""
    args = [np.asarray(a, np.float64 if np.issubdtype(np.asarray(a).dtype,
                                                      np.floating) else None)
            if np.issubdtype(np.asarray(a).dtype, np.floating)
            else np.asarray(a) for a in args]
    x = args[wrt].astype(np.float64)
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        hi = float(np.sum(np.asarray(
            fn(*[a if j != wrt else x.reshape(x.shape) for j, a in enumerate(args)]))))
        flat[i] = orig - eps
        lo = float(np.sum(np.asarray(
            fn(*[a if j != wrt else x.reshape(x.shape) for j, a in enumerate(args)]))))
        flat[i] = orig
        gflat[i] = (hi - lo) / (2 * eps)
    return g


def check_output(op_fn: Callable, ref_fn: Callable, args: Sequence,
                 rtol: float = 1e-5, atol: float = 1e-6):
    got = np.asarray(op_fn(*args))
    want = np.asarray(ref_fn(*[np.asarray(a) for a in args]))
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)


def check_grad(op_fn: Callable, args: Sequence, wrt=(0,), rtol: float = 5e-3,
               atol: float = 5e-4, eps: float = 1e-3):
    """Analytic (jax.grad) vs numeric finite-difference gradients."""
    def scalar_fn(*xs):
        return jnp.sum(op_fn(*xs))
    for i in wrt:
        analytic = np.asarray(jax.grad(scalar_fn, argnums=i)(
            *[jnp.asarray(a, jnp.float32) if np.issubdtype(
                np.asarray(a).dtype, np.floating) else jnp.asarray(a)
              for a in args]))
        def np_fn(*xs):
            return op_fn(*[jnp.asarray(x) for x in xs])
        numeric = numeric_grad(np_fn, args, i, eps)
        np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                                   err_msg=f"grad mismatch wrt arg {i}")
