"""Sequence/context parallelism tests: ring attention == full attention,
Ulysses GPT == serial GPT — the parallel==serial doctrine applied to the
long-context axis (additive capability; reference has none, SURVEY §5)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.sequence_parallel import (ring_attention,
                                                      ring_attention_sharded,
                                                      shard_map)
from paddle_tpu.framework import random as fw_random
from paddle_tpu.nn import functional as F

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.set_hybrid_communicate_group(None)


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


class TestRingAttention:
    def _data(self, B=2, H=4, S=64, D=16, dtype=jnp.float32):
        rng = np.random.RandomState(0)
        mk = lambda: jnp.asarray(rng.randn(B, H, S, D), dtype)
        return mk(), mk(), mk()

    def test_forward_matches_full(self):
        q, k, v = self._data()
        ref = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=0.0, training=False)
        mesh = _mesh((4,), ("sp",))

        out = jax.jit(lambda q, k, v: shard_map(
            lambda a, b, c: ring_attention(a, b, c, "sp"),
            mesh=mesh, in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None))(q, k, v))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_non_causal(self):
        q, k, v = self._data()
        ref = F.scaled_dot_product_attention(
            q, k, v, is_causal=False, dropout_p=0.0, training=False)
        mesh = _mesh((4,), ("sp",))
        out = jax.jit(lambda q, k, v: shard_map(
            lambda a, b, c: ring_attention(a, b, c, "sp", causal=False),
            mesh=mesh, in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None))(q, k, v))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_full(self):
        q, k, v = self._data()
        mesh = _mesh((4,), ("sp",))

        def ring_loss(q, k, v):
            out = shard_map(
                lambda a, b, c: ring_attention(a, b, c, "sp"),
                mesh=mesh, in_specs=P(None, None, "sp", None),
                out_specs=P(None, None, "sp", None))(q, k, v)
            return jnp.sum(out ** 2)

        def full_loss(q, k, v):
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=0.0, training=False)
            return jnp.sum(out ** 2)

        g_r = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
        g_f = jax.jit(jax.grad(full_loss, argnums=(0, 1, 2)))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_r, g_f):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4, err_msg=name)

    def test_sharded_wrapper_on_hybrid_mesh(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2}
        # 'sp' via explicit topology: dp×sp×mp needs the sp axis in the mesh
        topo = dist.CommunicateTopology(["data", "sequence", "model"], [2, 2, 2])
        dist.set_hybrid_communicate_group(
            dist.HybridCommunicateGroup(topo))
        q, k, v = self._data(B=2, H=4, S=64, D=16)
        ref = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=0.0, training=False)
        out = jax.jit(
            lambda a, b, c: ring_attention_sharded(a, b, c))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestGPTSequenceParallel:
    def _model_and_data(self, **cfg_kw):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        pt.seed(13)
        cfg = GPTConfig(hidden_size=128, num_layers=2, num_heads=8,
                        max_position_embeddings=128, vocab_size=512,
                        hidden_dropout=0.0, attention_dropout=0.0, **cfg_kw)
        model = GPTForCausalLM(cfg)
        model.eval()
        rng = np.random.RandomState(1)
        ids = jnp.asarray(rng.randint(0, 512, (4, 64)), jnp.int32)
        return model, ids

    def _sp_topology(self, dp, sp, mp):
        topo = dist.CommunicateTopology(["data", "sequence", "model"],
                                        [dp, sp, mp])
        dist.set_hybrid_communicate_group(dist.HybridCommunicateGroup(topo))

    def test_ulysses_matches_serial(self):
        model, ids = self._model_and_data(sequence_parallel=True)
        params = model.state_dict()
        loss_s, _ = model.apply(params, ids, labels=ids)

        self._sp_topology(2, 2, 2)
        dist.get_mesh()
        from paddle_tpu.distributed.parallel import (
            device_put_sharded_variables)
        device_put_sharded_variables(model)
        params_d = model.state_dict()
        loss_p, _ = jax.jit(
            lambda p, i: model.apply(p, i, labels=i)
        )(params_d, dist.shard_batch(ids))
        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=2e-5)

    def test_ring_gpt_matches_serial(self):
        model, ids = self._model_and_data(context_parallel=True)
        params = model.state_dict()
        loss_s, _ = model.apply(params, ids, labels=ids)  # serial fallback

        self._sp_topology(2, 2, 2)
        from paddle_tpu.distributed.parallel import (
            device_put_sharded_variables)
        device_put_sharded_variables(model)
        params_d = model.state_dict()
        loss_p, _ = jax.jit(
            lambda p, i: model.apply(p, i, labels=i)
        )(params_d, dist.shard_batch(ids))
        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=2e-5)

    def test_ulysses_grads_match_serial(self):
        model, ids = self._model_and_data(sequence_parallel=True)
        model.train()
        params = model.state_dict()
        key = jax.random.key(3)

        def loss_fn(p, i):
            with fw_random.key_scope(key):
                loss, _ = model.apply(p, i, labels=i)
            return loss

        g_s = jax.grad(loss_fn)(params, ids)
        self._sp_topology(2, 2, 2)
        from paddle_tpu.distributed.parallel import (
            device_put_sharded_variables)
        device_put_sharded_variables(model)
        params_d = model.state_dict()
        g_p = jax.jit(jax.grad(loss_fn))(params_d, dist.shard_batch(ids))
        for k in g_s:
            np.testing.assert_allclose(np.asarray(g_p[k]),
                                       np.asarray(g_s[k]),
                                       rtol=5e-4, atol=5e-5, err_msg=k)


class TestContextParallelFallback:
    def test_mesh_without_sp_axis_uses_serial_path(self):
        """Regression: context_parallel on an sp-less mesh must fall back to
        the serial attention path, not crash."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        pt.seed(2)
        cfg = GPTConfig(hidden_size=64, num_layers=2, num_heads=4,
                        max_position_embeddings=128, vocab_size=512,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        context_parallel=True)
        model = GPTForCausalLM(cfg)
        model.eval()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        fleet.distributed_model(model)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (4, 32)),
                          jnp.int32)
        loss, _ = jax.jit(lambda p, i: model.apply(p, i, labels=i))(
            model.state_dict(), dist.shard_batch(ids))
        assert np.isfinite(float(loss))

    def test_attention_dropout_rejected(self):
        from paddle_tpu.models import GPTConfig
        with pytest.raises(Exception, match="attention_dropout"):
            GPTConfig(context_parallel=True, attention_dropout=0.1)
