"""paddle.signal parity (reference python/paddle/signal.py): torch
goldens for stft/istft, analytic checks for frame/overlap_add."""
import numpy as np
import torch

import jax.numpy as jnp

import paddle_tpu as pt

R = np.random.RandomState(0)


class TestFrame:
    def test_frame_last_axis(self):
        x = jnp.asarray(np.arange(10, dtype=np.float32))
        f = np.asarray(pt.signal.frame(x, frame_length=4, hop_length=2))
        assert f.shape == (4, 4)
        np.testing.assert_array_equal(f[:, 0], [0, 1, 2, 3])
        np.testing.assert_array_equal(f[:, 1], [2, 3, 4, 5])
        np.testing.assert_array_equal(f[:, 3], [6, 7, 8, 9])

    def test_frame_batched(self):
        x = jnp.asarray(R.randn(3, 16), jnp.float32)
        f = pt.signal.frame(x, 8, 4)
        assert f.shape == (3, 8, 3)

    def test_overlap_add_inverts_hop_eq_len(self):
        x = jnp.asarray(R.randn(2, 12), jnp.float32)
        f = pt.signal.frame(x, 4, 4)
        back = pt.signal.overlap_add(f, 4)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                                   rtol=1e-6)

    def test_overlap_add_sums_overlaps(self):
        ones = jnp.ones((4, 3))          # 3 frames of length 4, hop 2
        y = np.asarray(pt.signal.overlap_add(ones, 2))
        np.testing.assert_array_equal(y, [1, 1, 2, 2, 2, 2, 1, 1])


class TestStft:
    def test_matches_torch(self):
        x = R.randn(2, 256).astype(np.float32)
        win = np.hanning(128).astype(np.float32)
        got = np.asarray(pt.signal.stft(
            jnp.asarray(x), n_fft=128, hop_length=32,
            window=jnp.asarray(win)))
        want = torch.stft(torch.from_numpy(x), n_fft=128, hop_length=32,
                          window=torch.from_numpy(win), center=True,
                          pad_mode="reflect", onesided=True,
                          return_complex=True).numpy()
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_normalized_and_twosided(self):
        x = R.randn(128).astype(np.float32)
        got = np.asarray(pt.signal.stft(jnp.asarray(x), n_fft=64,
                                        onesided=False, normalized=True))
        want = torch.stft(torch.from_numpy(x), n_fft=64, center=True,
                          onesided=False, normalized=True,
                          return_complex=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestIstft:
    def test_round_trip(self):
        x = R.randn(1, 400).astype(np.float32)
        win = np.hanning(128).astype(np.float32)
        spec = pt.signal.stft(jnp.asarray(x), n_fft=128, hop_length=32,
                              window=jnp.asarray(win))
        back = np.asarray(pt.signal.istft(
            spec, n_fft=128, hop_length=32, window=jnp.asarray(win)))
        # exact within the frame-covered prefix (the tail past the last
        # full frame is unrecoverable, same as torch)
        n = back.shape[-1]
        np.testing.assert_allclose(back, x[:, :n], rtol=1e-3, atol=1e-4)

    def test_matches_torch(self):
        x = R.randn(300).astype(np.float32)
        win = np.hanning(64).astype(np.float32)
        spec_t = torch.stft(torch.from_numpy(x), n_fft=64, hop_length=16,
                            window=torch.from_numpy(win),
                            return_complex=True)
        got = np.asarray(pt.signal.istft(
            jnp.asarray(spec_t.numpy()), n_fft=64, hop_length=16,
            window=jnp.asarray(win), length=300))
        want = torch.istft(spec_t, n_fft=64, hop_length=16,
                           window=torch.from_numpy(win), length=300).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


class TestValidation:
    def test_hop_zero_rejected(self):
        import pytest
        from paddle_tpu.framework.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="hop_length"):
            pt.signal.stft(jnp.zeros(64), n_fft=16, hop_length=0)

    def test_nola_violation_raises(self):
        import pytest
        from paddle_tpu.framework.errors import InvalidArgumentError
        spec = jnp.zeros((17, 4), jnp.complex64)
        with pytest.raises(InvalidArgumentError, match="NOLA"):
            pt.signal.istft(spec, n_fft=32, hop_length=33,
                            window=jnp.asarray(
                                np.hanning(32).astype(np.float32)))

    def test_return_complex_needs_twosided(self):
        import pytest
        from paddle_tpu.framework.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="onesided"):
            pt.signal.istft(jnp.zeros((17, 4), jnp.complex64), n_fft=32,
                            return_complex=True)
