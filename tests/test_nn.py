"""Layer system + functional op tests (reference test strategy: SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

from op_test import check_grad, check_output


class TestLayerSystem:
    def test_parameter_registration(self):
        l = nn.Linear(4, 3)
        names = [n for n, _ in l.named_parameters()]
        assert names == ["weight", "bias"]
        assert l.weight.shape == (4, 3)
        assert l.bias.shape == (3,)

    def test_nested_state_dict(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = m.state_dict()
        assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}

    def test_set_state_dict_roundtrip(self):
        m1, m2 = nn.Linear(4, 3), nn.Linear(4, 3)
        m2.set_state_dict(m1.state_dict())
        x = pt.randn((2, 4))
        np.testing.assert_allclose(np.asarray(m1(x)), np.asarray(m2(x)))

    def test_apply_is_pure(self):
        m = nn.Linear(4, 3)
        x = pt.randn((2, 4))
        eager = m(x)
        sd = m.state_dict()
        out = m.apply(sd, x)
        np.testing.assert_allclose(np.asarray(eager), np.asarray(out))
        # zero params through apply, eager unchanged afterwards
        zeros = {k: jnp.zeros_like(v) for k, v in sd.items()}
        out0 = m.apply(zeros, x)
        assert float(jnp.abs(out0).sum()) == 0.0
        np.testing.assert_allclose(np.asarray(m(x)), np.asarray(eager))

    def test_apply_under_jit_and_grad(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
        x = pt.randn((16, 4))
        y = pt.randn((16, 1))
        sd = m.state_dict()

        @jax.jit
        def loss_fn(params):
            return jnp.mean((m.apply(params, x) - y) ** 2)

        g = jax.grad(loss_fn)(sd)
        assert set(g) == set(sd)
        assert all(np.isfinite(np.asarray(v)).all() for v in g.values())

    def test_train_eval_mode(self):
        m = nn.Dropout(0.5)
        x = jnp.ones((100,))
        m.eval()
        np.testing.assert_allclose(np.asarray(m(x)), np.ones(100))
        m.train()
        out = np.asarray(m(x))
        assert (out == 0).any() and (out > 1).any()

    def test_batchnorm_buffers_update(self):
        bn = nn.BatchNorm2D(3)
        x = pt.randn((4, 3, 8, 8)) * 2 + 1.0
        bn.train()
        _ = bn(x)
        rm = np.asarray(bn._buffers["_mean"])
        assert not np.allclose(rm, 0)  # moved toward batch mean

    def test_batchnorm_mutable_apply(self):
        bn = nn.BatchNorm2D(3)
        sd = bn.state_dict()
        x = pt.randn((4, 3, 8, 8)) + 5.0

        @jax.jit
        def step(variables):
            out, new_vars = bn.apply(variables, x, mutable=True)
            return out, new_vars

        _, new_vars = step(sd)
        assert not np.allclose(np.asarray(new_vars["_mean"]), 0)
        # stateful buffers untouched by the functional path
        np.testing.assert_allclose(np.asarray(bn._buffers["_mean"]), 0)

    def test_astype_casts_params(self):
        m = nn.Linear(4, 3).astype("bfloat16")
        assert m.weight.dtype == jnp.bfloat16


class TestFunctionalOps:
    def test_linear_matches_numpy(self):
        x = np.random.randn(5, 4).astype(np.float32)
        w = np.random.randn(4, 3).astype(np.float32)
        b = np.random.randn(3).astype(np.float32)
        check_output(lambda x, w, b: F.linear(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)),
                     lambda x, w, b: x @ w + b, [x, w, b])

    def test_linear_grad(self):
        x = np.random.randn(3, 4).astype(np.float32)
        w = np.random.randn(4, 2).astype(np.float32)
        check_grad(lambda x, w: F.linear(x, w), [x, w], wrt=(0, 1))

    def test_softmax_cross_entropy_matches_numpy(self):
        logits = np.random.randn(8, 10).astype(np.float32)
        labels = np.random.randint(0, 10, (8,))

        def ref(logits, labels):
            m = logits - logits.max(-1, keepdims=True)
            logp = m - np.log(np.exp(m).sum(-1, keepdims=True))
            return -logp[np.arange(8), labels].mean()

        got = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
        np.testing.assert_allclose(np.asarray(got), ref(logits, labels), rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.randn(4, 5).astype(np.float32)
        labels = np.array([1, -100, 3, -100])
        got = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels),
                              ignore_index=-100)
        keep = F.cross_entropy(jnp.asarray(logits[[0, 2]]),
                               jnp.asarray(labels[[0, 2]]))
        np.testing.assert_allclose(np.asarray(got), np.asarray(keep), rtol=1e-6)

    def test_layer_norm_grad(self):
        x = np.random.randn(4, 6).astype(np.float32)
        w = np.random.rand(6).astype(np.float32) + 0.5
        b = np.random.randn(6).astype(np.float32)
        # eps=1e-2 (like test_conv2d_grad): layer_norm evaluates in f32,
        # where the default eps=1e-3 central differences are dominated by
        # roundoff (~1e-6 per 24-element sum / 2e-3 ≈ the 5e-4 atol) —
        # red since the seed on CPU jax 0.4.37; the analytic grad is fine
        check_grad(lambda x, w, b: F.layer_norm(x, (6,), w, b), [x, w, b],
                   wrt=(0, 1, 2), eps=1e-2)

    def test_conv2d_matches_lax_reference(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        w = np.random.randn(4, 3, 3, 3).astype(np.float32)
        y = F.conv2d(jnp.asarray(x), jnp.asarray(w), stride=1, padding=1)
        assert y.shape == (2, 4, 8, 8)
        # against scipy-style direct computation on one output element
        patch = x[0, :, 0:3, 0:3]
        np.testing.assert_allclose(float(y[0, 1, 1, 1]),
                                   float((patch * w[1]).sum()), rtol=1e-4)

    def test_conv2d_grad(self):
        x = np.random.randn(1, 2, 5, 5).astype(np.float32)
        w = np.random.randn(3, 2, 3, 3).astype(np.float32)
        check_grad(lambda x, w: F.conv2d(x, w, padding=1), [x, w], wrt=(0, 1),
                   eps=1e-2, rtol=1e-2, atol=2e-3)

    def test_pooling(self):
        x = jnp.arange(16.0).reshape(1, 1, 4, 4)
        y = F.max_pool2d(x, 2)
        np.testing.assert_allclose(np.asarray(y)[0, 0],
                                   [[5.0, 7.0], [13.0, 15.0]])
        y2 = F.avg_pool2d(x, 2)
        np.testing.assert_allclose(np.asarray(y2)[0, 0],
                                   [[2.5, 4.5], [10.5, 12.5]])

    def test_batch_norm_eval_matches_formula(self):
        x = np.random.randn(4, 3, 2, 2).astype(np.float32)
        rm = np.random.randn(3).astype(np.float32)
        rv = np.random.rand(3).astype(np.float32) + 0.5
        y, _, _ = F.batch_norm(jnp.asarray(x), jnp.asarray(rm), jnp.asarray(rv),
                               training=False)
        ref = (x - rm[None, :, None, None]) / np.sqrt(rv[None, :, None, None] + 1e-5)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_embedding_padding_idx(self):
        w = np.random.randn(10, 4).astype(np.float32)
        ids = np.array([[1, 0, 3]])
        out = F.embedding(jnp.asarray(ids), jnp.asarray(w), padding_idx=0)
        np.testing.assert_allclose(np.asarray(out)[0, 1], np.zeros(4))
        np.testing.assert_allclose(np.asarray(out)[0, 0], w[1])

    def test_attention_matches_reference(self):
        q = np.random.randn(2, 2, 4, 8).astype(np.float32)
        k = np.random.randn(2, 2, 4, 8).astype(np.float32)
        v = np.random.randn(2, 2, 4, 8).astype(np.float32)
        out = F.scaled_dot_product_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), training=False)
        # numpy reference
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(8)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_causal_softmax(self):
        x = np.random.randn(1, 1, 4, 4).astype(np.float32)
        out = np.asarray(F.softmax_mask_fuse_upper_triangle(jnp.asarray(x)))
        assert np.allclose(out[0, 0, 0, 1:], 0)
        np.testing.assert_allclose(out.sum(-1), np.ones((1, 1, 4)), rtol=1e-5)

    def test_activations_grad(self):
        x = np.random.randn(3, 4).astype(np.float32)
        x = x + 0.25 * np.sign(x)  # keep clear of the relu kink at 0
        for fn in [F.relu, F.gelu, F.silu, F.sigmoid, F.tanh, F.softplus]:
            check_grad(fn, [x], eps=1e-2, rtol=1e-2, atol=1e-3)

    def test_dropout_determinism_under_key_scope(self):
        x = jnp.ones((1000,))
        with pt.key_scope(jax.random.key(0)):
            a = F.dropout(x, 0.5)
        with pt.key_scope(jax.random.key(0)):
            b = F.dropout(x, 0.5)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        mean = float(jnp.mean(a))
        assert 0.8 < mean < 1.2  # upscale_in_train keeps expectation


class TestMultiHeadAttention:
    def test_shapes_and_cache(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = pt.randn((2, 5, 16))
        out = mha(x)
        assert out.shape == (2, 5, 16)
        # decode with kv cache
        mha.eval()
        k0 = jnp.zeros((2, 4, 0, 4))
        out, (k, v) = mha(x[:, :1], cache=(k0, k0))
        assert k.shape == (2, 4, 1, 4)


class TestTransformerEncoder:
    def test_forward(self):
        enc = nn.TransformerEncoder(
            lambda: nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0), 2)
        x = pt.randn((2, 6, 16))
        out = enc(x)
        assert out.shape == (2, 6, 16)


def test_pad_paddle_convention():
    x = jnp.ones((1, 2, 3, 3))
    y = F.pad(x, [1, 1, 2, 2])  # W by (1,1), H by (2,2)
    assert y.shape == (1, 2, 7, 5)
    # full-length pad list: first dim to last (reference
    # python/paddle/nn/functional/common.py:1176-1187)
    y2 = F.pad(jnp.ones((2, 2)), [0, 0, 1, 0])
    assert y2.shape == (2, 3)


def test_conv_initializer_fans():
    from paddle_tpu.nn.initializer import _fans
    fan_in, fan_out = _fans((64, 3, 3, 3))  # OIHW
    assert fan_in == 27 and fan_out == 576


class TestLayerMethodParity:
    """Reference Layer public-method contract (dygraph layers.py:Layer),
    round-5 completion: children/full_name/state-dict hooks/etc."""

    def test_reference_layer_methods_all_present(self):
        import ast
        import os
        ref = "/root/reference/python/paddle/fluid/dygraph/layers.py"
        if not os.path.exists(ref):
            pytest.skip("reference not present")
        tree = ast.parse(open(ref).read())
        names = [n.name for node in ast.walk(tree)
                 if isinstance(node, ast.ClassDef) and node.name == "Layer"
                 for n in node.body if isinstance(n, ast.FunctionDef)
                 and not n.name.startswith("_")]
        missing = [x for x in names if not hasattr(nn.Layer, x)]
        assert not missing, missing

    def test_children_and_full_name(self):
        m = nn.Sequential(nn.Linear(2, 3), nn.ReLU())
        kids = list(m.children())
        assert len(kids) == 2 and isinstance(kids[0], nn.Linear)
        assert dict(m.named_children())
        a, b = nn.Linear(2, 2), nn.Linear(2, 2)
        assert a.full_name() != b.full_name()
        assert a.full_name() == a.full_name()     # stable per instance

    def test_state_dict_hook_runs(self):
        m = nn.Linear(2, 3)
        calls = []
        m.register_state_dict_hook(lambda sd: calls.append(len(sd)) or sd)
        sd = m.state_dict()
        assert calls == [len(sd)]

    def test_sublayer_state_dict_hook_fires_and_is_removable(self):
        m = nn.Sequential(nn.Linear(2, 3), nn.ReLU())
        calls = []
        handle = list(m.children())[0].register_state_dict_hook(
            lambda sd: calls.append(1) or sd)
        m.state_dict()
        assert calls == [1]
        handle.remove()
        m.state_dict()
        assert calls == [1]

    def test_non_persistable_variable_excluded_from_state_dict(self):
        l = nn.Linear(2, 2)
        l.create_variable(persistable=False)
        assert not any(k.startswith("_var") for k in l.state_dict())
        assert any(k.startswith("_var") for k, _ in l.named_buffers())

    def test_backward_raises_with_recipe(self):
        with pytest.raises(RuntimeError, match="value_and_grad"):
            nn.Linear(2, 2).backward()


def test_strict_roundtrip_with_non_persistable_buffer():
    """Regression: strict set_state_dict demanded back buffers that
    state_dict (correctly) no longer saves."""
    l = nn.Linear(2, 2)
    l.create_variable(persistable=False)
    l.set_state_dict(l.state_dict())
