"""Persistent compile cache (ISSUE 13 / ROADMAP 5a): PTPU_COMPILE_CACHE_DIR
wiring and the cross-process warm-start guarantee."""
import os
import subprocess
import sys

import pytest

from paddle_tpu.observability import compilecache


def test_disabled_without_knob(monkeypatch):
    monkeypatch.delenv("PTPU_COMPILE_CACHE_DIR", raising=False)
    compilecache.reset_for_tests()
    assert compilecache.maybe_enable_persistent_cache() is None
    assert compilecache.persistent_cache_dir() is None


def test_enable_is_idempotent(tmp_path, monkeypatch):
    cdir = str(tmp_path / "cc")
    monkeypatch.setenv("PTPU_COMPILE_CACHE_DIR", cdir)
    compilecache.reset_for_tests()
    try:
        assert compilecache.maybe_enable_persistent_cache() == cdir
        assert os.path.isdir(cdir)
        # second call: same answer, no reconfiguration
        assert compilecache.maybe_enable_persistent_cache() == cdir
        assert compilecache.persistent_cache_dir() == cdir
        import jax
        assert jax.config.jax_compilation_cache_dir == cdir
    finally:
        compilecache.reset_for_tests()


_WORKLOAD = r"""
import os, sys, json
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax, jax.numpy as jnp
from paddle_tpu.observability import get_registry
from paddle_tpu.observability.compilecache import maybe_enable_persistent_cache
assert maybe_enable_persistent_cache() == os.environ["PTPU_COMPILE_CACHE_DIR"]

@jax.jit
def f(x, y):
    return jnp.tanh(x @ y) + x.sum()

@jax.jit
def g(x):
    return jnp.sort(x * 3.0)[::-1]

x = jnp.ones((16, 16)); v = jnp.arange(32.0)
f(x, x).block_until_ready()
g(v).block_until_ready()
reg = get_registry()
print(json.dumps({
    "hits": reg.counter("compile.persistent_cache_hits").value,
    "requests": reg.counter("compile.persistent_cache_requests").value,
}))
"""


@pytest.mark.slow
def test_warm_start_compiles_nothing(tmp_path):
    """The ROADMAP 5a contract: a second process with the same program
    shapes loads every executable from disk — persistent hits equal the
    cacheable compile requests and no XLA compilation runs fresh."""
    env = dict(os.environ, PTPU_COMPILE_CACHE_DIR=str(tmp_path / "cc"),
               JAX_PLATFORMS="cpu")
    env.pop("PTPU_METRICS_DIR", None)

    def run():
        out = subprocess.run([sys.executable, "-c", _WORKLOAD],
                             capture_output=True, text=True, timeout=300,
                             env=env)
        assert out.returncode == 0, out.stderr
        import json
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["hits"] == 0            # nothing cached yet
    assert cold["requests"] >= 2        # both functions went to the cache
    assert os.listdir(str(tmp_path / "cc"))  # executables persisted
    warm = run()
    assert warm["requests"] >= 2
    assert warm["hits"] == warm["requests"]  # 0 fresh compiles
