"""Test config: force an 8-device CPU mesh (the analog of the reference's
localhost multi-process distributed tests, SURVEY.md §4) in-process, BEFORE
any test touches a backend — see paddle_tpu.framework.vmesh for why env vars
don't work here."""
from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh

force_virtual_cpu_mesh(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# numeric-verification tests need exact fp32 matmuls (this XLA CPU build
# defaults to a bf16-ish fast path)
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as pt
    pt.seed(1234)
    np.random.seed(1234)
    yield


# -- quick tier: `pytest -m quick` runs a <90s cross-section of the suite
# (one file per doctrine row; see tests/README.md for recorded timings)
_QUICK_MODULES = {
    "test_auto_parallel",          # sharding annotations
    "test_fleet_strategy",         # strategy-driven composition
    "test_distribution_extended",  # distributions + datasets
    "test_checkpoint",             # save/load/reshard
    "test_optimizer",              # optimizer family
    "test_launch_multihost",       # 2-process cluster proof
    "test_api_spec",               # API drift guard
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "quick: fast cross-section tier (<90s; see README.md)")
    config.addinivalue_line(
        "markers", "slow: heavyweight tests, deselect with -m 'not slow'")
    config.addinivalue_line(
        "markers", "faults: fault-injection / resilience tests "
        "(tier-1 runs these; budget ~30s on JAX_PLATFORMS=cpu)")
    config.addinivalue_line(
        "markers", "telemetry: observability-layer tests (registry, "
        "tracing, sinks, aggregation; ci.sh runs this tier explicitly)")
    config.addinivalue_line(
        "markers", "serving: paged-KV serving engine tests (KV cache, "
        "scheduler, ragged decode; ci.sh runs this tier explicitly)")
    config.addinivalue_line(
        "markers", "kernels: Pallas kernel / fused-op parity tests "
        "(flash attention, fused block, fused CE; ci.sh runs this tier "
        "explicitly)")
    config.addinivalue_line(
        "markers", "comm: communication-subsystem tests (compressed "
        "collectives, error feedback, ZeRO-1 sharded optimizer; ci.sh "
        "runs this tier explicitly)")
    config.addinivalue_line(
        "markers", "integrity: state-integrity guard tests (tree "
        "fingerprint, desync attribution, replay audit, healing "
        "ladder, checkpoint digest round trip; ci.sh runs this tier "
        "explicitly)")
    config.addinivalue_line(
        "markers", "ptlint: static-analysis engine tests (pass "
        "fixtures, annotation grammar, baseline workflow, whole-repo "
        "smoke; ci.sh runs this tier explicitly)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__ in _QUICK_MODULES:
            item.add_marker(pytest.mark.quick)
