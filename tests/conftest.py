"""Test config: force an 8-device CPU mesh (the analog of the reference's
localhost multi-process distributed tests, SURVEY.md §4).

Env vars (JAX_PLATFORMS / XLA_FLAGS) are NOT reliable here: the driver's site
hook overrides them after the shell exports, so the forcing must happen
in-process via jax.config BEFORE the first backend touch.  Verified: this
yields ``cpu / 8 devices`` even when the default platform is a real TPU.
"""
import jax

try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except RuntimeError:  # backend already initialized by an earlier import
    from jax.extend import backend as _jex_backend
    _jex_backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
assert len(jax.devices()) >= 8 and jax.devices()[0].platform == "cpu", (
    f"tests need an 8-device CPU mesh; have {jax.devices()}")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# numeric-verification tests need exact fp32 matmuls (this XLA CPU build
# defaults to a bf16-ish fast path)
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as pt
    pt.seed(1234)
    np.random.seed(1234)
    yield
