"""Test config: force an 8-device CPU mesh (the analog of the reference's
localhost multi-process distributed tests, SURVEY.md §4) in-process, BEFORE
any test touches a backend — see paddle_tpu.framework.vmesh for why env vars
don't work here."""
from paddle_tpu.framework.vmesh import force_virtual_cpu_mesh

force_virtual_cpu_mesh(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# numeric-verification tests need exact fp32 matmuls (this XLA CPU build
# defaults to a bf16-ish fast path)
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as pt
    pt.seed(1234)
    np.random.seed(1234)
    yield
