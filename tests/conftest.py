"""Test config: force an 8-device CPU mesh (the analog of the reference's
localhost multi-process distributed tests, SURVEY.md §4) BEFORE jax import."""
import os

# explicit override, not setdefault: the driver env may set JAX_PLATFORMS=axon
# (real TPU) and the multi-device CPU mesh tests must still run on 8 virtual
# CPU devices.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# numeric-verification tests need exact fp32 matmuls (this XLA CPU build
# defaults to a bf16-ish fast path)
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as pt
    pt.seed(1234)
    np.random.seed(1234)
    yield
