"""Real-hardware smoke tests.

The suite's conftest forces an 8-device CPU mesh in-process, which routes the
Pallas kernels through interpret mode — so nothing in the main suite proves
the kernels lower on a real TPU (exactly the failure BENCH_r03 recorded).
These tests spawn a fresh subprocess (default platform = whatever the machine
has) and skip when no TPU is attached.
"""
import functools
import os
import subprocess
import sys

import pytest

_PROBE = "import jax; print(jax.devices()[0].platform)"


def _sub_env() -> dict:
    # keep the parent env intact (the TPU platform plugin rides PYTHONPATH
    # and JAX_PLATFORMS); just make the repo importable
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # TPU compiles are ~20-40s each; persist them across subprocess runs
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    return env


@functools.lru_cache(maxsize=1)
def _tpu_available() -> bool:
    # lazy (called from inside the tests, not at collection) so CPU-only
    # runs and deselections never pay the subprocess jax import
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE], env=_sub_env(),
            capture_output=True, text=True, timeout=120)
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and out.stdout.strip().endswith("tpu")


def _require_tpu() -> None:
    if not _tpu_available():
        pytest.skip("no TPU attached")

_FLASH_SCRIPT = r"""
import numpy as np, jax, jax.numpy as jnp
assert jax.devices()[0].platform == "tpu", jax.devices()
# the XLA reference otherwise runs fp32 matmuls via reduced-precision bf16
# passes on TPU, while the Pallas kernel's fp32 dots are exact
jax.config.update("jax_default_matmul_precision", "highest")
from paddle_tpu.ops.flash_attention import flash_attention
from paddle_tpu.nn import functional as F

rng = np.random.RandomState(0)
for dtype, tol in ((jnp.float32, 2e-5), (jnp.bfloat16, 2e-2)):
    q = jnp.asarray(rng.randn(2, 4, 256, 64), dtype)
    k = jnp.asarray(rng.randn(2, 4, 256, 64), dtype)
    v = jnp.asarray(rng.randn(2, 4, 256, 64), dtype)
    for causal in (True, False):
        out = flash_attention(q, k, v, causal=causal)
        ref = F.scaled_dot_product_attention(
            q, k, v, is_causal=causal, dropout_p=0.0, training=False)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        assert err <= tol, (dtype, causal, err)

        # backward compiles dominate wall-clock: check grads for one causal
        # setting per dtype (fwd numerics already cover both)
        if causal != (dtype is jnp.float32):
            continue

        def lf(q, k, v, _c=causal):
            return jnp.sum(flash_attention(q, k, v, causal=_c)
                           .astype(jnp.float32) ** 2)
        def lr(q, k, v, _c=causal):
            return jnp.sum(F.scaled_dot_product_attention(
                q, k, v, is_causal=_c, dropout_p=0.0, training=False)
                .astype(jnp.float32) ** 2)
        g = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            gerr = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                         - b.astype(jnp.float32))))
            scale = max(1.0, float(jnp.max(jnp.abs(b.astype(jnp.float32)))))
            # grads flow through the recompute-based backward kernels: one
            # extra rounding step vs forward, so give them 5x headroom
            assert gerr / scale <= 5 * tol, (dtype, causal, gerr, scale)
print("flash-hw-ok")
"""

_TRAIN_SCRIPT = r"""
import numpy as np, jax, jax.numpy as jnp
assert jax.devices()[0].platform == "tpu", jax.devices()
import paddle_tpu as pt
from paddle_tpu.framework import random as fw_random
from paddle_tpu.models import GPTForCausalLM, gpt_tiny

pt.seed(0)
model = GPTForCausalLM(gpt_tiny(max_position_embeddings=256))
model.train()
params = model.state_dict()
opt = pt.optimizer.AdamW(learning_rate=1e-3)
state = opt.init(params)
rng = np.random.RandomState(0)
ids = jnp.asarray(rng.randint(0, 1024, (2, 256)), jnp.int32)

def step(params, state, key):
    def loss_fn(p):
        with fw_random.key_scope(key):
            loss, _ = model.apply(p, ids, labels=ids)
        return loss
    loss, grads = jax.value_and_grad(loss_fn)(params)
    p2, s2 = opt.apply_gradients(grads, params, state)
    return loss, p2, s2

jitted = jax.jit(step)
key = jax.random.key(0)
losses = []
for i in range(5):
    loss, params, state = jitted(params, state, jax.random.fold_in(key, i))
    losses.append(float(loss))
assert all(np.isfinite(l) for l in losses), losses
assert losses[-1] < losses[0], losses
print("train-hw-ok", losses[0], losses[-1])
"""


def _run(script: str, tag: str, timeout: int = 560) -> None:
    out = subprocess.run([sys.executable, "-c", script], env=_sub_env(),
                         capture_output=True, text=True, timeout=timeout)
    assert out.returncode == 0, f"stdout:\n{out.stdout}\nstderr:\n{out.stderr}"
    assert tag in out.stdout, out.stdout


def test_flash_attention_on_tpu():
    """The Pallas kernel must lower via Mosaic and match XLA numerics on
    real hardware (regression: BENCH_r03 lse BlockSpec failure)."""
    _require_tpu()
    _run(_FLASH_SCRIPT, "flash-hw-ok")


def test_gpt_train_step_on_tpu():
    """Five optimizer steps of the flagship model on the chip: finite and
    decreasing loss through the auto-routed fused-attention path."""
    _require_tpu()
    _run(_TRAIN_SCRIPT, "train-hw-ok")


_FLASH_NEW_PATHS_SCRIPT = r"""
import numpy as np, jax, jax.numpy as jnp
assert jax.devices()[0].platform == "tpu", jax.devices()
jax.config.update("jax_default_matmul_precision", "highest")
from paddle_tpu.ops.flash_attention import (flash_attention,
                                            flash_attention_kvcache)
from paddle_tpu.nn import functional as F

rng = np.random.RandomState(0)

# 1. in-kernel dropout lowers via Mosaic: deterministic per seed, disjoint
#    across seeds, mean preserved within tolerance
q = jnp.asarray(rng.randn(1, 4, 256, 64) * 0.5, jnp.float32)
k = jnp.asarray(rng.randn(1, 4, 256, 64) * 0.5, jnp.float32)
v = jnp.asarray(rng.randn(1, 4, 256, 64) * 0.5, jnp.float32)
a = flash_attention(q, k, v, dropout_p=0.3, seed=7)
b = flash_attention(q, k, v, dropout_p=0.3, seed=7)
c = flash_attention(q, k, v, dropout_p=0.3, seed=8)
assert bool(jnp.array_equal(a, b))
assert not bool(jnp.allclose(a, c))
g = jax.grad(lambda q_: jnp.sum(flash_attention(
    q_, k, v, dropout_p=0.3, seed=7) ** 2))(q)
assert bool(jnp.isfinite(g).all())

# 2. ragged auto-padding on hardware
qr = jnp.asarray(rng.randn(1, 2, 100, 64) * 0.5, jnp.float32)
kr = jnp.asarray(rng.randn(1, 2, 200, 64) * 0.5, jnp.float32)
vr = jnp.asarray(rng.randn(1, 2, 200, 64) * 0.5, jnp.float32)
out = flash_attention(qr, kr, vr, causal=True)
ref = F.scaled_dot_product_attention(qr, kr, vr, is_causal=True,
                                     dropout_p=0.0, training=False)
err = float(jnp.max(jnp.abs(out - ref)))
assert err < 2e-4, err

# 3. kv-cache decode kernel with a traced length
kc = jnp.asarray(rng.randn(1, 2, 256, 64) * 0.5, jnp.float32)
vc = jnp.asarray(rng.randn(1, 2, 256, 64) * 0.5, jnp.float32)
qd = jnp.asarray(rng.randn(1, 2, 1, 64) * 0.5, jnp.float32)
dec = jax.jit(lambda qq, n: flash_attention_kvcache(qq, kc, vc, n))
for used in (64, 131, 256):
    got = dec(qd, jnp.asarray(used, jnp.int32))
    want = F.scaled_dot_product_attention(
        qd, kc[:, :, :used], vc[:, :, :used], is_causal=False,
        dropout_p=0.0, training=False)
    derr = float(jnp.max(jnp.abs(got - want)))
    assert derr < 2e-4, (used, derr)
print("flash-newpaths-hw-ok")
"""


def test_flash_new_paths_on_tpu():
    """Round-5 kernel additions (in-kernel dropout, ragged auto-pad,
    kv-cache decode) must lower via Mosaic on real hardware — the CPU mesh
    only exercises interpret mode."""
    _require_tpu()
    _run(_FLASH_NEW_PATHS_SCRIPT, "flash-newpaths-hw-ok")
