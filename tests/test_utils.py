"""paddle.utils tests: deprecated/try_import/unique_name/run_check and the
cpp_extension custom-op path (compile C++ at test time, call under jit)."""
import os
import textwrap
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.utils import cpp_extension, deprecated, try_import, unique_name


def test_deprecated_warns():
    @deprecated(update_to="paddle_tpu.new_api", since="0.1")
    def old_api(x):
        return x + 1

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old_api(1) == 2
    assert any("deprecated" in str(x.message) for x in w)


def test_try_import():
    assert try_import("math") is not None
    with pytest.raises(ImportError, match="definitely_not_a_module"):
        try_import("definitely_not_a_module")


def test_unique_name_generate_and_guard():
    with unique_name.guard():
        assert unique_name.generate("fc") == "fc_0"
        assert unique_name.generate("fc") == "fc_1"
        assert unique_name.generate("conv") == "conv_0"
        with unique_name.guard():
            assert unique_name.generate("fc") == "fc_0"  # fresh scope
        assert unique_name.generate("fc") == "fc_2"      # restored


def test_run_check():
    assert pt.utils.run_check()


@pytest.fixture(scope="module")
def softsign_lib(tmp_path_factory):
    src = tmp_path_factory.mktemp("ext") / "softsign.cc"
    src.write_text(textwrap.dedent("""
        #include <cstdint>
        #include <cmath>
        extern "C" void softsign(const float* in, float* out, int64_t n) {
            for (int64_t i = 0; i < n; ++i)
                out[i] = in[i] / (1.0f + std::fabs(in[i]));
        }
    """))
    return cpp_extension.load("softsign_test", [str(src)])


def test_cpp_extension_compiles_and_runs(softsign_lib):
    op = cpp_extension.custom_op(softsign_lib, "softsign")
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)
    got = np.asarray(op(x))
    want = np.asarray(x) / (1.0 + np.abs(np.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_cpp_extension_custom_op_under_jit(softsign_lib):
    """The host op participates in a jitted program via pure_callback —
    the reference's custom-op-in-graph registration analog."""
    op = cpp_extension.custom_op(softsign_lib, "softsign")

    @jax.jit
    def f(x):
        return jnp.sum(op(x * 2.0) + 1.0)

    x = jnp.asarray(np.random.RandomState(1).randn(16), jnp.float32)
    got = float(f(x))
    xx = np.asarray(x) * 2.0
    want = float(np.sum(xx / (1.0 + np.abs(xx)) + 1.0))
    assert abs(got - want) < 1e-4


def test_cpp_extension_build_cache(softsign_lib, tmp_path):
    """Same sources → same .so path (content-hash cache hit)."""
    d = cpp_extension.get_build_directory()
    before = {f for f in os.listdir(d) if f.startswith("softsign_test")}
    assert len(before) == 1
