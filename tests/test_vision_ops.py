"""paddle.vision.ops tests: analytic references (no torchvision in-image).

- roi_align on a linear feature map must reproduce the bin-center values
  exactly (bilinear interpolation of a linear function is exact);
- deform_conv2d with zero offsets must equal the plain convolution;
- nms against a hand-worked suppression example; yolo_box against a
  manual decode.
"""
import io

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.nn import functional as F
from paddle_tpu.vision import ops as V


class TestRoIAlign:
    def test_linear_feature_exact(self):
        # f(y, x) = 2y + 3x: bilinear sampling is exact, so each output
        # bin equals f at the mean of its sample points = bin center
        H = W = 16
        yy, xx = np.mgrid[0:H, 0:W].astype(np.float32)
        feat = (2 * yy + 3 * xx)[None, None]          # (1,1,H,W)
        box = np.asarray([[2.0, 2.0, 10.0, 10.0]], np.float32)
        out = np.asarray(V.roi_align(jnp.asarray(feat), jnp.asarray(box),
                                     [1], output_size=4, aligned=True))
        assert out.shape == (1, 1, 4, 4)
        # aligned=True: sampling coords are box*scale - 0.5
        x1 = y1 = 2.0 - 0.5
        bin_sz = 8.0 / 4
        for i in range(4):
            for j in range(4):
                cy = y1 + (i + 0.5) * bin_sz
                cx = x1 + (j + 0.5) * bin_sz
                np.testing.assert_allclose(out[0, 0, i, j], 2 * cy + 3 * cx,
                                           rtol=1e-5)

    def test_batching_by_boxes_num(self):
        x = jnp.asarray(np.random.RandomState(0).rand(2, 3, 8, 8),
                        jnp.float32)
        boxes = jnp.asarray([[0, 0, 4, 4], [1, 1, 6, 6], [2, 2, 7, 7]],
                            jnp.float32)
        out = V.roi_align(x, boxes, [1, 2], output_size=2)
        assert out.shape == (3, 3, 2, 2)
        # box 0 samples image 0; boxes 1-2 sample image 1
        out_swapped = V.roi_align(x[::-1], boxes, [2, 1], output_size=2)
        assert not np.allclose(np.asarray(out), np.asarray(out_swapped))


class TestRoIPool:
    def test_constant_regions(self):
        feat = np.zeros((1, 1, 8, 8), np.float32)
        feat[:, :, :4] = 1.0
        feat[:, :, 4:] = 5.0
        box = np.asarray([[0.0, 0.0, 7.0, 7.0]], np.float32)
        out = np.asarray(V.roi_pool(jnp.asarray(feat), jnp.asarray(box),
                                    [1], output_size=2))
        np.testing.assert_allclose(out[0, 0], [[1, 1], [5, 5]])

    def test_sharp_peak_not_missed(self):
        # a single-pixel max must be found wherever it sits in the bin —
        # the old fixed 4x4 sample grid could miss it entirely
        feat = np.zeros((1, 1, 16, 16), np.float32)
        feat[0, 0, 3, 5] = 100.0
        feat[0, 0, 11, 13] = 7.0
        box = np.asarray([[0.0, 0.0, 15.0, 15.0]], np.float32)
        out = np.asarray(V.roi_pool(jnp.asarray(feat), jnp.asarray(box),
                                    [1], output_size=2))
        np.testing.assert_allclose(out[0, 0], [[100, 0], [0, 7]])

    def test_every_pixel_position_found(self):
        # exhaustive: the max pixel is found at EVERY position of a bin
        R = np.random.RandomState(0)
        feat = R.rand(1, 2, 9, 9).astype(np.float32)  # non-divisible bins
        box = np.asarray([[0.0, 0.0, 8.0, 8.0]], np.float32)
        out = np.asarray(V.roi_pool(jnp.asarray(feat), jnp.asarray(box),
                                    [1], output_size=2))
        # bins: rows/cols 0..4 and 5..8 (rh=9, bin=4.5 → floor/ceil)
        f = feat[0]
        for c in range(2):
            want = [[f[c, 0:5, 0:5].max(), f[c, 0:5, 4:9].max()],
                    [f[c, 4:9, 0:5].max(), f[c, 4:9, 4:9].max()]]
            np.testing.assert_allclose(out[0, c], want, rtol=1e-6)

    def test_box_past_image_uses_unclipped_partition(self):
        # bins are laid out over the UNclipped RoI (reference semantics);
        # only each bin's pixel range is clipped to the image
        R = np.random.RandomState(1)
        feat = R.rand(1, 1, 8, 8).astype(np.float32)
        box = np.asarray([[0.0, 0.0, 13.0, 13.0]], np.float32)
        out = np.asarray(V.roi_pool(jnp.asarray(feat), jnp.asarray(box),
                                    [1], output_size=2))
        f = feat[0, 0]   # rh=14 → bin=7: rows [0,7) and [7,14)→clip→[7,8)
        want = [[f[0:7, 0:7].max(), f[0:7, 7:8].max()],
                [f[7:8, 0:7].max(), f[7:8, 7:8].max()]]
        np.testing.assert_allclose(out[0, 0], want, rtol=1e-6)
        # a fully out-of-image bin yields 0
        far = np.asarray([[0.0, 0.0, 31.0, 31.0]], np.float32)
        out2 = np.asarray(V.roi_pool(jnp.asarray(feat), jnp.asarray(far),
                                     [1], output_size=4))
        assert np.all(out2[0, 0, 2:, :] == 0) and np.all(out2[0, 0, :, 2:] == 0)

    def test_nan_propagates(self):
        feat = np.ones((1, 1, 8, 8), np.float32)
        feat[0, 0, 2, 2] = np.nan
        box = np.asarray([[0.0, 0.0, 7.0, 7.0]], np.float32)
        out = np.asarray(V.roi_pool(jnp.asarray(feat), jnp.asarray(box),
                                    [1], output_size=2))
        assert np.isnan(out[0, 0, 0, 0])

    def test_psroi_pool_selects_bin_groups(self):
        ph = pw = 2
        out_c = 3
        C = out_c * ph * pw
        # channel c*4 + i*2 + j is constant (c*100 + i*10 + j)
        feat = np.zeros((1, C, 8, 8), np.float32)
        for c in range(out_c):
            for i in range(ph):
                for j in range(pw):
                    feat[0, c * ph * pw + i * pw + j] = c * 100 + i * 10 + j
        box = np.asarray([[0.0, 0.0, 8.0, 8.0]], np.float32)
        out = np.asarray(V.psroi_pool(jnp.asarray(feat), jnp.asarray(box),
                                      [1], output_size=2))
        for c in range(out_c):
            for i in range(ph):
                for j in range(pw):
                    assert out[0, c, i, j] == c * 100 + i * 10 + j


class TestNMS:
    def test_greedy_suppression(self):
        boxes = jnp.asarray([[0, 0, 10, 10], [1, 1, 11, 11],
                             [20, 20, 30, 30], [21, 21, 29, 29]],
                            jnp.float32)
        scores = jnp.asarray([0.9, 0.8, 0.7, 0.95])
        keep = np.asarray(V.nms(boxes, 0.3, scores=scores))
        # box 3 beats box 2 (overlap), box 0 beats box 1
        assert set(keep.tolist()) == {0, 3}
        assert keep.tolist()[0] == 3  # score-descending order

    def test_multiclass_does_not_cross_suppress(self):
        boxes = jnp.asarray([[0, 0, 10, 10], [0, 0, 10, 10]], jnp.float32)
        scores = jnp.asarray([0.9, 0.8])
        cats = jnp.asarray([0, 1])
        keep = np.asarray(V.nms(boxes, 0.3, scores=scores,
                                category_idxs=cats, categories=[0, 1]))
        assert set(keep.tolist()) == {0, 1}

    def test_top_k_and_jittable_mask(self):
        boxes = jnp.asarray([[0, 0, 4, 4], [10, 10, 14, 14],
                             [20, 20, 24, 24]], jnp.float32)
        scores = jnp.asarray([0.5, 0.9, 0.7])
        keep = np.asarray(V.nms(boxes, 0.5, scores=scores, top_k=2))
        assert keep.tolist() == [1, 2]
        mask = jax.jit(lambda b, s: V.nms_mask(b, s, 0.5))(boxes, scores)
        assert np.asarray(mask).all()   # disjoint boxes all kept


class TestYoloBox:
    def test_decode_matches_manual(self):
        n, a, cls, h, w = 1, 2, 3, 4, 4
        rng = np.random.RandomState(0)
        x = rng.randn(n, a * (5 + cls), h, w).astype(np.float32)
        anchors = [10, 13, 16, 30]
        img = np.asarray([[128, 128]], np.int32)
        boxes, scores = V.yolo_box(jnp.asarray(x), jnp.asarray(img),
                                   anchors, cls, conf_thresh=0.0,
                                   downsample_ratio=32, clip_bbox=False)
        assert boxes.shape == (1, a * h * w, 4)
        assert scores.shape == (1, a * h * w, cls)
        # manual decode of anchor 0, cell (0, 0)
        f = x.reshape(n, a, 5 + cls, h, w)
        sig = lambda v: 1 / (1 + np.exp(-v))
        cx = sig(f[0, 0, 0, 0, 0]) / w
        cy = sig(f[0, 0, 1, 0, 0]) / h
        bw = np.exp(f[0, 0, 2, 0, 0]) * anchors[0] / (32 * w)
        bh = np.exp(f[0, 0, 3, 0, 0]) * anchors[1] / (32 * h)
        want = [(cx - bw / 2) * 128, (cy - bh / 2) * 128,
                (cx + bw / 2) * 128, (cy + bh / 2) * 128]
        np.testing.assert_allclose(np.asarray(boxes)[0, 0], want, rtol=1e-4)
        # conf = sigmoid(obj) * sigmoid(cls)
        want_s = sig(f[0, 0, 4, 0, 0]) * sig(f[0, 0, 5, 0, 0])
        np.testing.assert_allclose(np.asarray(scores)[0, 0, 0], want_s,
                                   rtol=1e-5)

    def test_iou_aware_decode(self):
        """iou_aware: leading A channels are IoU logits; conf =
        sigmoid(obj)^(1-f) * sigmoid(iou)^f (yolo_box_kernel.cc:80)."""
        n, a, cls, h, w = 1, 2, 2, 2, 2
        rng = np.random.RandomState(3)
        x = rng.randn(n, a * (6 + cls), h, w).astype(np.float32)
        boxes, scores = V.yolo_box(jnp.asarray(x), jnp.asarray([[64, 64]]),
                                   [10, 13, 16, 30], cls, conf_thresh=0.0,
                                   downsample_ratio=32, iou_aware=True,
                                   iou_aware_factor=0.4)
        sig = lambda v: 1 / (1 + np.exp(-v))
        iou0 = sig(x[0, 0, 0, 0])                   # anchor 0, cell (0,0)
        body = x[:, a:].reshape(n, a, 5 + cls, h, w)
        obj0 = sig(body[0, 0, 4, 0, 0])
        cls0 = sig(body[0, 0, 5, 0, 0])
        want = (obj0 ** 0.6) * (iou0 ** 0.4) * cls0
        np.testing.assert_allclose(np.asarray(scores)[0, 0, 0], want,
                                   rtol=1e-5)
        # wrong channel count raises loudly
        with pytest.raises(Exception, match="channels"):
            V.yolo_box(jnp.asarray(x), jnp.asarray([[64, 64]]),
                       [10, 13, 16, 30], cls, conf_thresh=0.0,
                       downsample_ratio=32)

    def test_conf_thresh_zeroes(self):
        x = np.full((1, 7, 2, 2), -10.0, np.float32)  # obj ~ 0
        boxes, scores = V.yolo_box(jnp.asarray(x), jnp.asarray([[64, 64]]),
                                   [10, 13], 2, conf_thresh=0.5,
                                   downsample_ratio=32)
        assert float(jnp.sum(jnp.abs(boxes))) == 0.0
        assert float(jnp.sum(scores)) == 0.0


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 3, 8, 8), jnp.float32)
        w = jnp.asarray(rng.randn(4, 3, 3, 3), jnp.float32)
        b = jnp.asarray(rng.randn(4), jnp.float32)
        offset = jnp.zeros((2, 2 * 9, 8, 8))
        out = V.deform_conv2d(x, offset, w, b, padding=1)
        ref = F.conv2d(x, w, b, padding=1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_mask_scales_contribution(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(1, 2, 6, 6), jnp.float32)
        w = jnp.asarray(rng.randn(3, 2, 3, 3), jnp.float32)
        offset = jnp.zeros((1, 18, 6, 6))
        full = V.deform_conv2d(x, offset, w, padding=1)
        half = V.deform_conv2d(x, offset, w, padding=1,
                               mask=jnp.full((1, 9, 6, 6), 0.5))
        np.testing.assert_allclose(np.asarray(half), 0.5 * np.asarray(full),
                                   rtol=1e-4, atol=1e-5)

    def test_integer_offset_shifts(self):
        """A constant (0, +1) x-offset equals convolving the x-shifted
        image (interior pixels)."""
        rng = np.random.RandomState(2)
        x = np.zeros((1, 1, 8, 8), np.float32)
        x[0, 0] = rng.randn(8, 8)
        w = jnp.asarray(rng.randn(1, 1, 3, 3), jnp.float32)
        offset = np.zeros((1, 18, 8, 8), np.float32)
        offset[0, 1::2] = 1.0    # dx = +1 for every tap
        out = V.deform_conv2d(jnp.asarray(x), jnp.asarray(offset), w,
                              padding=1)
        shifted = np.zeros_like(x)
        shifted[0, 0, :, :-1] = x[0, 0, :, 1:]
        ref = F.conv2d(jnp.asarray(shifted), w, padding=1)
        np.testing.assert_allclose(np.asarray(out)[0, 0, 1:-1, 1:-2],
                                   np.asarray(ref)[0, 0, 1:-1, 1:-2],
                                   rtol=1e-4, atol=1e-4)

    def test_deform_conv2d_layer(self):
        pt.seed(0)
        layer = V.DeformConv2D(3, 8, 3, padding=1)
        x = jnp.asarray(np.random.RandomState(0).randn(1, 3, 8, 8),
                        jnp.float32)
        offset = jnp.zeros((1, 18, 8, 8))
        assert layer(x, offset).shape == (1, 8, 8, 8)


class TestImageIO:
    def test_read_and_decode_jpeg(self, tmp_path):
        from PIL import Image
        # smooth gradient: JPEG-friendly, so the roundtrip stays tight
        yy, xx = np.mgrid[0:10, 0:12]
        arr = np.stack([yy * 20, xx * 20, yy * 10 + xx * 10],
                       axis=-1).astype(np.uint8)
        p = tmp_path / "img.jpg"
        Image.fromarray(arr).save(str(p), quality=95)
        raw = V.read_file(str(p))
        assert raw.dtype == jnp.uint8 and raw.ndim == 1
        img = V.decode_jpeg(raw, mode="rgb")
        assert img.shape == (3, 10, 12)
        # lossy but close
        diff = np.abs(np.asarray(img, np.int32)
                      - np.transpose(arr, (2, 0, 1)).astype(np.int32))
        assert diff.mean() < 30
