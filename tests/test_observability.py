"""Telemetry-layer tests (ISSUE 3): registry concurrency + histogram
bounds, span nesting + chrome-trace export, JSONL sink durability through
injected fsio faults, the cross-worker aggregator, the vlog flag cache,
and an e2e ``Model.fit`` run asserting step-breakdown + MFU records land
on the same timeline as supervisor events."""
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.observability import (Counter, Histogram, MetricsRegistry,
                                      MetricsWriter, PrometheusTextfile,
                                      StderrSummary)
from paddle_tpu.observability import aggregate as agg_mod
from paddle_tpu.observability import tracing
from paddle_tpu.utils import fsio

pytestmark = pytest.mark.telemetry


class _ListSink:
    def __init__(self):
        self.records = []
        self.flushed = 0

    def write(self, record):
        self.records.append(record)

    def flush(self):
        self.flushed += 1

    def close(self):
        self.flush()


class TestRegistry:
    def test_counter_gauge_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("c")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        assert reg.counter("c") is c          # same name → same instrument
        g = reg.gauge("g")
        assert g.value is None
        g.set(7)
        assert g.value == 7.0

    def test_type_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_counter_concurrency_exact(self):
        reg = MetricsRegistry()
        c = reg.counter("n")
        threads = [threading.Thread(
            target=lambda: [c.inc() for _ in range(5000)])
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 40000

    def test_histogram_exact_stats_bounded_reservoir(self):
        h = Histogram("h", max_samples=64, seed=0)
        for i in range(10000):
            h.observe(float(i))
        snap = h.snapshot()
        assert snap["count"] == 10000
        assert snap["sum"] == sum(range(10000))
        assert snap["min"] == 0.0 and snap["max"] == 9999.0
        assert len(h._samples) == 64          # bounded regardless of count
        # reservoir percentiles are estimates; order must still hold
        assert snap["p50"] <= snap["p90"] <= snap["p99"] <= snap["max"]
        assert h.percentile(0) >= 0.0

    def test_histogram_concurrency_count_exact(self):
        h = Histogram("h", max_samples=32)
        threads = [threading.Thread(
            target=lambda: [h.observe(1.0) for _ in range(2000)])
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count == 8000 and h.sum == 8000.0

    def test_counter_inc_overhead_under_a_microsecond(self):
        # acceptance: with no sink attached, counter increments must stay
        # hot-path cheap.  Budget 5 µs/call (measured ~0.25 µs) so a
        # loaded CI box can't flake the bound.
        c = MetricsRegistry().counter("hot")
        n = 100000
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, f"inc() cost {per_call * 1e6:.2f} µs/call"
        assert c.value == n

    def test_emit_no_sink_is_noop_and_fast(self):
        reg = MetricsRegistry()
        n = 50000
        t0 = time.perf_counter()
        for _ in range(n):
            reg.emit("step", step=1, loss=0.5)
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6

    def test_emit_fans_out_and_stamps_ts(self):
        reg = MetricsRegistry(clock=lambda: 123.0)
        sink = reg.add_sink(_ListSink())
        reg.emit("step", step=3, loss=0.5)
        reg.emit("custom", ts=99.0)
        assert sink.records[0] == {"ts": 123.0, "kind": "step", "step": 3,
                                   "loss": 0.5}
        assert sink.records[1]["ts"] == 99.0
        reg.remove_sink(sink)
        reg.emit("step", step=4)
        assert len(sink.records) == 2         # detached sinks see nothing

    def test_broken_sink_never_raises_and_peers_still_receive(self):
        reg = MetricsRegistry()

        class Broken:
            def write(self, record):
                raise RuntimeError("boom")

            def flush(self):
                raise RuntimeError("boom")

            def close(self):
                pass

        good = _ListSink()
        reg.add_sink(Broken())
        reg.add_sink(good)
        reg.emit("step", step=1)
        reg.flush()
        assert len(good.records) == 1

    def test_snapshot_shape(self):
        reg = MetricsRegistry()
        reg.counter("a").inc(2)
        reg.gauge("b").set(1.5)
        reg.histogram("c").observe(10.0)
        snap = reg.snapshot()
        assert snap["a"] == {"type": "counter", "value": 2.0}
        assert snap["b"]["value"] == 1.5
        assert snap["c"]["count"] == 1


class TestTracing:
    def setup_method(self):
        tracing.reset_tracing()

    def test_span_nesting_paths_and_self_time(self):
        with obs.span("step"):
            with obs.span("dispatch"):
                time.sleep(0.01)
            with obs.span("readback"):
                time.sleep(0.005)
        tree = obs.span_tree_totals()
        assert set(tree) == {"step", "step/dispatch", "step/readback"}
        step = tree["step"]
        assert step["count"] == 1
        # self time excludes the children
        child_total = (tree["step/dispatch"]["total_ms"]
                       + tree["step/readback"]["total_ms"])
        assert step["self_ms"] <= step["total_ms"] - child_total + 1.0
        assert tree["step/dispatch"]["total_ms"] >= 9.0

    def test_span_elapsed_exposed(self):
        with obs.span("x") as sp:
            time.sleep(0.002)
        assert sp.elapsed >= 0.002

    def test_same_leaf_under_different_parents_distinct(self):
        with obs.span("a"):
            with obs.span("io"):
                pass
        with obs.span("b"):
            with obs.span("io"):
                pass
        tree = obs.span_tree_totals()
        assert "a/io" in tree and "b/io" in tree

    def test_chrome_trace_export(self, tmp_path):
        with obs.span("outer"):
            with obs.span("inner"):
                time.sleep(0.002)
        path = str(tmp_path / "trace.json")
        n = obs.export_chrome_trace(path)
        assert n == 2
        doc = json.loads(fsio.read_bytes(path))
        events = doc["traceEvents"]
        by_name = {e["name"]: e for e in events}
        assert set(by_name) == {"outer", "outer/inner"}
        inner, outer = by_name["outer/inner"], by_name["outer"]
        for e in events:
            assert e["ph"] == "X" and e["dur"] > 0
        # the child interval sits inside the parent's
        assert inner["ts"] >= outer["ts"] - 1.0
        assert (inner["ts"] + inner["dur"]
                <= outer["ts"] + outer["dur"] + 1.0)

    def test_spans_feed_profiler_host_table_and_summary(self):
        from paddle_tpu.profiler import Profiler, profiler_summary
        profiler_summary(reset=True)
        with obs.span("step"):
            with obs.span("dispatch"):
                pass
        stats = profiler_summary()
        assert stats["step"][0] == 1
        assert stats["step/dispatch"][0] == 1
        text = Profiler(timer_only=True).summary()
        assert "step/dispatch" in text and "self ms" in text

    def test_reset(self):
        with obs.span("x"):
            pass
        tracing.reset_tracing()
        assert obs.span_tree_totals() == {}
        assert tracing.trace_events() == []


class TestMetricsWriter:
    def test_writes_jsonl(self, tmp_path):
        w = MetricsWriter(str(tmp_path), worker_id=3, flush_every=2)
        w.write({"ts": 1.0, "kind": "step", "step": 0})
        w.write({"ts": 2.0, "kind": "step", "step": 1})   # triggers flush
        w.write({"ts": 3.0, "kind": "step", "step": 2})
        w.close()                                          # flushes the tail
        path = tmp_path / "worker-3.jsonl"
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        assert [r["step"] for r in recs] == [0, 1, 2]
        assert w.written == 3 and w.dropped == 0

    def test_survives_injected_fsio_faults(self, tmp_path, monkeypatch):
        w = MetricsWriter(str(tmp_path), worker_id=0, flush_every=1)
        real_append = fsio.append_bytes
        fail = {"on": True}

        def flaky(path, payload):
            if fail["on"]:
                raise OSError("injected telemetry fault")
            real_append(path, payload)

        monkeypatch.setattr(fsio, "append_bytes", flaky)
        w.write({"kind": "step", "step": 0})   # flush fails, record kept
        w.write({"kind": "step", "step": 1})
        assert w.written == 0
        fail["on"] = False                      # fault clears
        w.write({"kind": "step", "step": 2})
        w.close()
        recs = [json.loads(l) for l in
                (tmp_path / "worker-0.jsonl").read_text().splitlines()]
        # nothing was lost across the fault window
        assert [r["step"] for r in recs] == [0, 1, 2]
        assert w.dropped == 0

    def test_wedged_stream_drops_oldest_and_counts(self, tmp_path,
                                                   monkeypatch):
        w = MetricsWriter(str(tmp_path), worker_id=0, flush_every=1,
                          max_buffered=5)

        def always_fail(path, payload):
            raise OSError("wedged")

        monkeypatch.setattr(fsio, "append_bytes", always_fail)
        for i in range(9):
            w.write({"kind": "step", "step": i})
        assert w.dropped == 4                   # 9 written, 5 retained
        assert len(w._buf) == 5
        assert json.loads(w._buf[0])["step"] == 4   # oldest dropped first


class TestSnapshotSinks:
    def test_stderr_summary_logs_line(self):
        reg = MetricsRegistry()
        reg.counter("supervisor.rollback").inc()
        s = reg.add_sink(StderrSummary(interval=0.0))
        reg.emit("step", step=5, step_time_ms=12.0, tokens_per_sec=100.0,
                 mfu=0.41)
        assert s.emitted >= 1
        assert s._last_step["step"] == 5

    def test_prometheus_textfile(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("step.count").inc(3)
        reg.gauge("step.mfu").set(0.45)
        reg.histogram("step.time_ms").observe(10.0)
        sink = reg.add_sink(PrometheusTextfile(
            str(tmp_path / "m.prom"), interval=0.0))
        reg.emit("step", step=0)
        text = (tmp_path / "m.prom").read_text()
        assert "# TYPE paddle_tpu_step_count counter" in text
        assert "paddle_tpu_step_count 3" in text
        assert "paddle_tpu_step_mfu 0.45" in text
        assert 'paddle_tpu_step_time_ms{quantile="0.5"} 10' in text
        assert "paddle_tpu_step_time_ms_count 1" in text


class TestMfuHelpers:
    def test_flops_per_token_matches_bench_formula(self):
        n, L, h, S = 125_000_000, 12, 768, 2048
        want = 6 * n + 12 * L * h * S // 2
        assert obs.flops_per_token(n, L, h, S, causal=True) == want
        assert obs.flops_per_token(n, L, h, S, causal=False) == \
            6 * n + 12 * L * h * S
        assert obs.flops_per_token(n) == 6 * n   # shapeless fallback

    def test_param_count_and_mfu(self):
        params = {"w": np.zeros((4, 8)), "b": np.zeros((8,))}
        assert obs.param_count(params) == 40
        assert obs.mfu(1000.0, 1e9, peak=1e13) == pytest.approx(1e-1)
        assert obs.peak_flops_per_sec() > 0   # CPU nominal fallback


class TestAggregate:
    def _write_worker(self, mdir, wid, records, torn_tail=False):
        lines = "".join(json.dumps(r) + "\n" for r in records)
        if torn_tail:
            lines += '{"ts": 9, "kind": "st'      # mid-append death
        os.makedirs(mdir, exist_ok=True)
        with open(os.path.join(mdir, f"worker-{wid}.jsonl"), "w") as f:
            f.write(lines)

    def test_merges_workers_and_skips_torn_lines(self, tmp_path):
        run_dir = str(tmp_path)
        mdir = obs.metrics_dir(run_dir)
        self._write_worker(mdir, 0, [
            {"ts": 1.0, "kind": "supervisor.run_start"},
            {"ts": 2.0, "kind": "step", "step": 0, "step_time_ms": 10.0,
             "tokens": 64, "tokens_per_sec": 6400.0, "mfu": 0.2},
            {"ts": 3.0, "kind": "step", "step": 1, "step_time_ms": 30.0,
             "tokens": 64, "tokens_per_sec": 2133.0, "mfu": 0.1},
        ], torn_tail=True)
        self._write_worker(mdir, 1, [
            {"ts": 2.5, "kind": "step", "step": 0, "step_time_ms": 20.0,
             "tokens": 64, "tokens_per_sec": 3200.0, "mfu": 0.3},
        ])
        summary = obs.aggregate_run(run_dir)
        assert summary["workers"] == [0, 1]
        assert summary["records"] == 4            # torn line skipped
        assert summary["kinds"]["step"] == 3
        assert summary["supervisor_events"] == {
            "supervisor.run_start": 1}
        assert summary["overall"]["steps"] == 3
        assert summary["overall"]["total_tokens"] == 192.0
        assert summary["overall"]["step_time_ms"]["min"] == 10.0
        assert summary["overall"]["step_time_ms"]["max"] == 30.0
        assert summary["overall"]["mfu"]["max"] == 0.3
        assert summary["per_worker"]["1"]["steps"] == 1
        assert summary["time_range"] == [1.0, 3.0]
        on_disk = json.loads(
            (tmp_path / "metrics" / "summary.json").read_text())
        assert on_disk["records"] == 4

    def test_no_metrics_dir_returns_none(self, tmp_path):
        assert obs.aggregate_run(str(tmp_path / "nope")) is None

    def test_cli_main(self, tmp_path, capsys):
        mdir = obs.metrics_dir(str(tmp_path))
        self._write_worker(mdir, 0, [{"ts": 1.0, "kind": "step",
                                      "step": 0}])
        assert agg_mod.main([str(tmp_path)]) == 0
        assert json.loads(capsys.readouterr().out)["records"] == 1
        assert agg_mod.main([str(tmp_path / "missing")]) == 1


class TestVlogFlagCache:
    def test_cache_invalidated_by_set_flags(self):
        from paddle_tpu.framework import flags as fl
        from paddle_tpu.framework import log as fw_log
        base = fl.get_flags(["log_level"])["log_level"]
        calls = []
        orig_info = fw_log.get_logger().info
        try:
            fw_log.get_logger().info = lambda msg, *a: calls.append(msg)
            fw_log.vlog(3, "hidden")           # level 0: suppressed
            assert calls == []
            pt.set_flags({"log_level": 3})     # invalidates the cache
            fw_log.vlog(3, "shown")
            assert calls == ["shown"]
            pt.set_flags({"log_level": base})
            fw_log.vlog(3, "hidden again")
            assert calls == ["shown"]
        finally:
            fw_log.get_logger().info = orig_info
            pt.set_flags({"log_level": base})

    def test_disabled_vlog_is_cheap(self):
        from paddle_tpu.framework.log import vlog
        n = 50000
        t0 = time.perf_counter()
        for _ in range(n):
            vlog(9, "never shown %d", 1)
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, f"vlog cost {per_call * 1e6:.2f} µs/call"


class TestFsioAppend:
    def test_append_bytes(self, tmp_path):
        p = str(tmp_path / "a.jsonl")
        fsio.append_bytes(p, b"one\n")
        fsio.append_bytes(p, b"two\n")
        assert fsio.read_bytes(p) == b"one\ntwo\n"


class TestCollectiveInstrumentation:
    def test_barrier_records_latency(self):
        import paddle_tpu.distributed as dist
        reg = obs.get_registry()
        before = reg.counter("collective.barrier.calls").value
        dist.barrier()
        assert reg.counter("collective.barrier.calls").value == before + 1
        assert reg.histogram("collective.barrier.ms").count >= 1


def _tiny_model():
    net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                           pt.nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-3),
                  loss=pt.nn.CrossEntropyLoss())
    return model


def _tiny_data(n=32):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 8).astype("float32")
    y = rng.randint(0, 4, (n,)).astype("int64")
    return list(zip(x, y))


class TestFitTelemetryE2E:
    def test_fit_emits_step_breakdown_and_mfu(self, tmp_path):
        reg = obs.get_registry()
        sink = reg.add_sink(_ListSink())
        try:
            _tiny_model().fit(_tiny_data(), batch_size=8, epochs=1,
                              verbose=0)
        finally:
            reg.remove_sink(sink)
        steps = [r for r in sink.records if r["kind"] == "step"]
        assert len(steps) == 4
        for r in steps:
            for key in ("ts", "step", "step_time_ms", "data_ms",
                        "compute_ms", "readback_ms", "tokens",
                        "tokens_per_sec", "mfu", "loss"):
                assert key in r, f"step record missing {key}"
            assert r["step_time_ms"] >= r["data_ms"]
            assert r["tokens"] == 8
            assert r["tokens_per_sec"] > 0
            assert 0.0 <= r["mfu"] < 1.0
        # instruments accumulated alongside the event stream
        assert reg.counter("step.count").value >= 4
        assert reg.histogram("step.time_ms").count >= 4
        assert reg.gauge("step.mfu").value is not None

    def test_fit_with_supervisor_single_timeline(self, tmp_path):
        """The acceptance-criteria drill: a supervised CPU fit leaves
        <run_dir>/metrics/worker-0.jsonl whose one stream holds per-step
        breakdown records AND supervisor events."""
        from paddle_tpu.supervisor import RunSupervisor
        run_dir = str(tmp_path / "run")
        sup = RunSupervisor(run_dir, watchdog_secs=60.0, worker_id=0)
        _tiny_model().fit(_tiny_data(), batch_size=8, epochs=1, verbose=0,
                          supervisor=sup)
        path = os.path.join(run_dir, "metrics", "worker-0.jsonl")
        assert os.path.exists(path)
        recs = [json.loads(l) for l in open(path)]
        kinds = {r["kind"] for r in recs}
        assert "step" in kinds
        assert "supervisor.run_start" in kinds
        assert "supervisor.run_end" in kinds
        steps = [r for r in recs if r["kind"] == "step"]
        assert all("step_time_ms" in r and "mfu" in r
                   and "tokens_per_sec" in r for r in steps)
        # the stream is one ordered timeline: run_start precedes the
        # first step record, run_end follows the last
        ordered = [r["kind"] for r in recs]
        assert ordered.index("supervisor.run_start") \
            < ordered.index("step")
        assert ordered.index("supervisor.run_end") \
            > len(ordered) - 1 - ordered[::-1].index("step")
        # and the launcher-side aggregator reads it back
        summary = obs.aggregate_run(run_dir)
        assert summary["overall"]["steps"] == len(steps)
        assert summary["supervisor_events"]["supervisor.run_start"] == 1
