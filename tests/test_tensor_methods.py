"""paddle.Tensor method-surface tests: the reference monkey-patches its
method corpus onto Tensor (python/paddle/tensor/__init__.py); here the
same idioms are installed on jax arrays AND tracers — both paths pinned."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt


@pytest.mark.quick
class TestTensorMethods:
    def test_host_methods(self):
        x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert isinstance(x.numpy(), np.ndarray)
        assert x.cpu().shape == (2, 2)
        assert x.numel() == 4
        assert x.dim() == 2 and x.ndimension() == 2

    def test_math_methods_match_functions(self):
        x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        np.testing.assert_allclose(np.asarray(x.add(x)),
                                   np.asarray(pt.add(x, x)))
        np.testing.assert_allclose(np.asarray(x.multiply(x)),
                                   np.asarray(x) ** 2)
        np.testing.assert_allclose(np.asarray(x.matmul(x)),
                                   np.asarray(x) @ np.asarray(x))
        np.testing.assert_allclose(np.asarray(x.sigmoid()),
                                   1 / (1 + np.exp(-np.asarray(x))),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(x.rsqrt()),
                                   1 / np.sqrt(np.asarray(x)), rtol=1e-6)
        assert bool(x.greater_than(pt.zeros([2, 2])).all())

    def test_t_reference_contract(self):
        v = pt.to_tensor([1.0, 2.0])
        np.testing.assert_allclose(np.asarray(v.t()), np.asarray(v))
        m = pt.to_tensor(np.arange(6.0).reshape(2, 3))
        assert m.t().shape == (3, 2)
        with pytest.raises(ValueError, match="rank"):
            pt.to_tensor(np.zeros((2, 2, 2))).t()

    def test_norm_delegates_to_functional(self):
        x = pt.to_tensor(np.arange(24.0).reshape(2, 3, 4))
        np.testing.assert_allclose(np.asarray(x.norm()),
                                   np.asarray(pt.norm(x)), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(x.norm(p=2, axis=1, keepdim=True)),
            np.asarray(pt.norm(x, p=2, axis=1, keepdim=True)), rtol=1e-6)

    def test_shape_methods(self):
        x = pt.to_tensor(np.arange(6.0).reshape(2, 3))
        assert x.unsqueeze(0).shape == (1, 2, 3)
        assert x.t().shape == (3, 2)
        assert x.expand([4, 2, 3]).shape == (4, 2, 3)
        assert x.tile([2, 1]).shape == (4, 3)
        np.testing.assert_allclose(
            np.asarray(x.gather([1], axis=1)).ravel(), [1.0, 4.0])
        assert str(x.cast("int64").dtype) in ("int64", "int32")

    def test_detach_stops_gradient(self):
        g = jax.grad(lambda t: jnp.sum(t.detach() * t))(
            pt.to_tensor([2.0, 3.0]))
        np.testing.assert_allclose(np.asarray(g), [2.0, 3.0])

    def test_methods_work_under_jit(self):
        x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])

        @jax.jit
        def f(t):
            return t.add(t).tanh().matmul(t.t()).unsqueeze(0).norm()

        assert float(f(x)) > 0

    def test_jax_native_methods_not_overridden(self):
        from paddle_tpu.framework.tensor_methods import _METHODS
        x = pt.to_tensor([1.0, 2.0])
        # native jax methods keep native semantics
        assert x.reshape(2, 1).shape == (2, 1)      # jax-style varargs OK
        assert float(x.sum()) == 3.0
        # nothing in our table shadows something jax already had
        assert "reshape" not in _METHODS and "sum" not in _METHODS

    def test_numpy_raises_under_jit(self):
        x = pt.to_tensor([1.0])

        @jax.jit
        def f(t):
            return t.numpy()

        with pytest.raises((jax.errors.TracerArrayConversionError,
                            jax.errors.ConcretizationTypeError)):
            f(x)
