"""paddle.Tensor method-surface tests: the reference monkey-patches its
method corpus onto Tensor (python/paddle/tensor/__init__.py); here the
same idioms are installed on jax arrays AND tracers — both paths pinned."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt


@pytest.mark.quick
class TestTensorMethods:
    def test_host_methods(self):
        x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert isinstance(x.numpy(), np.ndarray)
        assert x.cpu().shape == (2, 2)
        assert x.numel() == 4
        assert x.dim() == 2 and x.ndimension() == 2

    def test_math_methods_match_functions(self):
        x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        np.testing.assert_allclose(np.asarray(x.add(x)),
                                   np.asarray(pt.add(x, x)))
        np.testing.assert_allclose(np.asarray(x.multiply(x)),
                                   np.asarray(x) ** 2)
        np.testing.assert_allclose(np.asarray(x.matmul(x)),
                                   np.asarray(x) @ np.asarray(x))
        np.testing.assert_allclose(np.asarray(x.sigmoid()),
                                   1 / (1 + np.exp(-np.asarray(x))),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(x.rsqrt()),
                                   1 / np.sqrt(np.asarray(x)), rtol=1e-6)
        assert bool(x.greater_than(pt.zeros([2, 2])).all())

    def test_t_reference_contract(self):
        v = pt.to_tensor([1.0, 2.0])
        np.testing.assert_allclose(np.asarray(v.t()), np.asarray(v))
        m = pt.to_tensor(np.arange(6.0).reshape(2, 3))
        assert m.t().shape == (3, 2)
        with pytest.raises(ValueError, match="rank"):
            pt.to_tensor(np.zeros((2, 2, 2))).t()

    def test_norm_delegates_to_functional(self):
        x = pt.to_tensor(np.arange(24.0).reshape(2, 3, 4))
        np.testing.assert_allclose(np.asarray(x.norm()),
                                   np.asarray(pt.norm(x)), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(x.norm(p=2, axis=1, keepdim=True)),
            np.asarray(pt.norm(x, p=2, axis=1, keepdim=True)), rtol=1e-6)

    def test_shape_methods(self):
        x = pt.to_tensor(np.arange(6.0).reshape(2, 3))
        assert x.unsqueeze(0).shape == (1, 2, 3)
        assert x.t().shape == (3, 2)
        assert x.expand([4, 2, 3]).shape == (4, 2, 3)
        assert x.tile([2, 1]).shape == (4, 3)
        np.testing.assert_allclose(
            np.asarray(x.gather([1], axis=1)).ravel(), [1.0, 4.0])
        assert str(x.cast("int64").dtype) in ("int64", "int32")

    def test_detach_stops_gradient(self):
        g = jax.grad(lambda t: jnp.sum(t.detach() * t))(
            pt.to_tensor([2.0, 3.0]))
        np.testing.assert_allclose(np.asarray(g), [2.0, 3.0])

    def test_methods_work_under_jit(self):
        x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])

        @jax.jit
        def f(t):
            return t.add(t).tanh().matmul(t.t()).unsqueeze(0).norm()

        assert float(f(x)) > 0

    def test_jax_native_methods_not_overridden(self):
        from paddle_tpu.framework.tensor_methods import _METHODS
        x = pt.to_tensor([1.0, 2.0])
        # native jax methods keep native semantics
        assert x.reshape(2, 1).shape == (2, 1)      # jax-style varargs OK
        assert float(x.sum()) == 3.0
        # nothing in our table shadows something jax already had
        assert "reshape" not in _METHODS and "sum" not in _METHODS

    def test_numpy_raises_under_jit(self):
        x = pt.to_tensor([1.0])

        @jax.jit
        def f(t):
            return t.numpy()

        with pytest.raises((jax.errors.TracerArrayConversionError,
                            jax.errors.ConcretizationTypeError)):
            f(x)


@pytest.mark.quick
def test_full_reference_method_contract():
    """Every name in the reference's tensor_method_func list (the exact
    monkey-patch corpus, python/paddle/tensor/__init__.py) is callable
    as a method here."""
    import ast
    import os
    ref = "/root/reference/python/paddle/tensor/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference not present")
    tree = ast.parse(open(ref).read())
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tg in node.targets:
                if isinstance(tg, ast.Name) and tg.id == "tensor_method_func":
                    names = [ast.literal_eval(e) for e in node.value.elts]
    assert len(names) > 200
    x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    missing = [n for n in names if not hasattr(x, n)]
    assert not missing, missing
    # two tiers, by design (module docstring): names WE installed take
    # paddle-shaped arguments; names jax already had keep jax signatures
    # (x.sum(keepdims=...) not keepdim= — documented in MIGRATION.md).
    from paddle_tpu.framework.tensor_methods import INSTALLED_METHODS
    assert len(INSTALLED_METHODS) > 150
    # every installed delegate is callable with a tensor receiver
    import inspect
    for n in ("logsumexp", "flip", "topk", "cholesky", "mv", "lerp"):
        assert n in INSTALLED_METHODS
        assert callable(getattr(x, n))


@pytest.mark.quick
def test_delegated_method_semantics_spot_checks():
    x = pt.to_tensor([[4.0, 0.0], [0.0, 9.0]])
    np.testing.assert_allclose(np.asarray(x.cholesky()), [[2, 0], [0, 3]])
    np.testing.assert_allclose(np.asarray(x.inverse()),
                               [[0.25, 0], [0, 1 / 9]], rtol=1e-6)
    v = pt.to_tensor([1.0, 2.0])
    np.testing.assert_allclose(np.asarray(x.mv(v)), [4.0, 18.0])
    np.testing.assert_allclose(float(v.logsumexp()),
                               np.log(np.exp([1, 2]).sum()), rtol=1e-6)
    # uniform_ fills SELF's shape (not the creation-op signature)
    u = x.uniform_(min=0.0, max=1.0)
    assert u.shape == x.shape and float(np.asarray(u).max()) <= 1.0
    # inplace-alias spelling returns the result (immutable arrays)
    np.testing.assert_allclose(float(pt.to_tensor([2.0]).sqrt_()[0]),
                               2 ** 0.5, rtol=1e-6)
    vals, idx = x.topk(1)
    assert vals.shape == (2, 1)
    # where: condition-method form
    c = pt.to_tensor([[True, False], [False, True]])
    np.testing.assert_allclose(
        np.asarray(c.where(pt.ones([2, 2]), pt.zeros([2, 2]))),
        [[1, 0], [0, 1]])
