"""PP + ZeRO tests (reference invariants: hybrid_parallel_pp_transformer.py,
dygraph_sharding_stage2/3.py — parallel == serial numerics)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.pipeline import (
    gpipe_spmd, merge_microbatches, split_microbatches, stack_stage_params,
    pipeline_stage_specs)
from paddle_tpu.distributed.sharding import (
    group_sharded_parallel, shard_optimizer_state, shard_spec_for_leaf)

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    dist.set_hybrid_communicate_group(None)


def _toy_stack(n_layers=8, width=16, seed=0):
    r = np.random.RandomState(seed)
    ws = jnp.asarray(r.randn(n_layers, width, width) * 0.3, jnp.float32)
    bs = jnp.asarray(r.randn(n_layers, width) * 0.1, jnp.float32)
    return {"w": ws, "b": bs}


def _serial_apply(params, x):
    n = params["w"].shape[0]
    for i in range(n):
        x = jnp.tanh(x @ params["w"][i] + params["b"][i])
    return x


def _stage_fn(stage_params, x):
    # one stage = its chunk of layers, scanned
    def layer(x, wb):
        w, b = wb
        return jnp.tanh(x @ w + b), None
    out, _ = jax.lax.scan(layer, x, (stage_params["w"], stage_params["b"]))
    return out


def _to_stages(params, num_stages):
    n = params["w"].shape[0]
    per = n // num_stages
    return {k: v.reshape(num_stages, per, *v.shape[1:])
            for k, v in params.items()}


class TestGPipeSchedule:
    def test_matches_serial_no_mesh(self):
        params = _toy_stack()
        x = jnp.asarray(np.random.RandomState(1).randn(8, 4, 16), jnp.float32)
        out = gpipe_spmd(_stage_fn, _to_stages(params, 4), x, remat=False)
        ref = jax.vmap(lambda mb: _serial_apply(params, mb))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_matches_serial_on_pp_mesh_jit(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "pp_degree": 4,
                                   "mp_degree": 1}
        fleet.init(strategy=strategy)
        mesh = fleet.get_mesh()
        params = _toy_stack()
        stages = _to_stages(params, 4)
        stages = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
                  for k, v in stages.items()}
        x = jnp.asarray(np.random.RandomState(2).randn(8, 4, 16), jnp.float32)
        xs = jax.device_put(x, NamedSharding(mesh, P(None, "dp")))

        f = jax.jit(lambda sp, mb: gpipe_spmd(_stage_fn, sp, mb))
        out = f(stages, xs)
        ref = jax.vmap(lambda mb: _serial_apply(params, mb))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_grads_match_serial(self):
        params = _toy_stack(n_layers=4)
        x = jnp.asarray(np.random.RandomState(3).randn(4, 2, 16), jnp.float32)

        def loss_pipe(stages):
            out = gpipe_spmd(_stage_fn, stages, x)
            return jnp.mean(out ** 2)

        def loss_serial(params):
            out = jax.vmap(lambda mb: _serial_apply(params, mb))(x)
            return jnp.mean(out ** 2)

        g_pipe = jax.grad(loss_pipe)(_to_stages(params, 2))
        g_ser = jax.grad(loss_serial)(params)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(g_pipe[k]).reshape(g_ser[k].shape),
                np.asarray(g_ser[k]), rtol=2e-5, atol=1e-6)

    def test_microbatch_split_merge_roundtrip(self):
        x = jnp.arange(24.0).reshape(8, 3)
        mb = split_microbatches(x, 4)
        assert mb.shape == (4, 2, 3)
        np.testing.assert_allclose(np.asarray(merge_microbatches(mb)),
                                   np.asarray(x))


class TestStackStageParams:
    def test_gpt_layer_stacking(self):
        pt.seed(0)
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        m = GPTForCausalLM(gpt_tiny())
        params = m.state_dict()
        stacked, rest = stack_stage_params(
            params, r"gpt\.h\.(\d+)\.(.*)", num_stages=2)
        assert "attn.qkv_proj.weight" in stacked
        s = stacked["attn.qkv_proj.weight"]
        assert s.shape[0] == 2 and s.shape[1] == 1  # 2 layers → 2 stages
        np.testing.assert_allclose(
            np.asarray(s[0, 0]), np.asarray(params["gpt.h.0.attn.qkv_proj.weight"]))
        assert "gpt.wte.weight" in rest and "gpt.ln_f.weight" in rest


class TestZeroSharding:
    def test_shard_spec_for_leaf(self):
        leaf = jnp.zeros((64, 16))
        assert shard_spec_for_leaf(leaf, None, "dp", 8) == P("dp", None)
        # first dim taken by mp → dp goes to dim 1
        assert shard_spec_for_leaf(leaf, P("mp", None), "dp", 8) == \
            P("mp", "dp")
        # nothing divisible → replicated (None)
        assert shard_spec_for_leaf(jnp.zeros((3, 5)), None, "dp", 8) is None

    def test_optimizer_state_sharded_and_numerics_equal(self):
        import paddle_tpu.nn as nn
        pt.seed(5)
        model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
        opt = pt.optimizer.AdamW(learning_rate=1e-2, weight_decay=0.01)
        params = model.state_dict()
        x = jnp.asarray(np.random.RandomState(0).randn(16, 16), jnp.float32)
        y = jnp.asarray(np.random.RandomState(1).randn(16, 16), jnp.float32)

        def step(params, state, xx, yy):
            def loss_fn(p):
                return jnp.mean((model.apply(p, xx) - yy) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            newp, state = opt.apply_gradients(grads, params, state)
            return loss, newp, state

        state_s = opt.init(params)
        loss_s, params_s, _ = step(params, state_s, x, y)

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8}
        fleet.init(strategy=strategy)
        mesh = fleet.get_mesh()
        fleet.distributed_model(model)
        params_d = model.state_dict()
        state_d = shard_optimizer_state(opt.init(params_d),
                                        params_layer=model)
        # slots really sharded over dp
        m1 = state_d["slots"]["0.weight"]["moment1"]
        assert "dp" in (m1.sharding.spec[0],)
        xs = dist.shard_batch(x); ys = dist.shard_batch(y)
        loss_p, params_p, state_p = jax.jit(step)(params_d, state_d, xs, ys)
        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=1e-6)
        for k in params_s:
            np.testing.assert_allclose(np.asarray(params_p[k]),
                                       np.asarray(params_s[k]),
                                       rtol=3e-5, atol=3e-6)

    def test_group_sharded_parallel_facade(self):
        import paddle_tpu.nn as nn
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8}
        fleet.init(strategy=strategy)
        pt.seed(6)
        model = nn.Linear(16, 64)
        opt = pt.optimizer.Adam(learning_rate=1e-3)
        model, opt, _ = group_sharded_parallel(model, opt, level="os")
        state = opt.init(model.state_dict())
        spec = state["slots"]["weight"]["moment1"].sharding.spec
        assert "dp" in spec
