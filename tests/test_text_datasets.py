"""Text dataset tests: schema, determinism, learnability through the
DataLoader (the reference's dataset tests check schema + first-item
values; synthetic data replaces golden values here)."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.io import DataLoader
from paddle_tpu.text import (Conll05st, Imdb, Imikolov, Movielens,
                             UCIHousing)


def test_schemas_and_determinism():
    imdb = Imdb(mode="train", synthetic_size=64)
    doc, label = imdb[0]
    assert doc.dtype == np.int64 and label in (0, 1)
    imdb2 = Imdb(mode="train", synthetic_size=64)
    np.testing.assert_array_equal(imdb[3][0], imdb2[3][0])

    ngram = Imikolov(window_size=5, synthetic_size=32)
    assert ngram[0].shape == (5,)

    words, pred, labels = Conll05st(synthetic_size=16)[0]
    assert words.shape == labels.shape and pred.ndim == 0

    u, age, job, m, cat, r = Movielens(synthetic_size=16)[0]
    assert 1.0 <= r <= 5.0

    x, y = UCIHousing(mode="train", synthetic_size=32)[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_uci_housing_trains_linear_regression():
    ds = UCIHousing(mode="train")
    loader = DataLoader(ds, batch_size=64, shuffle=True)
    pt.seed(0)
    model = nn.Linear(13, 1)
    params = model.state_dict()
    opt = pt.optimizer.Adam(learning_rate=5e-2)
    state = opt.init(params)

    @jax.jit
    def step(p, s, x, y):
        def lf(q):
            return jnp.mean((model.apply(q, x) - y) ** 2)
        loss, g = jax.value_and_grad(lf)(p)
        return (loss, *opt.apply_gradients(g, p, s))

    first = last = None
    for epoch in range(12):
        for x, y in loader:
            loss, params, state = step(params, state, jnp.asarray(x),
                                       jnp.asarray(y))
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < 0.3 * first


def test_imdb_trains_bow_classifier():
    ds = Imdb(mode="train", synthetic_size=512)
    loader = DataLoader(ds, batch_size=64, shuffle=True)
    pt.seed(1)
    emb = nn.Embedding(5000, 16)
    head = nn.Linear(16, 2)
    params = {"emb": emb.state_dict(), "head": head.state_dict()}
    opt = pt.optimizer.Adam(learning_rate=5e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s, ids, y):
        def lf(q):
            pooled = jnp.mean(emb.apply(q["emb"], ids), axis=1)
            logits = head.apply(q["head"], pooled)
            return nn.functional.cross_entropy(logits, y)
        loss, g = jax.value_and_grad(lf)(p)
        return (loss, *opt.apply_gradients(g, p, s))

    first = last = None
    for epoch in range(4):
        for ids, y in loader:
            loss, params, state = step(params, state, jnp.asarray(ids),
                                       jnp.asarray(y))
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < 0.5 * first


def test_wmt_schema_and_dicts():
    from paddle_tpu.text import WMT14, WMT16

    d = WMT14(mode="train", dict_size=200, synthetic_size=32)
    src, trg, trg_next = d[0]
    # reference wmt14.py:162-163: trg is <s>-prefixed, trg_next </e>-suffixed
    assert trg[0] == 0 and trg_next[-1] == 1
    np.testing.assert_array_equal(trg[1:], trg_next[:-1])
    assert len(trg) == len(src) + 1
    src_dict, trg_dict = d.get_dict()
    assert src_dict["<unk>"] == 2 and len(trg_dict) == 200
    assert d.get_dict(reverse=True)[0][0] == "<s>"
    # determinism + disjoint splits
    d2 = WMT14(mode="train", dict_size=200, synthetic_size=32)
    np.testing.assert_array_equal(d[5][0], d2[5][0])
    dt = WMT14(mode="test", dict_size=200, synthetic_size=32)
    assert not (len(d[0][0]) == len(dt[0][0])
                and np.array_equal(d[0][0], dt[0][0]))

    w = WMT16(mode="val", src_dict_size=150, trg_dict_size=180, lang="en",
              synthetic_size=16)
    src, trg, trg_next = w[3]
    assert trg[0] == 0 and trg_next[-1] == 1
    assert len(w.get_dict("en")) == 150 and len(w.get_dict("de")) == 180

    # the synthetic "translation" is a fixed dict permutation: the same
    # source token always maps to the same target token (learnable task)
    mapping = {}
    for i in range(len(d)):
        s, _, tn = d[i]
        for a, b in zip(s, tn[:-1]):
            assert mapping.setdefault(int(a), int(b)) == int(b)


def test_movielens_record_types():
    from paddle_tpu.text import MovieInfo, UserInfo

    u = UserInfo(7, "F", 35, 11)
    assert u.value() == [[7], [1], [3], [11]]
    m = MovieInfo(2, ["action", "war"], "Saving Private Ryan")
    cats = {"action": 0, "war": 1}
    titles = {"saving": 10, "private": 11, "ryan": 12}
    assert m.value(cats, titles) == [[2], [0, 1], [10, 11, 12]]
    assert "MovieInfo" in str(m) and "UserInfo" in str(u)


def test_wmt_translation_mapping_shared_across_splits():
    """Regression: each split used to draw its own permutation, making
    train and test DIFFERENT translation tasks — a model trained on one
    could never decode the other."""
    from paddle_tpu.text import WMT14

    def mapping(ds):
        m = {}
        for i in range(len(ds)):
            s, _, tn = ds[i]
            for a, b in zip(s, tn[:-1]):
                m.setdefault(int(a), int(b))
        return m

    tr = mapping(WMT14(mode="train", dict_size=40, synthetic_size=128))
    ge = mapping(WMT14(mode="gen", dict_size=40, synthetic_size=128))
    shared = set(tr) & set(ge)
    assert len(shared) > 10
    assert all(tr[k] == ge[k] for k in shared)
