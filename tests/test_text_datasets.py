"""Text dataset tests: schema, determinism, learnability through the
DataLoader (the reference's dataset tests check schema + first-item
values; synthetic data replaces golden values here)."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.io import DataLoader
from paddle_tpu.text import (Conll05st, Imdb, Imikolov, Movielens,
                             UCIHousing)


def test_schemas_and_determinism():
    imdb = Imdb(mode="train", synthetic_size=64)
    doc, label = imdb[0]
    assert doc.dtype == np.int64 and label in (0, 1)
    imdb2 = Imdb(mode="train", synthetic_size=64)
    np.testing.assert_array_equal(imdb[3][0], imdb2[3][0])

    ngram = Imikolov(window_size=5, synthetic_size=32)
    assert ngram[0].shape == (5,)

    words, pred, labels = Conll05st(synthetic_size=16)[0]
    assert words.shape == labels.shape and pred.ndim == 0

    u, age, job, m, cat, r = Movielens(synthetic_size=16)[0]
    assert 1.0 <= r <= 5.0

    x, y = UCIHousing(mode="train", synthetic_size=32)[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_uci_housing_trains_linear_regression():
    ds = UCIHousing(mode="train")
    loader = DataLoader(ds, batch_size=64, shuffle=True)
    pt.seed(0)
    model = nn.Linear(13, 1)
    params = model.state_dict()
    opt = pt.optimizer.Adam(learning_rate=5e-2)
    state = opt.init(params)

    @jax.jit
    def step(p, s, x, y):
        def lf(q):
            return jnp.mean((model.apply(q, x) - y) ** 2)
        loss, g = jax.value_and_grad(lf)(p)
        return (loss, *opt.apply_gradients(g, p, s))

    first = last = None
    for epoch in range(12):
        for x, y in loader:
            loss, params, state = step(params, state, jnp.asarray(x),
                                       jnp.asarray(y))
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < 0.3 * first


def test_imdb_trains_bow_classifier():
    ds = Imdb(mode="train", synthetic_size=512)
    loader = DataLoader(ds, batch_size=64, shuffle=True)
    pt.seed(1)
    emb = nn.Embedding(5000, 16)
    head = nn.Linear(16, 2)
    params = {"emb": emb.state_dict(), "head": head.state_dict()}
    opt = pt.optimizer.Adam(learning_rate=5e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s, ids, y):
        def lf(q):
            pooled = jnp.mean(emb.apply(q["emb"], ids), axis=1)
            logits = head.apply(q["head"], pooled)
            return nn.functional.cross_entropy(logits, y)
        loss, g = jax.value_and_grad(lf)(p)
        return (loss, *opt.apply_gradients(g, p, s))

    first = last = None
    for epoch in range(4):
        for ids, y in loader:
            loss, params, state = step(params, state, jnp.asarray(ids),
                                       jnp.asarray(y))
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < 0.5 * first
