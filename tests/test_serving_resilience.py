"""Serving resilience (ISSUE 15): request-lifecycle guard (deadlines,
cancellation), poisoned-request quarantine with batch bisection,
watchdog-supervised steps, graceful drain/resume, collect timeouts,
callback-error accounting, KV-block leak-freedom, and the doctor /
healthz surfaces."""
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.inference import CollectTimeout, ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability import doctor
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.testing import faults

pytestmark = [pytest.mark.serving, pytest.mark.faults]


def tiny_model(max_pos=32):
    pt.seed(7)
    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=2,
                    num_heads=2, ffn_hidden_size=64,
                    max_position_embeddings=max_pos, hidden_dropout=0.0,
                    attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def dense_continuation(model, prompt, max_new, eos=None):
    out = model.generate(jnp.asarray([prompt], jnp.int32),
                         max_new_tokens=max_new, temperature=0.0,
                         eos_token_id=eos)
    return np.asarray(out)[0, len(prompt):].tolist()


def make_engine(model=None, **kw):
    model = model or tiny_model()
    kw.setdefault("registry", MetricsRegistry())
    return ServingEngine(model, **kw)


# ---------------------------------------------------------------------------
# deadlines & cancellation
# ---------------------------------------------------------------------------
class TestLifecycleGuard:
    def test_deadline_eviction(self):
        clk = faults.expire_clock()
        eng = make_engine(max_seqs=4, kv_block_size=4, clock=clk)
        doomed = eng.submit([1, 2, 3], max_new_tokens=20,
                            deadline_ms=50.0)
        healthy = eng.submit([4, 5], max_new_tokens=4)
        eng.step()                      # some progress before expiry
        clk.advance(1.0)                # way past 50ms
        eng.run(max_steps=100)
        out = eng.collect(doomed)
        assert out["finish_reason"] == "deadline"
        assert eng.collect(healthy)["finish_reason"] == "max_new_tokens"
        assert eng.cache.allocator.num_used == 0
        st = eng.stats()["resilience"]
        assert st["deadline_misses"] == 1 and st["cancelled"] == 0
        reg = eng._reg().snapshot()
        assert reg["serve.deadline_misses"]["value"] == 1

    def test_ttft_deadline_only_hits_before_first_token(self):
        clk = faults.expire_clock()
        eng = make_engine(max_seqs=2, kv_block_size=4, clock=clk)
        # queued behind nothing: first token arrives on step 1, so a
        # ttft deadline passed AFTER that must not evict
        rid = eng.submit([1, 2, 3], max_new_tokens=4,
                         ttft_deadline_ms=100.0)
        eng.step()                      # prefill → first token
        clk.advance(10.0)
        eng.run(max_steps=50)
        assert eng.collect(rid)["finish_reason"] == "max_new_tokens"

    def test_ttft_deadline_expires_while_queued(self):
        clk = faults.expire_clock()
        # max_seqs=1: the second submit waits behind the first
        eng = make_engine(max_seqs=1, kv_block_size=4, clock=clk)
        eng.submit([1, 2, 3], max_new_tokens=20)
        queued = eng.submit([4, 5, 6], max_new_tokens=4,
                            ttft_deadline_ms=50.0)
        eng.step()
        clk.advance(1.0)
        eng.run(max_steps=200)
        out = eng.collect(queued)
        assert out["finish_reason"] == "deadline"
        assert out["tokens"] == []      # never started

    def test_cancel_running_and_waiting(self):
        eng = make_engine(max_seqs=1, kv_block_size=4)
        running = eng.submit([1, 2, 3], max_new_tokens=20)
        waiting = eng.submit([4, 5], max_new_tokens=4)
        eng.step()
        assert eng.cancel(running) and eng.cancel(waiting)
        assert not eng.cancel("no-such-request")
        eng.run(max_steps=50)
        assert eng.collect(running)["finish_reason"] == "cancelled"
        assert eng.collect(waiting)["finish_reason"] == "cancelled"
        assert eng.cache.allocator.num_used == 0
        assert eng.stats()["resilience"]["cancelled"] == 2
        assert not eng.cancel(running)  # already finished

    def test_terminal_reason_reaches_callback(self):
        events = []
        eng = make_engine(max_seqs=2, kv_block_size=4)
        rid = eng.submit([1, 2, 3], max_new_tokens=20,
                         on_token=lambda r, t, fin: events.append(
                             (r, t, fin)))
        eng.step()
        eng.cancel(rid)
        eng.run(max_steps=50)
        assert eng.drain_callbacks(timeout=5.0)
        assert events[-1] == (rid, None, True)

    def test_env_default_deadline(self, monkeypatch):
        monkeypatch.setenv("PTPU_SERVE_DEADLINE_MS", "50")
        clk = faults.expire_clock()
        eng = make_engine(max_seqs=2, kv_block_size=4, clock=clk)
        rid = eng.submit([1, 2, 3], max_new_tokens=20)
        eng.step()
        clk.advance(1.0)
        eng.run(max_steps=100)
        assert eng.collect(rid)["finish_reason"] == "deadline"


# ---------------------------------------------------------------------------
# poisoned-request quarantine
# ---------------------------------------------------------------------------
class TestQuarantine:
    def _traffic(self, model, n=4, max_new=6, **kw):
        eng = make_engine(model, max_seqs=n, kv_block_size=4, **kw)
        prompts = [[1 + i, 2, 3 + i] for i in range(n)]
        rids = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        eng.run(max_steps=500)
        return eng, rids, [eng.collect(r)["tokens"] for r in rids]

    def test_decode_raise_bisects_to_culprit(self, tmp_path):
        model = tiny_model()
        _, _, clean = self._traffic(model)
        injector = faults.poison_request(2, mode="raise",
                                         kinds=("decode",))
        eng, rids, outs = self._traffic(model, step_fault=injector,
                                        run_dir=str(tmp_path))
        assert injector.fired > 1       # bisection probes re-fired it
        bad = eng._submit_order[2]
        assert list(eng.quarantined) == [bad]
        assert eng.sched.finished[bad].finish_reason == "poisoned"
        # peers token-exact vs the clean run
        for i in (0, 1, 3):
            assert outs[i] == clean[i], (i, outs[i], clean[i])
        # durable record
        qdir = tmp_path / "serve" / "replica-0" / "quarantine"
        files = os.listdir(qdir)
        assert len(files) == 1
        rec = json.loads((qdir / files[0]).read_text())
        assert rec["request_id"] == bad
        assert rec["reason"] == "poisoned"
        assert rec["step_kind"] == "decode"
        assert "injected poisoned step" in rec["error"]
        assert eng.cache.allocator.num_used == 0

    def test_prefill_raise_quarantines_immediately(self, tmp_path):
        model = tiny_model()
        injector = faults.poison_request(1, mode="raise",
                                         kinds=("prefill",))
        eng, rids, outs = self._traffic(model, step_fault=injector,
                                        run_dir=str(tmp_path))
        bad = eng._submit_order[1]
        assert eng.sched.finished[bad].finish_reason == "poisoned"
        assert eng.quarantined[bad]["step_kind"] == "prefill"
        assert eng.collect(rids[1])["tokens"] == []

    def test_nan_guard_names_culprit_without_bisection(self, tmp_path):
        model = tiny_model()
        _, _, clean = self._traffic(model)
        injector = faults.poison_request(0, mode="nan",
                                         kinds=("decode",))
        eng, rids, outs = self._traffic(model, step_fault=injector,
                                        nan_guard=True,
                                        run_dir=str(tmp_path))
        bad = eng._submit_order[0]
        assert list(eng.quarantined) == [bad]
        assert "nonfinite" in eng.quarantined[bad]["error"]
        for i in (1, 2, 3):
            assert outs[i] == clean[i]

    def test_nan_without_guard_flows_through(self):
        # guard off: NaN logits do NOT fault the step — argmax still
        # returns a token (garbage-tolerant, the pre-ISSUE-15 behavior)
        model = tiny_model()
        injector = faults.poison_request(0, mode="nan",
                                         kinds=("decode",), count=1)
        eng, rids, outs = self._traffic(model, step_fault=injector,
                                        nan_guard=False)
        assert not eng.quarantined
        assert all(len(t) > 0 for t in outs)

    def test_quarantine_counters_and_timeline(self, tmp_path):
        model = tiny_model()
        injector = faults.poison_request(2, mode="raise",
                                         kinds=("decode",))
        eng, _, _ = self._traffic(model, step_fault=injector,
                                  run_dir=str(tmp_path))
        snap = eng._reg().snapshot()
        assert snap["serve.poisoned"]["value"] == 1
        assert eng.stats()["resilience"]["poisoned"] == 1
        assert eng.stats()["resilience"]["quarantined"] == \
            [eng._submit_order[2]]


# ---------------------------------------------------------------------------
# watchdog supervision
# ---------------------------------------------------------------------------
class TestWatchdogRecovery:
    # step_timeout must cover a COLD compile (the watchdog cannot tell
    # XLA compiling from a wedged device) — these tests warm the shape
    # set under a generous timeout, then tighten it for the hang drill;
    # the post-recovery rebuild re-traces but hits jax's backend compile
    # cache, so the tight timeout only has to cover tracing.

    def test_hung_step_recovers_token_exact(self):
        model = tiny_model()
        prompt = [2, 3, 4]
        want = dense_continuation(model, prompt, 6)
        injector = faults.poison_request(1, mode="hang", seconds=30.0,
                                         kinds=("decode",), count=1)
        eng = make_engine(model, max_seqs=2, kv_block_size=4,
                          step_timeout=120.0, step_fault=injector)
        try:
            eng.submit([1, 2, 3], max_new_tokens=6)   # warm (index 0)
            eng.run(max_steps=100)
            eng.step_timeout = 2.0
            rid = eng.submit(prompt, max_new_tokens=6)  # target (index 1)
            eng.run(max_steps=200)
            assert eng.watchdog_restarts == 1
            assert injector.fired == 1
            out = eng.collect(rid)
            # recompute-prefill re-admission: same tokens as a clean run
            assert out["tokens"] == want
            assert out["preemptions"] >= 1
            assert eng.stats()["resilience"]["watchdog_restarts"] == 1
        finally:
            eng.stop()

    def test_jitted_fns_rebuilt_after_hang(self):
        model = tiny_model()
        injector = faults.poison_request(1, mode="hang", seconds=30.0,
                                         kinds=("decode",), count=1)
        eng = make_engine(model, max_seqs=2, kv_block_size=4,
                          step_timeout=120.0, step_fault=injector)
        try:
            eng.submit([1, 2, 3], max_new_tokens=3)   # warm (index 0)
            eng.run(max_steps=100)
            eng.step_timeout = 2.0
            eng.submit([2, 3, 4], max_new_tokens=3)   # target (index 1)
            eng.step()                   # prefill
            assert eng._decode_tracked is not None
            eng.step()                   # decode hangs → recovery
            assert eng._decode_tracked is None
            assert eng._prefill_tracked == {}
            eng.run(max_steps=100)
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# graceful drain / resume
# ---------------------------------------------------------------------------
class TestDrainResume:
    def test_drain_finishes_running_spills_waiting(self, tmp_path):
        model = tiny_model()
        eng = make_engine(model, max_seqs=2, kv_block_size=4,
                          run_dir=str(tmp_path))
        rids = [eng.submit([1 + i, 2, 3], max_new_tokens=4)
                for i in range(6)]
        eng.step(); eng.step()
        report = eng.drain(timeout=30.0)
        assert eng.state == "stopped"
        assert not report["timed_out"]
        assert report["spilled"] > 0
        assert report["finished"] + report["spilled"] == 6 \
            or report["finished"] >= 2  # running set finished at minimum
        for r in rids:
            assert r in eng.sched.finished
        spilled_rids = [r for r in rids
                        if eng.sched.finished[r].finish_reason
                        == "spilled"]
        assert len(spilled_rids) == report["spilled"]
        assert eng.cache.allocator.num_used == 0
        # the spill file is a fresh engine's intake
        payload = json.loads(
            open(report["spill_path"]).read())  # noqa: fsio — test-side read
        assert payload["version"] == 1
        assert {r["request_id"] for r in payload["spilled"]} \
            == set(spilled_rids)

    def test_resume_continues_token_exact(self, tmp_path):
        model = tiny_model()
        prompts = {f"r{i}": [1 + i, 2, 3] for i in range(4)}
        want = {rid: dense_continuation(model, p, 6)
                for rid, p in prompts.items()}
        eng = make_engine(model, max_seqs=1, kv_block_size=4,
                          run_dir=str(tmp_path))
        for rid, p in prompts.items():
            eng.submit(p, max_new_tokens=6, request_id=rid)
        eng.step(); eng.step(); eng.step()   # partial progress
        report = eng.drain(timeout=30.0)
        finished = {r: eng.sched.finished[r].output
                    for r in prompts if
                    eng.sched.finished[r].finish_reason != "spilled"}
        fresh = make_engine(model, max_seqs=1, kv_block_size=4)
        resumed = fresh.resume(report["spill_path"])
        assert set(resumed) | set(finished) == set(prompts)
        fresh.run(max_steps=500)
        for rid in resumed:
            out = fresh.collect(rid)
            assert out["tokens"] == want[rid], (rid, out["tokens"],
                                               want[rid])
        for rid, toks in finished.items():
            assert toks == want[rid]

    def test_submit_refused_after_drain_begins(self, tmp_path):
        eng = make_engine(max_seqs=2, kv_block_size=4,
                          run_dir=str(tmp_path))
        eng.submit([1, 2], max_new_tokens=2)
        eng.begin_drain()
        assert eng.state == "draining"
        with pytest.raises(Exception, match="draining"):
            eng.submit([3, 4], max_new_tokens=2)
        eng.drain(timeout=30.0)
        with pytest.raises(Exception, match="stopped"):
            eng.submit([3, 4], max_new_tokens=2)

    def test_drain_timeout_spills_running(self, tmp_path):
        model = tiny_model()
        eng = make_engine(model, max_seqs=2, kv_block_size=4,
                          run_dir=str(tmp_path))
        eng.submit([1, 2, 3], max_new_tokens=20)
        eng.step()                         # admit → running mid-decode
        report = eng.drain(timeout=0.0)    # no time to finish anything
        assert report["timed_out"]
        assert report["spilled"] == 1
        assert eng.cache.allocator.num_used == 0

    def test_resume_rejects_bad_version(self, tmp_path):
        spill = tmp_path / "serve_spill.json"
        spill.write_text(json.dumps({"version": 99, "spilled": []}))
        eng = make_engine(max_seqs=2, kv_block_size=4)
        with pytest.raises(Exception, match="version"):
            eng.resume(str(spill))

    def test_spill_lands_in_replica_namespace(self, tmp_path):
        # ISSUE 16: per-replica artifact namespacing — default spill
        # path is <run_dir>/serve/replica-<i>/spill.json
        eng = make_engine(max_seqs=2, kv_block_size=4,
                          run_dir=str(tmp_path), replica_id=3)
        eng.submit([1, 2, 3], max_new_tokens=20)
        eng.step()
        report = eng.drain(timeout=0.0)
        assert report["spilled"] == 1
        assert report["spill_path"] == str(
            tmp_path / "serve" / "replica-3" / "spill.json")
        assert report["spilled_records"][0]["request_id"] \
            == eng._submit_order[0]

    def test_resume_reads_legacy_spill_path(self, tmp_path):
        # pre-ISSUE-16 run dirs keep <run_dir>/serve_spill.json — a
        # fresh engine with only run_dir must still find and resume it
        model = tiny_model()
        want = dense_continuation(model, [1, 2, 3], 6)
        eng = make_engine(model, max_seqs=2, kv_block_size=4)
        eng.submit([1, 2, 3], max_new_tokens=6, request_id="legacy")
        eng.step(); eng.step()
        legacy = tmp_path / "serve_spill.json"
        eng.drain(timeout=0.0, spill_path=str(legacy))
        assert legacy.exists()
        fresh = make_engine(model, max_seqs=2, kv_block_size=4,
                            run_dir=str(tmp_path))
        assert fresh.resume() == ["legacy"]
        fresh.run(max_steps=200)
        assert fresh.collect("legacy")["tokens"] == want


# ---------------------------------------------------------------------------
# collect timeout / stuck-run diagnostics
# ---------------------------------------------------------------------------
class TestCollectTimeout:
    def test_collect_timeout_names_scheduler_state(self):
        eng = make_engine(max_seqs=1, kv_block_size=4)
        eng.submit([1, 2, 3], max_new_tokens=20)
        queued = eng.submit([4, 5], max_new_tokens=2)
        eng.step()
        eng.begin_drain()           # queued can never be admitted now
        with pytest.raises(CollectTimeout) as ei:
            eng.collect(queued, timeout=0.3)
        msg = str(ei.value)
        assert queued in msg and "queue_position" in msg

    def test_run_names_stuck_requests(self):
        eng = make_engine(max_seqs=1, kv_block_size=4)
        stuck = eng.submit([1, 2, 3], max_new_tokens=20)
        with pytest.raises(RuntimeError, match=stuck):
            eng.run(max_steps=2)


# ---------------------------------------------------------------------------
# callback-error accounting
# ---------------------------------------------------------------------------
class TestCallbackErrors:
    def test_consumer_exception_counted_not_fatal(self):
        eng = make_engine(max_seqs=2, kv_block_size=4)

        def bad_cb(rid, token, finished):
            raise ValueError("consumer bug")

        rid = eng.submit([1, 2, 3], max_new_tokens=3, on_token=bad_cb)
        eng.run(max_steps=50)
        assert eng.drain_callbacks(timeout=5.0)
        assert eng.collect(rid)["finish_reason"] == "max_new_tokens"
        st = eng.stats()["resilience"]["callbacks"]
        assert st["errors"] == 3 and st["dispatched"] == 3
        assert "consumer bug" in st["last_error"]
        snap = eng._reg().snapshot()
        assert snap["serve.callback_errors"]["value"] == 3
        eng.stop()

    def test_stop_terminates_callback_thread(self):
        eng = make_engine(max_seqs=2, kv_block_size=4)
        eng.submit([1, 2], max_new_tokens=2,
                   on_token=lambda *a: None)
        eng.run(max_steps=50)
        assert eng.drain_callbacks(timeout=5.0)
        thread = eng._cb_thread
        assert thread is not None and thread.is_alive()
        eng.stop()
        assert eng._cb_thread is None
        assert not thread.is_alive()


# ---------------------------------------------------------------------------
# KV-block leak freedom (property-style)
# ---------------------------------------------------------------------------
class TestLeakFreedom:
    def test_any_interleaving_returns_to_baseline(self, tmp_path):
        """Finish / cancel / deadline-evict / preempt / quarantine, all
        interleaved on a tight pool across several rounds — occupancy
        must return exactly to baseline with balanced alloc/free
        ledgers every round."""
        model = tiny_model()
        clk = faults.expire_clock()
        rng = np.random.RandomState(3)
        for round_idx in range(4):
            injector = faults.poison_request(
                int(rng.randint(0, 6)), mode="raise", kinds=("decode",))
            # tight pool: 10 blocks of 4 for up to 6 seqs forces
            # preemption churn alongside the evictions
            eng = make_engine(model, max_seqs=4, kv_block_size=4,
                              num_kv_blocks=10, clock=clk,
                              step_fault=injector,
                              run_dir=str(tmp_path / str(round_idx)))
            assert eng.cache.allocator.num_used == 0
            rids = []
            for i in range(6):
                kw = {}
                if i == 1:
                    kw["deadline_ms"] = 50.0
                rids.append(eng.submit(
                    [1 + i, 2, 3, 4], max_new_tokens=int(
                        rng.randint(2, 8)), **kw))
            for s in range(40):
                if s == 3:
                    eng.cancel(rids[int(rng.randint(0, 6))])
                if s == 5:
                    clk.advance(1.0)    # expire rids[1] (if still live)
                eng.step()
                if not eng.has_work():
                    break
            eng.run(max_steps=500)
            stats = eng.cache.allocator.stats()
            assert stats["num_used"] == 0, eng.cache.leak_report()
            assert stats["balanced"], stats
            report = eng.cache.leak_report()
            assert report["leaked_blocks"] == 0
            assert report["tabled_blocks"] == 0
            for r in rids:
                assert r in eng.sched.finished


# ---------------------------------------------------------------------------
# observability surfaces: /healthz, /statusz, doctor
# ---------------------------------------------------------------------------
class TestSurfaces:
    def test_healthz_draining_then_stopped(self):
        from paddle_tpu.observability.monitor import StatusServer
        eng = make_engine(max_seqs=2, kv_block_size=4)
        srv = StatusServer(registry=eng._registry, engine=eng)
        code, state = srv.healthz()
        assert code == 200
        eng.begin_drain()
        code, state = srv.healthz()
        assert (code, state) == (503, "draining")
        eng.drain(timeout=10.0)
        code, state = srv.healthz()
        assert (code, state) == (503, "stopped")

    def test_statusz_resilience_section(self):
        from paddle_tpu.observability.monitor import StatusServer
        eng = make_engine(max_seqs=2, kv_block_size=4)
        rid = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.cancel(rid)
        eng.run(max_steps=50)
        srv = StatusServer(registry=eng._registry, engine=eng)
        res = srv.statusz()["serving"]["resilience"]
        assert res["cancelled"] == 1
        assert res["state"] == "serving"
        assert res["callbacks"]["errors"] == 0

    def test_doctor_check_serving(self):
        workers = {0: [
            {"kind": "serve.quarantine", "request_id": "req-7",
             "step_kind": "decode", "error": "RuntimeError('boom')"},
            {"kind": "serve.deadline_miss", "request_id": "req-8",
             "miss": "ttft"},
            {"kind": "serve.deadline_miss", "request_id": "req-9",
             "miss": "total"},
        ]}
        findings = doctor.check_serving(workers)
        kinds = {f["kind"]: f for f in findings}
        assert set(kinds) == {"serve_poisoned", "serve_deadline_misses"}
        assert kinds["serve_poisoned"]["data"]["count"] == 1
        assert kinds["serve_deadline_misses"]["data"]["count"] == 2
        assert kinds["serve_deadline_misses"]["data"]["ttft_misses"] == 1
        assert kinds["serve_poisoned"]["severity"] \
            > kinds["serve_deadline_misses"]["severity"]
        assert doctor.check_serving({0: []}) == []
