"""Tests for the nn/nn.functional long-tail surface added for reference
__all__ parity: activations, pools, unfold/fold, grid sampling, losses,
beam decode, layer wrappers (reference nn/functional/* semantics)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


@pytest.mark.quick
class TestActivationsExt:
    def test_values_against_formulas(self):
        x = jnp.asarray(np.linspace(-3, 3, 13, dtype=np.float32))
        xn = np.asarray(x)
        np.testing.assert_allclose(
            np.asarray(F.celu(x, 1.5)),
            np.maximum(xn, 0) + np.minimum(1.5 * np.expm1(xn / 1.5), 0),
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(F.selu(x)),
            1.0507009873554805 * np.where(
                xn > 0, xn, 1.6732632423543772 * np.expm1(xn)), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(F.softsign(x)),
                                   xn / (1 + np.abs(xn)), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(F.tanhshrink(x)),
                                   xn - np.tanh(xn), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(F.softshrink(x, 0.5)),
            np.where(xn > 0.5, xn - 0.5, np.where(xn < -0.5, xn + 0.5, 0)),
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(F.hardshrink(x)), np.where(np.abs(xn) > 0.5, xn, 0))
        np.testing.assert_allclose(
            np.asarray(F.thresholded_relu(x)), np.where(xn > 1.0, xn, 0))
        np.testing.assert_allclose(np.asarray(F.hardtanh(x, -2, 2)),
                                   np.clip(xn, -2, 2))
        np.testing.assert_allclose(np.asarray(F.log_sigmoid(x)),
                                   -np.log1p(np.exp(-xn)), rtol=1e-5,
                                   atol=1e-6)

    def test_maxout_grouping(self):
        x = jnp.asarray(np.arange(6, dtype=np.float32).reshape(1, 6, 1, 1))
        out = F.maxout(x, groups=2)
        # channels pair up: (0,1) (2,3) (4,5) -> max of each
        np.testing.assert_allclose(np.asarray(out).ravel(), [1, 3, 5])

    def test_gumbel_softmax_hard_is_onehot_and_differentiable(self):
        x = jnp.asarray(np.random.RandomState(0).randn(4, 7), jnp.float32)
        y = F.gumbel_softmax(x, hard=True, key=jax.random.key(0))
        np.testing.assert_allclose(np.asarray(y.sum(-1)), 1.0, rtol=1e-6)
        assert set(np.unique(np.asarray(y))) <= {0.0, 1.0}
        g = jax.grad(lambda x_: jnp.sum(
            F.gumbel_softmax(x_, hard=True, key=jax.random.key(0)) ** 2))(x)
        assert float(jnp.abs(g).sum()) > 0   # straight-through grads

    def test_layer_wrappers(self):
        x = jnp.asarray(np.random.RandomState(0).randn(2, 4, 6, 6),
                        jnp.float32)
        for cls in (nn.CELU, nn.ELU, nn.SELU, nn.Silu, nn.Swish,
                    nn.Softsign, nn.LogSigmoid, nn.Hardshrink,
                    nn.Softshrink, nn.Tanhshrink, nn.ThresholdedReLU):
            assert cls()(x).shape == x.shape
        assert nn.Hardtanh(-2, 2)(x).shape == x.shape
        assert nn.Maxout(2)(x).shape == (2, 2, 6, 6)


@pytest.mark.quick
class TestPoolingExt:
    def test_pool3d_matches_manual(self):
        x = jnp.asarray(np.random.RandomState(0).randn(1, 2, 4, 4, 4),
                        jnp.float32)
        out = F.max_pool3d(x, 2)
        ref = np.asarray(x).reshape(1, 2, 2, 2, 2, 2, 2, 2)[
            :, :, :, :, :].reshape(1, 2, 2, 2, 2, 2, 2, 2)
        manual = np.asarray(x).reshape(1, 2, 2, 2, 2, 2, 2, 2)
        manual = manual.max(axis=(3, 5, 7))
        np.testing.assert_allclose(np.asarray(out), manual, rtol=1e-6)
        avg = F.avg_pool3d(x, 2)
        manual_avg = np.asarray(x).reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(
            axis=(3, 5, 7))
        np.testing.assert_allclose(np.asarray(avg), manual_avg, rtol=1e-5)

    def test_adaptive_1d_3d(self):
        x = jnp.asarray(np.random.RandomState(1).randn(2, 3, 10),
                        jnp.float32)
        out = F.adaptive_avg_pool1d(x, 5)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(x).reshape(2, 3, 5, 2).mean(-1), rtol=1e-5)
        om = F.adaptive_max_pool1d(x, 3)
        assert om.shape == (2, 3, 3)
        x3 = jnp.asarray(np.random.RandomState(2).randn(1, 2, 5, 6, 7),
                         jnp.float32)
        assert F.adaptive_avg_pool3d(x3, 2).shape == (1, 2, 2, 2, 2)
        assert F.adaptive_max_pool3d(x3, (2, 3, 2)).shape == (1, 2, 2, 3, 2)

    def test_max_pool_mask_and_unpool(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 3, 6, 6), jnp.float32)
        out, mask = F.max_pool2d(x, 2, return_mask=True)
        flat = np.asarray(x).reshape(2, 3, -1)
        gathered = np.take_along_axis(
            flat, np.asarray(mask).reshape(2, 3, -1), -1)
        np.testing.assert_allclose(gathered,
                                   np.asarray(out).reshape(2, 3, -1))
        rec = F.max_unpool2d(out, mask, 2)
        assert rec.shape == x.shape
        np.testing.assert_allclose(
            np.take_along_axis(np.asarray(rec).reshape(2, 3, -1),
                               np.asarray(mask).reshape(2, 3, -1), -1),
            np.asarray(out).reshape(2, 3, -1))
        # layer forms
        assert nn.MaxUnPool2D(2)(out, mask).shape == x.shape

    def test_unfold_fold_roundtrip_counts(self):
        x = jnp.asarray(np.random.RandomState(3).randn(2, 3, 8, 8),
                        jnp.float32)
        u = F.unfold(x, 2, 2)        # non-overlapping: fold inverts exactly
        assert u.shape == (2, 3 * 4, 16)
        back = F.fold(u, (8, 8), 2, 2)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                                   rtol=1e-6)
        # overlapping windows scatter-ADD (each pixel counted per visit)
        u2 = F.unfold(x, 3, 1, 1)
        acc = F.fold(u2, (8, 8), 3, 1, 1)
        ones = F.fold(F.unfold(jnp.ones_like(x), 3, 1, 1), (8, 8), 3, 1, 1)
        np.testing.assert_allclose(np.asarray(acc / ones), np.asarray(x),
                                   rtol=1e-5)


@pytest.mark.quick
class TestVisionFunctional:
    def test_affine_grid_sample_identity_and_shift(self):
        x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 8, 8),
                        jnp.float32)
        theta = jnp.tile(jnp.asarray([[[1.0, 0, 0], [0, 1, 0]]]), (2, 1, 1))
        g = F.affine_grid(theta, (2, 3, 8, 8))
        np.testing.assert_allclose(np.asarray(F.grid_sample(x, g)),
                                   np.asarray(x), atol=1e-4)
        # horizontal flip via theta
        flip = jnp.tile(jnp.asarray([[[-1.0, 0, 0], [0, 1, 0]]]), (2, 1, 1))
        gf = F.affine_grid(flip, (2, 3, 8, 8))
        np.testing.assert_allclose(np.asarray(F.grid_sample(x, gf)),
                                   np.asarray(x)[:, :, :, ::-1], atol=1e-4)

    def test_temporal_shift_layout(self):
        x = jnp.asarray(np.arange(2 * 2 * 8, dtype=np.float32
                                  ).reshape(4, 8, 1, 1))
        out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
        xr = np.asarray(x).reshape(2, 2, 8, 1, 1)
        on = np.asarray(out).reshape(2, 2, 8, 1, 1)
        # first quarter shifted backward: t gets t+1, last t zero
        np.testing.assert_allclose(on[:, 0, :2], xr[:, 1, :2])
        np.testing.assert_allclose(on[:, 1, :2], 0)
        # second quarter forward: t gets t-1, first t zero
        np.testing.assert_allclose(on[:, 1, 2:4], xr[:, 0, 2:4])
        np.testing.assert_allclose(on[:, 0, 2:4], 0)
        # rest untouched
        np.testing.assert_allclose(on[:, :, 4:], xr[:, :, 4:])


@pytest.mark.quick
class TestLossesExt:
    def test_bce_and_focal_and_log_loss(self):
        p = jnp.asarray([0.9, 0.1, 0.8], jnp.float32)
        y = jnp.asarray([1.0, 0.0, 1.0])
        ref = -(np.log([0.9, 0.9, 0.8])).mean()
        np.testing.assert_allclose(float(F.binary_cross_entropy(p, y)), ref,
                                   rtol=1e-5)
        assert float(nn.BCELoss()(p, y)) == pytest.approx(ref, rel=1e-5)
        ll = F.log_loss(p, y, epsilon=0.0)
        np.testing.assert_allclose(np.asarray(ll),
                                   -np.log([0.9, 0.9, 0.8]), rtol=1e-5)
        fl = F.sigmoid_focal_loss(jnp.zeros(3), y, reduction="none")
        assert fl.shape == (3,) and np.all(np.asarray(fl) > 0)

    def test_softmax_with_cross_entropy_matches_manual(self):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(5, 7), jnp.float32)
        y = jnp.asarray(rng.randint(0, 7, 5))
        loss, sm = F.softmax_with_cross_entropy(logits, y,
                                                return_softmax=True)
        lsm = np.log(np.asarray(sm))
        manual = -lsm[np.arange(5), np.asarray(y)]
        np.testing.assert_allclose(np.asarray(loss)[:, 0], manual,
                                   rtol=1e-5)
        # ignore_index zeroes the loss
        y2 = y.at[0].set(-100)
        l2 = F.softmax_with_cross_entropy(logits, y2, ignore_index=-100)
        assert float(l2[0, 0]) == 0.0

    def test_margin_cross_entropy_margins_increase_loss(self):
        rng = np.random.RandomState(0)
        cos = jnp.asarray(np.clip(rng.randn(6, 10) * 0.3, -0.95, 0.95),
                          jnp.float32)
        y = jnp.asarray(rng.randint(0, 10, 6))
        plain = float(F.margin_cross_entropy(cos, y, margin1=1.0,
                                             margin2=0.0, margin3=0.0))
        arc = float(F.margin_cross_entropy(cos, y, margin1=1.0,
                                           margin2=0.5, margin3=0.0))
        assert arc > plain   # margins make the target harder

    def test_hsigmoid_learnable(self):
        # the hierarchical loss trains a linear model to separate classes
        rng = np.random.RandomState(0)
        C, D, N = 4, 8, 64
        protos = rng.randn(C, D).astype(np.float32) * 2
        y = rng.randint(0, C, N)
        x = jnp.asarray(protos[y] + 0.1 * rng.randn(N, D).astype(np.float32))
        yj = jnp.asarray(y)
        w0 = jnp.asarray(rng.randn(C - 1, D).astype(np.float32) * 0.1)

        def loss_fn(w):
            return jnp.mean(F.hsigmoid_loss(x, yj, C, w))

        w = w0
        first = float(loss_fn(w))
        for _ in range(60):
            w = w - 0.5 * jax.grad(loss_fn)(w)
        assert float(loss_fn(w)) < first * 0.5

    def test_npair_and_dice(self):
        rng = np.random.RandomState(0)
        a = jnp.asarray(rng.randn(6, 4), jnp.float32)
        p = jnp.asarray(rng.randn(6, 4), jnp.float32)
        lbl = jnp.asarray([0, 0, 1, 1, 2, 2])
        assert float(F.npair_loss(a, p, lbl)) > 0
        probs = jnp.asarray([[0.9, 0.1], [0.2, 0.8]], jnp.float32)
        dl = F.dice_loss(probs, jnp.asarray([[0], [1]]))
        assert 0 < float(dl) < 1

    def test_class_center_sample(self):
        lbl, sampled = F.class_center_sample(
            jnp.asarray([1, 5, 9, 5]), 20, 6, seed=0)
        s = np.asarray(sampled)
        assert len(s) == 6 and {1, 5, 9} <= set(s.tolist())
        # positives remap inside the sampled set
        remapped = np.asarray(lbl)
        assert all(s[r] == orig for r, orig in zip(remapped, [1, 5, 9, 5]))


@pytest.mark.quick
class TestNormAndMisc:
    def test_local_response_norm_formula(self):
        x = jnp.asarray(np.random.RandomState(0).rand(1, 6, 3, 3),
                        jnp.float32)
        out = F.local_response_norm(x, size=3, alpha=1e-2, beta=0.5, k=1.0)
        xn = np.asarray(x)
        acc = np.zeros_like(xn)
        for c in range(6):
            lo, hi = max(0, c - 1), min(6, c + 2)
            acc[:, c] = (xn[:, lo:hi] ** 2).sum(1)
        ref = xn / (1.0 + 1e-2 / 3 * acc) ** 0.5
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)

    def test_instance_norm_zero_mean_unit_var(self):
        x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 8, 8) * 5 + 2,
                        jnp.float32)
        y = np.asarray(F.instance_norm(x))
        np.testing.assert_allclose(y.mean(axis=(2, 3)), 0, atol=1e-5)
        np.testing.assert_allclose(y.var(axis=(2, 3)), 1, atol=1e-3)

    def test_dropout_channels_and_alpha(self):
        x = jnp.ones((4, 8, 5, 5))
        y = np.asarray(F.dropout2d(x, 0.5, key=jax.random.key(0)))
        per_channel = y.reshape(4, 8, -1)
        # each channel all-zero or all-scaled
        assert all(len(np.unique(c)) == 1 for b in per_channel for c in b)
        ya = F.alpha_dropout(x, 0.3, key=jax.random.key(1))
        assert ya.shape == x.shape
        m = nn.AlphaDropout(0.3); m.eval()
        np.testing.assert_array_equal(np.asarray(m(x)), np.asarray(x))

    def test_sequence_mask_and_diag_embed(self):
        np.testing.assert_array_equal(
            np.asarray(F.sequence_mask(jnp.asarray([1, 3]), maxlen=4)),
            [[1, 0, 0, 0], [1, 1, 1, 0]])
        d = F.diag_embed(jnp.asarray([[1.0, 2.0]]))
        assert d.shape == (1, 2, 2)
        np.testing.assert_allclose(np.asarray(d)[0], [[1, 0], [0, 2]])

    def test_conv_transpose_1d3d_shapes_and_grad(self):
        pt.seed(0)
        ct = nn.Conv1DTranspose(4, 6, 3, stride=2)
        y = ct(jnp.ones((2, 4, 5)))
        assert y.shape == (2, 6, 11)
        c3 = nn.Conv3DTranspose(2, 3, 3)
        assert c3(jnp.ones((1, 2, 4, 4, 4))).shape == (1, 3, 6, 6, 6)
        # functional gradcheck via conv identity: transpose of conv
        g = jax.grad(lambda w: jnp.sum(F.conv1d_transpose(
            jnp.ones((1, 2, 4)), w) ** 2))(jnp.ones((2, 3, 2)) * 0.1)
        assert g.shape == (2, 3, 2)

    def test_bilinear_einsum(self):
        x1 = jnp.asarray([[1.0, 2.0]])
        x2 = jnp.asarray([[3.0, 4.0, 5.0]])
        w = jnp.ones((1, 2, 3))
        out = F.bilinear(x1, x2, w)
        assert float(out[0, 0]) == pytest.approx((1 + 2) * (3 + 4 + 5))


@pytest.mark.quick
class TestBeamDecode:
    def test_gather_tree_backtrace(self):
        ids = jnp.asarray([[[1, 5]], [[2, 6]], [[3, 7]]])      # (T=3,B=1,K=2)
        parents = jnp.asarray([[[0, 0]], [[0, 0]], [[1, 0]]])
        out = np.asarray(F.gather_tree(ids, parents))
        # beam 0's chain: t2 token 3 (parent 1) <- t1 token 6 (parent 0)
        # <- t0 token 1
        np.testing.assert_array_equal(out[:, 0, 0], [1, 6, 3])

    def test_beam_search_decodes_argmax_chain(self):
        class ToyCell:
            def __call__(self, tok, states):
                V = 7
                logits = jnp.full((tok.shape[0], V), -5.0)
                logits = logits.at[jnp.arange(tok.shape[0]),
                                   (tok + 1) % V].set(5.0)
                return logits, states

        dec = nn.BeamSearchDecoder(ToyCell(), start_token=0, end_token=6,
                                   beam_size=2)
        seqs, lp = nn.dynamic_decode(dec, inits={"h": jnp.zeros((2, 1))},
                                     max_step_num=10)
        np.testing.assert_array_equal(np.asarray(seqs)[0, 0][:6],
                                      [1, 2, 3, 4, 5, 6])
        assert float(lp[0, 0]) > float(lp[0, 1])


@pytest.mark.quick
class TestContainersAndNorm:
    def test_layer_dict(self):
        ld = nn.LayerDict({"a": nn.Linear(2, 3), "b": nn.ReLU()})
        assert set(ld.keys()) == {"a", "b"} and "a" in ld
        ld["c"] = nn.Tanh()
        assert len(ld) == 3
        popped = ld.pop("c")
        assert isinstance(popped, nn.Tanh) and len(ld) == 2
        # registered as sublayers -> parameters visible
        assert any("a" in k for k in ld.state_dict())

    def test_batchnorm_legacy_and_sync_convert(self):
        pt.seed(0)
        bn = nn.BatchNorm(4, act="relu")
        bn.train()
        x = jnp.asarray(np.random.RandomState(0).randn(8, 4, 3, 3),
                        jnp.float32)
        y = bn(x)
        assert float(jnp.min(y)) >= 0          # act applied
        net = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4))
        net2 = nn.SyncBatchNorm.convert_sync_batchnorm(net)
        assert isinstance(net2[1], nn.SyncBatchNorm)
        net2.train()
        assert net2(jnp.ones((2, 3, 8, 8))).shape == (2, 4, 6, 6)


@pytest.mark.quick
class TestReviewRegressions:
    """Round-5 review findings pinned as regressions."""

    def test_maxpool_layer_returns_tensor_not_tuple(self):
        out = nn.MaxPool2D(2, data_format="NCHW")(jnp.ones((1, 1, 4, 4)))
        assert not isinstance(out, tuple)

    def test_return_mask_with_padding_and_negative_values(self):
        x = -jnp.asarray(np.random.RandomState(0).rand(1, 1, 4, 4) + 0.5,
                         jnp.float32)
        o, m = F.max_pool2d(x, 2, stride=2, padding=1, return_mask=True)
        mv = np.asarray(m).ravel()
        assert mv.min() >= 0 and mv.max() < 16
        flat = np.asarray(x).reshape(1, 1, -1)
        np.testing.assert_allclose(
            np.take_along_axis(flat, np.asarray(m).reshape(1, 1, -1), -1),
            np.asarray(o).reshape(1, 1, -1))

    def test_exponential_family_batched_entropy(self):
        from paddle_tpu.distribution import ExponentialFamily

        class DiagNormalEF(ExponentialFamily):
            def __init__(self, loc, scale):
                self.loc = jnp.asarray(loc)
                self.scale = jnp.asarray(scale)

            @property
            def _natural_parameters(self):
                return (self.loc / self.scale ** 2,
                        -0.5 / self.scale ** 2)

            def _log_normalizer(self, n1, n2):
                return -n1 ** 2 / (4 * n2) - 0.5 * jnp.log(-2 * n2)

            @property
            def _mean_carrier_measure(self):
                return -0.5 * np.log(2 * np.pi)

        scale = np.asarray([1.0, 2.0, 0.5])
        d = DiagNormalEF(jnp.asarray([0.0, 1.0, 2.0]), jnp.asarray(scale))
        ent = d.entropy()
        assert ent.shape == (3,)
        np.testing.assert_allclose(
            np.asarray(ent), 0.5 * np.log(2 * np.pi * np.e * scale ** 2),
            rtol=1e-5)

    def test_program_translator_enable_false_runs_eagerly(self):
        from paddle_tpu import jit
        calls = []

        @jit.to_static
        def f(a):
            calls.append(1)
            return a * 2

        t = jit.ProgramTranslator.get_instance()
        t.enable(True)
        f(jnp.ones(2)); f(jnp.ones(2))
        traced_calls = len(calls)
        t.enable(False)
        try:
            f(jnp.ones(2)); f(jnp.ones(2))
            assert len(calls) == traced_calls + 2   # eager: runs per call
        finally:
            t.enable(True)

    def test_global_initializer_top_level_create_parameter(self):
        nn.initializer.set_global_initializer(nn.initializer.Constant(0.25))
        try:
            w = pt.create_parameter([3, 3], "float32")
            assert float(w.value[0, 0]) == 0.25
        finally:
            nn.initializer.set_global_initializer(None, None)
