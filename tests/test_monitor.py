"""Live run monitor tests (ISSUE 5): status server scraped during a real
supervised ``fit()``, flight-recorder dumps on a watchdog-killed hang and
on SIGTERM, the live aggregator naming a straggler from a *partial*
(still-growing) stream, and the doctor ingesting a flight bundle when the
worker JSONL tail was lost."""
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.observability import aggregate as agg_mod
from paddle_tpu.observability import compilation, doctor, flight, monitor
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.supervisor import RunSupervisor
from paddle_tpu.supervisor.rollback import RollbackBudgetExceeded
from paddle_tpu.testing import faults

pytestmark = pytest.mark.telemetry


def _get(url: str):
    """(status, body bytes) — 503s return instead of raising."""
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _model(n_in=8, n_out=4):
    net = pt.nn.Sequential(pt.nn.Linear(n_in, n_out))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-3),
                  loss=pt.nn.CrossEntropyLoss())
    return model


def _data(n=32, n_in=8, n_cls=4):
    rng = np.random.RandomState(0)
    x = rng.randn(n, n_in).astype("float32")
    y = rng.randint(0, n_cls, (n,)).astype("int64")
    return list(zip(x, y))


class _RaggedLoader(pt.io.DataLoader):
    """Batch-dimension churn → one retrace per distinct shape."""

    def __init__(self, sizes, n_feat=8, slow_secs=0.0):
        self.sizes = list(sizes)
        self.n_feat = n_feat
        self.slow_secs = slow_secs

    def __iter__(self):
        rng = np.random.RandomState(3)
        for b in self.sizes:
            if self.slow_secs:
                faults.hang(self.slow_secs)
            x = rng.randn(b, self.n_feat).astype("float32")
            y = rng.randint(0, 4, (b,)).astype("int64")
            yield [x, y]

    def __len__(self):
        return len(self.sizes)


# -- the status server ------------------------------------------------------
class TestStatusServer:
    def test_scraped_during_supervised_fit(self, tmp_path, monkeypatch):
        """ISSUE 5 satellite: /metrics + /statusz answered mid-``fit()``
        — step counters, live MFU, heartbeat age, watchdog state and
        compile-cache stats all present while batches still run."""
        monkeypatch.setenv(monitor.MONITOR_PORT_ENV, "0")  # ephemeral
        scraped = {}

        class Scraper(Callback):
            def on_train_batch_end(self, step, logs=None):
                sup = self.model._supervisor
                if step == 2 and sup is not None:
                    base = f"http://127.0.0.1:{sup.status_server.port}"
                    scraped["healthz"] = _get(base + "/healthz")
                    scraped["metrics"] = _get(base + "/metrics")[1].decode()
                    scraped["statusz"] = json.loads(
                        _get(base + "/statusz")[1])
                    scraped["missing"] = _get(base + "/nope")[0]

        model = _model()
        sup = RunSupervisor(str(tmp_path / "run"), worker_id=0,
                            sigterm_handler=False)
        model.fit(_data(), batch_size=8, epochs=1, verbose=0,
                  supervisor=sup, callbacks=[Scraper()])
        assert scraped["healthz"][0] == 200
        assert json.loads(scraped["healthz"][1])["ok"] is True
        # a known instrument in Prometheus text format
        assert "paddle_tpu_step_time_ms_count" in scraped["metrics"]
        assert "# TYPE paddle_tpu_step_count counter" in scraped["metrics"]
        sz = scraped["statusz"]
        assert sz["step"] is not None and sz["step"] >= 2
        assert sz["step_time_ms"]["p50"] > 0
        assert sz["step_time_ms"]["p99"] >= sz["step_time_ms"]["p50"]
        assert sz["mfu"] is not None
        assert sz["heartbeat"]["beats"] >= 1
        assert sz["watchdog"]["timeouts"] == 0
        assert not sz["watchdog"]["closed"]
        assert sz["supervisor"]["running"] is True
        assert "hapi.train_step" in (sz["compile"] or {})
        assert sz["flight"]["capacity"] >= 16
        assert scraped["missing"] == 404
        # the server is torn down with the run
        assert sup.status_server is None

    def test_healthz_503_when_not_running(self):
        reg = MetricsRegistry()

        class _Sup:  # the duck the server reads
            _running = False
            pending_rollback = None
            monitor = type("M", (), {"_last_state": None})()

        with obs.StatusServer(port=0, registry=reg,
                              supervisor=_Sup()) as srv:
            code, body = _get(f"http://127.0.0.1:{srv.port}/healthz")
            assert code == 503
            assert json.loads(body)["state"] == "not-running"

    def test_port_offset_by_worker_rank(self, monkeypatch):
        srv0 = obs.StatusServer(port=0, registry=MetricsRegistry()).start()
        base = srv0.port  # a port we know is taken: rank 0 owns it
        monkeypatch.setenv(monitor.MONITOR_PORT_ENV, str(base))
        try:
            srv1 = monitor.maybe_start_server(worker_id=1)
            assert srv1 is not None and srv1.port == base + 1
            srv1.stop()
            # rank 0 would collide with the running server: bind fails
            # loudly→None, never takes the run down
            assert monitor.maybe_start_server(worker_id=0) is None
        finally:
            srv0.stop()

    def test_unset_port_means_no_server(self, monkeypatch):
        monkeypatch.delenv(monitor.MONITOR_PORT_ENV, raising=False)
        assert monitor.maybe_start_server(worker_id=0) is None


# -- stream tailing ---------------------------------------------------------
class TestStreamTail:
    def test_partial_tail_line_is_not_torn(self, tmp_path):
        p = str(tmp_path / "worker-0.jsonl")
        tail = agg_mod.StreamTail(p)
        with open(p, "a") as f:
            f.write(json.dumps({"ts": 1.0, "kind": "step", "step": 0})
                    + "\n")
            f.write('{"ts": 2.0, "kind": "st')     # writer mid-append
            f.flush()
            assert [r["step"] for r in tail.poll()] == [0]
            assert tail.drops["torn_lines"] == 0   # not torn, unfinished
            f.write('ep", "step": 1}\n')           # append completes
            f.flush()
            assert [r["step"] for r in tail.poll()] == [1]
        assert tail.poll() == []                    # nothing new

    def test_truncation_rereads(self, tmp_path):
        p = str(tmp_path / "worker-0.jsonl")
        tail = agg_mod.StreamTail(p)
        with open(p, "w") as f:
            f.write(json.dumps({"ts": 1.0, "kind": "step", "step": 0})
                    + "\n")
        assert len(tail.poll()) == 1
        with open(p, "w") as f:  # rotated under us: shorter file
            f.write(json.dumps({"ts": 9.0, "kind": "x"}) + "\n")
        assert tail.poll()[0]["kind"] == "x"


# -- the flight recorder ----------------------------------------------------
class TestFlightRecorder:
    def test_ring_is_bounded_and_dump_durable(self, tmp_path):
        fr = flight.FlightRecorder(str(tmp_path), worker_id=3, capacity=8)
        for i in range(50):
            fr.write({"ts": float(i), "kind": "step", "step": i})
        assert fr.seen == 50
        path = fr.dump("unit")
        bundle = flight.read_flight_bundles(str(tmp_path))[3]
        assert path.endswith("flight/worker-3.json")
        assert len(bundle["records"]) == 8          # only the newest ring
        assert bundle["records"][-1]["step"] == 49
        assert bundle["records_seen"] == 50
        assert bundle["reason"] == "unit"

    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv(flight.FLIGHT_BUFFER_ENV, "64")
        assert flight.default_capacity() == 64

    def test_dump_on_hang_watchdog_kill(self, tmp_path):
        """ISSUE 5 satellite: injected ``faults.hang`` → watchdog
        StepTimeout on every step → rollback budget 0 → the run dies —
        and leaves a flight bundle the doctor can still rank."""
        run_dir = str(tmp_path / "run")
        model = _model()
        sup = RunSupervisor(run_dir, worker_id=0, watchdog_secs=0.2,
                            rollback_budget=0, sigterm_handler=False)
        sup.inject_loss(lambda step, loss: faults.hang(30.0) or loss)
        with pytest.raises(RollbackBudgetExceeded):
            model.fit(_data(), batch_size=8, epochs=1, verbose=0,
                      supervisor=sup)
        bundles = flight.read_flight_bundles(run_dir)
        assert 0 in bundles
        assert bundles[0]["reason"] == "end_run:failed"
        kinds = {r.get("kind") for r in bundles[0]["records"]}
        assert "supervisor.watchdog_timeout" in kinds
        # acceptance: kill the JSONL stream (the lost tail) — the doctor
        # diagnoses from the flight bundle alone, non-empty and ranked
        for name in os.listdir(obs.metrics_dir(run_dir)):
            os.remove(os.path.join(obs.metrics_dir(run_dir), name))
        diag = doctor.diagnose(run_dir)
        assert diag is not None and diag["findings"]
        assert diag["flight_workers"] == [0]
        sevs = [f["severity"] for f in diag["findings"]]
        assert sevs == sorted(sevs, reverse=True)
        assert any(f["kind"] == "unstable" for f in diag["findings"])
        # the CLI sees the same evidence
        assert doctor.main([run_dir]) == 0

    def test_dump_on_sigterm_chains_previous_handler(self, tmp_path):
        run_dir = str(tmp_path / "run")
        hits = []
        orig = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, lambda *a: hits.append(a))
        try:
            sup = RunSupervisor(run_dir, worker_id=0,
                                sigterm_handler=False)
            sup.begin_run()
            obs.get_registry().emit("step", step=1, step_time_ms=5.0)
            os.kill(os.getpid(), signal.SIGTERM)   # preemption notice
            bundles = flight.read_flight_bundles(run_dir)
            assert 0 in bundles
            assert bundles[0]["reason"] == f"signal-{signal.SIGTERM}"
            assert any(r.get("kind") == "step"
                       for r in bundles[0]["records"])
            assert hits, "previous SIGTERM handler was not chained"
            sup.end_run("completed")
            # clean end restores the chain and disarms atexit
            assert sup.flight is None
        finally:
            signal.signal(signal.SIGTERM, orig)

    def test_clean_run_leaves_no_bundle(self, tmp_path):
        run_dir = str(tmp_path / "run")
        model = _model()
        sup = RunSupervisor(run_dir, worker_id=0, sigterm_handler=False)
        model.fit(_data(n=16), batch_size=8, epochs=1, verbose=0,
                  supervisor=sup)
        assert flight.read_flight_bundles(run_dir) == {}


# -- the live aggregator ----------------------------------------------------
def _append_stream(mdir, wid, records):
    os.makedirs(mdir, exist_ok=True)
    with open(os.path.join(mdir, f"worker-{wid}.jsonl"), "a") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


class TestLiveAggregator:
    def test_straggler_named_from_partial_stream(self, tmp_path):
        """The monitor's whole point: worker 1's stream is PARTIAL
        (still growing) and the straggler verdict already fires."""
        run_dir = str(tmp_path / "run")
        mdir = obs.metrics_dir(run_dir)
        steps = lambda wid, hi, ms: [  # noqa: E731
            {"ts": 1000.0 + s, "kind": "step", "step": s,
             "step_time_ms": ms, "data_ms": 1.0} for s in range(hi)]
        agg = obs.LiveAggregator(run_dir, interval=0)
        _append_stream(mdir, 0, steps(0, 20, 10.0))
        _append_stream(mdir, 1, steps(1, 4, 50.0))   # 4 of 20 so far
        status = agg.poll(force=True)
        strag = [f for f in status["findings"]
                 if f["kind"] == "straggler"]
        assert strag and strag[0]["data"]["worker"] == 1
        assert status["last_step"] == {"0": 19, "1": 3}
        assert len(status["alerts"]) == 1
        # stream grows; alert does NOT re-fire for the same verdict
        _append_stream(mdir, 1, steps(1, 20, 50.0)[4:])
        status = agg.poll(force=True)
        assert len(status["alerts"]) == 1
        assert status["last_step"]["1"] == 19

    def test_alert_lands_on_supervisor_timeline(self, tmp_path):
        from paddle_tpu.supervisor.report import SupervisorReport
        run_dir = str(tmp_path / "run")
        mdir = obs.metrics_dir(run_dir)
        _append_stream(mdir, 0, [
            {"ts": 1000.0 + i, "kind": "compile",
             "function": "hapi.train_step", "retrace": i > 0,
             "changed": [{"arg": "data[0]",
                          "detail": "f32[4,8] -> f32[5,8]"}],
             "wall_ms": 5.0} for i in range(5)])
        report = SupervisorReport(os.path.join(run_dir,
                                               "launcher_report.json"))
        agg = obs.LiveAggregator(run_dir, interval=0, report=report)
        agg.poll(force=True)
        alerts = report.of_kind("monitor.alert")
        assert alerts and alerts[0]["verdict"] == "retrace_storm"
        assert "data[0]" in alerts[0]["title"]

    def test_interval_throttling(self, tmp_path):
        agg = obs.LiveAggregator(str(tmp_path), interval=3600)
        assert agg.poll(force=True) is not None
        assert agg.poll() is None                   # throttled
        assert agg.poll(force=True) is not None

    def test_e2e_degraded_fit_alerts_before_run_ends(self, tmp_path,
                                                     monkeypatch):
        """ISSUE 5 acceptance: shape-churning loader + one worker slowed
        via ``faults.slow_call`` — ``live_status.json`` names a
        retrace/straggler alert asserted MID-RUN, before worker 1's fit
        returns."""
        monkeypatch.setenv("PTPU_METRICS_INTERVAL", "0.05")  # eager flush
        compilation.reset_tracker()
        run_dir = str(tmp_path / "run")
        sizes = [4, 6, 8, 10, 4, 6, 8, 10]

        def run_worker(wid, slow):
            model = _model()
            if slow:
                model._train_step = faults.slow_call(model._train_step,
                                                     0.25)
            sup = RunSupervisor(run_dir, worker_id=wid,
                                watchdog_secs=120.0,
                                sigterm_handler=False)
            model.fit(_RaggedLoader(sizes), epochs=1, verbose=0,
                      supervisor=sup)

        run_worker(0, slow=False)                   # fast worker: done
        done = threading.Event()

        def worker1():
            try:
                run_worker(1, slow=True)
            finally:
                done.set()

        t = threading.Thread(target=worker1, daemon=True)
        t.start()
        agg = obs.LiveAggregator(run_dir, interval=0)
        mid_run_alerts = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not done.is_set():
            status = agg.poll(force=True)
            kinds = {a["kind"] for a in status["alerts"]}
            if {"retrace_storm", "straggler"} <= kinds:
                assert not done.is_set(), "run ended before the alert"
                mid_run_alerts = json.load(
                    open(monitor.live_status_path(run_dir)))["alerts"]
                break
            time.sleep(0.05)
        t.join(timeout=60.0)
        assert mid_run_alerts is not None, \
            "no retrace+straggler alert before the run ended"
        by_kind = {a["kind"]: a for a in mid_run_alerts}
        assert "data[" in by_kind["retrace_storm"]["title"]
        assert "worker 1" in by_kind["straggler"]["title"]


# -- doctor × flight --------------------------------------------------------
class TestDoctorFlightIngestion:
    def test_truncated_stream_recovered_from_bundle(self, tmp_path):
        """Worker 1's JSONL lost its tail (buffered records died with the
        process); its flight bundle carries them — the doctor folds the
        bundle in and still attributes the straggler + the OOM."""
        run_dir = str(tmp_path / "run")
        mdir = obs.metrics_dir(run_dir)
        fast = [{"ts": 1000.0 + s, "kind": "step", "step": s,
                 "step_time_ms": 10.0, "data_ms": 1.0} for s in range(20)]
        slow = [{"ts": 1000.0 + s, "kind": "step", "step": s,
                 "step_time_ms": 40.0, "data_ms": 1.0} for s in range(20)]
        _append_stream(mdir, 0, fast)
        _append_stream(mdir, 1, slow[:3])           # the surviving head
        fr = flight.FlightRecorder(run_dir, worker_id=1, capacity=64)
        for r in slow:                              # the ring saw it all
            fr.write(r)
        fr.write({"ts": 1020.0, "kind": "memory.oom", "step": 19,
                  "error": "RESOURCE_EXHAUSTED",
                  "devices": {"tpu:1": {"bytes_in_use": 990,
                                        "peak_bytes_in_use": 999,
                                        "bytes_limit": 1000,
                                        "utilization": 0.99}}})
        fr.dump("sigkill-simulated")
        diag = doctor.diagnose(run_dir)
        assert diag["flight_workers"] == [1]
        kinds = [f["kind"] for f in diag["findings"]]
        assert kinds[0] == "oom"                    # only in the bundle
        strag = next(f for f in diag["findings"]
                     if f["kind"] == "straggler")
        assert strag["data"]["worker"] == 1
        # without the bundle the straggler is invisible (3 aligned steps
        # of a 20-step run barely registers) — prove the bundle mattered
        report = doctor.render_report(diag)
        assert "flight-recorder evidence" in report

    def test_garbled_bundle_is_skipped(self, tmp_path):
        run_dir = str(tmp_path / "run")
        os.makedirs(flight.flight_dir(run_dir))
        with open(os.path.join(flight.flight_dir(run_dir),
                               "worker-0.json"), "w") as f:
            f.write('{"worker": 0, "records": [')   # torn dump
        assert flight.read_flight_bundles(run_dir) == {}
        assert doctor.diagnose(run_dir) is None
