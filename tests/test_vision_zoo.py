"""Vision model-zoo construction + forward-shape tests.

Mirrors the reference's test_vision_models.py doctrine: build each
architecture, run one forward on a small batch, check the logits shape.
Small spatial sizes keep CPU wall-clock low; Inception/GoogLeNet need their
minimum legal inputs.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.vision import models as M


def _forward(model, hw=64, batch=2, channels=3):
    model.eval()
    x = jnp.asarray(np.random.RandomState(0).randn(batch, channels, hw, hw),
                    jnp.float32)
    return model(x)


@pytest.mark.parametrize("ctor,kw,hw", [
    (M.alexnet, {}, 224),
    (M.vgg11, {}, 64),
    (M.vgg16, {"batch_norm": True}, 64),
    (M.mobilenet_v1, {"scale": 0.25}, 64),
    (M.mobilenet_v2, {"scale": 0.25}, 64),
    (M.mobilenet_v3_small, {"scale": 0.5}, 64),
    (M.mobilenet_v3_large, {"scale": 0.35}, 64),
    (M.squeezenet1_0, {}, 96),
    (M.squeezenet1_1, {}, 96),
    (M.shufflenet_v2_x0_25, {}, 64),
    (M.shufflenet_v2_swish, {}, 64),
    (M.densenet121, {}, 64),
    (M.resnext50_32x4d, {}, 64),
    (M.inception_v3, {}, 128),
])
def test_zoo_forward_shape(ctor, kw, hw):
    pt.seed(0)
    model = ctor(num_classes=10, **kw)
    out = _forward(model, hw=hw)
    assert out.shape == (2, 10)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_googlenet_returns_aux_heads():
    pt.seed(0)
    model = M.googlenet(num_classes=10)
    out, aux1, aux2 = _forward(model, hw=128)
    assert out.shape == aux1.shape == aux2.shape == (2, 10)


def test_headless_backbone_modes():
    """num_classes=0 / with_pool toggles parallel the reference's API."""
    pt.seed(0)
    m = M.mobilenet_v2(scale=0.25, num_classes=0)
    feats = _forward(m, hw=64)
    assert feats.shape[0:2] == (2, 1280) and feats.ndim == 4

    m = M.vgg11(num_classes=0, with_pool=False)
    feats = _forward(m, hw=64)
    assert feats.ndim == 4 and feats.shape[1] == 512


def test_mobilenet_v2_trains_one_step():
    """One SGD step decreases loss on an overfit-able toy batch."""
    import jax

    pt.seed(0)
    model = M.mobilenet_v2(scale=0.25, num_classes=4)
    model.train()
    params = model.state_dict()
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3, 32, 32), jnp.float32)
    y = jnp.asarray([0, 1, 2, 3], jnp.int32)
    opt = pt.optimizer.Adam(learning_rate=1e-3)
    state = opt.init(params)

    def loss_fn(p):
        logits = model.apply(p, x)
        from paddle_tpu.nn import functional as F
        return F.cross_entropy(logits, y)

    @jax.jit
    def step(p, s):
        loss, grads = jax.value_and_grad(loss_fn)(p)
        p2, s2 = opt.apply_gradients(grads, p, s)
        return loss, p2, s2

    losses = []
    for _ in range(6):
        loss, params, state = step(params, state)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_transform_family():
    """New transforms: shapes, ranges, determinism under seed."""
    from paddle_tpu.vision import transforms as T

    np.random.seed(0)
    img = (np.random.rand(24, 24, 3) * 255).astype(np.uint8)
    assert T.Pad(2)(img).shape == (28, 28, 3)
    assert T.Pad((1, 2))(img).shape == (28, 26, 3)
    g = T.Grayscale()(img)
    assert g.shape == (24, 24, 1) and g.dtype == np.uint8
    assert T.Grayscale(3)(img).shape == (24, 24, 3)
    assert T.RandomResizedCrop(12)(img).shape == (12, 12, 3)
    rot = T.RandomRotation(30)(img)
    assert rot.shape == img.shape
    out = T.ColorJitter(0.3, 0.3, 0.3, 0.1)(img)
    assert out.shape == img.shape and out.dtype == np.uint8
    flip = T.RandomVerticalFlip(prob=1.0)(img)
    np.testing.assert_array_equal(flip, img[::-1])
    # full pipeline composes into a CHW float tensor
    np.random.seed(1)
    pipe = T.Compose([T.RandomResizedCrop(16), T.ColorJitter(0.2),
                      T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3)])
    out = pipe(img)
    assert out.shape == (3, 16, 16) and out.dtype == np.float32


def test_adaptive_avg_pool_non_divisible():
    """The general adaptive-pool path (matmul formulation) matches a numpy
    reference on a non-divisible 14→4 bin layout (GoogLeNet aux head)."""
    from paddle_tpu.nn import functional as F

    x = np.random.RandomState(0).randn(2, 3, 14, 14).astype(np.float32)
    got = np.asarray(F.adaptive_avg_pool2d(jnp.asarray(x), (4, 4)))
    # bin i covers [floor(i*in/out), ceil((i+1)*in/out))
    ref = np.zeros((2, 3, 4, 4), np.float32)
    for i in range(4):
        hs, he = (i * 14) // 4, -(-((i + 1) * 14) // 4)
        for j in range(4):
            ws, we = (j * 14) // 4, -(-((j + 1) * 14) // 4)
            ref[:, :, i, j] = x[:, :, hs:he, ws:we].mean(axis=(2, 3))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_image_folder_label_free(tmp_path):
    from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder

    import numpy as _np
    for sub in ("a", "b"):
        (tmp_path / sub).mkdir()
        for i in range(2):
            _np.save(tmp_path / sub / f"{i}.npy",
                     _np.full((4, 4), ord(sub) + i, _np.uint8))
    loader = lambda p: _np.load(p)

    flat = ImageFolder(str(tmp_path), loader=loader,
                       extensions=(".npy",))
    assert len(flat) == 4
    item = flat[0]
    assert isinstance(item, list) and len(item) == 1  # no label
    assert item[0].shape == (4, 4)

    tree = DatasetFolder(str(tmp_path), loader=loader,
                         extensions=(".npy",))
    img, label = tree[0]
    assert label == 0 and tree.class_to_idx == {"a": 0, "b": 1}


def test_image_folder_filters_non_images(tmp_path):
    import pytest
    from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder

    (tmp_path / "c").mkdir()
    (tmp_path / "c" / "README.txt").write_text("not an image")
    (tmp_path / "c" / "x.npy").write_bytes(b"")
    # default extensions exclude both .txt and .npy -> reference-style error
    with pytest.raises(RuntimeError, match="Found 0 files"):
        ImageFolder(str(tmp_path))
    with pytest.raises(RuntimeError, match="Found 0 files"):
        DatasetFolder(str(tmp_path))
    # widening extensions indexes only the matching file
    assert len(ImageFolder(str(tmp_path), extensions=(".npy",))) == 1
