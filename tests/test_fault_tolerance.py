"""Fault-tolerance proof for the resilience layer (ISSUE 1).

Uses ``paddle_tpu.testing.faults`` to deliver torn writes, bit flips,
transient ``OSError``s and SIGTERM into the checkpoint/elastic/training
stack, and asserts the documented recovery behavior:

- a byte-flipped shard in the newest checkpoint is caught by CRC32,
  quarantined, and training resumes from the previous committed step;
- SIGTERM mid-run flushes a checkpoint that restores bit-exact;
- up to 3 consecutive transient I/O errors are absorbed by retry with no
  caller-visible failure;
- v1 (pre-checksum) checkpoints stay loadable.
"""
import os
import signal
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import framework
from paddle_tpu.distributed.checkpoint import (CheckpointCorruption,
                                               load_sharded, save_sharded,
                                               verify_sharded)
from paddle_tpu.distributed.elastic import (ElasticTrainState,
                                            committed_checkpoints,
                                            latest_checkpoint)
from paddle_tpu.testing import faults
from paddle_tpu.utils.retry import (RetriesExhausted, RetryPolicy,
                                    retry_call)

pytestmark = pytest.mark.faults


def _mgr(tmp_path, **kw):
    kw.setdefault("install_sigterm_handler", False)
    return ElasticTrainState(str(tmp_path), **kw)


def _state(seed=0, n=16):
    return {"w": jnp.asarray(np.random.RandomState(seed).randn(n)
                             .astype(np.float32)),
            "step": jnp.asarray(seed, jnp.int32)}


def _template(n=16):
    return {"w": jax.ShapeDtypeStruct((n,), np.float32),
            "step": jax.ShapeDtypeStruct((), np.int32)}


# -- retry primitive -------------------------------------------------------
class TestRetry:
    def test_absorbs_transient_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 4:
                raise OSError("transient")
            return "ok"

        policy = RetryPolicy(max_attempts=4, sleep=lambda _t: None)
        assert retry_call(flaky, policy=policy) == "ok"
        assert len(calls) == 4

    def test_exhaustion_raises_with_cause(self):
        policy = RetryPolicy(max_attempts=2, sleep=lambda _t: None)
        with pytest.raises(RetriesExhausted) as ei:
            retry_call(lambda: (_ for _ in ()).throw(OSError("boom")),
                       policy=policy)
        assert isinstance(ei.value.__cause__, OSError)

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            retry_call(bad, policy=RetryPolicy(sleep=lambda _t: None))
        assert len(calls) == 1

    def test_deadline_cuts_retries_short(self):
        slept = []
        policy = RetryPolicy(max_attempts=10, base_delay=5.0, jitter=0.0,
                             deadline=1.0, sleep=slept.append)
        with pytest.raises(RetriesExhausted):
            retry_call(lambda: (_ for _ in ()).throw(OSError()),
                       policy=policy)
        assert not slept  # first 5s backoff already exceeds the deadline


# -- transient I/O errors on save (acceptance criterion 3) ----------------
class TestTransientIO:
    def test_three_transient_write_errors_absorbed(self, tmp_path):
        state = _state(3)
        path = str(tmp_path / "ck")
        with faults.fast_retries(max_attempts=4):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=3)
                save_sharded(state, path)  # no caller-visible failure
        assert len(fi.injected) == 3
        back = load_sharded(path)
        np.testing.assert_array_equal(back["w"], np.asarray(state["w"]))

    def test_persistent_write_errors_surface(self, tmp_path):
        with faults.fast_retries(max_attempts=3):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=99)
                with pytest.raises(RetriesExhausted):
                    save_sharded(_state(), str(tmp_path / "ck"))
        assert fi.write_count == 3

    def test_async_save_error_surfaces_via_wait(self, tmp_path):
        mgr = _mgr(tmp_path / "ck")
        with faults.fast_retries(max_attempts=2):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=99)
                mgr.save(1, _state(1))
                with pytest.raises(RetriesExhausted):
                    mgr.wait()
        # nothing committed: the staging dir never got promoted
        assert latest_checkpoint(str(tmp_path / "ck")) is None


# -- checksum verification (manifest v2) ----------------------------------
class TestChecksums:
    def test_flipped_byte_detected(self, tmp_path):
        path = str(tmp_path / "ck")
        save_sharded(_state(5), path)
        assert verify_sharded(path) == []
        faults.corrupt_shard(path, offset=-2)  # data byte, size unchanged
        problems = verify_sharded(path)
        assert len(problems) == 1 and "crc32" in problems[0]
        with pytest.raises(CheckpointCorruption):
            load_sharded(path)

    def test_truncated_shard_detected_by_size(self, tmp_path):
        path = str(tmp_path / "ck")
        save_sharded(_state(6), path)
        import glob
        shard = sorted(glob.glob(os.path.join(path, "*", "shard-*.npy")))[0]
        faults.truncate_file(shard, keep_bytes=8)
        problems = verify_sharded(path)
        assert problems and "size" in problems[0]

    def test_strict_false_demotes_to_warning(self, tmp_path):
        path = str(tmp_path / "ck")
        save_sharded(_state(7), path)
        faults.corrupt_shard(path, offset=-2)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            back = load_sharded(path, strict=False)
        assert any("failed verification" in str(x.message) for x in w)
        assert back["w"].shape == (16,)  # loaded despite the damage

    def test_torn_write_at_save_time_caught(self, tmp_path):
        # the injector truncates the shard write itself: the manifest then
        # records the INTENDED size/crc, so verification must flag it
        path = str(tmp_path / "ck")
        with faults.FaultInjector() as fi:
            fi.truncate_write(1, keep_bytes=8)
            save_sharded(_state(8), path)
        problems = verify_sharded(path)
        assert problems and "size" in problems[0]

    def test_v1_manifest_still_loads(self, tmp_path):
        path = str(tmp_path / "ck")
        state = _state(9)
        save_sharded(state, path)
        # rewrite the manifest as a pre-checksum v1 writer would have
        mpath = os.path.join(path, "manifest-p0.json")
        import json
        with open(mpath) as f:
            m = json.load(f)
        m["version"] = 1
        for entry in m["leaves"].values():
            for shard in entry["shards"]:
                shard.pop("crc32", None)
                shard.pop("bytes", None)
        with open(mpath, "w") as f:
            json.dump(m, f)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            back = load_sharded(path)
        assert any("no checksums" in str(x.message) for x in w)
        np.testing.assert_array_equal(back["w"], np.asarray(state["w"]))


# -- atomic commit + restore fallback chain -------------------------------
class TestRestoreFallback:
    def test_corrupt_newest_falls_back_and_quarantines(self, tmp_path):
        """Acceptance criterion 1: flipped bit → quarantine → resume from
        the previous committed step."""
        d = str(tmp_path / "ck")
        mgr = _mgr(d, save_interval_steps=2, keep=4)
        states = {s: _state(s) for s in (2, 4)}
        for s in (2, 4):
            mgr.save(s, states[s], use_async=False)
        faults.corrupt_shard(os.path.join(d, "step-4"), offset=-2)

        restored, start = mgr.restore_or(lambda: None, _template)
        assert start == 3  # resumed after step 2, not 4
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(states[2]["w"]))
        names = set(os.listdir(d))
        assert "step-4.corrupt" in names and "step-4" not in names

    def test_truncated_manifest_falls_back(self, tmp_path):
        d = str(tmp_path / "ck")
        mgr = _mgr(d, save_interval_steps=1, keep=4)
        for s in (1, 2):
            mgr.save(s, _state(s), use_async=False)
        faults.corrupt_manifest(os.path.join(d, "step-2"))
        restored, start = mgr.restore_or(lambda: None, _template)
        assert start == 2
        assert "step-2.corrupt" in os.listdir(d)

    def test_every_checkpoint_corrupt_falls_to_init(self, tmp_path):
        d = str(tmp_path / "ck")
        mgr = _mgr(d, keep=4)
        for s in (1, 2):
            mgr.save(s, _state(s), use_async=False)
        for s in (1, 2):
            faults.corrupt_shard(os.path.join(d, f"step-{s}"), offset=-2)
        state, start = mgr.restore_or(lambda: {"fresh": True}, _template)
        assert start == 0 and state == {"fresh": True}
        assert committed_checkpoints(d) == []

    def test_failed_save_leaves_no_committed_step(self, tmp_path):
        d = str(tmp_path / "ck")
        mgr = _mgr(d)
        with faults.fast_retries(max_attempts=2):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=99)
                with pytest.raises(RetriesExhausted):
                    mgr.save(3, _state(3), use_async=False)
        assert latest_checkpoint(d) is None
        assert any(n.startswith("step-3.") and n.endswith(".tmp")
                   for n in os.listdir(d))  # staging dir only, never final

    def test_gc_sweeps_stale_debris(self, tmp_path):
        d = str(tmp_path / "ck")
        os.makedirs(os.path.join(d, "step-1.tmp"))
        # quarantines are bounded to the newest PTPU_CORRUPT_KEEP
        # (default 2) regardless of age (ISSUE 9) — the newest stay as
        # forensic evidence, older ones are swept
        os.makedirs(os.path.join(d, "step-0.corrupt"))
        os.makedirs(os.path.join(d, "step-2.corrupt"))
        os.makedirs(os.path.join(d, "step-4.corrupt"))
        os.makedirs(os.path.join(d, "step-3"))       # uncommitted crash
        os.makedirs(os.path.join(d, "step-9.tmp"))   # in-flight, newer
        mgr = _mgr(d, keep=2)
        mgr.save(5, _state(5), use_async=False)      # commit triggers gc
        names = set(os.listdir(d))
        assert "step-1.tmp" not in names
        assert "step-0.corrupt" not in names         # beyond the bound
        assert "step-2.corrupt" in names             # newest 2 kept
        assert "step-4.corrupt" in names
        assert "step-3" not in names
        assert "step-9.tmp" in names                 # never touch newer
        assert "step-5" in names


# -- SIGTERM / preemption --------------------------------------------------
class TestSigterm:
    def test_sigterm_mid_run_flushes_bitexact(self, tmp_path):
        """Acceptance criterion 2: SIGTERM mid-run → flushed checkpoint
        restores bit-exact."""
        d = str(tmp_path / "ck")
        orig = signal.getsignal(signal.SIGTERM)
        try:
            mgr = ElasticTrainState(d, save_interval_steps=1000,
                                    install_sigterm_handler=True)
            mgr._prev_handler = lambda *a: None  # don't kill pytest
            rng = np.random.RandomState(0)
            state = None
            for step in range(1, 6):
                state = {"w": jnp.asarray(rng.randn(16).astype(np.float32)),
                         "step": jnp.asarray(step, jnp.int32)}
                mgr.maybe_save(step, state)
                if step == 5:
                    os.kill(os.getpid(), signal.SIGTERM)  # preemption notice
            path = latest_checkpoint(d)
            assert path is not None and path.endswith("step-5")
            restored, start = _mgr(d).restore_or(lambda: None, _template)
            assert start == 6
            np.testing.assert_array_equal(np.asarray(restored["w"]),
                                          np.asarray(state["w"]))
        finally:
            signal.signal(signal.SIGTERM, orig)

    def test_sigterm_mid_save_still_commits(self, tmp_path):
        """Injector delivers SIGTERM while a save is writing shards; the
        handler re-enters save() and a committed checkpoint survives."""
        d = str(tmp_path / "ck")
        orig = signal.getsignal(signal.SIGTERM)
        try:
            mgr = ElasticTrainState(d, save_interval_steps=1000,
                                    install_sigterm_handler=True)
            mgr._prev_handler = lambda *a: None
            state = _state(11)
            mgr.maybe_save(11, state)
            with faults.FaultInjector() as fi:
                fi.sigterm_on_write(1)
                mgr.save(11, state, use_async=False)
            assert ("sigterm" in {k for _, k, _p in fi.injected})
            path = latest_checkpoint(d)
            assert path is not None and path.endswith("step-11")
            back = load_sharded(path, _template())
            np.testing.assert_array_equal(np.asarray(back["w"]),
                                          np.asarray(state["w"]))
        finally:
            signal.signal(signal.SIGTERM, orig)

    def test_sigterm_survives_failed_pending_async_save(self, tmp_path):
        """Satellite: a pending async save whose background thread failed
        must not abort the handler — the final sync flush still lands."""
        d = str(tmp_path / "ck")
        mgr = _mgr(d)
        state = _state(12)
        mgr.maybe_save(12, state)
        with faults.fast_retries(max_attempts=2):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=99)
                mgr.save(12, state)  # async; will fail in the background
                mgr._pending._thread.join()  # fail while faults are active
        mgr._prev_handler = lambda *a: None
        mgr._on_sigterm(signal.SIGTERM, None)  # must not raise
        path = latest_checkpoint(d)
        assert path is not None and path.endswith("step-12")


# -- resharded restore under injected faults ------------------------------
@pytest.mark.skipif(jax.device_count() < 8,
                    reason="needs the 8-device CPU mesh")
class TestReshardedRestoreUnderFaults:
    def test_dp4_mp2_to_dp2_mp4_with_corrupt_newest(self, tmp_path):
        def mesh(shape, names):
            devs = np.array(jax.devices()[: int(np.prod(shape))])
            return Mesh(devs.reshape(shape), names)

        m1, m2 = mesh((4, 2), ("dp", "mp")), mesh((2, 4), ("dp", "mp"))
        rng = np.random.RandomState(0)
        w = rng.randn(16, 8).astype(np.float32)
        d = str(tmp_path / "ck")
        mgr = _mgr(d, keep=4)
        # step 2: the good checkpoint, saved under dp4×mp2 with 3 transient
        # write errors injected (retry must absorb them)
        good = {"w": jax.device_put(w, NamedSharding(m1, P("dp", "mp")))}
        with faults.fast_retries(max_attempts=4):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=3)
                mgr.save(2, good, use_async=False)
        # step 4: newer but corrupted on disk
        mgr.save(4, {"w": jax.device_put(
            w + 1.0, NamedSharding(m1, P("dp", "mp")))}, use_async=False)
        faults.corrupt_shard(os.path.join(d, "step-4"), offset=-2)

        template = {"w": jax.ShapeDtypeStruct(
            (16, 8), np.float32, sharding=NamedSharding(m2, P(None, "mp")))}
        restored, start = mgr.restore_or(lambda: None, lambda: template)
        assert start == 3
        np.testing.assert_array_equal(np.asarray(restored["w"]), w)
        assert restored["w"].sharding.mesh.devices.shape == (2, 4)
        assert "step-4.corrupt" in os.listdir(d)


# -- framework.io atomic pickle save --------------------------------------
class TestAtomicPickleSave:
    def test_crash_mid_save_preserves_previous_file(self, tmp_path):
        path = str(tmp_path / "m.pdparams")
        framework.save({"w": jnp.ones(4)}, path)
        with faults.fast_retries(max_attempts=2):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=99)
                with pytest.raises(RetriesExhausted):
                    framework.save({"w": jnp.zeros(4)}, path)
        # the torn save never reached ``path`` — old contents intact
        back = framework.load(path)
        np.testing.assert_array_equal(np.asarray(back["w"]), np.ones(4))

    def test_transient_errors_absorbed(self, tmp_path):
        path = str(tmp_path / "m.pdparams")
        with faults.fast_retries(max_attempts=4):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=3)
                framework.save({"w": jnp.full((3,), 5.0)}, path)
        np.testing.assert_array_equal(
            np.asarray(framework.load(path)["w"]), np.full((3,), 5.0))

    def test_no_torn_file_visible_at_final_path(self, tmp_path):
        path = str(tmp_path / "fresh.pdparams")
        with faults.fast_retries(max_attempts=2):
            with faults.FaultInjector() as fi:
                fi.fail_writes(first=1, times=99)
                with pytest.raises(RetriesExhausted):
                    framework.save({"w": jnp.ones(2)}, path)
        assert not os.path.exists(path)  # absent beats unloadable


# -- reader retry ----------------------------------------------------------
class TestReaderRetry:
    def _flaky_reader(self, fail_at=3, fails=(2,)):
        attempts = {"n": 0}

        def reader():
            attempts["n"] += 1
            for i in range(6):
                if i == fail_at and attempts["n"] in fails:
                    raise OSError("transient fetch failure")
                yield i
        return reader, attempts

    def test_transient_fetch_absorbed_no_dup_no_loss(self):
        from paddle_tpu.reader import retry_reader
        reader, attempts = self._flaky_reader(fail_at=3, fails=(1, 2))
        robust = retry_reader(reader, max_attempts=3, sleep=lambda _t: None)
        assert list(robust()) == [0, 1, 2, 3, 4, 5]
        assert attempts["n"] == 3

    def test_budget_exhausted_raises(self):
        from paddle_tpu.reader import retry_reader
        reader, _ = self._flaky_reader(fail_at=3, fails=(1, 2, 3))
        robust = retry_reader(reader, max_attempts=3, sleep=lambda _t: None)
        with pytest.raises(OSError):
            list(robust())

    def test_batch_with_retries(self):
        from paddle_tpu.reader import batch
        reader, _ = self._flaky_reader(fail_at=4, fails=(1,))
        out = list(batch(reader, 2, retries=2)())
        assert out == [[0, 1], [2, 3], [4, 5]]

    def test_non_retryable_propagates(self):
        from paddle_tpu.reader import retry_reader

        def reader():
            yield 0
            raise ValueError("bad sample")

        with pytest.raises(ValueError):
            list(retry_reader(reader, sleep=lambda _t: None)())


# -- non-finite loss guard in hapi ----------------------------------------
class TestNonFiniteGuard:
    def _toy(self, budget):
        from paddle_tpu import nn
        from paddle_tpu.hapi import Model
        pt.seed(0)
        net = nn.Linear(4, 2)
        model = Model(net)
        model.prepare(optimizer=pt.optimizer.SGD(learning_rate=1e-2),
                      loss=lambda out, y: jnp.mean((out - y) ** 2),
                      nonfinite_skip_budget=budget)
        return model

    def _data(self, poison_row=2):
        rng = np.random.RandomState(0)
        x = rng.randn(6, 4).astype(np.float32)
        y = rng.randn(6, 2).astype(np.float32)
        x[poison_row] = np.nan  # one bad batch at batch_size=1
        from paddle_tpu.io import TensorDataset
        return TensorDataset([x, y])

    def test_bad_batch_skipped_run_stays_finite(self):
        model = self._toy(budget=2)
        history = model.fit(self._data(), batch_size=1, epochs=1,
                            shuffle=False, verbose=0)
        assert model._nonfinite_skipped == 1
        assert sum(1 for l in history["loss"] if not np.isfinite(l)) == 1
        for _, p in model.network.named_parameters():
            assert np.isfinite(np.asarray(p.value)).all()

    def test_skip_count_reaches_batch_logs(self):
        from paddle_tpu.hapi import Callback
        seen = []

        class Rec(Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.append(logs.get("nonfinite_skipped"))

        model = self._toy(budget=2)
        model.fit(self._data(), batch_size=1, epochs=1, shuffle=False,
                  verbose=0, callbacks=[Rec()])
        assert seen[-1] == 1 and seen[0] == 0

    def test_budget_exhaustion_raises(self):
        model = self._toy(budget=0)
        x = np.full((1, 4), np.nan, np.float32)
        y = np.zeros((1, 2), np.float32)
        with pytest.raises(FloatingPointError):
            model.train_batch([x], [y])

    def test_guard_off_keeps_legacy_behavior(self):
        model = self._toy(budget=None)
        x = np.full((1, 4), np.nan, np.float32)
        y = np.zeros((1, 2), np.float32)
        loss, _ = model.train_batch([x], [y])  # no raise, update applies
        assert not np.isfinite(loss)


# -- lint: no new bare excepts --------------------------------------------
class TestBareExceptLint:
    def test_package_is_clean(self):
        out = subprocess.run(
            [sys.executable, "tools/lint_bare_except.py"],
            capture_output=True, text=True, cwd="/root/repo")
        assert out.returncode == 0, out.stdout + out.stderr

    def test_linter_catches_bare_except(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("try:\n    pass\nexcept:\n    pass\n")
        out = subprocess.run(
            [sys.executable, "/root/repo/tools/lint_bare_except.py",
             str(tmp_path)],
            capture_output=True, text=True)
        assert out.returncode == 1
        assert "bad.py:3" in out.stdout
