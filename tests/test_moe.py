"""MoE / expert-parallel tests (BASELINE config #5).

Mirrors the reference's MoE test doctrine: dispatch correctness against a
dense recomputation, capacity-limit semantics (_limit_by_capacity), and the
expert-parallel == serial invariant on the virtual mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.moe import (MoELayer, global_gather,
                                        global_scatter, gshard_gating,
                                        limit_by_capacity, switch_gating)

from paddle_tpu.distributed.sequence_parallel import shard_map

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.set_hybrid_communicate_group(None)


class TestGating:
    def test_limit_by_capacity(self):
        mask = jnp.asarray([[1, 0], [1, 0], [1, 0], [0, 1]], jnp.float32)
        kept, pos = limit_by_capacity(mask, capacity=2)
        # third token to expert 0 dropped
        np.testing.assert_array_equal(
            np.asarray(kept), [[1, 0], [1, 0], [0, 0], [0, 1]])
        assert pos[0, 0] == 0 and pos[1, 0] == 1 and pos[3, 1] == 0

    def test_switch_dispatch_reconstructs_top1(self):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(16, 4), jnp.float32)
        cap = 16  # no dropping
        dispatch, combine, aux = switch_gating(logits, cap)
        probs = jax.nn.softmax(logits, -1)
        top1 = np.argmax(np.asarray(probs), -1)
        # each token dispatched exactly once, to its argmax expert
        sums = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        np.testing.assert_array_equal(sums, np.ones(16))
        chosen = np.argmax(np.asarray(jnp.sum(dispatch, axis=2)), -1)
        np.testing.assert_array_equal(chosen, top1)
        # combine weight = gate prob of the chosen expert
        g = np.asarray(jnp.sum(combine, axis=(1, 2)))
        np.testing.assert_allclose(
            g, np.asarray(probs)[np.arange(16), top1], rtol=1e-6)
        assert float(aux) > 0

    def test_gshard_top2_weights_normalized(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(32, 4), jnp.float32)
        dispatch, combine, aux = gshard_gating(logits, capacity=32)
        # two slots per token, combine weights sum to 1
        np.testing.assert_array_equal(
            np.asarray(jnp.sum(dispatch, axis=(1, 2))), 2 * np.ones(32))
        np.testing.assert_allclose(
            np.asarray(jnp.sum(combine, axis=(1, 2))), np.ones(32),
            rtol=1e-5)

    def test_capacity_drops_overflow(self):
        # all tokens to expert 0 → only `cap` survive
        logits = jnp.tile(jnp.asarray([[10.0, 0.0]]), (8, 1))
        dispatch, combine, _ = switch_gating(logits, capacity=3)
        assert float(jnp.sum(dispatch)) == 3.0


class TestGlobalScatterGather:
    def test_roundtrip_places_tokens_on_expert_ranks(self):
        """global_scatter then global_gather is the identity, and scatter
        really moves expert e's bucket onto rank e // (E/world)."""
        devs = np.array(jax.devices()[:4])
        mesh = Mesh(devs, ("ep",))
        E, C, H = 8, 3, 5
        rng = np.random.RandomState(0)
        # per-rank buckets: x[rank] is (E, C, H)
        x = jnp.asarray(rng.randn(4, E, C, H), jnp.float32)

        @jax.jit
        def run(x):
            def inner(xs):
                xs = xs[0]                      # (E, C, H) this rank
                sc = global_scatter(xs, "ep")   # (E/4, 4*C, H)
                back = global_gather(sc, "ep")  # (E, C, H)
                return sc[None], back[None]
            return shard_map(
                inner, mesh=mesh, in_specs=P("ep"),
                out_specs=(P("ep"), P("ep")))(x)

        sc, back = run(x)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                                   rtol=1e-6)
        # rank r holds experts [2r, 2r+1]; its scattered rows for local
        # expert 0 grouped by source rank
        sc = np.asarray(sc)                     # (4, E/4, 4*C, H)
        for r in range(4):
            for src in range(4):
                np.testing.assert_allclose(
                    sc[r, 0, src * C:(src + 1) * C],
                    np.asarray(x)[src, 2 * r], rtol=1e-6)


class TestMoELayerParallel:
    def _layer(self, E=4):
        pt.seed(11)
        return MoELayer(16, 32, E, gate="gshard", capacity_factor=2.0)

    def test_ep_parallel_matches_serial(self):
        """The §4 invariant for EP: same layer, serial vs ep=4 mesh."""
        layer = self._layer()
        params = layer.state_dict()
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(2, 8, 16), jnp.float32)
        out_s, aux_s = layer.apply(params, x, method="forward_with_aux")

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "ep_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        fleet.distributed_model(layer)
        params_d = layer.state_dict()
        assert params_d["experts.w1"].sharding.spec == P("ep", None, None)
        xd = dist.shard_batch(x)
        out_p, aux_p = jax.jit(
            lambda v, xx: layer.apply(v, xx, method="forward_with_aux")
        )(params_d, xd)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_s),
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(float(aux_p), float(aux_s), rtol=1e-5)

    def test_grads_match_serial(self):
        layer = self._layer()
        params = layer.state_dict()
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(2, 8, 16), jnp.float32)

        def loss(p, xx):
            out, aux = layer.apply(p, xx, method="forward_with_aux")
            return jnp.sum(out ** 2) + 0.01 * aux

        g_s = jax.grad(loss)(params, x)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "ep_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        fleet.distributed_model(layer)
        params_d = layer.state_dict()
        g_p = jax.jit(jax.grad(loss))(params_d, dist.shard_batch(x))
        for k in g_s:
            np.testing.assert_allclose(np.asarray(g_p[k]),
                                       np.asarray(g_s[k]),
                                       rtol=5e-4, atol=5e-6, err_msg=k)


class TestGPTMoE:
    def test_moe_gpt_trains_on_hybrid_mesh(self):
        """Config #5: GPT with MoE FFN layers trains (finite, decreasing
        loss) on a dp×ep mesh, aux loss included."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        pt.seed(21)
        cfg = GPTConfig(hidden_size=64, num_layers=2, num_heads=4,
                        max_position_embeddings=128, vocab_size=512,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        moe_num_experts=4, moe_every=2)
        model = GPTForCausalLM(cfg)
        model.train()
        # layer 1 is MoE, layer 0 dense
        from paddle_tpu.distributed.moe import MoELayer as _M
        assert isinstance(model.gpt.h[1].mlp, _M)
        assert not isinstance(model.gpt.h[0].mlp, _M)

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "ep_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        fleet.distributed_model(model)
        params = model.state_dict()
        opt = pt.optimizer.AdamW(learning_rate=1e-3)
        state = opt.init(params)
        rng = np.random.RandomState(0)
        ids = dist.shard_batch(
            rng.randint(0, 512, (8, 32)).astype(np.int32))

        from paddle_tpu.framework import random as fw_random

        def step(p, s, key):
            def loss_fn(q):
                with fw_random.key_scope(key):
                    loss, _ = model.apply(q, ids, labels=ids)
                return loss
            loss, grads = jax.value_and_grad(loss_fn)(p)
            p2, s2 = opt.apply_gradients(grads, p, s)
            return loss, p2, s2

        jitted = jax.jit(step)
        losses = []
        for i in range(5):
            loss, params, state = jitted(
                params, state, jax.random.fold_in(jax.random.key(0), i))
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses), losses
        assert losses[-1] < losses[0], losses


class TestMoEComposition:
    def test_moe_with_recompute_trains(self):
        """Regression: the aux side-channel must cross jax.checkpoint as a
        remat output, not leak a tracer (use_recompute is the documented
        enabler for 1.3B+ configs, so MoE + recompute must train)."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.framework import random as fw_random
        pt.seed(31)
        cfg = GPTConfig(hidden_size=64, num_layers=2, num_heads=4,
                        max_position_embeddings=128, vocab_size=512,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        moe_num_experts=2, moe_every=2, use_recompute=True)
        model = GPTForCausalLM(cfg)
        model.train()
        params = model.state_dict()
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, 512, (2, 16)), jnp.int32)

        def loss_fn(p):
            with fw_random.key_scope(jax.random.key(0)):
                loss, _ = model.apply(p, ids, labels=ids)
            return loss

        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
        assert np.isfinite(float(loss))
        g = grads["gpt.h.1.mlp.gate_weight"]
        assert float(jnp.max(jnp.abs(g))) > 0

    def test_pipeline_includes_aux_loss(self):
        """MoE × pp: with one micro-batch the pipelined aux equals the
        serial full-batch aux, so total losses must match exactly; and the
        aux term must actually move the loss."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.framework import random as fw_random
        pt.seed(33)
        kw = dict(hidden_size=64, num_layers=2, num_heads=4,
                  max_position_embeddings=128, vocab_size=512,
                  hidden_dropout=0.0, attention_dropout=0.0,
                  moe_num_experts=4, moe_every=1)  # homogeneous MoE trunk
        model = GPTForCausalLM(GPTConfig(**kw))
        model.train()
        params = model.state_dict()
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, 512, (4, 16)), jnp.int32)
        key = jax.random.key(0)

        def serial_loss(p):
            with fw_random.key_scope(key):
                loss, _ = model.apply(p, ids, labels=ids)
            return loss
        loss_s = float(serial_loss(params))

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                                   "pp_degree": 2, "ep_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": 1}
        fleet.init(is_collective=True, strategy=strategy)
        pipe = fleet.distributed_model(model)
        state = pipe.place_state(pipe.split_state(params))
        loss_p, grads = jax.jit(pipe.loss_and_grads)(
            state, dist.shard_batch(ids), dist.shard_batch(ids), key)
        np.testing.assert_allclose(float(loss_p), loss_s, rtol=2e-5)
        # aux really contributes: zero-weight variant gives a lower loss
        pipe0 = model.build_pipeline(2, 1)
        pipe0.config = None  # guard: not used after this point
        model.config.moe_aux_weight = 0.0
        pipe0 = model.build_pipeline(2, 1)
        loss0, _ = jax.jit(pipe0.loss_and_grads)(
            state, dist.shard_batch(ids), dist.shard_batch(ids), key)
        model.config.moe_aux_weight = 0.01
        assert float(loss_p) > float(loss0)
