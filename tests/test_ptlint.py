"""ptlint engine tests (ISSUE 12): seeded-bug fixtures per pass, the
noqa / ``# guarded_by:`` annotation grammar, the baseline workflow, the
deprecation shims, and the whole-repo smoke (the package itself must be
clean against the checked-in baseline)."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.ptlint import (Project, load_baseline, new_findings,  # noqa: E402
                          run_passes, write_baseline)
from tools.ptlint.__main__ import main as ptlint_main  # noqa: E402

pytestmark = pytest.mark.ptlint


def _lint(tmp_path, source, passes, docs="", name="snippet.py"):
    """Write one fixture module + docs file, lint it, return findings."""
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    docs_path = tmp_path / "ARCH.md"
    docs_path.write_text(docs)
    project = Project([str(path)], repo_root=str(tmp_path),
                      docs_path=str(docs_path))
    return run_passes(project, passes)


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------
class TestTraceSafety:
    def test_impure_helper_names_the_jit_entry(self, tmp_path):
        fs = _lint(tmp_path, """
            import time
            import jax

            def helper(x):
                return x + time.time()

            def step(x):
                return helper(x) * 2

            fast = jax.jit(step)
        """, ["trace"])
        assert len(fs) == 1
        f = fs[0]
        assert f.pass_name == "trace" and f.code == "impure-call"
        assert "time.time" in f.message
        # the finding must name the jit entry whose trace is poisoned,
        # not just the helper the impurity sits in
        assert "helper" in f.message and "::step" in f.message
        assert "jax.jit" in f.message

    def test_decorator_form_env_read_and_rng(self, tmp_path):
        fs = _lint(tmp_path, """
            import os
            import numpy as np
            import jax

            @jax.jit
            def step(x):
                scale = float(os.environ.get("SCALE", "1"))
                noise = np.random.randn(4)
                return x * scale + noise
        """, ["trace"])
        codes = sorted((f.code, f.message.split("`")[1]) for f in fs)
        assert ("impure-call", "os.environ.get()") in codes
        assert any("np.random.randn" in m for _c, m in codes)

    def test_pallas_kernel_body_print(self, tmp_path):
        fs = _lint(tmp_path, """
            from jax.experimental import pallas as pl

            def kernel(x_ref, o_ref):
                print("dbg")
                o_ref[...] = x_ref[...]

            def run(x):
                return pl.pallas_call(kernel, out_shape=x)(x)
        """, ["trace"])
        assert len(fs) == 1
        assert "print()" in fs[0].message
        assert "pallas_call" in fs[0].message and "kernel" in fs[0].message

    def test_concretization_is_a_warning(self, tmp_path):
        fs = _lint(tmp_path, """
            import jax

            @jax.jit
            def step(x):
                return x.mean().item()
        """, ["trace"])
        assert len(fs) == 1
        assert fs[0].code == "concretize"
        assert fs[0].severity == "warning"

    def test_global_mutation(self, tmp_path):
        fs = _lint(tmp_path, """
            import jax

            _CALLS = 0

            @jax.jit
            def step(x):
                global _CALLS
                _CALLS += 1
                return x
        """, ["trace"])
        assert len(fs) == 1
        assert fs[0].code == "global-mutation" and "_CALLS" in fs[0].message

    def test_defvjp_bodies_are_roots(self, tmp_path):
        fs = _lint(tmp_path, """
            import os
            import jax

            @jax.custom_vjp
            def op(x):
                return x * 2

            def op_fwd(x):
                if os.environ.get("PTPU_DEBUG"):
                    pass
                return op(x), x

            def op_bwd(res, g):
                return (g,)

            op.defvjp(op_fwd, op_bwd)
        """, ["trace"])
        assert any(f.code == "impure-call" and "op_fwd" in f.message
                   for f in fs)

    def test_unreachable_impurity_is_not_flagged(self, tmp_path):
        fs = _lint(tmp_path, """
            import time
            import jax

            def host_side():
                return time.time()

            @jax.jit
            def step(x):
                return x * 2
        """, ["trace"])
        assert fs == []

    def test_noqa_suppresses(self, tmp_path):
        fs = _lint(tmp_path, """
            import time
            import jax

            @jax.jit
            def step(x):
                t = time.time()  # noqa: trace — fixture: deliberate
                return x + t
        """, ["trace"])
        assert fs == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------
_RACY = """
    import threading

    class Worker:
        def __init__(self):
            self.count = 0
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            while True:
                self.count += 1

        def bump(self):
            self.count += 1
"""


class TestLockDiscipline:
    def test_dual_write_names_attr_and_both_contexts(self, tmp_path):
        fs = _lint(tmp_path, _RACY, ["locks"])
        assert len(fs) == 1
        f = fs[0]
        assert f.code == "unguarded-field"
        # names the attribute AND both access contexts
        assert "self.count" in f.message and "Worker" in f.message
        assert "_run" in f.message and "bump" in f.message
        assert "guarded_by" in f.message

    def test_thread_only_helper_is_not_dual(self, tmp_path):
        fs = _lint(tmp_path, """
            import threading

            class Worker:
                def __init__(self):
                    self.ticks = 0

                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    while True:
                        self._tick()

                def _tick(self):
                    self.ticks += 1
        """, ["locks"])
        assert fs == []

    def test_thread_subclass_run(self, tmp_path):
        fs = _lint(tmp_path, """
            import threading

            class Beater(threading.Thread):
                def __init__(self):
                    super().__init__(daemon=True)
                    self.beats = 0

                def run(self):
                    while True:
                        self.beats += 1

                def poke(self):
                    self.beats += 1
        """, ["locks"])
        assert [f.symbol for f in fs] == ["Beater.beats"]

    def test_guarded_by_annotation_and_lexical_lock(self, tmp_path):
        fs = _lint(tmp_path, """
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0  # guarded_by: _lock

                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    with self._lock:
                        self.count += 1

                def bump(self):
                    with self._lock:
                        self.count += 1

                def sloppy(self):
                    self.count += 1
        """, ["locks"])
        # annotation kills the unguarded-field finding; the one access
        # outside `with self._lock:` is the only violation left
        assert len(fs) == 1
        f = fs[0]
        assert f.code == "unlocked-access"
        assert "self.count" in f.message and "_lock" in f.message
        assert "sloppy" in f.message

    def test_noqa_locks_suppresses(self, tmp_path):
        fs = _lint(tmp_path, """
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0

                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    self.count += 1  # noqa: locks — fixture: display only

                def bump(self):
                    self.count += 1
        """, ["locks"])
        assert fs == []

    def test_nested_thread_body_with_self_alias(self, tmp_path):
        fs = _lint(tmp_path, """
            import threading

            class Saver:
                def __init__(self):
                    self.commits = 0

                def save(self):
                    mgr = self

                    def _finish():
                        mgr.commits += 1

                    threading.Thread(target=_finish).start()

                def note(self):
                    self.commits += 1
        """, ["locks"])
        assert [f.symbol for f in fs] == ["Saver.commits"]
        assert "_finish" in fs[0].message


# ---------------------------------------------------------------------------
# env-knob inventory
# ---------------------------------------------------------------------------
class TestKnobInventory:
    SRC = """
        import os
        INTERVAL = float(os.environ.get("PTPU_FIXTURE_KNOB", "5"))
    """

    def test_undocumented_knob(self, tmp_path):
        fs = _lint(tmp_path, self.SRC, ["knobs"], docs="no tables here")
        assert [f.symbol for f in fs] == ["PTPU_FIXTURE_KNOB"]
        assert "PTPU_FIXTURE_KNOB" in fs[0].message

    def test_documented_knob_passes(self, tmp_path):
        fs = _lint(tmp_path, self.SRC, ["knobs"],
                   docs="| `PTPU_FIXTURE_KNOB` | 5 | fixture interval |")
        assert fs == []

    def test_substring_of_longer_knob_does_not_count(self, tmp_path):
        # PTPU_FIXTURE_KNOB must not ride on PTPU_FIXTURE_KNOB_MAX
        fs = _lint(tmp_path, self.SRC, ["knobs"],
                   docs="| `PTPU_FIXTURE_KNOB_MAX` | 9 | something else |")
        assert [f.symbol for f in fs] == ["PTPU_FIXTURE_KNOB"]

    def test_noqa_knobs_suppresses(self, tmp_path):
        fs = _lint(tmp_path, """
            import os
            X = os.environ.get("PTPU_SECRET_HOOK")  # noqa: knobs — internal
        """, ["knobs"], docs="")
        assert fs == []


# ---------------------------------------------------------------------------
# absorbed legacy lints
# ---------------------------------------------------------------------------
class TestAbsorbedLints:
    def test_bare_except(self, tmp_path):
        fs = _lint(tmp_path, """
            def f():
                try:
                    risky()
                except:
                    pass
        """, ["bare_except"])
        assert [f.code for f in fs] == ["bare-except"]

    def test_swallow_and_noqa(self, tmp_path):
        src = """
            def f():
                try:
                    risky()
                except Exception:
                    pass{noqa}
        """
        assert [f.code for f in
                _lint(tmp_path, src.format(noqa=""), ["bare_except"])] \
            == ["swallow"]
        assert _lint(tmp_path,
                     src.format(noqa="  # noqa: swallow — fixture"),
                     ["bare_except"]) == []

    def test_print_and_noqa(self, tmp_path):
        src = """
            def f():
                print("hello"){noqa}
        """
        assert [f.code for f in
                _lint(tmp_path, src.format(noqa=""), ["print"])] == ["print"]
        assert _lint(tmp_path, src.format(noqa="  # noqa: print — fixture"),
                     ["print"]) == []

    def test_fsio_write_open_and_replace(self, tmp_path):
        fs = _lint(tmp_path, """
            import os

            def f(path):
                data = open(path).read()          # read mode: fine
                with open(path, "w") as fh:       # raw write: flagged
                    fh.write(data)
                os.replace(path + ".tmp", path)   # flagged
                os.replace(path, path + ".bak")   # noqa: fsio — fixture
        """, ["fsio"])
        assert sorted(f.code for f in fs) == ["open-write", "os-replace"]


# ---------------------------------------------------------------------------
# engine: baseline workflow + CLI
# ---------------------------------------------------------------------------
class TestBaselineWorkflow:
    def test_baselined_findings_do_not_fail(self, tmp_path):
        fs = _lint(tmp_path, "def f():\n    print('x')\n", ["print"])
        assert len(fs) == 1
        base = tmp_path / "baseline.json"
        write_baseline(fs, str(base))
        assert new_findings(fs, load_baseline(str(base))) == []
        # a NEW finding (different symbol) still fails
        fs2 = _lint(tmp_path, "def f():\n    print('x')\n"
                              "def g():\n    print('y')\n", ["print"])
        fresh = new_findings(fs2, load_baseline(str(base)))
        assert [f.symbol for f in fresh] == ["g"]

    def test_fingerprints_are_line_free(self, tmp_path):
        fs1 = _lint(tmp_path, "def f():\n    print('x')\n", ["print"])
        fs2 = _lint(tmp_path, "\n\n\ndef f():\n    print('x')\n", ["print"])
        assert fs1[0].line != fs2[0].line
        assert fs1[0].fingerprint == fs2[0].fingerprint

    def test_syntax_error_is_a_parse_finding(self, tmp_path):
        fs = _lint(tmp_path, "def broken(:\n", ["print"])
        assert [f.pass_name for f in fs] == ["parse"]


class TestCli:
    def test_json_output(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("def f():\n    print('x')\n")
        rc = ptlint_main(["--pass", "print", "--no-baseline", "--json",
                          str(bad)])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["new"] == 1
        (f,) = payload["findings"]
        assert f["pass"] == "print" and f["line"] == 2 and f["new"]

    def test_unknown_pass_is_a_usage_error(self, tmp_path, capsys):
        assert ptlint_main(["--pass", "nope", str(tmp_path)]) == 2

    def test_shims_reexec_the_engine(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text('with open("f", "w") as fh:\n    fh.write("x")\n')
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_fsio.py"),
             str(tmp_path)],
            capture_output=True, text=True, cwd=str(tmp_path))
        assert out.returncode == 1, out.stderr
        assert "bad.py:1" in out.stdout
        assert "ptlint" in out.stderr  # the deprecation note


# ---------------------------------------------------------------------------
# whole-repo smoke: the package itself is clean against the baseline
# ---------------------------------------------------------------------------
class TestRepoClean:
    def test_package_passes_all_passes(self):
        out = subprocess.run(
            [sys.executable, "-m", "tools.ptlint", "--all"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, f"\n{out.stdout}\n{out.stderr}"
