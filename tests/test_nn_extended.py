"""Extended nn surface tests: torch golden parity for conv_transpose /
conv3d / CTC / distance-losses, shape checks for the rest, a seq2seq
Transformer smoke train.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

torch = pytest.importorskip("torch")
R = np.random.RandomState(0)


class TestConvFamily:
    @pytest.mark.parametrize("stride,padding,output_padding", [
        (1, 0, 0), (2, 1, 0), (2, 1, 1), (3, 2, 1)])
    def test_conv2d_transpose_matches_torch(self, stride, padding,
                                            output_padding):
        x = R.randn(2, 3, 8, 8).astype(np.float32)
        w = R.randn(3, 4, 3, 3).astype(np.float32)   # (in, out, kh, kw)
        b = R.randn(4).astype(np.float32)
        want = torch.nn.functional.conv_transpose2d(
            torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
            stride=stride, padding=padding,
            output_padding=output_padding).numpy()
        got = np.asarray(F.conv2d_transpose(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), stride=stride,
            padding=padding, output_padding=output_padding))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_conv2d_transpose_grouped(self):
        x = R.randn(1, 4, 6, 6).astype(np.float32)
        w = R.randn(4, 2, 3, 3).astype(np.float32)   # groups=2: out=4
        want = torch.nn.functional.conv_transpose2d(
            torch.from_numpy(x), torch.from_numpy(w), stride=2,
            padding=1, groups=2).numpy()
        got = np.asarray(F.conv2d_transpose(
            jnp.asarray(x), jnp.asarray(w), stride=2, padding=1, groups=2))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_conv3d_matches_torch(self):
        x = R.randn(2, 3, 5, 6, 7).astype(np.float32)
        w = R.randn(4, 3, 3, 3, 3).astype(np.float32)
        b = R.randn(4).astype(np.float32)
        want = torch.nn.functional.conv3d(
            torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
            stride=2, padding=1).numpy()
        got = np.asarray(F.conv3d(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), stride=2, padding=1))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_conv_layers_shapes(self):
        pt.seed(0)
        y = nn.Conv1D(3, 8, 3, padding=1)(jnp.zeros((2, 3, 16)))
        assert y.shape == (2, 8, 16)
        y = nn.Conv3D(2, 4, 3, padding=1)(jnp.zeros((1, 2, 4, 4, 4)))
        assert y.shape == (1, 4, 4, 4, 4)
        y = nn.Conv2DTranspose(4, 6, 4, stride=2, padding=1)(
            jnp.zeros((1, 4, 8, 8)))
        assert y.shape == (1, 6, 16, 16)
        # output_size derives the output padding (paddle call form)
        deconv = nn.Conv2DTranspose(4, 6, 3, stride=2, padding=1)
        assert deconv(jnp.zeros((1, 4, 5, 5))).shape == (1, 6, 9, 9)
        assert deconv(jnp.zeros((1, 4, 5, 5)),
                      output_size=(10, 10)).shape == (1, 6, 10, 10)


class TestPoolNormAct:
    def test_pool1d(self):
        x = jnp.asarray(R.randn(2, 3, 16), jnp.float32)
        assert nn.MaxPool1D(2)(x).shape == (2, 3, 8)
        assert nn.AvgPool1D(4, stride=4)(x).shape == (2, 3, 4)
        got = np.asarray(nn.MaxPool1D(2)(x))
        want = torch.nn.functional.max_pool1d(
            torch.from_numpy(np.asarray(x)), 2).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_adaptive_max_pool(self):
        x = jnp.asarray(R.randn(2, 3, 8, 8), jnp.float32)
        got = np.asarray(nn.AdaptiveMaxPool2D((2, 2))(x))
        want = torch.nn.functional.adaptive_max_pool2d(
            torch.from_numpy(np.asarray(x)), (2, 2)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_adaptive_max_pool_non_divisible(self):
        # general path: output size does not divide input size
        for out_size in [(3, 3), (5, 2), (7, 6)]:
            x = jnp.asarray(R.randn(2, 3, 8, 9), jnp.float32)
            got = np.asarray(nn.AdaptiveMaxPool2D(out_size)(x))
            want = torch.nn.functional.adaptive_max_pool2d(
                torch.from_numpy(np.asarray(x)), out_size).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_instance_norm_matches_torch(self):
        x = R.randn(2, 4, 8, 8).astype(np.float32)
        pt.seed(0)
        inorm = nn.InstanceNorm2D(4)
        got = np.asarray(inorm(jnp.asarray(x)))
        want = torch.nn.functional.instance_norm(
            torch.from_numpy(x), weight=torch.ones(4),
            bias=torch.zeros(4)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_spectral_norm_unit_sigma(self):
        pt.seed(0)
        w = jnp.asarray(R.randn(8, 16), jnp.float32)
        sn = nn.SpectralNorm(w.shape, power_iters=20)
        sn.train()
        wn = sn(w)
        s = np.linalg.svd(np.asarray(wn), compute_uv=False)
        assert abs(s[0] - 1.0) < 1e-3

    def test_prelu_pixelshuffle_glu(self):
        pt.seed(0)
        x = jnp.asarray(R.randn(2, 4, 4, 4), jnp.float32)
        y = nn.PReLU(4, init=0.1)(x)
        np.testing.assert_allclose(
            np.asarray(y),
            np.where(np.asarray(x) >= 0, np.asarray(x), 0.1 * np.asarray(x)),
            rtol=1e-6)
        ps = nn.PixelShuffle(2)(x)
        assert ps.shape == (2, 1, 8, 8)
        back = nn.PixelUnshuffle(2)(ps)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x))
        want = torch.nn.functional.pixel_shuffle(
            torch.from_numpy(np.asarray(x)), 2).numpy()
        np.testing.assert_allclose(np.asarray(ps), want)
        g = nn.GLU(-1)(jnp.asarray(R.randn(2, 8), jnp.float32))
        assert g.shape == (2, 4)

    def test_upsample(self):
        x = jnp.asarray(R.randn(1, 2, 4, 4), jnp.float32)
        assert nn.Upsample(scale_factor=2)(x).shape == (1, 2, 8, 8)
        assert nn.UpsamplingBilinear2D(size=(6, 6))(x).shape == (1, 2, 6, 6)
        # UpsamplingBilinear2D is align_corners=True — torch golden
        want = torch.nn.functional.interpolate(
            torch.from_numpy(np.asarray(x)), size=(6, 6), mode="bilinear",
            align_corners=True).numpy()
        got = np.asarray(nn.UpsamplingBilinear2D(size=(6, 6))(x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        assert nn.Unflatten(1, (1, 2))(jnp.zeros((3, 2, 5))).shape \
            == (3, 1, 2, 5)
        assert nn.Identity()(x) is x
        # regression: UpsamplingNearest2D used to pass data_format into
        # align_corners positionally and raise on every forward
        got = np.asarray(nn.UpsamplingNearest2D(scale_factor=2)(x))
        want = torch.nn.functional.interpolate(
            torch.from_numpy(np.asarray(x)), scale_factor=2,
            mode="nearest").numpy()
        np.testing.assert_allclose(got, want)


class TestLosses:
    def test_kl_div_matches_torch(self):
        logp = torch.log_softmax(torch.randn(4, 5), dim=-1)
        target = torch.softmax(torch.randn(4, 5), dim=-1)
        want = torch.nn.functional.kl_div(logp, target,
                                          reduction="mean").item()
        got = float(F.kl_div(jnp.asarray(logp.numpy()),
                             jnp.asarray(target.numpy()), "mean"))
        assert abs(got - want) < 1e-5

    def test_margin_ranking_matches_torch(self):
        a, b = torch.randn(6), torch.randn(6)
        y = torch.sign(torch.randn(6)) + 0.0
        y[y == 0] = 1.0
        want = torch.nn.functional.margin_ranking_loss(
            a, b, y, margin=0.3).item()
        got = float(nn.MarginRankingLoss(margin=0.3)(
            jnp.asarray(a.numpy()), jnp.asarray(b.numpy()),
            jnp.asarray(y.numpy())))
        assert abs(got - want) < 1e-5

    def test_triplet_and_cosine_losses(self):
        a, p, n = (torch.randn(4, 8) for _ in range(3))
        want = torch.nn.functional.triplet_margin_loss(a, p, n).item()
        got = float(nn.TripletMarginLoss()(
            jnp.asarray(a.numpy()), jnp.asarray(p.numpy()),
            jnp.asarray(n.numpy())))
        assert abs(got - want) < 1e-4
        y = torch.tensor([1.0, -1.0, 1.0, -1.0])
        want = torch.nn.functional.cosine_embedding_loss(a, p, y).item()
        got = float(nn.CosineEmbeddingLoss()(
            jnp.asarray(a.numpy()), jnp.asarray(p.numpy()),
            jnp.asarray(y.numpy())))
        assert abs(got - want) < 1e-4

    @pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
    def test_ctc_loss_matches_torch(self, reduction):
        T, B, C, S = 12, 3, 6, 5
        g = torch.Generator().manual_seed(0)
        logits = torch.randn(T, B, C, generator=g)
        log_probs = torch.log_softmax(logits, dim=-1)
        labels = torch.randint(1, C, (B, S), generator=g)
        in_lens = torch.tensor([12, 10, 7])
        lab_lens = torch.tensor([5, 3, 2])
        want = torch.nn.functional.ctc_loss(
            log_probs, labels, in_lens, lab_lens, blank=0,
            reduction=reduction, zero_infinity=False)
        got = F.ctc_loss(jnp.asarray(log_probs.numpy()),
                         jnp.asarray(labels.numpy()),
                         jnp.asarray(in_lens.numpy()),
                         jnp.asarray(lab_lens.numpy()),
                         blank=0, reduction=reduction)
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_ctc_loss_repeated_labels(self):
        """Repeated labels exercise the no-skip rule (a-a needs a blank)."""
        T, B, C = 10, 2, 5
        g = torch.Generator().manual_seed(1)
        log_probs = torch.log_softmax(torch.randn(T, B, C, generator=g), -1)
        labels = torch.tensor([[2, 2, 3], [1, 1, 1]])
        in_lens = torch.tensor([10, 10])
        lab_lens = torch.tensor([3, 3])
        want = torch.nn.functional.ctc_loss(
            log_probs, labels, in_lens, lab_lens, blank=0,
            reduction="none")
        got = F.ctc_loss(jnp.asarray(log_probs.numpy()),
                         jnp.asarray(labels.numpy()),
                         jnp.asarray(in_lens.numpy()),
                         jnp.asarray(lab_lens.numpy()), reduction="none")
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_ctc_loss_zero_length_label(self):
        """Empty targets: loss is -sum log p(blank), no doubled path."""
        T, B, C = 6, 2, 4
        g = torch.Generator().manual_seed(2)
        log_probs = torch.log_softmax(torch.randn(T, B, C, generator=g), -1)
        labels = torch.tensor([[1, 2], [0, 0]])
        in_lens = torch.tensor([6, 6])
        lab_lens = torch.tensor([2, 0])
        want = torch.nn.functional.ctc_loss(
            log_probs, labels, in_lens, lab_lens, blank=0, reduction="none")
        got = F.ctc_loss(jnp.asarray(log_probs.numpy()),
                         jnp.asarray(labels.numpy()),
                         jnp.asarray(in_lens.numpy()),
                         jnp.asarray(lab_lens.numpy()), reduction="none")
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_ctc_loss_grad_finite(self):
        T, B, C, S = 8, 2, 4, 3
        logits = jnp.asarray(R.randn(T, B, C), jnp.float32)
        labels = jnp.asarray(R.randint(1, C, (B, S)))
        il = jnp.asarray([8, 6])
        ll = jnp.asarray([3, 2])

        def loss(lg):
            return F.ctc_loss(jax.nn.log_softmax(lg, -1), labels, il, ll)

        g = jax.grad(loss)(logits)
        assert np.all(np.isfinite(np.asarray(g)))


class TestMiscFunctional:
    def test_label_smooth(self):
        oh = jnp.asarray([[0.0, 1.0, 0.0, 0.0]])
        got = np.asarray(F.label_smooth(oh, epsilon=0.1))
        np.testing.assert_allclose(got, [[0.025, 0.925, 0.025, 0.025]],
                                   rtol=1e-6)
        prior = jnp.asarray([0.4, 0.3, 0.2, 0.1])
        got = np.asarray(F.label_smooth(oh, prior_dist=prior, epsilon=0.2))
        np.testing.assert_allclose(
            got, 0.8 * np.asarray(oh) + 0.2 * np.asarray(prior)[None],
            rtol=1e-6)

    def test_label_smooth_integer_one_hot(self):
        """Integer one-hots must promote to float (a 1/k prior would
        truncate to 0 in int dtype)."""
        oh = jnp.asarray([[0, 1, 0, 0]], jnp.int32)
        got = np.asarray(F.label_smooth(oh, epsilon=0.1))
        np.testing.assert_allclose(got.sum(), 1.0, rtol=1e-6)

    def test_square_error_cost(self):
        got = np.asarray(F.square_error_cost(jnp.asarray([1.0, 2.0]),
                                             jnp.asarray([3.0, 1.0])))
        np.testing.assert_allclose(got, [4.0, 1.0])

    def test_amp_dtype_probes(self):
        import paddle_tpu as pt2
        assert pt2.amp.is_bfloat16_supported() is True
        assert isinstance(pt2.amp.is_float16_supported(), bool)


class TestDistanceOps:
    def test_cosine_similarity_matches_torch(self):
        a, b = torch.randn(4, 8), torch.randn(4, 8)
        want = torch.nn.functional.cosine_similarity(a, b, dim=1).numpy()
        got = np.asarray(nn.CosineSimilarity(axis=1)(
            jnp.asarray(a.numpy()), jnp.asarray(b.numpy())))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_pairwise_distance_matches_torch(self):
        a, b = torch.randn(4, 8), torch.randn(4, 8)
        want = torch.nn.functional.pairwise_distance(a, b).numpy()
        got = np.asarray(nn.PairwiseDistance()(
            jnp.asarray(a.numpy()), jnp.asarray(b.numpy())))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestTransformer:
    def test_seq2seq_forward_and_causal_mask(self):
        pt.seed(0)
        model = nn.Transformer(d_model=32, nhead=4, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=64)
        model.eval()
        src = jnp.asarray(R.randn(2, 7, 32), jnp.float32)
        tgt = jnp.asarray(R.randn(2, 5, 32), jnp.float32)
        mask = nn.Transformer.generate_square_subsequent_mask(5)
        out = model(src, tgt, tgt_mask=mask)
        assert out.shape == (2, 5, 32)
        # causality: changing a later tgt step must not affect earlier outs
        tgt2 = tgt.at[:, 3].add(1.0)
        out2 = model(src, tgt2, tgt_mask=mask)
        np.testing.assert_allclose(np.asarray(out[:, :3]),
                                   np.asarray(out2[:, :3]),
                                   rtol=1e-4, atol=1e-5)
        assert not np.allclose(np.asarray(out[:, 3]), np.asarray(out2[:, 3]))

    def test_decoder_incremental_cache_matches_full(self):
        pt.seed(1)
        d = 16
        layer_fn = lambda: nn.TransformerDecoderLayer(d, 2, 32, dropout=0.0)
        dec = nn.TransformerDecoder(layer_fn, 2)
        dec.eval()
        memory = jnp.asarray(R.randn(1, 6, d), jnp.float32)
        tgt = jnp.asarray(R.randn(1, 4, d), jnp.float32)
        # the cached path is causal by construction, so the full pass
        # must mask the future too
        full = dec(tgt, memory,
                   tgt_mask=nn.Transformer.generate_square_subsequent_mask(4))
        # incremental: feed one token at a time with kv caches
        caches = [(jnp.zeros((1, 2, 0, d // 2)), jnp.zeros((1, 2, 0, d // 2)))
                  for _ in range(2)]
        outs = []
        for t in range(4):
            step_out, caches = dec(tgt[:, t:t + 1], memory, cache=caches)
            outs.append(step_out)
        inc = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(np.asarray(full), np.asarray(inc),
                                   rtol=1e-4, atol=1e-5)

    def test_transformer_trains(self):
        pt.seed(2)
        model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                               num_decoder_layers=1, dim_feedforward=32,
                               dropout=0.0)
        model.train()
        params = model.state_dict()
        opt = pt.optimizer.Adam(learning_rate=1e-3)
        state = opt.init(params)
        src = jnp.asarray(R.randn(4, 6, 16), jnp.float32)
        tgt = jnp.asarray(R.randn(4, 5, 16), jnp.float32)
        want = jnp.asarray(R.randn(4, 5, 16), jnp.float32)

        @jax.jit
        def step(p, s):
            def lf(q):
                out = model.apply(q, src, tgt)
                return jnp.mean((out - want) ** 2)
            loss, g = jax.value_and_grad(lf)(p)
            return (loss, *opt.apply_gradients(g, p, s))

        losses = []
        for _ in range(15):
            loss, params, state = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
