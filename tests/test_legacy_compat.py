"""Legacy compat namespaces: paddle.batch + reader decorators
(reference batch.py, reader/decorator.py), paddle.dataset facade,
paddle.callbacks, paddle.sysconfig, paddle.hub (local)."""
import os

import numpy as np

import paddle_tpu as pt


class TestBatchAndReader:
    def test_batch(self):
        r = pt.batch(lambda: iter(range(10)), batch_size=3)
        out = [len(b) for b in r()]
        assert out == [3, 3, 3, 1]
        r2 = pt.batch(lambda: iter(range(10)), batch_size=3,
                      drop_last=True)
        assert [len(b) for b in r2()] == [3, 3, 3]

    def test_shuffle_chain_firstn_cache(self):
        base = lambda: iter(range(20))  # noqa: E731
        s = sorted(pt.reader.shuffle(base, buf_size=8)())
        assert s == list(range(20))
        c = list(pt.reader.chain(lambda: iter([1, 2]),
                                 lambda: iter([3]))())
        assert c == [1, 2, 3]
        assert list(pt.reader.firstn(base, 5)()) == [0, 1, 2, 3, 4]
        calls = []

        def counting():
            calls.append(1)
            return iter([7, 8])

        cached = pt.reader.cache(counting)
        assert list(cached()) == [7, 8] and list(cached()) == [7, 8]
        assert len(calls) == 1

    def test_map_and_compose(self):
        a = lambda: iter([1, 2, 3])     # noqa: E731
        b = lambda: iter([10, 20, 30])  # noqa: E731
        m = list(pt.reader.map_readers(lambda x, y: x + y, a, b)())
        assert m == [11, 22, 33]
        z = list(pt.reader.compose(a, b)())
        assert z == [(1, 10), (2, 20), (3, 30)]

    def test_xmap_and_buffered(self):
        base = lambda: iter(range(5))   # noqa: E731
        assert list(pt.reader.xmap_readers(lambda x: x * 2, base, 2, 4)()) \
            == [0, 2, 4, 6, 8]
        assert list(pt.reader.buffered(base, 2)()) == [0, 1, 2, 3, 4]


class TestDatasetFacade:
    def test_mnist_reader_schema(self):
        r = pt.dataset.mnist.test()
        img, label = next(r())
        assert img.shape == (28, 28) and img.dtype == np.float32
        assert 0 <= label < 10
        batched = pt.batch(r, 16)
        first = next(batched())
        assert len(first) == 16

    def test_uci_housing(self):
        x, y = next(pt.dataset.uci_housing.train()())
        assert x.ndim == 1 and np.issubdtype(x.dtype, np.floating)


class TestMiscNamespaces:
    def test_callbacks_alias(self):
        assert pt.callbacks.EarlyStopping is not None
        from paddle_tpu.hapi.callbacks import EarlyStopping
        assert pt.callbacks.EarlyStopping is EarlyStopping

    def test_sysconfig(self):
        assert os.path.isdir(pt.sysconfig.get_include())
        assert isinstance(pt.sysconfig.get_lib(), str)

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny(n=2):\n    '''doc'''\n    return n * 2\n")
        assert "tiny" in pt.hub.list(str(tmp_path))
        assert pt.hub.help(str(tmp_path), "tiny") == "doc"
        assert pt.hub.load(str(tmp_path), "tiny", n=3) == 6

    def test_hub_remote_gated(self):
        import pytest
        from paddle_tpu.framework.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="egress"):
            pt.hub.list("github.com/some/repo")

    def test_dataset_submodule_import_idiom(self):
        # the dominant tutorial idiom must work
        import paddle_tpu.dataset.mnist as mnist_mod
        img, label = next(mnist_mod.test()())
        assert img.shape == (28, 28)
        import paddle_tpu.dataset.cifar as cifar_mod
        assert next(cifar_mod.train10()())[0].shape == (32, 32, 3)

    def test_compose_misalignment_raises_both_ways(self):
        import pytest
        a4 = lambda: iter([1, 2, 3, 4])   # noqa: E731
        b3 = lambda: iter([10, 20, 30])   # noqa: E731
        with pytest.raises(ValueError):
            list(pt.reader.compose(a4, b3)())
        with pytest.raises(ValueError):
            list(pt.reader.compose(b3, a4)())

    def test_stft_win_length_validation(self):
        import pytest
        import jax.numpy as jnp
        from paddle_tpu.framework.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="win_length"):
            pt.signal.stft(jnp.zeros(64), n_fft=16, win_length=32)
        with pytest.raises(InvalidArgumentError, match="win_length"):
            pt.signal.stft(jnp.zeros(64), n_fft=16, win_length=0)
