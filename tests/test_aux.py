"""Aux subsystem tests: nan/inf debug flag, vlog, launcher env wiring,
elastic auto-checkpoint (SURVEY §5 rows)."""
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.framework import debug
from paddle_tpu.framework.flags import get_flags, set_flags


class TestCheckNanInf:
    def test_finite_flags_and_raise(self):
        flags = debug.finite_flags(
            {"ok": jnp.ones(3), "bad": jnp.asarray([1.0, np.inf]),
             "nested": {"nan": jnp.asarray([np.nan])},
             "ints": jnp.arange(3)})
        assert bool(flags["ok"])
        assert not bool(flags["bad"])
        assert "ints" not in flags  # integer leaves skipped
        with pytest.raises(FloatingPointError, match="bad"):
            debug.assert_all_finite(flags, context="test")

    def test_hapi_train_raises_on_nan(self):
        from paddle_tpu import nn
        from paddle_tpu.hapi import Model
        set_flags({"check_nan_inf": True})
        try:
            pt.seed(0)
            net = nn.Sequential(nn.Linear(4, 4))
            model = Model(net)
            model.prepare(
                optimizer=pt.optimizer.SGD(learning_rate=1e30),
                loss=lambda out, y: jnp.sum(jnp.exp(out * 1e20)))
            x = np.ones((2, 4), np.float32)
            with pytest.raises(FloatingPointError):
                for _ in range(3):
                    model.train_batch([x], [x])
        finally:
            set_flags({"check_nan_inf": False})

    def test_hapi_train_clean_when_finite(self):
        from paddle_tpu import nn
        from paddle_tpu.hapi import Model
        set_flags({"check_nan_inf": True})
        try:
            pt.seed(0)
            net = nn.Sequential(nn.Linear(4, 4))
            model = Model(net)
            model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1),
                          loss=lambda out, y: jnp.mean((out - y) ** 2))
            x = np.ones((2, 4), np.float32)
            loss, _ = model.train_batch([x], [x])
            assert np.isfinite(loss)
        finally:
            set_flags({"check_nan_inf": False})


class TestVlog:
    def test_gated_by_flag(self, capsys):
        from paddle_tpu.framework.log import vlog
        set_flags({"log_level": 0})
        vlog(2, "hidden message")
        assert "hidden message" not in capsys.readouterr().err
        set_flags({"log_level": 2})
        try:
            vlog(2, "visible message")
            assert "visible message" in capsys.readouterr().err
        finally:
            set_flags({"log_level": 0})


class TestLauncherEnv:
    def test_init_from_env_wires_jax_args(self, monkeypatch):
        from paddle_tpu.distributed import launch as launch_mod
        captured = {}
        monkeypatch.setattr(
            jax.distributed, "initialize",
            lambda **kw: captured.update(kw))
        monkeypatch.setenv("PADDLE_MASTER", "127.0.0.1:1234")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
        launch_mod.init_from_env()
        assert captured == {"coordinator_address": "127.0.0.1:1234",
                            "num_processes": 4, "process_id": 2}

    def test_single_node_exec(self, tmp_path):
        script = tmp_path / "train.py"
        script.write_text("print('LAUNCH-OK', flush=True)\n")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             str(script)],
            capture_output=True, text=True, timeout=120,
            cwd="/root/repo")
        assert out.returncode == 0, out.stderr
        assert "LAUNCH-OK" in out.stdout


class TestElastic:
    def test_restore_or_fresh(self, tmp_path):
        from paddle_tpu.distributed.elastic import ElasticTrainState
        mgr = ElasticTrainState(str(tmp_path / "none"),
                                install_sigterm_handler=False)
        state, start = mgr.restore_or(lambda: {"w": jnp.ones(2)},
                                      lambda: None)
        assert start == 0
        np.testing.assert_array_equal(state["w"], np.ones(2))

    def test_interval_save_and_resume(self, tmp_path):
        from paddle_tpu.distributed.elastic import (ElasticTrainState,
                                                    latest_checkpoint)
        d = str(tmp_path / "ck")
        mgr = ElasticTrainState(d, save_interval_steps=2, keep=2,
                                install_sigterm_handler=False)
        state = {"w": jnp.zeros(3), "step": jnp.asarray(0)}
        for step in range(1, 6):
            state = {"w": state["w"] + 1.0, "step": jnp.asarray(step)}
            mgr.maybe_save(step, state)
        mgr.wait()
        assert latest_checkpoint(d).endswith("step-4")

        mgr2 = ElasticTrainState(d, install_sigterm_handler=False)
        template = {"w": jax.ShapeDtypeStruct((3,), np.float32),
                    "step": jax.ShapeDtypeStruct((), state["step"].dtype)}
        restored, start = mgr2.restore_or(lambda: None, lambda: template)
        assert start == 5
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      4.0 * np.ones(3))

    def test_sigterm_flushes_final_checkpoint(self, tmp_path):
        from paddle_tpu.distributed.elastic import (ElasticTrainState,
                                                    latest_checkpoint)
        d = str(tmp_path / "pre")
        mgr = ElasticTrainState(d, save_interval_steps=1000,
                                install_sigterm_handler=False)
        mgr.maybe_save(7, {"w": jnp.full((2,), 7.0)})
        # simulate the preemption notice without killing pytest
        mgr._prev_handler = lambda *a: None
        mgr._on_sigterm(signal.SIGTERM, None)
        path = latest_checkpoint(d)
        assert path is not None and path.endswith("step-7")


class TestWorkerInfo:
    def test_get_worker_info_in_workers(self):
        from paddle_tpu.io import DataLoader, Dataset, get_worker_info

        assert get_worker_info() is None  # main process

        class DS(Dataset):
            def __getitem__(self, i):
                wi = get_worker_info()
                assert wi is not None and wi.num_workers == 2
                return np.asarray([i, wi.id])

            def __len__(self):
                return 8

        loader = DataLoader(DS(), batch_size=4, num_workers=2)
        seen = set()
        for batch in loader:
            seen.update(np.asarray(batch)[:, 1].tolist())
        assert seen and seen <= {0, 1}

    def test_iterable_dataset_sees_single_worker_view(self):
        """The canonical get_worker_info() sharding pattern must work on
        the in-process IterableDataset path (one shard = the stream)."""
        from paddle_tpu.io import DataLoader, IterableDataset, \
            get_worker_info

        class Stream(IterableDataset):
            def __iter__(self):
                wi = get_worker_info()
                assert wi is not None
                for i in range(wi.id, 8, wi.num_workers):  # shard pattern
                    yield np.asarray([i])

        out = [int(np.asarray(b)[0]) for b in
               DataLoader(Stream(), batch_size=1, num_workers=2)]
        assert out == list(range(8))
        assert get_worker_info() is None   # restored after iteration


class TestNativeDataLoader:
    def test_ring_transport_matches_queue(self):
        """Same data through the native shm ring and the python queue
        (≙ the reference's shared-memory vs non-shared DataLoader modes)."""
        from paddle_tpu.io import DataLoader, TensorDataset
        from paddle_tpu.io.native import native_available
        if not native_available():
            pytest.skip("native core unavailable (no toolchain)")
        rng = np.random.RandomState(0)
        xs = rng.randn(64, 8).astype(np.float32)
        ys = rng.randint(0, 4, (64,)).astype(np.int64)
        ds = TensorDataset([xs, ys])

        def collect(use_native):
            set_flags({"dataloader_use_native": use_native})
            try:
                loader = DataLoader(ds, batch_size=16, num_workers=2,
                                    shuffle=False, to_device=False)
                return [jax.tree_util.tree_map(np.asarray, b)
                        for b in loader]
            finally:
                set_flags({"dataloader_use_native": True})

        native = collect(True)
        plain = collect(False)
        assert len(native) == len(plain) == 4
        for a, b in zip(native, plain):
            for x, y in zip(a, b):
                np.testing.assert_array_equal(x, y)

    def test_worker_error_propagates_through_ring(self):
        from paddle_tpu.io import DataLoader, Dataset
        from paddle_tpu.io.native import native_available
        if not native_available():
            pytest.skip("native core unavailable")

        class Bad(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom-5")
                return np.zeros(4, np.float32)

        loader = DataLoader(Bad(), batch_size=4, num_workers=2,
                            to_device=False)
        with pytest.raises(RuntimeError, match="boom-5"):
            list(loader)


class TestScalerAndLoaderCompat:
    """Round-5: GradScaler accessor tail + pre-2.0 generator loaders."""

    def test_grad_scaler_accessors(self):
        from paddle_tpu import amp
        sc = amp.GradScaler()
        sc.set_incr_ratio(3.0)
        assert sc.get_incr_ratio() == 3.0
        with pytest.raises(Exception):
            sc.set_incr_ratio(0.5)
        sc.set_decr_ratio(0.25)
        assert sc.get_decr_ratio() == 0.25
        sc.set_init_loss_scaling(1024.0)
        assert sc.get_init_loss_scaling() == 1024.0
        assert float(sc._st["scale"]) == 1024.0     # state reseeded
        sc.set_incr_every_n_steps(7)
        assert sc.get_incr_every_n_steps() == 7
        sc.set_decr_every_n_nan_or_inf(5)
        assert sc.get_decr_every_n_nan_or_inf() == 5
        assert sc.is_use_dynamic_loss_scaling()

    def test_scaler_unscale_(self):
        from paddle_tpu import amp, nn, optimizer
        import paddle_tpu as pt
        pt.seed(0)
        lin = nn.Linear(2, 2)
        o = optimizer.SGD(parameters=[p for _, p in lin.named_parameters()])
        sc = amp.GradScaler(init_loss_scaling=8.0)
        for p in o._parameters:
            p._grad = jnp.ones_like(jnp.asarray(p)) * 8.0
        sc.unscale_(o)
        for p in o._parameters:
            np.testing.assert_allclose(np.asarray(p._grad), 1.0)

    def test_unscale_then_step_no_double_unscale(self):
        """Regression: the grad-clip idiom unscale_ -> step must apply
        the TRUE gradient, not grad/scale^2."""
        from paddle_tpu import amp, nn, optimizer
        import paddle_tpu as pt
        pt.seed(0)
        lin = nn.Linear(2, 1)
        o = optimizer.SGD(learning_rate=1.0,
                          parameters=[p for _, p in lin.named_parameters()])
        sc = amp.GradScaler(init_loss_scaling=8.0)
        w0 = np.asarray(lin.weight.value).copy()
        for p in o._parameters:
            p._grad = jnp.ones_like(jnp.asarray(p)) * 8.0
        sc.unscale_(o)
        sc.step(o)
        np.testing.assert_allclose(w0 - np.asarray(lin.weight.value),
                                   1.0, rtol=1e-6)

    def test_from_generator_batch_and_sample(self):
        from paddle_tpu.io import DataLoader
        loader = DataLoader.from_generator(capacity=4)
        loader.set_batch_generator(lambda: iter([np.ones(2), np.zeros(2)]))
        assert len(list(loader)) == 2

        def samples():
            for i in range(5):
                yield (np.float32(i),)

        loader2 = DataLoader.from_generator().set_sample_generator(
            samples, batch_size=2)
        for _ in range(2):                        # re-iterable
            out = list(loader2)
            assert len(out) == 2                  # drop_last on 5/2
            slot0 = out[0][0]                     # per-slot batch arrays
            assert np.asarray(slot0).shape == (2,)

    def test_from_dataset_requires_loaded_memory(self):
        from paddle_tpu.io import DataLoader
        import paddle_tpu.distributed as dist
        ds = dist.InMemoryDataset()
        with pytest.raises(Exception, match="load_into_memory"):
            DataLoader.from_dataset(ds)

    def test_from_dataset_batches_and_reiterates(self, tmp_path):
        from paddle_tpu.io import DataLoader
        import paddle_tpu.distributed as dist
        p = tmp_path / "recs.txt"
        p.write_text("a\nb\nc\nd\ne\n")
        ds = dist.InMemoryDataset()
        ds.init(batch_size=2)
        ds.set_filelist([str(p)])
        ds.load_into_memory()
        loader = DataLoader.from_dataset(ds)
        for _ in range(2):                        # re-iterable
            batches = list(loader)
            assert batches[0] == ["a", "b"] and len(batches) == 2
