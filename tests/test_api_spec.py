"""API-compat guard (≙ the reference's API.spec + check_api_compatible.py
CI gate): the live public-API signatures must match the committed spec, so
every API change is an explicit, reviewed event — regenerate with
``python tools/print_signatures.py --update``."""
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_public_api_matches_spec():
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "print_signatures.py")],
        capture_output=True, text=True, timeout=240, cwd=_ROOT)
    assert out.returncode == 0, out.stderr
    live = out.stdout.splitlines()
    with open(os.path.join(_ROOT, "API.spec")) as f:
        spec = f.read().splitlines()
    added = sorted(set(live) - set(spec))
    removed = sorted(set(spec) - set(live))
    assert not added and not removed, (
        "public API drifted from API.spec — regenerate with "
        "`python tools/print_signatures.py --update` and review:\n"
        + "\n".join(f"+ {l}" for l in added[:10])
        + "\n".join(f"- {l}" for l in removed[:10]))
