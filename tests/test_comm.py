"""Communication subsystem tests (ISSUE 8, marker ``comm``): blockwise
quantization error bounds, compressed collectives vs the exact lax path,
error-feedback gradient sync tracking the fp32 loss trajectory, ZeRO-1
ShardedOptimizer parity with replicated Adam on the 8-device virtual dp
mesh (the MULTICHIP-style correctness drill), fleet/strategy wiring, the
deprecation alias over the old ``all_reduce_quantized`` stub, byte
accounting, and the doctor's ``comm_bound`` verdict."""
import inspect

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import comm
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.comm import (CommConfig, ShardedOptimizer,
                                         dequantize_blockwise,
                                         quantization_error_bound,
                                         quantize_blockwise, sync_gradients,
                                         wire_bytes)
from paddle_tpu.distributed.comm.compress import pad_to_multiple
from paddle_tpu.distributed.comm.config import set_default_comm_config
from paddle_tpu.framework.errors import EnforceNotMet

pytestmark = [pytest.mark.comm, pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")]

N_DEV = 8


def make_mesh():
    return Mesh(np.array(jax.devices()[:N_DEV]), ("dp",))


def smap(f, mesh, in_specs, out_specs):
    """shard_map with the replication check off (collective outputs are
    value-replicated but VMA-typed device-varying; kwarg renamed across
    jax versions)."""
    params = inspect.signature(shard_map).parameters
    kw = {("check_vma" if "check_vma" in params else "check_rep"): False}
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **kw)


@pytest.fixture(autouse=True)
def _clean_comm_state():
    set_default_comm_config(None)
    dist.set_hybrid_communicate_group(None)
    yield
    set_default_comm_config(None)
    dist.set_hybrid_communicate_group(None)


# ---------------------------------------------------------------------------
# quantization primitives
# ---------------------------------------------------------------------------
class TestCompress:
    @pytest.mark.parametrize("block_size", [32, 64, 256])
    @pytest.mark.parametrize("bits", [4, 8])
    def test_round_trip_error_within_bound(self, block_size, bits):
        """The implementation is pinned to the analytic per-block bound:
        |x - dq(q(x))| <= scale / (2·qmax), per block size and width."""
        rng = np.random.RandomState(0)
        flat = jnp.asarray(rng.randn(block_size * 16) * 3.0, jnp.float32)
        codes, scale = quantize_blockwise(flat, bits=bits,
                                          block_size=block_size)
        back = dequantize_blockwise(codes, scale, bits=bits)
        err = np.abs(np.asarray(back - flat)).reshape(-1, block_size)
        bound = np.asarray(quantization_error_bound(scale, bits=bits))
        assert (err.max(axis=1) <= bound + 1e-7).all(), \
            (err.max(axis=1) - bound).max()
        # the bound is tight-ish: the observed max error is within 2x of
        # the half-step bound for a dense gaussian block
        assert err.max() > 0.05 * bound.max()

    def test_smaller_blocks_tighter_error(self):
        rng = np.random.RandomState(1)
        # heavy-tailed data: one outlier per big block inflates its scale
        flat = jnp.asarray(rng.standard_cauchy(4096), jnp.float32)
        errs = {}
        for bs in (32, 256):
            codes, scale = quantize_blockwise(flat, block_size=bs)
            back = dequantize_blockwise(codes, scale)
            errs[bs] = float(jnp.mean(jnp.abs(back - flat)))
        assert errs[32] < errs[256]

    def test_zero_block_decodes_to_zero(self):
        flat = jnp.zeros((512,), jnp.float32)
        codes, scale = quantize_blockwise(flat)
        assert float(jnp.abs(dequantize_blockwise(codes, scale)).max()) == 0.0

    def test_pad_to_multiple(self):
        flat = jnp.ones((33,), jnp.float32)
        padded, pad = pad_to_multiple(flat, 256)
        assert padded.shape == (256,) and pad == 223
        assert float(padded[33:].max()) == 0.0
        same, pad0 = pad_to_multiple(jnp.ones((256,)), 256)
        assert pad0 == 0 and same.shape == (256,)

    def test_rejects_non_flat_and_ragged(self):
        with pytest.raises(EnforceNotMet):
            quantize_blockwise(jnp.ones((4, 4)))
        with pytest.raises(EnforceNotMet):
            quantize_blockwise(jnp.ones((100,)), block_size=64)


# ---------------------------------------------------------------------------
# compressed collectives
# ---------------------------------------------------------------------------
class TestCompressedCollectives:
    def _all_reduce(self, x, cfg, op="sum"):
        mesh = make_mesh()
        return smap(lambda v: comm.all_reduce(v, op=op, group="dp",
                                              config=cfg),
                    mesh, P("dp", None), P("dp", None))(x)

    def test_int8_all_reduce_close_to_exact(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 4096), jnp.float32)
        exact = np.asarray(self._all_reduce(x, None))
        quant = np.asarray(self._all_reduce(
            x, CommConfig(dtype="int8", min_size_to_compress=0)))
        scale = np.abs(exact).max()
        assert np.abs(quant - exact).max() / scale < 0.05

    def test_bf16_all_reduce_close_to_exact(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(8, 4096), jnp.float32)
        exact = np.asarray(self._all_reduce(x, None, op="avg"), np.float32)
        bf = np.asarray(self._all_reduce(
            x, CommConfig(dtype="bfloat16", min_size_to_compress=0),
            op="avg"), np.float32)
        assert np.abs(bf - exact).max() / np.abs(exact).max() < 0.02

    def test_small_payload_stays_exact(self):
        """Below min_size_to_compress the int8 config must take the
        bitwise-exact lax path."""
        x = jnp.asarray(np.random.RandomState(2).randn(8, 64), jnp.float32)
        exact = np.asarray(self._all_reduce(x, None))
        cfg = CommConfig(dtype="int8", min_size_to_compress=4096)
        np.testing.assert_array_equal(
            np.asarray(self._all_reduce(x, cfg)), exact)

    def test_max_op_stays_exact(self):
        x = jnp.asarray(np.random.RandomState(3).randn(8, 4096), jnp.float32)
        cfg = CommConfig(dtype="int8", min_size_to_compress=0)
        exact = np.asarray(self._all_reduce(x, None, op="max"))
        np.testing.assert_array_equal(
            np.asarray(self._all_reduce(x, cfg, op="max")), exact)

    def test_identity_outside_mesh(self):
        x = jnp.asarray(np.random.RandomState(4).randn(128), jnp.float32)
        out = comm.all_reduce(x, config=CommConfig(dtype="int8"))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_int8_reduce_scatter_close_to_exact(self):
        mesh = make_mesh()
        rng = np.random.RandomState(5)
        # flat length divisible by n*block_size (the ZeRO shape)
        x = jnp.asarray(rng.randn(8 * 256 * 2), jnp.float32)
        cfg = CommConfig(dtype="int8", min_size_to_compress=0)
        exact = smap(lambda v: comm.reduce_scatter(v, op="avg", group="dp"),
                     mesh, P(None), P("dp"))(x)
        quant = smap(lambda v: comm.reduce_scatter(v, op="avg", group="dp",
                                                   config=cfg),
                     mesh, P(None), P("dp"))(x)
        scale = float(np.abs(np.asarray(exact)).max())
        assert np.abs(np.asarray(quant) - np.asarray(exact)).max() \
            / scale < 0.05

    def test_reduce_scatter_rejects_ragged_compressed_shape(self):
        mesh = make_mesh()
        cfg = CommConfig(dtype="int8", min_size_to_compress=0,
                         block_size=256)
        with pytest.raises(EnforceNotMet):
            smap(lambda v: comm.reduce_scatter(v, group="dp", config=cfg),
                 mesh, P(None), P("dp"))(jnp.ones((8 * 300,), jnp.float32))

    def test_config_validation(self):
        with pytest.raises(EnforceNotMet):
            CommConfig(dtype="fp8")
        with pytest.raises(EnforceNotMet):
            CommConfig(bits=16)
        with pytest.raises(EnforceNotMet):
            CommConfig.from_dict({"dtyp": "int8"})  # typo'd knob is loud
        assert CommConfig.from_dict(None) == CommConfig()
        assert CommConfig(dtype="int8").compressed
        assert not CommConfig().compressed


# ---------------------------------------------------------------------------
# gradient sync + error feedback
# ---------------------------------------------------------------------------
class TestSyncGradients:
    def test_exact_sync_matches_psum_mean(self):
        mesh = make_mesh()
        rng = np.random.RandomState(0)
        g = jnp.asarray(rng.randn(8, 4096), jnp.float32)

        def f(v):
            synced, res = sync_gradients({"w": v.reshape(-1)}, group="dp")
            assert res is None
            return synced["w"]

        out = np.asarray(smap(f, mesh, P("dp", None), P(None))(g))
        np.testing.assert_allclose(out, np.asarray(g).mean(0), rtol=1e-6)

    def test_error_feedback_residual_reinjects(self):
        """The residual is exactly what the quantizer dropped, and adding
        it back next step shrinks the accumulated quantization bias:
        after two EF steps the summed sync error is smaller than two
        independent (EF-off) sync errors."""
        mesh = make_mesh()
        rng = np.random.RandomState(1)
        g = jnp.asarray(rng.randn(8, 4096), jnp.float32)
        cfg_ef = CommConfig(dtype="int8", min_size_to_compress=0,
                            error_feedback=True)
        cfg_no = CommConfig(dtype="int8", min_size_to_compress=0)

        def two_steps_ef(v):
            tree = {"w": v.reshape(-1)}
            s1, r1 = sync_gradients(tree, config=cfg_ef, group="dp")
            s2, r2 = sync_gradients(tree, config=cfg_ef, group="dp",
                                    residual=r1)
            return s1["w"] + s2["w"], r2["w"]

        def two_steps_no(v):
            tree = {"w": v.reshape(-1)}
            s1, _ = sync_gradients(tree, config=cfg_no, group="dp")
            s2, _ = sync_gradients(tree, config=cfg_no, group="dp")
            return s1["w"] + s2["w"]

        want = 2 * np.asarray(g).mean(0).reshape(-1)
        got_ef, resid = smap(two_steps_ef, mesh, P("dp", None),
                             (P(None), P("dp")))(g)
        got_no = smap(two_steps_no, mesh, P("dp", None), P(None))(g)
        err_ef = np.abs(np.asarray(got_ef) - want).mean()
        err_no = np.abs(np.asarray(got_no) - want).mean()
        assert err_ef < err_no, (err_ef, err_no)
        assert np.abs(np.asarray(resid)).max() > 0  # residual is real

    def test_small_leaves_get_zero_residual(self):
        mesh = make_mesh()
        cfg = CommConfig(dtype="int8", error_feedback=True,
                         min_size_to_compress=10_000)

        def f(v):
            synced, res = sync_gradients({"w": v}, config=cfg, group="dp")
            return synced["w"], res["w"]

        g = jnp.asarray(np.random.RandomState(2).randn(8, 64), jnp.float32)
        out, res = smap(f, mesh, P("dp", None), (P(None), P("dp", None)))(g)
        np.testing.assert_allclose(np.asarray(out)[0], np.asarray(g).mean(0),
                                   rtol=1e-6)
        assert float(np.abs(np.asarray(res)).max()) == 0.0

    def test_none_leaves_pass_through(self):
        mesh = make_mesh()

        def f(v):
            synced, _ = sync_gradients({"w": v, "frozen": None}, group="dp")
            assert synced["frozen"] is None
            return synced["w"]

        g = jnp.asarray(np.ones((8, 32)), jnp.float32)
        out = smap(f, mesh, P("dp", None), P(None))(g)
        np.testing.assert_allclose(np.asarray(out)[0], np.ones(32),
                                   rtol=1e-6)

    def test_int8_ef_training_tracks_fp32_loss(self):
        """ISSUE 8 acceptance shape at test scale: 30 data-parallel SGD
        steps on a least-squares model; the int8+error-feedback leg's
        final loss must land within 1% of the fp32 leg's."""
        mesh = make_mesh()
        rng = np.random.RandomState(0)
        Xs = jnp.asarray(rng.randn(8, 4, 16), jnp.float32)   # per-rank data
        W_true = rng.randn(16, 8).astype(np.float32)
        Ys = jnp.asarray(np.einsum("rbi,io->rbo", np.asarray(Xs), W_true)
                         + 0.01 * rng.randn(8, 4, 8).astype(np.float32))
        w0 = jnp.zeros((16, 8), jnp.float32)
        cfg = CommConfig(dtype="int8", error_feedback=True, block_size=32,
                         min_size_to_compress=0)

        def run(ccfg):
            def loop(x, y):
                def body(carry, _):
                    w, res = carry
                    loss, g = jax.value_and_grad(
                        lambda w: jnp.mean((x @ w - y) ** 2))(w)
                    synced, new_res = sync_gradients(
                        {"w": g}, config=ccfg, group="dp", residual=res)
                    return (w - 0.05 * synced["w"], new_res), loss
                res0 = ({"w": jnp.zeros_like(w0)}
                        if ccfg is not None and ccfg.error_feedback
                        else None)
                (w, _), losses = lax.scan(body, (w0, res0), None, length=30)
                final = jnp.mean((x @ w - y) ** 2)
                return lax.pmean(final, "dp")
            out = smap(loop, mesh, (P("dp", None, None),
                                    P("dp", None, None)), P())(Xs, Ys)
            return float(np.asarray(out).reshape(-1)[0])

        loss_fp32 = run(None)
        loss_int8 = run(cfg)
        assert abs(loss_int8 - loss_fp32) / abs(loss_fp32) < 0.01, \
            (loss_int8, loss_fp32)


# ---------------------------------------------------------------------------
# ZeRO-1 ShardedOptimizer
# ---------------------------------------------------------------------------
def _uneven_params():
    """Param tree exercising every packing edge: total float count not
    divisible by dp=8, a scalar leaf, mixed float dtypes, and a non-float
    leaf that must pass through untouched."""
    rng = np.random.RandomState(0)
    return {
        "w": jnp.asarray(rng.randn(13, 7), jnp.float32),      # 91 elems
        "b": jnp.asarray(rng.randn(5), jnp.float32),          # 5
        "scale": jnp.asarray(1.5, jnp.float32),               # scalar
        "h": jnp.asarray(rng.randn(3, 3), jnp.bfloat16),      # mixed dtype
        "steps": jnp.asarray(7, jnp.int32),                   # non-float
    }


def _like_grads(params, seed=1):
    rng = np.random.RandomState(seed)

    def g(p):
        if not jnp.issubdtype(p.dtype, jnp.floating):
            return None
        return jnp.asarray(rng.randn(*p.shape) if p.ndim else rng.randn(),
                           jnp.float32).astype(p.dtype)
    return {k: g(v) for k, v in params.items()}


class TestShardedOptimizer:
    def _parity(self, make_inner, steps=3, comm_cfg=None, atol=3e-6):
        """shard_map drill on the 8-device dp mesh: the sharded update's
        unpacked params must match the replicated inner optimizer's
        within dtype tolerance (default: a few f32 ulps — the flat-pack
        reduce order differs from the per-leaf order)."""
        mesh = make_mesh()
        params = _uneven_params()
        zo = ShardedOptimizer(make_inner(), axis="dp", num_shards=N_DEV,
                              comm=comm_cfg)
        specs = zo.state_sharding_specs()

        def init(p):
            return zo.init(p)

        def step_fn(p, state, g):
            new_p, new_s = zo.apply_gradients(g, p, state)
            return new_p, new_s

        state = jax.jit(smap(init, mesh, (P(),), specs))(params)
        step = jax.jit(smap(step_fn, mesh, (P(), specs, P()),
                            (P(), specs)))
        ref = make_inner()
        ref_state = ref.init(params)
        p_sharded, p_ref = params, params
        for i in range(steps):
            grads = _like_grads(params, seed=i + 1)
            # replicated grads: every rank supplies the same local grad,
            # so avg(local) == the replicated gradient
            p_sharded, state = step(p_sharded, state, grads)
            p_ref, ref_state = ref.apply_gradients(grads, p_ref, ref_state)
        for k in ("w", "b", "scale", "h"):
            a = np.asarray(p_sharded[k], np.float32)
            b = np.asarray(p_ref[k], np.float32)
            # bf16 leaves tolerate one ulp: a sub-ulp f32 master diff can
            # land on a rounding boundary
            tol = max(atol, 0.01) if p_sharded[k].dtype == jnp.bfloat16 \
                else atol
            np.testing.assert_allclose(a, b, atol=tol, rtol=0,
                                       err_msg=f"leaf {k}")
        assert int(p_sharded["steps"]) == int(params["steps"])
        return p_sharded, p_ref

    def test_parity_adam_uneven_shapes(self):
        self._parity(lambda: pt.optimizer.Adam(learning_rate=1e-2))

    def test_parity_adamw_decoupled_decay(self):
        self._parity(lambda: pt.optimizer.AdamW(learning_rate=1e-2,
                                                weight_decay=0.1))

    def test_parity_momentum_coupled_decay(self):
        self._parity(lambda: pt.optimizer.Momentum(
            learning_rate=1e-2, momentum=0.9, weight_decay=0.05))

    def test_parity_global_norm_clip(self):
        from paddle_tpu.optimizer import ClipGradByGlobalNorm
        self._parity(lambda: pt.optimizer.Adam(
            learning_rate=1e-2, grad_clip=ClipGradByGlobalNorm(0.5)))

    def test_int8_compressed_reduce_scatter_stays_close(self):
        """ZeRO with an int8-compressed gradient reduce-scatter: not
        bitwise, but within the quantization error of replicated Adam."""
        p_sh, p_ref = self._parity(
            lambda: pt.optimizer.Adam(learning_rate=1e-2), steps=2,
            comm_cfg=CommConfig(dtype="int8", block_size=32,
                                min_size_to_compress=0),
            atol=5e-3)

    def test_gspmd_mode_parity(self):
        """hapi/GSPMD form: mesh installed via fleet, axis unbound, the
        state carries sharding constraints; numerics must still match
        replicated Adam bitwise."""
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        params = _uneven_params()
        zo = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-2))
        assert zo.num_shards == 8 and zo.axis == "dp"
        state = zo.init(params)
        assert "dp" in tuple(state["flat"].sharding.spec)
        grads = _like_grads(params)
        new_p, state = jax.jit(zo.apply_gradients)(grads, params, state)
        ref = pt.optimizer.Adam(learning_rate=1e-2)
        rp, _ = ref.apply_gradients(grads, params, ref.init(params))
        for k in ("w", "b", "scale", "h"):
            np.testing.assert_allclose(np.asarray(new_p[k], np.float32),
                                       np.asarray(rp[k], np.float32),
                                       atol=0, rtol=0, err_msg=k)

    def test_no_mesh_single_replica_identical(self):
        params = _uneven_params()
        zo = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-2))
        assert zo.num_shards == 1
        state = zo.init(params)
        grads = _like_grads(params)
        new_p, _ = zo.apply_gradients(grads, params, state)
        ref = pt.optimizer.Adam(learning_rate=1e-2)
        rp, _ = ref.apply_gradients(grads, params, ref.init(params))
        for k in ("w", "b", "scale", "h"):
            np.testing.assert_allclose(np.asarray(new_p[k], np.float32),
                                       np.asarray(rp[k], np.float32),
                                       atol=0, rtol=0)

    def test_init_packs_tp_placed_params_exactly(self):
        """Regression: eagerly concatenating a TP-placed model's leaves
        (mixed PartitionSpecs on a dp×mp mesh) miscompiled on this stack
        — replicated LN weights came back summed across devices (1.0 →
        16.0) in the flat master.  init must round-trip placed params
        bit-exactly."""
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        pt.seed(0)
        model = GPTForCausalLM(gpt_tiny(num_layers=1))
        model = fleet.distributed_model(model)
        params = model.state_dict()
        zo = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3))
        meta = zo._meta(params)
        leaves = meta.treedef.flatten_up_to(params)
        flat = np.asarray(zo._pack_flat(leaves, meta))
        for info in meta.packed:
            seg = flat[info.offset:info.offset + info.size]
            want = np.ravel(np.asarray(leaves[info.index], np.float32))
            np.testing.assert_array_equal(seg, want, err_msg=info.path)

    def test_rejects_non_elementwise_and_bad_comm(self):
        from paddle_tpu.optimizer import Lamb
        with pytest.raises(EnforceNotMet):
            ShardedOptimizer(Lamb(learning_rate=1e-2))
        with pytest.raises(EnforceNotMet):
            ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-2),
                             comm=CommConfig(dtype="int8",
                                             error_feedback=True))
        with pytest.raises(EnforceNotMet):
            ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-2),
                             comm=CommConfig(dtype="bfloat16"))


# ---------------------------------------------------------------------------
# fleet / strategy wiring
# ---------------------------------------------------------------------------
class TestFleetWiring:
    def test_comm_configs_install_process_default(self):
        from paddle_tpu.distributed.comm import get_default_comm_config
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
        strategy.comm_configs = {"dtype": "int8", "error_feedback": True}
        fleet.init(is_collective=True, strategy=strategy)
        cfg = get_default_comm_config()
        assert cfg.dtype == "int8" and cfg.error_feedback
        # re-init with an empty dict resets to exact
        strategy.comm_configs = {}
        fleet.init(is_collective=True, strategy=strategy)
        assert get_default_comm_config() == CommConfig()

    def test_shard_weight_update_one_config_line(self):
        """The GPT-pretrain switch: sharding_configs["shard_weight_update"]
        routes the fleet optimizer through ZeRO-1, bitwise-matching the
        replicated update under jit on the dp mesh."""
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 1, "shard_weight_update": True}
        fleet.init(is_collective=True, strategy=strategy)
        opt = fleet.distributed_optimizer(
            pt.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.01),
            strategy)
        assert isinstance(opt.inner, ShardedOptimizer)
        params = {"w": jnp.asarray(np.random.RandomState(0).randn(16, 32),
                                   jnp.float32)}
        state = opt.init(params)
        assert "dp" in tuple(state["inner"]["flat"].sharding.spec)
        grads = {"w": jnp.full((16, 32), 0.1, jnp.float32)}
        new_p, _ = jax.jit(opt.apply_gradients)(grads, params, state)
        ref = pt.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.01)
        rp, _ = ref.apply_gradients(grads, params, ref.init(params))
        np.testing.assert_allclose(np.asarray(new_p["w"]),
                                   np.asarray(rp["w"]), atol=0, rtol=0)

    def test_stage1_without_flag_keeps_placement_form(self):
        from paddle_tpu.distributed.fleet.optimizer import \
            HybridParallelOptimizer
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
        strategy.sharding = True
        fleet.init(is_collective=True, strategy=strategy)
        opt = fleet.distributed_optimizer(
            pt.optimizer.Adam(learning_rate=1e-3), strategy)
        assert isinstance(opt, HybridParallelOptimizer)
        assert not isinstance(opt.inner, ShardedOptimizer)
        st = opt.init({"w": jnp.ones((16, 32), jnp.float32)})
        assert "slots" in st["inner"]  # per-param layout, not flat

    def test_hapi_prepare_binds_mesh(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        zo = ShardedOptimizer(pt.optimizer.Adam(learning_rate=1e-3))
        assert zo.num_shards == 8           # resolved against this mesh
        dist.set_hybrid_communicate_group(None)
        zo.bind_mesh()                       # hapi.prepare's hook
        assert zo.num_shards == 1            # re-resolved: mesh gone


# ---------------------------------------------------------------------------
# deprecation alias + byte accounting
# ---------------------------------------------------------------------------
class TestAliasAndAccounting:
    def test_all_reduce_quantized_alias_warns_and_matches(self):
        mesh = make_mesh()
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 512), jnp.float32)
        exact = smap(lambda v: dist.all_reduce(v, group="dp"),
                     mesh, P("dp", None), P("dp", None))(x)
        with pytest.warns(DeprecationWarning):
            quant = smap(lambda v: dist.all_reduce_quantized(v, group="dp"),
                         mesh, P("dp", None), P("dp", None))(x)
        scale = float(np.abs(np.asarray(exact)).max())
        assert np.abs(np.asarray(quant) - np.asarray(exact)).max() \
            / scale < 0.05

    def test_wire_bytes_formulas(self):
        exact = CommConfig()
        assert wire_bytes(1024, exact, rounds=2) == 2 * 4 * 1024
        bf16 = CommConfig(dtype="bfloat16")
        assert wire_bytes(1024, bf16, rounds=2) == 2 * 2 * 1024
        int8 = CommConfig(dtype="int8", block_size=256)
        assert wire_bytes(1024, int8, rounds=2) == 2 * (1024 + 4 * 4)
        # ~3.9x at block_size=256
        ratio = wire_bytes(2 ** 20, exact) / wire_bytes(2 ** 20, int8)
        assert ratio > 3.9

    @staticmethod
    def _family_total(reg, base):
        # ISSUE 20: the byte counters carry [axis=..,leg=..] labels —
        # readers sum the whole family, never just the unlabeled name
        from paddle_tpu.observability.registry import split_labels
        total = 0.0
        for name, m in reg.snapshot().items():
            if m.get("type") == "counter" and split_labels(name)[0] == base:
                total += float(m.get("value") or 0.0)
        return total

    def test_counters_advance_and_ratio(self):
        from paddle_tpu.observability import get_registry
        reg = get_registry()
        raw0 = self._family_total(reg, "comm.bytes")
        wire0 = self._family_total(reg, "comm.compressed_bytes")
        mesh = make_mesh()
        x = jnp.asarray(np.random.RandomState(0).randn(8, 8192), jnp.float32)
        cfg = CommConfig(dtype="int8", min_size_to_compress=0)
        smap(lambda v: comm.all_reduce(v.reshape(-1), group="dp",
                                       config=cfg),
             mesh, P("dp", None), P(None))(x)
        raw = self._family_total(reg, "comm.bytes") - raw0
        wire = self._family_total(reg, "comm.compressed_bytes") - wire0
        assert raw > 0 and wire > 0
        assert raw / wire >= 3.0, raw / wire
        assert reg.gauge("comm.compress_ratio").value >= 3.0

    def test_int8_two_phase_books_per_leg(self):
        # ISSUE 20 satellite: the int8 schedule's two legs are booked
        # separately — one all_to_all round, one all_gather round, both
        # on the dp axis, with equal wire bytes (same codes+scales ship
        # on each leg)
        from paddle_tpu.observability import get_registry
        reg = get_registry()

        def leg_value(base, leg):
            name = f"{base}[axis=dp,leg={leg}]"
            m = reg.snapshot().get(name)
            return float((m or {}).get("value") or 0.0)

        before = {leg: leg_value("comm.compressed_bytes", leg)
                  for leg in ("all_to_all", "all_gather")}
        mesh = make_mesh()
        x = jnp.asarray(np.random.RandomState(1).randn(8, 8192),
                        jnp.float32)
        cfg = CommConfig(dtype="int8", min_size_to_compress=0)
        smap(lambda v: comm.all_reduce(v.reshape(-1), group="dp",
                                       config=cfg),
             mesh, P("dp", None), P(None))(x)
        deltas = {leg: leg_value("comm.compressed_bytes", leg) - before[leg]
                  for leg in ("all_to_all", "all_gather")}
        assert deltas["all_to_all"] > 0
        assert deltas["all_to_all"] == deltas["all_gather"], deltas


# ---------------------------------------------------------------------------
# doctor: comm_bound verdict
# ---------------------------------------------------------------------------
def _window(coll_p50, step_p50, n_steps=8, op="all_reduce"):
    recs = [{"kind": "step", "step_time_ms": step_p50, "ts": float(i)}
            for i in range(n_steps)]
    recs.append({"kind": "metrics.snapshot", "ts": float(n_steps),
                 "snapshot": {
                     f"collective.{op}.ms": {
                         "type": "histogram", "count": 50,
                         "sum": coll_p50 * 50, "p50": coll_p50},
                     "step.time_ms": {"type": "histogram", "count": n_steps,
                                      "sum": step_p50 * n_steps,
                                      "p50": step_p50}}})
    return {0: recs}


class TestDoctorCommBound:
    def test_flags_dominant_collective(self):
        from paddle_tpu.observability import doctor
        findings = doctor.check_comm_bound(_window(40.0, 100.0))
        assert len(findings) == 1
        f = findings[0]
        assert f["kind"] == "comm_bound"
        assert f["data"]["op"] == "all_reduce"
        assert f["data"]["worker"] == 0
        assert abs(f["data"]["ratio"] - 0.4) < 1e-6
        assert any("all_reduce" in e for e in f["evidence"])

    def test_quiet_below_threshold(self):
        from paddle_tpu.observability import doctor
        assert doctor.check_comm_bound(_window(10.0, 100.0)) == []

    def test_fraction_configurable(self):
        from paddle_tpu.observability import doctor
        w = _window(10.0, 100.0)
        assert doctor.check_comm_bound(w, frac=0.05)
        assert doctor.check_comm_bound(w, frac=0.5) == []

    def test_step_p50_falls_back_to_snapshot(self):
        from paddle_tpu.observability import doctor
        w = _window(40.0, 100.0)
        w[0] = [r for r in w[0] if r["kind"] != "step"]  # snapshot only
        findings = doctor.check_comm_bound(w)
        assert findings and findings[0]["data"]["step_p50_ms"] == 100.0

    def test_diagnose_surfaces_comm_bound(self, tmp_path):
        """End-to-end: a run dir whose worker stream carries the synthetic
        window gets a ranked comm_bound finding from diagnose()."""
        import json
        from paddle_tpu.observability import doctor
        from paddle_tpu.observability.aggregate import SCHEMA_VERSION
        mdir = tmp_path / "metrics"
        mdir.mkdir()
        recs = _window(60.0, 100.0)[0]
        with open(mdir / "worker-0.jsonl", "w") as fh:
            for r in recs:
                fh.write(json.dumps({"schema_version": SCHEMA_VERSION,
                                     **r}) + "\n")
        diag = doctor.diagnose(str(tmp_path))
        kinds = {f["kind"] for f in diag["findings"]}
        assert "comm_bound" in kinds, kinds
