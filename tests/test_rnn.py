"""RNN family tests: golden parity against torch's CPU LSTM/GRU/RNN
(gate orders match the reference paddle cells), variable-length masking,
jit/grad compatibility.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn

torch = pytest.importorskip("torch")


def _copy_weights_from_torch(cell, t_mod, layer=0, suffix=""):
    """torch packs (G*H, in); ours is (in, G*H)."""
    sd = {k: v.detach().numpy() for k, v in t_mod.state_dict().items()}
    cell.weight_ih.value = jnp.asarray(sd[f"weight_ih_l{layer}{suffix}"].T)
    cell.weight_hh.value = jnp.asarray(sd[f"weight_hh_l{layer}{suffix}"].T)
    cell.bias_ih.value = jnp.asarray(sd[f"bias_ih_l{layer}{suffix}"])
    cell.bias_hh.value = jnp.asarray(sd[f"bias_hh_l{layer}{suffix}"])


def _reorder_gru_gates(cell):
    """torch GRU gate order is (r, z, n) = ours; nothing to do — kept as a
    documentation hook in case upstream order changes."""


@pytest.mark.parametrize("bidirect", [False, True])
def test_lstm_matches_torch(bidirect):
    B, T, I, H = 3, 7, 5, 4
    rng = np.random.RandomState(0)
    x = rng.randn(B, T, I).astype(np.float32)

    t_lstm = torch.nn.LSTM(I, H, num_layers=1, batch_first=True,
                           bidirectional=bidirect)
    pt.seed(0)
    ours = nn.LSTM(I, H, num_layers=1,
                   direction="bidirect" if bidirect else "forward")
    _copy_weights_from_torch(ours.cells[0], t_lstm)
    if bidirect:
        _copy_weights_from_torch(ours.cells[1], t_lstm, suffix="_reverse")

    with torch.no_grad():
        t_out, (t_h, t_c) = t_lstm(torch.from_numpy(x))
    out, (h, c) = ours(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), t_h.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c), t_c.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_gru_matches_torch_two_layers():
    B, T, I, H = 2, 5, 4, 6
    rng = np.random.RandomState(1)
    x = rng.randn(B, T, I).astype(np.float32)

    t_gru = torch.nn.GRU(I, H, num_layers=2, batch_first=True)
    pt.seed(0)
    ours = nn.GRU(I, H, num_layers=2)
    _copy_weights_from_torch(ours.cells[0], t_gru, layer=0)
    _copy_weights_from_torch(ours.cells[1], t_gru, layer=1)

    with torch.no_grad():
        t_out, t_h = t_gru(torch.from_numpy(x))
    out, h = ours(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), t_h.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_simple_rnn_matches_torch():
    B, T, I, H = 2, 6, 3, 5
    rng = np.random.RandomState(2)
    x = rng.randn(B, T, I).astype(np.float32)
    t_rnn = torch.nn.RNN(I, H, batch_first=True, nonlinearity="tanh")
    pt.seed(0)
    ours = nn.SimpleRNN(I, H)
    _copy_weights_from_torch(ours.cells[0], t_rnn)
    with torch.no_grad():
        t_out, t_h = t_rnn(torch.from_numpy(x))
    out, h = ours(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), t_h.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_sequence_length_masking():
    """Padded steps emit zeros and do not advance the state."""
    B, T, I, H = 2, 6, 3, 4
    rng = np.random.RandomState(3)
    x = rng.randn(B, T, I).astype(np.float32)
    lens = jnp.asarray([4, 6])
    pt.seed(7)
    lstm = nn.LSTM(I, H)
    out, (h, c) = lstm(jnp.asarray(x), sequence_length=lens)
    out = np.asarray(out)
    # padded outputs zero
    assert np.all(out[0, 4:] == 0.0)
    assert np.any(out[0, 3] != 0.0)
    # final state equals the state at the last valid step
    out_full, (h_full, _) = lstm(jnp.asarray(x[:, :4]))
    np.testing.assert_allclose(np.asarray(h)[0, 0],
                               np.asarray(h_full)[0, 0], rtol=1e-5,
                               atol=1e-6)


def test_rnn_and_birnn_wrappers():
    B, T, I, H = 2, 5, 3, 4
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(B, T, I), jnp.float32)
    pt.seed(0)
    cell = nn.GRUCell(I, H)
    wrapper = nn.RNN(cell)
    out, h = wrapper(x)
    assert out.shape == (B, T, H) and h.shape == (B, H)
    # single-step cell call parity with the wrapper's first step
    h1, _ = cell(x[:, 0])
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(h1),
                               rtol=1e-5, atol=1e-6)

    bi = nn.BiRNN(nn.LSTMCell(I, H), nn.LSTMCell(I, H))
    out, (fin_fw, fin_bw) = bi(x)
    assert out.shape == (B, T, 2 * H)
    assert fin_fw[0].shape == (B, H) and fin_bw[0].shape == (B, H)


def test_custom_tuple_state_cell_in_rnn_wrapper():
    """RNN() must drive any cell whose state is a tuple, not just LSTMCell
    (regression: tuple handling used isinstance checks)."""
    class Peephole(nn.LSTMCell):
        # subclass with an extra accumulator state leaf driver must carry
        def get_initial_states(self, batch_size, dtype=jnp.float32):
            z = jnp.zeros((batch_size, self.hidden_size), dtype)
            return (z, z)

    pt.seed(0)
    cell = Peephole(3, 4)
    out, fin = nn.RNN(cell)(jnp.asarray(
        np.random.RandomState(0).randn(2, 5, 3), jnp.float32))
    assert out.shape == (2, 5, 4)
    assert isinstance(fin, tuple) and fin[0].shape == (2, 4)
    # with sequence lengths: every tuple leaf frozen past the length
    lens = jnp.asarray([2, 5])
    out2, (h2, c2) = nn.RNN(cell)(jnp.asarray(
        np.random.RandomState(0).randn(2, 5, 3), jnp.float32),
        sequence_length=lens)
    assert np.all(np.asarray(out2)[0, 2:] == 0)


def test_lstm_trains_under_jit():
    """Language-model-ish smoke: LSTM + Linear fits a tiny sequence task."""
    B, T, I, H = 4, 8, 6, 16
    pt.seed(11)
    lstm = nn.LSTM(I, H)
    head = nn.Linear(H, 2)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(B, T, I), jnp.float32)
    y = jnp.asarray(rng.randint(0, 2, (B,)), jnp.int32)

    params = {"lstm": lstm.state_dict(), "head": head.state_dict()}
    opt = pt.optimizer.Adam(learning_rate=1e-2)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        def loss_fn(q):
            out, _ = lstm.apply(q["lstm"], x)
            logits = head.apply(q["head"], out[:, -1])
            return pt.nn.functional.cross_entropy(logits, y)
        loss, g = jax.value_and_grad(loss_fn)(p)
        p2, s2 = opt.apply_gradients(g, p, s)
        return loss, p2, s2

    losses = []
    for _ in range(30):
        loss, params, state = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0]
