"""Serving-fleet tests (ISSUE 16): router dispatch policy with fake
replicas, fleet admission + retry/backoff, token-exact failover via
journal replay in-process, drain migration, and the multi-process
SIGKILL drill (marked slow — ci.sh's fleet tier runs it).
"""
import json
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.inference import ServingEngine
from paddle_tpu.inference.fleet import (DispatchExhausted, FleetOverloaded,
                                        LocalReplica, ReplicaManager,
                                        Router)
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.testing import faults

pytestmark = pytest.mark.serving


def tiny_model(max_pos=64):
    pt.seed(7)
    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=2,
                    num_heads=2, ffn_hidden_size=64,
                    max_position_embeddings=max_pos, hidden_dropout=0.0,
                    attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def dense_continuation(model, prompt, max_new, eos=None):
    out = model.generate(jnp.asarray([prompt], jnp.int32),
                         max_new_tokens=max_new, temperature=0.0,
                         eos_token_id=eos)
    return np.asarray(out)[0, len(prompt):].tolist()


def local_fleet(n=2, registry=None, max_pos=64, **engine_kw):
    reg = registry or MetricsRegistry()
    reps = [LocalReplica(ServingEngine(tiny_model(max_pos), registry=reg,
                                       replica_id=i, **engine_kw),
                         replica_id=i)
            for i in range(n)]
    return reps, reg


# ---------------------------------------------------------------------------
# fake replicas: dispatch policy without a model
# ---------------------------------------------------------------------------
class FakeReplica:
    """Replica protocol stub with a scriptable load and liveness."""

    def __init__(self, replica_id, load=0.0, up=True):
        self.replica_id = replica_id
        self.load = float(load)
        self.up = up
        self.submitted = []

    def submit(self, record):
        if not self.up:
            raise ConnectionError(f"replica {self.replica_id} down")
        self.submitted.append(record)

    def poll(self, rid, start=0):
        if not self.up:
            raise ConnectionError(f"replica {self.replica_id} down")
        return {"tokens": [], "finished": False, "reason": None}

    def pump(self):
        return False

    def serving_stats(self):
        return {"queue_depth": self.load, "waiting": 0, "running": 0}

    def healthz(self):
        return (200, "serving") if self.up else (503, "dead")

    def alive(self):
        return self.up


class TestDispatchPolicy:
    def test_least_loaded_wins(self):
        reps = [FakeReplica(0, load=5), FakeReplica(1, load=1),
                FakeReplica(2, load=9)]
        router = Router(reps, registry=MetricsRegistry())
        router.submit([1, 2], max_new_tokens=4)
        assert len(reps[1].submitted) == 1
        assert not reps[0].submitted and not reps[2].submitted

    def test_session_affinity_beats_load(self):
        reps = [FakeReplica(0, load=5), FakeReplica(1, load=1)]
        router = Router(reps, registry=MetricsRegistry())
        router.submit([1], max_new_tokens=4, session="u1")
        first = 0 if reps[0].submitted else 1
        # second stream for the same session lands on the same replica
        # even though the other one is less loaded
        reps[first].load = 50
        router.submit([2], max_new_tokens=4, session="u1")
        assert len(reps[first].submitted) == 2

    def test_affinity_broken_when_replica_dies(self):
        reps = [FakeReplica(0, load=0), FakeReplica(1, load=5)]
        router = Router(reps, registry=MetricsRegistry())
        router.submit([1], max_new_tokens=4, session="u1")
        assert len(reps[0].submitted) == 1
        reps[0].up = False
        router.submit([2], max_new_tokens=4, session="u1")
        assert len(reps[1].submitted) == 1

    def test_fleet_admission_shed(self):
        reps = [FakeReplica(0, load=40), FakeReplica(1, load=30)]
        reg = MetricsRegistry()
        router = Router(reps, registry=reg, shed_queue_depth=64)
        with pytest.raises(FleetOverloaded, match="aggregate depth"):
            router.submit([1], max_new_tokens=4)
        snap = reg.snapshot()
        assert snap["fleet.shed"]["value"] == 1.0

    def test_no_healthy_replica_sheds(self):
        reps = [FakeReplica(0, up=False), FakeReplica(1, up=False)]
        router = Router(reps, registry=MetricsRegistry())
        with pytest.raises(FleetOverloaded, match="0 healthy"):
            router.submit([1], max_new_tokens=4)

    def test_retry_exhaustion_names_replica_set(self):
        reps = [FakeReplica(0), FakeReplica(1)]
        reg = MetricsRegistry()
        router = Router(reps, registry=reg, retry_max=2,
                        retry_backoff_ms=0.0, sleep=lambda _t: None)
        router.dispatch_fault = faults.drop_dispatch(count=10**6)
        with pytest.raises(DispatchExhausted) as ei:
            router.submit([1], max_new_tokens=4)
        msg = str(ei.value)
        assert "[0, 1]" in msg            # the replica set, by name
        assert "3 attempts" in msg
        assert reg.snapshot()["fleet.retries"]["value"] == 2.0

    def test_transient_drop_recovers_with_retry(self):
        reps = [FakeReplica(0), FakeReplica(1)]
        reg = MetricsRegistry()
        slept = []
        router = Router(reps, registry=reg, retry_max=3,
                        retry_backoff_ms=10.0, sleep=slept.append)
        fault = faults.drop_dispatch(count=3)
        router.dispatch_fault = fault
        rid = router.submit([1], max_new_tokens=4)
        assert rid in router.journals
        assert fault.fired == 3
        assert sum(len(r.submitted) for r in reps) == 1
        # one retry round (2 drops on attempt 0, 1 on attempt 1), so
        # exactly one backoff sleep at the base delay
        assert slept == [pytest.approx(0.010)]

    def test_drop_dispatch_scoped_to_replica(self):
        fault = faults.drop_dispatch(count=5, replica_id=1)
        fault(0, {"request_id": "a"})     # other replica: passes
        assert fault.fired == 0
        with pytest.raises(ConnectionError):
            fault(1, {"request_id": "a"})
        assert fault.fired == 1


# ---------------------------------------------------------------------------
# journal replay: token-exact failover, in-process
# ---------------------------------------------------------------------------
class TestFailoverInProcess:
    def test_failover_token_exact_vs_dense(self):
        model = tiny_model()
        want = {i: dense_continuation(model, [1, 2, 3 + i], 10)
                for i in range(3)}
        reps, reg = local_fleet(2, max_seqs=4, kv_block_size=4)
        router = Router(reps, registry=reg)
        rids = [router.submit([1, 2, 3 + i], max_new_tokens=10)
                for i in range(3)]
        # accept a few tokens, then hard-stop whichever replica serves
        # the first stream (simulated SIGKILL: no drain, no spill)
        while len(router.journals[rids[0]].tokens) < 3:
            router.pump()
        victim = router.journals[rids[0]].replica_id
        reps[victim].engine._state = "stopped"
        outs = [router.collect(r, timeout=60) for r in rids]
        for i, out in enumerate(outs):
            assert out["tokens"] == want[i], (i, out)
        assert router.failovers >= 1
        assert reg.snapshot()["fleet.failovers"]["value"] \
            == float(router.failovers)
        # survivors' allocators drained clean
        for i, rep in enumerate(reps):
            if i != victim:
                assert rep.engine.cache.leak_report()["leaked_blocks"] \
                    == 0

    def test_journal_record_is_spill_format(self):
        reps, reg = local_fleet(1, max_seqs=2, kv_block_size=4)
        router = Router(reps, registry=reg)
        rid = router.submit([1, 2, 3], max_new_tokens=8,
                            eos_token_id=9)
        while len(router.journals[rid].tokens) < 2:
            router.pump()
        rec = router.journals[rid].record()
        assert rec["prompt"] == [1, 2, 3]
        assert rec["output"] == router.journals[rid].tokens
        assert rec["max_new_tokens"] == 8
        assert rec["eos_token_id"] == 9
        # and it round-trips through a fresh engine's admit_record
        fresh = ServingEngine(tiny_model(), max_seqs=2,
                              registry=MetricsRegistry())
        assert fresh.admit_record(rec) == rid

    def test_drain_migration_token_exact(self, tmp_path):
        model = tiny_model()
        want = {i: dense_continuation(model, [1, 2, 3 + i], 12)
                for i in range(4)}
        # both replicas share one run_dir — the ISSUE 16 namespacing
        # keeps their spill/quarantine artifacts from colliding
        reps, reg = local_fleet(2, max_seqs=4, kv_block_size=4,
                                run_dir=str(tmp_path))
        router = Router(reps, registry=reg)
        rids = [router.submit([1, 2, 3 + i], max_new_tokens=12)
                for i in range(4)]
        router.pump()
        moved = router.drain_replica(0, timeout=0.0)
        live_on_0 = [r for r in rids
                     if router.journals[r].replica_id == 0
                     and not router.journals[r].finished]
        assert not live_on_0                 # everything re-homed
        outs = [router.collect(r, timeout=60) for r in rids]
        for i, out in enumerate(outs):
            assert out["tokens"] == want[i], (i, out)
        assert router.migrations == moved
        if moved:
            assert reg.snapshot()["fleet.migrations"]["value"] \
                == float(moved)

    def test_statusz_fleet_section(self):
        from paddle_tpu.observability.monitor import StatusServer
        reps, reg = local_fleet(2, max_seqs=2, kv_block_size=4)
        router = Router(reps, registry=reg)
        rid = router.submit([1, 2, 3], max_new_tokens=4)
        router.collect(rid, timeout=60)
        page = StatusServer(registry=reg, router=router).statusz()
        fleet = page["fleet"]
        assert fleet["dispatch"] >= 1
        assert fleet["replicas"] == 2
        assert fleet["states"].get("healthy") == 2
        assert fleet["streams"]["finished"] == 1

    def test_doctor_fleet_failover_verdict(self):
        from paddle_tpu.observability.doctor import check_fleet
        recs = [{"kind": "fleet.failover", "request_id": "r1",
                 "from_replica": 0, "to_replica": 1,
                 "why": "replica died", "accepted_tokens": 5},
                {"kind": "fleet.replica_state", "replica": 0,
                 "prev": "healthy", "state": "dead"}]
        findings = check_fleet({0: recs})
        assert len(findings) == 1
        f = findings[0]
        assert f["kind"] == "fleet_failover"
        assert f["data"]["count"] == 1
        assert any("token-exact" in line for line in f["evidence"])
        assert not check_fleet({0: [recs[1]]})   # death alone: no verdict


# ---------------------------------------------------------------------------
# the multi-process drills (ci.sh fleet tier; slow)
# ---------------------------------------------------------------------------
def fleet_spec(max_pos=64):
    return {"seed": 7,
            "config": {"vocab_size": 32, "hidden_size": 32,
                       "num_layers": 2, "num_heads": 2,
                       "ffn_hidden_size": 64,
                       "max_position_embeddings": max_pos,
                       "hidden_dropout": 0.0, "attention_dropout": 0.0},
            "engine": {"max_seqs": 4}}


@pytest.mark.slow
class TestMultiProcessDrills:
    def test_sigkill_failover_drill(self, tmp_path):
        reg = MetricsRegistry()
        mgr = ReplicaManager(fleet_spec(), replicas=2, registry=reg,
                             run_dir=str(tmp_path))
        mgr.start()
        try:
            router = Router(mgr.replicas, manager=mgr, registry=reg)
            rids = [router.submit([1, 2, 3 + i], max_new_tokens=40)
                    for i in range(6)]
            kill = faults.kill_replica(
                mgr, index=0,
                when=lambda: any(
                    len(j.tokens) >= 2 for j in router.journals.values()
                    if j.replica_id == 0 and not j.finished))
            deadline = time.monotonic() + 120
            while not kill.fired and time.monotonic() < deadline:
                router.pump()
                kill.maybe()
                time.sleep(0.01)
            assert kill.fired == 1
            assert mgr.poll_states()[0] == "dead"
            outs = [router.collect(r, timeout=120) for r in rids]
            assert router.failovers >= 1
            # token-exact vs an uninterrupted single-engine reference
            model = tiny_model()
            ref = ServingEngine(model, max_seqs=4,
                                registry=MetricsRegistry())
            ref_out = ref.generate([[1, 2, 3 + i] for i in range(6)],
                                   max_new_tokens=40)
            assert [o["tokens"] for o in outs] == ref_out
            # survivor leak report clean
            stats = router.replicas[1].serving_stats()
            assert stats["kv_blocks"]["leaked"] == 0
        finally:
            mgr.stop()

    def test_rolling_upgrade_zero_drops(self, tmp_path):
        reg = MetricsRegistry()
        mgr = ReplicaManager(fleet_spec(), replicas=2, registry=reg,
                             run_dir=str(tmp_path))
        mgr.start()
        try:
            router = Router(mgr.replicas, manager=mgr, registry=reg)
            rids = [router.submit([1, 2, 3 + i], max_new_tokens=48)
                    for i in range(6)]
            router.pump()
            router.rolling_upgrade(timeout_per_replica=0.05)
            assert mgr.restarts == 2
            states = mgr.poll_states()
            assert all(s == "healthy" for s in states.values())
            outs = [router.collect(r, timeout=120) for r in rids]
            # zero dropped or truncated streams
            assert all(len(o["tokens"]) == 48 for o in outs)
            model = tiny_model()
            ref = ServingEngine(model, max_seqs=4,
                                registry=MetricsRegistry())
            assert [o["tokens"] for o in outs] == ref.generate(
                [[1, 2, 3 + i] for i in range(6)], max_new_tokens=48)
        finally:
            mgr.stop()

    def test_worker_spill_namespaced_per_replica(self, tmp_path):
        reg = MetricsRegistry()
        mgr = ReplicaManager(fleet_spec(), replicas=1, registry=reg,
                             run_dir=str(tmp_path))
        mgr.start()
        try:
            router = Router(mgr.replicas, manager=mgr, registry=reg)
            router.submit([1, 2, 3], max_new_tokens=40)
            router.pump()
            report = router.replicas[0].drain(timeout=0.0)
            if report["spilled_records"]:
                spill = (tmp_path / "serve" / "replica-0"
                         / "spill.json")
                assert spill.exists()
                payload = json.loads(spill.read_text())
                assert payload["version"] == 1
        finally:
            mgr.stop()
