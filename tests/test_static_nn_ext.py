"""static.nn long-tail tests (reference static/nn/__init__.py __all__):
conv/norm builders cached on the Program, control flow on lax, and the
LoD sequence family on the padded-batch + lengths contract."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.static as st

N = st.nn


@pytest.fixture
def prog():
    p = st.Program("static_nn_ext_test")
    with st.program_guard(p):
        yield p


class TestStaticNNBuilders:
    def test_conv_family(self, prog):
        x4 = jnp.asarray(np.random.RandomState(0).randn(2, 3, 8, 8),
                         jnp.float32)
        assert N.conv2d(x4, 6, 3, padding=1).shape == (2, 6, 8, 8)
        assert N.conv2d_transpose(x4, 5, 3).shape == (2, 5, 10, 10)
        assert N.conv3d(jnp.ones((1, 2, 4, 4, 4)), 3, 3,
                        padding=1).shape == (1, 3, 4, 4, 4)
        assert N.conv3d_transpose(jnp.ones((1, 2, 4, 4, 4)), 3,
                                  3).shape == (1, 3, 6, 6, 6)

    def test_params_cached_across_calls(self, prog):
        x = jnp.ones((1, 2, 4, 4))
        a = N.conv2d(x, 3, 3, padding=1, name="c")
        b = N.conv2d(x, 3, 3, padding=1, name="c")    # same layer slot
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_norm_family(self, prog):
        x4 = jnp.asarray(np.random.RandomState(1).randn(2, 4, 6, 6),
                         jnp.float32)
        assert N.layer_norm(x4, begin_norm_axis=1).shape == x4.shape
        assert N.group_norm(x4, 2).shape == x4.shape
        assert N.instance_norm(x4).shape == x4.shape
        assert N.data_norm(x4).shape == x4.shape
        assert N.prelu(x4).shape == x4.shape
        assert N.spectral_norm(jnp.ones((4, 5))).shape == (4, 5)

    def test_misc_builders(self, prog):
        assert N.bilinear_tensor_product(jnp.ones((2, 3)),
                                         jnp.ones((2, 4)), 5).shape == (2, 5)
        assert N.row_conv(jnp.ones((2, 6, 4)), 2).shape == (2, 6, 4)
        loss = N.nce(jnp.ones((4, 8)), jnp.asarray([0, 1, 2, 3]), 10)
        assert loss.shape == (4, 1) and float(loss.sum()) > 0
        assert N.sparse_embedding(jnp.asarray([[1, 2]]),
                                  [10, 6]).shape == (1, 2, 6)
        path = N.crf_decoding(
            jnp.asarray(np.random.rand(2, 5, 4), jnp.float32))
        assert path.shape == (2, 5)

    def test_multi_box_head(self, prog):
        locs, confs, prior, var = N.multi_box_head(
            [jnp.ones((1, 4, 4, 4)), jnp.ones((1, 8, 2, 2))], None, 3,
            aspect_ratios=[[2.0], [2.0]])
        assert locs.shape[-1] == 4 and confs.shape[-1] == 3
        assert prior.shape[-1] == 4 and var.shape == prior.shape
        assert locs.shape[1] == confs.shape[1]


class TestStaticControlFlow:
    def test_cond_while_case_switch(self):
        assert float(N.cond(True, lambda: jnp.asarray(1.0),
                            lambda: jnp.asarray(2.0))) == 1.0
        out = N.while_loop(lambda i, s: i < 5,
                           lambda i, s: (i + 1, s + i),
                           [jnp.asarray(0), jnp.asarray(0)])
        assert int(out[1]) == 10
        c = N.case([(jnp.asarray(False), lambda: jnp.asarray(1.0)),
                    (jnp.asarray(True), lambda: jnp.asarray(2.0))],
                   default=lambda: jnp.asarray(3.0))
        assert float(c) == 2.0
        assert float(N.switch_case(
            jnp.asarray(1),
            [lambda: jnp.asarray(10.0), lambda: jnp.asarray(20.0)])) == 20.0
        # under jit too (the whole point of the lax mapping)
        f = jax.jit(lambda p: N.cond(p, lambda: jnp.asarray(1.0),
                                     lambda: jnp.asarray(2.0)))
        assert float(f(jnp.asarray(False))) == 2.0


class TestSequenceFamily:
    """The LoD contract rendered as padded batch + lengths."""

    def setup_method(self, _):
        self.x = jnp.asarray(np.arange(24, dtype=np.float32
                                       ).reshape(2, 4, 3))
        self.len = jnp.asarray([2, 4])

    def test_softmax_pool_steps(self):
        sm = N.sequence_softmax(jnp.ones((2, 4)), self.len)
        np.testing.assert_allclose(np.asarray(sm[0]), [0.5, 0.5, 0, 0],
                                   atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(N.sequence_pool(self.x, "average", self.len)[0]),
            np.asarray(self.x)[0, :2].mean(0), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(N.sequence_pool(self.x, "max", self.len)[0]),
            np.asarray(self.x)[0, :2].max(0), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(N.sequence_last_step(self.x, self.len)[0]),
            np.asarray(self.x)[0, 1])
        np.testing.assert_allclose(
            np.asarray(N.sequence_first_step(self.x)[1]),
            np.asarray(self.x)[1, 0])

    def test_reverse_respects_lengths(self):
        rev = N.sequence_reverse(self.x, self.len)
        np.testing.assert_allclose(np.asarray(rev)[0, :2],
                                   np.asarray(self.x)[0, [1, 0]])
        np.testing.assert_allclose(np.asarray(rev)[0, 2:],
                                   np.asarray(self.x)[0, 2:])
        np.testing.assert_allclose(np.asarray(rev)[1],
                                   np.asarray(self.x)[1, ::-1])

    def test_pad_unpad_reshape_concat_slice(self):
        padded, lens = N.sequence_pad(self.x, 0.0, maxlen=6,
                                      length=self.len)
        assert padded.shape == (2, 6, 3)
        assert float(jnp.abs(padded[0, 2:]).sum()) == 0
        assert N.sequence_unpad(self.x, self.len).shape == self.x.shape
        assert N.sequence_reshape(self.x, 6).shape == (2, 2, 6)
        assert N.sequence_concat([self.x, self.x]).shape == (2, 8, 3)
        sl = N.sequence_slice(self.x, jnp.asarray([0, 1]),
                              jnp.asarray([2, 2]))
        np.testing.assert_allclose(np.asarray(sl)[1],
                                   np.asarray(self.x)[1, 1:3])

    def test_expand_enumerate_scatter_conv(self):
        assert N.sequence_expand(jnp.ones((2, 3)),
                                 jnp.ones((2, 4))).shape == (8, 3)
        assert N.sequence_expand_as(jnp.ones((2, 3)),
                                    jnp.ones((6, 3))).shape == (6, 3)
        en = N.sequence_enumerate(jnp.asarray([[1, 2, 3]]), 2, pad_value=9)
        np.testing.assert_array_equal(np.asarray(en)[0],
                                      [[1, 2], [2, 3], [3, 9]])
        sc = N.sequence_scatter(jnp.zeros((2, 5)),
                                jnp.asarray([[0, 1], [2, 3]]),
                                jnp.ones((2, 2)))
        assert float(sc[0, 0]) == 1.0 and float(sc[1, 2]) == 1.0
        with st.program_guard(st.Program("seqconv")):
            assert N.sequence_conv(self.x, 7, 3).shape == (2, 4, 7)


class TestStaticNNReviewRegressions:
    def test_conv_transpose_output_size_form(self):
        with st.program_guard(st.Program("r1")):
            y = N.conv2d_transpose(jnp.ones((1, 2, 7, 7)), 4,
                                   output_size=[14, 14], stride=2,
                                   padding=1)
            assert y.shape == (1, 4, 14, 14)

    def test_conv2d_nhwc_forwarded(self):
        with st.program_guard(st.Program("r2")):
            z = N.conv2d(jnp.ones((1, 8, 8, 3)), 6, 3, padding=1,
                         data_format="NHWC")
            assert z.shape == (1, 8, 8, 6)

    def test_switch_case_exact_key_default(self):
        table = {1: lambda: jnp.asarray(1.0), 3: lambda: jnp.asarray(3.0)}
        assert float(N.switch_case(jnp.asarray(2), table,
                                   default=lambda: jnp.asarray(-1.0))) == -1.0
        assert float(N.switch_case(jnp.asarray(3), table,
                                   default=lambda: jnp.asarray(-1.0))) == 3.0

    def test_multi_box_priors_location_major(self):
        with st.program_guard(st.Program("r3")):
            locs, confs, prior, var = N.multi_box_head(
                [jnp.ones((1, 4, 2, 2))], None, 3, aspect_ratios=[[2.0]])
        p = np.asarray(prior)
        # consecutive priors share a cell center (prior-minor order)
        c0 = (p[0, 0] + p[0, 2]) / 2
        c1 = (p[1, 0] + p[1, 2]) / 2
        assert abs(c0 - c1) < 1e-6
        assert locs.shape[1] == prior.shape[0]

    def test_data_norm_accumulates_running_stats(self):
        big = jnp.asarray(np.random.RandomState(0).randn(64, 4) * 5 + 3,
                          jnp.float32)
        with st.program_guard(st.Program("r4")):
            for _ in range(80):
                out = N.data_norm(big, name="dn")
        # identity behavior (the old bug) would leave mean ~= 3; the
        # accumulated global stats pull it well below (the reference's
        # 1e4-sample init prior keeps it off exact 0 this early)
        assert abs(float(jnp.mean(out))) < 1.0
